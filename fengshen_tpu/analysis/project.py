"""Phase 1 of the two-phase analyzer: the whole-package project index.

Per-file rules (phase 2a) see one tree at a time; the concurrency
rules (phase 2b: ``unguarded-shared-state``, ``blocking-under-lock``,
``lock-order``) need facts no single file contains — which class owns
which ``threading.Lock``, which helper is only ever called with that
lock held, which call chain crosses a module boundary into a blocking
socket read. This module builds that view:

- **per-class inventory**: attributes assigned anywhere in the class,
  lock-family attributes (``self._lock = threading.Lock()`` and
  friends), waitables (Event/Queue), threads, jitted callables, and
  attribute *types* when the right-hand side constructs a
  package-internal class (``self.router = Router(...)``) — the hook
  that lets the call graph cross object boundaries
- **guard scopes**: every ``with <lock>:`` body, with the lock
  resolved to a stable identity (``module::Class.attr`` /
  ``module::VAR`` / ``module::fn.<local>``)
- **call graph**: package-internal edges resolved through import
  aliases, ``self.method``, typed attributes, and module singletons
  (``REGISTRY = MetricsRegistry()`` then ``registry.REGISTRY.count``)
- **fixpoints** over the graph: functions *always* called with a lock
  held (so ``_step_locked``-style helpers don't read as unguarded),
  the transitive blocking-call closure, the transitive lock-
  acquisition closure, and thread-confined private methods

The index is pure stdlib, content-hash cached (``--index-cache``) and
memoised in-process on file stats, and every iteration order is
sorted, so ``--json`` output stays byte-deterministic regardless of
``PYTHONHASHSEED`` or cache state.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import io
import json
import os
import re
import tokenize
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

from fengshen_tpu.analysis import dataflow

INDEX_CACHE_VERSION = 4

#: filled by every build_index() call — files seen, cache hit/miss
#: split, and whether the in-process memo short-circuited the build.
#: The CLI surfaces this via ``--stats`` (perf budget for the
#: analyzer itself: the warm path must stay cheap as rules grow).
LAST_BUILD_STATS: Dict[str, int] = {
    "files": 0, "cache_hits": 0, "cache_misses": 0, "memo_hit": 0}

#: constructor qualnames that make an attribute/variable a *guard*
LOCK_FACTORIES = {
    "threading.Lock": "Lock", "threading.RLock": "RLock",
    "threading.Condition": "Condition",
    "threading.Semaphore": "Semaphore",
    "threading.BoundedSemaphore": "BoundedSemaphore",
    "multiprocessing.Lock": "Lock", "multiprocessing.RLock": "RLock",
}
#: constructors whose instances block on wait()/get()/put()/join()
WAITABLE_FACTORIES = {
    "threading.Event": "Event",
    "queue.Queue": "Queue", "queue.SimpleQueue": "Queue",
    "queue.LifoQueue": "Queue", "queue.PriorityQueue": "Queue",
}
THREAD_FACTORIES = {"threading.Thread": "Thread"}
#: wrapping a function in these makes *calling* it a device dispatch
JIT_FACTORIES = {"jax.jit", "jax.pmap"}

#: free calls that block the calling thread (network, child process,
#: host sleep, device sync) — the direct seeds of blocking-under-lock
BLOCKING_FREE_CALLS = {
    "time.sleep": "time.sleep()",
    "urllib.request.urlopen": "urllib.request.urlopen()",
    "socket.create_connection": "socket.create_connection()",
    "select.select": "select.select()",
    "subprocess.run": "subprocess.run()",
    "subprocess.call": "subprocess.call()",
    "subprocess.check_call": "subprocess.check_call()",
    "subprocess.check_output": "subprocess.check_output()",
    "subprocess.Popen": "subprocess.Popen()",
    "requests.get": "requests.get()", "requests.post": "requests.post()",
    "requests.put": "requests.put()", "requests.request":
        "requests.request()",
    "jax.device_get": "jax.device_get()",
}
#: sync methods that block regardless of receiver type
BLOCKING_ANY_METHODS = {"block_until_ready": ".block_until_ready()"}

INIT_METHODS = frozenset({"__init__", "__post_init__", "__new__",
                          "__del__", "__set_name__"})

#: container/deque/dict/set methods that mutate the receiver in place
MUTATOR_METHODS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "pop",
    "popleft", "popitem", "remove", "clear", "add", "discard",
    "update", "setdefault", "sort", "reverse", "rotate",
})

_SUPPRESS_RE = re.compile(
    r"#\s*fslint:\s*disable(?:=(?P<rules>[\w,\- ]+))?")


# -- shared file-level helpers (engine.py imports these) --------------


def collect_aliases(tree: ast.Module) -> Dict[str, str]:
    """local name -> dotted origin, from import statements."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = \
                    a.name if a.asname else a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom) and node.module:
            prefix = ("." * node.level) + node.module
            for a in node.names:
                if a.name == "*":
                    continue
                aliases[a.asname or a.name] = f"{prefix}.{a.name}"
    return aliases


def collect_comments(source: str) -> Dict[int, str]:
    comments: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                comments[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass  # ast.parse already succeeded; comment map is best-effort
    return comments


def collect_suppressions(
        comments: Dict[int, str]) -> Dict[int, frozenset]:
    """line -> suppressed rule ids (empty frozenset = all rules)."""
    out: Dict[int, frozenset] = {}
    for line, text in comments.items():
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = m.group("rules")
        out[line] = frozenset(
            r.strip() for r in rules.split(",") if r.strip()) \
            if rules else frozenset()
    return out


def iter_py_files(paths: Iterable[str]) -> Iterator[str]:
    for path in paths:
        if os.path.isfile(path):
            yield path
            continue
        if not os.path.isdir(path):
            # a typo'd path must fail LOUDLY, not lint nothing and
            # report the tree clean (a vacuous CI gate)
            raise FileNotFoundError(f"no such file or directory: {path}")
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(
                d for d in dirnames
                if d not in ("__pycache__", ".git", ".venv"))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def module_name_for(path: str) -> str:
    """Dotted import name, by climbing ``__init__.py`` parents.

    Files outside any package get their stem (made unique enough by
    the directory name) — lock identities only need to be stable."""
    path = os.path.abspath(path)
    parts = [os.path.splitext(os.path.basename(path))[0]]
    d = os.path.dirname(path)
    while os.path.exists(os.path.join(d, "__init__.py")):
        parts.append(os.path.basename(d))
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    if parts[0] == "__init__":
        parts = parts[1:] or parts
    return ".".join(reversed(parts))


# -- summaries --------------------------------------------------------


@dataclasses.dataclass
class ClassSummary:
    name: str
    line: int
    attrs: List[str]                    # every self.X ever assigned
    lock_attrs: Dict[str, str]          # attr -> Lock/RLock/Condition/…
    waitable_attrs: Dict[str, str]      # attr -> Event/Queue
    thread_attrs: List[str]
    jit_attrs: List[str]
    attr_types: Dict[str, str]          # attr -> constructed class ref
    thread_targets: List[str]           # methods run on owned threads
    methods: List[str]

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "ClassSummary":
        return cls(**d)


@dataclasses.dataclass
class FunctionSummary:
    qual: str            # "Class.method", "func", "func.inner"
    cls: Optional[str]
    name: str
    line: int
    # (attr, line, col, guards) — self.attr mutations with the lock
    # ids lexically held at the site
    writes: List[Tuple[str, int, int, Tuple[str, ...]]]
    # (callee spec, line, col, guards); spec kinds:
    #   "self:meth" | "obj:attr.meth" | "name:f" | "qual:a.b.c"
    calls: List[Tuple[str, int, int, Tuple[str, ...]]]
    # (line, col, description, exempt-lock-or-"", guards)
    blocking: List[Tuple[int, int, str, str, Tuple[str, ...]]]
    # (lock id, line, col, locks already held)
    acquisitions: List[Tuple[str, int, int, Tuple[str, ...]]]

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "FunctionSummary":
        return cls(
            qual=d["qual"], cls=d["cls"], name=d["name"], line=d["line"],
            writes=[tuple(w[:3]) + (tuple(w[3]),) for w in d["writes"]],
            calls=[tuple(c[:3]) + (tuple(c[3]),) for c in d["calls"]],
            blocking=[tuple(b[:4]) + (tuple(b[4]),)
                      for b in d["blocking"]],
            acquisitions=[tuple(a[:3]) + (tuple(a[3]),)
                          for a in d["acquisitions"]])


@dataclasses.dataclass
class FileSummary:
    relpath: str
    module: str
    classes: Dict[str, ClassSummary]
    functions: Dict[str, FunctionSummary]   # keyed by qual
    module_locks: Dict[str, str]            # var -> lock kind
    module_waitables: Dict[str, str]
    module_jit_vars: List[str]
    module_var_types: Dict[str, str]        # var -> constructed class
    module_thread_targets: List[str]        # fns run on module threads
    suppressions: Dict[int, frozenset]
    parse_error: Optional[str] = None
    # dataflow-tier facts (analysis/dataflow.py), computed at
    # summarise time so warm-cache runs never re-parse:
    # (var, callee, bind_line, call_line, read_line, read_col)
    donation_findings: List[Tuple] = dataclasses.field(
        default_factory=list)
    # (kind, protocol, var, line, col, other_line, detail)
    lifecycle_findings: List[Tuple] = dataclasses.field(
        default_factory=list)
    # (surface, METHOD, raw_path, line, col)
    routes: List[Tuple] = dataclasses.field(default_factory=list)
    # (name, kind, labelnames, line, col)
    metrics: List[Tuple] = dataclasses.field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "relpath": self.relpath, "module": self.module,
            "classes": {k: v.to_dict()
                        for k, v in sorted(self.classes.items())},
            "functions": {k: v.to_dict()
                          for k, v in sorted(self.functions.items())},
            "module_locks": dict(sorted(self.module_locks.items())),
            "module_waitables":
                dict(sorted(self.module_waitables.items())),
            "module_jit_vars": sorted(self.module_jit_vars),
            "module_var_types":
                dict(sorted(self.module_var_types.items())),
            "module_thread_targets": sorted(self.module_thread_targets),
            "suppressions": {str(k): sorted(v) for k, v in
                             sorted(self.suppressions.items())},
            "parse_error": self.parse_error,
            "donation_findings": [list(t) for t in
                                  self.donation_findings],
            "lifecycle_findings": [list(t) for t in
                                   self.lifecycle_findings],
            "routes": [list(t) for t in self.routes],
            "metrics": [[t[0], t[1], list(t[2]), t[3], t[4]]
                        for t in self.metrics],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "FileSummary":
        return cls(
            relpath=d["relpath"], module=d["module"],
            classes={k: ClassSummary.from_dict(v)
                     for k, v in d["classes"].items()},
            functions={k: FunctionSummary.from_dict(v)
                       for k, v in d["functions"].items()},
            module_locks=d["module_locks"],
            module_waitables=d["module_waitables"],
            module_jit_vars=list(d["module_jit_vars"]),
            module_var_types=d["module_var_types"],
            module_thread_targets=list(d["module_thread_targets"]),
            suppressions={int(k): frozenset(v) for k, v in
                          d["suppressions"].items()},
            parse_error=d["parse_error"],
            donation_findings=[tuple(t) for t in
                               d["donation_findings"]],
            lifecycle_findings=[tuple(t) for t in
                                d["lifecycle_findings"]],
            routes=[tuple(t) for t in d["routes"]],
            metrics=[(t[0], t[1], tuple(t[2]), t[3], t[4])
                     for t in d["metrics"]])


# -- per-file summarisation -------------------------------------------


class _FileSummarizer:
    """One lexical walk of a file, guard-stack aware."""

    def __init__(self, relpath: str, module: str, tree: ast.Module,
                 source: str) -> None:
        self.relpath = relpath
        self.module = module
        self.aliases = collect_aliases(tree)
        self.classes: Dict[str, ClassSummary] = {}
        self.functions: Dict[str, FunctionSummary] = {}
        self.module_locks: Dict[str, str] = {}
        self.module_waitables: Dict[str, str] = {}
        self.module_jit_vars: List[str] = []
        self.module_var_types: Dict[str, str] = {}
        self.module_thread_targets: List[str] = []
        self.suppressions = collect_suppressions(
            collect_comments(source))
        self._scan_module_vars(tree)
        self._pre_scan_classes(tree)
        for node in tree.body:
            self._visit_toplevel(node, cls=None, prefix="")

    # the dotted origin of an expression, through import aliases
    def _qual(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Name):
            return self.aliases.get(node.id, node.id)
        if isinstance(node, ast.Attribute):
            base = self._qual(node.value)
            return None if base is None else f"{base}.{node.attr}"
        return None

    def _factory_kind(self, value: ast.AST, table: Dict[str, str],
                      ) -> Optional[str]:
        if isinstance(value, ast.Call):
            qn = self._qual(value.func)
            if qn in table:
                return table[qn]
        return None

    def _is_jit_value(self, value: ast.AST) -> bool:
        if not isinstance(value, ast.Call):
            return False
        qn = self._qual(value.func)
        if qn in JIT_FACTORIES:
            return True
        if qn in ("functools.partial", "partial") and value.args:
            return self._qual(value.args[0]) in JIT_FACTORIES
        return False

    def _constructed_class(self, value: ast.AST) -> Optional[str]:
        """``Router(...)`` -> the (possibly dotted) class reference.

        Sees through the default-argument idiom (``metrics or
        MetricsRegistry()``, ``x if x is not None else Router()``)."""
        if isinstance(value, ast.BoolOp):
            for v in value.values:
                ref = self._constructed_class(v)
                if ref:
                    return ref
            return None
        if isinstance(value, ast.IfExp):
            return self._constructed_class(value.body) or \
                self._constructed_class(value.orelse)
        if not isinstance(value, ast.Call):
            return None
        qn = self._qual(value.func)
        if qn is None or qn in LOCK_FACTORIES or qn in \
                WAITABLE_FACTORIES or qn in THREAD_FACTORIES:
            return None
        leaf = qn.rsplit(".", 1)[-1]
        # class-name heuristic: constructors are CapWords
        if leaf[:1].isupper():
            return qn
        return None

    def _scan_module_vars(self, tree: ast.Module) -> None:
        for node in tree.body:
            if not isinstance(node, ast.Assign):
                continue
            names = [t.id for t in node.targets
                     if isinstance(t, ast.Name)]
            if not names:
                continue
            kind = self._factory_kind(node.value, LOCK_FACTORIES)
            if kind:
                for n in names:
                    self.module_locks[n] = kind
                continue
            kind = self._factory_kind(node.value, WAITABLE_FACTORIES)
            if kind:
                for n in names:
                    self.module_waitables[n] = kind
                continue
            if self._is_jit_value(node.value):
                self.module_jit_vars.extend(names)
                continue
            ref = self._constructed_class(node.value)
            if ref:
                for n in names:
                    self.module_var_types[n] = ref
        # module-level threading.Thread(target=fn)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and \
                    self._qual(node.func) in THREAD_FACTORIES:
                tgt = self._thread_target(node)
                if tgt and tgt[0] is None:
                    self.module_thread_targets.append(tgt[1])

    def _thread_target(self, call: ast.Call,
                       ) -> Optional[Tuple[Optional[str], str]]:
        """(receiver, name) of a Thread target: (None, 'fn') for a
        bare function, ('self', 'meth') for a bound method."""
        for kw in call.keywords:
            if kw.arg != "target":
                continue
            v = kw.value
            if isinstance(v, ast.Name):
                return (None, v.id)
            if isinstance(v, ast.Attribute) and \
                    isinstance(v.value, ast.Name) and \
                    v.value.id == "self":
                return ("self", v.attr)
        return None

    def _pre_scan_classes(self, tree: ast.Module) -> None:
        """Inventory pass: attribute kinds must be known before the
        guard-stack walk classifies ``with self._lock:`` scopes."""
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            cs = ClassSummary(
                name=node.name, line=node.lineno, attrs=[],
                lock_attrs={}, waitable_attrs={}, thread_attrs=[],
                jit_attrs=[], attr_types={}, thread_targets=[],
                methods=[n.name for n in node.body
                         if isinstance(n, (ast.FunctionDef,
                                           ast.AsyncFunctionDef))])
            seen: Set[str] = set()

            def annotation_ref(ann: Optional[ast.AST],
                               ) -> Optional[str]:
                # `recorder: Recorder` / `recorder: "Recorder"` /
                # `recorder: Optional[Recorder]` type an attribute
                # assigned straight from the parameter
                if isinstance(ann, ast.Constant) and \
                        isinstance(ann.value, str):
                    leaf = ann.value.rsplit(".", 1)[-1]
                    return ann.value if leaf[:1].isupper() else None
                if isinstance(ann, ast.Subscript):
                    return annotation_ref(ann.slice)
                qn = self._qual(ann) if ann is not None else None
                if qn and qn.rsplit(".", 1)[-1][:1].isupper() and \
                        qn not in ("None", "Optional", "Any"):
                    return qn
                return None

            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and \
                        self._qual(sub.func) in THREAD_FACTORIES:
                    tgt = self._thread_target(sub)
                    if tgt and tgt[0] == "self":
                        cs.thread_targets.append(tgt[1])
            for meth in ast.walk(node):
                if not isinstance(meth, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                params = {a.arg: annotation_ref(a.annotation)
                          for a in (*meth.args.posonlyargs,
                                    *meth.args.args,
                                    *meth.args.kwonlyargs)}
                for sub in ast.walk(meth):
                    tgt_attr = None
                    if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                        targets = sub.targets if isinstance(
                            sub, ast.Assign) else [sub.target]
                        for t in targets:
                            if isinstance(t, ast.Attribute) and \
                                    isinstance(t.value, ast.Name) and \
                                    t.value.id == "self":
                                tgt_attr = t.attr
                    if tgt_attr is None:
                        continue
                    if tgt_attr not in seen:
                        seen.add(tgt_attr)
                        cs.attrs.append(tgt_attr)
                    value = sub.value
                    if value is None:
                        continue
                    kind = self._factory_kind(value, LOCK_FACTORIES)
                    if kind:
                        cs.lock_attrs[tgt_attr] = kind
                        continue
                    kind = self._factory_kind(value,
                                              WAITABLE_FACTORIES)
                    if kind:
                        cs.waitable_attrs[tgt_attr] = kind
                        continue
                    if self._factory_kind(value, THREAD_FACTORIES):
                        cs.thread_attrs.append(tgt_attr)
                        continue
                    if self._is_jit_value(value):
                        cs.jit_attrs.append(tgt_attr)
                        continue
                    ref = self._constructed_class(value)
                    if ref is None and isinstance(value, ast.Name):
                        ref = params.get(value.id)
                    if ref:
                        cs.attr_types.setdefault(tgt_attr, ref)
            cs.thread_targets = sorted(set(cs.thread_targets))
            self.classes[node.name] = cs

    # -- lexical walk --------------------------------------------------

    def _visit_toplevel(self, node: ast.AST, cls: Optional[str],
                        prefix: str) -> None:
        if isinstance(node, ast.ClassDef):
            for sub in node.body:
                self._visit_toplevel(sub, cls=node.name,
                                     prefix=f"{node.name}.")
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._summarize_function(node, cls, prefix)

    def _lock_id_for(self, expr: ast.AST, cls: Optional[str],
                     fn_qual: str, local_locks: Dict[str, str],
                     ) -> Optional[str]:
        """Resolve a with-item / acquire receiver to a lock identity,
        or None when it isn't a known lock-family object."""
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and cls is not None:
            cs = self.classes.get(cls)
            if cs and expr.attr in cs.lock_attrs:
                return f"{self.module}::{cls}.{expr.attr}"
            return None
        if isinstance(expr, ast.Name):
            if expr.id in local_locks:
                return f"{self.module}::{fn_qual}.{expr.id}"
            if expr.id in self.module_locks:
                return f"{self.module}::{expr.id}"
            qn = self.aliases.get(expr.id)
            if qn and "." in qn:
                # a lock imported from a sibling module keeps its
                # defining module's identity
                mod, leaf = qn.rsplit(".", 1)
                return f"{mod}::{leaf}" if leaf.lower().find("lock") \
                    >= 0 or leaf.lower().find("cv") >= 0 else None
        return None

    def _summarize_function(self, fn: ast.AST, cls: Optional[str],
                            prefix: str) -> None:
        qual = f"{prefix}{fn.name}"
        fs = FunctionSummary(qual=qual, cls=cls, name=fn.name,
                             line=fn.lineno, writes=[], calls=[],
                             blocking=[], acquisitions=[])
        self.functions[qual] = fs
        local_locks: Dict[str, str] = {}
        local_waitables: Dict[str, str] = {}
        local_threads: Set[str] = set()
        local_jit: Set[str] = set()

        def classify_local(stmt: ast.Assign) -> None:
            names = [t.id for t in stmt.targets
                     if isinstance(t, ast.Name)]
            if not names:
                return
            kind = self._factory_kind(stmt.value, LOCK_FACTORIES)
            if kind:
                local_locks.update({n: kind for n in names})
                return
            kind = self._factory_kind(stmt.value, WAITABLE_FACTORIES)
            if kind:
                local_waitables.update({n: kind for n in names})
                return
            if self._factory_kind(stmt.value, THREAD_FACTORIES):
                local_threads.update(names)
                return
            if self._is_jit_value(stmt.value):
                local_jit.update(names)

        # locals must be known before guard classification: one
        # pre-pass over direct (non-nested) statements
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign):
                classify_local(sub)

        cs = self.classes.get(cls) if cls else None

        def waitable_kind(recv: ast.AST) -> Optional[str]:
            if isinstance(recv, ast.Attribute) and \
                    isinstance(recv.value, ast.Name) and \
                    recv.value.id == "self" and cs:
                if recv.attr in cs.waitable_attrs:
                    return cs.waitable_attrs[recv.attr]
                if recv.attr in cs.lock_attrs:
                    return cs.lock_attrs[recv.attr]
            if isinstance(recv, ast.Name):
                if recv.id in local_waitables:
                    return local_waitables[recv.id]
                if recv.id in self.module_waitables:
                    return self.module_waitables[recv.id]
                if recv.id in local_locks:
                    return local_locks[recv.id]
                if recv.id in self.module_locks:
                    return self.module_locks[recv.id]
            return None

        def is_thread(recv: ast.AST) -> bool:
            if isinstance(recv, ast.Attribute) and \
                    isinstance(recv.value, ast.Name) and \
                    recv.value.id == "self" and cs:
                return recv.attr in cs.thread_attrs
            return isinstance(recv, ast.Name) and \
                recv.id in local_threads

        def is_jit_callable(func: ast.AST) -> bool:
            if isinstance(func, ast.Attribute) and \
                    isinstance(func.value, ast.Name) and \
                    func.value.id == "self" and cs:
                return func.attr in cs.jit_attrs
            if isinstance(func, ast.Name):
                return func.id in local_jit or \
                    func.id in self.module_jit_vars
            return False

        def record_write(attr: str, node: ast.AST,
                         guards: Tuple[str, ...]) -> None:
            fs.writes.append((attr, node.lineno, node.col_offset,
                              guards))

        def self_attr(expr: ast.AST) -> Optional[str]:
            if isinstance(expr, ast.Attribute) and \
                    isinstance(expr.value, ast.Name) and \
                    expr.value.id == "self":
                return expr.attr
            return None

        def call_spec(func: ast.AST) -> Optional[str]:
            if isinstance(func, ast.Name):
                imported = self.aliases.get(func.id)
                if imported and "." in imported:
                    return f"qual:{imported}"
                return f"name:{func.id}"
            if isinstance(func, ast.Attribute):
                if isinstance(func.value, ast.Name) and \
                        func.value.id == "self":
                    return f"self:{func.attr}"
                if isinstance(func.value, ast.Attribute) and \
                        isinstance(func.value.value, ast.Name) and \
                        func.value.value.id == "self":
                    return f"obj:{func.value.attr}.{func.attr}"
                qn = self._qual(func)
                if qn:
                    return f"qual:{qn}"
            return None

        def handle_call(node: ast.Call,
                        guards: Tuple[str, ...]) -> None:
            func = node.func
            qn = self._qual(func)
            line, col = node.lineno, node.col_offset
            if qn in BLOCKING_FREE_CALLS:
                fs.blocking.append((line, col,
                                    BLOCKING_FREE_CALLS[qn], "",
                                    guards))
                return
            if isinstance(func, ast.Attribute):
                meth, recv = func.attr, func.value
                if meth in BLOCKING_ANY_METHODS:
                    fs.blocking.append(
                        (line, col, BLOCKING_ANY_METHODS[meth], "",
                         guards))
                    return
                if meth == "wait":
                    kind = waitable_kind(recv)
                    if kind in ("Event", "Condition"):
                        # waiting the condition you HOLD releases it —
                        # that lock is exempt at this site
                        exempt = ""
                        if kind == "Condition":
                            exempt = self._lock_id_for(
                                recv, cls, qual, local_locks) or ""
                        fs.blocking.append(
                            (line, col, f"{kind}.wait()", exempt,
                             guards))
                        return
                if meth == "join" and is_thread(recv):
                    fs.blocking.append(
                        (line, col, "Thread.join()", "", guards))
                    return
                if meth in ("get", "put") and \
                        waitable_kind(recv) == "Queue" and not any(
                            kw.arg == "block" and isinstance(
                                kw.value, ast.Constant) and
                            kw.value.value is False
                            for kw in node.keywords):
                    fs.blocking.append(
                        (line, col, f"Queue.{meth}()", "", guards))
                    return
                if meth == "acquire":
                    lid = self._lock_id_for(recv, cls, qual,
                                            local_locks)
                    if lid:
                        fs.acquisitions.append((lid, line, col,
                                                guards))
                        return
                # in-place mutation of a lock-owning class's state:
                # self.q.append(...) is a write to self.q
                attr = self_attr(recv)
                if attr is not None and meth in MUTATOR_METHODS:
                    record_write(attr, node, guards)
            if is_jit_callable(func):
                fs.blocking.append(
                    (line, col, "jit-compiled dispatch", "", guards))
                return
            spec = call_spec(func)
            if spec:
                fs.calls.append((spec, line, col, guards))

        def visit(node: ast.AST, guards: Tuple[str, ...]) -> None:
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)) and \
                    node is not fn:
                # nested def: its body runs later, outside the
                # current guard scope; summarise it separately
                self._summarize_function(node, cls,
                                         prefix=f"{qual}.")
                return
            if isinstance(node, ast.Lambda):
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                new = list(guards)
                for item in node.items:
                    lid = self._lock_id_for(item.context_expr, cls,
                                            qual, local_locks)
                    if lid:
                        fs.acquisitions.append(
                            (lid, item.context_expr.lineno,
                             item.context_expr.col_offset,
                             tuple(new)))
                        new.append(lid)
                    for sub in ast.iter_child_nodes(item.context_expr):
                        visit(sub, guards)
                for stmt in node.body:
                    visit(stmt, tuple(new))
                return
            if isinstance(node, (ast.Assign, ast.AnnAssign,
                                 ast.AugAssign)):
                targets = node.targets if isinstance(
                    node, ast.Assign) else [node.target]
                for t in targets:
                    attr = self_attr(t)
                    if attr is None and isinstance(
                            t, ast.Subscript):
                        attr = self_attr(t.value)
                    if attr is not None:
                        record_write(attr, node, guards)
            if isinstance(node, ast.Delete):
                for t in node.targets:
                    attr = self_attr(t)
                    if attr is None and isinstance(t, ast.Subscript):
                        attr = self_attr(t.value)
                    if attr is not None:
                        record_write(attr, node, guards)
            if isinstance(node, ast.Call):
                handle_call(node, guards)
            for child in ast.iter_child_nodes(node):
                visit(child, guards)

        for stmt in fn.body:
            visit(stmt, ())


def summarize_file(path: str, relpath: str) -> FileSummary:
    try:
        with open(path, encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
    except (OSError, UnicodeDecodeError, SyntaxError) as e:
        return FileSummary(
            relpath=relpath, module=module_name_for(path), classes={},
            functions={}, module_locks={}, module_waitables={},
            module_jit_vars=[], module_var_types={},
            module_thread_targets=[], suppressions={},
            parse_error=str(e))
    s = _FileSummarizer(relpath, module_name_for(path), tree, source)
    return FileSummary(
        relpath=relpath, module=s.module, classes=s.classes,
        functions=s.functions, module_locks=s.module_locks,
        module_waitables=s.module_waitables,
        module_jit_vars=sorted(set(s.module_jit_vars)),
        module_var_types=s.module_var_types,
        module_thread_targets=sorted(set(s.module_thread_targets)),
        suppressions=s.suppressions,
        donation_findings=dataflow.analyze_donation_use(tree),
        lifecycle_findings=dataflow.analyze_lifecycle(tree),
        routes=dataflow.extract_routes(tree),
        metrics=dataflow.extract_metrics(tree))


# -- the index --------------------------------------------------------


class ProjectIndex:
    """Resolved whole-package view + lazily computed graph closures.

    Function ids are ``module::qual`` (``fengshen_tpu.fleet.router::
    Router._attempt``), lock ids ``module::Class.attr`` /
    ``module::VAR`` / ``module::fn.name`` — stable across hosts."""

    def __init__(self, files: Dict[str, FileSummary]) -> None:
        self.files = files
        self.by_module: Dict[str, FileSummary] = {}
        for fsum in files.values():
            self.by_module[fsum.module] = fsum
        # fn id -> (FileSummary, FunctionSummary)
        self.functions: Dict[str, Tuple[FileSummary, FunctionSummary]]
        self.functions = {}
        for rel in sorted(files):
            fsum = files[rel]
            for q in sorted(fsum.functions):
                self.functions[f"{fsum.module}::{q}"] = \
                    (fsum, fsum.functions[q])
        self._edges: Optional[Dict[str, List[Tuple[str, int, int,
                                                   Tuple[str, ...]]]]]
        self._edges = None
        self._callers: Optional[Dict[str, List[Tuple[str, Tuple[str,
                                                                ...]]]]]
        self._callers = None
        self._held: Optional[Dict[str, Set[str]]] = None
        self._blocking: Optional[Dict[str, List]] = None
        self._acquired: Optional[Dict[str, Dict[str, List[str]]]] = None
        self._confined: Optional[Set[str]] = None

    # -- resolution ---------------------------------------------------

    def _resolve_class_ref(self, fsum: FileSummary,
                           ref: str) -> Optional[Tuple[str, str]]:
        """class reference -> (module, class name) when indexed."""
        if "." not in ref:
            if ref in fsum.classes:
                return (fsum.module, ref)
            return None
        mod, leaf = ref.rsplit(".", 1)
        target = self.by_module.get(mod)
        if target and leaf in target.classes:
            return (mod, leaf)
        return None

    def resolve_call(self, fn_id: str, spec: str) -> List[str]:
        """Resolve one recorded call spec to candidate fn ids."""
        fsum, fs = self.functions[fn_id]
        kind, _, rest = spec.partition(":")
        out: List[str] = []
        if kind == "self" and fs.cls is not None:
            cand = f"{fsum.module}::{fs.cls}.{rest}"
            if cand in self.functions:
                out.append(cand)
        elif kind == "name":
            # bare name: module-level function, or a sibling nested
            # def in the same enclosing function
            cand = f"{fsum.module}::{rest}"
            if cand in self.functions:
                out.append(cand)
            if "." in fs.qual:
                parent = fs.qual.rsplit(".", 1)[0]
                cand = f"{fsum.module}::{parent}.{rest}"
                if cand in self.functions:
                    out.append(cand)
        elif kind == "obj" and fs.cls is not None:
            attr, _, meth = rest.partition(".")
            cs = fsum.classes.get(fs.cls)
            if cs and attr in cs.attr_types:
                rc = self._resolve_class_ref(fsum, cs.attr_types[attr])
                if rc:
                    cand = f"{rc[0]}::{rc[1]}.{meth}"
                    if cand in self.functions:
                        out.append(cand)
        elif kind == "qual":
            out.extend(self._resolve_qual(fsum, rest))
        return out

    def _resolve_qual(self, fsum: FileSummary, qn: str) -> List[str]:
        parts = qn.split(".")
        # longest-prefix module match
        for i in range(len(parts) - 1, 0, -1):
            mod = ".".join(parts[:i])
            target = self.by_module.get(mod)
            if target is None:
                continue
            tail = parts[i:]
            if len(tail) == 1 and tail[0] in target.functions:
                return [f"{mod}::{tail[0]}"]
            if len(tail) == 2:
                cls_or_var, meth = tail
                if f"{cls_or_var}.{meth}" in target.functions:
                    return [f"{mod}::{cls_or_var}.{meth}"]
                if cls_or_var in target.module_var_types:
                    rc = self._resolve_class_ref(
                        target, target.module_var_types[cls_or_var])
                    if rc:
                        cand = f"{rc[0]}::{rc[1]}.{meth}"
                        if cand in self.functions:
                            return [cand]
            return []
        return []

    # -- graphs -------------------------------------------------------

    def edges(self) -> Dict[str, List[Tuple[str, int, int,
                                            Tuple[str, ...]]]]:
        if self._edges is None:
            self._edges = {}
            for fn_id in self.functions:
                _, fs = self.functions[fn_id]
                out: List[Tuple[str, int, int, Tuple[str, ...]]] = []
                for spec, line, col, guards in fs.calls:
                    for callee in self.resolve_call(fn_id, spec):
                        out.append((callee, line, col, guards))
                self._edges[fn_id] = out
        return self._edges

    def callers(self) -> Dict[str, List[Tuple[str, Tuple[str, ...]]]]:
        """callee -> [(caller id, guards at the call site)]."""
        if self._callers is None:
            self._callers = {}
            for fn_id in sorted(self.edges()):
                for callee, _l, _c, guards in self.edges()[fn_id]:
                    self._callers.setdefault(callee, []).append(
                        (fn_id, guards))
        return self._callers

    def class_lock_ids(self, module: str, cls: ClassSummary,
                       ) -> Set[str]:
        return {f"{module}::{cls.name}.{a}" for a in cls.lock_attrs}

    def guaranteed_held(self) -> Dict[str, Set[str]]:
        """fn id -> locks provably held at EVERY resolved call site
        (plus the ``*_locked`` naming convention: such a method of a
        lock-owning class asserts its class locks are held)."""
        if self._held is not None:
            return self._held
        callers = self.callers()
        all_locks: Set[str] = set()
        for fn_id in self.functions:
            _, fs = self.functions[fn_id]
            for _a, _l, _c, g in fs.writes:
                all_locks.update(g)
            for lid, _l, _c, held in fs.acquisitions:
                all_locks.add(lid)
                all_locks.update(held)
        held: Dict[str, Set[str]] = {}
        convention: Dict[str, Set[str]] = {}
        for fn_id in self.functions:
            fsum, fs = self.functions[fn_id]
            conv: Set[str] = set()
            if fs.name.endswith("_locked") and fs.cls:
                cs = fsum.classes.get(fs.cls)
                if cs and cs.lock_attrs:
                    conv = self.class_lock_ids(fsum.module, cs)
            convention[fn_id] = conv
            held[fn_id] = set(all_locks) if callers.get(fn_id) \
                else set(conv)
        changed = True
        while changed:
            changed = False
            for fn_id in sorted(self.functions):
                sites = callers.get(fn_id)
                if not sites:
                    continue
                new: Optional[Set[str]] = None
                for caller, guards in sites:
                    site_held = set(guards) | held.get(caller, set())
                    new = site_held if new is None else new & site_held
                new = (new or set()) | convention[fn_id]
                if new != held[fn_id]:
                    held[fn_id] = new
                    changed = True
        self._held = held
        return held

    def blocking_closure(self) -> Dict[str, List[Tuple[str, str,
                                                       List[str]]]]:
        """fn id -> [(description, exempt lock, witness chain)] of
        blocking operations reachable from its body (its own ops plus
        resolved callees', chains capped for readability)."""
        if self._blocking is not None:
            return self._blocking
        closure: Dict[str, Dict[Tuple[str, str], List[str]]] = {}
        for fn_id in self.functions:
            _, fs = self.functions[fn_id]
            own: Dict[Tuple[str, str], List[str]] = {}
            for line, _col, desc, exempt, _g in sorted(fs.blocking):
                own.setdefault((desc, exempt), [f"{fn_id}:{line}"])
            closure[fn_id] = own
        changed = True
        while changed:
            changed = False
            for fn_id in sorted(self.functions):
                mine = closure[fn_id]
                for callee, line, _c, _g in self.edges()[fn_id]:
                    for key, chain in closure[callee].items():
                        if key not in mine and len(chain) < 6:
                            mine[key] = [f"{fn_id}:{line}"] + chain
                            changed = True
        self._blocking = {
            fn_id: sorted((d, e, c) for (d, e), c in m.items())
            for fn_id, m in closure.items()}
        return self._blocking

    def acquired_closure(self) -> Dict[str, Dict[str, List[str]]]:
        """fn id -> {lock id: witness chain} of locks acquired in the
        function or any resolved callee."""
        if self._acquired is not None:
            return self._acquired
        closure: Dict[str, Dict[str, List[str]]] = {}
        for fn_id in self.functions:
            _, fs = self.functions[fn_id]
            own: Dict[str, List[str]] = {}
            for lid, line, _c, _h in sorted(fs.acquisitions):
                own.setdefault(lid, [f"{fn_id}:{line}"])
            closure[fn_id] = own
        changed = True
        while changed:
            changed = False
            for fn_id in sorted(self.functions):
                mine = closure[fn_id]
                for callee, line, _c, _g in self.edges()[fn_id]:
                    for lid, chain in closure[callee].items():
                        if lid not in mine and len(chain) < 6:
                            mine[lid] = [f"{fn_id}:{line}"] + chain
                            changed = True
        self._acquired = closure
        return closure

    def thread_confined(self) -> Set[str]:
        """Private functions that only ever run on a dedicated owned
        thread (the scheduler-thread escape hatch): thread targets,
        plus private helpers all of whose resolved callers are
        confined."""
        if self._confined is not None:
            return self._confined
        entries: Set[str] = set()
        for rel in sorted(self.files):
            fsum = self.files[rel]
            for name in fsum.module_thread_targets:
                fid = f"{fsum.module}::{name}"
                if fid in self.functions:
                    entries.add(fid)
            for cname in sorted(fsum.classes):
                cs = fsum.classes[cname]
                for meth in cs.thread_targets:
                    fid = f"{fsum.module}::{cname}.{meth}"
                    if fid in self.functions:
                        entries.add(fid)
        confined = set(entries)
        callers = self.callers()
        changed = True
        while changed:
            changed = False
            for fn_id in sorted(self.functions):
                if fn_id in confined:
                    continue
                _, fs = self.functions[fn_id]
                if not fs.name.startswith("_"):
                    continue  # public: callable from anywhere
                sites = callers.get(fn_id)
                if sites and all(c in confined for c, _g in sites):
                    confined.add(fn_id)
                    changed = True
        self._confined = confined
        return confined

    def relpath_of(self, fn_id: str) -> str:
        return self.functions[fn_id][0].relpath

    def describe_site(self, site: str) -> str:
        """'module::qual:line' -> 'relpath:line (qual)'."""
        fn_id, _, line = site.rpartition(":")
        if fn_id in self.functions:
            fsum, fs = self.functions[fn_id]
            return f"{fsum.relpath}:{line} ({fs.qual})"
        return site

    def is_suppressed(self, relpath: str, line: int,
                      rule_id: str) -> bool:
        fsum = self.files.get(relpath)
        if fsum is None:
            return False
        rules = fsum.suppressions.get(line)
        if rules is None:
            return False
        return not rules or rule_id in rules


# -- building + caching -----------------------------------------------

#: in-process memo: stat signature of the file set -> ProjectIndex.
#: Keeps the test suite's many whole-package runs at one build.
_MEMO: Dict[Tuple, ProjectIndex] = {}
_MEMO_CAP = 8


def _relpath(path: str, root: str) -> str:
    rel = os.path.relpath(os.path.abspath(path), root)
    return rel.replace(os.sep, "/")


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 16), b""):
            h.update(chunk)
    return h.hexdigest()


def build_index(paths: Iterable[str], project_root: str,
                cache_path: Optional[str] = None) -> ProjectIndex:
    """Build (or load) the project index for ``paths``.

    ``cache_path`` enables the on-disk cache: per-file summaries keyed
    by content sha256, so an incremental run only re-parses files
    whose bytes changed. The produced index is identical with a cold,
    warm, or stale cache — the cache can only save time, never change
    findings."""
    files = sorted(set(iter_py_files(paths)))
    sig = tuple((p, os.path.getmtime(p), os.path.getsize(p))
                for p in files) + (project_root,)
    LAST_BUILD_STATS.update(files=len(files), cache_hits=0,
                            cache_misses=0, memo_hit=0)
    memo = _MEMO.get(sig)
    if memo is not None and cache_path is None:
        LAST_BUILD_STATS["memo_hit"] = 1
        return memo

    cache: Dict[str, dict] = {}
    if cache_path and os.path.exists(cache_path):
        try:
            with open(cache_path, encoding="utf-8") as f:
                raw = json.load(f)
            if raw.get("version") == INDEX_CACHE_VERSION:
                cache = raw.get("files", {})
        except (OSError, ValueError):
            cache = {}  # unreadable cache == cold cache

    summaries: Dict[str, FileSummary] = {}
    out_cache: Dict[str, dict] = {}
    for path in files:
        rel = _relpath(path, project_root)
        sha = _sha256(path)
        entry = cache.get(rel)
        if entry is not None and entry.get("sha") == sha:
            try:
                summaries[rel] = FileSummary.from_dict(
                    entry["summary"])
                out_cache[rel] = entry
                LAST_BUILD_STATS["cache_hits"] += 1
                continue
            except (KeyError, TypeError, ValueError):
                pass  # corrupt entry: fall through to re-summarise
        summary = summarize_file(path, rel)
        summaries[rel] = summary
        out_cache[rel] = {"sha": sha, "summary": summary.to_dict()}
        LAST_BUILD_STATS["cache_misses"] += 1

    if cache_path:
        tmp = cache_path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"version": INDEX_CACHE_VERSION,
                           "files": out_cache}, f, sort_keys=True)
            os.replace(tmp, cache_path)
        except OSError:
            pass  # a read-only checkout still lints, just uncached

    index = ProjectIndex(summaries)
    if len(_MEMO) >= _MEMO_CAP:
        _MEMO.pop(next(iter(_MEMO)))
    _MEMO[sig] = index
    return index
