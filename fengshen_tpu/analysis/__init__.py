"""fslint — AST-based SPMD hazard analyzer for fengshen_tpu.

Catches JAX/SPMD-specific bugs (host divergence, blocking transfers,
retrace hazards, sharding typos, nondeterministic iteration, blanket
excepts) at review time instead of step 40k on 256 chips. Pure stdlib;
never imports jax. See docs/static_analysis.md for the rule catalog,
the suppression/baseline workflow, and how to write a new rule.

CLI: ``python -m fengshen_tpu.analysis [paths] [--select/--ignore]
[--json]``. Library: ``check_paths(paths, make_rules())``.
"""

from fengshen_tpu.analysis.engine import (Finding, check_file,
                                          check_paths,
                                          default_project_root)
from fengshen_tpu.analysis.project import ProjectIndex, build_index
from fengshen_tpu.analysis.registry import (ProjectRule, Rule,
                                            all_rule_ids, make_rules,
                                            register)

__all__ = [
    "Finding", "ProjectIndex", "ProjectRule", "Rule", "all_rule_ids",
    "build_index", "check_file", "check_paths",
    "default_project_root", "make_rules", "register",
]
