"""fslint rule modules — importing this package registers every rule.

To add rule 7: drop a module here with a ``@register``-decorated
``Rule`` subclass (~50 lines, see any sibling) and import it below.
"""

from fengshen_tpu.analysis.rules import (  # noqa: F401
    blanket_except,
    blocking_transfer,
    blocking_under_lock,
    host_divergence,
    lock_order,
    metrics_in_traced_code,
    nondet_iteration,
    partition_spec_axes,
    retrace_hazard,
    unguarded_shared_state,
)
