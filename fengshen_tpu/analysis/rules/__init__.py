"""fslint rule modules — importing this package registers every rule.

To add rule 7: drop a module here with a ``@register``-decorated
``Rule`` subclass (~50 lines, see any sibling) and import it below.
"""

from fengshen_tpu.analysis.rules import (  # noqa: F401
    api_surface_parity,
    blanket_except,
    blocking_transfer,
    blocking_under_lock,
    donated_buffer_use,
    host_divergence,
    lock_order,
    metric_contract,
    metrics_in_traced_code,
    nondet_iteration,
    partition_spec_axes,
    resource_lifecycle,
    retrace_hazard,
    unguarded_shared_state,
)
