"""blocking-transfer: device->host syncs inside step hot paths.

``.item()`` / ``.tolist()``, ``float()/int()/bool()`` on array-derived
values, ``np.asarray``, and ``jax.device_get`` inside a traced
function either raise ``ConcretizationTypeError`` at trace time (on
tracers) or — worse — silently force a blocking device->host transfer
per step on values closed over from outside the trace, stalling the
dispatch pipeline the trainer works hard to keep async
(docs/performance.md). Either way the right fix is the same: keep the
hot path pure, pull scalars out ONCE outside the step.

Precision: a cheap per-function taint pass separates array-derived
values from trace-time-static host math, so ``int(cfg.hidden_size *
8 / 3)`` or ``int(mesh.shape[axis])`` in a flax ``__call__`` stays
clean while ``float(loss)`` on a value computed from a batch operand
fires. Taint seeds are the traced function's parameters (arrays by
convention; ``self``/``cls`` and params annotated as plain Python
scalars are exempt) plus ``jnp``/``jax`` call results; ``.shape`` /
``.dtype``-style metadata reads and subscript *indices* launder taint
(host-static), everything else propagates it.
"""

from __future__ import annotations

import ast
from typing import Dict, Optional, Set

from fengshen_tpu.analysis.registry import Rule, register

SYNC_METHOD_CALLS = frozenset({"item", "tolist", "block_until_ready"})
SYNC_FREE_CALLS = frozenset({
    "jax.device_get",
    "numpy.asarray", "numpy.array", "numpy.asanyarray",
})
SCALAR_CASTS = frozenset({"float", "int", "bool"})

#: attribute reads on an array that yield host-static metadata
METADATA_ATTRS = frozenset({"shape", "dtype", "ndim", "size", "sharding"})
#: parameter annotations marking a host scalar (never an array)
SCALAR_ANNOTATIONS = frozenset({"int", "float", "bool", "str", "bytes"})
#: call roots whose results are host scalars even on tainted args
HOST_MATH_ROOTS = frozenset({"math", "len", "max", "min", "abs",
                             "round", "sum", "sorted", "range"})
ARRAY_ROOTS = ("jax", "jax.numpy")


def _is_scalar_annotation(ann) -> bool:
    if isinstance(ann, ast.Name):
        return ann.id in SCALAR_ANNOTATIONS
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return ann.value in SCALAR_ANNOTATIONS
    if isinstance(ann, ast.Subscript):  # Optional[int] etc.
        return _is_scalar_annotation(ann.slice)
    return False


class _Taint:
    """Array-taint over one function scope (nested defs excluded)."""

    def __init__(self, fn, ctx) -> None:
        self.ctx = ctx
        self.names: Set[str] = set()
        for arg in (*fn.args.posonlyargs, *fn.args.args,
                    *fn.args.kwonlyargs):
            if arg.arg in ("self", "cls"):
                continue
            if arg.annotation is not None and \
                    _is_scalar_annotation(arg.annotation):
                continue
            self.names.add(arg.arg)
        stmts = [s for s in ast.walk(fn)
                 if isinstance(s, (ast.Assign, ast.AugAssign,
                                   ast.AnnAssign, ast.For, ast.AsyncFor,
                                   ast.comprehension, ast.NamedExpr))
                 and self._owner(s, fn)]
        # two passes: catches simple later-assigned-earlier-used loops
        for _ in range(2):
            for s in stmts:
                self._absorb(s)

    def _owner(self, node, fn) -> bool:
        for anc in self.ctx.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return anc is fn
        return False

    def _absorb(self, stmt) -> None:
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.comprehension)):
            # `for x in xs:` — iterating a tainted array yields tainted
            # elements
            value, targets = stmt.iter, [stmt.target]
        elif isinstance(stmt, ast.Assign):
            value, targets = stmt.value, stmt.targets
        else:  # AnnAssign / AugAssign / NamedExpr
            value, targets = stmt.value, [stmt.target]
        if value is None or not self.tainted(value):
            return
        for tgt in targets:
            for leaf in ast.walk(tgt):
                if isinstance(leaf, ast.Name):
                    self.names.add(leaf.id)

    def tainted(self, expr) -> bool:
        if isinstance(expr, ast.Name):
            return expr.id in self.names
        if isinstance(expr, ast.Attribute):
            if expr.attr in METADATA_ATTRS:
                return False  # x.shape / x.dtype are host-static
            return self.tainted(expr.value)
        if isinstance(expr, ast.Subscript):
            return self.tainted(expr.value)  # index taint is laundered
        if isinstance(expr, (ast.BinOp,)):
            return self.tainted(expr.left) or self.tainted(expr.right)
        if isinstance(expr, ast.UnaryOp):
            return self.tainted(expr.operand)
        if isinstance(expr, ast.Compare):
            return self.tainted(expr.left) or \
                any(self.tainted(c) for c in expr.comparators)
        if isinstance(expr, (ast.Tuple, ast.List)):
            return any(self.tainted(e) for e in expr.elts)
        if isinstance(expr, ast.IfExp):
            return self.tainted(expr.body) or self.tainted(expr.orelse)
        if isinstance(expr, ast.Call):
            qn = self.ctx.qualname(expr.func)
            if qn is not None:
                root = qn.split(".", 1)[0]
                if any(qn == r or qn.startswith(r + ".")
                       for r in ARRAY_ROOTS) or root == "jnp":
                    return True
                if root in HOST_MATH_ROOTS:
                    return False
            if isinstance(expr.func, ast.Attribute) and \
                    self.tainted(expr.func.value):
                return True  # method on an array: (x ** 2).mean()
            return any(self.tainted(a) for a in expr.args)
        return False


@register
class BlockingTransfer(Rule):
    id = "blocking-transfer"
    hint = ("keep the traced body pure jnp; read scalars outside the "
            "step (after dispatch), or use lax primitives instead of "
            "host round-trips")
    NODE_TYPES = (ast.Call,)

    def begin_file(self, ctx) -> None:
        self._taints: Dict[int, _Taint] = {}

    def _taint_for(self, node, ctx) -> Optional[_Taint]:
        fns = ctx.enclosing_functions(node)
        fn = next((f for f in fns
                   if isinstance(f, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))), None)
        if fn is None:
            return None
        key = id(fn)
        if key not in self._taints:
            self._taints[key] = _Taint(fn, ctx)
        return self._taints[key]

    def check(self, node: ast.Call, ctx):
        if not ctx.in_traced_context(node):
            return
        func = node.func
        if isinstance(func, ast.Attribute) and \
                func.attr in SYNC_METHOD_CALLS and not node.args:
            taint = self._taint_for(node, ctx)
            if taint is not None and taint.tainted(func.value):
                yield node, (f"`.{func.attr}()` on an array in a "
                             "traced function forces a blocking "
                             "device->host transfer (or a "
                             "ConcretizationTypeError on a tracer)")
            return
        qn = ctx.qualname(func)
        if qn in SYNC_FREE_CALLS:
            taint = self._taint_for(node, ctx)
            if taint is not None and node.args and \
                    taint.tainted(node.args[0]):
                yield node, (f"`{qn}` on an array in a traced function "
                             "pulls it to host memory every step — use "
                             "jnp, or lift the conversion out of the "
                             "trace")
            return
        if qn in SCALAR_CASTS and node.args and not isinstance(
                node.args[0], ast.Constant):
            taint = self._taint_for(node, ctx)
            if taint is not None and taint.tainted(node.args[0]):
                yield node, (f"`{qn}(...)` on an array-derived value "
                             "in a traced function concretizes it on "
                             "host — tracers raise, closures silently "
                             "sync per step")
