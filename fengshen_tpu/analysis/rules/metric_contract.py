"""metric-contract: every ``fstpu_*`` family is registered once,
consistently, and documented.

Two checks over the index's metric registration sites (name, kind,
label set — extracted by the dataflow tier from every
``registry.counter/gauge/histogram`` get-or-create call with a
statically constant name):

- **collision**: the same metric name registered with a different
  label set or kind anywhere in the package. Prometheus registries
  reject that at runtime — but only on the code path that registers
  second, which may be a rarely-exercised serve mode.
- **docs drift**: the code table diffed against the "Metrics
  reference" table in ``docs/observability.md``. A registered family
  missing from the docs, a documented family no longer registered,
  and a label-set/kind mismatch are all findings, so the docs can't
  rot silently.

Families whose registration is dynamic — the serving outcome counters
built in a dict comprehension and the AOT cache counters whose name
is a parameter — are invisible to static extraction; they are
documented but live on ``DYNAMIC_REGISTRATIONS`` below so the rule
lands with a genuinely empty baseline instead of day-one
suppressions. The docs diff only runs when the analyzed set includes
package files and the docs file exists (fixture runs in tmp roots
check collisions only); documented-but-unregistered findings anchor
at the registry module so whole-package runs surface them.
"""

from __future__ import annotations

import os
from typing import Dict, Iterator, List, Tuple

from fengshen_tpu.analysis.dataflow import parse_metric_docs
from fengshen_tpu.analysis.registry import ProjectRule, register

#: documented families whose get-or-create site has no statically
#: constant name. Keep in sync with docs/observability.md — a name
#: here must still be documented; it is only excused from the
#: "documented but never registered" direction of the diff.
DYNAMIC_REGISTRATIONS = frozenset({
    # serving/metrics.py builds its outcome counters in a dict
    # comprehension over the name list
    "fstpu_serving_admitted_total",
    "fstpu_serving_cancelled_total",
    "fstpu_serving_completed_total",
    "fstpu_serving_deferred_admissions_total",
    "fstpu_serving_expired_total",
    "fstpu_serving_rejected_draining_total",
    "fstpu_serving_rejected_duplicate_total",
    "fstpu_serving_rejected_prompt_too_long_total",
    "fstpu_serving_rejected_queue_full_total",
    # aot/cache.py registers through a helper taking the name as a
    # parameter
    "fstpu_aot_cache_errors_total",
    "fstpu_aot_cache_hits_total",
    "fstpu_aot_cache_misses_total",
})

#: where documented-but-unregistered findings anchor (the registry
#: module is the natural owner of the metric namespace and is always
#: part of a whole-package run)
_DOCS_ANCHOR = "fengshen_tpu/observability/registry.py"
_DOCS_PATH = os.path.join("docs", "observability.md")


@register
class MetricContract(ProjectRule):
    id = "metric-contract"
    hint = ("register each fstpu_* family exactly once per "
            "(name, labelnames, kind) and mirror it in the metrics "
            "reference table of docs/observability.md; dynamic "
            "registrations belong on the rule's "
            "DYNAMIC_REGISTRATIONS allowlist")

    def check_project(self, index) -> Iterator[
            Tuple[str, int, int, str]]:
        # (name) -> list of (relpath, line, col, kind, sorted labels)
        sites: Dict[str, List[Tuple[str, int, int, str,
                                    Tuple[str, ...]]]] = {}
        package_run = False
        for rel in sorted(index.files):
            if rel.startswith("fengshen_tpu/"):
                package_run = True
            for name, kind, labels, line, col in \
                    index.files[rel].metrics:
                sites.setdefault(name, []).append(
                    (rel, line, col, kind, tuple(sorted(labels))))

        # -- collisions (always, including fixture runs) -------------
        for name in sorted(sites):
            recs = sorted(sites[name])
            first = recs[0]
            for rec in recs[1:]:
                if (rec[3], rec[4]) == (first[3], first[4]):
                    continue
                yield (rec[0], rec[1], rec[2],
                       f"metric `{name}` registered as {rec[3]}"
                       f"{{{','.join(rec[4])}}} here but as "
                       f"{first[3]}{{{','.join(first[4])}}} at "
                       f"{first[0]}:{first[1]} — same family, "
                       f"conflicting schema")

        # -- docs drift (package runs with the docs present) ---------
        docs_file = os.path.join(self.project_root, _DOCS_PATH)
        if not package_run or not os.path.isfile(docs_file):
            return
        try:
            with open(docs_file, encoding="utf-8") as f:
                documented = parse_metric_docs(f.read())
        except (OSError, UnicodeDecodeError):
            return

        code: Dict[str, Tuple[str, int, int, str,
                              Tuple[str, ...]]] = {}
        for name in sorted(sites):
            pkg = [r for r in sorted(sites[name])
                   if r[0].startswith("fengshen_tpu/")]
            if pkg:
                code[name] = pkg[0]

        for name in sorted(set(code) - set(documented)):
            rel, line, col, kind, labels = code[name]
            yield (rel, line, col,
                   f"metric `{name}` ({kind}"
                   f"{{{','.join(labels)}}}) is registered but "
                   f"missing from the metrics reference table in "
                   f"{_DOCS_PATH}")
        for name in sorted(set(documented) - set(code)):
            if name in DYNAMIC_REGISTRATIONS:
                continue
            labels, kind, doc_line = documented[name]
            yield (_DOCS_ANCHOR, 1, 0,
                   f"metric `{name}` is documented "
                   f"({_DOCS_PATH}:{doc_line}) but never "
                   f"registered in the package — remove the row or "
                   f"add it to DYNAMIC_REGISTRATIONS if the "
                   f"registration is dynamic")
        for name in sorted(set(documented) & set(code)):
            rel, line, col, kind, labels = code[name]
            doc_labels, doc_kind, doc_line = documented[name]
            if (kind, labels) != (doc_kind, doc_labels):
                yield (rel, line, col,
                       f"metric `{name}` is {kind}"
                       f"{{{','.join(labels)}}} in code but "
                       f"documented as {doc_kind}"
                       f"{{{','.join(doc_labels)}}} at "
                       f"{_DOCS_PATH}:{doc_line}")
