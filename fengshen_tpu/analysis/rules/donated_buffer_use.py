"""donated-buffer-use: a buffer read after being donated to a jitted
call is reading freed device memory.

``jax.jit(fn, donate_argnums=...)`` (and the AOT-cache wrappers
``cached_compile`` / ``CachedFunction`` / ``aot.wrap``, which forward
the keyword) hands the listed arguments' buffers to XLA — after the
call dispatches, the caller's reference is invalid and reading it
returns garbage or raises, depending on backend and timing. That makes
this the classic silent-corruption bug: it passes on CPU test runs
(where donation is a no-op) and corrupts state on TPU.

The dataflow tier (``analysis/dataflow.py``) binds
``donate_argnums``/``donate_argnames`` positions through the wrapping
call to the variable the callable lands in (a local, a module var, or
a ``self._step_jit`` attribute), arms the caller variables passed in
donated positions at every call through that binding, and flags any
read on any later path. Rebinding from the outputs —

    state = step(state, batch)          # clean: donate + rebind
    cache, logits = self._decode_jit(tokens, cache, positions)

disarms the variable; that is the doctrine (docs/static_analysis.md,
"Donation & lifecycle doctrine"). The findings carried by each
``FileSummary`` were computed flow-sensitively at index time, so this
rule is a cheap re-emission and warm-cache runs stay fast.
"""

from __future__ import annotations

from typing import Iterator, Tuple

from fengshen_tpu.analysis.registry import ProjectRule, register


@register
class DonatedBufferUse(ProjectRule):
    id = "donated-buffer-use"
    hint = ("rebind the variable from the call's outputs "
            "(`x = f(x, ...)`) — a donated buffer is invalidated "
            "by dispatch; if the read is intentional (e.g. CPU-only "
            "path), suppress with a rationale")

    def check_project(self, index) -> Iterator[
            Tuple[str, int, int, str]]:
        for rel in sorted(index.files):
            fsum = index.files[rel]
            for (var, callee, bind_line, call_line, read_line,
                 read_col) in fsum.donation_findings:
                yield (rel, read_line, read_col,
                       f"`{var}` is read after being donated to "
                       f"`{callee}()` — witness: donate_argnums "
                       f"bound at {rel}:{bind_line} -> donating "
                       f"call at :{call_line} -> read at "
                       f":{read_line}")
