"""unguarded-shared-state: inconsistent lock discipline on one attr.

The RacerD-style heuristic: once a class protects an attribute with
one of its own locks *somewhere*, every other mutation of that
attribute is claiming the same invariant — a write outside the guard
is either a latent race (PR 11's JsonlSink interleaved-writer bug was
exactly this shape) or an undocumented threading assumption that the
next editor will break. The rule fires on attributes of a lock-owning
class that are mutated BOTH under a class lock and outside any,
counting in-place container mutation (``self.q.append``) as a write.

Escape hatches, in line with the serving stack's actual doctrine:

- ``__init__``-family writes: construction happens-before sharing
- guard inference through the call graph: a helper that every
  resolved call site enters with the lock held (``step()`` →
  ``_step_locked()``) is guarded, as is anything honouring the
  ``*_locked`` naming convention
- thread confinement: private methods that only ever run on the
  class's own dedicated thread (``threading.Thread(target=self._loop)``
  and helpers reachable solely from it) are single-writer by
  construction
"""

from __future__ import annotations

from typing import Iterator, List, Tuple

from fengshen_tpu.analysis.registry import ProjectRule, register


@register
class UnguardedSharedState(ProjectRule):
    id = "unguarded-shared-state"
    hint = ("take the owning lock around this mutation (or move it "
            "into __init__/the owning thread, or suppress with the "
            "threading rationale)")

    def check_project(self, index) -> Iterator[Tuple[str, int, int,
                                                     str]]:
        held = index.guaranteed_held()
        confined = index.thread_confined()
        for relpath in sorted(index.files):
            fsum = index.files[relpath]
            for cname in sorted(fsum.classes):
                cs = fsum.classes[cname]
                if not cs.lock_attrs:
                    continue
                lock_ids = index.class_lock_ids(fsum.module, cs)
                # infra attributes follow their own lifecycle (locks
                # and threads are created once, never raced over)
                skip = set(cs.lock_attrs) | set(cs.waitable_attrs) \
                    | set(cs.thread_attrs) | set(cs.jit_attrs)
                guarded: dict = {}
                unguarded: dict = {}
                for q in sorted(fsum.functions):
                    fs = fsum.functions[q]
                    if fs.cls != cname:
                        continue
                    fn_id = f"{fsum.module}::{q}"
                    base = held.get(fn_id, set())
                    is_init = fs.name in ("__init__", "__post_init__",
                                          "__new__", "__del__",
                                          "__set_name__")
                    for attr, line, col, site_guards in fs.writes:
                        if attr in skip:
                            continue
                        eff = set(site_guards) | base
                        if eff & lock_ids:
                            guarded.setdefault(attr, []).append(
                                (relpath, line, col, q))
                        elif not is_init and fn_id not in confined:
                            unguarded.setdefault(attr, []).append(
                                (relpath, line, col, q))
                for attr in sorted(set(guarded) & set(unguarded)):
                    g0 = min(guarded[attr])
                    locks = " / ".join(
                        sorted(a for a in cs.lock_attrs))
                    for rel, line, col, q in sorted(unguarded[attr]):
                        yield (rel, line, col,
                               f"`self.{attr}` of {cname} is mutated "
                               f"here without the class lock "
                               f"(`{locks}`), but under it at "
                               f"{g0[0]}:{g0[1]} ({g0[3]}) — "
                               "inconsistent guarding is a data race")
