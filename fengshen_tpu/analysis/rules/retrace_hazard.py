"""retrace-hazard: jitted functions that recompile (or constant-bloat).

Two concrete shapes, both seen in the wild:

1. a jit-decorated function closing over a module-level ``jnp`` array —
   the array is baked into every trace as a constant (HBM copy per
   compiled program, and a silent retrace if the global is rebound).
   Pass it as an argument so jit sees it as a traced operand.
2. a jit-decorated function with an unhashable default (``[]``, ``{}``,
   ``set()``) and no ``static_argnums``/``static_argnames`` — jit
   hashes static arguments for its compilation cache; an unhashable
   default either raises at call time or, as a pytree operand, invites
   per-call retraces when callers mutate the shared default.
"""

from __future__ import annotations

import ast

from fengshen_tpu.analysis.registry import Rule, register

#: jnp/np constructors whose module-level results are device/host arrays
ARRAY_MAKERS = frozenset({
    "array", "asarray", "zeros", "ones", "full", "arange", "linspace",
    "eye", "tri", "empty",
})
ARRAY_ROOTS = ("jax.numpy", "numpy", "jax.nn")

JIT_CALLS = frozenset({"jax.jit", "jax.pmap", "jit", "pmap"})


def _jit_decoration(fn, ctx):
    """The jit decorator Call node (for kwargs inspection), True for a
    bare ``@jax.jit``, or None when the function is not jit-decorated."""
    for dec in fn.decorator_list:
        if ctx.qualname(dec) in JIT_CALLS:
            return True
        if isinstance(dec, ast.Call):
            if ctx.qualname(dec.func) in JIT_CALLS:
                return dec
            if ctx.qualname(dec.func) in ("functools.partial", "partial") \
                    and dec.args and \
                    ctx.qualname(dec.args[0]) in JIT_CALLS:
                return dec
    return None


def _has_static_kwarg(dec) -> bool:
    if dec is True or dec is None:
        return False
    return any(kw.arg and kw.arg.startswith("static_")
               for kw in dec.keywords)


@register
class RetraceHazard(Rule):
    id = "retrace-hazard"
    hint = ("pass module-level arrays as arguments; mark unhashable "
            "config via static_argnums/static_argnames or make the "
            "default hashable (None + in-body default)")
    NODE_TYPES = (ast.FunctionDef, ast.AsyncFunctionDef)

    def begin_file(self, ctx) -> None:
        # module-level `X = jnp.zeros(...)`-style array globals
        self._module_arrays = set()
        for stmt in ctx.tree.body:
            if not isinstance(stmt, ast.Assign) or \
                    not isinstance(stmt.value, ast.Call):
                continue
            qn = ctx.qualname(stmt.value.func)
            if qn and qn.rsplit(".", 1)[-1] in ARRAY_MAKERS and \
                    any(qn.startswith(root + ".") for root in ARRAY_ROOTS):
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        self._module_arrays.add(tgt.id)

    def check(self, fn, ctx):
        dec = _jit_decoration(fn, ctx)
        if dec is None:
            return

        if self._module_arrays:
            # python scoping: ANY binding inside the function (param,
            # assignment, for/with/walrus target) makes the name local —
            # a Load of it is not a closure over the module array
            local = {a.arg for a in (*fn.args.args, *fn.args.posonlyargs,
                                     *fn.args.kwonlyargs)}
            local.update(n.id for n in ast.walk(fn)
                         if isinstance(n, ast.Name)
                         and isinstance(n.ctx, ast.Store))
            seen = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Name) and \
                        isinstance(node.ctx, ast.Load) and \
                        node.id in self._module_arrays and \
                        node.id not in local and node.id not in seen:
                    seen.add(node.id)
                    yield node, (
                        f"jitted `{fn.name}` closes over module-level "
                        f"array `{node.id}` — baked into every trace "
                        "as a constant (HBM bloat, silent retrace on "
                        "rebind)")

        if not _has_static_kwarg(dec):
            defaults = (*fn.args.defaults, *fn.args.kw_defaults)
            for d in defaults:
                if isinstance(d, (ast.List, ast.Dict, ast.Set)) or (
                        isinstance(d, ast.Call) and
                        ctx.qualname(d.func) in ("set", "dict", "list")):
                    yield d, (
                        f"jitted `{fn.name}` takes an unhashable "
                        f"default `{ast.unparse(d)}` without "
                        "static_argnums — uncacheable as static, "
                        "retrace-bait as a shared mutable pytree")
