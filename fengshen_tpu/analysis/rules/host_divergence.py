"""host-divergence: host-varying values reached from traced code.

``random.*``, ``time.time()``, ``os.environ``, ``uuid.*`` evaluated
while JAX traces a step function are baked into the compiled program as
constants — each host (and each retrace) bakes a DIFFERENT constant.
When that value feeds a collective, a branch, or pytree structure, the
hosts compile different programs and the pod deadlocks or silently
diverges at step N, exactly the class of bug Megatron-style trainers
make fail at review time instead (ISSUE 2 / arxiv 2104.04473 §B).

Only fires inside traced contexts (jit/grad/vmap'd functions,
scan/cond/while bodies, and functions they call — the engine's
trace-context analysis), so host-side setup code that legitimately
reads the environment stays clean.
"""

from __future__ import annotations

import ast

from fengshen_tpu.analysis.registry import Rule, register

#: dotted prefixes whose call results vary per host / per call
HOST_VARYING_CALLS = (
    "random.",          # python stdlib RNG (module `random` only;
                        # numpy.random / jax.random resolve differently)
    "uuid.",
    "secrets.",
)
HOST_VARYING_EXACT = frozenset({
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "os.getenv", "os.urandom", "os.getpid", "socket.gethostname",
})
#: attribute/subscript roots that are host state
HOST_VARYING_ATTRS = frozenset({"os.environ"})


@register
class HostDivergence(Rule):
    id = "host-divergence"
    hint = ("hoist the host value out of the traced function and pass "
            "it in as an argument (or fold it into the PRNG key / "
            "config before tracing)")
    NODE_TYPES = (ast.Call, ast.Subscript, ast.Attribute)

    def check(self, node: ast.AST, ctx):
        if isinstance(node, ast.Call):
            qn = ctx.qualname(node.func)
            if qn is None:
                return
            hit = qn in HOST_VARYING_EXACT or \
                any(qn.startswith(p) for p in HOST_VARYING_CALLS) or \
                any(qn.startswith(a + ".") or qn == a
                    for a in HOST_VARYING_ATTRS)
        elif isinstance(node, ast.Subscript):
            hit = ctx.qualname(node.value) in HOST_VARYING_ATTRS
        else:
            # Attribute read like `os.environ` passed around (incl. as a
            # call argument: `dict(os.environ)`). Attribute/Subscript
            # parents are excluded only to avoid double-reporting
            # `os.environ.get(...)` / `os.environ[...]`, which the Call
            # and Subscript branches already cover.
            hit = ctx.qualname(node) in HOST_VARYING_ATTRS and \
                not isinstance(ctx.parent(node),
                               (ast.Attribute, ast.Subscript))
        if not hit or not ctx.in_traced_context(node):
            return
        desc = ctx.qualname(node.func if isinstance(node, ast.Call)
                            else node.value if isinstance(node,
                                                          ast.Subscript)
                            else node)
        yield node, (f"`{desc}` inside a traced function bakes a "
                     "host-varying constant into the compiled program — "
                     "hosts trace different programs and diverge (or "
                     "deadlock in collectives)")
