"""nondet-iteration: host-order-dependent loops feeding SPMD state.

Iterating a ``set`` (or set-algebra over ``dict.keys()``) is ordered by
string hashes, and ``PYTHONHASHSEED`` differs across hosts unless
pinned — so a loop like ``for name in set(params) - skip:`` that emits
collectives or builds a pytree runs in a DIFFERENT order on each host:
collectives issue in different sequences (deadlock) or the pytrees
disagree structurally (sharding mismatch at dispatch). ``sorted(...)``
around the set is the one-token fix and is recognized as clean.

Only set-typed iterables of non-literal origin fire; a literal
``{"a", "b"}`` display is visible at review time and plain
``dict``/``dict.keys()`` iteration is insertion-ordered (deterministic
when the insertions are).
"""

from __future__ import annotations

import ast

from fengshen_tpu.analysis.registry import Rule, register

SET_CONSTRUCTORS = frozenset({"set", "frozenset"})
SET_METHODS = frozenset({"intersection", "union", "difference",
                         "symmetric_difference"})
COLLECTIVES = frozenset({
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
    "ppermute", "pbroadcast", "axis_index", "psum_scatter",
    "with_sharding_constraint", "device_put", "make_array_from_callback",
})
PYTREE_BUILD_METHODS = frozenset({"append", "add", "update",
                                  "setdefault", "extend"})


def _is_setish(expr, ctx) -> bool:
    if isinstance(expr, ast.Call):
        qn = ctx.qualname(expr.func)
        if qn in SET_CONSTRUCTORS:
            return True
        if isinstance(expr.func, ast.Attribute) and \
                expr.func.attr in SET_METHODS:
            return True
        return False
    if isinstance(expr, ast.BinOp) and isinstance(
            expr.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)):
        # set algebra: `set(a) - b`, `a.keys() & b.keys()` are sets
        return _is_setish(expr.left, ctx) or _is_setish(expr.right, ctx) \
            or _is_keys_call(expr.left) or _is_keys_call(expr.right)
    return False


def _is_keys_call(expr) -> bool:
    return isinstance(expr, ast.Call) and \
        isinstance(expr.func, ast.Attribute) and \
        expr.func.attr == "keys" and not expr.args


def _body_feeds_spmd(body, ctx) -> bool:
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                qn = ctx.qualname(node.func)
                last = qn.rsplit(".", 1)[-1] if qn else (
                    node.func.attr if isinstance(node.func,
                                                 ast.Attribute) else None)
                if last in COLLECTIVES:
                    return True
                if last in PYTREE_BUILD_METHODS or \
                        last in ("dict", "list"):
                    return True
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                if any(isinstance(t, ast.Subscript) for t in targets):
                    return True
    return False


@register
class NondetIteration(Rule):
    id = "nondet-iteration"
    hint = ("wrap the iterable in sorted(...) so every host walks the "
            "same order")
    NODE_TYPES = (ast.For,)

    def check(self, node: ast.For, ctx):
        if not _is_setish(node.iter, ctx):
            return
        if not _body_feeds_spmd(node.body, ctx):
            return
        yield node, (
            "iterating a set whose order is PYTHONHASHSEED-dependent "
            "while the body emits collectives / builds pytrees — hosts "
            "walk different orders and the SPMD programs disagree")
