"""blocking-under-lock: slow/blocking work inside a critical section.

A ``with lock:`` body is a convoy point: every thread that touches the
same lock stalls for as long as the holder runs. Sleeping, socket or
HTTP I/O, child processes, queue waits, device syncs
(``block_until_ready`` / ``jax.device_get``) and jit-compiled
dispatches all turn a microsecond critical section into a
milliseconds-to-unbounded one — the fleet router holding its placement
lock across a replica HTTP call would serialise the whole fleet on one
slow replica. The rule flags blocking operations lexically inside a
guard scope AND — through the cross-module call graph — calls whose
resolved callee chain reaches one (``with self._lock:
self._flush()`` where ``_flush`` eventually does ``urlopen``).

``Condition.wait`` on the condition currently held is exempt (waiting
releases it — that is the point of a condition variable); waiting on
a *different* lock's condition or an ``Event`` while holding a lock
still fires.
"""

from __future__ import annotations

from typing import Iterator, Set, Tuple

from fengshen_tpu.analysis.registry import ProjectRule, register


def _offending(guards: Tuple[str, ...], exempt: str) -> Set[str]:
    held = set(guards)
    if exempt:
        held.discard(exempt)
    return held


def _short(lock_id: str) -> str:
    return lock_id.split("::", 1)[-1]


@register
class BlockingUnderLock(ProjectRule):
    id = "blocking-under-lock"
    hint = ("move the blocking call outside the `with lock:` body — "
            "snapshot state under the lock, do the slow work after "
            "releasing it")

    def check_project(self, index) -> Iterator[Tuple[str, int, int,
                                                     str]]:
        closure = index.blocking_closure()
        edges = index.edges()
        for fn_id in sorted(index.functions):
            fsum, fs = index.functions[fn_id]
            # direct blocking ops under a lexical guard
            for line, col, desc, exempt, guards in sorted(fs.blocking):
                bad = _offending(guards, exempt)
                if bad:
                    locks = ", ".join(sorted(_short(b) for b in bad))
                    yield (fsum.relpath, line, col,
                           f"{desc} while holding `{locks}` — every "
                           "contender on the lock stalls behind it")
            # calls under a lexical guard whose callee chain blocks
            seen_lines: Set[int] = set()
            for callee, line, col, guards in sorted(edges[fn_id]):
                if not guards or line in seen_lines:
                    continue
                for desc, exempt, chain in closure.get(callee, ()):
                    bad = _offending(guards, exempt)
                    if not bad:
                        continue
                    locks = ", ".join(sorted(_short(b) for b in bad))
                    site = index.describe_site(chain[-1])
                    via = index.functions[callee][1].qual
                    yield (fsum.relpath, line, col,
                           f"call into `{via}` reaches {desc} (at "
                           f"{site}) while holding `{locks}` — the "
                           "critical section blocks on it")
                    seen_lines.add(line)
                    break
