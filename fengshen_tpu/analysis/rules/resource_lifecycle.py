"""resource-lifecycle: typestate over the package's declared
acquire/release protocols.

The paged-KV ``BlockAllocator`` (``alloc``/``free``), the serving slot
pool (``assign_slot``/``assign_paged`` vs ``rollback_slots``/
``reset_free_slots``), lane handoff (``export_lane`` vs
``detach_lane`` after the peer ACKs), engine drain
(``begin_drain``/``idle``) and bare file handles (``open``/``close``)
all pair an acquire with a hand-written release. The dataflow tier
(``analysis/dataflow.py``, ``PROTOCOLS``) walks every function with a
small typestate engine and flags:

- **leak-on-exception-path**: a raising call runs while the resource
  is held and no ``finally`` (or broad ``except`` that releases)
  covers it — the release is skipped when that call raises. The
  witness names the acquire site and the first unprotected call.
- **double-release**: the same resource released twice along a single
  path.

Conservatism runs toward silence: ``with``-managed acquires, escaped
resources (stored on ``self``, returned, aliased), and the
allocator's ``if blocks is None`` exhaustion/null-block branch are
never flagged. Findings are computed at index time and cached in each
``FileSummary``; this rule re-emits them with witness chains like
``blocking-under-lock``.
"""

from __future__ import annotations

from typing import Iterator, Tuple

from fengshen_tpu.analysis.registry import ProjectRule, register


@register
class ResourceLifecycle(ProjectRule):
    id = "resource-lifecycle"
    hint = ("release in a `finally` (or a broad `except` that "
            "releases and re-raises) so an exception between acquire "
            "and release cannot leak the resource; for deliberate "
            "ownership transfer, suppress with a rationale")

    def check_project(self, index) -> Iterator[
            Tuple[str, int, int, str]]:
        for rel in sorted(index.files):
            fsum = index.files[rel]
            for (kind, protocol, var, line, col, other_line,
                 detail) in fsum.lifecycle_findings:
                if kind == "leak":
                    yield (rel, line, col,
                           f"`{var}` ({protocol} acquire at "
                           f"{rel}:{line}) has no release on the "
                           f"path where `{detail}(...)` at "
                           f":{other_line} raises — witness: "
                           f"acquire :{line} -> raising call "
                           f":{other_line} -> release skipped")
                else:
                    yield (rel, line, col,
                           f"`{var}` ({protocol}) is released twice "
                           f"on one path — witness: first release "
                           f"at {rel}:{other_line} -> released "
                           f"again at :{line}")
