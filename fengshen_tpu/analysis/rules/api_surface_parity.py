"""api-surface-parity: the twin API implementations must expose the
same routes.

``api/main.py`` carries two complete HTTP surfaces — the fastapi app
(``@app.get("/healthz")`` decorators) and the dependency-free stdlib
``BaseHTTPRequestHandler`` (``do_GET`` comparing ``self.path``). Every
endpoint must exist on BOTH, a "BOTH paths" invariant that used to be
enforced by N hand-pinned tests. This rule checks it at lint time:
the dataflow tier extracts each file's route set per surface
(decorator paths; ``self.path`` equality and ``.startswith`` prefix
dispatch), normalises path parameters and f-string prefixes to ``*``,
and diffs the ``(METHOD, path)`` sets whenever one file carries both
surfaces. A file with a single surface (``fleet/server.py``'s
stdlib-only router front) has nothing to diff and is skipped.
"""

from __future__ import annotations

from typing import Dict, Iterator, Set, Tuple

from fengshen_tpu.analysis.dataflow import normalize_route
from fengshen_tpu.analysis.registry import ProjectRule, register


@register
class ApiSurfaceParity(ProjectRule):
    id = "api-surface-parity"
    hint = ("register the route on both the fastapi app and the "
            "stdlib dispatcher (or remove it from both) — the twin "
            "surfaces must stay interchangeable")

    def check_project(self, index) -> Iterator[
            Tuple[str, int, int, str]]:
        for rel in sorted(index.files):
            fsum = index.files[rel]
            surfaces: Dict[str, Dict[Tuple[str, str],
                                     Tuple[int, int]]] = {
                "fastapi": {}, "stdlib": {}}
            for surface, method, raw, line, col in fsum.routes:
                key = (method, normalize_route(raw))
                surfaces[surface].setdefault(key, (line, col))
            fa, sl = surfaces["fastapi"], surfaces["stdlib"]
            if not fa or not sl:
                continue  # single-surface file: nothing to diff
            for key in sorted(set(fa) - set(sl)):
                line, col = fa[key]
                yield (rel, line, col,
                       f"route {key[0]} {key[1]} is registered on "
                       f"the fastapi surface but has no stdlib "
                       f"dispatcher match — witness: fastapi "
                       f"{len(fa)} routes vs stdlib {len(sl)} in "
                       f"{rel}")
            for key in sorted(set(sl) - set(fa)):
                line, col = sl[key]
                yield (rel, line, col,
                       f"route {key[0]} {key[1]} is dispatched on "
                       f"the stdlib surface but has no fastapi "
                       f"decorator match — witness: stdlib "
                       f"{len(sl)} routes vs fastapi {len(fa)} in "
                       f"{rel}")
