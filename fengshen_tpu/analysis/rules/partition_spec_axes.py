"""partition-spec-axes: every PartitionSpec axis must exist on the mesh.

GSPMD treats an unknown axis name in a ``PartitionSpec`` as "not on
the mesh" and SILENTLY REPLICATES that dimension — a typo like
``P("tenosr", "fsdp")`` compiles, runs, and quietly costs a full copy
of the tensor on every device (the exact failure mode the round-2
dryrun caught as an involuntary-rematerialization warning, except
without the warning). The authoritative axis vocabulary is parsed from
``fengshen_tpu/parallel/mesh.py`` (the ``*_AXIS`` constants), so a new
mesh axis is one edit away from being legal everywhere.
"""

from __future__ import annotations

import ast
import os
from typing import FrozenSet, Optional

from fengshen_tpu.analysis.registry import Rule, register

MESH_FILE = os.path.join("fengshen_tpu", "parallel", "mesh.py")

_AXES_CACHE: dict = {}


def mesh_axes(project_root: str) -> Optional[FrozenSet[str]]:
    """Axis names declared in mesh.py, parsed statically (no jax
    import). None when mesh.py is missing (rule stays silent)."""
    if project_root in _AXES_CACHE:
        return _AXES_CACHE[project_root]
    path = os.path.join(project_root, MESH_FILE)
    axes = None
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        tree = None
    if tree is not None:
        found = set()
        for stmt in tree.body:
            if isinstance(stmt, ast.Assign):
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name) and \
                            tgt.id.endswith("_AXIS") and \
                            isinstance(stmt.value, ast.Constant) and \
                            isinstance(stmt.value.value, str):
                        found.add(stmt.value.value)
        axes = frozenset(found) or None
    _AXES_CACHE[project_root] = axes
    return axes


def _is_spec_call(node: ast.Call, ctx) -> bool:
    qn = ctx.qualname(node.func)
    if qn and qn.rsplit(".", 1)[-1] == "PartitionSpec":
        return True
    # the ubiquitous `from jax.sharding import PartitionSpec as P` plus
    # re-exports: accept a call on a bare name `P` that the file
    # imported (alias origin ending in .P or .PartitionSpec)
    if isinstance(node.func, ast.Name) and node.func.id == "P":
        origin = ctx.aliases.get("P")
        return origin is None or origin.rsplit(".", 1)[-1] in ("P",
                                                               "PartitionSpec")
    return False


def _axis_strings(arg):
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        yield arg, arg.value
    elif isinstance(arg, (ast.Tuple, ast.List)):
        for elt in arg.elts:
            yield from _axis_strings(elt)


@register
class PartitionSpecAxes(Rule):
    id = "partition-spec-axes"
    hint = ("use an axis name declared in fengshen_tpu/parallel/mesh.py "
            "(MESH_AXES) — unknown names silently replicate the "
            "dimension")
    NODE_TYPES = (ast.Call,)

    def begin_file(self, ctx) -> None:
        self._axes = mesh_axes(ctx.project_root)

    def check(self, node: ast.Call, ctx):
        if self._axes is None or not _is_spec_call(node, ctx):
            return
        for sub, value in ((s, v) for a in node.args
                           for s, v in _axis_strings(a)):
            if value not in self._axes:
                yield sub, (
                    f"PartitionSpec axis {value!r} is not a mesh axis "
                    f"({', '.join(sorted(self._axes))}) — XLA will "
                    "silently replicate this dimension")
