"""partition-spec-axes: every PartitionSpec axis must exist on the mesh.

GSPMD treats an unknown axis name in a ``PartitionSpec`` as "not on
the mesh" and SILENTLY REPLICATES that dimension — a typo like
``P("tenosr", "fsdp")`` compiles, runs, and quietly costs a full copy
of the tensor on every device (the exact failure mode the round-2
dryrun caught as an involuntary-rematerialization warning, except
without the warning). The authoritative axis vocabulary is parsed from
``fengshen_tpu/parallel/mesh.py`` (the ``*_AXIS`` constants), so a new
mesh axis is one edit away from being legal everywhere.

The rule also validates the declarative sharding subsystem's tables
(docs/sharding.md) statically:

- ``*PARAM_LOGICAL_AXES`` tables (regex → logical-axis tuple): every
  logical name must be declared in
  ``fengshen_tpu/sharding/axes.py`` (``LOGICAL_AXES``) — an unknown
  name would raise at resolution, but only on the code path that
  resolves it; the fast lane catches it at definition site.
- ``*LOGICAL_AXIS_RULES`` tables (logical axis → mesh axis): the
  logical side must be in the vocabulary and any LITERAL mesh axis
  must exist on the mesh (names imported from mesh.py — ``*_AXIS`` /
  ``BATCH_AXES`` — are definitionally valid and accepted as-is).
"""

from __future__ import annotations

import ast
import os
from typing import FrozenSet, Optional

from fengshen_tpu.analysis.registry import Rule, register

MESH_FILE = os.path.join("fengshen_tpu", "parallel", "mesh.py")
AXES_FILE = os.path.join("fengshen_tpu", "sharding", "axes.py")

_AXES_CACHE: dict = {}
_LOGICAL_CACHE: dict = {}


def mesh_axes(project_root: str) -> Optional[FrozenSet[str]]:
    """Axis names declared in mesh.py, parsed statically (no jax
    import). None when mesh.py is missing (rule stays silent)."""
    if project_root in _AXES_CACHE:
        return _AXES_CACHE[project_root]
    path = os.path.join(project_root, MESH_FILE)
    axes = None
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        tree = None
    if tree is not None:
        found = set()
        for stmt in tree.body:
            if isinstance(stmt, ast.Assign):
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name) and \
                            tgt.id.endswith("_AXIS") and \
                            isinstance(stmt.value, ast.Constant) and \
                            isinstance(stmt.value.value, str):
                        found.add(stmt.value.value)
        axes = frozenset(found) or None
    _AXES_CACHE[project_root] = axes
    return axes


def logical_axes(project_root: str) -> Optional[FrozenSet[str]]:
    """Logical-axis vocabulary from sharding/axes.py (the flat literal
    ``LOGICAL_AXES`` tuple), parsed statically. None when the file is
    missing (the table checks stay silent)."""
    if project_root in _LOGICAL_CACHE:
        return _LOGICAL_CACHE[project_root]
    path = os.path.join(project_root, AXES_FILE)
    axes = None
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        tree = None
    if tree is not None:
        found = set()
        for stmt in tree.body:
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = stmt.targets
            elif isinstance(stmt, ast.AnnAssign) and stmt.value:
                targets = [stmt.target]
            for tgt in targets:
                if isinstance(tgt, ast.Name) and \
                        tgt.id == "LOGICAL_AXES" and \
                        isinstance(stmt.value, (ast.Tuple, ast.List)):
                    for elt in stmt.value.elts:
                        if isinstance(elt, ast.Constant) and \
                                isinstance(elt.value, str):
                            found.add(elt.value)
        axes = frozenset(found) or None
    _LOGICAL_CACHE[project_root] = axes
    return axes


def _is_spec_call(node: ast.Call, ctx) -> bool:
    qn = ctx.qualname(node.func)
    if qn and qn.rsplit(".", 1)[-1] == "PartitionSpec":
        return True
    # the ubiquitous `from jax.sharding import PartitionSpec as P` plus
    # re-exports: accept a call on a bare name `P` that the file
    # imported (alias origin ending in .P or .PartitionSpec)
    if isinstance(node.func, ast.Name) and node.func.id == "P":
        origin = ctx.aliases.get("P")
        return origin is None or origin.rsplit(".", 1)[-1] in ("P",
                                                               "PartitionSpec")
    return False


def _axis_strings(arg):
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        yield arg, arg.value
    elif isinstance(arg, (ast.Tuple, ast.List)):
        for elt in arg.elts:
            yield from _axis_strings(elt)


def _table_entries(value):
    """2-tuples of a literal list/tuple table, skipping anything not
    shaped like one (computed tables are out of scope)."""
    if not isinstance(value, (ast.Tuple, ast.List)):
        return
    for elt in value.elts:
        if isinstance(elt, (ast.Tuple, ast.List)) and len(elt.elts) == 2:
            yield elt.elts[0], elt.elts[1]


def _assign_name(node) -> Optional[str]:
    if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
            isinstance(node.targets[0], ast.Name):
        return node.targets[0].id
    if isinstance(node, ast.AnnAssign) and node.value is not None and \
            isinstance(node.target, ast.Name):
        return node.target.id
    return None


@register
class PartitionSpecAxes(Rule):
    id = "partition-spec-axes"
    hint = ("use an axis name declared in fengshen_tpu/parallel/mesh.py "
            "(MESH_AXES) — unknown names silently replicate the "
            "dimension; logical-axis names come from "
            "fengshen_tpu/sharding/axes.py (LOGICAL_AXES)")
    NODE_TYPES = (ast.Call, ast.Assign, ast.AnnAssign)

    def begin_file(self, ctx) -> None:
        self._axes = mesh_axes(ctx.project_root)
        self._logical = logical_axes(ctx.project_root)

    def check(self, node, ctx):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            yield from self._check_tables(node)
            return
        if self._axes is None or not _is_spec_call(node, ctx):
            return
        for sub, value in ((s, v) for a in node.args
                           for s, v in _axis_strings(a)):
            if value not in self._axes:
                yield sub, (
                    f"PartitionSpec axis {value!r} is not a mesh axis "
                    f"({', '.join(sorted(self._axes))}) — XLA will "
                    "silently replicate this dimension")

    def _check_tables(self, node):
        """The declarative sharding tables (docs/sharding.md)."""
        name = _assign_name(node)
        if name is None or self._logical is None:
            return
        if name.endswith("PARAM_LOGICAL_AXES"):
            for _, axes in _table_entries(node.value):
                for sub, value in _axis_strings(axes):
                    if value not in self._logical:
                        yield sub, (
                            f"logical axis {value!r} is not declared in "
                            "fengshen_tpu/sharding/axes.py "
                            "(LOGICAL_AXES) — resolution would raise "
                            "at run time")
        elif name.endswith("LOGICAL_AXIS_RULES"):
            for logical, mesh_axis in _table_entries(node.value):
                for sub, value in _axis_strings(logical):
                    if value not in self._logical:
                        yield sub, (
                            f"logical axis {value!r} is not declared in "
                            "fengshen_tpu/sharding/axes.py "
                            "(LOGICAL_AXES)")
                if self._axes is None:
                    continue
                for sub, value in _axis_strings(mesh_axis):
                    if value not in self._axes:
                        yield sub, (
                            f"rules table maps to {value!r}, not a mesh "
                            f"axis ({', '.join(sorted(self._axes))}) — "
                            "XLA would silently replicate every dim "
                            "with this role")
