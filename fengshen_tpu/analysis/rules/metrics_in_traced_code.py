"""metrics-in-traced-code: registry mutations reached from traced code.

The observability registry's mutators (``inc``/``dec``/``set``/
``observe`` — docs/observability.md) are host-side Python: called from
a jit-traced function they run ONCE, at trace time, and the compiled
program never touches them again — the counter silently stops counting
(and, worse, records a tracer-shaped nonsense sample at every retrace).
The fix is structural: return the value out of the traced function and
record it on the host, exactly how the Trainer pulls
``bad_step_count`` out of the step metrics.

Precision: only receivers PROVEN metric-shaped fire — a name (or
``self.<attr>``) assigned from a ``counter(...)``/``gauge(...)``/
``histogram(...)`` factory call, a direct factory chain
(``registry.counter("x").inc()``), or a ``labels(...)`` hop off either.
Bare ``.set()`` on anything else — above all jax's ubiquitous
``arr.at[i].set(v)`` — never matches, because its receiver is a
subscript, not a tracked metric binding.
"""

from __future__ import annotations

import ast

from fengshen_tpu.analysis.registry import Rule, register

#: mutation methods of observability.registry metric objects
MUTATOR_METHODS = frozenset({"inc", "dec", "set", "observe"})
#: registry factory methods whose results are metric objects
FACTORY_METHODS = frozenset({"counter", "gauge", "histogram"})


def _target_key(node: ast.AST):
    """Binding key for an assignment target: plain name or self-attr."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return f"self.{node.attr}"
    return None


def _is_factory_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in FACTORY_METHODS)


@register
class MetricsInTracedCode(Rule):
    id = "metrics-in-traced-code"
    hint = ("metrics record at TRACE time only inside jit — return the "
            "value out of the traced function and mutate the registry "
            "on the host (see docs/observability.md)")
    NODE_TYPES = (ast.Call,)

    def begin_file(self, ctx) -> None:
        # one pre-pass: every name / self-attr bound to a registry
        # factory result anywhere in the file (module consts, __init__
        # attributes, locals). Instance attributes are also remembered
        # by bare attr name so `stats.tokens.inc()` resolves when
        # `self.tokens = reg.counter(...)` appears in the same file.
        self._metric_bindings = set()
        self._metric_attrs = set()
        for node in ast.walk(ctx.tree):
            value = None
            if isinstance(node, ast.Assign):
                value, targets = node.value, node.targets
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                value, targets = node.value, [node.target]
            else:
                continue
            if not (_is_factory_call(value) or
                    self._is_labels_hop(value)):
                continue
            for t in targets:
                key = _target_key(t)
                if key is not None:
                    self._metric_bindings.add(key)
                    if key.startswith("self."):
                        self._metric_attrs.add(key[len("self."):])

    def _is_metric_expr(self, node: ast.AST) -> bool:
        """Is this expression a metric object? A tracked binding, a
        direct factory chain, or a labels() hop off either."""
        key = _target_key(node)
        if key is not None and key in self._metric_bindings:
            return True
        if isinstance(node, ast.Attribute) and \
                node.attr in self._metric_attrs:
            return True
        if _is_factory_call(node):
            return True
        return self._is_labels_hop(node)

    def _is_labels_hop(self, node: ast.AST) -> bool:
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "labels"
                and self._is_metric_expr(node.func.value))

    def check(self, node: ast.Call, ctx):
        func = node.func
        if not isinstance(func, ast.Attribute) or \
                func.attr not in MUTATOR_METHODS:
            return
        if not self._is_metric_expr(func.value):
            return
        if not ctx.in_traced_context(node):
            return
        yield node, (
            f"metric mutation `.{func.attr}(...)` inside a traced "
            "function runs at trace time only — the compiled step "
            "never records it (and retraces record garbage)")
