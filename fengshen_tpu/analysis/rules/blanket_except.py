"""blanket-except: no silent ``except:`` / ``except Exception:``.

AST successor of the regex lint that used to live in
``tests/test_lint_excepts.py`` — same guarantee (resilience code dies
when a blanket handler swallows a real error and turns a crash into a
silently-wrong run), without the regex false positives on strings,
comments, or ``except Exception as e: raise`` spread over lines.

A blanket handler is allowed when the same line carries an explicit
justification marker: ``# noqa: BLE001`` for re-raise/bounded-retry
sites, ``# pragma: no cover`` for defensive probes (both grandfathered
from the regex lint), or a ``# fslint: disable=blanket-except``.
"""

from __future__ import annotations

import ast

from fengshen_tpu.analysis.registry import Rule, register

BLANKET_NAMES = ("Exception", "BaseException")
JUSTIFICATION_MARKERS = ("# noqa: BLE001", "# pragma: no cover")


def _is_blanket(expr) -> bool:
    if expr is None:  # bare `except:`
        return True
    if isinstance(expr, ast.Name):
        return expr.id in BLANKET_NAMES
    if isinstance(expr, ast.Attribute):  # builtins.Exception etc.
        return expr.attr in BLANKET_NAMES
    if isinstance(expr, ast.Tuple):
        return any(_is_blanket(e) for e in expr.elts)
    return False


@register
class BlanketExcept(Rule):
    id = "blanket-except"
    hint = ("catch the specific exception, or justify on the same line "
            "with `# noqa: BLE001` (re-raise/bounded-retry) or "
            "`# pragma: no cover` (defensive probe)")
    NODE_TYPES = (ast.ExceptHandler,)

    def check(self, node: ast.ExceptHandler, ctx):
        if not _is_blanket(node.type):
            return
        line = ctx.line_comment(node.lineno)
        if any(marker in line for marker in JUSTIFICATION_MARKERS):
            return
        what = "bare `except:`" if node.type is None else \
            f"blanket `except {ast.unparse(node.type)}:`"
        yield node, (f"{what} without a justification marker swallows "
                     "real errors (turns crashes into silently-wrong "
                     "runs)")
