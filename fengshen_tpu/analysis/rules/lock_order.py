"""lock-order: inconsistent nested acquisition order across the package.

Two threads taking the same two locks in opposite orders is the
classic ABBA deadlock, and it is invisible per-file: the engine
scheduler holding its condition while bumping a metrics counter
(registry lock) is fine until some exporter thread holds the registry
lock while calling back into the engine. Phase 1 records every
acquisition (``with lock:`` nesting and ``.acquire()``) together with
the locks already held, and follows resolved calls made under a guard
into their transitive acquisitions — so the pair (engine._cv →
registry._lock) is observed even though the two ``with`` statements
live in different modules. The rule then reports every site of an
order that some other site inverts.

Fix direction: pick one global order (document it in
docs/static_analysis.md "Concurrency doctrine") and release the outer
lock before taking the inner one on the minority path.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Tuple

from fengshen_tpu.analysis.registry import ProjectRule, register


def _short(lock_id: str) -> str:
    return lock_id.split("::", 1)[-1]


@register
class LockOrder(ProjectRule):
    id = "lock-order"
    hint = ("acquire these locks in one consistent order everywhere "
            "(or drop the outer lock before taking the inner one)")

    def check_project(self, index) -> Iterator[Tuple[str, int, int,
                                                     str]]:
        acquired = index.acquired_closure()
        edges = index.edges()
        # (outer, inner) -> [(relpath, line, col, how)]
        pairs: Dict[Tuple[str, str],
                    List[Tuple[str, int, int, str]]] = {}

        for fn_id in sorted(index.functions):
            fsum, fs = index.functions[fn_id]
            for lock, line, col, held in sorted(fs.acquisitions):
                for outer in held:
                    if outer != lock:
                        pairs.setdefault((outer, lock), []).append(
                            (fsum.relpath, line, col, "acquired here"))
            for callee, line, col, guards in sorted(edges[fn_id]):
                if not guards:
                    continue
                via = index.functions[callee][1].qual
                for lock in sorted(acquired.get(callee, ())):
                    for outer in guards:
                        if outer == lock:
                            continue
                        pairs.setdefault((outer, lock), []).append(
                            (fsum.relpath, line, col,
                             f"acquired via `{via}`"))

        emitted = set()
        for outer, inner in sorted(pairs):
            if (inner, outer) not in pairs:
                continue
            other = sorted(pairs[(inner, outer)])[0]
            for relpath, line, col, how in sorted(pairs[(outer,
                                                         inner)]):
                key = (relpath, line, col, outer, inner)
                if key in emitted:
                    continue
                emitted.add(key)
                yield (relpath, line, col,
                       f"`{_short(inner)}` {how} while holding "
                       f"`{_short(outer)}`, but the reverse order is "
                       f"taken at {other[0]}:{other[1]} — ABBA "
                       "deadlock hazard")
