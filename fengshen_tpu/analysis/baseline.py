"""Checked-in baseline for legacy fslint findings.

A baseline entry pins one pre-existing finding by ``(path, rule,
code)`` — the stripped source line, NOT the line number — so unrelated
edits above a legacy site don't invalidate the baseline, while any
edit to the flagged line itself surfaces the finding again (you
touched it, you fix it). Line numbers are stored purely for human
navigation and refreshed by ``--write-baseline``.

The file is JSON with findings sorted by (path, line, rule) and
written with sorted keys + a trailing newline, so regeneration is
byte-stable across hosts and CI diffs are meaningful.
"""

from __future__ import annotations

import json
import os
from collections import Counter
from typing import Dict, List, Optional, Tuple

from fengshen_tpu.analysis.engine import Finding

BASELINE_VERSION = 1
DEFAULT_BASELINE = os.path.join("fengshen_tpu", "analysis",
                                "fslint_baseline.json")


def default_baseline_path(project_root: str) -> str:
    return os.path.join(project_root, DEFAULT_BASELINE)


def load_baseline(path: str) -> List[Dict[str, object]]:
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path} has version {data.get('version')!r}; "
            f"this fslint understands version {BASELINE_VERSION}")
    return data["findings"]


def write_baseline(path: str, findings: List[Finding],
                   keep_entries: Optional[List[Dict[str, object]]] = None,
                   ) -> None:
    """Write the baseline from ``findings``, carrying over
    ``keep_entries`` verbatim — entries outside the current run's
    rule/path scope that a partial ``--write-baseline`` (with
    ``--select``/``--ignore`` or explicit paths) must not delete."""
    entries = [{"path": f.path, "line": f.line, "rule": f.rule,
                "code": f.code, "justification": "TODO: why is this "
                "finding acceptable?"}
               for f in sorted(findings, key=Finding.sort_key)]
    # keep hand-written justifications across regeneration
    old = {}
    if os.path.exists(path):
        for e in load_baseline(path):
            old[(e["path"], e["rule"], e["code"])] = e.get("justification")
    for e in entries:
        prev = old.get((e["path"], e["rule"], e["code"]))
        if prev:
            e["justification"] = prev
    entries.extend(keep_entries or [])
    entries.sort(key=lambda e: (e["path"], e["line"], e["rule"]))
    payload = {"version": BASELINE_VERSION, "findings": entries}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")


def split_by_baseline(
        findings: List[Finding],
        baseline_entries: List[Dict[str, object]],
) -> Tuple[List[Finding], List[Finding], List[Dict[str, object]]]:
    """(new, baselined, stale-baseline-entries).

    Each baseline entry absorbs at most one current finding with the
    same (path, rule, code); leftovers on either side are reported.
    """
    budget = Counter((e["path"], e["rule"], e["code"])
                     for e in baseline_entries)
    new: List[Finding] = []
    baselined: List[Finding] = []
    for f in sorted(findings, key=Finding.sort_key):
        key = (f.path, f.rule, f.code)
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            baselined.append(f)
        else:
            new.append(f)
    stale = []
    remaining = dict(budget)
    for e in baseline_entries:
        key = (e["path"], e["rule"], e["code"])
        if remaining.get(key, 0) > 0:
            remaining[key] -= 1
            stale.append(e)
    return new, baselined, stale
