"""fslint command line.

    python -m fengshen_tpu.analysis [paths...] [options]

Exit codes: 0 = clean (everything baselined or nothing found),
1 = non-baselined findings, 2 = bad invocation (unknown rule id,
unreadable baseline).

``--format=json`` (alias: ``--json``) emits a machine-readable report
sorted by (path, line, col, rule) — byte-stable across hosts, so CI
can diff runs directly. ``--format=github`` emits one
``::error file=...`` workflow annotation per finding.
``--format=sarif`` emits a minimal SARIF 2.1.0 log (same ordering
guarantee) for code-scanning upload. ``--stats`` reports run
statistics — files indexed, rules run, index-cache hits/misses, wall
time — inside the JSON report (``"stats"`` key) or on stderr for the
other formats; wall time is the only non-deterministic field, so
determinism tests compare reports without ``--stats``.

``--changed`` lints only files touched in the working tree (``git
diff --name-only HEAD`` plus untracked files), but the project rules
still index the whole package — cross-module context is never
narrowed, only where findings may be reported.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import List, Optional

from fengshen_tpu.analysis import baseline as baseline_mod
from fengshen_tpu.analysis import engine
from fengshen_tpu.analysis import project as project_mod
from fengshen_tpu.analysis.registry import all_rule_ids, make_rules


def _rule_list(value: str) -> List[str]:
    return [r.strip() for r in value.split(",") if r.strip()]


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m fengshen_tpu.analysis",
        description="fslint — AST-based SPMD hazard analyzer for "
                    "fengshen_tpu (see docs/static_analysis.md)")
    parser.add_argument(
        "paths", nargs="*",
        help="files/directories to analyze (default: the fengshen_tpu "
             "package)")
    parser.add_argument(
        "--select", type=_rule_list, default=[],
        help="comma-separated rule ids to run (default: all)")
    parser.add_argument(
        "--ignore", type=_rule_list, default=[],
        help="comma-separated rule ids to skip")
    parser.add_argument(
        "--json", action="store_true",
        help="alias for --format=json")
    parser.add_argument(
        "--format", choices=("text", "json", "github", "sarif"),
        default=None,
        help="output format (default: text; 'github' emits workflow "
             "::error annotations; 'sarif' a SARIF 2.1.0 log)")
    parser.add_argument(
        "--stats", action="store_true",
        help="report run statistics (files, rules, index-cache "
             "hits/misses, wall time) in the JSON report or on stderr")
    parser.add_argument(
        "--changed", action="store_true",
        help="lint only files changed vs HEAD (plus untracked files); "
             "project rules still index the whole package")
    parser.add_argument(
        "--index-cache", default=None, metavar="PATH",
        help="project-index cache file (default: "
             "<repo>/.fslint_cache.json; content-hash keyed, only "
             "ever a speedup)")
    parser.add_argument(
        "--no-index-cache", action="store_true",
        help="build the project index from scratch, no cache file")
    parser.add_argument(
        "--baseline", default=None, metavar="PATH",
        help="baseline file (default: fengshen_tpu/analysis/"
             "fslint_baseline.json)")
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="report every finding, ignoring the baseline")
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="rewrite the baseline from the current findings and exit 0")
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print registered rule ids and exit")
    return parser


def _changed_py_files(root: str) -> List[str]:
    """Working-tree changes vs HEAD plus untracked files, .py only,
    sorted and deduplicated. Raises RuntimeError when git is absent
    or the root is not a repository."""
    rels: List[str] = []
    for cmd in (["git", "diff", "--name-only", "HEAD", "--"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            proc = subprocess.run(cmd, cwd=root, capture_output=True,
                                  text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired) as e:
            raise RuntimeError(str(e))
        if proc.returncode != 0:
            raise RuntimeError(proc.stderr.strip() or
                               f"{' '.join(cmd)} failed")
        rels.extend(proc.stdout.splitlines())
    out = []
    for rel in sorted({r.strip() for r in rels}):
        if not rel.endswith(".py"):
            continue
        path = os.path.join(root, rel.replace("/", os.sep))
        if os.path.isfile(path):   # deleted files stay listed by diff
            out.append(path)
    return out


def _sarif_report(findings, rules) -> dict:
    """Minimal SARIF 2.1.0 log. Rules sorted by id, results in the
    engine's (path, line, col, rule) order — byte-stable for the same
    inputs, like the JSON report."""
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "fslint",
                "informationUri":
                    "https://github.com/IDEA-CCNL/Fengshenbang-LM",
                "rules": [
                    {"id": r.id,
                     "shortDescription": {"text": r.hint}}
                    for r in sorted(rules, key=lambda r: r.id)],
            }},
            "results": [
                {"ruleId": f.rule,
                 "level": "error",
                 "message": {"text": f"{f.message} (fix: {f.hint})"},
                 "locations": [{"physicalLocation": {
                     "artifactLocation": {"uri": f.path},
                     "region": {"startLine": f.line,
                                "startColumn": f.col + 1}}}]}
                for f in findings],
        }],
    }


def main(argv: Optional[List[str]] = None) -> int:
    t0 = time.monotonic()
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for rid in all_rule_ids():
            print(rid)
        return 0
    fmt = args.format or ("json" if args.json else "text")

    root = engine.default_project_root()
    paths = args.paths or [os.path.join(root, "fengshen_tpu")]
    try:
        rules = make_rules(select=args.select, ignore=args.ignore)
    except ValueError as e:
        print(f"fslint: {e}", file=sys.stderr)
        return 2

    cache_path: Optional[str] = None
    if not args.no_index_cache:
        cache_path = args.index_cache or \
            os.path.join(root, ".fslint_cache.json")

    index = None
    if args.changed:
        try:
            changed = _changed_py_files(root)
        except RuntimeError as e:
            print(f"fslint: --changed needs git: {e}", file=sys.stderr)
            return 2
        if not changed:
            if fmt == "text":
                print("fslint: no changed python files")
            elif fmt == "json":
                print(json.dumps({"findings": [], "baselined": 0,
                                  "stale_baseline": []},
                                 indent=2, sort_keys=True))
            elif fmt == "sarif":
                print(json.dumps(_sarif_report([], rules),
                                 indent=2, sort_keys=True))
            return 0
        paths = changed
        if any(r.PROJECT for r in rules):
            # cross-module rules always see the full package; only the
            # reporting surface narrows to the changed files
            index = project_mod.build_index(
                list(engine.iter_py_files(
                    [os.path.join(root, "fengshen_tpu")])),
                root, cache_path=cache_path)

    try:
        findings = engine.check_paths(paths, rules, project_root=root,
                                      index=index,
                                      index_cache=cache_path)
    except FileNotFoundError as e:
        print(f"fslint: {e}", file=sys.stderr)
        return 2

    baseline_path = args.baseline or \
        baseline_mod.default_baseline_path(root)
    if args.write_baseline:
        # a partial run (--select/--ignore or explicit paths) must not
        # delete entries it never re-checked: carry over everything
        # outside the active rule set or the analyzed paths
        kept: list = []
        if os.path.exists(baseline_path):
            active = {r.id for r in rules}
            analyzed = [engine._relpath(p, root) for p in paths]

            def covered(rel: str) -> bool:
                return any(rel == a or rel.startswith(a + "/")
                           for a in analyzed)

            try:
                kept = [e for e in baseline_mod.load_baseline(
                            baseline_path)
                        if e["rule"] not in active
                        or not covered(str(e["path"]))]
            except (ValueError, json.JSONDecodeError) as e:
                print(f"fslint: cannot read baseline: {e}",
                      file=sys.stderr)
                return 2
        baseline_mod.write_baseline(baseline_path, findings,
                                    keep_entries=kept)
        print(f"fslint: wrote {len(findings) + len(kept)} finding(s) "
              f"({len(kept)} carried over) to {baseline_path}")
        return 0

    stale: list = []
    baselined: list = []
    if not args.no_baseline:
        try:
            entries = baseline_mod.load_baseline(baseline_path)
        except (ValueError, OSError, json.JSONDecodeError) as e:
            print(f"fslint: cannot read baseline: {e}", file=sys.stderr)
            return 2
        findings, baselined, stale = baseline_mod.split_by_baseline(
            findings, entries)

    stats = {
        "files": project_mod.LAST_BUILD_STATS["files"],
        "rules": len(rules),
        "index_cache_hits": project_mod.LAST_BUILD_STATS["cache_hits"],
        "index_cache_misses":
            project_mod.LAST_BUILD_STATS["cache_misses"],
        "memo_hit": project_mod.LAST_BUILD_STATS["memo_hit"],
        "wall_time_s": round(time.monotonic() - t0, 3),
    }
    if fmt == "json":
        report = {
            "findings": [f.to_dict() for f in findings],
            "baselined": len(baselined),
            "stale_baseline": [
                {"path": e["path"], "rule": e["rule"], "code": e["code"]}
                for e in stale],
        }
        if args.stats:
            report["stats"] = stats
        print(json.dumps(report, indent=2, sort_keys=True))
    elif fmt == "sarif":
        print(json.dumps(_sarif_report(findings, rules),
                         indent=2, sort_keys=True))
    elif fmt == "github":
        for f in findings:
            # workflow-command annotation; messages are single-line by
            # construction, but escape the reserved characters anyway
            msg = f"{f.message} (fix: {f.hint})".replace(
                "%", "%25").replace("\n", "%0A")
            print(f"::error file={f.path},line={f.line},"
                  f"col={f.col + 1},title=fslint {f.rule}::{msg}")
    else:
        for f in findings:
            print(f.render())
        if baselined:
            print(f"fslint: {len(baselined)} baselined finding(s) "
                  "suppressed", file=sys.stderr)
        for e in stale:
            print(f"fslint: stale baseline entry {e['path']} "
                  f"[{e['rule']}] `{e['code']}` no longer fires — "
                  "remove it (or --write-baseline)", file=sys.stderr)
        if not findings:
            print("fslint: clean")
    if args.stats and fmt != "json":
        print("fslint stats: " + json.dumps(stats, sort_keys=True),
              file=sys.stderr)
    return 1 if findings else 0
