"""Rule registry for fslint.

A rule is a class with a unique ``id``, a one-line ``hint`` (the fix
suggestion attached to every finding), and a ``check(node, ctx)``
generator that yields findings for the AST node types it subscribed to
via ``NODE_TYPES``. The engine walks each file's tree exactly once and
dispatches every node to all rules registered for its type — adding a
rule is a new ~50-line module under ``analysis/rules/`` plus an import
in ``rules/__init__.py``; nothing else changes.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Iterator, List, Tuple, Type

#: rule id -> rule class (instantiated fresh per run)
_RULES: Dict[str, Type["Rule"]] = {}


class Rule:
    """Base class for fslint rules.

    Subclasses set:

    - ``id``         — kebab-case rule name (stable; used in suppressions,
      ``--select/--ignore``, and the baseline file)
    - ``hint``       — one-line fix suggestion shown with every finding
    - ``NODE_TYPES`` — tuple of ``ast`` node classes ``check`` wants

    and implement ``check(node, ctx)`` yielding ``(node, message)``
    pairs. ``begin_file(ctx)`` runs before the walk (per-file state),
    ``end_file(ctx)`` after it (whole-file conclusions).
    """

    id: str = ""
    hint: str = ""
    NODE_TYPES: Tuple[type, ...] = ()
    #: True for whole-package rules (see ProjectRule below)
    PROJECT: bool = False

    def begin_file(self, ctx) -> None:  # noqa: B027 - optional hook
        pass

    def check(self, node: ast.AST, ctx) -> Iterator[Tuple[ast.AST, str]]:
        return iter(())

    def end_file(self, ctx) -> Iterator[Tuple[ast.AST, str]]:
        return iter(())


class ProjectRule(Rule):
    """Base class for phase-2b rules that need the whole-package view.

    Instead of per-node dispatch, a project rule implements
    ``check_project(index)`` — called once per run with the
    ``ProjectIndex`` (class/lock inventories, guard scopes, the
    cross-module call graph and its closures; see
    ``analysis/project.py``) — and yields ``(relpath, line, col,
    message)`` tuples. The engine turns those into ``Finding``s,
    honouring per-line suppressions exactly like per-file rules.

    The engine sets ``project_root`` before ``check_project`` so rules
    that diff the index against on-disk artifacts (the metric-contract
    docs tables) can find them; rules must tolerate it being ""."""

    PROJECT = True
    project_root: str = ""

    def check_project(self, index) -> Iterator[
            Tuple[str, int, int, str]]:
        return iter(())


def register(cls: Type[Rule]) -> Type[Rule]:
    """Class decorator: add a rule to the global registry."""
    if not cls.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if cls.id in _RULES:
        raise ValueError(f"duplicate rule id {cls.id!r}")
    _RULES[cls.id] = cls
    return cls


def all_rule_ids() -> List[str]:
    _ensure_loaded()
    return sorted(_RULES)


def make_rules(select: Iterable[str] = (),
               ignore: Iterable[str] = ()) -> List[Rule]:
    """Instantiate the active rule set.

    ``select`` restricts to the given ids (empty = all); ``ignore``
    drops ids from the selection. Unknown ids raise ``ValueError`` so a
    typo in CI config fails loudly instead of silently checking nothing.
    """
    _ensure_loaded()
    select, ignore = list(select), list(ignore)
    unknown = [r for r in (*select, *ignore) if r not in _RULES]
    if unknown:
        raise ValueError(
            f"unknown rule id(s) {unknown}; known: {sorted(_RULES)}")
    active = select or sorted(_RULES)
    return [_RULES[rid]() for rid in active if rid not in ignore]


def _ensure_loaded() -> None:
    # importing the subpackage registers every rule module
    from fengshen_tpu.analysis import rules  # noqa: F401
