"""Phase-1.5 of the analyzer: flow-sensitive per-file dataflow facts.

The project index (``analysis/project.py``) records *where* things
happen — calls, writes, guard scopes. The rules added by the dataflow
tier need to know *what happens next on each path*:

- **donation tracking**: a ``jax.jit(fn, donate_argnums=...)`` /
  ``cached_compile`` / ``CachedFunction`` binding makes specific
  positional arguments of every later call through that binding
  *donated* — the caller's buffer is invalidated by dispatch. The flow
  engine arms the variables passed in donated positions at each call
  site and reports any read on any later path; rebinding from the
  call's outputs (``state = step(state, ...)``) disarms, which is
  exactly the clean idiom.
- **resource lifecycle**: a small typestate engine over the declared
  acquire/release protocols in ``PROTOCOLS`` (allocator alloc/free,
  slot assignment/rollback, lane export/detach, drain, bare file
  handles). It flags a release that can be skipped by an exception
  (acquire .. raising-call .. release with no ``finally`` and no broad
  ``except`` that releases) and double-release along a single path.
- **contract extraction**: the fastapi-decorator and stdlib
  ``do_GET``-dispatch route surfaces, and every ``fstpu_*`` metric
  get-or-create site (name, kind, label set) — cheap facts the
  contract rules diff across files and against docs.

Everything here is pure stdlib ``ast``, runs per file with no project
state, and returns sorted tuples of primitives, so results are cached
in the ``FileSummary`` (content-sha keyed) and stay byte-deterministic
across ``PYTHONHASHSEED`` values.

The analysis is deliberately per-file: a donated callable bound in one
module and called from another is out of scope (no such site exists in
the package — bindings are ``self._step_jit``-style attributes used by
their own class). Conservatism runs toward silence: an unresolvable
``donate_argnums`` expression, an aliased resource, or a branch where
states disagree drops out of tracking instead of guessing.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

# --------------------------------------------------------------------
# shared small helpers
# --------------------------------------------------------------------

_SKIP_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

_TRY_TYPES = (ast.Try,) + ((ast.TryStar,) if hasattr(ast, "TryStar")
                           else ())


def _scan(node: ast.AST) -> Iterator[ast.AST]:
    """ast.walk that does not descend into nested function bodies —
    a closure's reads happen at *its* call time, not here."""
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, _SKIP_SCOPES) and n is not node:
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _int_tuple(node: ast.AST) -> Optional[Tuple[int, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals: List[int] = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and \
                    isinstance(e.value, int) and \
                    not isinstance(e.value, bool):
                vals.append(e.value)
            else:
                return None
        return tuple(vals)
    return None


def _str_tuple(node: ast.AST) -> Optional[Tuple[str, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals: List[str] = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and \
                    isinstance(e.value, str):
                vals.append(e.value)
            else:
                return None
        return tuple(vals)
    return None


def _expr_text(node: ast.AST) -> str:
    """Dotted text of a Name/Attribute chain (``self._allocator``);
    "" for anything else (calls, subscripts)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _expr_text(node.value)
        return f"{base}.{node.attr}" if base else ""
    return ""


def _as_route_str(node: ast.AST) -> Optional[str]:
    """A string constant, with f-strings collapsed to their literal
    prefix + ``*`` (``f"/api/{task}"`` -> ``/api/*``)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts: List[str] = []
        for v in node.values:
            if isinstance(v, ast.Constant) and \
                    isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("*")
                break
        return "".join(parts)
    return None


def _str_const_map(tree: ast.Module) -> Dict[str, str]:
    """name -> string value for every simple ``NAME = "..."`` /
    ``NAME = f"..."`` assignment anywhere in the file (module
    constants like route prefixes and metric-name constants)."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            s = _as_route_str(node.value)
            if s is not None:
                out[node.targets[0].id] = s
    return out


# --------------------------------------------------------------------
# donation tracking
# --------------------------------------------------------------------


def _donate_positions(call: ast.Call,
                      defs_by_name: Dict[str, ast.AST],
                      ) -> Optional[Tuple[int, ...]]:
    """Donated positional indices of a wrapping call, or None when
    they are not statically constant. ``donate_argnames`` resolves to
    positions through the wrapped function's own def when that def is
    in the same file."""
    kws = {k.arg: k.value for k in call.keywords if k.arg}
    if "donate_argnums" in kws:
        return _int_tuple(kws["donate_argnums"])
    if "donate_argnames" in kws:
        names = _str_tuple(kws["donate_argnames"])
        if names is None or not call.args or \
                not isinstance(call.args[0], ast.Name):
            return None
        fdef = defs_by_name.get(call.args[0].id)
        if fdef is None:
            return None
        params = [a.arg for a in fdef.args.args]
        try:
            return tuple(params.index(n) for n in names)
        except ValueError:
            return None
    return None


def _find_donate_calls(value: ast.AST,
                       defs_by_name: Dict[str, ast.AST],
                       ) -> List[Tuple[ast.Call, Tuple[int, ...]]]:
    """Every call carrying a resolvable donate keyword anywhere inside
    ``value`` — sees through ``self._maybe_aot_wrap(jax.jit(...))``
    nesting and conditional-expression branches."""
    hits: List[Tuple[ast.Call, Tuple[int, ...]]] = []
    for n in ast.walk(value):
        if isinstance(n, ast.Call):
            pos = _donate_positions(n, defs_by_name)
            if pos is not None:
                hits.append((n, pos))
    return hits


class _DonationCollector:
    """One pass binding donated callables to stable scope keys.

    Keys: ``qual::name`` for a local/module variable (``qual`` is the
    project-index function qual, "" at module level), ``Cls.attr`` for
    ``self.attr`` bindings and class-level assignments. The flow pass
    looks keys up through the lexical scope chain."""

    def __init__(self, tree: ast.Module) -> None:
        self.defs_by_name: Dict[str, ast.AST] = {
            n.name: n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        # key -> (donated positions, bind line)
        self.bindings: Dict[str, Tuple[Tuple[int, ...], int]] = {}
        # (fdef, qual, class qual or None)
        self.functions: List[Tuple[ast.AST, str, Optional[str]]] = []
        self._walk(tree.body, "", None, in_class=False)

    def _bind(self, key: Optional[str], pos: Tuple[int, ...],
              line: int) -> None:
        if key:
            self.bindings[key] = (pos, line)

    def _target_key(self, target: ast.AST, qual: str,
                    cls: Optional[str], in_class: bool,
                    ) -> Optional[str]:
        if isinstance(target, ast.Name):
            if in_class and cls:
                return f"{cls}.{target.id}"
            return f"{qual}::{target.id}"
        if isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id == "self" and cls:
            return f"{cls}.{target.attr}"
        return None

    def _walk(self, body: List[ast.stmt], qual: str,
              cls: Optional[str], in_class: bool) -> None:
        for node in body:
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                sub = f"{qual}.{node.name}" if qual else node.name
                self.functions.append((node, sub, cls))
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call):
                        pos = _donate_positions(dec, self.defs_by_name)
                        if pos is not None:
                            key = f"{cls}.{node.name}" \
                                if in_class and cls else \
                                f"{qual}::{node.name}"
                            self._bind(key, pos, node.lineno)
                self._walk(node.body, sub, cls, in_class=False)
            elif isinstance(node, ast.ClassDef):
                cq = f"{qual}.{node.name}" if qual else node.name
                self._walk(node.body, cq, cq, in_class=True)
            elif isinstance(node, ast.Assign):
                hits = _find_donate_calls(node.value, self.defs_by_name)
                possets = {p for _, p in hits}
                if len(possets) == 1:
                    pos = next(iter(possets))
                    for t in node.targets:
                        self._bind(self._target_key(t, qual, cls,
                                                    in_class),
                                   pos, node.lineno)
            elif isinstance(node, (ast.If, ast.For, ast.AsyncFor,
                                   ast.While, ast.With,
                                   ast.AsyncWith) + _TRY_TYPES):
                for field in ("body", "orelse", "finalbody"):
                    self._walk(getattr(node, field, []) or [],
                               qual, cls, in_class)
                for h in getattr(node, "handlers", []) or []:
                    self._walk(h.body, qual, cls, in_class)


class _DonationFlow:
    """Read-after-donation walk of one function body.

    State: armed variable key -> info about the donating call. A read
    of an armed key is a finding; any rebinding kills the key. ``If``
    forks and joins by union (read on *any* path is the bug); loops
    re-walk their body once so a second-iteration read of a buffer
    donated on the first iteration is seen."""

    def __init__(self, coll: _DonationCollector, fdef: ast.AST,
                 qual: str, cls: Optional[str],
                 findings: Set[Tuple]) -> None:
        self.coll = coll
        self.fdef = fdef
        self.cls = cls
        self.findings = findings
        # lexical lookup chain: "A.b.c" -> ["A.b.c", "A.b", "A", ""]
        chain = [qual]
        while "." in chain[-1]:
            chain.append(chain[-1].rsplit(".", 1)[0])
        if chain[-1]:
            chain.append("")
        self.scope_chain = chain

    def run(self) -> None:
        self._walk_body(self.fdef.body, {})

    # -- binding lookup ----------------------------------------------

    def _match_call(self, call: ast.Call,
                    ) -> Optional[Tuple[str, Tuple[int, ...], int]]:
        f = call.func
        if isinstance(f, ast.Name):
            for scope in self.scope_chain:
                entry = self.coll.bindings.get(f"{scope}::{f.id}")
                if entry is not None:
                    return (f.id,) + entry
        elif isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name) and \
                f.value.id == "self" and self.cls:
            entry = self.coll.bindings.get(f"{self.cls}.{f.attr}")
            if entry is not None:
                return (f"self.{f.attr}",) + entry
        return None

    @staticmethod
    def _arg_key(arg: ast.AST) -> Optional[Tuple[str, str]]:
        """(state key, display name) for a trackable donated arg."""
        if isinstance(arg, ast.Name):
            return (f"n:{arg.id}", arg.id)
        if isinstance(arg, ast.Attribute) and \
                isinstance(arg.value, ast.Name) and \
                arg.value.id == "self":
            return (f"a:{arg.attr}", f"self.{arg.attr}")
        return None

    # -- per-statement read/arm/kill ----------------------------------

    def _use(self, state: Dict[str, dict], exprs: List[ast.AST],
             kill_targets: List[ast.AST]) -> None:
        armed: Dict[str, dict] = {}
        reads: List[Tuple[str, int, int]] = []
        for expr in exprs:
            if expr is None:
                continue
            for n in _scan(expr):
                if isinstance(n, ast.Call):
                    m = self._match_call(n)
                    if m is None:
                        continue
                    callee, positions, bind_line = m
                    for p in positions:
                        if p >= len(n.args):
                            continue
                        ak = self._arg_key(n.args[p])
                        if ak is None:
                            continue
                        key, disp = ak
                        armed[key] = {
                            "var": disp, "callee": callee,
                            "bind": bind_line, "call": n.lineno}
                elif isinstance(n, ast.Name) and \
                        isinstance(n.ctx, ast.Load):
                    reads.append((f"n:{n.id}", n.lineno,
                                  n.col_offset))
                elif isinstance(n, ast.Attribute) and \
                        isinstance(n.ctx, ast.Load) and \
                        isinstance(n.value, ast.Name) and \
                        n.value.id == "self":
                    reads.append((f"a:{n.attr}", n.lineno,
                                  n.col_offset))
        # reads check against the state *before* this statement's
        # armings; earliest read of each armed key wins
        for key, line, col in sorted(reads, key=lambda r: (r[1], r[2])):
            info = state.get(key)
            if info is None:
                continue
            self.findings.add((info["var"], info["callee"],
                               info["bind"], info["call"], line, col))
            del state[key]
        state.update(armed)
        for t in kill_targets:
            self._kill_target(state, t)

    def _kill_target(self, state: Dict[str, dict],
                     target: ast.AST) -> None:
        if isinstance(target, ast.Name):
            state.pop(f"n:{target.id}", None)
        elif isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id == "self":
            state.pop(f"a:{target.attr}", None)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._kill_target(state, e)
        elif isinstance(target, ast.Starred):
            self._kill_target(state, target.value)

    # -- control flow -------------------------------------------------

    @staticmethod
    def _join(a: Optional[Dict[str, dict]],
              b: Optional[Dict[str, dict]],
              ) -> Optional[Dict[str, dict]]:
        if a is None:
            return None if b is None else dict(b)
        if b is None:
            return dict(a)
        out = dict(a)
        for k, v in b.items():
            out.setdefault(k, v)
        return out

    def _walk_body(self, body: List[ast.stmt],
                   state: Optional[Dict[str, dict]],
                   ) -> Optional[Dict[str, dict]]:
        for st in body:
            if state is None:
                return None
            state = self._walk_stmt(st, state)
        return state

    def _walk_stmt(self, st: ast.stmt, state: Dict[str, dict],
                   ) -> Optional[Dict[str, dict]]:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            state.pop(f"n:{st.name}", None)
            return state
        if isinstance(st, ast.Return):
            self._use(state, [st.value], [])
            return None
        if isinstance(st, ast.Raise):
            self._use(state, [st.exc, st.cause], [])
            return None
        if isinstance(st, (ast.Break, ast.Continue)):
            return None
        if isinstance(st, ast.Assign):
            # subscript/attribute targets read their base expression
            # (``x[0] = v`` writes into the donated buffer — a read)
            extra = [t for t in st.targets
                     if isinstance(t, (ast.Subscript, ast.Attribute))]
            self._use(state, [st.value] + extra, st.targets)
            return state
        if isinstance(st, ast.AugAssign):
            self._use(state, [st.target, st.value], [st.target])
            return state
        if isinstance(st, ast.AnnAssign):
            self._use(state, [st.value],
                      [st.target] if st.value is not None else [])
            return state
        if isinstance(st, ast.Expr):
            self._use(state, [st.value], [])
            return state
        if isinstance(st, ast.Assert):
            self._use(state, [st.test, st.msg], [])
            return state
        if isinstance(st, ast.Delete):
            for t in st.targets:
                self._kill_target(state, t)
            return state
        if isinstance(st, ast.If):
            self._use(state, [st.test], [])
            s1 = self._walk_body(st.body, dict(state))
            s2 = self._walk_body(st.orelse, dict(state)) \
                if st.orelse else dict(state)
            return self._join(s1, s2)
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self._use(state, [st.iter], [])
            self._kill_target(state, st.target)
            s1 = self._walk_body(st.body, dict(state))
            entry2 = self._join(dict(state), s1)
            if entry2 is not None:
                self._kill_target(entry2, st.target)
            s2 = self._walk_body(st.body, entry2) \
                if entry2 is not None else None
            after = self._join(self._join(s1, s2), dict(state))
            if st.orelse and after is not None:
                after = self._walk_body(st.orelse, after)
            return after
        if isinstance(st, ast.While):
            self._use(state, [st.test], [])
            s1 = self._walk_body(st.body, dict(state))
            entry2 = self._join(dict(state), s1)
            s2 = self._walk_body(st.body, entry2) \
                if entry2 is not None else None
            after = self._join(self._join(s1, s2), dict(state))
            if st.orelse and after is not None:
                after = self._walk_body(st.orelse, after)
            return after
        if isinstance(st, (ast.With, ast.AsyncWith)):
            self._use(state, [it.context_expr for it in st.items], [])
            for it in st.items:
                if it.optional_vars is not None:
                    self._kill_target(state, it.optional_vars)
            return self._walk_body(st.body, state)
        if isinstance(st, _TRY_TYPES):
            sb = self._walk_body(st.body, dict(state))
            base = self._join(dict(state), sb) or dict(state)
            cur = sb
            if cur is not None and st.orelse:
                cur = self._walk_body(st.orelse, cur)
            outs = [cur] if cur is not None else []
            for h in st.handlers:
                hstate = dict(base)
                if h.name:
                    hstate.pop(f"n:{h.name}", None)
                sh = self._walk_body(h.body, hstate)
                if sh is not None:
                    outs.append(sh)
            merged: Optional[Dict[str, dict]] = None
            for o in outs:
                merged = self._join(merged, o)
            if st.finalbody:
                fentry = merged if merged is not None else dict(base)
                merged = self._walk_body(st.finalbody, fentry)
            return merged
        return state  # Pass/Import/Global/Nonlocal/...


def analyze_donation_use(tree: ast.Module,
                         ) -> List[Tuple[str, str, int, int, int,
                                         int]]:
    """Read-after-donation findings for one file.

    Returns sorted ``(var, callee, bind_line, call_line, read_line,
    read_col)`` tuples: variable ``var`` was passed in a donated
    position to ``callee`` (whose donate binding is at ``bind_line``)
    at ``call_line`` and read again at ``read_line`` on some path."""
    coll = _DonationCollector(tree)
    if not coll.bindings:
        return []
    findings: Set[Tuple] = set()
    for fdef, qual, cls in coll.functions:
        _DonationFlow(coll, fdef, qual, cls, findings).run()
    return sorted(findings,
                  key=lambda f: (f[4], f[5], f[0], f[3]))


# --------------------------------------------------------------------
# resource-lifecycle typestate
# --------------------------------------------------------------------

#: declared acquire/release protocols. ``receiver`` (regex) restricts
#: matches to calls whose receiver text matches; ``bare_only``
#: restricts the acquire to a bare-name call (``open(...)`` but not
#: ``os.open``/``img.open``). ``leak`` enables the
#: release-can-be-skipped-by-an-exception check; ``double`` the
#: released-twice-on-one-path check. Context-managed acquires
#: (``with open(...) as f``) are clean by construction and never
#: tracked; an allocator that returns its reserved null block is
#: handled by the ``is None`` branch pruning in the walker.
PROTOCOLS: Tuple[Dict[str, object], ...] = (
    {"name": "block-allocator", "acquire": ("alloc",),
     "release": ("free",), "receiver": r"allocat", "bare_only": False,
     "leak": True, "double": True},
    {"name": "slot-pool", "acquire": ("assign_slot", "assign_paged"),
     "release": ("rollback_slots", "reset_free_slots"),
     "receiver": None, "bare_only": False,
     "leak": False, "double": True},
    {"name": "lane-handoff", "acquire": ("export_lane",),
     "release": ("detach_lane",), "receiver": None, "bare_only": False,
     "leak": False, "double": True},
    {"name": "serve-drain", "acquire": ("begin_drain",),
     "release": ("idle",), "receiver": None, "bare_only": False,
     "leak": False, "double": True},
    {"name": "file-handle", "acquire": ("open",),
     "release": ("close",), "receiver": None, "bare_only": True,
     "leak": True, "double": True},
)

_HELD, _RELEASED, _ESCAPED = "held", "released", "escaped"


class _Resource:
    __slots__ = ("proto", "var", "line", "col", "state", "rel_line",
                 "leaked")

    def __init__(self, proto: int, var: str, line: int,
                 col: int) -> None:
        self.proto = proto
        self.var = var
        self.line = line
        self.col = col
        self.state = _HELD
        self.rel_line = 0
        self.leaked = False

    def clone(self) -> "_Resource":
        r = _Resource(self.proto, self.var, self.line, self.col)
        r.state = self.state
        r.rel_line = self.rel_line
        r.leaked = self.leaked
        return r


def _call_parts(call: ast.Call) -> Tuple[Optional[str], Optional[str]]:
    f = call.func
    if isinstance(f, ast.Name):
        return ("", f.id)
    if isinstance(f, ast.Attribute):
        return (_expr_text(f.value), f.attr)
    return (None, None)


def _match_protocol(call: ast.Call, phase: str) -> Optional[int]:
    recv, leaf = _call_parts(call)
    if leaf is None:
        return None
    for i, proto in enumerate(PROTOCOLS):
        if leaf not in proto[phase]:
            continue
        if proto["bare_only"] and phase == "acquire" and recv != "":
            continue
        pat = proto["receiver"]
        if pat is not None and not re.search(pat, recv or ""):
            continue
        return i
    return None


def _broad_handler(h: ast.excepthandler) -> bool:
    def broad(t: ast.AST) -> bool:
        return isinstance(t, ast.Name) and \
            t.id in ("Exception", "BaseException")
    if h.type is None:
        return True
    if broad(h.type):
        return True
    return isinstance(h.type, ast.Tuple) and \
        any(broad(e) for e in h.type.elts)


class _LifecycleFlow:
    """Typestate walk of one function body over ``PROTOCOLS``."""

    def __init__(self, fdef: ast.AST, findings: Set[Tuple]) -> None:
        self.fdef = fdef
        self.findings = findings
        self._protected: List[Set[int]] = []

    def run(self) -> None:
        self._walk_body(self.fdef.body, {})

    # -- statement-level semantics ------------------------------------

    def _stmt_calls(self, st: ast.stmt) -> List[ast.Call]:
        calls = [n for n in _scan(st) if isinstance(n, ast.Call)]
        calls.sort(key=lambda c: (c.lineno, c.col_offset))
        return calls

    def _release_candidates(self, call: ast.Call) -> List[str]:
        names: List[str] = []
        recv, _ = _call_parts(call)
        if recv and "." not in recv and recv != "self":
            names.append(recv)
        for a in call.args:
            if isinstance(a, ast.Name):
                names.append(a.id)
        return names

    def _process_calls(self, st: ast.stmt,
                       state: Dict[str, _Resource],
                       skip_acquire_target: Optional[str] = None,
                       ) -> None:
        calls = self._stmt_calls(st)
        protected: Set[int] = set()
        for s in self._protected:
            protected |= s
        for call in calls:
            rel = _match_protocol(call, "release")
            acq = _match_protocol(call, "acquire")
            if rel is not None:
                self._do_release(call, rel, state)
                continue
            if acq is not None:
                continue  # the acquire itself can't leak its result
            # a plain call may raise: every held, unprotected resource
            # of a leak-checked protocol escapes cleanup on that path
            for var in sorted(state):
                r = state[var]
                if r.state != _HELD or r.leaked or \
                        var == skip_acquire_target:
                    continue
                proto = PROTOCOLS[r.proto]
                if not proto["leak"] or r.proto in protected:
                    continue
                _, leaf = _call_parts(call)
                self.findings.add((
                    "leak", proto["name"], r.var, r.line, r.col,
                    call.lineno, leaf or "call"))
                r.leaked = True

    def _do_release(self, call: ast.Call, proto_idx: int,
                    state: Dict[str, _Resource]) -> None:
        cands = self._release_candidates(call)
        target: Optional[_Resource] = None
        for name in cands:
            r = state.get(name)
            if r is not None and r.proto == proto_idx:
                target = r
                break
        if target is None:
            held = [state[v] for v in sorted(state)
                    if state[v].proto == proto_idx and
                    state[v].state == _HELD]
            if len(held) == 1 and not cands:
                target = held[0]
        if target is None:
            return
        if target.state == _RELEASED and \
                PROTOCOLS[proto_idx]["double"]:
            self.findings.add((
                "double-release", PROTOCOLS[proto_idx]["name"],
                target.var, call.lineno, call.col_offset,
                target.rel_line, ""))
        elif target.state == _HELD:
            target.state = _RELEASED
            target.rel_line = call.lineno
        # ESCAPED: ownership ambiguous — stay silent

    def _escape_if_referenced(self, value: Optional[ast.AST],
                              state: Dict[str, _Resource]) -> None:
        if value is None:
            return
        for n in _scan(value):
            if isinstance(n, ast.Name) and n.id in state:
                state[n.id].state = _ESCAPED
            elif isinstance(n, (ast.Yield, ast.YieldFrom)):
                pass  # children visited anyway

    # -- control flow -------------------------------------------------

    @staticmethod
    def _join(a: Optional[Dict[str, _Resource]],
              b: Optional[Dict[str, _Resource]],
              ) -> Optional[Dict[str, _Resource]]:
        if a is None:
            return None if b is None else b
        if b is None:
            return a
        out: Dict[str, _Resource] = {}
        for k in sorted(set(a) | set(b)):
            ra, rb = a.get(k), b.get(k)
            if ra is None or rb is None:
                out[k] = ra or rb
            elif ra.state == rb.state:
                out[k] = ra
            else:
                merged = ra.clone()
                merged.state = _ESCAPED
                out[k] = merged
        return out

    @staticmethod
    def _fork(state: Dict[str, _Resource]) -> Dict[str, _Resource]:
        return {k: v.clone() for k, v in state.items()}

    def _walk_body(self, body: List[ast.stmt],
                   state: Optional[Dict[str, _Resource]],
                   ) -> Optional[Dict[str, _Resource]]:
        for st in body:
            if state is None:
                return None
            state = self._walk_stmt(st, state)
        return state

    def _none_pruned(self, test: ast.AST, state: Dict[str, _Resource],
                     ) -> Tuple[Dict[str, _Resource],
                                Dict[str, _Resource]]:
        """(body state, else state) for an If, dropping the resource
        on the branch where ``v is None`` holds — the allocator's
        exhaustion/null-block return means nothing was acquired."""
        body_state, else_state = self._fork(state), self._fork(state)
        if isinstance(test, ast.Compare) and \
                isinstance(test.left, ast.Name) and \
                len(test.ops) == 1 and \
                len(test.comparators) == 1 and \
                isinstance(test.comparators[0], ast.Constant) and \
                test.comparators[0].value is None and \
                test.left.id in state:
            if isinstance(test.ops[0], ast.Is):
                body_state.pop(test.left.id, None)
            elif isinstance(test.ops[0], ast.IsNot):
                else_state.pop(test.left.id, None)
        return body_state, else_state

    def _walk_stmt(self, st: ast.stmt, state: Dict[str, _Resource],
                   ) -> Optional[Dict[str, _Resource]]:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return state
        if isinstance(st, ast.Return):
            self._escape_if_referenced(st.value, state)
            return None
        if isinstance(st, ast.Raise):
            self._process_calls(st, state)
            return None
        if isinstance(st, (ast.Break, ast.Continue)):
            return None
        if isinstance(st, ast.Assign):
            acquired_var: Optional[str] = None
            if len(st.targets) == 1 and \
                    isinstance(st.targets[0], ast.Name) and \
                    isinstance(st.value, ast.Call):
                acq = _match_protocol(st.value, "acquire")
                if acq is not None:
                    acquired_var = st.targets[0].id
            self._process_calls(st, state,
                                skip_acquire_target=acquired_var)
            # aliasing / storing a live resource hands ownership off
            if any(isinstance(t, (ast.Attribute, ast.Subscript))
                   for t in st.targets) or \
                    (isinstance(st.value, ast.Name) and
                     st.value.id in state):
                self._escape_if_referenced(st.value, state)
            for t in st.targets:
                if isinstance(t, ast.Name):
                    state.pop(t.id, None)
            if acquired_var is not None:
                state[acquired_var] = _Resource(
                    _match_protocol(st.value, "acquire"),
                    acquired_var, st.lineno, st.col_offset)
            return state
        if isinstance(st, (ast.AugAssign, ast.AnnAssign, ast.Expr,
                           ast.Assert, ast.Delete)):
            self._process_calls(st, state)
            if isinstance(st, ast.Expr):
                self._escape_if_yield(st.value, state)
            return state
        if isinstance(st, ast.If):
            self._process_calls_in_expr(st.test, state)
            bstate, estate = self._none_pruned(st.test, state)
            s1 = self._walk_body(st.body, bstate)
            s2 = self._walk_body(st.orelse, estate) if st.orelse \
                else estate
            return self._join(s1, s2)
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self._process_calls_in_expr(st.iter, state)
            s1 = self._walk_body(st.body, self._fork(state))
            after = self._join(s1, state)
            if st.orelse and after is not None:
                after = self._walk_body(st.orelse, after)
            return after
        if isinstance(st, ast.While):
            self._process_calls_in_expr(st.test, state)
            s1 = self._walk_body(st.body, self._fork(state))
            after = self._join(s1, state)
            if st.orelse and after is not None:
                after = self._walk_body(st.orelse, after)
            return after
        if isinstance(st, (ast.With, ast.AsyncWith)):
            # ``with open(...) as f`` is release-by-construction;
            # other context managers may raise like any call
            for it in st.items:
                if not (isinstance(it.context_expr, ast.Call) and
                        _match_protocol(it.context_expr, "acquire")
                        is not None):
                    self._process_calls_in_expr(it.context_expr, state)
            return self._walk_body(st.body, state)
        if isinstance(st, _TRY_TYPES):
            protected = self._try_protection(st)
            self._protected.append(protected)
            sb = self._walk_body(st.body, self._fork(state))
            self._protected.pop()
            base = self._join(self._fork(state), sb)
            cur = sb
            if cur is not None and st.orelse:
                cur = self._walk_body(st.orelse, cur)
            outs = [cur] if cur is not None else []
            for h in st.handlers:
                sh = self._walk_body(h.body, self._fork(base))
                if sh is not None:
                    outs.append(sh)
            merged: Optional[Dict[str, _Resource]] = None
            for o in outs:
                merged = self._join(merged, o)
            if st.finalbody:
                fentry = merged if merged is not None \
                    else self._fork(base)
                merged = self._walk_body(st.finalbody, fentry)
            return merged
        return state

    def _process_calls_in_expr(self, expr: Optional[ast.AST],
                               state: Dict[str, _Resource]) -> None:
        if expr is not None:
            wrapper = ast.Expr(value=expr)
            ast.copy_location(wrapper, expr)
            self._process_calls(wrapper, state)

    def _escape_if_yield(self, value: ast.AST,
                         state: Dict[str, _Resource]) -> None:
        for n in _scan(value):
            if isinstance(n, (ast.Yield, ast.YieldFrom)) and \
                    n.value is not None:
                self._escape_if_referenced(n.value, state)

    def _try_protection(self, st: ast.AST) -> Set[int]:
        """Protocols whose release provably runs when the try body
        raises: a release call in ``finally`` or in a broad handler."""
        nodes: List[ast.AST] = list(st.finalbody)
        for h in st.handlers:
            if _broad_handler(h):
                nodes.extend(h.body)
        prot: Set[int] = set()
        for node in nodes:
            for n in _scan(node):
                if isinstance(n, ast.Call):
                    idx = _match_protocol(n, "release")
                    if idx is not None:
                        prot.add(idx)
        return prot


def analyze_lifecycle(tree: ast.Module,
                      ) -> List[Tuple[str, str, str, int, int, int,
                                      str]]:
    """Typestate findings for one file, sorted.

    ``("leak", protocol, var, acq_line, acq_col, witness_line,
    witness_call)``: the resource acquired at ``acq_line`` has no
    release on the path where the call at ``witness_line`` raises.
    ``("double-release", protocol, var, line, col, first_rel_line,
    "")``: released again at ``line`` after ``first_rel_line`` on one
    path."""
    findings: Set[Tuple] = set()
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _LifecycleFlow(n, findings).run()
    return sorted(findings, key=lambda f: (f[3], f[4], f[0], f[2]))


# --------------------------------------------------------------------
# API route surfaces
# --------------------------------------------------------------------

_HTTP_VERBS = ("delete", "get", "patch", "post", "put")
_STDLIB_DISPATCH = {"do_DELETE": "DELETE", "do_GET": "GET",
                    "do_PATCH": "PATCH", "do_POST": "POST",
                    "do_PUT": "PUT"}


def _is_self_path(node: ast.AST) -> bool:
    return isinstance(node, ast.Attribute) and node.attr == "path" \
        and isinstance(node.value, ast.Name) and node.value.id == "self"


def extract_routes(tree: ast.Module,
                   ) -> List[Tuple[str, str, str, int, int]]:
    """Sorted ``(surface, METHOD, raw_path, line, col)`` for both API
    surfaces of a file: fastapi ``@app.<verb>(path)`` decorators and
    stdlib ``do_<METHOD>`` dispatchers comparing ``self.path`` (``==``
    / ``!=`` / ``.startswith``, prefix matches recorded as
    ``prefix*``). Paths resolve through same-file string constants and
    f-string prefixes."""
    consts = _str_const_map(tree)
    app_names = {
        t.id
        for node in ast.walk(tree) if isinstance(node, ast.Assign)
        for t in node.targets if isinstance(t, ast.Name)
        if isinstance(node.value, ast.Call) and
        _expr_text(node.value.func).rsplit(".", 1)[-1] == "FastAPI"}

    def resolve(expr: ast.AST) -> Optional[str]:
        s = _as_route_str(expr)
        if s is not None:
            return s
        if isinstance(expr, ast.Name):
            return consts.get(expr.id)
        return None

    out: List[Tuple[str, str, str, int, int]] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            if isinstance(dec, ast.Call) and \
                    isinstance(dec.func, ast.Attribute) and \
                    dec.func.attr in _HTTP_VERBS and \
                    isinstance(dec.func.value, ast.Name) and \
                    dec.func.value.id in app_names and dec.args:
                path = resolve(dec.args[0])
                if path:
                    out.append(("fastapi", dec.func.attr.upper(),
                                path, dec.lineno, dec.col_offset))
        method = _STDLIB_DISPATCH.get(node.name)
        if method is None:
            continue
        for n in _scan(node):
            if isinstance(n, ast.Compare) and \
                    all(isinstance(op, (ast.Eq, ast.NotEq))
                        for op in n.ops):
                sides = [n.left] + list(n.comparators)
                if any(_is_self_path(s) for s in sides):
                    for s in sides:
                        p = resolve(s)
                        if p:
                            out.append(("stdlib", method, p,
                                        n.lineno, n.col_offset))
            elif isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr == "startswith" and \
                    _is_self_path(n.func.value) and n.args:
                p = resolve(n.args[0])
                if p:
                    out.append(("stdlib", method, p + "*",
                                n.lineno, n.col_offset))
    return sorted(set(out))


def normalize_route(path: str) -> str:
    """Comparable form of a route: path params and f-string/prefix
    wildcards both become ``*``; trailing slashes are insignificant."""
    p = re.sub(r"\{[^}]*\}", "*", path)
    p = re.sub(r"\*+", "*", p)
    if len(p) > 1 and p.endswith("/"):
        p = p[:-1]
    return p


# --------------------------------------------------------------------
# metric registration sites
# --------------------------------------------------------------------

_METRIC_KINDS = ("counter", "gauge", "histogram")


def extract_metrics(tree: ast.Module,
                    ) -> List[Tuple[str, str, Tuple[str, ...], int,
                                    int]]:
    """Sorted ``(name, kind, labelnames, line, col)`` for every
    ``fstpu_*`` registry get-or-create site with a statically constant
    name (a string literal or a module-level string constant).
    Dynamically named families (loop variables, f-strings) are
    invisible here and belong on the metric-contract allowlist."""
    consts = {
        node.targets[0].id: node.value.value
        for node in tree.body
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and
        isinstance(node.targets[0], ast.Name) and
        isinstance(node.value, ast.Constant) and
        isinstance(node.value.value, str)}
    out: List[Tuple[str, str, Tuple[str, ...], int, int]] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and
                isinstance(node.func, ast.Attribute) and
                node.func.attr in _METRIC_KINDS and node.args):
            continue
        a0 = node.args[0]
        if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
            name = a0.value
        elif isinstance(a0, ast.Name):
            name = consts.get(a0.id, "")
        else:
            continue
        if not name.startswith("fstpu_"):
            continue
        lab_node: Optional[ast.AST] = None
        for k in node.keywords:
            if k.arg == "labelnames":
                lab_node = k.value
        if lab_node is None and len(node.args) > 2:
            lab_node = node.args[2]
        labels: Tuple[str, ...] = ()
        if lab_node is not None:
            resolved = _str_tuple(lab_node)
            if resolved is None:
                continue  # unverifiable label expression
            labels = resolved
        out.append((name, node.func.attr, labels, node.lineno,
                    node.col_offset))
    return sorted(out)


_DOC_ROW = re.compile(
    r"^\|\s*`(?P<name>fstpu_[a-z0-9_]+)"
    r"(?:\{(?P<labels>[^}`]*)\})?`\s*\|\s*"
    r"(?P<kind>counter|gauge|histogram)\b")


def parse_metric_docs(text: str,
                      ) -> Dict[str, Tuple[Tuple[str, ...], str, int]]:
    """The documented metric families out of a markdown metrics table:
    name -> (sorted labelnames, kind, doc line). Rows look like
    ``| `fstpu_http_requests_total{route,code}` | counter | ... |``."""
    docs: Dict[str, Tuple[Tuple[str, ...], str, int]] = {}
    for i, line in enumerate(text.splitlines(), 1):
        m = _DOC_ROW.match(line.strip())
        if m and m.group("name") not in docs:
            raw = m.group("labels") or ""
            labels = tuple(sorted(
                x.strip() for x in raw.split(",") if x.strip()))
            docs[m.group("name")] = (labels, m.group("kind"), i)
    return docs
