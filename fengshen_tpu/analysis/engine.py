"""fslint engine: one AST walk per file, shared trace-context analysis.

Pure stdlib — the analyzer never imports jax (or the package under
analysis), so ``python -m fengshen_tpu.analysis`` starts in
milliseconds and runs identically on a dev laptop, CI, and a TPU host.

The engine owns everything rules share:

- parsing + a parent map (``ctx.parent``) over each file's tree
- import-alias resolution (``ctx.qualname`` turns ``jnp.zeros`` /
  ``P(...)`` / ``device_get(...)`` back into dotted origins like
  ``jax.numpy.zeros`` regardless of local import spelling)
- traced-context analysis (``ctx.in_traced_context``): which functions
  are jitted / grad-transformed / scan-cond-while bodies, including
  functions reached transitively by name from a traced one
- per-line suppressions: ``# fslint: disable=<rule>[,<rule>]`` (or a
  bare ``# fslint: disable`` for all rules) on the finding's line
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

from fengshen_tpu.analysis.registry import Rule

#: calls whose function-valued arguments are traced by JAX. Matched
#: against alias-resolved dotted names, so ``from jax import lax;
#: lax.scan`` and ``jax.lax.scan`` both hit.
TRACING_ENTRY_POINTS = frozenset({
    "jax.jit", "jax.pmap", "jax.grad", "jax.value_and_grad", "jax.vmap",
    "jax.checkpoint", "jax.remat", "jax.eval_shape", "jax.make_jaxpr",
    "jax.lax.scan", "jax.lax.cond", "jax.lax.while_loop",
    "jax.lax.fori_loop", "jax.lax.switch", "jax.lax.map",
    "jax.lax.associative_scan", "jax.lax.custom_root",
    "jax.experimental.shard_map.shard_map", "jax.shard_map", "shard_map",
    "flax.linen.scan", "flax.linen.remat", "nn.scan", "nn.remat",
})

#: function names that are step functions by convention even when the
#: jit call lives in another file (the trainer jits
#: ``module.training_loss`` etc. — the definition site can't see that)
TRACED_BY_NAME = frozenset({
    "train_step", "eval_step", "training_loss", "validation_loss",
    "predict_step",
})

_SUPPRESS_RE = re.compile(
    r"#\s*fslint:\s*disable(?:=(?P<rules>[\w,\- ]+))?")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One analyzer hit. Sorts by (path, line, col, rule) so text and
    ``--json`` output — and therefore the baseline file and CI diffs —
    are deterministic across hosts and dict orderings."""

    path: str       # repo-relative, posix separators
    line: int
    col: int
    rule: str
    message: str
    hint: str
    code: str       # stripped source line (anchors baseline matching)

    def sort_key(self) -> Tuple[str, int, int, str]:
        return (self.path, self.line, self.col, self.rule)

    def to_dict(self) -> Dict[str, object]:
        return {"path": self.path, "line": self.line, "col": self.col,
                "rule": self.rule, "message": self.message,
                "hint": self.hint, "code": self.code}

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule}: "
                f"{self.message}\n    {self.code}\n    fix: {self.hint}")


class FileContext:
    """Everything rules may ask about one source file."""

    def __init__(self, path: str, relpath: str, source: str,
                 tree: ast.Module, project_root: str) -> None:
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.project_root = project_root
        self._parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent
        self.aliases = _collect_aliases(tree)
        self.comments = _collect_comments(source)
        self.suppressions = _collect_suppressions(self.comments)
        self._traced = _traced_functions(self)

    # -- structure ---------------------------------------------------
    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self._parents.get(node)
        while cur is not None:
            yield cur
            cur = self._parents.get(cur)

    def enclosing_functions(self, node: ast.AST) -> List[ast.AST]:
        return [a for a in self.ancestors(node)
                if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda))]

    # -- names -------------------------------------------------------
    def qualname(self, node: ast.AST) -> Optional[str]:
        """Dotted origin of a Name/Attribute chain, alias-resolved.

        ``jnp.zeros`` -> ``jax.numpy.zeros`` (under ``import jax.numpy
        as jnp``); non-name expressions (calls, subscripts) -> None.
        """
        if isinstance(node, ast.Name):
            return self.aliases.get(node.id, node.id)
        if isinstance(node, ast.Attribute):
            base = self.qualname(node.value)
            return None if base is None else f"{base}.{node.attr}"
        return None

    # -- tracing -----------------------------------------------------
    def is_traced_function(self, fn: ast.AST) -> bool:
        return fn in self._traced

    def in_traced_context(self, node: ast.AST) -> bool:
        """True when any enclosing function is traced by JAX (jitted,
        grad/vmap-transformed, or a scan/cond/while body) — directly,
        lexically (nested inside one), or transitively by call."""
        return any(fn in self._traced
                   for fn in self.enclosing_functions(node))

    # -- suppressions ------------------------------------------------
    def is_suppressed(self, line: int, rule_id: str) -> bool:
        rules = self.suppressions.get(line)
        if rules is None:
            return False
        return not rules or rule_id in rules

    def line_comment(self, line: int) -> str:
        return self.comments.get(line, "")


def _collect_aliases(tree: ast.Module) -> Dict[str, str]:
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = \
                    a.name if a.asname else a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom) and node.module:
            prefix = ("." * node.level) + node.module
            for a in node.names:
                if a.name == "*":
                    continue
                aliases[a.asname or a.name] = f"{prefix}.{a.name}"
    return aliases


def _collect_comments(source: str) -> Dict[int, str]:
    comments: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                comments[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass  # ast.parse already succeeded; comment map is best-effort
    return comments


def _collect_suppressions(
        comments: Dict[int, str]) -> Dict[int, frozenset]:
    """line -> suppressed rule ids (empty frozenset = all rules)."""
    out: Dict[int, frozenset] = {}
    for line, text in comments.items():
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = m.group("rules")
        out[line] = frozenset(
            r.strip() for r in rules.split(",") if r.strip()) \
            if rules else frozenset()
    return out


def _function_nodes(tree: ast.Module) -> List[ast.AST]:
    return [n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]


def _is_tracing_decorator(dec: ast.AST, ctx: "FileContext") -> bool:
    qn = ctx.qualname(dec)
    if qn in TRACING_ENTRY_POINTS:
        return True
    if isinstance(dec, ast.Call):
        # @jax.jit(static_argnums=...) / @partial(jax.jit, ...)
        fqn = ctx.qualname(dec.func)
        if fqn in TRACING_ENTRY_POINTS:
            return True
        if fqn in ("functools.partial", "partial") and dec.args:
            return ctx.qualname(dec.args[0]) in TRACING_ENTRY_POINTS
    return False


def _in_flax_module(fn: ast.AST, ctx: "FileContext") -> bool:
    """Is ``fn`` a method of a class whose bases resolve to a flax
    ``nn.Module`` (directly or through a local Module subclass)?"""
    for anc in ctx.ancestors(fn):
        if isinstance(anc, ast.ClassDef):
            return any(
                (ctx.qualname(b) or "").rsplit(".", 1)[-1] == "Module"
                or isinstance(b, ast.Name) and b.id.endswith("Module")
                for b in anc.bases)
    return False


def _traced_functions(ctx: "FileContext") -> Set[ast.AST]:
    """Seed + fixpoint: which function defs end up inside a trace."""
    fns = _function_nodes(ctx.tree)
    by_name: Dict[str, List[ast.AST]] = {}
    for fn in fns:
        by_name.setdefault(fn.name, []).append(fn)

    traced: Set[ast.AST] = set()
    for fn in fns:
        if fn.name in TRACED_BY_NAME:
            traced.add(fn)
        if any(_is_tracing_decorator(d, ctx) for d in fn.decorator_list):
            traced.add(fn)
        if fn.name == "__call__" and _in_flax_module(fn, ctx):
            # flax modules' __call__ always executes under a trace
            traced.add(fn)

    # functions passed by name into a tracing entry point:
    #   jax.jit(train_step, ...), lax.scan(body, ...), partial(jax.jit, f)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fqn = ctx.qualname(node.func)
        args = node.args
        if fqn in ("functools.partial", "partial") and args and \
                ctx.qualname(args[0]) in TRACING_ENTRY_POINTS:
            args = args[1:]
        elif fqn not in TRACING_ENTRY_POINTS:
            continue
        for arg in args:
            if isinstance(arg, ast.Name) and arg.id in by_name:
                traced.update(by_name[arg.id])

    # transitive closure: a call by bare name from a traced body drags
    # the callee into the trace (grad_step -> micro -> loss_fn chains).
    # Call edges are collected in one pass: callee name -> caller defs.
    callers_of: Dict[str, Set[ast.AST]] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Name) and \
                node.func.id in by_name:
            callers_of.setdefault(node.func.id, set()).update(
                ctx.enclosing_functions(node))
    changed = True
    while changed:
        changed = False
        for fn in fns:
            if fn not in traced and \
                    callers_of.get(fn.name, set()) & traced:
                traced.add(fn)
                changed = True
    return traced


# ---------------------------------------------------------------------------


def iter_py_files(paths: Iterable[str]) -> Iterator[str]:
    for path in paths:
        if os.path.isfile(path):
            yield path
            continue
        if not os.path.isdir(path):
            # a typo'd path must fail LOUDLY, not lint nothing and
            # report the tree clean (a vacuous CI gate)
            raise FileNotFoundError(f"no such file or directory: {path}")
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(
                d for d in dirnames
                if d not in ("__pycache__", ".git", ".venv"))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def default_project_root() -> str:
    """The repo root: parent of the fengshen_tpu package directory."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def check_file(path: str, rules: List[Rule],
               project_root: Optional[str] = None) -> List[Finding]:
    project_root = project_root or default_project_root()
    try:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    except (OSError, UnicodeDecodeError) as e:
        return [_pseudo_finding(path, project_root, 1,
                                f"unreadable file: {e}")]
    relpath = _relpath(path, project_root)
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [_pseudo_finding(path, project_root, e.lineno or 1,
                                f"syntax error: {e.msg}")]

    ctx = FileContext(path, relpath, source, tree, project_root)
    dispatch: Dict[type, List[Rule]] = {}
    for rule in rules:
        rule.begin_file(ctx)
        for nt in rule.NODE_TYPES:
            dispatch.setdefault(nt, []).append(rule)

    findings: List[Finding] = []

    def emit(rule: Rule, hits: Iterable[Tuple[ast.AST, str]]) -> None:
        for node, message in hits:
            line = getattr(node, "lineno", 1)
            col = getattr(node, "col_offset", 0)
            if ctx.is_suppressed(line, rule.id):
                continue
            code = ctx.lines[line - 1].strip() \
                if 0 < line <= len(ctx.lines) else ""
            findings.append(Finding(
                path=relpath, line=line, col=col, rule=rule.id,
                message=message, hint=rule.hint, code=code))

    for node in ast.walk(tree):
        for rule in dispatch.get(type(node), ()):
            emit(rule, rule.check(node, ctx))
    for rule in rules:
        emit(rule, rule.end_file(ctx))
    findings.sort(key=Finding.sort_key)
    return findings


def check_paths(paths: Iterable[str], rules: List[Rule],
                project_root: Optional[str] = None) -> List[Finding]:
    project_root = project_root or default_project_root()
    findings: List[Finding] = []
    for path in iter_py_files(paths):
        findings.extend(check_file(path, rules, project_root))
    findings.sort(key=Finding.sort_key)
    return findings


def _relpath(path: str, root: str) -> str:
    rel = os.path.relpath(os.path.abspath(path), root)
    return rel.replace(os.sep, "/")


def _pseudo_finding(path: str, root: str, line: int,
                    message: str) -> Finding:
    return Finding(path=_relpath(path, root), line=line, col=0,
                   rule="parse-error", message=message,
                   hint="fix the file so ast.parse succeeds", code="")
