"""fslint engine: two-phase analysis over per-file and project rules.

Pure stdlib — the analyzer never imports jax (or the package under
analysis), so ``python -m fengshen_tpu.analysis`` starts in
milliseconds and runs identically on a dev laptop, CI, and a TPU host.

Two tiers of rules share this engine:

- **per-file rules** (the original contract): one AST walk per file,
  every node dispatched to the rules subscribed to its type. The
  engine provides parsing + a parent map (``ctx.parent``),
  import-alias resolution (``ctx.qualname``), and traced-context
  analysis (``ctx.in_traced_context``).
- **project rules** (``registry.ProjectRule``): run once per
  invocation over the whole-package ``ProjectIndex`` built by
  ``analysis/project.py`` (phase 1) — lock inventories, guard scopes,
  and the cross-module call graph the concurrency rules need. Their
  findings are filtered to the analyzed paths, so ``--changed`` stays
  fast while the rules still see the full package.

Both tiers honour per-line suppressions: ``# fslint:
disable=<rule>[,<rule>]`` (or a bare ``# fslint: disable`` for all
rules) on the finding's line.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

from fengshen_tpu.analysis import project as project_mod
from fengshen_tpu.analysis.project import (collect_aliases,
                                           collect_comments,
                                           collect_suppressions,
                                           iter_py_files)
from fengshen_tpu.analysis.registry import Rule

#: calls whose function-valued arguments are traced by JAX. Matched
#: against alias-resolved dotted names, so ``from jax import lax;
#: lax.scan`` and ``jax.lax.scan`` both hit.
TRACING_ENTRY_POINTS = frozenset({
    "jax.jit", "jax.pmap", "jax.grad", "jax.value_and_grad", "jax.vmap",
    "jax.checkpoint", "jax.remat", "jax.eval_shape", "jax.make_jaxpr",
    "jax.lax.scan", "jax.lax.cond", "jax.lax.while_loop",
    "jax.lax.fori_loop", "jax.lax.switch", "jax.lax.map",
    "jax.lax.associative_scan", "jax.lax.custom_root",
    "jax.experimental.shard_map.shard_map", "jax.shard_map", "shard_map",
    "flax.linen.scan", "flax.linen.remat", "nn.scan", "nn.remat",
})

#: function names that are step functions by convention even when the
#: jit call lives in another file (the trainer jits
#: ``module.training_loss`` etc. — the definition site can't see that)
TRACED_BY_NAME = frozenset({
    "train_step", "eval_step", "training_loss", "validation_loss",
    "predict_step",
})

@dataclasses.dataclass(frozen=True)
class Finding:
    """One analyzer hit. Sorts by (path, line, col, rule) so text and
    ``--json`` output — and therefore the baseline file and CI diffs —
    are deterministic across hosts and dict orderings."""

    path: str       # repo-relative, posix separators
    line: int
    col: int
    rule: str
    message: str
    hint: str
    code: str       # stripped source line (anchors baseline matching)

    def sort_key(self) -> Tuple[str, int, int, str]:
        return (self.path, self.line, self.col, self.rule)

    def to_dict(self) -> Dict[str, object]:
        return {"path": self.path, "line": self.line, "col": self.col,
                "rule": self.rule, "message": self.message,
                "hint": self.hint, "code": self.code}

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule}: "
                f"{self.message}\n    {self.code}\n    fix: {self.hint}")


class FileContext:
    """Everything rules may ask about one source file."""

    def __init__(self, path: str, relpath: str, source: str,
                 tree: ast.Module, project_root: str) -> None:
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.project_root = project_root
        self._parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent
        self.aliases = collect_aliases(tree)
        self.comments = collect_comments(source)
        self.suppressions = collect_suppressions(self.comments)
        self._traced = _traced_functions(self)

    # -- structure ---------------------------------------------------
    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self._parents.get(node)
        while cur is not None:
            yield cur
            cur = self._parents.get(cur)

    def enclosing_functions(self, node: ast.AST) -> List[ast.AST]:
        return [a for a in self.ancestors(node)
                if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda))]

    # -- names -------------------------------------------------------
    def qualname(self, node: ast.AST) -> Optional[str]:
        """Dotted origin of a Name/Attribute chain, alias-resolved.

        ``jnp.zeros`` -> ``jax.numpy.zeros`` (under ``import jax.numpy
        as jnp``); non-name expressions (calls, subscripts) -> None.
        """
        if isinstance(node, ast.Name):
            return self.aliases.get(node.id, node.id)
        if isinstance(node, ast.Attribute):
            base = self.qualname(node.value)
            return None if base is None else f"{base}.{node.attr}"
        return None

    # -- tracing -----------------------------------------------------
    def is_traced_function(self, fn: ast.AST) -> bool:
        return fn in self._traced

    def in_traced_context(self, node: ast.AST) -> bool:
        """True when any enclosing function is traced by JAX (jitted,
        grad/vmap-transformed, or a scan/cond/while body) — directly,
        lexically (nested inside one), or transitively by call."""
        return any(fn in self._traced
                   for fn in self.enclosing_functions(node))

    # -- suppressions ------------------------------------------------
    def is_suppressed(self, line: int, rule_id: str) -> bool:
        rules = self.suppressions.get(line)
        if rules is None:
            return False
        return not rules or rule_id in rules

    def line_comment(self, line: int) -> str:
        return self.comments.get(line, "")


def _function_nodes(tree: ast.Module) -> List[ast.AST]:
    return [n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]


def _is_tracing_decorator(dec: ast.AST, ctx: "FileContext") -> bool:
    qn = ctx.qualname(dec)
    if qn in TRACING_ENTRY_POINTS:
        return True
    if isinstance(dec, ast.Call):
        # @jax.jit(static_argnums=...) / @partial(jax.jit, ...)
        fqn = ctx.qualname(dec.func)
        if fqn in TRACING_ENTRY_POINTS:
            return True
        if fqn in ("functools.partial", "partial") and dec.args:
            return ctx.qualname(dec.args[0]) in TRACING_ENTRY_POINTS
    return False


def _in_flax_module(fn: ast.AST, ctx: "FileContext") -> bool:
    """Is ``fn`` a method of a class whose bases resolve to a flax
    ``nn.Module`` (directly or through a local Module subclass)?"""
    for anc in ctx.ancestors(fn):
        if isinstance(anc, ast.ClassDef):
            return any(
                (ctx.qualname(b) or "").rsplit(".", 1)[-1] == "Module"
                or isinstance(b, ast.Name) and b.id.endswith("Module")
                for b in anc.bases)
    return False


def _traced_functions(ctx: "FileContext") -> Set[ast.AST]:
    """Seed + fixpoint: which function defs end up inside a trace."""
    fns = _function_nodes(ctx.tree)
    by_name: Dict[str, List[ast.AST]] = {}
    for fn in fns:
        by_name.setdefault(fn.name, []).append(fn)

    traced: Set[ast.AST] = set()
    for fn in fns:
        if fn.name in TRACED_BY_NAME:
            traced.add(fn)
        if any(_is_tracing_decorator(d, ctx) for d in fn.decorator_list):
            traced.add(fn)
        if fn.name == "__call__" and _in_flax_module(fn, ctx):
            # flax modules' __call__ always executes under a trace
            traced.add(fn)

    # functions passed by name into a tracing entry point:
    #   jax.jit(train_step, ...), lax.scan(body, ...), partial(jax.jit, f)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fqn = ctx.qualname(node.func)
        args = node.args
        if fqn in ("functools.partial", "partial") and args and \
                ctx.qualname(args[0]) in TRACING_ENTRY_POINTS:
            args = args[1:]
        elif fqn not in TRACING_ENTRY_POINTS:
            continue
        for arg in args:
            if isinstance(arg, ast.Name) and arg.id in by_name:
                traced.update(by_name[arg.id])

    # transitive closure: a call by bare name from a traced body drags
    # the callee into the trace (grad_step -> micro -> loss_fn chains).
    # Call edges are collected in one pass: callee name -> caller defs.
    callers_of: Dict[str, Set[ast.AST]] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Name) and \
                node.func.id in by_name:
            callers_of.setdefault(node.func.id, set()).update(
                ctx.enclosing_functions(node))
    changed = True
    while changed:
        changed = False
        for fn in fns:
            if fn not in traced and \
                    callers_of.get(fn.name, set()) & traced:
                traced.add(fn)
                changed = True
    return traced


# ---------------------------------------------------------------------------


def default_project_root() -> str:
    """The repo root: parent of the fengshen_tpu package directory."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def _check_one_file(path: str, rules: List[Rule],
                    project_root: str) -> List[Finding]:
    """Phase 2a: the per-file walk (per-file rules only)."""
    try:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    except (OSError, UnicodeDecodeError) as e:
        return [_pseudo_finding(path, project_root, 1,
                                f"unreadable file: {e}")]
    relpath = _relpath(path, project_root)
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [_pseudo_finding(path, project_root, e.lineno or 1,
                                f"syntax error: {e.msg}")]

    ctx = FileContext(path, relpath, source, tree, project_root)
    dispatch: Dict[type, List[Rule]] = {}
    for rule in rules:
        rule.begin_file(ctx)
        for nt in rule.NODE_TYPES:
            dispatch.setdefault(nt, []).append(rule)

    findings: List[Finding] = []

    def emit(rule: Rule, hits: Iterable[Tuple[ast.AST, str]]) -> None:
        for node, message in hits:
            line = getattr(node, "lineno", 1)
            col = getattr(node, "col_offset", 0)
            if ctx.is_suppressed(line, rule.id):
                continue
            code = ctx.lines[line - 1].strip() \
                if 0 < line <= len(ctx.lines) else ""
            findings.append(Finding(
                path=relpath, line=line, col=col, rule=rule.id,
                message=message, hint=rule.hint, code=code))

    for node in ast.walk(tree):
        for rule in dispatch.get(type(node), ()):
            emit(rule, rule.check(node, ctx))
    for rule in rules:
        emit(rule, rule.end_file(ctx))
    findings.sort(key=Finding.sort_key)
    return findings


def run_project_rules(rules: List[Rule],
                      index: "project_mod.ProjectIndex",
                      project_root: str,
                      restrict: Optional[Set[str]] = None,
                      ) -> List[Finding]:
    """Phase 2b: project rules over the index. ``restrict`` limits
    emission to the analyzed relpaths (``--changed`` lints a subset
    of the files the index was built from)."""
    findings: List[Finding] = []
    line_cache: Dict[str, List[str]] = {}

    def code_line(relpath: str, line: int) -> str:
        if relpath not in line_cache:
            try:
                with open(os.path.join(project_root, relpath),
                          encoding="utf-8") as f:
                    line_cache[relpath] = f.read().splitlines()
            except (OSError, UnicodeDecodeError):
                line_cache[relpath] = []
        lines = line_cache[relpath]
        return lines[line - 1].strip() if 0 < line <= len(lines) \
            else ""

    for rule in rules:
        rule.project_root = project_root
        for relpath, line, col, message in rule.check_project(index):
            if restrict is not None and relpath not in restrict:
                continue
            if index.is_suppressed(relpath, line, rule.id):
                continue
            findings.append(Finding(
                path=relpath, line=line, col=col, rule=rule.id,
                message=message, hint=rule.hint,
                code=code_line(relpath, line)))
    findings.sort(key=Finding.sort_key)
    return findings


def check_file(path: str, rules: List[Rule],
               project_root: Optional[str] = None,
               index: Optional["project_mod.ProjectIndex"] = None,
               ) -> List[Finding]:
    project_root = project_root or default_project_root()
    file_rules = [r for r in rules if not r.PROJECT]
    proj_rules = [r for r in rules if r.PROJECT]
    findings = _check_one_file(path, file_rules, project_root)
    if proj_rules:
        if index is None:
            index = project_mod.build_index([path], project_root)
        findings.extend(run_project_rules(
            proj_rules, index, project_root,
            restrict={_relpath(path, project_root)}))
    findings.sort(key=Finding.sort_key)
    return findings


def check_paths(paths: Iterable[str], rules: List[Rule],
                project_root: Optional[str] = None,
                index: Optional["project_mod.ProjectIndex"] = None,
                index_cache: Optional[str] = None) -> List[Finding]:
    """Two-phase run over ``paths``.

    When ``index`` is given (e.g. built over the whole package for a
    ``--changed`` subset run), project rules use it for cross-module
    context but only report inside the analyzed paths; otherwise the
    index is built from ``paths`` themselves."""
    project_root = project_root or default_project_root()
    file_rules = [r for r in rules if not r.PROJECT]
    proj_rules = [r for r in rules if r.PROJECT]
    findings: List[Finding] = []
    analyzed: Set[str] = set()
    files = list(iter_py_files(paths))
    for path in files:
        analyzed.add(_relpath(path, project_root))
        findings.extend(_check_one_file(path, file_rules,
                                        project_root))
    if proj_rules:
        if index is None:
            index = project_mod.build_index(files, project_root,
                                            cache_path=index_cache)
        findings.extend(run_project_rules(proj_rules, index,
                                          project_root,
                                          restrict=analyzed))
    findings.sort(key=Finding.sort_key)
    return findings


def _relpath(path: str, root: str) -> str:
    rel = os.path.relpath(os.path.abspath(path), root)
    return rel.replace(os.sep, "/")


def _pseudo_finding(path: str, root: str, line: int,
                    message: str) -> Finding:
    return Finding(path=_relpath(path, root), line=line, col=0,
                   rule="parse-error", message=message,
                   hint="fix the file so ast.parse succeeds", code="")
