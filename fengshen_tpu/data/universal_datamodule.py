"""UniversalDataModule — one datamodule for every workload.

Port of the reference's universal datamodule
(reference: fengshen/data/universal_datamodule/universal_datamodule.py:20-189):
three dataset sources (passed-in datasets dict, a named dataset from the
registry, or raw json/csv files via HF `datasets`), resumable Megatron-style
samplers, and DP-rank-aware sharding. The torch DataLoader machinery is
replaced by a small host-side loader producing numpy batches for
`jax.device_put` (device transfer/prefetch is the trainer's job).
"""

from __future__ import annotations

import argparse
from typing import Any, Callable, Optional

import numpy as np

from fengshen_tpu.data.universal_sampler import (PretrainingRandomSampler,
                                                 PretrainingSampler)


def get_consumed_samples(trainer_or_model: Any, global_batch: int) -> int:
    """Reference: universal_datamodule.py:8-17 — prefer the checkpointed
    `consumed_samples`, else derive from global_step × global batch."""
    consumed = getattr(trainer_or_model, "consumed_samples", None)
    if consumed is not None:
        return int(consumed)
    step = getattr(trainer_or_model, "global_step", 0)
    return int(step * global_batch)


def _default_collate(samples: list) -> dict:
    """Stack dict-of-arrays samples into a numpy batch."""
    if not samples:
        return {}
    first = samples[0]
    if isinstance(first, dict):
        return {k: np.stack([np.asarray(s[k]) for s in samples])
                for k in first}
    return {"batch": np.stack([np.asarray(s) for s in samples])}


class DataLoader:
    """Sampler-driven host loader yielding numpy batches."""

    def __init__(self, dataset, sampler, collate_fn: Optional[Callable] = None,
                 global_batch_size: int = 1):
        self.dataset = dataset
        self.sampler = sampler
        self.collate_fn = collate_fn or _default_collate
        self.global_batch_size = global_batch_size
        self.num_samples = len(dataset)

    def __len__(self) -> int:
        return max(1, self.num_samples // self.global_batch_size)

    def __iter__(self):
        for indices in self.sampler:
            try:
                batch = self.collate_fn([self.dataset[int(i)]
                                         for i in indices])
            except Exception:  # noqa: BLE001 — always re-raised
                # the stateful sampler already counted these indices as
                # consumed; roll it back so a retry (ResilientLoader
                # re-entry) sees the same batch, not the next one
                unconsume = getattr(self.sampler, "unconsume", None)
                if callable(unconsume):
                    unconsume()
                raise
            yield batch

    def skip_next(self) -> None:
        """ResilientLoader's cooperative skip protocol: advance the
        sampler past the next (poison) batch without fetching it —
        the escape hatch when a batch fails deterministically and the
        `unconsume` rollback would otherwise pin retries onto it."""
        next(iter(self.sampler), None)

    def peek(self):
        """A shape-representative batch WITHOUT advancing the (stateful)
        sampler — used by the trainer to derive batch specs."""
        micro = getattr(self.sampler, "micro_batch_size", None) or \
            getattr(self.sampler, "batch", 1)
        n = min(micro, self.num_samples)
        return self.collate_fn([self.dataset[i % self.num_samples]
                                for i in range(n)])


class _SimpleBatchSampler:
    """Plain epoch sampler (shuffled or not) used when resumability is not
    requested — the analog of Lightning's default DistributedSampler path
    (reference: universal_datamodule.py:134-160)."""

    def __init__(self, total: int, batch: int, rank: int, world: int,
                 shuffle: bool, seed: int = 0, drop_last: bool = True):
        self.total, self.batch = total, batch
        self.rank, self.world = rank, world
        self.shuffle, self.seed = shuffle, seed
        self.drop_last = drop_last
        self.epoch = 0

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def __iter__(self):
        order = np.arange(self.total)
        if self.shuffle:
            order = np.random.RandomState(self.seed + self.epoch
                                          ).permutation(self.total)
        global_batch = self.batch * self.world
        usable = self.total - self.total % global_batch if self.drop_last \
            else self.total
        for start in range(0, usable, global_batch):
            chunk = order[start:start + global_batch]
            mine = chunk[self.rank * self.batch:(self.rank + 1) * self.batch]
            if len(mine) < self.batch:
                # Tail batch: pad so EVERY rank yields the same number
                # of full batches — a rank whose slice would be empty
                # must not fall out of step with its peers on a
                # multi-host mesh (ADVICE r4; torch DistributedSampler
                # drop_last=False contract). Pad from the rank's OWN
                # slice when it has one, so per-rank dedup (save_test's
                # `written` set) removes those duplicates; a rank with
                # an EMPTY tail slice has to borrow rows from the global
                # chunk, and those rows also appear on the owning rank —
                # duplicate model outputs are identical (same params,
                # same row), so merged multi-rank outputs must be
                # deduped by id, which is lossless.
                src = mine if len(mine) else chunk
                mine = np.resize(src, self.batch)
            yield list(mine)


class UniversalDataModule:
    @staticmethod
    def add_data_specific_args(parent_args: argparse.ArgumentParser):
        """Reference: universal_datamodule.py:21-44 (same flag names)."""
        parser = parent_args.add_argument_group("Universal DataModule")
        parser.add_argument("--num_workers", default=8, type=int)
        parser.add_argument("--dataloader_workers", default=2, type=int)
        parser.add_argument("--train_batchsize", default=16, type=int)
        parser.add_argument("--val_batchsize", default=16, type=int)
        parser.add_argument("--test_batchsize", default=16, type=int)
        parser.add_argument("--datasets_name", type=str, default=None)
        parser.add_argument("--train_datasets_field", type=str,
                            default="train")
        parser.add_argument("--val_datasets_field", type=str,
                            default="validation")
        parser.add_argument("--test_datasets_field", type=str, default="test")
        parser.add_argument("--train_file", type=str, default=None)
        parser.add_argument("--val_file", type=str, default=None)
        parser.add_argument("--test_file", type=str, default=None)
        parser.add_argument("--raw_file_type", type=str, default="json")
        parser.add_argument("--sampler_type", type=str, default="random",
                            choices=["single", "random"])
        parser.add_argument("--use_mpu", action="store_true", default=False)
        return parent_args

    def __init__(self, tokenizer=None, collate_fn: Optional[Callable] = None,
                 args=None, datasets: Optional[dict] = None, **kwargs):
        self.tokenizer = tokenizer
        self.collate_fn = collate_fn
        self.args = args
        self.trainer = None  # set by Trainer.fit for consumed_samples
        if datasets is not None:
            self.datasets = datasets
        elif getattr(args, "datasets_name", None) is not None:
            from fengshen_tpu.data.fs_datasets import load_dataset
            self.datasets = load_dataset(
                args.datasets_name,
                num_proc=getattr(args, "num_workers", 1))
        elif any(getattr(args, attr, None) for attr in
                 ("train_file", "val_file", "test_file")):
            # any split file triggers file loading — predict-only runs
            # pass just --test_file (e.g. qa_t5 run_predict.sh)
            import datasets as hf_datasets
            file_type = getattr(args, "raw_file_type", "json")
            data_files = {}
            for split, attr in (("train", "train_file"),
                                ("validation", "val_file"),
                                ("test", "test_file")):
                if getattr(args, attr, None):
                    data_files[split] = getattr(args, attr)
            self.datasets = hf_datasets.load_dataset(
                file_type, data_files=data_files)
        else:
            self.datasets = {}

    # -- dp topology -----------------------------------------------------
    def _dp_info(self) -> tuple[int, int]:
        from fengshen_tpu.parallel.mesh import (data_parallel_rank,
                                                data_parallel_world_size,
                                                get_mesh)
        mesh = get_mesh()
        if mesh is None:
            return 0, 1
        return data_parallel_rank(mesh), data_parallel_world_size(mesh)

    # -- loaders ---------------------------------------------------------
    def _make_loader(self, split_field: str, batch_size: int,
                     resumable: bool, shuffle: bool):
        ds = self.datasets.get(split_field) if hasattr(
            self.datasets, "get") else self.datasets[split_field]
        if ds is None:
            return None
        rank, world = self._dp_info()
        consumed = get_consumed_samples(self.trainer, batch_size * world) \
            if resumable and self.trainer is not None else 0
        if resumable:
            sampler_type = getattr(self.args, "sampler_type", "random")
            if sampler_type == "random":
                sampler = PretrainingRandomSampler(
                    total_samples=len(ds), consumed_samples=consumed,
                    micro_batch_size=batch_size, data_parallel_rank=rank,
                    data_parallel_size=world,
                    epoch_seed=getattr(self.args, "seed", 42))
            else:
                sampler = PretrainingSampler(
                    total_samples=len(ds), consumed_samples=consumed,
                    micro_batch_size=batch_size, data_parallel_rank=rank,
                    data_parallel_size=world)
        else:
            sampler = _SimpleBatchSampler(
                len(ds), batch_size, rank, world, shuffle,
                seed=getattr(self.args, "seed", 42))
        return DataLoader(ds, sampler, self.collate_fn,
                          global_batch_size=batch_size * world)

    def train_dataloader(self):
        return self._make_loader(
            getattr(self.args, "train_datasets_field", "train"),
            getattr(self.args, "train_batchsize", 16),
            resumable=True, shuffle=True)

    def val_dataloader(self):
        field = getattr(self.args, "val_datasets_field", "validation")
        if not self._has_split(field):
            return None
        return self._make_loader(field,
                                 getattr(self.args, "val_batchsize", 16),
                                 resumable=False, shuffle=False)

    def test_dataloader(self):
        field = getattr(self.args, "test_datasets_field", "test")
        if not self._has_split(field):
            return None
        return self._make_loader(field,
                                 getattr(self.args, "test_batchsize", 16),
                                 resumable=False, shuffle=False)

    def _has_split(self, field: str) -> bool:
        try:
            return field in self.datasets and \
                self.datasets[field] is not None
        except TypeError:
            return False
