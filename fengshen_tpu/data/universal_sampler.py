"""Resumable deterministic samplers.

Functional port of the reference's Megatron-style samplers
(reference: fengshen/data/universal_datamodule/universal_sampler.py:22-125):
- `PretrainingSampler` — sequential order, resumes by skipping
  `consumed_samples` (:22-60).
- `PretrainingRandomSampler` — per-epoch seeded shuffle inside this
  data-parallel rank's bucket, resuming mid-epoch via
  `consumed_samples % active_total` (:63-125).

Both yield micro-batches of indices for ONE data-parallel rank; determinism
across ranks comes from seeding with the epoch only (same permutation on
every host). The math is pure index arithmetic, so these are plain Python
iterables — no torch Sampler base class needed.
"""

from __future__ import annotations

import numpy as np


class PretrainingSampler:
    #: iteration does NOT mutate consumed_samples — re-entering restarts
    #: from the construction-time position (see ResilientLoader)
    resumes_mid_epoch = False

    def __init__(self, total_samples: int, consumed_samples: int,
                 micro_batch_size: int, data_parallel_rank: int,
                 data_parallel_size: int, drop_last: bool = True):
        if total_samples <= 0:
            raise ValueError(f"no samples to consume: {total_samples}")
        if consumed_samples >= total_samples:
            raise ValueError("consumed_samples >= total_samples "
                             f"({consumed_samples} >= {total_samples})")
        if data_parallel_rank >= data_parallel_size:
            raise ValueError("data_parallel_rank >= data_parallel_size "
                             f"({data_parallel_rank} >= {data_parallel_size})")
        self.total_samples = total_samples
        self.consumed_samples = consumed_samples
        self.micro_batch_size = micro_batch_size
        self.data_parallel_rank = data_parallel_rank
        self.data_parallel_size = data_parallel_size
        self.drop_last = drop_last
        self.global_batch = micro_batch_size * data_parallel_size

    def __len__(self) -> int:
        return self.total_samples

    def _rank_slice(self, batch: list[int]) -> list[int]:
        start = self.data_parallel_rank * self.micro_batch_size
        return batch[start:start + self.micro_batch_size]

    def __iter__(self):
        batch: list[int] = []
        for idx in range(self.consumed_samples, self.total_samples):
            batch.append(idx)
            if len(batch) == self.global_batch:
                yield self._rank_slice(batch)
                batch = []
        if batch and not self.drop_last:
            yield self._rank_slice(batch)


class PretrainingRandomSampler:
    #: consumed_samples advances as batches are yielded, so re-entering
    #: (`iter()` again) resumes mid-epoch — the property ResilientLoader
    #: keys its retry semantics on
    resumes_mid_epoch = True

    def __init__(self, total_samples: int, consumed_samples: int,
                 micro_batch_size: int, data_parallel_rank: int,
                 data_parallel_size: int, epoch_seed: int = 0):
        if total_samples <= 0:
            raise ValueError(f"no samples to consume: {total_samples}")
        if data_parallel_rank >= data_parallel_size:
            raise ValueError("data_parallel_rank >= data_parallel_size "
                             f"({data_parallel_rank} >= {data_parallel_size})")
        self.total_samples = total_samples
        self.consumed_samples = consumed_samples
        self.micro_batch_size = micro_batch_size
        self.data_parallel_rank = data_parallel_rank
        self.data_parallel_size = data_parallel_size
        self.epoch_seed = epoch_seed
        self.global_batch = micro_batch_size * data_parallel_size
        # samples beyond the last full global batch are dropped each epoch
        self.active_total = total_samples - total_samples % self.global_batch
        if self.active_total <= 0:
            raise ValueError(
                f"total_samples {total_samples} < one global batch "
                f"{self.global_batch}")

    def __len__(self) -> int:
        return self.total_samples

    @property
    def epoch(self) -> int:
        return self.consumed_samples // self.active_total

    def __iter__(self):
        epoch = self.epoch
        # position within the current epoch, split across DP ranks
        current = self.consumed_samples % self.active_total
        bucket_size = self.active_total // self.data_parallel_size
        bucket_offset = current // self.data_parallel_size
        start = self.data_parallel_rank * bucket_size

        rng = np.random.RandomState(self.epoch_seed + epoch)
        order = start + rng.permutation(bucket_size)
        order = order[bucket_offset:]

        batch: list[int] = []
        for idx in order:
            batch.append(int(idx))
            if len(batch) == self.micro_batch_size:
                self.consumed_samples += self.global_batch
                yield batch
                batch = []

    def unconsume(self) -> None:
        """Roll the cursor back one global batch: the DataLoader calls
        this when fetching the just-yielded indices fails, so a
        ResilientLoader re-entry retries the SAME batch instead of
        silently dropping it."""
        self.consumed_samples = max(0, self.consumed_samples -
                                    self.global_batch)
