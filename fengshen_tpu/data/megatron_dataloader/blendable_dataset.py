"""Weighted multi-corpus blending
(reference: fengshen/data/megatron_dataloader/blendable_dataset.py:26-64,
indices built by the native `build_blending_indices`)."""

from __future__ import annotations

from typing import Sequence

import numpy as np

from fengshen_tpu.data.megatron_dataloader.helpers import (
    build_blending_indices)


class BlendableDataset:
    def __init__(self, datasets: Sequence, weights: Sequence[float],
                 size: int | None = None):
        if len(datasets) != len(weights):
            raise ValueError("datasets and weights length mismatch")
        self.datasets = list(datasets)
        if size is None:
            size = sum(len(d) for d in datasets)
        self.size = size
        w = np.asarray(weights, np.float64)
        self.dataset_index, self.dataset_sample_index = \
            build_blending_indices(w, size)

    def __len__(self) -> int:
        return self.size

    def __getitem__(self, idx: int):
        d = int(self.dataset_index[idx])
        s = int(self.dataset_sample_index[idx]) % len(self.datasets[d])
        return self.datasets[d][s]
