"""BERT-style MLM+NSP/SOP dataset over an indexed corpus.

Behavioural port of reference:
fengshen/data/megatron_dataloader/bert_dataset.py:30-196 — sentence-window
samples from the native `build_mapping` index, A/B segment pairing,
truncation, [CLS]/[SEP] assembly, whole-word MLM, and fixed-length padding
with -100 loss masking (`pad_and_convert_to_numpy`, :166).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from fengshen_tpu.data.data_utils import (create_masked_lm_predictions,
                                          create_tokens_and_tokentypes,
                                          get_a_and_b_segments,
                                          truncate_segments)
from fengshen_tpu.data.megatron_dataloader.helpers import build_mapping
from fengshen_tpu.data.megatron_dataloader.indexed_dataset import (
    MMapIndexedDataset)


class BertDataset:
    """Sentence-pair MLM+NSP samples (reference: bert_dataset.py:30-88)."""

    def __init__(self, indexed: MMapIndexedDataset,
                 tokenizer: Any,
                 max_seq_length: int = 512,
                 masked_lm_prob: float = 0.15,
                 short_seq_prob: float = 0.1,
                 seed: int = 0,
                 zh_tokenizer: Optional[Any] = None):
        self.indexed = indexed
        self.tokenizer = tokenizer
        self.max_seq_length = max_seq_length
        self.masked_lm_prob = masked_lm_prob
        self.seed = seed
        # None = default to jieba (the reference's Chinese WWM);
        # False = plain wordpiece grouping (non-Chinese corpora / tests)
        if zh_tokenizer is None:
            try:
                import jieba
                zh_tokenizer = jieba.lcut
            except ImportError:  # pragma: no cover
                zh_tokenizer = False
        self.zh_tokenizer = zh_tokenizer or None
        # sentence windows from the native mapping (reference uses the C++
        # build_mapping over doc/sentence indices, :44-56)
        docs = np.asarray(indexed.doc_idx, np.int64)
        sizes = np.asarray(indexed.sizes, np.int32)
        self.samples_mapping = build_mapping(
            docs, sizes, max_seq_length - 3, short_seq_prob, seed)
        vocab = tokenizer.get_vocab()
        self.vocab_id_list = list(vocab.values())
        self.vocab_id_to_token = {v: k for k, v in vocab.items()}

    def __len__(self) -> int:
        return len(self.samples_mapping)

    def __getitem__(self, idx: int) -> dict:
        start, end, target_len = (int(x) for x in self.samples_mapping[idx])
        sents = [np.asarray(self.indexed[i]).tolist()
                 for i in range(start, end)]
        np_rng = np.random.RandomState((self.seed + idx) % (2 ** 31))
        tok = self.tokenizer

        a, b, is_random = get_a_and_b_segments(sents, np_rng)
        truncate_segments(a, b, len(a), len(b), target_len, np_rng)
        tokens, tokentypes = create_tokens_and_tokentypes(
            a, b, tok.cls_token_id, tok.sep_token_id)
        masked_tokens, positions, labels = create_masked_lm_predictions(
            tokens, self.vocab_id_list, self.vocab_id_to_token,
            self.masked_lm_prob, tok.cls_token_id, tok.sep_token_id,
            tok.mask_token_id,
            max_predictions_per_seq=int(
                self.masked_lm_prob * self.max_seq_length) + 1,
            np_rng=np_rng, zh_tokenizer=self.zh_tokenizer)

        mlm_labels = [-100] * len(tokens)
        for pos, label in zip(positions, labels):
            mlm_labels[pos] = label
        pad_id = tok.pad_token_id or 0
        pad = self.max_seq_length - len(masked_tokens)
        return {
            "input_ids": np.asarray(masked_tokens + [pad_id] * pad,
                                    np.int32),
            "attention_mask": np.asarray(
                [1] * len(masked_tokens) + [0] * pad, np.int32),
            "token_type_ids": np.asarray(tokentypes + [0] * pad, np.int32),
            "labels": np.asarray(mlm_labels + [-100] * pad, np.int32),
            "next_sentence_label": np.asarray(int(is_random), np.int32),
        }
