"""Memory-mapped token storage (.idx + .bin).

Functional counterpart of the reference's fairseq-derived
`MMapIndexedDataset` (reference:
fengshen/data/megatron_dataloader/indexed_dataset.py, 585 LoC): binary token
storage addressed by a sequence index, document-boundary aware, built once
and mmapped at training time so TB-scale corpora never load into RAM.

Format (little-endian):
  .idx: magic b'FSTPUIDX' | version u64 | dtype_code u8 |
        n_sequences u64 | n_docs u64 | sizes i32[n_sequences] |
        pointers i64[n_sequences] | doc_idx i64[n_docs+1]
  .bin: the raw token arrays back to back
"""

from __future__ import annotations

import os
import struct
from typing import Optional, Union

import numpy as np

_MAGIC = b"FSTPUIDX"
_VERSION = 1

_DTYPES = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32,
           5: np.int64, 6: np.float32, 7: np.float64, 8: np.uint16}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def data_file_path(prefix: str) -> str:
    return prefix + ".bin"


def index_file_path(prefix: str) -> str:
    return prefix + ".idx"


class MMapIndexedDatasetBuilder:
    def __init__(self, out_file: str, dtype=np.int32):
        self._data = open(data_file_path(out_file), "wb")
        self._prefix = out_file
        self._dtype = np.dtype(dtype)
        self._sizes: list[int] = []
        self._doc_idx: list[int] = [0]

    def add_item(self, tokens) -> None:
        arr = np.asarray(tokens, dtype=self._dtype)
        self._data.write(arr.tobytes(order="C"))
        self._sizes.append(len(arr))

    def end_document(self) -> None:
        self._doc_idx.append(len(self._sizes))

    def merge_file_(self, another_prefix: str) -> None:
        other = MMapIndexedDataset(another_prefix)
        offset = len(self._sizes)
        for i in range(len(other)):
            self.add_item(other[i])
        for d in other.doc_idx[1:]:
            self._doc_idx.append(int(d) + offset)

    def finalize(self) -> None:
        self._data.close()
        sizes = np.asarray(self._sizes, np.int32)
        pointers = np.zeros(len(sizes), np.int64)
        np.cumsum(sizes[:-1] * self._dtype.itemsize, out=pointers[1:])
        with open(index_file_path(self._prefix), "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<Q", _VERSION))
            f.write(struct.pack("<B", _DTYPE_CODES[self._dtype]))
            f.write(struct.pack("<Q", len(sizes)))
            f.write(struct.pack("<Q", len(self._doc_idx) - 1))
            f.write(sizes.tobytes())
            f.write(pointers.tobytes())
            f.write(np.asarray(self._doc_idx, np.int64).tobytes())


class MMapIndexedDataset:
    def __init__(self, prefix: str):
        with open(index_file_path(prefix), "rb") as f:
            magic = f.read(len(_MAGIC))
            if magic != _MAGIC:
                raise ValueError(f"bad index magic in {prefix}.idx")
            (version,) = struct.unpack("<Q", f.read(8))
            if version != _VERSION:
                raise ValueError(f"unsupported index version {version}")
            (dtype_code,) = struct.unpack("<B", f.read(1))
            (n_seq,) = struct.unpack("<Q", f.read(8))
            (n_docs,) = struct.unpack("<Q", f.read(8))
            self._dtype = np.dtype(_DTYPES[dtype_code])
            offset = f.tell()
        idx_buffer = np.memmap(index_file_path(prefix), mode="r",
                               dtype=np.uint8)
        self.sizes = idx_buffer[offset:offset + 4 * n_seq].view(np.int32)
        offset += 4 * n_seq
        self._pointers = idx_buffer[offset:offset + 8 * n_seq].view(np.int64)
        offset += 8 * n_seq
        self.doc_idx = idx_buffer[offset:offset + 8 * (n_docs + 1)].view(
            np.int64)
        self._data = np.memmap(data_file_path(prefix), mode="r",
                               dtype=np.uint8)
        self._prefix = prefix

    def __len__(self) -> int:
        return len(self.sizes)

    def __getitem__(self, idx: Union[int, slice]) -> np.ndarray:
        if isinstance(idx, slice):
            return [self[i] for i in range(*idx.indices(len(self)))]
        ptr = int(self._pointers[idx])
        size = int(self.sizes[idx])
        return self._data[ptr:ptr + size * self._dtype.itemsize].view(
            self._dtype)

    def get(self, idx: int, offset: int = 0,
            length: Optional[int] = None) -> np.ndarray:
        """Partial read within a sequence (used by GPT sample packing)."""
        full = self[idx]
        if length is None:
            length = len(full) - offset
        return full[offset:offset + length]

    @staticmethod
    def exists(prefix: str) -> bool:
        return os.path.exists(index_file_path(prefix)) and \
            os.path.exists(data_file_path(prefix))
