"""ctypes binding for the native index builders, with numpy fallbacks.

Replaces the reference's pybind11 `helpers` module and its on-demand build
(reference: fengshen/data/megatron_dataloader/dataset_utils.py:77-88
`compile_helper`). If the shared object is missing we build it with make;
if that fails (no toolchain), pure-numpy fallbacks keep everything working.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libindex_helpers.so")

_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def compile_helper() -> bool:
    """Build the shared object (reference: dataset_utils.py:77-88)."""
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                       capture_output=True)
        return True
    except (OSError, subprocess.SubprocessError):
        # make missing (OSError) or the build failed (CalledProcessError)
        # — caller falls back to the pure-python index builders
        return False


def _get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _lib_tried
    if _lib is not None or _lib_tried:
        return _lib
    _lib_tried = True
    if not os.path.exists(_LIB_PATH):
        if not compile_helper():
            return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    i8p = ctypes.POINTER(ctypes.c_int8)
    dp = ctypes.POINTER(ctypes.c_double)
    lib.build_sample_idx.argtypes = [i32p, i32p, ctypes.c_int64,
                                     ctypes.c_int32, ctypes.c_int32,
                                     ctypes.c_int64, i32p, ctypes.c_int64]
    lib.build_blending_indices.argtypes = [i8p, i64p, dp, ctypes.c_int32,
                                           ctypes.c_int64, ctypes.c_int32]
    lib.build_mapping.argtypes = [i64p, ctypes.c_int64, i32p,
                                  ctypes.c_int32, ctypes.c_double,
                                  ctypes.c_int32, i64p, ctypes.c_int64]
    lib.build_mapping.restype = ctypes.c_int64
    lib.build_blocks_mapping.argtypes = [i64p, ctypes.c_int64, i32p,
                                         ctypes.c_int32, i64p,
                                         ctypes.c_int64]
    lib.build_blocks_mapping.restype = ctypes.c_int64
    _lib = lib
    return _lib


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def build_sample_idx(sizes: np.ndarray, doc_idx: np.ndarray,
                     seq_length: int, num_epochs: int,
                     tokens_per_epoch: int) -> np.ndarray:
    """[(num_samples+1), 2] (document position, token offset) boundaries."""
    sizes = np.ascontiguousarray(sizes, np.int32)
    doc_idx = np.ascontiguousarray(doc_idx, np.int32)
    total_tokens = int(sizes[doc_idx].sum())
    num_samples = max((total_tokens - 1) // seq_length, 1)
    lib = _get_lib()
    if lib is not None:
        out = np.zeros((num_samples + 1, 2), np.int32)
        lib.build_sample_idx(_ptr(sizes, ctypes.c_int32),
                             _ptr(doc_idx, ctypes.c_int32),
                             len(doc_idx), seq_length, num_epochs,
                             tokens_per_epoch,
                             _ptr(out, ctypes.c_int32), num_samples)
        return out
    # numpy fallback
    out = np.zeros((num_samples + 1, 2), np.int32)
    doc_pos, doc_offset = 0, 0
    for s in range(1, num_samples + 1):
        remaining = seq_length + 1
        while remaining > 0 and doc_pos < len(doc_idx):
            doc_len = int(sizes[doc_idx[doc_pos]]) - doc_offset
            if doc_len >= remaining:
                # one-token overlap (reference: helpers.cpp:165): next
                # sample re-starts at this sample's last (label) token
                doc_offset += remaining - 1
                remaining = 0
            else:
                remaining -= doc_len
                doc_pos += 1
                doc_offset = 0
        out[s] = (doc_pos, doc_offset)
        if doc_pos >= len(doc_idx):
            out[s + 1:] = out[s]
            break
    return out


def build_blending_indices(weights: np.ndarray, size: int,
                           verbose: bool = False
                           ) -> tuple[np.ndarray, np.ndarray]:
    weights = np.ascontiguousarray(weights, np.float64)
    weights = weights / weights.sum()
    lib = _get_lib()
    dataset_index = np.zeros((size,), np.int8)
    dataset_sample_index = np.zeros((size,), np.int64)
    if lib is not None:
        lib.build_blending_indices(
            _ptr(dataset_index, ctypes.c_int8),
            _ptr(dataset_sample_index, ctypes.c_int64),
            _ptr(weights, ctypes.c_double), len(weights), size,
            int(verbose))
        return dataset_index, dataset_sample_index
    counts = np.zeros((len(weights),), np.int64)
    for i in range(size):
        gaps = weights * (i + 1) - counts
        best = int(gaps.argmax())
        dataset_index[i] = best
        dataset_sample_index[i] = counts[best]
        counts[best] += 1
    return dataset_index, dataset_sample_index


def build_mapping(docs: np.ndarray, sizes: np.ndarray, max_seq_length: int,
                  short_seq_prob: float, seed: int) -> np.ndarray:
    """[(N, 3)] (start sentence, end sentence, target length) windows."""
    docs = np.ascontiguousarray(docs, np.int64)
    sizes = np.ascontiguousarray(sizes, np.int32)
    lib = _get_lib()
    if lib is None:
        raise RuntimeError(
            "native index helpers unavailable; run make -C native "
            "(build_mapping has no numpy fallback)")
    null = ctypes.POINTER(ctypes.c_int64)()
    count = lib.build_mapping(_ptr(docs, ctypes.c_int64), len(docs) - 1,
                              _ptr(sizes, ctypes.c_int32), max_seq_length,
                              short_seq_prob, seed, null, 0)
    out = np.zeros((count, 3), np.int64)
    lib.build_mapping(_ptr(docs, ctypes.c_int64), len(docs) - 1,
                      _ptr(sizes, ctypes.c_int32), max_seq_length,
                      short_seq_prob, seed, _ptr(out, ctypes.c_int64),
                      count)
    return out


def build_blocks_mapping(docs: np.ndarray, sizes: np.ndarray,
                         max_seq_length: int) -> np.ndarray:
    docs = np.ascontiguousarray(docs, np.int64)
    sizes = np.ascontiguousarray(sizes, np.int32)
    lib = _get_lib()
    if lib is None:
        raise RuntimeError("native index helpers unavailable; run "
                           "make -C native")
    null = ctypes.POINTER(ctypes.c_int64)()
    count = lib.build_blocks_mapping(_ptr(docs, ctypes.c_int64),
                                     len(docs) - 1,
                                     _ptr(sizes, ctypes.c_int32),
                                     max_seq_length, null, 0)
    out = np.zeros((count, 3), np.int64)
    lib.build_blocks_mapping(_ptr(docs, ctypes.c_int64), len(docs) - 1,
                             _ptr(sizes, ctypes.c_int32), max_seq_length,
                             _ptr(out, ctypes.c_int64), count)
    return out
