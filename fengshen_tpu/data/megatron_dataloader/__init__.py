"""Megatron-style indexed datasets for TB-scale corpora
(reference: fengshen/data/megatron_dataloader/)."""

from fengshen_tpu.data.megatron_dataloader.indexed_dataset import (
    MMapIndexedDataset, MMapIndexedDatasetBuilder)
from fengshen_tpu.data.megatron_dataloader.blendable_dataset import (
    BlendableDataset)
from fengshen_tpu.data.megatron_dataloader.gpt_dataset import GPTDataset
from fengshen_tpu.data.megatron_dataloader.bert_dataset import BertDataset
from fengshen_tpu.data.megatron_dataloader.bart_dataset import BartDataset

__all__ = ["MMapIndexedDataset", "MMapIndexedDatasetBuilder",
           "BlendableDataset", "GPTDataset", "BertDataset",
           "BartDataset"]
