"""BART-style denoising dataset over an indexed corpus.

Behavioural port of reference:
fengshen/data/megatron_dataloader/bart_dataset.py:13-443 — fairseq-style
text infilling for Chinese: sentence windows assembled with [CLS]/[SEP]
full stops, sentence permutation (permute_sentences, :190-207), and
whole-word span masking with Poisson(λ=3) span lengths where each selected
span collapses to a single [MASK] (add_whole_word_mask with
replace_length=1) and a fraction of masks becomes random tokens. Word
units come from jieba over the detokenized span (word_starts, :218-289).
Targets are the ORIGINAL tokens shifted (decoder reconstructs the clean
text); pads are -100 in labels.
"""

from __future__ import annotations

import math
from typing import Any, Optional

import numpy as np

from fengshen_tpu.data.data_utils.mask_utils import whole_word_spans
from fengshen_tpu.data.megatron_dataloader.indexed_dataset import (
    MMapIndexedDataset)


def _poisson_span_lengths(n: int, lam: float, np_rng) -> np.ndarray:
    """Sample span lengths ≥ 1 from a truncated Poisson(λ)
    (reference: bart_dataset.py:71-85 precomputed cdf sampling)."""
    lengths = np_rng.poisson(lam, size=n)
    return np.maximum(lengths, 1)


class BartDataset:
    """Denoising samples {input_ids, attention_mask, labels}
    (reference: bart_dataset.py:98-188 build_training_sample)."""

    def __init__(self, indexed: MMapIndexedDataset, tokenizer: Any,
                 max_seq_length: int = 512,
                 masked_lm_prob: float = 0.15,
                 permute_sentence_ratio: float = 1.0,
                 random_ratio: float = 0.1,
                 poisson_lambda: float = 3.0,
                 seed: int = 0,
                 zh_tokenizer: Optional[Any] = None):
        self.indexed = indexed
        self.tokenizer = tokenizer
        self.max_seq_length = max_seq_length
        self.mask_ratio = masked_lm_prob
        self.permute_sentence_ratio = permute_sentence_ratio
        self.random_ratio = random_ratio
        self.poisson_lambda = poisson_lambda
        self.seed = seed
        # None = default to jieba (the reference's Chinese WWM);
        # False = plain wordpiece grouping (non-Chinese corpora / tests)
        if zh_tokenizer is None:
            try:
                import jieba
                zh_tokenizer = jieba.lcut
            except ImportError:  # pragma: no cover
                zh_tokenizer = False
        self.zh_tokenizer = zh_tokenizer or None
        vocab = tokenizer.get_vocab()
        self.vocab_id_to_token = {v: k for k, v in vocab.items()}
        self.vocab_size = len(vocab)
        self.doc_idx = np.asarray(indexed.doc_idx, np.int64)

    def __len__(self) -> int:
        return len(self.doc_idx) - 1

    # -- noising pieces ----------------------------------------------------

    def _permute_sentences(self, tokens: list[int], np_rng) -> list[int]:
        """Shuffle [SEP]-delimited sentences, keeping [CLS] first
        (reference: permute_sentences :190-207)."""
        sep = self.tokenizer.sep_token_id
        sents, cur = [], []
        for t in tokens[1:]:
            cur.append(t)
            if t == sep:
                sents.append(cur)
                cur = []
        if cur:
            sents.append(cur)
        if len(sents) <= 1:
            return tokens
        n = len(sents)
        num_to_permute = math.ceil(n * self.permute_sentence_ratio)
        order = np.arange(n)
        chosen = np_rng.permutation(n)[:num_to_permute]
        order[np.sort(chosen)] = chosen
        out = [tokens[0]]
        for i in order:
            out.extend(sents[i])
        return out

    def _whole_word_mask(self, tokens: list[int], np_rng) -> list[int]:
        """Poisson-span whole-word infilling: each selected word-span run
        collapses to ONE mask token (replace_length=1), a fraction becomes
        a random token instead (reference: add_whole_word_mask)."""
        tok = self.tokenizer
        specials = {tok.cls_token_id, tok.sep_token_id}
        token_strs = [self.vocab_id_to_token.get(t, str(t)) for t in tokens]
        units = whole_word_spans(token_strs, self.vocab_id_to_token,
                                 self.zh_tokenizer)
        cand = [u for u in units
                if all(tokens[i] not in specials for i in u)]
        if not cand:
            return tokens
        # reference :140 doubles the ratio in decoder-reconstruction mode
        # (always on in this fork)
        n_to_mask = max(1, int(round(
            sum(len(u) for u in cand) * self.mask_ratio * 2)))
        order = np_rng.permutation(len(cand))
        span_lens = _poisson_span_lengths(len(cand), self.poisson_lambda,
                                          np_rng)
        drop: set[int] = set()
        mask_at: dict[int, int] = {}
        covered = 0
        for oi, ui in enumerate(order):
            if covered >= n_to_mask:
                break
            # a span starts at this word and runs span_lens[oi] words
            start = int(ui)
            span = cand[start: start + int(span_lens[oi])]
            idxs = [i for u in span for i in u]
            if not idxs or any(i in drop or i in mask_at for i in idxs):
                continue
            keep = min(idxs)
            if np_rng.random() < self.random_ratio:
                mask_at[keep] = int(np_rng.randint(5, self.vocab_size))
            else:
                mask_at[keep] = tok.mask_token_id
            drop.update(i for i in idxs if i != keep)
            covered += len(idxs)
        out = []
        for i, t in enumerate(tokens):
            if i in mask_at:
                out.append(mask_at[i])
            elif i not in drop:
                out.append(t)
        return out

    # -- sample assembly ---------------------------------------------------

    def __getitem__(self, idx: int) -> dict:
        tok = self.tokenizer
        np_rng = np.random.RandomState((self.seed + idx) % (2 ** 31))
        lo, hi = int(self.doc_idx[idx]), int(self.doc_idx[idx + 1])
        tokens = [tok.cls_token_id]
        for i in range(lo, hi):
            tokens.extend(np.asarray(self.indexed[i]).tolist())
            if tokens[-1] != tok.sep_token_id:
                tokens.append(tok.sep_token_id)
        tokens = tokens[: self.max_seq_length]
        tokens[-1] = tok.sep_token_id

        target = tokens[1:]
        source = tokens
        if self.permute_sentence_ratio > 0.0:
            source = self._permute_sentences(source, np_rng)
        if self.mask_ratio > 0.0:
            # decoder-mode doubling (reference :140: mask_ratio*2 when the
            # decoder reconstructs)
            source = self._whole_word_mask(source, np_rng)

        pad_id = tok.pad_token_id or 0
        src = np.full((self.max_seq_length,), pad_id, np.int32)
        src[: len(source)] = source[: self.max_seq_length]
        labels = np.full((self.max_seq_length,), -100, np.int32)
        labels[: len(target)] = target[: self.max_seq_length]
        return {"input_ids": src,
                "attention_mask": (src != pad_id).astype(np.int32),
                "labels": labels}
