"""GPT pretraining dataset: contiguous-token packing over an indexed corpus.

Reference: the GPT path of
fengshen/data/megatron_dataloader/dataset_utils.py:504-788 — the
`build_sample_idx` index plus the `.npy` cache contract of
`get_samples_mapping` (:731-788): index maps are built once (natively),
cached next to the data, and mmapped by every subsequent run. Unlike the
reference (which deleted the cross-rank barrier and requires the cache to be
prebuilt, :763-776), cache building here is atomic (tmp + rename) so
concurrent hosts race safely.
"""

from __future__ import annotations

import hashlib
import os
from typing import Optional

import numpy as np

from fengshen_tpu.data.megatron_dataloader.helpers import build_sample_idx
from fengshen_tpu.data.megatron_dataloader.indexed_dataset import (
    MMapIndexedDataset)


class GPTDataset:
    """Packs documents into fixed seq_length training samples."""

    def __init__(self, indexed: MMapIndexedDataset, seq_length: int,
                 seed: int = 0, num_epochs: int = 1,
                 documents: Optional[np.ndarray] = None,
                 cache_dir: Optional[str] = None,
                 name: str = "gpt"):
        self.indexed = indexed
        self.seq_length = seq_length
        if documents is None:
            documents = np.arange(len(indexed.doc_idx) - 1, dtype=np.int32)
        rng = np.random.RandomState(seed)

        # shuffled document order, repeated per epoch
        doc_idx_parts = []
        for _ in range(num_epochs):
            doc_idx_parts.append(rng.permutation(documents).astype(np.int32))
        doc_order = np.concatenate(doc_idx_parts)

        # document order → sequence order (documents may span sequences;
        # here one document == one indexed sequence, doc_idx maps ranges)
        seq_order = []
        for d in doc_order:
            lo, hi = int(indexed.doc_idx[d]), int(indexed.doc_idx[d + 1])
            seq_order.extend(range(lo, hi))
        self.seq_order = np.asarray(seq_order, np.int32)
        sizes = np.asarray(indexed.sizes, np.int32)

        self.sample_idx = self._cached_sample_idx(
            sizes, self.seq_order, seq_length, num_epochs, seed, cache_dir,
            name)

    def _cached_sample_idx(self, sizes, seq_order, seq_length, num_epochs,
                           seed, cache_dir, name) -> np.ndarray:
        if cache_dir is None:
            return build_sample_idx(sizes, seq_order, seq_length,
                                    num_epochs,
                                    int(sizes[seq_order].sum()))
        key = hashlib.md5(
            f"{name}-{seq_length}-{num_epochs}-{seed}-"
            f"{len(seq_order)}".encode()).hexdigest()[:16]
        cache = os.path.join(cache_dir, f"{name}_sample_idx_{key}.npy")
        if os.path.exists(cache):
            return np.load(cache, mmap_mode="r")
        idx = build_sample_idx(sizes, seq_order, seq_length, num_epochs,
                               int(sizes[seq_order].sum()))
        os.makedirs(cache_dir, exist_ok=True)
        tmp = cache[:-len(".npy")] + f".tmp{os.getpid()}.npy"
        np.save(tmp, idx)
        os.replace(tmp, cache)  # atomic: concurrent builders race safely
        return idx

    def __len__(self) -> int:
        return len(self.sample_idx) - 1

    def __getitem__(self, idx: int) -> dict:
        """One seq_length+1 token window, boundaries INCLUSIVE of the token
        at the end offset (the one-token overlap convention of the index
        builder, reference helpers.cpp:165).

        Returns ``input_ids`` of exactly seq_length tokens (the window minus
        its final label token) so batch shapes stay tile/mesh-aligned, and
        unshifted ``labels == input_ids`` (with -100 at padding): the
        training module owns the shift (CausalLMModule.training_loss
        computes logits[:, :-1] vs labels[:, 1:]), so the dataset must NOT
        pre-shift. The window's last token is not a target here — it is the
        next sample's first input via the one-token overlap.
        """
        doc_f, off_f = self.sample_idx[idx]
        doc_l, off_l = self.sample_idx[idx + 1]
        if doc_f == doc_l:
            tokens = self.indexed.get(int(self.seq_order[doc_f]),
                                      offset=int(off_f),
                                      length=int(off_l - off_f) + 1)
            parts = [tokens]
        else:
            parts = [self.indexed.get(int(self.seq_order[doc_f]),
                                      offset=int(off_f))]
            for d in range(int(doc_f) + 1, int(doc_l)):
                parts.append(self.indexed[int(self.seq_order[d])])
            if doc_l < len(self.seq_order):
                parts.append(self.indexed.get(int(self.seq_order[doc_l]),
                                              length=int(off_l) + 1))
        tokens = np.concatenate(parts)
        tokens = tokens[: self.seq_length]
        n_valid = len(tokens)
        if n_valid < self.seq_length:
            tokens = np.pad(tokens, (0, self.seq_length - n_valid))
        labels = tokens.astype(np.int32).copy()
        labels[n_valid:] = -100  # pad positions never contribute to the loss
        return {"input_ids": tokens.astype(np.int32),
                "labels": labels}
