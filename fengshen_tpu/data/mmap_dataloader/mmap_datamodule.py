"""Datamodule over MMapIndexDataset
(reference: fengshen/data/mmap_dataloader/mmap_datamodule.py:7-68)."""

from __future__ import annotations

import argparse

from fengshen_tpu.data.mmap_dataloader.mmap_index_dataset import (
    MMapIndexDataset)
from fengshen_tpu.data.universal_datamodule import UniversalDataModule


class MMapDataModule(UniversalDataModule):
    @staticmethod
    def add_data_specific_args(parent_args: argparse.ArgumentParser):
        parent_args = UniversalDataModule.add_data_specific_args(parent_args)
        parser = parent_args.add_argument_group("MMap DataModule")
        parser.add_argument("--train_datas_dir", type=str, default=None)
        parser.add_argument("--val_datas_dir", type=str, default=None)
        parser.add_argument("--test_datas_dir", type=str, default=None)
        parser.add_argument("--input_tensor_name", type=str, nargs="+",
                            default=["input_ids"])
        return parent_args

    def __init__(self, collate_fn=None, args=None, **kwargs):
        datasets = {}
        names = getattr(args, "input_tensor_name", ["input_ids"])
        for split, attr in (("train", "train_datas_dir"),
                            ("validation", "val_datas_dir"),
                            ("test", "test_datas_dir")):
            path = getattr(args, attr, None)
            if path:
                datasets[split] = MMapIndexDataset(path, names)
        super().__init__(collate_fn=collate_fn, args=args,
                         datasets=datasets, **kwargs)
