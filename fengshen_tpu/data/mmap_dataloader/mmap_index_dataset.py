"""N-tensor memmap dataset keyed by tensor names.

Port of reference: fengshen/data/mmap_dataloader/mmap_index_dataset.py:7-53
— each named tensor is a pair of files `{name}.npy` (flat data memmap) and
`{name}_idx.npy` (row offsets); `__getitem__` returns a dict of rows.
"""

from __future__ import annotations

import os
from typing import Sequence

import numpy as np


class MMapIndexDataset:
    def __init__(self, data_dir: str, input_tensor_name: Sequence[str]):
        self.names = list(input_tensor_name)
        self._data = {}
        self._idx = {}
        for name in self.names:
            self._data[name] = np.load(
                os.path.join(data_dir, f"{name}.npy"), mmap_mode="r")
            self._idx[name] = np.load(
                os.path.join(data_dir, f"{name}_idx.npy"))

    def __len__(self) -> int:
        first = self.names[0]
        return len(self._idx[first]) - 1

    def __getitem__(self, i: int) -> dict:
        out = {}
        for name in self.names:
            lo, hi = int(self._idx[name][i]), int(self._idx[name][i + 1])
            out[name] = np.asarray(self._data[name][lo:hi])
        return out


def convert_py_to_npy(rows: Sequence[Sequence[int]], data_dir: str,
                      name: str, dtype=np.int32) -> None:
    """Build the `{name}.npy`/`{name}_idx.npy` pair from python lists
    (reference: fengshen/utils/convert_py_to_npy.py)."""
    os.makedirs(data_dir, exist_ok=True)
    flat = np.concatenate([np.asarray(r, dtype) for r in rows]) if rows \
        else np.zeros((0,), dtype)
    idx = np.zeros((len(rows) + 1,), np.int64)
    np.cumsum([len(r) for r in rows], out=idx[1:])
    np.save(os.path.join(data_dir, f"{name}.npy"), flat)
    np.save(os.path.join(data_dir, f"{name}_idx.npy"), idx)
