"""Generic memmap datamodule (reference: fengshen/data/mmap_dataloader/)."""

from fengshen_tpu.data.mmap_dataloader.mmap_index_dataset import (
    MMapIndexDataset)
from fengshen_tpu.data.mmap_dataloader.mmap_datamodule import MMapDataModule

__all__ = ["MMapIndexDataset", "MMapDataModule"]
