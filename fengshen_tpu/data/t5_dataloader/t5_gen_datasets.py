"""T5 generation-task data (knowledge-grounded dialog).

Behavioural port of reference:
fengshen/data/t5_dataloader/t5_gen_datasets.py:38-343 — multi-turn dialog
samples {context: [turns], knowledge, target} rendered as
``[KNSTART] knowledge [KNEND] [CTSTART] context-tail [CTEND]`` with the
context truncated from the LEFT (keep the latest turns, :155-163), target
truncated to max_target_length with eos, and decoder inputs shifted right
(:288-301).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import numpy as np


@dataclass
class DialogCollator:
    """reference: DialogDataset.regular_tokenize + DialogDataModel
    collate_fn."""

    tokenizer: Any
    max_seq_length: int = 512
    max_knowledge_length: int = 128
    max_target_length: int = 128
    decoder_start_token_id: int = 0

    def _marker(self, name: str) -> int:
        tok = self.tokenizer
        tid = tok.convert_tokens_to_ids(name) if hasattr(
            tok, "convert_tokens_to_ids") else None
        unk = getattr(tok, "unk_token_id", None)
        if tid is None or tid == unk:
            # markers absent from the vocab degrade to [SEP]
            return tok.sep_token_id
        return tid

    def __call__(self, samples: list[dict]) -> dict:
        tok = self.tokenizer
        pad_id = tok.pad_token_id or 0
        eos = tok.eos_token_id
        kn_s, kn_e = self._marker("[KNSTART]"), self._marker("[KNEND]")
        ct_s, ct_e = self._marker("[CTSTART]"), self._marker("[CTEND]")
        batch = {"input_ids": [], "attention_mask": [],
                 "decoder_input_ids": [], "labels": []}
        for s in samples:
            context = s.get("context", [])
            if isinstance(context, str):
                context = [context]
            flat: list[int] = []
            for turn in context:
                flat.extend(tok.encode(turn, add_special_tokens=False))
            knowledge = tok.encode(s.get("knowledge", ""),
                                   add_special_tokens=False
                                   )[: self.max_knowledge_length - 2]
            kn = [kn_s] + knowledge + [kn_e]
            # knowledge itself must leave room for the context markers
            kn = kn[: max(self.max_seq_length - 2, 0)]
            # keep the TAIL of the context (latest turns); clamp at 0 so an
            # oversized knowledge never flips the slice to the HEAD
            l_ct = max(0, min(len(flat),
                              self.max_seq_length - len(kn) - 2))
            ct = [ct_s] + (flat[-l_ct:] if l_ct else []) + [ct_e]
            src = kn + ct  # ≤ max_seq_length by construction, CTEND kept

            tgt = tok.encode(s["target"], add_special_tokens=False
                             )[: self.max_target_length - 1]
            if eos is not None:
                tgt = tgt + [eos]
            dec_in = [self.decoder_start_token_id] + tgt[:-1]
            ps = self.max_seq_length - len(src)
            pt = self.max_target_length - len(tgt)
            batch["input_ids"].append(src + [pad_id] * ps)
            batch["attention_mask"].append([1] * len(src) + [0] * ps)
            batch["decoder_input_ids"].append(dec_in + [pad_id] * pt)
            batch["labels"].append(tgt + [-100] * pt)
        return {k: np.asarray(v) for k, v in batch.items()}
