"""T5 pretraining data (reference: fengshen/data/t5_dataloader/)."""

from fengshen_tpu.data.t5_dataloader.t5_datasets import (
    compute_input_and_target_lengths, random_spans_noise_mask,
    T5SpanCorruptionCollator)

__all__ = ["compute_input_and_target_lengths", "random_spans_noise_mask",
           "T5SpanCorruptionCollator"]
