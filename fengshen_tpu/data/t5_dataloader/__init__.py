"""T5 pretraining + generation-task data
(reference: fengshen/data/t5_dataloader/)."""

from fengshen_tpu.data.t5_dataloader.t5_datasets import (
    compute_input_and_target_lengths, random_spans_noise_mask,
    T5SpanCorruptionCollator)
from fengshen_tpu.data.t5_dataloader.t5_gen_datasets import DialogCollator

__all__ = ["compute_input_and_target_lengths", "random_spans_noise_mask",
           "T5SpanCorruptionCollator", "DialogCollator"]
