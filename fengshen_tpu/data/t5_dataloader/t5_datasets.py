"""T5 span-corruption pretraining data.

Port of the reference's T5 dataloader
(reference: fengshen/data/t5_dataloader/t5_datasets.py:14-560 —
`compute_input_and_target_lengths` from mesh-tf, span-corruption sample
construction for `UnsuperviseT5Dataset`). The collator maps tokenized text
to (input with sentinel tokens, target with sentinels) pairs.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import numpy as np


def compute_input_and_target_lengths(inputs_length: int,
                                     noise_density: float,
                                     mean_noise_span_length: float
                                     ) -> tuple[int, int]:
    """Raw token count whose corruption yields exactly `inputs_length`
    encoder tokens (reference: t5_datasets.py:14-59, from mesh-tf)."""

    def lengths(tokens_length: int) -> tuple[int, int]:
        num_noise_tokens = int(round(tokens_length * noise_density))
        num_nonnoise_tokens = tokens_length - num_noise_tokens
        num_spans = int(round(num_noise_tokens / mean_noise_span_length))
        num_spans = max(num_spans, 1)
        # inputs: non-noise tokens + one sentinel per span + eos
        return (num_nonnoise_tokens + num_spans + 1,
                num_noise_tokens + num_spans + 1)

    tokens_length = inputs_length
    while lengths(tokens_length + 1)[0] <= inputs_length:
        tokens_length += 1
    return tokens_length, lengths(tokens_length)[1]


def random_spans_noise_mask(length: int, noise_density: float,
                            mean_noise_span_length: float,
                            np_rng) -> np.ndarray:
    """Boolean mask of noise positions made of random spans
    (mesh-tf `random_spans_noise_mask` semantics)."""
    num_noise = int(round(length * noise_density))
    num_noise = min(max(num_noise, 1), length - 1)
    num_spans = int(round(num_noise / mean_noise_span_length))
    num_spans = max(num_spans, 1)
    num_nonnoise = length - num_noise

    def random_segmentation(total, n):
        ids = np.arange(total - 1) < n - 1
        np_rng.shuffle(ids)
        starts = np.concatenate([[True], ids])
        segment = np.cumsum(starts) - 1
        return np.bincount(segment, minlength=n)

    noise_spans = random_segmentation(num_noise, num_spans)
    nonnoise_spans = random_segmentation(num_nonnoise, num_spans)
    interleaved = np.zeros((num_spans * 2,), np.int64)
    interleaved[0::2] = nonnoise_spans
    interleaved[1::2] = noise_spans
    span_starts = np.cumsum(interleaved)[:-1]
    mask = np.zeros((length,), bool)
    indicator = np.zeros((length,), bool)
    indicator[span_starts] = True
    segment = np.cumsum(indicator)
    return (segment % 2) == 1


@dataclass
class T5SpanCorruptionCollator:
    """text → (input_ids, labels) span corruption with sentinels.

    Reference workload: fengshen/examples/pretrain_t5/pretrain_t5.py over
    `UnsuperviseT5DataModel`.
    """

    tokenizer: Any
    max_seq_length: int = 512
    noise_density: float = 0.15
    mean_noise_span_length: float = 3.0
    content_key: str = "text"
    seed: int = 42
    decoder_start_token_id: int = 0

    def __post_init__(self):
        self.np_rng = np.random.RandomState(self.seed)
        self.tokens_length, self.targets_length = \
            compute_input_and_target_lengths(
                self.max_seq_length, self.noise_density,
                self.mean_noise_span_length)
        # sentinel ids: sentencepiece T5 puts <extra_id_0> LAST and
        # descends; the char-level T5Tokenizer wrapper APPENDS
        # <extra_id_0..117> so its ids ascend — it publishes them as
        # `sentinel_token_ids` (models/t5/tokenization_megatron_t5.py)
        sentinels = getattr(self.tokenizer, "sentinel_token_ids", None)
        if sentinels:
            self.sentinels = list(sentinels)
        else:
            self.sentinels = [len(self.tokenizer) - 1 - i
                              for i in range(100)]
        self.eos = self.tokenizer.eos_token_id or 1
        self.pad = self.tokenizer.pad_token_id or 0

    def _corrupt(self, ids: list[int]) -> tuple[list[int], list[int]]:
        ids = ids[: self.tokens_length]
        if len(ids) < 2:
            ids = ids + [self.eos]
        mask = random_spans_noise_mask(len(ids), self.noise_density,
                                       self.mean_noise_span_length,
                                       self.np_rng)
        inp, tgt = [], []
        span_i = 0
        prev_noise = False
        for tok, is_noise in zip(ids, mask):
            if is_noise:
                if not prev_noise:
                    sentinel = self.sentinels[
                        min(span_i, len(self.sentinels) - 1)]
                    inp.append(sentinel)
                    tgt.append(sentinel)
                    span_i += 1
                tgt.append(tok)
            else:
                inp.append(tok)
            prev_noise = bool(is_noise)
        inp.append(self.eos)
        tgt.append(self.eos)
        return inp, tgt

    def __call__(self, samples: list[dict]) -> dict:
        batch = {"input_ids": [], "attention_mask": [],
                 "decoder_input_ids": [], "labels": []}
        for s in samples:
            text = s[self.content_key] if isinstance(s, dict) else s
            ids = self.tokenizer.encode(text, add_special_tokens=False)
            inp, tgt = self._corrupt(ids)
            inp = inp[: self.max_seq_length]
            tgt = tgt[: self.targets_length]
            dec_in = [self.decoder_start_token_id] + tgt[:-1]

            pad_i = self.max_seq_length - len(inp)
            pad_t = self.targets_length - len(tgt)
            batch["input_ids"].append(inp + [self.pad] * pad_i)
            batch["attention_mask"].append([1] * len(inp) + [0] * pad_i)
            batch["decoder_input_ids"].append(dec_in + [self.pad] * pad_t)
            batch["labels"].append(tgt + [-100] * pad_t)
        return {k: np.asarray(v) for k, v in batch.items()}
