"""Image-text data for CLIP/SD (reference: fengshen/data/clip_dataloader/
flickr.py and fengshen/data/taiyi_stable_diffusion_datasets/)."""

from fengshen_tpu.data.clip_dataloader.image_text import (
    ImageTextCSVDataset, CLIPCollator, SDCollator)

__all__ = ["ImageTextCSVDataset", "CLIPCollator", "SDCollator"]
