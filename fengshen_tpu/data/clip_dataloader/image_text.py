"""Image-text csv datasets + collators for CLIP / Stable Diffusion.

Reference: fengshen/data/clip_dataloader/flickr.py (image-path/caption csv
for Taiyi-CLIP) and fengshen/data/taiyi_stable_diffusion_datasets/
taiyi_datasets.py (image+caption rows for SD finetune). Images are loaded
with PIL, resized/center-cropped and normalised on host; tensors are NHWC
float32 (TPU conv layout).
"""

from __future__ import annotations

import csv
import os
from dataclasses import dataclass
from typing import Any, Optional

import numpy as np


class ImageTextCSVDataset:
    """csv rows (image_path, caption) → dicts. Separator configurable
    (the reference's flickr csv uses tab)."""

    def __init__(self, csv_path: str, image_root: Optional[str] = None,
                 image_key: str = "image", caption_key: str = "caption",
                 delimiter: str = ","):
        self.rows: list[dict] = []
        self.image_root = image_root or os.path.dirname(csv_path)
        with open(csv_path) as f:
            reader = csv.DictReader(f, delimiter=delimiter)
            for row in reader:
                self.rows.append({"image": row[image_key],
                                  "caption": row[caption_key]})

    def __len__(self) -> int:
        return len(self.rows)

    def __getitem__(self, i: int) -> dict:
        row = self.rows[i]
        path = row["image"]
        if not os.path.isabs(path):
            path = os.path.join(self.image_root, path)
        return {"image_path": path, "caption": row["caption"]}


def load_image(path: str, size: int) -> np.ndarray:
    """PIL load → resize shorter side → center crop → [0,1] NHWC float."""
    from PIL import Image
    img = Image.open(path).convert("RGB")
    w, h = img.size
    scale = size / min(w, h)
    img = img.resize((max(int(w * scale), size),
                      max(int(h * scale), size)))
    w, h = img.size
    left, top = (w - size) // 2, (h - size) // 2
    img = img.crop((left, top, left + size, top + size))
    return np.asarray(img, np.float32) / 255.0


@dataclass
class CLIPCollator:
    """captions+images → contrastive batch (Taiyi-CLIP pretrain)."""

    tokenizer: Any
    image_size: int = 224
    max_length: int = 77
    mean: tuple = (0.48145466, 0.4578275, 0.40821073)
    std: tuple = (0.26862954, 0.26130258, 0.27577711)

    def __call__(self, samples: list[dict]) -> dict:
        enc = self.tokenizer([s["caption"] for s in samples],
                             padding="max_length", truncation=True,
                             max_length=self.max_length,
                             return_tensors="np")
        images = np.stack([load_image(s["image_path"], self.image_size)
                           for s in samples])
        images = (images - np.asarray(self.mean)) / np.asarray(self.std)
        return {"input_ids": enc["input_ids"].astype(np.int32),
                "attention_mask": enc["attention_mask"].astype(np.int32),
                "pixel_values": images.astype(np.float32)}


@dataclass
class SDCollator:
    """captions+images → latent-diffusion batch (pixels in [-1, 1],
    reference: taiyi_datasets.py normalisation)."""

    tokenizer: Any
    image_size: int = 512
    max_length: int = 77

    def __call__(self, samples: list[dict]) -> dict:
        enc = self.tokenizer([s["caption"] for s in samples],
                             padding="max_length", truncation=True,
                             max_length=self.max_length,
                             return_tensors="np")
        images = np.stack([load_image(s["image_path"], self.image_size)
                           for s in samples])
        return {"input_ids": enc["input_ids"].astype(np.int32),
                "attention_mask": enc["attention_mask"].astype(np.int32),
                "pixel_values": (images * 2.0 - 1.0).astype(np.float32)}
