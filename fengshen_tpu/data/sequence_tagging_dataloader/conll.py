"""CoNLL-format NER loading
(reference: fengshen/data/sequence_tagging_dataloader/ — span/bio collators
and conll loaders)."""

from __future__ import annotations

from typing import Optional


def load_conll(path: str, sep: Optional[str] = None
               ) -> list[dict]:
    """Read `char TAG` lines separated by blank lines →
    [{"text": str, "labels": [tags]}]."""
    samples: list[dict] = []
    chars: list[str] = []
    tags: list[str] = []
    with open(path) as f:
        for line in f:
            line = line.rstrip("\n")
            if not line.strip():
                if chars:
                    samples.append({"text": "".join(chars),
                                    "labels": list(tags)})
                    chars, tags = [], []
                continue
            parts = line.split(sep)
            chars.append(parts[0])
            tags.append(parts[-1] if len(parts) > 1 else "O")
    if chars:
        samples.append({"text": "".join(chars), "labels": list(tags)})
    return samples


class ConllDataset:
    def __init__(self, path: str):
        self.samples = load_conll(path)

    def __len__(self) -> int:
        return len(self.samples)

    def __getitem__(self, i: int) -> dict:
        return self.samples[i]
