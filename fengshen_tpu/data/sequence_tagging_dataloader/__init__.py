"""Sequence-tagging data (reference:
fengshen/data/sequence_tagging_dataloader/)."""

from fengshen_tpu.data.sequence_tagging_dataloader.conll import (
    load_conll, ConllDataset)

__all__ = ["load_conll", "ConllDataset"]
