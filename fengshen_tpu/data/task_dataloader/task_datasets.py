"""Summarisation / medical-QA json datasets.

Port of reference: fengshen/data/task_dataloader/task_datasets.py:1-206
(LCSTS summary) and medicalQADataset.py (YuyuanQA) — jsonl loaders
producing encoder-decoder / causal-QA samples.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional


class _JsonlDataset:
    def __init__(self, data_path: str):
        self.rows: list[dict] = []
        with open(data_path) as f:
            for line in f:
                line = line.strip()
                if line:
                    self.rows.append(json.loads(line))

    def __len__(self) -> int:
        return len(self.rows)


class LCSTSDataset(_JsonlDataset):
    """{"text": ..., "summary": ...} rows
    (reference: task_datasets.py LCSTSDataset)."""

    def __init__(self, data_path: str, text_key: str = "text",
                 summary_key: str = "summary"):
        super().__init__(data_path)
        self.text_key, self.summary_key = text_key, summary_key

    def __getitem__(self, i: int) -> dict:
        row = self.rows[i]
        return {"text": row[self.text_key],
                "summary": row.get(self.summary_key, "")}


class MedicalQADataset(_JsonlDataset):
    """{"question"/"query": ..., "answer": ...} rows
    (reference: medicalQADataset.py)."""

    def __init__(self, data_path: str, question_key: str = "question",
                 answer_key: str = "answer"):
        super().__init__(data_path)
        self.question_key, self.answer_key = question_key, answer_key

    def __getitem__(self, i: int) -> dict:
        row = self.rows[i]
        q = row.get(self.question_key) or row.get("query", "")
        return {"question": q, "answer": row.get(self.answer_key, "")}
