"""Task datasets (reference: fengshen/data/task_dataloader/)."""

from fengshen_tpu.data.task_dataloader.task_datasets import (
    LCSTSDataset, MedicalQADataset)

__all__ = ["LCSTSDataset", "MedicalQADataset"]
