"""BERT corpus pipeline (reference: fengshen/data/bert_dataloader/ —
corpus sharding + sentence-level preprocessing + BertDataModule)."""

from fengshen_tpu.data.bert_dataloader.load import (shard_corpus,
                                                    preprocess_corpus)

__all__ = ["shard_corpus", "preprocess_corpus"]
