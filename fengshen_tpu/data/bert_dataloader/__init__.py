"""BERT corpus pipeline (reference: fengshen/data/bert_dataloader/ —
corpus sharding + sentence-level preprocessing + BertDataModule)."""

from fengshen_tpu.data.bert_dataloader.load import (
    auto_split, cut_sent_file, mark_sentence_boundaries,
    generate_cache_arrow, preprocess_corpus, repack_segments,
    shard_corpus, split_train_test_validation_index)

__all__ = ["shard_corpus", "preprocess_corpus", "cut_sent_file",
           "mark_sentence_boundaries", "repack_segments", "auto_split",
           "generate_cache_arrow", "split_train_test_validation_index"]
