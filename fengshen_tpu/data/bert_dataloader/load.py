"""Corpus sharding + preprocessing.

Port of reference: fengshen/data/bert_dataloader/load.py:27-200 +
preprocessing.py + auto_split.sh — split a large jsonl corpus into
~size-bounded shards and normalise documents to sentence lists.
"""

from __future__ import annotations

import json
import os
from typing import Iterator

from fengshen_tpu.data.data_utils.sentence_split import (
    ChineseSentenceSplitter)


def shard_corpus(input_path: str, output_dir: str,
                 shard_mb: int = 100) -> list[str]:
    """Split a jsonl corpus into ≤shard_mb files
    (reference: auto_split.sh's 100MB sharding)."""
    os.makedirs(output_dir, exist_ok=True)
    limit = shard_mb * 1024 * 1024
    shards: list[str] = []
    out = None
    written = 0
    with open(input_path) as f:
        for line in f:
            if out is None or written >= limit:
                if out is not None:
                    out.close()
                path = os.path.join(output_dir,
                                    f"shard_{len(shards):05d}.jsonl")
                shards.append(path)
                out = open(path, "w")
                written = 0
            out.write(line)
            written += len(line.encode())
    if out is not None:
        out.close()
    return shards


def preprocess_corpus(input_path: str, output_path: str,
                      content_key: str = "text") -> int:
    """Document → sentence-list rows
    (reference: preprocessing.py sentence-level normalisation)."""
    splitter = ChineseSentenceSplitter()
    n = 0
    with open(input_path) as fin, open(output_path, "w") as fout:
        for line in fin:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            sentences = splitter.tokenize(row.get(content_key, ""))
            if sentences:
                fout.write(json.dumps({"sentences": sentences},
                                      ensure_ascii=False) + "\n")
                n += 1
    return n
