"""Corpus sharding + preprocessing.

Port of reference: fengshen/data/bert_dataloader/load.py:27-200 +
preprocessing.py + auto_split.sh — split a large jsonl corpus into
~size-bounded shards and normalise documents to sentence lists.
"""

from __future__ import annotations

import json
import os
from typing import Iterator

from fengshen_tpu.data.data_utils.sentence_split import (
    ChineseSentenceSplitter)


def shard_corpus(input_path: str, output_dir: str,
                 shard_mb: int = 100) -> list[str]:
    """Split a jsonl corpus into ≤shard_mb files
    (reference: auto_split.sh's 100MB sharding)."""
    os.makedirs(output_dir, exist_ok=True)
    limit = shard_mb * 1024 * 1024
    shards: list[str] = []
    out = None
    written = 0
    try:
        with open(input_path) as f:
            for line in f:
                if out is None or written >= limit:
                    if out is not None:
                        out.close()
                    path = os.path.join(output_dir,
                                        f"shard_{len(shards):05d}.jsonl")
                    shards.append(path)
                    out = open(path, "w")
                    written = 0
                out.write(line)
                written += len(line.encode())
    finally:
        if out is not None:
            out.close()
    return shards


def preprocess_corpus(input_path: str, output_path: str,
                      content_key: str = "text") -> int:
    """Document → sentence-list rows
    (reference: preprocessing.py sentence-level normalisation)."""
    splitter = ChineseSentenceSplitter()
    n = 0
    with open(input_path) as fin, open(output_path, "w") as fout:
        for line in fin:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            sentences = splitter.tokenize(row.get(content_key, ""))
            if sentences:
                fout.write(json.dumps({"sentences": sentences},
                                      ensure_ascii=False) + "\n")
                n += 1
    return n


# -- the reference's exact wudao cleaning semantics -----------------------

import re

_BOUNDARY = "#####"
#: The five published sentence-boundary rules of the wudao cleaning
#: pipeline (reference: fengshen/data/bert_dataloader/
#: preprocessing.py:27-37 cut_sent). The regex patterns ARE the cleaning
#: spec — quoted-sentence handling depends on applying them verbatim and
#: in this order: (1) break after terminal-punct runs, (2) break after
#: ellipses, (3)/(4) break after punct+closing-quote, (5) re-attach a
#: closing quote that rule 1 separated from its sentence.
_BOUNDARY_RULES = (
    ("([？。！\\?\\!…]+)([^”’]|[”’])", r"\1" + _BOUNDARY + r"\2"),
    ("([\\.]{3,})([^”’])", r"\1" + _BOUNDARY + r"\2"),
    ("([。！？\\?\\!…][”’])([^，。！？\\?\\!]|\\s)",
     r"\1" + _BOUNDARY + r"\2"),
    ("([\\.]{3,}[”’])([^，。！？\\?\\!]|\\s)", r"\1" + _BOUNDARY + r"\2"),
    ("([#]{5})([”’])([^，。！？\\?\\!])", r"\2" + _BOUNDARY + r"\3"),
)


def mark_sentence_boundaries(text: str) -> list[str]:
    """Split one document into sentences by the reference's rule
    cascade. The trailing space matches the reference (rule 1 needs a
    lookahead character to fire on a document-final sentence)."""
    marked = text + " "
    for pattern, repl in _BOUNDARY_RULES:
        marked = re.sub(pattern, repl, marked)
    return marked.strip().split(_BOUNDARY)


def repack_segments(sentences: Iterator[str],
                    max_chars: int = 512) -> list[str]:
    """Greedy re-packing of sentences into ~max_chars segments —
    reference: preprocessing.py:39-50 ("一个512里面多个样本"), including
    its two deliberate quirks: a segment may exceed max_chars by the
    final appended sentence (the bound is checked BEFORE appending), and
    an empty sentence flushes the current segment."""
    segments: list[str] = []
    current = ""
    for sentence in sentences:
        sentence = sentence.strip()
        if len(current) < max_chars and len(sentence) > 0:
            current += sentence
        else:
            segments.append(current)
            current = sentence
    segments.append(current)
    return segments


def cut_sent_file(input_path: str, output_path: str,
                  content_key: str = "text",
                  max_chars: int = 512) -> int:
    """jsonl of documents → jsonl of ≈max_chars cleaned text segments
    (the per-file body of reference preprocessing.py:11-50)."""
    n = 0
    with open(input_path, encoding="utf-8") as fin, \
            open(output_path, "w", encoding="utf-8") as fout:
        for line in fin:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            sentences = mark_sentence_boundaries(row.get(content_key, ""))
            for segment in repack_segments(iter(sentences), max_chars):
                fout.write(json.dumps({"text": segment},
                                      ensure_ascii=False) + "\n")
                n += 1
    return n


def auto_split(data_dir: str, threshold_mb: int = 1024,
               chunk_mb: int = 300, suffix: str = ".json") -> list[str]:
    """Line-safe re-sharding of oversized corpus files — the semantics
    of reference auto_split.sh: files over `threshold_mb` are split into
    ≈`chunk_mb` chunks named `<stem>-aa.json`, `<stem>-ab.json`, … and
    the original is removed. `split -C` never breaks a line; neither
    does this."""
    import itertools
    import string

    new_paths: list[str] = []
    for name in sorted(os.listdir(data_dir)):
        path = os.path.join(data_dir, name)
        if not os.path.isfile(path) or \
                os.path.getsize(path) <= threshold_mb * 1024 * 1024:
            continue
        stem = name[: -len(suffix)] if name.endswith(suffix) else name
        suffixes = ("".join(p) for p in
                    itertools.product(string.ascii_lowercase, repeat=2))
        limit = chunk_mb * 1024 * 1024
        out, written = None, 0
        try:
            with open(path, encoding="utf-8") as fin:
                for line in fin:
                    size = len(line.encode())
                    if out is None or written + size > limit:
                        if out is not None:
                            out.close()
                        chunk = os.path.join(
                            data_dir, f"{stem}-{next(suffixes)}{suffix}")
                        new_paths.append(chunk)
                        out = open(chunk, "w", encoding="utf-8")
                        written = 0
                    out.write(line)
                    written += size
        finally:
            if out is not None:
                out.close()
        os.remove(path)
    return new_paths


def split_train_test_validation_index(train_test_validation: str) -> dict:
    """'950,49,1' → the two nested split rates the reference derives
    (reference: load.py:60-66)."""
    parts = [int(i) for i in train_test_validation.split(",")]
    return {"train_rate": parts[0] / sum(parts),
            "test_rate": parts[1] / sum(parts[1:])}


def generate_cache_arrow(data_dir: str, save_path: str,
                         train_test_validation: str = "950,49,1",
                         seed: int = 42) -> list[str]:
    """Per-shard 3-way split + HF-datasets arrow cache — the
    reference's BertDataGenerate.generate_cache_arrow
    (reference: load.py:27-103), with a fixed seed so regenerated
    caches are reproducible (the reference's splits are not)."""
    import datasets as hf_datasets

    idx = split_train_test_validation_index(train_test_validation)
    os.makedirs(save_path, exist_ok=True)
    saved = []
    for name in sorted(os.listdir(data_dir)):
        path = os.path.join(data_dir, name)
        if not os.path.isfile(path):
            continue
        ds = hf_datasets.load_dataset("json", data_files=path)
        split1 = ds["train"].train_test_split(
            train_size=idx["train_rate"], seed=seed)
        split2 = split1["test"].train_test_split(
            train_size=idx["test_rate"], seed=seed)
        out = hf_datasets.DatasetDict({
            "train": split1["train"],
            "test": split2["train"],
            "validation": split2["test"]})
        target = os.path.join(save_path, name)
        out.save_to_disk(target)
        saved.append(target)
    return saved
