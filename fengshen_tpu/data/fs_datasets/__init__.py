"""Dataset registry — the `fs_datasets` equivalent.

The reference's `fengshen/data/fs_datasets/` is the hub-hosted Chinese
dataset wrapper collection (empty in the surveyed snapshot but referenced by
`universal_datamodule.py:59`, SURVEY.md §2.6). Here it is a name registry:
names map either to local loader callables registered via
`register_dataset`, or fall through to HF `datasets.load_dataset`.
"""

from __future__ import annotations

from typing import Callable

_REGISTRY: dict[str, Callable] = {}


def register_dataset(name: str, loader: Callable) -> None:
    _REGISTRY[name] = loader


def load_dataset(name: str, num_proc: int = 1, **kwargs):
    if name in _REGISTRY:
        return _REGISTRY[name](num_proc=num_proc, **kwargs)
    import datasets as hf_datasets
    return hf_datasets.load_dataset(name, **kwargs)
