"""[CLS]/[SEP] assembly with token types
(reference: fengshen/data/data_utils/token_type_utils.py
`create_tokens_and_tokentypes`)."""

from __future__ import annotations


def create_tokens_and_tokentypes(tokens_a: list[int], tokens_b: list[int],
                                 cls_id: int, sep_id: int
                                 ) -> tuple[list[int], list[int]]:
    """[CLS] A [SEP] (B [SEP]) with 0/1 segment ids."""
    tokens = [cls_id] + list(tokens_a) + [sep_id]
    tokentypes = [0] * len(tokens)
    if tokens_b:
        tokens += list(tokens_b) + [sep_id]
        tokentypes += [1] * (len(tokens_b) + 1)
    return tokens, tokentypes
