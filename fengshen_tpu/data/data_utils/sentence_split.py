"""Chinese sentence splitting
(reference: fengshen/data/data_utils/sentence_split.py:4 —
`ChineseSentenceSplitter`)."""

from __future__ import annotations

import re

# sentence-final punctuation (with closing quotes attached)
_SPLIT_PATTERN = re.compile(
    r'([。！？\?!…]+[”’"\']?)')


class ChineseSentenceSplitter:
    """Split text into sentences on Chinese terminal punctuation, keeping
    the punctuation attached to its sentence."""

    def tokenize(self, text: str) -> list[str]:
        pieces = _SPLIT_PATTERN.split(text)
        sentences: list[str] = []
        for i in range(0, len(pieces) - 1, 2):
            sent = (pieces[i] + pieces[i + 1]).strip()
            if sent:
                sentences.append(sent)
        tail = pieces[-1].strip() if len(pieces) % 2 == 1 else ""
        if tail:
            sentences.append(tail)
        return sentences

    __call__ = tokenize
