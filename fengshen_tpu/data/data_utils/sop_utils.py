"""Sentence-order-prediction pairing
(reference: fengshen/data/data_utils/sop_utils.py:3 `get_a_and_b_segments`)."""

from __future__ import annotations


def get_a_and_b_segments(sample: list[list[int]], np_rng
                         ) -> tuple[list[int], list[int], bool]:
    """Split a multi-sentence sample into two segments at a random boundary;
    with p=0.5 swap them (SOP label True = swapped/"is not next")."""
    n_sentences = len(sample)
    assert n_sentences > 1, "need at least two sentences for SOP"
    a_end = 1 if n_sentences == 2 else np_rng.randint(1, n_sentences)
    tokens_a: list[int] = []
    for s in sample[:a_end]:
        tokens_a.extend(s)
    tokens_b: list[int] = []
    for s in sample[a_end:]:
        tokens_b.extend(s)

    is_next_random = bool(np_rng.random() < 0.5)
    if is_next_random:
        tokens_a, tokens_b = tokens_b, tokens_a
    return tokens_a, tokens_b, is_next_random
