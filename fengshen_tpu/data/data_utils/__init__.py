"""Pretraining sample-construction utilities
(reference: fengshen/data/data_utils/)."""

from fengshen_tpu.data.data_utils.sentence_split import ChineseSentenceSplitter
from fengshen_tpu.data.data_utils.sop_utils import get_a_and_b_segments
from fengshen_tpu.data.data_utils.truncate_utils import truncate_segments
from fengshen_tpu.data.data_utils.token_type_utils import (
    create_tokens_and_tokentypes)
from fengshen_tpu.data.data_utils.mask_utils import (
    create_masked_lm_predictions, MaskedLmInstance)

__all__ = ["ChineseSentenceSplitter", "get_a_and_b_segments",
           "truncate_segments", "create_tokens_and_tokentypes",
           "create_masked_lm_predictions", "MaskedLmInstance"]
