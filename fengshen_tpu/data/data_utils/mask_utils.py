"""Masked-LM sample construction with whole-word masking.

Behavioural port of the reference's MLM utilities
(reference: fengshen/data/data_utils/mask_utils.py:18-285
`create_masked_lm_predictions` — whole-word masking via jieba for Chinese,
bert- and t5-style masking). 80/10/10 mask/random/keep split for bert style;
t5 style replaces each chosen span with a growing mask (handled by the T5
data module on top of the span selection here).
"""

from __future__ import annotations

import collections
from typing import Callable, Optional

MaskedLmInstance = collections.namedtuple("MaskedLmInstance",
                                          ["index", "label"])


def is_start_piece(piece: str) -> bool:
    """WordPiece continuation check (##-prefix convention)."""
    return not piece.startswith("##")


def whole_word_spans(tokens: list[str],
                     vocab_id_to_token: Optional[dict] = None,
                     zh_tokenizer: Optional[Callable] = None
                     ) -> list[list[int]]:
    """Group token indices into maskable word units.

    For Chinese, each wordpiece is a character; jieba word segmentation over
    the reconstructed text groups adjacent characters into words
    (reference: mask_utils.py whole-word masking via jieba).
    """
    if zh_tokenizer is not None:
        text = "".join(t[2:] if t.startswith("##") else t for t in tokens)
        words = list(zh_tokenizer(text))
        spans: list[list[int]] = []
        ti = 0
        for w in words:
            span: list[int] = []
            consumed = 0
            while ti < len(tokens) and consumed < len(w):
                piece = tokens[ti]
                plain = piece[2:] if piece.startswith("##") else piece
                span.append(ti)
                consumed += len(plain)
                ti += 1
            if span:
                spans.append(span)
        while ti < len(tokens):  # tail safety
            spans.append([ti])
            ti += 1
        return spans

    spans = []
    for i, tok in enumerate(tokens):
        if is_start_piece(tok) or not spans:
            spans.append([i])
        else:
            spans[-1].append(i)
    return spans


def create_masked_lm_predictions(
        tokens: list[int],
        vocab_id_list: list[int],
        vocab_id_to_token_dict: dict,
        masked_lm_prob: float,
        cls_id: int, sep_id: int, mask_id: int,
        max_predictions_per_seq: int,
        np_rng,
        masking_style: str = "bert",
        zh_tokenizer: Optional[Callable] = None,
        do_whole_word_mask: bool = True,
        ) -> tuple[list[int], list[int], list[int]]:
    """Returns (output_tokens, masked_positions, masked_labels).

    Reference contract: fengshen/data/data_utils/mask_utils.py:18-285.
    """
    special = {cls_id, sep_id}
    token_strs = [vocab_id_to_token_dict.get(t, str(t)) for t in tokens]

    # candidate word units (skip specials)
    if do_whole_word_mask:
        units = whole_word_spans(token_strs, vocab_id_to_token_dict,
                                 zh_tokenizer)
        cand_units = [u for u in units
                      if all(tokens[i] not in special for i in u)]
    else:
        cand_units = [[i] for i, t in enumerate(tokens) if t not in special]

    num_to_predict = min(
        max_predictions_per_seq,
        max(1, int(round(len(tokens) * masked_lm_prob))))

    order = np_rng.permutation(len(cand_units))
    output = list(tokens)
    masked: list[MaskedLmInstance] = []
    covered: set[int] = set()
    for ui in order:
        unit = cand_units[int(ui)]
        if len(masked) + len(unit) > num_to_predict:
            continue
        if any(i in covered for i in unit):
            continue
        covered.update(unit)
        for i in unit:
            masked.append(MaskedLmInstance(index=i, label=tokens[i]))
            if masking_style == "bert":
                r = np_rng.random()
                if r < 0.8:
                    output[i] = mask_id
                elif r < 0.9:
                    output[i] = int(vocab_id_list[
                        np_rng.randint(0, len(vocab_id_list))])
                # else keep original
            elif masking_style == "t5":
                output[i] = mask_id
            else:
                raise ValueError(f"unknown masking style {masking_style!r}")
        if len(masked) >= num_to_predict:
            break

    masked.sort(key=lambda x: x.index)
    positions = [m.index for m in masked]
    labels = [m.label for m in masked]
    return output, positions, labels
