"""Pair truncation
(reference: fengshen/data/data_utils/truncate_utils.py `truncate_segments`)."""

from __future__ import annotations


def truncate_segments(tokens_a: list, tokens_b: list, len_a: int, len_b: int,
                      max_num_tokens: int, np_rng) -> bool:
    """Trim the pair to max_num_tokens, randomly from front or back of the
    longer segment each round. Returns True if anything was truncated."""
    truncated = False
    while len_a + len_b > max_num_tokens:
        if len_a > len_b:
            tokens, length = tokens_a, len_a
            len_a -= 1
        else:
            tokens, length = tokens_b, len_b
            len_b -= 1
        if np_rng.random() < 0.5:
            del tokens[0]
        else:
            tokens.pop()
        truncated = True
    return truncated
