"""HuBERT audio data (reference: fengshen/data/hubert/hubert_dataset.py)."""

from fengshen_tpu.data.hubert.hubert_dataset import (
    HubertDataset, HubertCollator, load_audio_manifest, load_labels,
    read_waveform, conv_frames)

__all__ = ["HubertDataset", "HubertCollator", "load_audio_manifest",
           "load_labels", "read_waveform", "conv_frames"]
