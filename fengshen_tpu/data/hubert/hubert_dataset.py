"""HuBERT pretraining audio dataset.

Behavioural port of the reference's fairseq-style dataset
(reference: fengshen/data/hubert/hubert_dataset.py:39-360 — `load_audio`
manifest parsing, `load_label`/`load_label_offset` frame-label loading,
`verify_label_lengths`, random crop to max_sample_size and right-pad
collation). TPU-native differences: numpy throughout, stdlib `wave` (PCM)
or `.npy` waveform loading instead of soundfile, and the collator emits
frame-aligned cluster targets for the VALID-conv frame count of
fengshen_tpu.models.hubert.
"""

from __future__ import annotations

import os
import wave
from typing import Any, Optional, Sequence

import numpy as np


def load_audio_manifest(manifest_path: str, max_keep: Optional[int] = None,
                        min_keep: Optional[int] = None
                        ) -> tuple[str, list[str], list[int], list[int]]:
    """Parse a fairseq tsv manifest: first line is the root dir, then
    `relative_path\tnum_samples` rows (reference: hubert_dataset.py:39-66).
    Returns (root, paths, n_samples, kept_indices)."""
    paths, sizes, inds = [], [], []
    with open(manifest_path) as f:
        root = f.readline().strip()
        for i, line in enumerate(f):
            parts = line.strip().split("\t")
            if len(parts) < 2:
                continue
            sz = int(parts[1])
            if max_keep is not None and sz > max_keep:
                continue
            if min_keep is not None and sz < min_keep:
                continue
            paths.append(parts[0])
            sizes.append(sz)
            inds.append(i)
    return root, paths, sizes, inds


def load_labels(label_path: str, inds: Sequence[int]) -> list[list[int]]:
    """One space-separated label line per original manifest row; keep the
    rows surviving the length filter (reference: hubert_dataset.py:67-87)."""
    with open(label_path) as f:
        lines = f.readlines()
    keep = set(inds)
    out = []
    for i, line in enumerate(lines):
        if i in keep:
            out.append([int(x) for x in line.split()])
    return out


def read_waveform(path: str) -> np.ndarray:
    """Load mono audio as float32 in [-1, 1]: `.npy` arrays or PCM `.wav`
    via the stdlib (substitutes the reference's soundfile read,
    hubert_dataset.py:188-196)."""
    if path.endswith(".npy"):
        wav = np.load(path).astype(np.float32)
        return wav.reshape(-1)
    with wave.open(path, "rb") as w:
        n = w.getnframes()
        width = w.getsampwidth()
        raw = w.readframes(n)
        if width == 1:
            # 8-bit PCM WAV is UNSIGNED (0-255, 128 = silence)
            wav = (np.frombuffer(raw, np.uint8).astype(np.float32)
                   - 128.0) / 127.0
        else:
            dtype = {2: np.int16, 4: np.int32}[width]
            wav = np.frombuffer(raw, dtype=dtype).astype(np.float32)
            wav /= float(np.iinfo(dtype).max)
        if w.getnchannels() > 1:
            wav = wav.reshape(-1, w.getnchannels()).mean(-1)
        return wav


def conv_frames(n_samples: int,
                conv_layers: Sequence[Sequence[int]]) -> int:
    """Frame count after the VALID-padded conv encoder."""
    n = n_samples
    for _, kernel, stride in conv_layers:
        n = (n - kernel) // stride + 1
    return max(n, 0)


class HubertDataset:
    """manifest + k-means labels → {waveform, cluster_ids} samples
    (reference: hubert_dataset.py:127-360)."""

    def __init__(self, manifest_path: str, label_path: str,
                 sample_rate: int = 16000,
                 label_rate: float = 50.0,
                 max_keep_sample_size: Optional[int] = None,
                 min_keep_sample_size: Optional[int] = None,
                 max_sample_size: Optional[int] = None,
                 random_crop: bool = True,
                 seed: int = 0):
        self.root, self.paths, self.sizes, inds = load_audio_manifest(
            manifest_path, max_keep_sample_size, min_keep_sample_size)
        self.labels = load_labels(label_path, inds)
        assert len(self.labels) == len(self.paths), \
            f"{len(self.labels)} label rows != {len(self.paths)} audios"
        self.sample_rate = sample_rate
        self.label_rate = label_rate
        self.max_sample_size = max_sample_size
        self.random_crop = random_crop
        self.rng = np.random.RandomState(seed)
        # soft verify (reference: verify_label_lengths tolerance warning)
        for i, (sz, lab) in enumerate(zip(self.sizes, self.labels)):
            expect = sz / sample_rate * label_rate
            if abs(len(lab) - expect) > max(2.0, 0.1 * expect):
                import warnings
                warnings.warn(
                    f"label length {len(lab)} far from expected "
                    f"{expect:.1f} for row {i}")

    def __len__(self) -> int:
        return len(self.paths)

    def __getitem__(self, i: int) -> dict:
        wav = read_waveform(os.path.join(self.root, self.paths[i]))
        labels = np.asarray(self.labels[i], np.int32)
        if self.max_sample_size and len(wav) > self.max_sample_size:
            # random crop, labels cropped at label_rate (reference:
            # hubert_dataset.py crop_to_max_size)
            diff = len(wav) - self.max_sample_size
            start = self.rng.randint(0, diff + 1) if self.random_crop else 0
            wav = wav[start: start + self.max_sample_size]
            l0 = int(start / self.sample_rate * self.label_rate)
            l1 = int((start + self.max_sample_size) /
                     self.sample_rate * self.label_rate)
            labels = labels[l0: max(l1, l0 + 1)]
        return {"waveform": wav, "cluster_ids": labels}


class HubertCollator:
    """Right-pad waveforms, resample cluster labels to the conv-encoder
    frame grid, and draw the span time-mask (reference:
    hubert_dataset.py `collater` + fairseq mask sampling)."""

    def __init__(self, conv_layers: Sequence[Sequence[int]],
                 mask_prob: float = 0.65, mask_length: int = 10,
                 seed: int = 0, pad_to: Optional[int] = None):
        self.conv_layers = conv_layers
        self.mask_prob = mask_prob
        self.mask_length = mask_length
        self.rng = np.random.RandomState(seed)
        # fixed padding length: per-batch max would hand the jitted train
        # step a new shape (and an XLA recompile) nearly every batch
        self.pad_to = pad_to

    def __call__(self, samples: list[dict]) -> dict:
        from fengshen_tpu.models.hubert.modeling_hubert import (
            compute_mask_indices)
        max_t = self.pad_to or max(len(s["waveform"]) for s in samples)
        batch = len(samples)
        frames = conv_frames(max_t, self.conv_layers)
        waveform = np.zeros((batch, max_t), np.float32)
        targets = np.zeros((batch, frames), np.int32)
        valid = np.zeros((batch, frames), bool)
        for b, s in enumerate(samples):
            wav, lab = s["waveform"], np.asarray(s["cluster_ids"])
            waveform[b, : len(wav)] = wav
            # labels are resampled onto THIS sample's own frame count, not
            # the batch-max grid — shorter clips must not get dilated
            # labels or fabricated labels over the pad region
            n_f = min(conv_frames(len(wav), self.conv_layers), frames)
            if len(lab) and n_f > 0:
                idx = np.minimum(
                    (np.arange(n_f) * len(lab) / n_f).astype(np.int64),
                    len(lab) - 1)
                targets[b, :n_f] = lab[idx]
                valid[b, :n_f] = True
        mask = compute_mask_indices((batch, frames), self.mask_prob,
                                    self.mask_length, self.rng)
        # the loss only counts masked frames; restricting the mask to valid
        # frames keeps pad frames out of training. frame_mask also gates
        # the optional unmasked (pred_nomask) loss term.
        mask &= valid
        return {"waveform": waveform, "cluster_ids": targets,
                "mask_time_indices": mask, "frame_mask": valid}
