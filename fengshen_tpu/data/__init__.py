"""Data layer (reference: fengshen/data/, SURVEY.md §2.6)."""

from fengshen_tpu.data.universal_sampler import (PretrainingSampler,
                                                 PretrainingRandomSampler)
from fengshen_tpu.data.universal_datamodule import (UniversalDataModule,
                                                    DataLoader)

__all__ = ["PretrainingSampler", "PretrainingRandomSampler",
           "UniversalDataModule", "DataLoader"]
