"""Tokenizer training utilities (reference: fengshen/tokenizer/)."""

from fengshen_tpu.tokenizer.sentencepiece_train import (train_sentencepiece,
                                                        shuffle_corpus)

__all__ = ["train_sentencepiece", "shuffle_corpus"]
