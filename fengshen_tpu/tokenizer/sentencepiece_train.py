"""SentencePiece training pipeline.

Port of reference: fengshen/tokenizer/sentencepiece/pretrain_google_sp.sh
(spm_train with vocab 40k, character coverage .9995) + shuffle_corpus.py.
The sentencepiece package is optional in this environment — gated at call
time with the same defaults as the reference's shell script.
"""

from __future__ import annotations

import random
from typing import Optional


def shuffle_corpus(input_path: str, output_path: str,
                   seed: int = 42) -> None:
    """Reference: fengshen/tokenizer/sentencepiece/shuffle_corpus.py."""
    with open(input_path) as f:
        lines = f.readlines()
    random.Random(seed).shuffle(lines)
    with open(output_path, "w") as f:
        f.writelines(lines)


def train_sentencepiece(input_path: str, model_prefix: str,
                        vocab_size: int = 40000,
                        character_coverage: float = 0.9995,
                        model_type: str = "unigram",
                        user_defined_symbols: Optional[list[str]] = None,
                        ) -> str:
    """spm_train with the reference's defaults
    (reference: pretrain_google_sp.sh)."""
    try:
        import sentencepiece as spm
    except ImportError as e:
        raise ImportError(
            "sentencepiece is not installed in this environment; install it "
            "or run the reference's spm_train CLI with the same flags"
        ) from e
    spm.SentencePieceTrainer.train(
        input=input_path, model_prefix=model_prefix,
        vocab_size=vocab_size, character_coverage=character_coverage,
        model_type=model_type,
        user_defined_symbols=user_defined_symbols or [])
    return f"{model_prefix}.model"
