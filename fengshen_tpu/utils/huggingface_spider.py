"""List IDEA-CCNL models on the HF hub
(reference: fengshen/utils/huggingface_spider.py, 12 LoC)."""

from __future__ import annotations


def list_fengshenbang_models(author: str = "IDEA-CCNL") -> list[str]:
    from huggingface_hub import HfApi
    return [m.modelId for m in HfApi().list_models(author=author)]
