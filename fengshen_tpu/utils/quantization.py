"""Weight-only int8 quantization for serving.

The TPU-native analog of the reference's 8-bit Ziya serving path
(reference: fengshen/examples/ziya_inference/ — bitsandbytes
`load_in_8bit` and llama.cpp quantized inference). Weights are stored as
int8 with per-output-channel absmax scales (halving checkpoint size and
weights-at-rest HBM); the dequantize runs inside the jitted forward, where
XLA fuses the int8→bf16 multiply into the consuming matmul's input
pipeline.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

_Q_KEY = "_int8"
_S_KEY = "_scale"


def _is_quantizable(path: str, leaf, min_size: int) -> bool:
    return (hasattr(leaf, "ndim") and leaf.ndim >= 2 and
            leaf.size >= min_size and
            jnp.issubdtype(leaf.dtype, jnp.floating))


def quantize_params_int8(params: Any, min_size: int = 4096) -> Any:
    """Pytree → pytree with large 2D+ float leaves replaced by
    {_int8, _scale} dicts (per-output-channel absmax, symmetric)."""

    def quant(leaf):
        if not _is_quantizable("", leaf, min_size):
            return leaf
        # flax kernels are [..., in, out]: scale per output channel
        absmax = jnp.max(jnp.abs(leaf), axis=tuple(range(leaf.ndim - 1)),
                         keepdims=True)
        scale = jnp.maximum(absmax, 1e-8) / 127.0
        q = jnp.clip(jnp.round(leaf / scale), -127, 127).astype(jnp.int8)
        return {_Q_KEY: q, _S_KEY: scale.astype(jnp.float32)}

    return jax.tree_util.tree_map(quant, params)


def _is_qdict(x) -> bool:
    return isinstance(x, dict) and _Q_KEY in x and _S_KEY in x


def dequantize_params(qparams: Any, dtype=jnp.bfloat16) -> Any:
    """Inverse of quantize_params_int8; call INSIDE jit so XLA fuses the
    dequant into each weight's consumer."""

    def dequant(x):
        if _is_qdict(x):
            return (x[_Q_KEY].astype(dtype) *
                    x[_S_KEY].astype(dtype))
        return x

    return jax.tree_util.tree_map(dequant, qparams, is_leaf=_is_qdict)


def quantized_nbytes(qparams: Any) -> int:
    return sum(leaf.nbytes for leaf in jax.tree_util.tree_leaves(qparams))


def quantization_error(params: Any, qparams: Any) -> float:
    """Max relative per-tensor reconstruction error (sanity metric)."""
    deq = dequantize_params(qparams, jnp.float32)
    errs = []
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(deq)):
        denom = float(jnp.max(jnp.abs(a))) or 1.0
        errs.append(float(jnp.max(jnp.abs(a - b))) / denom)
    return max(errs)
