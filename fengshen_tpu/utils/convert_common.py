"""Shared torch→flax weight-mapping helpers used by the per-family
convert.py modules (pattern: fengshen_tpu/models/llama/convert.py;
replaces the reference's per-family conversion scripts under
fengshen/utils/)."""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np


def tensor(state_dict: Mapping[str, Any], name: str) -> np.ndarray:
    x = state_dict[name]
    if hasattr(x, "detach"):
        x = x.detach().cpu().float().numpy()
    # ALWAYS copy: for fp32 params `.float()` is a no-op and `.numpy()`
    # shares the torch storage — and `jnp.asarray` on the CPU backend can
    # be zero-copy, so without this a later in-place torch update (e.g.
    # optimizer.step() in a parity test) would silently mutate the
    # already-converted jax params through the aliased buffer.
    return np.array(x, copy=True)


def make_helpers(state_dict: Mapping[str, Any]):
    """(t, lin, ln) closures over one state dict: raw tensor, transposed
    Linear, LayerNorm scale/bias."""

    def t(name):
        return tensor(state_dict, name)

    def lin(prefix):
        return {"kernel": t(f"{prefix}.weight").T,
                "bias": t(f"{prefix}.bias")}

    def ln(prefix):
        return {"scale": t(f"{prefix}.weight"), "bias": t(f"{prefix}.bias")}

    return t, lin, ln


def bert_layer(state_dict: Mapping[str, Any], prefix: str) -> dict:
    """HF BERT encoder layer → the shared flax BertLayer naming
    (query/key/value/attention_output_dense/attention_ln/
    intermediate_dense/output_dense/output_ln)."""
    _, lin, ln = make_helpers(state_dict)
    return {
        "query": lin(f"{prefix}.attention.self.query"),
        "key": lin(f"{prefix}.attention.self.key"),
        "value": lin(f"{prefix}.attention.self.value"),
        "attention_output_dense": lin(f"{prefix}.attention.output.dense"),
        "attention_ln": ln(f"{prefix}.attention.output.LayerNorm"),
        "intermediate_dense": lin(f"{prefix}.intermediate.dense"),
        "output_dense": lin(f"{prefix}.output.dense"),
        "output_ln": ln(f"{prefix}.output.LayerNorm"),
    }


def seq2seq_attention(state_dict: Mapping[str, Any], prefix: str) -> dict:
    """HF BART-family attention block (q/k/v/out_proj)."""
    _, lin, _ = make_helpers(state_dict)
    return {"q_proj": lin(f"{prefix}.q_proj"),
            "k_proj": lin(f"{prefix}.k_proj"),
            "v_proj": lin(f"{prefix}.v_proj"),
            "out_proj": lin(f"{prefix}.out_proj")}


def strip_prefix(state_dict: Mapping[str, Any], prefix: str) -> dict:
    """Sub-dict of keys under `prefix` with the prefix removed."""
    return {k[len(prefix):]: v for k, v in state_dict.items()
            if k.startswith(prefix)}


def unwrap_lightning(state_dict: Mapping[str, Any]) -> Mapping[str, Any]:
    """Strip the `model.` prefix Lightning's save_checkpoint adds (the
    reference trains every task head inside a LightningModule whose model
    attr is `self.model`, e.g. fengshen/models/unimc/modeling_unimc.py:351);
    also unwraps a nested `state_dict` key from a raw torch.save(ckpt)."""
    if "state_dict" in state_dict and not hasattr(
            state_dict["state_dict"], "detach"):
        state_dict = state_dict["state_dict"]
    if any(k.startswith("model.") for k in state_dict):
        return strip_prefix(state_dict, "model.")
    return state_dict


def detect_bert_arch(state_dict: Mapping[str, Any]) -> str:
    """'bert' (post-LN HF naming: attention.output.LayerNorm) vs
    'megatron_bert' (pre-LN HF naming: attention.ln / encoder.ln)."""
    for k in state_dict:
        if ".attention.output.LayerNorm." in k:
            return "bert"
        if ".attention.ln." in k or k.endswith("encoder.ln.weight"):
            return "megatron_bert"
    raise ValueError("cannot detect bert architecture from state dict keys")


def encoder_tower_params(state_dict: Mapping[str, Any], config,
                         backbone_type: str) -> dict:
    """Map a `bert.`-prefixed tower state dict → flax tower params (the
    sub-tree that lives under the head's name="bert" module)."""
    if backbone_type == "bert":
        from fengshen_tpu.models.bert.convert import torch_to_params
        return torch_to_params(state_dict, config)["bert"]
    from fengshen_tpu.models.megatron_bert.convert import torch_to_params
    return torch_to_params(state_dict, config, head="none")["bert"]


def lstm_cell_params(state_dict: Mapping[str, Any], prefix: str,
                     layer: int, reverse: bool) -> dict:
    """torch nn.LSTM layer → flax OptimizedLSTMCell tree. torch packs the
    four gates as rows of weight_ih/weight_hh in (i, f, g, o) order with
    two bias vectors; flax keeps per-gate Denses (input side bias-free,
    hidden side carrying the sum of both torch biases)."""
    sfx = f"l{layer}" + ("_reverse" if reverse else "")
    w_ih = tensor(state_dict, f"{prefix}.weight_ih_{sfx}")
    w_hh = tensor(state_dict, f"{prefix}.weight_hh_{sfx}")
    b = (tensor(state_dict, f"{prefix}.bias_ih_{sfx}") +
         tensor(state_dict, f"{prefix}.bias_hh_{sfx}"))
    h = w_hh.shape[1]
    gates = ("i", "f", "g", "o")
    cell = {}
    for gi, g in enumerate(gates):
        cell[f"i{g}"] = {"kernel": w_ih[gi * h:(gi + 1) * h].T}
        cell[f"h{g}"] = {"kernel": w_hh[gi * h:(gi + 1) * h].T,
                         "bias": b[gi * h:(gi + 1) * h]}
    return cell


def invert_import(torch_to_params_fn, template: Mapping[str, Any],
                  config, params: dict, **fn_kwargs) -> dict:
    """Generic fs→HF export: the exact inverse of a permutation-style
    importer, learned numerically (reference merge-back path:
    fengshen/utils/llama_convert/merge_lt_mp_to_hf.py:1-164 — there a
    hand-written inverse per family; here ONE inverse derived from the
    import itself, so the two directions can never drift apart).

    How: run `torch_to_params_fn` on a state dict whose every scalar is
    replaced by a unique tag id. Transposes/reshapes/stacks/slices move
    the tags exactly like they move real weights, so each flax leaf
    position names its source torch position; flax values then scatter
    straight back into torch-shaped buffers.

    `template` supplies the torch keys/shapes/dtypes — the original HF
    checkpoint you imported from, or a freshly instantiated HF model's
    state_dict (values are only kept for positions the import never
    read, e.g. RoBERTa's two reserved position rows).

    Leaves the importer synthesized rather than read (zeros-init heads)
    are detected — their values are not integral tag ids — and skipped.
    Raises if a read leaf's values are not pure tags (an importer doing
    arithmetic needs a hand-written inverse instead).
    """
    import jax

    def _is_tensor(v):
        return hasattr(v, "detach") or isinstance(v, np.ndarray) or (
            hasattr(v, "shape") and hasattr(v, "dtype"))

    # Lightning-format checkpoints carry non-tensor metadata (epoch,
    # optimizer_states, a nested state_dict…) — only weight entries
    # participate in the inversion
    keys = [k for k in template.keys() if _is_tensor(template[k])]
    np_template = {k: tensor(template, k) for k in keys}

    def _orig_dtype(v):
        # tensor() upcasts torch fp16/bf16 to float32; exports must come
        # back in the checkpoint's own dtype
        name = str(getattr(v, "dtype", np.float32)).replace("torch.", "")
        if name == "bfloat16":
            import ml_dtypes
            return np.dtype(ml_dtypes.bfloat16)
        try:
            return np.dtype(name)
        except TypeError:
            return np.float32
    dtypes = {k: _orig_dtype(template[k]) for k in keys}
    sizes = {k: int(np_template[k].size) for k in keys}
    offsets, off = {}, 0
    for k in keys:
        offsets[k] = off
        off += sizes[k]
    total = off
    # tags are arange + 0.25: exactly representable in float64, and no
    # synthesized constant array (zeros/ones init) can collide with one
    tagged = {k: (np.arange(offsets[k], offsets[k] + sizes[k],
                            dtype=np.float64) + 0.25
                  ).reshape(np_template[k].shape) for k in keys}
    if config is None:
        # config-free importers (ppvae, gavae towers) take one argument
        tag_tree = torch_to_params_fn(tagged, **fn_kwargs)
    else:
        tag_tree = torch_to_params_fn(tagged, config, **fn_kwargs)

    tag_leaves = dict(jax.tree_util.tree_flatten_with_path(tag_tree)[0])
    val_leaves = dict(jax.tree_util.tree_flatten_with_path(params)[0])
    flat = np.concatenate([np_template[k].astype(np.float64).ravel()
                           for k in keys]) if total else np.zeros(0)
    filled = np.zeros(total, dtype=bool)
    for path, tags in tag_leaves.items():
        if path not in val_leaves:
            raise KeyError(
                f"params tree lacks leaf {jax.tree_util.keystr(path)} "
                f"produced by the importer — wrong params/config pair?")
        tags = np.asarray(tags, dtype=np.float64)
        vals = np.asarray(val_leaves[path], dtype=np.float64)
        if tags.shape != vals.shape:
            raise ValueError(
                f"shape mismatch at {jax.tree_util.keystr(path)}: "
                f"importer yields {tags.shape}, params have {vals.shape}")
        ids = tags.ravel() - 0.25
        is_tag = (ids == np.round(ids)) & (ids >= 0) & (ids < total)
        if not is_tag.any():
            # No direct tags. A genuinely synthesized leaf is a CONSTANT
            # init (zeros/ones/any fill value) — constant arrays carry
            # no template information, so skipping them is safe. Any
            # NON-constant tag-free leaf must be derived from template
            # tensors by arithmetic (sums, differences, scales — which
            # all destroy the +0.25 tag fingerprint while keeping the
            # values distinct): exporting would silently emit stale
            # template values, so refuse loudly instead.
            tvals = tags.ravel()
            if tvals.size and not np.all(tvals == tvals.flat[0]):
                raise ValueError(
                    f"leaf {jax.tree_util.keystr(path)} is tag-free but "
                    "non-constant — it looks DERIVED from template "
                    "tensors by arithmetic; this importer needs a "
                    "hand-written inverse (refusing to export stale "
                    "template values)")
            continue  # synthesized constant (fresh head init)
        if not is_tag.all() and not (
                # mixed leaves happen when the import pads (e.g. rows of
                # zeros appended); only the tagged positions round-trip
                np.isin(np.unique(tags.ravel()[~is_tag]),
                        (0.0, 1.0)).all()):
            raise ValueError(
                f"leaf {jax.tree_util.keystr(path)} mixes tags with "
                f"computed values — this importer does arithmetic and "
                f"needs a hand-written inverse")
        idx = ids[is_tag].astype(np.int64)
        flat[idx] = vals.ravel()[is_tag]
        filled[idx] = True
    # Tied duplicates: a key the importer never reads but whose template
    # values exactly mirror a read key's (e.g. lm_head.weight tied to the
    # embedding) must follow the finetuned values, or a torch
    # load_state_dict on a tied model would copy the STALE tensor into
    # the shared storage last and silently revert the finetune.
    untouched = [k for k in keys
                 if sizes[k] and not filled[offsets[k]:offsets[k]
                                            + sizes[k]].any()]
    exported = [k for k in keys
                if sizes[k] and filled[offsets[k]:offsets[k]
                                       + sizes[k]].all()]
    for k in untouched:
        for j in exported:
            if (np_template[k].shape == np_template[j].shape
                    and np.array_equal(np_template[k], np_template[j])):
                flat[offsets[k]:offsets[k] + sizes[k]] = \
                    flat[offsets[j]:offsets[j] + sizes[j]]
                break
    out = {}
    for k in keys:
        arr = flat[offsets[k]:offsets[k] + sizes[k]].reshape(
            np_template[k].shape)
        out[k] = arr.astype(dtypes[k])
    return out


def make_derived_export(torch_to_params_fn):
    """Build a family's ``params_to_torch_state`` as the derived exact
    inverse of its importer (see `invert_import`). The returned function
    takes ``(params, config, template_state, **import_kwargs)`` where
    ``template_state`` is the source checkpoint (a state dict, a raw
    Lightning checkpoint dict, or a checkpoint dir path) supplying key
    names/shapes/dtypes and values for positions the import never read."""

    def params_to_torch_state(params, config, template_state,
                              **import_kwargs):
        if isinstance(template_state, str):
            template_state = load_torch_checkpoint(template_state)
        if "state_dict" in template_state and not hasattr(
                template_state["state_dict"], "detach"):
            # raw Lightning checkpoint: invert against the inner weights
            # (keys keep their own naming, incl. any `model.` prefix)
            template_state = template_state["state_dict"]
        return invert_import(torch_to_params_fn, template_state, config,
                             params, **import_kwargs)

    params_to_torch_state.__doc__ = make_derived_export.__doc__
    return params_to_torch_state


def load_weight_files(ckpt_dir: str, stem: str) -> dict:
    """Merge a checkpoint's weight files for one canonical `stem`
    (e.g. ``pytorch_model`` or ``diffusion_pytorch_model``): the exact
    ``{stem}.safetensors`` if present, else sharded
    ``{stem}*.safetensors``, else ``{stem}*.bin``. Variant files a full
    HF snapshot may carry (``.fp16``, ``.non_ema``) are only read when
    no canonical file exists."""
    import glob
    import os

    exact = os.path.join(ckpt_dir, f"{stem}.safetensors")
    st_files = [exact] if os.path.exists(exact) else sorted(
        f for f in glob.glob(os.path.join(ckpt_dir,
                                          f"{stem}*.safetensors"))
        if ".fp16." not in f and ".non_ema." not in f) or sorted(
        glob.glob(os.path.join(ckpt_dir, f"{stem}*.safetensors")))
    if st_files:
        from safetensors import safe_open
        state: dict = {}
        for f in st_files:
            with safe_open(f, framework="pt") as sf:
                for key in sf.keys():
                    state[key] = sf.get_tensor(key)
        return state
    import torch
    state = {}
    for f in sorted(glob.glob(os.path.join(ckpt_dir, f"{stem}*.bin"))):
        state.update(torch.load(f, map_location="cpu",
                                weights_only=True))
    if not state:
        raise FileNotFoundError(
            f"no {stem}*.safetensors / {stem}*.bin under {ckpt_dir}")
    return state


def load_torch_checkpoint(ckpt_dir: str) -> Mapping[str, Any]:
    """State dict from a reference-format checkpoint dir, trying the
    file names the reference publishes under (HF pytorch_model.bin or
    sharded *.safetensors, Lightning model.ckpt / last.ckpt)."""
    import glob
    import os

    import torch

    for name in ("pytorch_model.bin", "model.ckpt", "last.ckpt"):
        path = os.path.join(ckpt_dir, name)
        if os.path.exists(path):
            return torch.load(path, map_location="cpu",
                              weights_only=False)
    st_files = sorted(glob.glob(os.path.join(ckpt_dir, "*.safetensors")))
    if st_files:
        from safetensors import safe_open
        state: dict = {}
        for f in st_files:
            with safe_open(f, framework="pt") as sf:
                for key in sf.keys():
                    state[key] = sf.get_tensor(key)
        return state
    raise FileNotFoundError(
        f"no pytorch_model.bin / *.safetensors / model.ckpt / last.ckpt "
        f"under {ckpt_dir}")
