"""Shared torch→flax weight-mapping helpers used by the per-family
convert.py modules (pattern: fengshen_tpu/models/llama/convert.py;
replaces the reference's per-family conversion scripts under
fengshen/utils/)."""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np


def tensor(state_dict: Mapping[str, Any], name: str) -> np.ndarray:
    x = state_dict[name]
    if hasattr(x, "detach"):
        x = x.detach().cpu().float().numpy()
    # ALWAYS copy: for fp32 params `.float()` is a no-op and `.numpy()`
    # shares the torch storage — and `jnp.asarray` on the CPU backend can
    # be zero-copy, so without this a later in-place torch update (e.g.
    # optimizer.step() in a parity test) would silently mutate the
    # already-converted jax params through the aliased buffer.
    return np.array(x, copy=True)


def make_helpers(state_dict: Mapping[str, Any]):
    """(t, lin, ln) closures over one state dict: raw tensor, transposed
    Linear, LayerNorm scale/bias."""

    def t(name):
        return tensor(state_dict, name)

    def lin(prefix):
        return {"kernel": t(f"{prefix}.weight").T,
                "bias": t(f"{prefix}.bias")}

    def ln(prefix):
        return {"scale": t(f"{prefix}.weight"), "bias": t(f"{prefix}.bias")}

    return t, lin, ln


def bert_layer(state_dict: Mapping[str, Any], prefix: str) -> dict:
    """HF BERT encoder layer → the shared flax BertLayer naming
    (query/key/value/attention_output_dense/attention_ln/
    intermediate_dense/output_dense/output_ln)."""
    _, lin, ln = make_helpers(state_dict)
    return {
        "query": lin(f"{prefix}.attention.self.query"),
        "key": lin(f"{prefix}.attention.self.key"),
        "value": lin(f"{prefix}.attention.self.value"),
        "attention_output_dense": lin(f"{prefix}.attention.output.dense"),
        "attention_ln": ln(f"{prefix}.attention.output.LayerNorm"),
        "intermediate_dense": lin(f"{prefix}.intermediate.dense"),
        "output_dense": lin(f"{prefix}.output.dense"),
        "output_ln": ln(f"{prefix}.output.LayerNorm"),
    }


def seq2seq_attention(state_dict: Mapping[str, Any], prefix: str) -> dict:
    """HF BART-family attention block (q/k/v/out_proj)."""
    _, lin, _ = make_helpers(state_dict)
    return {"q_proj": lin(f"{prefix}.q_proj"),
            "k_proj": lin(f"{prefix}.k_proj"),
            "v_proj": lin(f"{prefix}.v_proj"),
            "out_proj": lin(f"{prefix}.out_proj")}


def strip_prefix(state_dict: Mapping[str, Any], prefix: str) -> dict:
    """Sub-dict of keys under `prefix` with the prefix removed."""
    return {k[len(prefix):]: v for k, v in state_dict.items()
            if k.startswith(prefix)}


def unwrap_lightning(state_dict: Mapping[str, Any]) -> Mapping[str, Any]:
    """Strip the `model.` prefix Lightning's save_checkpoint adds (the
    reference trains every task head inside a LightningModule whose model
    attr is `self.model`, e.g. fengshen/models/unimc/modeling_unimc.py:351);
    also unwraps a nested `state_dict` key from a raw torch.save(ckpt)."""
    if "state_dict" in state_dict and not hasattr(
            state_dict["state_dict"], "detach"):
        state_dict = state_dict["state_dict"]
    if any(k.startswith("model.") for k in state_dict):
        return strip_prefix(state_dict, "model.")
    return state_dict


def detect_bert_arch(state_dict: Mapping[str, Any]) -> str:
    """'bert' (post-LN HF naming: attention.output.LayerNorm) vs
    'megatron_bert' (pre-LN HF naming: attention.ln / encoder.ln)."""
    for k in state_dict:
        if ".attention.output.LayerNorm." in k:
            return "bert"
        if ".attention.ln." in k or k.endswith("encoder.ln.weight"):
            return "megatron_bert"
    raise ValueError("cannot detect bert architecture from state dict keys")


def encoder_tower_params(state_dict: Mapping[str, Any], config,
                         backbone_type: str) -> dict:
    """Map a `bert.`-prefixed tower state dict → flax tower params (the
    sub-tree that lives under the head's name="bert" module)."""
    if backbone_type == "bert":
        from fengshen_tpu.models.bert.convert import torch_to_params
        return torch_to_params(state_dict, config)["bert"]
    from fengshen_tpu.models.megatron_bert.convert import torch_to_params
    return torch_to_params(state_dict, config, head="none")["bert"]


def lstm_cell_params(state_dict: Mapping[str, Any], prefix: str,
                     layer: int, reverse: bool) -> dict:
    """torch nn.LSTM layer → flax OptimizedLSTMCell tree. torch packs the
    four gates as rows of weight_ih/weight_hh in (i, f, g, o) order with
    two bias vectors; flax keeps per-gate Denses (input side bias-free,
    hidden side carrying the sum of both torch biases)."""
    sfx = f"l{layer}" + ("_reverse" if reverse else "")
    w_ih = tensor(state_dict, f"{prefix}.weight_ih_{sfx}")
    w_hh = tensor(state_dict, f"{prefix}.weight_hh_{sfx}")
    b = (tensor(state_dict, f"{prefix}.bias_ih_{sfx}") +
         tensor(state_dict, f"{prefix}.bias_hh_{sfx}"))
    h = w_hh.shape[1]
    gates = ("i", "f", "g", "o")
    cell = {}
    for gi, g in enumerate(gates):
        cell[f"i{g}"] = {"kernel": w_ih[gi * h:(gi + 1) * h].T}
        cell[f"h{g}"] = {"kernel": w_hh[gi * h:(gi + 1) * h].T,
                         "bias": b[gi * h:(gi + 1) * h]}
    return cell


def load_torch_checkpoint(ckpt_dir: str) -> Mapping[str, Any]:
    """State dict from a reference-format checkpoint dir, trying the
    file names the reference publishes under (HF pytorch_model.bin or
    sharded *.safetensors, Lightning model.ckpt / last.ckpt)."""
    import glob
    import os

    import torch

    for name in ("pytorch_model.bin", "model.ckpt", "last.ckpt"):
        path = os.path.join(ckpt_dir, name)
        if os.path.exists(path):
            return torch.load(path, map_location="cpu",
                              weights_only=False)
    st_files = sorted(glob.glob(os.path.join(ckpt_dir, "*.safetensors")))
    if st_files:
        from safetensors import safe_open
        state: dict = {}
        for f in st_files:
            with safe_open(f, framework="pt") as sf:
                for key in sf.keys():
                    state[key] = sf.get_tensor(key)
        return state
    raise FileNotFoundError(
        f"no pytorch_model.bin / *.safetensors / model.ckpt / last.ckpt "
        f"under {ckpt_dir}")
