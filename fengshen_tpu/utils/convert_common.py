"""Shared torch→flax weight-mapping helpers used by the per-family
convert.py modules (pattern: fengshen_tpu/models/llama/convert.py;
replaces the reference's per-family conversion scripts under
fengshen/utils/)."""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np


def tensor(state_dict: Mapping[str, Any], name: str) -> np.ndarray:
    x = state_dict[name]
    if hasattr(x, "detach"):
        x = x.detach().cpu().float().numpy()
    # ALWAYS copy: for fp32 params `.float()` is a no-op and `.numpy()`
    # shares the torch storage — and `jnp.asarray` on the CPU backend can
    # be zero-copy, so without this a later in-place torch update (e.g.
    # optimizer.step() in a parity test) would silently mutate the
    # already-converted jax params through the aliased buffer.
    return np.array(x, copy=True)


def make_helpers(state_dict: Mapping[str, Any]):
    """(t, lin, ln) closures over one state dict: raw tensor, transposed
    Linear, LayerNorm scale/bias."""

    def t(name):
        return tensor(state_dict, name)

    def lin(prefix):
        return {"kernel": t(f"{prefix}.weight").T,
                "bias": t(f"{prefix}.bias")}

    def ln(prefix):
        return {"scale": t(f"{prefix}.weight"), "bias": t(f"{prefix}.bias")}

    return t, lin, ln


def bert_layer(state_dict: Mapping[str, Any], prefix: str) -> dict:
    """HF BERT encoder layer → the shared flax BertLayer naming
    (query/key/value/attention_output_dense/attention_ln/
    intermediate_dense/output_dense/output_ln)."""
    _, lin, ln = make_helpers(state_dict)
    return {
        "query": lin(f"{prefix}.attention.self.query"),
        "key": lin(f"{prefix}.attention.self.key"),
        "value": lin(f"{prefix}.attention.self.value"),
        "attention_output_dense": lin(f"{prefix}.attention.output.dense"),
        "attention_ln": ln(f"{prefix}.attention.output.LayerNorm"),
        "intermediate_dense": lin(f"{prefix}.intermediate.dense"),
        "output_dense": lin(f"{prefix}.output.dense"),
        "output_ln": ln(f"{prefix}.output.LayerNorm"),
    }


def seq2seq_attention(state_dict: Mapping[str, Any], prefix: str) -> dict:
    """HF BART-family attention block (q/k/v/out_proj)."""
    _, lin, _ = make_helpers(state_dict)
    return {"q_proj": lin(f"{prefix}.q_proj"),
            "k_proj": lin(f"{prefix}.k_proj"),
            "v_proj": lin(f"{prefix}.v_proj"),
            "out_proj": lin(f"{prefix}.out_proj")}
