"""Generation / sampling utilities.

Covers two reference surfaces:
- `top_k_logits` / `sample_sequence(_batch)` sampling helpers
  (reference: fengshen/utils/transfo_xl_utils.py, exported at
  fengshen/utils/__init__.py:1-4) — here with top-p added;
- the HF-`generate`-style decode path used for LLaMA SFT inference
  (reference: fengshen/examples/ziya_llama/llama_generate.py:17-58 —
  left-padded batch, kv-cache trim, position_ids from mask cumsum,
  reference: fengshen/models/llama/modeling_llama.py:353-375).

TPU-native: the whole decode loop is one `lax.scan` inside jit (static
shapes, preallocated cache), instead of a per-token Python loop.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np


def top_k_logits(logits: jax.Array, k: int = 0, p: float = 0.0,
                 filter_value: float = -1e9) -> jax.Array:
    """Reference: fengshen/utils/transfo_xl_utils.py top_k_logits — combined
    top-k then nucleus filtering."""
    if k > 0:
        kth = jax.lax.top_k(logits, k)[0][..., -1:]
        logits = jnp.where(logits < kth, filter_value, logits)
    if p > 0.0:
        logits = top_p_logits(logits, p, filter_value)
    return logits


def top_p_logits(logits: jax.Array, p: float,
                 filter_value: float = -1e9) -> jax.Array:
    """Nucleus filtering: keep the smallest set of tokens with cumulative
    probability ≥ p (always keeps the argmax)."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # mask tokens whose prefix (excluding themselves) already reaches p
    cutoff_mask = (cum - probs) >= p
    threshold = jnp.where(cutoff_mask, jnp.inf, sorted_logits).min(
        axis=-1, keepdims=True)
    return jnp.where(logits < threshold, filter_value, logits)


def _select_token(logits, rng, do_sample, temperature, top_k, top_p):
    logits = logits.astype(jnp.float32)
    if not do_sample:
        return logits.argmax(-1)
    logits = logits / jnp.maximum(temperature, 1e-6)
    logits = top_k_logits(logits, k=top_k, p=top_p)
    return jax.random.categorical(rng, logits, axis=-1)


def generate(model: Any, params: Any, input_ids: jax.Array,
             attention_mask: Optional[jax.Array] = None,
             max_new_tokens: int = 32,
             do_sample: bool = False, temperature: float = 1.0,
             top_k: int = 0, top_p: float = 0.0,
             eos_token_id: Optional[int] = None,
             pad_token_id: int = 0,
             rng: Optional[jax.Array] = None) -> jax.Array:
    """Batched decode with a preallocated KV cache.

    `input_ids` is LEFT-padded [B, S] (the reference pads left for batched
    generation, reference: llama_generate.py:17-40); `attention_mask` marks
    real tokens. Returns [B, S + max_new_tokens] with pad after eos.
    """
    batch, prompt_len = input_ids.shape
    if attention_mask is None:
        attention_mask = jnp.ones((batch, prompt_len), jnp.int32)
    if rng is None:
        rng = jax.random.PRNGKey(0)

    # position_ids from mask cumsum (left-pad aware,
    # reference: modeling_llama.py:353-375)
    position_ids = jnp.clip(attention_mask.cumsum(-1) - 1, 0, None)

    # cache built from abstract shapes only — a real init would materialize
    # a full-precision param tree (fatal for the int8 serving path on
    # models sized to barely fit)
    abstract = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros((batch, 1), jnp.int32),
                           init_cache=True))
    cache = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), abstract["cache"])

    logits, mutated = model.apply(
        {"params": params, "cache": cache}, input_ids,
        attention_mask=attention_mask, position_ids=position_ids,
        init_cache=True, mutable=["cache"])
    cache = mutated["cache"]

    rng, step_rng = jax.random.split(rng)
    next_token = _select_token(logits[:, -1], step_rng, do_sample,
                               temperature, top_k, top_p)
    finished = jnp.zeros((batch,), bool)
    if eos_token_id is not None:
        finished = finished | (next_token == eos_token_id)

    def step(carry, step_rng):
        cache, token, pos, finished = carry
        logits, mutated = model.apply(
            {"params": params, "cache": cache}, token[:, None],
            attention_mask=attention_mask,
            position_ids=pos[:, None], init_cache=True, mutable=["cache"])
        nxt = _select_token(logits[:, -1], step_rng, do_sample,
                            temperature, top_k, top_p)
        nxt = jnp.where(finished, pad_token_id, nxt)
        if eos_token_id is not None:
            finished = finished | (nxt == eos_token_id)
        return (mutated["cache"], nxt, pos + 1, finished), nxt

    pos0 = position_ids[:, -1] + 1
    step_rngs = jax.random.split(rng, max(max_new_tokens - 1, 0))
    (_, _, _, _), tokens = jax.lax.scan(
        step, (cache, next_token, pos0, finished), step_rngs)

    out = jnp.concatenate(
        [input_ids, next_token[:, None], tokens.T], axis=1)
    return out


def sample_sequence_batch(model, params, context: jax.Array,
                          max_out_seq: int, *,
                          attention_mask: Optional[jax.Array] = None,
                          temperature: float = 1.0,
                          top_k: int = 0, top_p: float = 0.0,
                          eos_token_id: Optional[int] = None,
                          rng: Optional[jax.Array] = None) -> jax.Array:
    """Name/shape parity with the reference's sampling helper
    (reference: fengshen/utils/transfo_xl_utils.py sample_sequence_batch).
    `attention_mask` marks real tokens of a LEFT-padded context — required
    whenever prompts in the batch have different lengths."""
    max_new = max_out_seq - context.shape[1]
    return generate(model, params, context,
                    attention_mask=attention_mask, max_new_tokens=max_new,
                    do_sample=True, temperature=temperature, top_k=top_k,
                    top_p=top_p, eos_token_id=eos_token_id, rng=rng)


def generate_with_prompts(model, params, tokenizer, prompts: list,
                          max_out_seq: int = 128, *,
                          temperature: float = 1.0, top_k: int = 0,
                          top_p: float = 0.0, seed: int = 0) -> list:
    """Encode → strip trailing eos → LEFT-pad with mask → sample → decode
    continuations (the shared driver behind the transfo_xl paraphrase /
    reasoning surfaces, reference: fengshen/utils/transfo_xl_utils.py).
    Returns the decoded text AFTER each prompt."""
    import numpy as np

    enc = [tokenizer.encode(p) for p in prompts]
    enc = [ids[:-1] if ids and ids[-1] == tokenizer.eos_token_id else ids
           for ids in enc]
    max_len = max(len(x) for x in enc)
    pad = tokenizer.pad_token_id or 0
    batch = np.full((len(enc), max_len), pad, np.int32)
    mask = np.zeros((len(enc), max_len), np.int32)
    for i, ids in enumerate(enc):
        batch[i, max_len - len(ids):] = ids
        mask[i, max_len - len(ids):] = 1
    out = sample_sequence_batch(
        model, params, jnp.asarray(batch),
        attention_mask=jnp.asarray(mask), max_out_seq=max_out_seq,
        temperature=temperature, top_k=top_k, top_p=top_p,
        eos_token_id=tokenizer.eos_token_id,
        rng=jax.random.PRNGKey(seed))
    return [tokenizer.decode([int(t) for t in row[max_len:]])
            for row in np.asarray(out)]
