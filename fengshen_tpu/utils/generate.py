"""Generation / sampling utilities.

Covers two reference surfaces:
- `top_k_logits` / `sample_sequence(_batch)` sampling helpers
  (reference: fengshen/utils/transfo_xl_utils.py, exported at
  fengshen/utils/__init__.py:1-4) — here with top-p added;
- the HF-`generate`-style decode path used for LLaMA SFT inference
  (reference: fengshen/examples/ziya_llama/llama_generate.py:17-58 —
  left-padded batch, kv-cache trim, position_ids from mask cumsum,
  reference: fengshen/models/llama/modeling_llama.py:353-375).

TPU-native: the whole decode loop is one `lax.scan` inside jit (static
shapes, preallocated cache), instead of a per-token Python loop.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np


def top_k_logits(logits: jax.Array, k: int = 0, p: float = 0.0,
                 filter_value: float = -1e9) -> jax.Array:
    """Reference: fengshen/utils/transfo_xl_utils.py top_k_logits — combined
    top-k then nucleus filtering."""
    if k > 0:
        kth = jax.lax.top_k(logits, k)[0][..., -1:]
        logits = jnp.where(logits < kth, filter_value, logits)
    if p > 0.0:
        logits = top_p_logits(logits, p, filter_value)
    return logits


def top_p_logits(logits: jax.Array, p: float,
                 filter_value: float = -1e9) -> jax.Array:
    """Nucleus filtering: keep the smallest set of tokens with cumulative
    probability ≥ p (always keeps the argmax)."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # mask tokens whose prefix (excluding themselves) already reaches p
    cutoff_mask = (cum - probs) >= p
    threshold = jnp.where(cutoff_mask, jnp.inf, sorted_logits).min(
        axis=-1, keepdims=True)
    return jnp.where(logits < threshold, filter_value, logits)


def apply_logits_controls(logits, history, cur_index, *,
                          repetition_penalty: float = 1.0,
                          no_repeat_ngram_size: int = 0,
                          min_length: int = 0,
                          eos_token_id: Optional[int] = None,
                          history_mask=None):
    """HF-`generate`-compatible logits processors, fully jittable
    (reference: fengshen/utils/transfo_xl_utils.py penalized sampling;
    the examples pass the HF kwargs — mt5_summary, qa_t5, ziya).

    logits [N, V]; history [N, L] tokens generated so far (prompt
    included for decoder-only); cur_index: traced count of valid history
    tokens (== the position the next token will take); history_mask
    [N, L] marks real tokens (left-padded prompts).
    """
    n_rows, vocab = logits.shape
    length = history.shape[1]
    logits = logits.astype(jnp.float32)
    valid = jnp.arange(length)[None, :] < cur_index
    if history_mask is not None:
        valid = valid & history_mask.astype(bool)

    if repetition_penalty != 1.0:
        seen = jnp.zeros((n_rows, vocab), jnp.int32).at[
            jnp.arange(n_rows)[:, None], history].max(
            valid.astype(jnp.int32)).astype(bool)
        penalized = jnp.where(logits > 0, logits / repetition_penalty,
                              logits * repetition_penalty)
        logits = jnp.where(seen, penalized, logits)

    if no_repeat_ngram_size == 1:
        # HF semantics at size 1: ban every previously generated token
        banned = jnp.zeros((n_rows, vocab), jnp.int32).at[
            jnp.arange(n_rows)[:, None], history].max(
            valid.astype(jnp.int32)).astype(bool)
        logits = jnp.where(banned, jnp.float32(-1e9), logits)
    elif no_repeat_ngram_size > 1:
        n = no_repeat_ngram_size
        # previous complete n-grams: windows [s, s+n) inside the valid
        # prefix; the candidate v is banned when the last (n-1)-gram plus
        # v matches one of them (HF NoRepeatNGramLogitsProcessor)
        n_win = length - n + 1
        if n_win > 0:
            idx = jnp.arange(n_win)[:, None] + jnp.arange(n - 1)[None, :]
            wins = history[:, idx]                     # [N, W, n-1]
            nxt = history[:, jnp.arange(n - 1, length)]  # [N, W]
            win_ok = valid[:, idx].all(-1) & \
                valid[:, jnp.arange(n - 1, length)]
            last = jax.lax.dynamic_slice_in_dim(
                history, cur_index - (n - 1), n - 1, axis=1)
            match = (wins == last[:, None, :]).all(-1) & win_ok
            match = match & (cur_index >= n - 1)
            banned = jnp.zeros((n_rows, vocab), jnp.int32).at[
                jnp.arange(n_rows)[:, None], nxt].max(
                match.astype(jnp.int32)).astype(bool)
            logits = jnp.where(banned, jnp.float32(-1e9), logits)

    if min_length > 0 and eos_token_id is not None:
        eos_col = jnp.arange(vocab) == eos_token_id
        logits = jnp.where(eos_col[None] & (cur_index < min_length),
                           jnp.float32(-1e9), logits)
    return logits


def _controls_active(repetition_penalty, no_repeat_ngram_size,
                     min_length) -> bool:
    return (repetition_penalty != 1.0 or no_repeat_ngram_size > 0 or
            min_length > 0)


def _make_control(control_kw: dict, history_mask=None):
    """`control(logits, history, cur_index)` — identity when no control
    is active, else apply_logits_controls bound to these settings. The
    ONE place every decode path gets its processor from."""
    if not _controls_active(control_kw["repetition_penalty"],
                            control_kw["no_repeat_ngram_size"],
                            control_kw["min_length"]):
        return lambda logits, history, cur: logits
    return partial(apply_logits_controls, history_mask=history_mask,
                   **control_kw)


def _filtered_logits(logits, temperature, top_k, top_p):
    """THE sampling filter pipeline (fp32, temperature, combined
    top-k/top-p). Shared by `_select_token` and `_spec_dist`: the
    speculative rejection scheme is distribution-exact only if the p/q
    it compares are exactly the distribution draft proposals are
    sampled from — one implementation keeps them from drifting."""
    logits = logits.astype(jnp.float32) / jnp.maximum(temperature, 1e-6)
    return top_k_logits(logits, k=top_k, p=top_p)


def _select_token(logits, rng, do_sample, temperature, top_k, top_p):
    if not do_sample:
        return logits.astype(jnp.float32).argmax(-1)
    return jax.random.categorical(
        rng, _filtered_logits(logits, temperature, top_k, top_p),
        axis=-1)


def generate(model: Any, params: Any, input_ids: jax.Array,
             attention_mask: Optional[jax.Array] = None,
             max_new_tokens: int = 32,
             do_sample: bool = False, temperature: float = 1.0,
             top_k: int = 0, top_p: float = 0.0,
             eos_token_id: Optional[int] = None,
             pad_token_id: int = 0,
             repetition_penalty: float = 1.0,
             no_repeat_ngram_size: int = 0,
             min_length: int = 0,
             rng: Optional[jax.Array] = None) -> jax.Array:
    """Batched decode with a preallocated KV cache.

    `input_ids` is LEFT-padded [B, S] (the reference pads left for batched
    generation, reference: llama_generate.py:17-40); `attention_mask` marks
    real tokens. Returns [B, S + max_new_tokens] with pad after eos.
    `min_length` counts the FULL sequence (prompt + generated), matching
    HF `generate(min_length=...)` for decoder-only models.
    """
    batch, prompt_len = input_ids.shape
    if max_new_tokens <= 0:
        return input_ids
    if attention_mask is None:
        attention_mask = jnp.ones((batch, prompt_len), jnp.int32)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    total_len = prompt_len + max_new_tokens
    hist_mask = jnp.concatenate(
        [attention_mask.astype(jnp.int32),
         jnp.ones((batch, max_new_tokens), jnp.int32)], axis=1)
    control = _make_control(
        dict(repetition_penalty=repetition_penalty,
             no_repeat_ngram_size=no_repeat_ngram_size,
             min_length=min_length, eos_token_id=eos_token_id),
        history_mask=hist_mask)

    # position_ids from mask cumsum (left-pad aware,
    # reference: modeling_llama.py:353-375)
    position_ids = jnp.clip(attention_mask.cumsum(-1) - 1, 0, None)

    logits, cache = _prefill_cache(model, params, input_ids,
                                   attention_mask, position_ids)

    buf = jnp.concatenate(
        [input_ids.astype(jnp.int32),
         jnp.full((batch, max_new_tokens), pad_token_id, jnp.int32)],
        axis=1)
    rng, step_rng = jax.random.split(rng)
    step_logits = control(logits[:, -1], buf, jnp.int32(prompt_len))
    next_token = _select_token(step_logits, step_rng, do_sample,
                               temperature, top_k, top_p)
    buf = buf.at[:, prompt_len].set(next_token.astype(jnp.int32))
    finished = jnp.zeros((batch,), bool)
    if eos_token_id is not None:
        finished = finished | (next_token == eos_token_id)

    def step(carry, inp):
        cache, buf, token, pos, finished = carry
        t, step_rng = inp
        logits, mutated = model.apply(
            {"params": params, "cache": cache}, token[:, None],
            attention_mask=attention_mask,
            position_ids=pos[:, None], init_cache=True, mutable=["cache"])
        step_logits = control(logits[:, -1], buf, t)
        nxt = _select_token(step_logits, step_rng, do_sample,
                            temperature, top_k, top_p)
        nxt = jnp.where(finished, pad_token_id, nxt).astype(jnp.int32)
        if eos_token_id is not None:
            finished = finished | (nxt == eos_token_id)
        buf = jax.lax.dynamic_update_slice_in_dim(
            buf, nxt[:, None], t, axis=1)
        return (mutated["cache"], buf, nxt, pos + 1, finished), None

    pos0 = position_ids[:, -1] + 1
    step_rngs = jax.random.split(rng, max(max_new_tokens - 1, 0))
    ts = jnp.arange(prompt_len + 1, total_len)
    (_, buf, _, _, _), _ = jax.lax.scan(
        step, (cache, buf, next_token, pos0, finished), (ts, step_rngs))
    return buf


def is_cache_index_path(path) -> bool:
    """True when a tree_map_with_path key path addresses a `cache_index`
    leaf (the decode write-position state in every cache family here).
    Shared by `_rollback_cache` and the serving slot pool's per-slot
    index surgery (fengshen_tpu/serving/cache.py)."""
    return any(getattr(k, "key", None) == "cache_index" for k in path)


def _rollback_cache(cache, delta):
    """Lower every `cache_index` leaf by `delta` (traced scalar).

    Sound for this repo's cache design (modeling_llama.py _update_cache
    and its siblings): entries are written with dynamic_update_slice AT
    the index, and attention validity is `key_pos <= idx + t` per
    query — so after lowering the index, stale tail entries are masked
    out and later overwritten in place."""
    def fix(path, leaf):
        if is_cache_index_path(path):
            return leaf - jnp.asarray(delta, leaf.dtype)
        return leaf
    return jax.tree_util.tree_map_with_path(fix, cache)


def _prefill_cache(model, params, input_ids, attention_mask,
                   position_ids):
    """Abstract-init a decode cache and run the prompt through it.
    Returns (prompt logits, primed cache).

    The cache is built from abstract shapes only — a real init would
    materialize a full-precision param tree (fatal for the int8 serving
    path on models sized to barely fit)."""
    batch = input_ids.shape[0]
    abstract = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros((batch, 1), jnp.int32),
                           init_cache=True))
    cache = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), abstract["cache"])
    logits, mutated = model.apply(
        {"params": params, "cache": cache}, input_ids,
        attention_mask=attention_mask, position_ids=position_ids,
        init_cache=True, mutable=["cache"])
    return logits, mutated["cache"]


def _spec_dist(logits, temperature, top_k, top_p):
    """The filtered sampling distribution `_select_token` draws from,
    as fp32 probabilities (same `_filtered_logits` pipeline)."""
    return jax.nn.softmax(
        _filtered_logits(logits, temperature, top_k, top_p), axis=-1)


def _spec_round_tokens(t_logits, d_logits, d, rng, *, do_sample,
                       temperature=1.0, top_k=0, top_p=0.0):
    """One speculative round's accept/commit math (pure — the
    distributional correctness of the sampling scheme is unit-tested
    directly against analytic probabilities).

    `t_logits` [B, g+1, V]: target logits over `[last, d_1..d_g]`;
    `d_logits` [B, g, V] or None (greedy): draft logits for the
    proposals `d` [B, g]. Returns `(n_r, w)`: per-row accepted-prefix
    length and the [B, g+1] window tokens — accepted proposals, then
    the correction/resample at the first rejection, then (meaningful
    only on full acceptance) the bonus token.

    Greedy: accept while the draft equals the target argmax; the
    correction IS the target argmax, so w is argmax(t_logits).
    Sampling (the standard speculative rejection scheme): accept d_i
    with prob min(1, p_i(d_i)/q_i(d_i)); at the first rejection
    resample from norm(max(0, p_i - q_i)); on full acceptance sample
    the bonus from p_{g+1}. Every committed token is then distributed
    EXACTLY as a plain sample from the target's filtered distribution
    conditioned on the committed prefix — the draft changes only how
    many target dispatches it takes.
    """
    gamma = d.shape[1]
    if not do_sample:
        y = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)
        m = (d == y[:, :gamma])
        n_r = jnp.sum(jnp.cumprod(m.astype(jnp.int32), axis=1), axis=1)
        return n_r, y
    p = _spec_dist(t_logits, temperature, top_k, top_p)  # [B, g+1, V]
    q = _spec_dist(d_logits, temperature, top_k, top_p)  # [B, g, V]
    p_d = jnp.take_along_axis(p[:, :gamma], d[..., None], -1)[..., 0]
    q_d = jnp.take_along_axis(q, d[..., None], -1)[..., 0]
    r_accept, r_resid, r_bonus = jax.random.split(rng, 3)
    # u < p/q without the division (q_d > 0: d was sampled from q)
    u = jax.random.uniform(r_accept, d.shape)
    accept = u * q_d < p_d
    n_r = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1)
    resid = jnp.maximum(p[:, :gamma] - q, 0.0)
    norm = resid.sum(-1, keepdims=True)
    # p == q makes the residual empty; any sample from p is then
    # already correct (rejection can't occur with prob > 0, but guard
    # the categorical against log(0) rows anyway)
    resid = jnp.where(norm > 0, resid / jnp.maximum(norm, 1e-20),
                      p[:, :gamma])
    resample = jax.random.categorical(
        r_resid, jnp.log(resid + 1e-20), axis=-1).astype(jnp.int32)
    bonus = jax.random.categorical(
        r_bonus, jnp.log(p[:, gamma] + 1e-20), axis=-1).astype(jnp.int32)
    w = jnp.concatenate(
        [jnp.where(jnp.arange(gamma)[None] < n_r[:, None], d, resample),
         bonus[:, None]], axis=1)
    return n_r, w


def _spec_round_tokens_lanes(t_logits, d_logits, d, keys, *, do_sample,
                             temperature=1.0, top_k=0, top_p=0.0):
    """Per-lane keyed variant of `_spec_round_tokens` for the serving
    engine's slot pool: each lane carries its OWN PRNG key (the
    engine's per-lane key ring), so a lane's accept/resample draws are
    a pure function of its request seed — independent of which other
    requests co-tenant the pool. `keys` is [B, 2] uint32 (one key per
    lane). Greedy delegates straight to the shared single-key path
    (the rng is unused there); sampling vmaps the SAME accept rule
    over lanes so there is exactly one implementation of the
    rejection-sampling math."""
    if not do_sample:
        return _spec_round_tokens(t_logits, None, d, None,
                                  do_sample=False)

    def per_lane(tl, dl, dd, key):
        n_r, w = _spec_round_tokens(
            tl[None], dl[None], dd[None], key, do_sample=True,
            temperature=temperature, top_k=top_k, top_p=top_p)
        return n_r[0], w[0]

    return jax.vmap(per_lane)(t_logits, d_logits, d, keys)


def _spec_early_return(input_ids, max_new_tokens, return_stats):
    """Shared no-op path for max_new_tokens <= 0 (None = proceed)."""
    if max_new_tokens > 0:
        return None
    return (input_ids, {"rounds": 0, "drafted": 0, "accepted": 0,
                        "acceptance_rate": 0.0}) \
        if return_stats else input_ids


def _check_spec_cache_headroom(models, total_len, gamma, fn_name):
    """The verify forward near the end writes cache entries up to index
    total_len + gamma - 1; a too-small preallocated cache would CLAMP
    the dynamic_update_slice start and silently corrupt committed
    entries (breaking exactness), so refuse loudly. `models` is
    (name, module) pairs."""
    for name, m in models:
        max_len = getattr(getattr(m, "config", None),
                          "max_position_embeddings", None)
        if max_len is not None and max_len < total_len + gamma:
            raise ValueError(
                f"{fn_name}: {name}.config.max_position_embeddings="
                f"{max_len} < prompt+max_new_tokens+gamma="
                f"{total_len + gamma}; the speculation window needs "
                "gamma extra cache slots")


def _speculative_loop(model, params, input_ids, attention_mask,
                      max_new_tokens, gamma, *, do_sample, temperature,
                      top_k, top_p, eos_token_id, pad_token_id, rng,
                      return_stats, propose, post_commit, extra_init):
    """The ONE copy of the propose→verify→commit speculative machinery
    (shared by `speculative_generate` and `prompt_lookup_generate` —
    the eos-masking, min-advance commit, and cache-rollback bookkeeping
    are subtle enough that two copies would silently diverge).

    `propose(extra, buf, t, pos, last, r_draft) -> (extra, d, d_logits)`
    supplies each round's [B, gamma] proposals (d_logits None in greedy
    modes); `post_commit(extra, n) -> extra` runs after the commit
    (e.g. draft-cache rollback); `extra` is any pytree carried through
    the while_loop (a draft KV cache, or () for draft-free lookup).
    `attention_mask` may be None (defaults to all-ones); the shared
    cache-headroom guard lives in `_check_spec_cache_headroom`.
    """
    batch, prompt_len = input_ids.shape
    if attention_mask is None:
        attention_mask = jnp.ones((batch, prompt_len), jnp.int32)
    total_len = prompt_len + max_new_tokens
    position_ids = jnp.clip(attention_mask.cumsum(-1) - 1, 0, None)
    if rng is None:
        rng = jax.random.PRNGKey(0)

    t_logits, t_cache = _prefill_cache(model, params, input_ids,
                                       attention_mask, position_ids)

    # slack columns keep the fixed-width window write in-bounds near
    # the end (dynamic_update_slice CLAMPS the start index, which would
    # silently mis-place the window)
    buf = jnp.concatenate(
        [input_ids.astype(jnp.int32),
         jnp.full((batch, max_new_tokens + gamma + 1), pad_token_id,
                  jnp.int32)], axis=1)
    rng, r_first = jax.random.split(rng)
    first = _select_token(t_logits[:, -1], r_first, do_sample,
                          temperature, top_k, top_p).astype(jnp.int32)
    buf = buf.at[:, prompt_len].set(first)
    finished = (first == eos_token_id) if eos_token_id is not None \
        else jnp.zeros((batch,), bool)
    last = jnp.where(finished, pad_token_id, first).astype(jnp.int32)
    pos0 = position_ids[:, -1] + 1

    def body(carry):
        (extra, t_cache, buf, t, pos, last, finished,
         rng, rounds, accepted) = carry
        prev_finished = finished
        rng, r_draft, r_round = jax.random.split(rng, 3)
        extra, d, d_logits = propose(extra, buf, t, pos, last, r_draft)

        verify = jnp.concatenate([last[:, None], d], axis=1)
        v_pos = pos[:, None] + jnp.arange(gamma + 1)[None]
        logits, mut = model.apply(
            {"params": params, "cache": t_cache}, verify,
            attention_mask=attention_mask, position_ids=v_pos,
            init_cache=True, mutable=["cache"])
        t_cache = mut["cache"]

        n_r, w = _spec_round_tokens(
            logits, d_logits, d, r_round, do_sample=do_sample,
            temperature=temperature, top_k=top_k, top_p=top_p)
        n_r = jnp.where(finished, gamma, n_r)
        n = jnp.min(n_r)
        c = n + 1  # committed this round (1..gamma+1)

        if eos_token_id is not None:
            is_eos = w == eos_token_id
            after = jnp.pad(jnp.cumsum(is_eos, axis=1)[:, :-1],
                            ((0, 0), (1, 0))) > 0
            w = jnp.where(after, pad_token_id, w)
            in_window = jnp.arange(gamma + 1)[None] < c
            finished = finished | jnp.any(is_eos & in_window, axis=1)
        w = jnp.where(prev_finished[:, None], pad_token_id, w)
        w = jnp.where(jnp.arange(gamma + 1)[None] < c, w, pad_token_id)

        buf = jax.lax.dynamic_update_slice_in_dim(buf, w, t, axis=1)
        new_last = jax.lax.dynamic_slice_in_dim(w, c - 1, 1, axis=1)[:, 0]
        # the committed count is c; the target cache advanced gamma+1
        # -> valid through the second-newest committed token, t'-1
        t_cache = _rollback_cache(t_cache, gamma - n)
        extra = post_commit(extra, n)
        return (extra, t_cache, buf, t + c, pos + c, new_last,
                finished, rng, rounds + 1, accepted + n)

    def cond(carry):
        t, finished = carry[3], carry[6]
        return (t < total_len) & ~jnp.all(finished)

    init = (extra_init, t_cache, buf, jnp.int32(prompt_len + 1), pos0,
            last, finished, rng, jnp.int32(0), jnp.int32(0))
    (_, _, buf, _, _, _, _, _, rounds, accepted) = \
        jax.lax.while_loop(cond, body, init)
    out = buf[:, :total_len]
    if return_stats:
        drafted = rounds * gamma
        return out, {"rounds": rounds, "drafted": drafted,
                     "accepted": accepted,
                     "acceptance_rate":
                         accepted.astype(jnp.float32) /
                         jnp.maximum(drafted, 1).astype(jnp.float32)}
    return out


def speculative_generate(model: Any, params: Any,
                         draft_model: Any, draft_params: Any,
                         input_ids: jax.Array,
                         attention_mask: Optional[jax.Array] = None,
                         max_new_tokens: int = 32,
                         gamma: int = 4,
                         do_sample: bool = False,
                         temperature: float = 1.0,
                         top_k: int = 0, top_p: float = 0.0,
                         eos_token_id: Optional[int] = None,
                         pad_token_id: int = 0,
                         rng: Optional[jax.Array] = None,
                         return_stats: bool = False):
    """Speculative decoding: the output law of plain `generate` at a
    fraction of the target-model dispatches (beyond-reference serving
    capability; the reference's serving path is plain per-token decode,
    fengshen/examples/ziya_llama/llama_generate.py:17-58).

    Each round the small draft model proposes `gamma` tokens
    autoregressively; the target model scores `[last, d_1..d_gamma]` in
    ONE forward; the longest acceptable prefix is committed plus one
    correction token. Greedy (`do_sample=False`): acceptance is
    draft==target-argmax and the output is TOKEN-EXACT vs plain greedy
    decode. Sampling (`do_sample=True`): the draft samples from its
    filtered distribution q, acceptance is the standard rejection rule
    min(1, p/q) with residual resampling (see `_spec_round_tokens`), so
    every committed token is distributed exactly as a plain sample from
    the target's filtered distribution — same law as `generate(...,
    do_sample=True)`, not token-identical (randomness is consumed
    differently). Per round the target runs once for 1..gamma+1
    committed tokens instead of once per token.

    Batched: rows advance together by the MINIMUM accepted length
    across unfinished rows (a shared cache index keeps positions
    aligned). An over-accepted row's discarded tail is re-derived next
    round: greedily that reproduces the identical tokens (exactness by
    determinism); under sampling the redo draws fresh randomness, and
    exactness holds in DISTRIBUTION — the fresh round conditions only
    on the committed prefix, so each committed token is still
    ~ p(.|prefix). Both KV caches roll back via `_rollback_cache` —
    sound because stale entries past the index are masked and
    overwritten (see that helper's docstring).

    The whole loop is one `lax.while_loop` under jit: static shapes,
    `gamma` static, dynamic trip count with >=1 committed token per
    round. `return_stats` also returns
    {"rounds", "drafted", "accepted"} for acceptance-rate tuning.
    """
    assert gamma >= 1, "speculative decoding needs gamma >= 1"
    batch, prompt_len = input_ids.shape
    early = _spec_early_return(input_ids, max_new_tokens, return_stats)
    if early is not None:
        return early
    if attention_mask is None:
        attention_mask = jnp.ones((batch, prompt_len), jnp.int32)
    _check_spec_cache_headroom(
        (("model", model), ("draft_model", draft_model)),
        prompt_len + max_new_tokens, gamma, "speculative_generate")
    position_ids = jnp.clip(attention_mask.cumsum(-1) - 1, 0, None)
    _, d_cache = _prefill_cache(draft_model, draft_params, input_ids,
                                attention_mask, position_ids)

    def draft_step(carry, step_rng):
        cache, tok, pos = carry
        logits, mut = draft_model.apply(
            {"params": draft_params, "cache": cache}, tok[:, None],
            attention_mask=attention_mask, position_ids=pos[:, None],
            init_cache=True, mutable=["cache"])
        nxt = _select_token(logits[:, -1], step_rng, do_sample,
                            temperature, top_k, top_p).astype(jnp.int32)
        ys = (nxt, logits[:, -1]) if do_sample else nxt
        return (mut["cache"], nxt, pos + 1), ys

    def propose(d_cache, buf, t, pos, last, r_draft):
        # draft gamma proposals (one extra feed keeps the draft cache
        # aligned with the target on full acceptance)
        (d_cache, _, _), drafts = jax.lax.scan(
            draft_step, (d_cache, last, pos),
            jax.random.split(r_draft, gamma + 1))
        if do_sample:
            d = jnp.moveaxis(drafts[0], 0, 1)[:, :gamma]  # [B, gamma]
            d_logits = jnp.moveaxis(drafts[1], 0, 1)[:, :gamma]
        else:
            d = jnp.moveaxis(drafts, 0, 1)[:, :gamma]
            d_logits = None
        return d_cache, d, d_logits

    return _speculative_loop(
        model, params, input_ids, attention_mask, max_new_tokens,
        gamma, do_sample=do_sample, temperature=temperature,
        top_k=top_k, top_p=top_p, eos_token_id=eos_token_id,
        pad_token_id=pad_token_id, rng=rng, return_stats=return_stats,
        propose=propose,
        post_commit=lambda d_cache, n: _rollback_cache(d_cache,
                                                       gamma - n),
        extra_init=d_cache)


def _ngram_propose(buf, t, ngram, gamma, pad_token_id):
    """Prompt-lookup proposals: find an earlier occurrence of the
    `ngram`-token suffix ending at position t (exclusive) in each row
    of `buf`, and propose the `gamma` tokens that followed it. Prefers
    the LATEST match whose whole gamma-token continuation lies inside
    the committed region — the very latest match's continuation can run
    into uncommitted pads, capping acceptance on exactly the periodic
    outputs lookup targets — falling back to the latest partial match.
    Rows with no match propose pads (they'll be rejected and the round
    degrades to plain one-token decode). Pure + static shapes; `t` may
    be traced."""
    batch, width = buf.shape
    suffix = jax.lax.dynamic_slice_in_dim(buf, t - ngram, ngram, axis=1)
    # windows[b, j] == buf[b, j:j+ngram]
    windows = jnp.stack(
        [buf[:, k:width - ngram + 1 + k] for k in range(ngram)], axis=-1)
    match = jnp.all(windows == suffix[:, None, :], axis=-1)
    pos = jnp.arange(width - ngram + 1)[None]
    # continuation must start strictly inside the committed region
    match = match & (pos + ngram < t)
    fits = match & (pos + ngram + gamma <= t)
    j_fit = jnp.max(jnp.where(fits, pos, -1), axis=1)
    j_any = jnp.max(jnp.where(match, pos, -1), axis=1)
    j = jnp.where(j_fit >= 0, j_fit, j_any)  # [B], -1 = none
    idx = jnp.clip(j[:, None] + ngram + jnp.arange(gamma)[None], 0,
                   width - 1)
    d = jnp.take_along_axis(buf, idx, axis=1)
    return jnp.where((j >= 0)[:, None], d, pad_token_id).astype(jnp.int32)


def _ngram_propose_lanes(buf, t, ngram, gamma, fallback):
    """Per-lane-cursor flavor of `_ngram_propose` for the serving slot
    pool (fengshen_tpu/serving/engine.py): `t` is a [B] vector — every
    lane's committed history ends at its own position — and a lane with
    no n-gram hit proposes its `fallback` token (its last committed
    token) repeated, so degenerate lanes degrade to >=1 committed token
    per verify instead of drafting pads that can never be accepted.
    Pure + static shapes; vmap turns the dynamic suffix slice into a
    gather, so the ONE matcher implementation serves both the lockstep
    `prompt_lookup_generate` loop and the pool's per-lane tick."""
    def one(row, ti, fb):
        return _ngram_propose(row[None], ti, ngram, gamma, fb)[0]
    return jax.vmap(one)(buf, t, fallback)


def prompt_lookup_generate(model: Any, params: Any,
                           input_ids: jax.Array,
                           attention_mask: Optional[jax.Array] = None,
                           max_new_tokens: int = 32,
                           gamma: int = 4, ngram: int = 2,
                           eos_token_id: Optional[int] = None,
                           pad_token_id: int = 0,
                           return_stats: bool = False):
    """DRAFT-FREE speculative decoding (prompt lookup): propose the
    continuation of the latest earlier occurrence of the current
    `ngram`-token suffix, verify all `gamma` proposals with one target
    forward, commit the accepted prefix + 1 correction. TOKEN-EXACT vs
    plain greedy `generate` — the lookup only changes how many target
    dispatches it takes. Big wins on extractive/repetitive workloads
    (summarisation, QA over a context, code) where the continuation
    often already appears verbatim in the prompt or the generation.

    Same loop/cache machinery as `speculative_generate` minus the
    draft model: one `lax.while_loop`, KV rollback via `_rollback_cache`,
    batched min-advance (see that function's docstring).
    """
    assert gamma >= 1 and ngram >= 1
    prompt_len = input_ids.shape[1]
    early = _spec_early_return(input_ids, max_new_tokens, return_stats)
    if early is not None:
        return early
    _check_spec_cache_headroom(
        (("model", model),), prompt_len + max_new_tokens, gamma,
        "prompt_lookup_generate")

    def propose(extra, buf, t, pos, last, r_draft):
        return extra, _ngram_propose(buf, t, ngram, gamma,
                                     pad_token_id), None

    return _speculative_loop(
        model, params, input_ids, attention_mask, max_new_tokens,
        gamma, do_sample=False, temperature=1.0, top_k=0, top_p=0.0,
        eos_token_id=eos_token_id, pad_token_id=pad_token_id, rng=None,
        return_stats=return_stats, propose=propose,
        post_commit=lambda extra, n: extra, extra_init=())


def _make_seq2seq_logits_fn(model, params, input_ids, attention_mask,
                            expand: int):
    """Build `logits_fn(dec_buf [N, L]) -> [N, L, V]` for an encoder-decoder
    model, with the batch expanded ×`expand` (beam width).

    Two protocols:
    - `encode` + `decode_logits` (every seq2seq family in the zoo — T5,
      BART, Pegasus, DeltaLM): the encoder runs ONCE outside the decode
      loop; only the decoder stack re-runs per step.
    - plain `__call__(input_ids, decoder_input_ids, ...)`: fallback for
      external/custom modules that only expose a full forward — the whole
      model re-runs per step.
    """
    if hasattr(model, "encode") and hasattr(model, "decode_logits"):
        enc = model.apply({"params": params}, input_ids, attention_mask,
                          method=model.encode)
        enc = jnp.repeat(enc, expand, axis=0)
        mask = (None if attention_mask is None
                else jnp.repeat(attention_mask, expand, axis=0))

        def logits_fn(dec_buf):
            return model.apply({"params": params}, dec_buf, enc, mask,
                               method=model.decode_logits)
    else:
        ids = jnp.repeat(input_ids, expand, axis=0)
        mask = (None if attention_mask is None
                else jnp.repeat(attention_mask, expand, axis=0))

        def logits_fn(dec_buf):
            return model.apply({"params": params}, ids, dec_buf,
                               attention_mask=mask)
    return logits_fn


def _seq2seq_supports_cache(model) -> bool:
    """True when `decode_logits` takes `init_cache` (T5-style KV cache)."""
    import inspect
    return (hasattr(model, "encode") and hasattr(model, "decode_logits")
            and "init_cache" in
            inspect.signature(model.decode_logits).parameters)


def _init_seq2seq_cache(model, src, dec1):
    """Zeros KV-cache pytree from abstract init shapes (no param
    materialisation — same trick as decoder-only `generate`)."""
    abstract = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), jnp.zeros_like(src),
                           jnp.zeros_like(dec1), init_cache=True))
    return jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), abstract["cache"])


def _cache_capacity(model) -> int:
    cfg = getattr(model, "config", None)
    cap = getattr(cfg, "decode_cache_length", 512)
    if _takes_position_offset(model):
        # absolute-position decoders cannot place tokens past their
        # position table; keep overflow on the buffer path, which fails
        # loudly instead of silently clamping the position lookup
        cap = min(cap, getattr(cfg, "max_position_embeddings", cap))
    return cap


def seq2seq_generate(model, params, input_ids: jax.Array,
                     attention_mask: Optional[jax.Array] = None, *,
                     max_new_tokens: int = 32,
                     decoder_start_token_id: int = 0,
                     eos_token_id: Optional[int] = None,
                     pad_token_id: int = 0,
                     do_sample: bool = False, temperature: float = 1.0,
                     top_k: int = 0, top_p: float = 0.0,
                     num_beams: int = 1, length_penalty: float = 1.0,
                     repetition_penalty: float = 1.0,
                     no_repeat_ngram_size: int = 0,
                     min_length: int = 0,
                     rng: Optional[jax.Array] = None) -> jax.Array:
    """Encoder-decoder decode (HF `generate` surface for the seq2seq
    examples — reference: fengshen/examples/mt5_summary, qa_t5,
    finetune_bart_qg all call HF `model.generate(num_beams=...)`).

    Greedy / sampling when `num_beams == 1`, otherwise beam search.
    Returns [B, 1 + max_new_tokens] decoder ids starting with
    `decoder_start_token_id`, padded after eos. `min_length` counts
    decoder tokens (start token included), matching HF seq2seq
    `generate(min_length=...)`; `repetition_penalty` and
    `no_repeat_ngram_size` act over the decoder sequence.
    """
    if num_beams > 1:
        if do_sample:
            raise ValueError(
                "beam-multinomial sampling is not supported; use either "
                "num_beams>1 (deterministic beam search) or do_sample=True")
        return seq2seq_beam_search(
            model, params, input_ids, attention_mask,
            max_new_tokens=max_new_tokens,
            decoder_start_token_id=decoder_start_token_id,
            eos_token_id=eos_token_id, pad_token_id=pad_token_id,
            num_beams=num_beams, length_penalty=length_penalty,
            repetition_penalty=repetition_penalty,
            no_repeat_ngram_size=no_repeat_ngram_size,
            min_length=min_length)

    batch = input_ids.shape[0]
    if max_new_tokens == 0:
        return jnp.full((batch, 1), decoder_start_token_id, jnp.int32)
    length = max_new_tokens + 1
    if rng is None:
        rng = jax.random.PRNGKey(0)
    control_kw = dict(repetition_penalty=repetition_penalty,
                      no_repeat_ngram_size=no_repeat_ngram_size,
                      min_length=min_length, eos_token_id=eos_token_id)
    if _seq2seq_supports_cache(model) and \
            max_new_tokens < _cache_capacity(model):
        return _cached_seq2seq_sample(
            model, params, input_ids, attention_mask,
            max_new_tokens=max_new_tokens,
            decoder_start_token_id=decoder_start_token_id,
            eos_token_id=eos_token_id, pad_token_id=pad_token_id,
            do_sample=do_sample, temperature=temperature, top_k=top_k,
            top_p=top_p, control_kw=control_kw, rng=rng)
    logits_fn = _make_seq2seq_logits_fn(model, params, input_ids,
                                        attention_mask, expand=1)
    buf = jnp.full((batch, length), pad_token_id, jnp.int32)
    buf = buf.at[:, 0].set(decoder_start_token_id)
    finished = jnp.zeros((batch,), bool)
    control = _make_control(control_kw)

    def step(carry, inp):
        buf, finished = carry
        t, step_rng = inp
        logits = jax.lax.dynamic_index_in_dim(
            logits_fn(buf), t - 1, axis=1, keepdims=False)
        logits = control(logits, buf, t)
        nxt = _select_token(logits, step_rng, do_sample, temperature,
                            top_k, top_p)
        nxt = jnp.where(finished, pad_token_id, nxt).astype(jnp.int32)
        if eos_token_id is not None:
            finished = finished | (nxt == eos_token_id)
        buf = jax.lax.dynamic_update_slice_in_dim(
            buf, nxt[:, None], t, axis=1)
        return (buf, finished), None

    ts = jnp.arange(1, length)
    (buf, _), _ = jax.lax.scan(
        step, (buf, finished), (ts, jax.random.split(rng, length - 1)))
    return buf


def _cross_cache_kwargs(model) -> dict:
    """{'cross_from_cache': True} when decode_logits supports reading the
    cross-attention K/V from the cache — the priming call projects the
    encoder K/V once and scan steps skip those matmuls entirely."""
    import inspect
    if "cross_from_cache" in \
            inspect.signature(model.decode_logits).parameters:
        return {"cross_from_cache": True}
    return {}


def _takes_position_offset(model) -> bool:
    """Absolute-position decoders (BART family) need the decode step's
    position explicitly; T5's relative bias derives it from the cache."""
    import inspect
    return "position_offset" in \
        inspect.signature(model.decode_logits).parameters


def _cached_seq2seq_sample(model, params, input_ids, attention_mask, *,
                           max_new_tokens, decoder_start_token_id,
                           eos_token_id, pad_token_id, do_sample,
                           temperature, top_k, top_p, control_kw, rng):
    """Greedy/sampling decode through the model's KV cache: the encoder
    runs once, cross-attention K/V are projected once on the priming
    call, and each scan step runs the decoder on ONE token (O(L)
    attention per step instead of the O(L²) full-prefix re-run)."""
    batch = input_ids.shape[0]
    control = _make_control(control_kw)
    enc = model.apply({"params": params}, input_ids, attention_mask,
                      method=model.encode)
    cache = _init_seq2seq_cache(model, input_ids,
                                jnp.zeros((batch, 1), jnp.int32))
    cross_kw = _cross_cache_kwargs(model)
    has_pos = _takes_position_offset(model)

    def decode(cache, tok, kw, offset):
        if has_pos:
            kw = dict(kw, position_offset=offset)
        logits, mutated = model.apply(
            {"params": params, "cache": cache}, tok[:, None], enc,
            attention_mask, init_cache=True, mutable=["cache"],
            method=model.decode_logits, **kw)
        return mutated["cache"], logits[:, -1]

    length = max_new_tokens + 1
    buf = jnp.full((batch, length), pad_token_id, jnp.int32)
    buf = buf.at[:, 0].set(decoder_start_token_id)
    start = jnp.full((batch,), decoder_start_token_id, jnp.int32)
    # same key stream as the buffer path (split(rng, max_new)): the two
    # implementations must sample identically for a given seed
    keys = jax.random.split(rng, max_new_tokens)
    # prime: projects cross K/V, decodes the start token at position 0
    cache, logits = decode(cache, start, {}, jnp.int32(0))
    tok = _select_token(control(logits, buf, jnp.int32(1)), keys[0],
                        do_sample, temperature, top_k, top_p
                        ).astype(jnp.int32)
    buf = buf.at[:, 1].set(tok)
    finished = jnp.zeros((batch,), bool)
    if eos_token_id is not None:
        finished = finished | (tok == eos_token_id)

    def step(carry, inp):
        cache, buf, tok, finished = carry
        t, step_rng = inp
        cache, logits = decode(cache, tok, cross_kw, t)
        nxt = _select_token(control(logits, buf, t + 1), step_rng,
                            do_sample, temperature, top_k, top_p)
        nxt = jnp.where(finished, pad_token_id, nxt).astype(jnp.int32)
        if eos_token_id is not None:
            finished = finished | (nxt == eos_token_id)
        buf = jax.lax.dynamic_update_slice_in_dim(
            buf, nxt[:, None], t + 1, axis=1)
        return (cache, buf, nxt, finished), None

    ts = jnp.arange(1, max_new_tokens)  # token t sits at position t
    (_, buf, _, _), _ = jax.lax.scan(
        step, (cache, buf, tok, finished), (ts, keys[1:]))
    return buf


_BEAM_NEG = jnp.float32(-1e9)


def _beam_init(batch, K, length, pad_token_id, decoder_start_token_id):
    """(alive_buf, alive_scores, fin_buf, fin_scores) — only beam 0 live."""
    alive_buf = jnp.full((batch, K, length), pad_token_id, jnp.int32)
    alive_buf = alive_buf.at[:, :, 0].set(decoder_start_token_id)
    alive_scores = jnp.tile(
        jnp.where(jnp.arange(K) == 0, 0.0, _BEAM_NEG)[None], (batch, 1))
    fin_buf = jnp.full((batch, K, length), pad_token_id, jnp.int32)
    fin_scores = jnp.full((batch, K), _BEAM_NEG)
    return alive_buf, alive_scores, fin_buf, fin_scores


def _beam_select(alive_buf, alive_scores, fin_buf, fin_scores, log_probs,
                 t, K, eos_token_id, length_penalty):
    """One beam bookkeeping step, shared by the cached and buffer paths:
    expand alive beams by the vocab, keep the top 2K candidates (2K
    guarantees K non-eos survivors), move eos hypotheses into the
    finished pool (length-penalized, merged top-K), re-select K alive
    beams. Returns the updated pools plus (src_beam, tok): which previous
    beam each new alive beam extends, and with what token."""
    batch = alive_buf.shape[0]
    vocab = log_probs.shape[-1]
    cand = (alive_scores[:, :, None] + log_probs).reshape(batch, -1)
    scores2k, idx = jax.lax.top_k(cand, 2 * K)
    beam_idx, tok = idx // vocab, (idx % vocab).astype(jnp.int32)
    buf2k = jnp.take_along_axis(alive_buf, beam_idx[:, :, None], axis=1)
    buf2k = jax.lax.dynamic_update_slice_in_dim(
        buf2k, tok[:, :, None], t, axis=2)
    if eos_token_id is not None:
        is_eos = tok == eos_token_id
        pen = scores2k / (t.astype(jnp.float32) ** length_penalty)
        pen = jnp.where(is_eos, pen, _BEAM_NEG)
        all_scores = jnp.concatenate([fin_scores, pen], axis=1)
        all_buf = jnp.concatenate([fin_buf, buf2k], axis=1)
        fin_scores, fin_idx = jax.lax.top_k(all_scores, K)
        fin_buf = jnp.take_along_axis(all_buf, fin_idx[:, :, None], axis=1)
        scores2k = jnp.where(is_eos, _BEAM_NEG, scores2k)
    alive_scores, alive_idx = jax.lax.top_k(scores2k, K)
    alive_buf = jnp.take_along_axis(buf2k, alive_idx[:, :, None], axis=1)
    src_beam = jnp.take_along_axis(beam_idx, alive_idx, axis=1)
    new_tok = jnp.take_along_axis(tok, alive_idx, axis=1)
    return alive_buf, alive_scores, fin_buf, fin_scores, src_beam, new_tok


def _beam_finish(alive_buf, alive_scores, fin_buf, fin_scores,
                 max_new_tokens, length_penalty):
    """Alive beams compete with the finished pool at the horizon length;
    return the best sequence per batch row."""
    horizon = jnp.float32(max_new_tokens) ** length_penalty
    all_scores = jnp.concatenate([fin_scores, alive_scores / horizon],
                                 axis=1)
    all_buf = jnp.concatenate([fin_buf, alive_buf], axis=1)
    best = jnp.argmax(all_scores, axis=1)
    return jnp.take_along_axis(all_buf, best[:, None, None], axis=1)[:, 0]


def _cached_seq2seq_beam(model, params, input_ids, attention_mask, *,
                         max_new_tokens, decoder_start_token_id,
                         eos_token_id, pad_token_id, num_beams,
                         length_penalty, control_kw):
    """Beam search through the KV cache: one-token decoder steps with the
    cache rows gathered along the beam dimension on every reorder."""
    batch = input_ids.shape[0]
    K = num_beams
    N = batch * K
    length = max_new_tokens + 1

    enc = model.apply({"params": params}, input_ids, attention_mask,
                      method=model.encode)
    enc = jnp.repeat(enc, K, axis=0)
    mask = (None if attention_mask is None
            else jnp.repeat(attention_mask, K, axis=0))
    src_rep = jnp.repeat(input_ids, K, axis=0)
    cache = _init_seq2seq_cache(model, src_rep,
                                jnp.zeros((N, 1), jnp.int32))

    alive_buf, alive_scores, fin_buf, fin_scores = _beam_init(
        batch, K, length, pad_token_id, decoder_start_token_id)
    last_tok = jnp.full((batch, K), decoder_start_token_id, jnp.int32)
    cross_kw = _cross_cache_kwargs(model)
    has_pos = _takes_position_offset(model)
    row_control = _make_control(control_kw)

    def control(log_probs, alive_buf, cur):
        # HF beam search runs the processors on the log-softmaxed scores
        vocab = log_probs.shape[-1]
        out = row_control(log_probs.reshape(batch * K, vocab),
                          alive_buf.reshape(batch * K, -1), cur)
        return out.reshape(batch, K, vocab)

    def decode(cache, last_tok, kw, offset):
        if has_pos:
            kw = dict(kw, position_offset=offset)
        logits, mutated = model.apply(
            {"params": params, "cache": cache}, last_tok.reshape(N, 1),
            enc, mask, init_cache=True, mutable=["cache"],
            method=model.decode_logits, **kw)
        log_probs = jax.nn.log_softmax(
            logits[:, -1].astype(jnp.float32), -1).reshape(batch, K, -1)
        return mutated["cache"], log_probs

    def reorder(cache, src_beam):
        # gather the self-attention cache rows onto the surviving beams'
        # source beams; cross K/V are identical across a row's beams
        # (encoder output is repeated), so gathering them would be pure
        # wasted HBM traffic — skip by key name
        flat = (jnp.arange(batch)[:, None] * K + src_beam).reshape(-1)

        def gather(path, c):
            if c.ndim != 4 or any("cross" in str(p) for p in path):
                return c
            return c[flat]
        return jax.tree_util.tree_map_with_path(gather, cache)

    # priming step (t=1): projects the cross-attention K/V into the cache
    cache, log_probs = decode(cache, last_tok, {}, jnp.int32(0))
    log_probs = control(log_probs, alive_buf, jnp.int32(1))
    (alive_buf, alive_scores, fin_buf, fin_scores, src_beam,
     last_tok) = _beam_select(alive_buf, alive_scores, fin_buf,
                              fin_scores, log_probs, jnp.int32(1), K,
                              eos_token_id, length_penalty)
    cache = reorder(cache, src_beam)

    def step(carry, t):
        (alive_buf, alive_scores, fin_buf, fin_scores, cache,
         last_tok) = carry
        # last_tok was selected at step t-1 and sits at position t-1
        cache, log_probs = decode(cache, last_tok, cross_kw, t - 1)
        log_probs = control(log_probs, alive_buf, t)
        (alive_buf, alive_scores, fin_buf, fin_scores, src_beam,
         last_tok) = _beam_select(alive_buf, alive_scores, fin_buf,
                                  fin_scores, log_probs, t, K,
                                  eos_token_id, length_penalty)
        cache = reorder(cache, src_beam)
        return (alive_buf, alive_scores, fin_buf, fin_scores, cache,
                last_tok), None

    carry = (alive_buf, alive_scores, fin_buf, fin_scores, cache, last_tok)
    (alive_buf, alive_scores, fin_buf, fin_scores, _, _), _ = jax.lax.scan(
        step, carry, jnp.arange(2, length))
    return _beam_finish(alive_buf, alive_scores, fin_buf, fin_scores,
                        max_new_tokens, length_penalty)


def seq2seq_predict_step(model, config, args, params, batch, *,
                         max_new_tokens: int) -> jax.Array:
    """The canonical `predict_step` body for seq2seq example modules
    (qa_t5, summary, …): beam/greedy decode driven by the module's parsed
    flags (`--num_beams`, `--length_penalty`)."""
    return seq2seq_generate(
        model, params, batch["input_ids"], batch.get("attention_mask"),
        max_new_tokens=max_new_tokens,
        decoder_start_token_id=getattr(config, "decoder_start_token_id", 0),
        eos_token_id=getattr(config, "eos_token_id", None),
        pad_token_id=getattr(config, "pad_token_id", 0) or 0,
        num_beams=getattr(args, "num_beams", 1),
        length_penalty=getattr(args, "length_penalty", 1.0),
        repetition_penalty=getattr(args, "repetition_penalty", 1.0),
        no_repeat_ngram_size=getattr(args, "no_repeat_ngram_size", 0),
        min_length=getattr(args, "min_length", 0))


def seq2seq_beam_search(model, params, input_ids: jax.Array,
                        attention_mask: Optional[jax.Array] = None, *,
                        max_new_tokens: int = 32,
                        decoder_start_token_id: int = 0,
                        eos_token_id: Optional[int] = None,
                        pad_token_id: int = 0, num_beams: int = 4,
                        length_penalty: float = 1.0,
                        repetition_penalty: float = 1.0,
                        no_repeat_ngram_size: int = 0,
                        min_length: int = 0) -> jax.Array:
    """Beam search over an encoder-decoder model, fully inside `lax.scan`
    (static shapes; TPU-friendly — no per-token host sync).

    Scoring: a hypothesis ending with eos at generated-length `t`
    (excluding the start token, including eos) scores
    `sum_logprobs / t ** length_penalty`; alive beams at the horizon are
    scored the same way at `t = max_new_tokens`. Returns the best
    sequence per batch row, [B, 1 + max_new_tokens].
    """
    batch = input_ids.shape[0]
    if max_new_tokens == 0:
        return jnp.full((batch, 1), decoder_start_token_id, jnp.int32)
    control_kw = dict(repetition_penalty=repetition_penalty,
                      no_repeat_ngram_size=no_repeat_ngram_size,
                      min_length=min_length, eos_token_id=eos_token_id)
    row_control = _make_control(control_kw)
    if _seq2seq_supports_cache(model) and \
            max_new_tokens < _cache_capacity(model):
        return _cached_seq2seq_beam(
            model, params, input_ids, attention_mask,
            max_new_tokens=max_new_tokens,
            decoder_start_token_id=decoder_start_token_id,
            eos_token_id=eos_token_id, pad_token_id=pad_token_id,
            num_beams=num_beams, length_penalty=length_penalty,
            control_kw=control_kw)
    K = num_beams
    length = max_new_tokens + 1

    logits_fn = _make_seq2seq_logits_fn(model, params, input_ids,
                                        attention_mask, expand=K)
    alive_buf, alive_scores, fin_buf, fin_scores = _beam_init(
        batch, K, length, pad_token_id, decoder_start_token_id)

    def step(carry, t):
        alive_buf, alive_scores, fin_buf, fin_scores = carry
        logits = jax.lax.dynamic_index_in_dim(
            logits_fn(alive_buf.reshape(batch * K, length)),
            t - 1, axis=1, keepdims=False)
        log_probs = jax.nn.log_softmax(
            logits.astype(jnp.float32), axis=-1)
        log_probs = row_control(
            log_probs, alive_buf.reshape(batch * K, length),
            t).reshape(batch, K, -1)
        (alive_buf, alive_scores, fin_buf, fin_scores, _, _) = \
            _beam_select(alive_buf, alive_scores, fin_buf, fin_scores,
                         log_probs, t, K, eos_token_id, length_penalty)
        return (alive_buf, alive_scores, fin_buf, fin_scores), None

    carry = (alive_buf, alive_scores, fin_buf, fin_scores)
    (alive_buf, alive_scores, fin_buf, fin_scores), _ = jax.lax.scan(
        step, carry, jnp.arange(1, length))
    return _beam_finish(alive_buf, alive_scores, fin_buf, fin_scores,
                        max_new_tokens, length_penalty)


def sample_sequence_batch(model, params, context: jax.Array,
                          max_out_seq: int, *,
                          attention_mask: Optional[jax.Array] = None,
                          temperature: float = 1.0,
                          top_k: int = 0, top_p: float = 0.0,
                          eos_token_id: Optional[int] = None,
                          rng: Optional[jax.Array] = None) -> jax.Array:
    """Name/shape parity with the reference's sampling helper
    (reference: fengshen/utils/transfo_xl_utils.py sample_sequence_batch).
    `attention_mask` marks real tokens of a LEFT-padded context — required
    whenever prompts in the batch have different lengths."""
    # a context already at/over max_out_seq generates nothing (the
    # reference loop simply doesn't iterate)
    max_new = max(max_out_seq - context.shape[1], 0)
    return generate(model, params, context,
                    attention_mask=attention_mask, max_new_tokens=max_new,
                    do_sample=True, temperature=temperature, top_k=top_k,
                    top_p=top_p, eos_token_id=eos_token_id, rng=rng)


def generate_with_prompts(model, params, tokenizer, prompts: list,
                          max_out_seq: int = 128, *,
                          temperature: float = 1.0, top_k: int = 0,
                          top_p: float = 0.0, seed: int = 0) -> list:
    """Encode → strip trailing eos → LEFT-pad with mask → sample → decode
    continuations (the shared driver behind the transfo_xl paraphrase /
    reasoning surfaces, reference: fengshen/utils/transfo_xl_utils.py).
    Returns the decoded text AFTER each prompt."""
    import numpy as np

    enc = [tokenizer.encode(p) for p in prompts]
    enc = [ids[:-1] if ids and ids[-1] == tokenizer.eos_token_id else ids
           for ids in enc]
    max_len = max(len(x) for x in enc)
    pad = tokenizer.pad_token_id or 0
    batch = np.full((len(enc), max_len), pad, np.int32)
    mask = np.zeros((len(enc), max_len), np.int32)
    for i, ids in enumerate(enc):
        batch[i, max_len - len(ids):] = ids
        mask[i, max_len - len(ids):] = 1
    out = sample_sequence_batch(
        model, params, jnp.asarray(batch),
        attention_mask=jnp.asarray(mask), max_out_seq=max_out_seq,
        temperature=temperature, top_k=top_k, top_p=top_p,
        eos_token_id=tokenizer.eos_token_id,
        rng=jax.random.PRNGKey(seed))
    return [tokenizer.decode([int(t) for t in row[max_len:]])
            for row in np.asarray(out)]
