"""Delta-weight release tooling.

Port of reference: fengshen/utils/apply_delta.py + make_delta.py — the
Ziya-LLaMA license workaround: published weights are deltas against the
original base model; users apply them locally. Works on flax param pytrees.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np


def make_delta(base_params: Any, target_params: Any) -> Any:
    """delta = target - base (reference: make_delta.py)."""
    return jax.tree_util.tree_map(
        lambda t, b: np.asarray(t, np.float32) - np.asarray(b, np.float32),
        target_params, base_params)


def apply_delta(base_params: Any, delta_params: Any) -> Any:
    """target = base + delta (reference: apply_delta.py)."""
    return jax.tree_util.tree_map(
        lambda b, d: np.asarray(b, np.float32) + np.asarray(d, np.float32),
        base_params, delta_params)
