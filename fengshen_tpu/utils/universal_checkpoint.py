"""UniversalCheckpoint — orbax-backed checkpoint callback.

Port of the reference's Lightning ModelCheckpoint subclass
(reference: fengshen/utils/universal_checkpoint.py:5-41): argparse-configured
monitor/mode/save_top_k/every_n_train_steps/save_ckpt_path/load_ckpt_path,
and the same silently-skip-missing-load behaviour (:38-41).

TPU-native: one LOGICAL checkpoint of sharded arrays (orbax) instead of
per-rank DeepSpeed engine shards — restoring onto a different mesh reshards
automatically, which obsoletes the reference's offline TP reshard tooling
(reference: fengshen/utils/llama_convert/convert_fs_llama_tp.py).
"""

from __future__ import annotations

import argparse
import os
from typing import Any, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp


class CheckpointStructureMismatch(ValueError):
    """The checkpoint's tree/shapes don't match the run's state — a
    config error (wrong model size, wrong directory), not data
    corruption. Surfaced immediately; falling back to older steps would
    fail identically N more times at multi-GB deserialization cost."""


class UniversalCheckpoint:
    @staticmethod
    def add_argparse_args(parent_parser: argparse.ArgumentParser):
        """Reference: universal_checkpoint.py:6-23 (same flag names)."""
        parser = parent_parser.add_argument_group("universal checkpoint")
        parser.add_argument("--monitor", default="step", type=str)
        parser.add_argument("--mode", default="max", type=str)
        parser.add_argument("--save_ckpt_path", default="./ckpt/", type=str)
        parser.add_argument("--load_ckpt_path", default="./ckpt/", type=str)
        parser.add_argument("--filename", default="model-{step:02d}",
                            type=str)
        parser.add_argument("--save_last", action="store_true", default=False)
        parser.add_argument("--save_top_k", default=3, type=int)
        parser.add_argument("--every_n_train_steps", default=None, type=int)
        parser.add_argument("--save_weights_only", action="store_true",
                            default=False)
        parser.add_argument("--every_n_epochs", default=None, type=int)
        parser.add_argument("--save_on_train_epoch_end", action="store_true",
                            default=None)
        parser.add_argument(
            "--async_save", action="store_true", default=False,
            help="orbax async checkpointing: serialization overlaps the "
                 "following train steps instead of blocking (flushed at "
                 "fit end and on preemption). No reference equivalent — "
                 "the reference's Lightning saves block training.")
        return parent_parser

    def __init__(self, args):
        self.args = args
        self.save_path = os.path.abspath(
            getattr(args, "save_ckpt_path", "./ckpt/"))
        self.load_path = getattr(args, "load_ckpt_path", None)
        every_n = getattr(args, "every_n_train_steps", None)
        self.every_n_train_steps = int(every_n) if every_n else 0
        self._manager: Optional[ocp.CheckpointManager] = None

    # -- manager -----------------------------------------------------------
    def _get_manager(self) -> ocp.CheckpointManager:
        if self._manager is None:
            top_k = getattr(self.args, "save_top_k", 3)
            options = ocp.CheckpointManagerOptions(
                max_to_keep=None if top_k in (-1, None) else max(top_k, 1),
                enable_async_checkpointing=bool(
                    getattr(self.args, "async_save", False)))
            self._manager = ocp.CheckpointManager(self.save_path,
                                                  options=options)
        return self._manager

    # -- save ---------------------------------------------------------------
    def save(self, state: Any, trainer: Any, sync: bool = False) -> None:
        """`sync=True` forces a flush (preemption / fit end must not
        lose the in-flight save); with --async_save, periodic saves
        return immediately and serialization overlaps training.

        Idempotent per step: a boundary save and the preemption
        autosave can both fire for the same global step in one loop
        iteration (and a rewind can replay a boundary) — orbax raises
        StepAlreadyExistsError on a re-save, so an already-committed
        step is skipped instead."""
        step = int(trainer.global_step)
        mgr = self._get_manager()
        if sync:
            mgr.wait_until_finished()  # land any in-flight async save
        if step in mgr.all_steps():
            return
        payload = {"params": state.params}
        if not getattr(self.args, "save_weights_only", False):
            payload["opt_state"] = state.opt_state
        meta = {"global_step": step,
                "consumed_samples": int(trainer.consumed_samples),
                "global_samples": int(trainer.consumed_samples)}
        self._get_manager().save(
            step, args=ocp.args.Composite(
                state=ocp.args.StandardSave(payload),
                meta=ocp.args.JsonSave(meta)))
        if sync or not getattr(self.args, "async_save", False):
            self._get_manager().wait_until_finished()
            # verify the commit actually landed (orbax finalizes a step
            # by atomic rename): a save that silently failed must not
            # masquerade as a restore point while older steps get
            # pruned out from under it
            mgr = self._get_manager()
            if hasattr(mgr, "reload"):
                mgr.reload()  # re-read the step list from disk
                committed = mgr.all_steps()
            else:  # pragma: no cover - pre-`reload` orbax
                committed = mgr.all_steps(read=True)
            if step not in committed:
                raise RuntimeError(
                    f"checkpoint step {step} did not commit under "
                    f"{self.save_path}")

    def wait(self) -> None:
        """Flush any in-flight async save."""
        if self._manager is not None:
            self._manager.wait_until_finished()

    # -- restore -------------------------------------------------------------
    def _restore_step(self, mgr: ocp.CheckpointManager, step: int,
                      state: Any, weights_only: bool) -> dict:
        """Restore ONE candidate step (raises on corrupt/partial data).

        What the checkpoint CONTAINS (not what this run's flags say)
        decides whether opt_state is restored: a weights-only
        checkpoint loaded into a full run must silently fall back to
        the freshly initialized optimizer state, and vice versa —
        matching the reference's silent-skip semantics (reference:
        universal_checkpoint.py:38-41)."""
        def _restore(with_opt: bool):
            payload = {"params": state.params}
            if with_opt:
                payload["opt_state"] = state.opt_state
            abstract = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=(
                    x.sharding if hasattr(x, "sharding") else None)),
                payload)
            return mgr.restore(
                step, args=ocp.args.Composite(
                    state=ocp.args.StandardRestore(abstract),
                    meta=ocp.args.JsonRestore()))

        if weights_only:
            # The eval path carries a zero-size optimizer, so the
            # payload cannot describe the on-disk opt_state; restore the
            # params SUBTREE only (no adam-moment deserialisation)
            abstract = {"params": jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(
                    x.shape, x.dtype,
                    sharding=getattr(x, "sharding", None)),
                state.params)}
            try:
                pytree_args = ocp.args.PyTreeRestore(
                    item=abstract, partial_restore=True)
            except TypeError:
                # older orbax (<0.9) spells partial restore as empty
                # `transforms` + per-leaf restore_args
                def _rarg(x):
                    sharding = getattr(x, "sharding", None)
                    if sharding is not None:
                        return ocp.ArrayRestoreArgs(
                            sharding=sharding, global_shape=x.shape,
                            dtype=x.dtype)
                    return ocp.RestoreArgs()

                pytree_args = ocp.args.PyTreeRestore(
                    item=abstract, transforms={},
                    restore_args=jax.tree_util.tree_map(_rarg, abstract))
            try:
                return mgr.restore(
                    step, args=ocp.args.Composite(
                        state=pytree_args,
                        meta=ocp.args.JsonRestore()))
            except ValueError as e:
                # same classification as the full path: a wrong-model
                # eval restore must fast-fail, corrupt data falls back
                if self._params_mismatch(mgr, step, state):
                    raise CheckpointStructureMismatch(str(e)) from e
                raise
        try:
            return _restore(with_opt=True)
        except ValueError as e:
            if "opt_state" in str(e):
                try:
                    return _restore(with_opt=False)
                except ValueError as e2:
                    e = e2
            # a genuine mismatch (param shapes/tree — wrong model
            # config or wrong directory) must surface, not silently
            # reset the optimizer and not trigger the corrupt-step
            # fallback; confirmed against the checkpoint METADATA,
            # because corrupt payloads also raise ValueError and those
            # must keep falling back to older steps
            if self._params_mismatch(mgr, step, state):
                raise CheckpointStructureMismatch(str(e)) from e
            raise e

    @staticmethod
    def _params_mismatch(mgr: ocp.CheckpointManager, step: int,
                         state: Any) -> bool:
        """Does the saved params tree structurally differ from the
        run's? Decided from the (cheap) checkpoint metadata; any
        failure reading it means the step is corrupt, which is NOT a
        structure mismatch."""
        def key_meta(tree):
            return {jax.tree_util.keystr(path):
                    (tuple(getattr(leaf, "shape", ())),
                     getattr(leaf, "dtype", None))
                    for path, leaf in
                    jax.tree_util.tree_flatten_with_path(tree)[0]}

        try:
            meta = mgr.item_metadata(step)
            saved = meta.get("state") if hasattr(meta, "get") else \
                getattr(meta, "state", None)
            want = key_meta(state.params)
            got = key_meta(saved["params"])
            if want.keys() != got.keys():
                return True
            for k, (shape_w, dtype_w) in want.items():
                shape_g, dtype_g = got[k]
                if shape_w != shape_g:
                    return True
                # dtype None on either side = metadata didn't record
                # it; only a confirmed disagreement is structural
                if dtype_w is not None and dtype_g is not None and \
                        jax.numpy.dtype(dtype_w) != jax.numpy.dtype(
                            dtype_g):
                    return True
            return False
        except Exception:  # noqa: BLE001 — unreadable metadata =
            # corrupt step, handled by the caller's fallback walk
            return False

    def maybe_restore(self, state: Any, trainer: Any,
                      weights_only: bool = False) -> Any:
        """Silently skip a missing load path, exactly like the reference
        (reference: universal_checkpoint.py:38-41). `weights_only` skips
        the optimizer moments entirely — the eval-only entry restores
        into a zero-size optimizer state.

        Integrity fallback (docs/fault_tolerance.md): candidate steps
        are tried newest→oldest, and a step whose restore raises
        (truncated/corrupt payload on a preempted or bit-rotted write)
        is rejected with a logged `checkpoint_restore_rejected` event
        instead of killing the run. Only when EVERY step is
        unrestorable does the error surface — silently training a 10B
        run from scratch would be worse than crashing."""
        path = self.load_path
        if not path or not os.path.isdir(path):
            return state
        path = os.path.abspath(path)
        # reuse the save-side manager when load and save point at the
        # same directory: a second CheckpointManager on one path races
        # an in-flight --async_save write
        mgr = self._get_manager() if path == self.save_path \
            else ocp.CheckpointManager(path)
        steps = sorted(mgr.all_steps(), reverse=True)
        if not steps:
            return state
        log = getattr(trainer, "_log", None) or (lambda entry: None)
        restored, errors = None, []
        for step in steps:
            try:
                restored = self._restore_step(mgr, step, state,
                                              weights_only)
                break
            except CheckpointStructureMismatch:
                raise  # config error, identical on every step
            except Exception as e:  # noqa: BLE001 — corrupt/partial
                # step: log, fall back to the previous one
                errors.append((step, e))
                log({"event": "checkpoint_restore_rejected",
                     "ckpt_step": int(step),
                     "error": f"{type(e).__name__}: {str(e)[:200]}"})
        if restored is None:
            detail = "; ".join(
                f"step {s}: {type(e).__name__}: {str(e)[:120]}"
                for s, e in errors)
            raise RuntimeError(
                f"no restorable checkpoint under {path} ({detail})")
        if errors and path == self.save_path:
            # we OWN this directory: drop the unrestorable steps so the
            # run can re-save past them — left in place, a corrupt
            # newest step would shadow every later boundary save (the
            # idempotent-save guard skips committed steps) and re-lose
            # the same window on every future restore
            for bad_step, _ in errors:
                try:
                    mgr.delete(bad_step)
                    log({"event": "checkpoint_rejected_deleted",
                         "ckpt_step": int(bad_step)})
                except Exception as e:  # noqa: BLE001 — best-effort
                    # cleanup; the restore itself already succeeded
                    log({"event": "checkpoint_delete_failed",
                         "ckpt_step": int(bad_step),
                         "error": str(e)[:200]})
        meta = restored["meta"]
        # restore loop counters the way the reference's on_load_checkpoint
        # does (reference: examples/pretrain_erlangshen_bert/
        # pretrain_erlangshen.py:192-197)
        trainer.global_step = int(meta["global_step"])
        trainer.consumed_samples = int(meta["consumed_samples"])
        new = state.replace(params=restored["state"]["params"],
                            step=jax.numpy.asarray(meta["global_step"],
                                                   jax.numpy.int32))
        if "opt_state" in restored["state"]:
            new = new.replace(opt_state=restored["state"]["opt_state"])
        return new

    # -- trainer hooks --------------------------------------------------------
    def on_train_step_end(self, trainer: Any, state: Any) -> None:
        if not self.every_n_train_steps:
            return
        # boundary-CROSSING, not equality: under --steps_per_execution K
        # global_step advances K at a time and can jump over the exact
        # multiple (trainer sets prev_global_step per execution)
        prev = int(getattr(trainer, "prev_global_step",
                           trainer.global_step - 1))
        if (trainer.global_step // self.every_n_train_steps) > \
                (prev // self.every_n_train_steps):
            self.save(state, trainer)

    def on_fit_end(self, trainer: Any, state: Any) -> None:
        if getattr(self.args, "save_last", False) or \
                not self.every_n_train_steps:
            self.save(state, trainer, sync=True)
        else:
            self.wait()
