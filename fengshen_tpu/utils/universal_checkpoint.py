"""UniversalCheckpoint — orbax-backed checkpoint callback.

Port of the reference's Lightning ModelCheckpoint subclass
(reference: fengshen/utils/universal_checkpoint.py:5-41): argparse-configured
monitor/mode/save_top_k/every_n_train_steps/save_ckpt_path/load_ckpt_path,
and the same silently-skip-missing-load behaviour (:38-41).

TPU-native: one LOGICAL checkpoint of sharded arrays (orbax) instead of
per-rank DeepSpeed engine shards — restoring onto a different mesh reshards
automatically, which obsoletes the reference's offline TP reshard tooling
(reference: fengshen/utils/llama_convert/convert_fs_llama_tp.py).
"""

from __future__ import annotations

import argparse
import os
from typing import Any, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp


class UniversalCheckpoint:
    @staticmethod
    def add_argparse_args(parent_parser: argparse.ArgumentParser):
        """Reference: universal_checkpoint.py:6-23 (same flag names)."""
        parser = parent_parser.add_argument_group("universal checkpoint")
        parser.add_argument("--monitor", default="step", type=str)
        parser.add_argument("--mode", default="max", type=str)
        parser.add_argument("--save_ckpt_path", default="./ckpt/", type=str)
        parser.add_argument("--load_ckpt_path", default="./ckpt/", type=str)
        parser.add_argument("--filename", default="model-{step:02d}",
                            type=str)
        parser.add_argument("--save_last", action="store_true", default=False)
        parser.add_argument("--save_top_k", default=3, type=int)
        parser.add_argument("--every_n_train_steps", default=None, type=int)
        parser.add_argument("--save_weights_only", action="store_true",
                            default=False)
        parser.add_argument("--every_n_epochs", default=None, type=int)
        parser.add_argument("--save_on_train_epoch_end", action="store_true",
                            default=None)
        parser.add_argument(
            "--async_save", action="store_true", default=False,
            help="orbax async checkpointing: serialization overlaps the "
                 "following train steps instead of blocking (flushed at "
                 "fit end and on preemption). No reference equivalent — "
                 "the reference's Lightning saves block training.")
        return parent_parser

    def __init__(self, args):
        self.args = args
        self.save_path = os.path.abspath(
            getattr(args, "save_ckpt_path", "./ckpt/"))
        self.load_path = getattr(args, "load_ckpt_path", None)
        every_n = getattr(args, "every_n_train_steps", None)
        self.every_n_train_steps = int(every_n) if every_n else 0
        self._manager: Optional[ocp.CheckpointManager] = None

    # -- manager -----------------------------------------------------------
    def _get_manager(self) -> ocp.CheckpointManager:
        if self._manager is None:
            top_k = getattr(self.args, "save_top_k", 3)
            options = ocp.CheckpointManagerOptions(
                max_to_keep=None if top_k in (-1, None) else max(top_k, 1),
                enable_async_checkpointing=bool(
                    getattr(self.args, "async_save", False)))
            self._manager = ocp.CheckpointManager(self.save_path,
                                                  options=options)
        return self._manager

    # -- save ---------------------------------------------------------------
    def save(self, state: Any, trainer: Any, sync: bool = False) -> None:
        """`sync=True` forces a flush (preemption / fit end must not
        lose the in-flight save); with --async_save, periodic saves
        return immediately and serialization overlaps training."""
        step = int(trainer.global_step)
        payload = {"params": state.params}
        if not getattr(self.args, "save_weights_only", False):
            payload["opt_state"] = state.opt_state
        meta = {"global_step": step,
                "consumed_samples": int(trainer.consumed_samples),
                "global_samples": int(trainer.consumed_samples)}
        self._get_manager().save(
            step, args=ocp.args.Composite(
                state=ocp.args.StandardSave(payload),
                meta=ocp.args.JsonSave(meta)))
        if sync or not getattr(self.args, "async_save", False):
            self._get_manager().wait_until_finished()

    def wait(self) -> None:
        """Flush any in-flight async save."""
        if self._manager is not None:
            self._manager.wait_until_finished()

    # -- restore -------------------------------------------------------------
    def maybe_restore(self, state: Any, trainer: Any,
                      weights_only: bool = False) -> Any:
        """Silently skip a missing load path, exactly like the reference
        (reference: universal_checkpoint.py:38-41). `weights_only` skips
        the optimizer moments entirely — the eval-only entry restores
        into a zero-size optimizer state."""
        path = self.load_path
        if not path or not os.path.isdir(path):
            return state
        mgr = ocp.CheckpointManager(os.path.abspath(path))
        step = mgr.latest_step()
        if step is None:
            return state

        def _restore(with_opt: bool):
            payload = {"params": state.params}
            if with_opt:
                payload["opt_state"] = state.opt_state
            abstract = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=(
                    x.sharding if hasattr(x, "sharding") else None)),
                payload)
            return mgr.restore(
                step, args=ocp.args.Composite(
                    state=ocp.args.StandardRestore(abstract),
                    meta=ocp.args.JsonRestore()))

        # What the checkpoint CONTAINS (not what this run's flags say) decides
        # whether opt_state is restored: a weights-only checkpoint loaded into
        # a full run must silently fall back to the freshly initialized
        # optimizer state, and vice versa — matching the reference's
        # silent-skip semantics (reference: universal_checkpoint.py:38-41).
        if weights_only:
            # The eval path carries a zero-size optimizer, so the
            # payload cannot describe the on-disk opt_state; restore the
            # params SUBTREE only (no adam-moment deserialisation)
            abstract = {"params": jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(
                    x.shape, x.dtype,
                    sharding=getattr(x, "sharding", None)),
                state.params)}
            restored = mgr.restore(
                step, args=ocp.args.Composite(
                    state=ocp.args.PyTreeRestore(item=abstract,
                                                 partial_restore=True),
                    meta=ocp.args.JsonRestore()))
        else:
            try:
                restored = _restore(with_opt=True)
            except ValueError as e:
                if "opt_state" not in str(e):
                    # a genuine mismatch elsewhere (param shapes/tree)
                    # must surface, not silently reset the optimizer
                    raise
                restored = _restore(with_opt=False)
        meta = restored["meta"]
        # restore loop counters the way the reference's on_load_checkpoint
        # does (reference: examples/pretrain_erlangshen_bert/
        # pretrain_erlangshen.py:192-197)
        trainer.global_step = int(meta["global_step"])
        trainer.consumed_samples = int(meta["consumed_samples"])
        new = state.replace(params=restored["state"]["params"],
                            step=jax.numpy.asarray(meta["global_step"],
                                                   jax.numpy.int32))
        if "opt_state" in restored["state"]:
            new = new.replace(opt_state=restored["state"]["opt_state"])
        return new

    # -- trainer hooks --------------------------------------------------------
    def on_train_step_end(self, trainer: Any, state: Any) -> None:
        if not self.every_n_train_steps:
            return
        # boundary-CROSSING, not equality: under --steps_per_execution K
        # global_step advances K at a time and can jump over the exact
        # multiple (trainer sets prev_global_step per execution)
        prev = int(getattr(trainer, "prev_global_step",
                           trainer.global_step - 1))
        if (trainer.global_step // self.every_n_train_steps) > \
                (prev // self.every_n_train_steps):
            self.save(state, trainer)

    def on_fit_end(self, trainer: Any, state: Any) -> None:
        if getattr(self.args, "save_last", False) or \
                not self.every_n_train_steps:
            self.save(state, trainer, sync=True)
        else:
            self.wait()
