"""Original-TensorFlow BERT checkpoint → flax params, directly.

Replaces the reference's TF→torch conversion script (reference:
fengshen/utils/convert_tf_checkpoint_to_pytorch.py:1-62 — a wrapper
over HF `load_tf_weights_in_bert` that materializes a torch model just
to re-serialize it). TPU-first version: read the checkpoint variables
with `tf.train.load_checkpoint` and map the original google-research
BERT naming straight onto the flax tree — TF kernels are already
[in, out] like flax Dense, so no transposes at all.

Variable naming (google-research/bert):
    bert/embeddings/{word,position,token_type}_embeddings
    bert/embeddings/LayerNorm/{gamma,beta}
    bert/encoder/layer_N/attention/self/{query,key,value}/{kernel,bias}
    bert/encoder/layer_N/attention/output/dense/…  + LayerNorm
    bert/encoder/layer_N/{intermediate,output}/dense/… + output/LayerNorm
    bert/pooler/dense/{kernel,bias}
    cls/predictions/transform/{dense,LayerNorm}/… + output_bias
"""

from __future__ import annotations

import numpy as np


def tf_bert_checkpoint_to_params(ckpt_path: str, config) -> dict:
    """TF checkpoint path (the `model.ckpt` prefix) → the same tree
    `models/bert/convert.torch_to_params` produces: {"bert": …} plus the
    MLM transform head when present."""
    import tensorflow as tf

    reader = tf.train.load_checkpoint(ckpt_path)
    names = set(reader.get_variable_to_shape_map())

    def t(name):
        if name not in names:
            raise KeyError(
                f"variable {name!r} not in TF checkpoint {ckpt_path} "
                f"(has {sorted(names)[:5]}…)")
        return np.asarray(reader.get_tensor(name))

    def lin(prefix):
        return {"kernel": t(f"{prefix}/kernel"),
                "bias": t(f"{prefix}/bias")}

    def ln(prefix):
        return {"scale": t(f"{prefix}/gamma"), "bias": t(f"{prefix}/beta")}

    bert = {
        "word_embeddings": {
            "embedding": t("bert/embeddings/word_embeddings")},
        "position_embeddings": {
            "embedding": t("bert/embeddings/position_embeddings")},
        "token_type_embeddings": {
            "embedding": t("bert/embeddings/token_type_embeddings")},
        "embeddings_ln": ln("bert/embeddings/LayerNorm"),
    }
    for i in range(config.num_hidden_layers):
        p = f"bert/encoder/layer_{i}"
        bert[f"layer_{i}"] = {
            "query": lin(f"{p}/attention/self/query"),
            "key": lin(f"{p}/attention/self/key"),
            "value": lin(f"{p}/attention/self/value"),
            "attention_output_dense": lin(f"{p}/attention/output/dense"),
            "attention_ln": ln(f"{p}/attention/output/LayerNorm"),
            "intermediate_dense": lin(f"{p}/intermediate/dense"),
            "output_dense": lin(f"{p}/output/dense"),
            "output_ln": ln(f"{p}/output/LayerNorm"),
        }
    if "bert/pooler/dense/kernel" in names:
        bert["pooler"] = lin("bert/pooler/dense")
    params: dict = {"bert": bert}
    if "cls/predictions/transform/dense/kernel" in names:
        params["transform_dense"] = lin("cls/predictions/transform/dense")
        params["transform_ln"] = ln("cls/predictions/transform/LayerNorm")
        params["bias"] = t("cls/predictions/output_bias")
    return params


def main(argv=None):
    """CLI analog of the reference script: TF checkpoint → ONE logical
    orbax checkpoint (no intermediate torch bin)."""
    import argparse
    import os

    parser = argparse.ArgumentParser("tf-bert -> fengshen-tpu convert")
    parser.add_argument("--tf_checkpoint_path", required=True, type=str)
    parser.add_argument("--bert_config_file", required=True, type=str)
    parser.add_argument("--output_path", required=True, type=str)
    args = parser.parse_args(argv)

    from fengshen_tpu.models.bert import BertConfig

    # the google-research layout names the file bert_config.json, so
    # pass the FILE path through (from_pretrained handles files; a
    # dirname would make it look for config.json and miss)
    config = BertConfig.from_pretrained(args.bert_config_file)
    params = tf_bert_checkpoint_to_params(args.tf_checkpoint_path, config)

    import orbax.checkpoint as ocp
    os.makedirs(args.output_path, exist_ok=True)
    config.save_pretrained(args.output_path)
    ckpt = ocp.StandardCheckpointer()
    ckpt.save(os.path.abspath(os.path.join(args.output_path, "params")),
              params, force=True)
    ckpt.wait_until_finished()
    print(f"converted -> {args.output_path}")


if __name__ == "__main__":
    main()
