"""Chinese text helpers (reference: fengshen/utils/utils.py:6-56)."""

from __future__ import annotations

_CJK_RANGES = (
    (0x4E00, 0x9FFF), (0x3400, 0x4DBF), (0x20000, 0x2A6DF),
    (0x2A700, 0x2B73F), (0x2B740, 0x2B81F), (0x2B820, 0x2CEAF),
    (0xF900, 0xFAFF), (0x2F800, 0x2FA1F),
)


def is_chinese_char(cp: int) -> bool:
    """CJK codepoint check (reference: utils.py:20-38 — the BERT ranges)."""
    return any(lo <= cp <= hi for lo, hi in _CJK_RANGES)


def chinese_char_tokenize(line: str) -> str:
    """Insert spaces around CJK chars so a word tokenizer splits them
    (reference: utils.py:41-56)."""
    out = []
    for ch in line:
        if is_chinese_char(ord(ch)):
            out.append(" ")
            out.append(ch)
            out.append(" ")
        else:
            out.append(ch)
    return "".join(out)
