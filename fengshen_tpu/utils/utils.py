"""Misc runtime utilities.

Reference: fengshen/utils/utils.py — `report_memory` (cuda
allocated/reserved printout, :62-74) becomes a jax live-buffer/HBM report;
jieba helpers live in fengshen_tpu.utils.chinese.
"""

from __future__ import annotations

import jax


def report_memory(name: str = "") -> dict:
    """Device-memory snapshot (reference: utils.py:62-74). Returns and
    prints per-device bytes-in-use when the backend exposes memory stats
    (TPU does; CPU returns zeros)."""
    stats = {}
    for dev in jax.local_devices():
        mem = getattr(dev, "memory_stats", lambda: None)()
        if mem:
            stats[str(dev)] = {
                "bytes_in_use": mem.get("bytes_in_use", 0),
                "peak_bytes_in_use": mem.get("peak_bytes_in_use", 0),
                "bytes_limit": mem.get("bytes_limit", 0),
            }
        else:
            stats[str(dev)] = {"bytes_in_use": 0, "peak_bytes_in_use": 0,
                               "bytes_limit": 0}
    total = sum(s["bytes_in_use"] for s in stats.values())
    print(f"[report_memory]{' ' + name if name else ''} "
          f"total={total / 2**30:.2f}GiB over {len(stats)} device(s)",
          flush=True)
    return stats


def start_profiler_trace(logdir: str) -> None:
    """jax.profiler trace start — the observability the reference lacked
    (SURVEY.md §5.1: wandb only, no profiler)."""
    jax.profiler.start_trace(logdir)


def stop_profiler_trace() -> None:
    jax.profiler.stop_trace()
