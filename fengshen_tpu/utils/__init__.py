"""Shared utilities (reference: fengshen/utils/)."""

from fengshen_tpu.utils.universal_checkpoint import UniversalCheckpoint
from fengshen_tpu.utils.generate import (top_k_logits, top_p_logits,
                                         sample_sequence_batch, generate,
                                         seq2seq_generate,
                                         seq2seq_beam_search)
from fengshen_tpu.utils.chinese import chinese_char_tokenize, is_chinese_char

__all__ = ["UniversalCheckpoint", "top_k_logits", "top_p_logits",
           "sample_sequence_batch", "generate", "seq2seq_generate",
           "seq2seq_beam_search", "chinese_char_tokenize",
           "is_chinese_char"]
