"""REST serving: JSON config → pipeline → FastAPI POST endpoint.

Port of reference: fengshen/API/main.py:12-75 + API/utils.py — a config
file names the task/model/server options; the server instantiates the
matching pipeline and exposes `POST /api/<task>`; CORS enabled; run with
uvicorn. FastAPI/uvicorn are optional deps — gated at call time.

    python -m fengshen_tpu.api.main --config text_classification.json

Beyond the reference: `"engine": "continuous"` in the SERVER block
routes generation tasks through the continuous-batching engine
(`fengshen_tpu/serving/`, docs/serving.md) — many concurrent requests
share ONE jitted decode step; the optional ENGINE block holds
`serving.EngineConfig` overrides (num_slots, buckets, max_queue, …,
plus the KV-pool physicals `kv_layout: "slot"|"paged"`,
`kv_dtype: "fp32"|"int8"`, `kv_block_size`, `kv_num_blocks` — the
paged/int8 pool serves ≥2x the concurrent requests per KV byte, see
docs/serving.md "Paged KV cache" — and the speculative-decode knobs
`spec_mode: "off"|"prompt_lookup"`, `spec_gamma`, `spec_ngram` — the
draft/verify tick commits >1 token per weight stream on repetitive
text, docs/serving.md "Speculative decoding"), and the optional AOT
block (`{"cache_dir": ...}`, docs/aot_cache.md) routes every engine
compile through the persistent executable cache so a restarted replica
deserializes instead of recompiling (the KV and spec knobs join the
cache key). `GET /stats` includes the KV-pool utilization (blocks
total/used/free, bytes, fragmentation, layout/dtype) alongside the
engine metrics, plus — on a spec engine only, so the non-spec payload
shape never churns — `spec_mode`/`spec_gamma`/`spec_drafted_total`/
`spec_accepted_total`/`spec_acceptance_rate`.

Both engines get warmed at startup so the first user never pays jit
compilation — warmup runs in a BACKGROUND thread while the server is
already listening, and `GET /healthz` answers 503 until it completes
(load balancers must not route to a still-compiling replica) and 200
after. `GET /stats` exposes the engine metrics as JSON (now incl.
`uptime_s` and `last_error` — type + age, never a traceback) and
`GET /metrics` renders the same registry (plus the process-global one —
HTTP counters, `fstpu_http_request_seconds{route}` latency histograms,
span timings, `fstpu_warmup_seconds{phase}`, `fstpu_build_info`) as
Prometheus text exposition, on BOTH the fastapi and the stdlib server
paths (docs/observability.md).

Debug introspection (docs/serving.md "Debug endpoints"), again on both
paths: `GET /debug/requests` lists in-flight + recently finished
request summaries, `GET /debug/requests/<id>` returns one request's
full lifecycle timeline and latency waterfall (queue wait / prefill /
decode phases), and `POST /debug/dump` writes the flight recorder's
post-mortem bundle on demand (docs/observability.md "Flight
recorder"). `main()` wires a `FlightRecorder` through the engine and
chains it onto SIGTERM, so a drained/killed replica leaves a bundle
behind.

Fleet composition (ISSUE 10, docs/fleet.md): N replicas of this server
compose behind `python -m fengshen_tpu.fleet`. The replica-side
contract lives here — `/healthz` 503 bodies carry `{"ready": false,
"reason": "warmup"|"draining"}` so the router can tell the way IN from
the way OUT; SIGTERM triggers a graceful drain (`install_drain_handler`:
admission stops, in-flight requests finish, then the process exits)
instead of immediate death; and a request body may carry a
`request_id`, which the engine DEDUPES (409 on a live duplicate) so
the router's retry-on-another-replica is idempotent-safe.
"""

from __future__ import annotations

import argparse
import dataclasses
import importlib
import json
import time
from typing import Any, Optional


@dataclasses.dataclass
class ServerConfig:
    """Reference: fengshen/API/utils.py config dataclasses, plus the
    serving-engine selection ("simple" = one pipeline call per POST;
    "continuous" = slot-pool continuous batching; "batch_image" /
    "embedding" = micro-batched multimodal engines,
    docs/serving.md "Multimodal engines")."""

    host: str = "0.0.0.0"
    port: int = 8000
    log_level: str = "info"
    engine: str = "simple"
    warmup: bool = True
    request_timeout_s: float = 120.0
    # prefill/decode disaggregation role (docs/disaggregation.md):
    # "prefill" | "decode" | "both". Surfaced in /stats so the fleet
    # router's phase-aware placement can split the two tiers; "both"
    # keeps the replica in the homogeneous rotation.
    phase: str = "both"
    # SIGTERM drain (docs/fleet.md "Drain runbook"): how long the
    # stdlib server waits for in-flight requests before shutting down
    drain_timeout_s: float = 30.0
    # live-evacuation peers (docs/fault_tolerance.md "Preemption
    # runbook"): base urls of sibling replicas this replica may push
    # its in-flight lanes to when a drain begins; empty = every lane
    # finishes locally (the pre-evacuation drain behavior)
    peers: tuple = ()
    # flight-recorder post-mortem bundles (POST /debug/dump, engine
    # tick errors, SIGTERM) land here (docs/observability.md)
    dump_dir: str = "fstpu_dumps"
    engine_args: dict = dataclasses.field(default_factory=dict)
    aot_args: dict = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if self.engine not in ("simple", "continuous", "batch_image",
                               "embedding"):
            # a typo must fail at startup, not silently serve the
            # batch-1 legacy path under a continuous-looking config
            raise ValueError(f"unknown engine {self.engine!r}; expected "
                             "'simple', 'continuous', 'batch_image' or "
                             "'embedding'")
        from fengshen_tpu.disagg.policy import validate_phase
        self.phase = validate_phase(self.phase)
        self.peers = tuple(str(p).rstrip("/")
                           for p in (self.peers or ()) if str(p).strip())


@dataclasses.dataclass
class PipelineConfig:
    task: str = "text_classification"
    model: Optional[str] = None
    pipeline_args: dict = dataclasses.field(default_factory=dict)


def load_config(path: str) -> tuple[ServerConfig, PipelineConfig]:
    with open(path) as f:
        raw = json.load(f)
    server = ServerConfig(**raw.get("SERVER", {}))
    server.engine_args = dict(raw.get("ENGINE", {}))
    server.aot_args = dict(raw.get("AOT", {}))
    pipeline = PipelineConfig(
        task=raw.get("PIPELINE", {}).get("task", "text_classification"),
        model=raw.get("PIPELINE", {}).get("model"),
        pipeline_args={k: v for k, v in raw.get("PIPELINE", {}).items()
                       if k not in ("task", "model")})
    return server, pipeline


def _healthz_payload(task: str, ready, draining) -> tuple[int, dict]:
    """The readiness contract BOTH server paths answer (pinned by
    tests): 503 with `{"ready": false, "reason": "warmup"|"draining"}`
    while the replica must not receive traffic, 200 with
    `{"ready": true}` otherwise. The legacy `status` key stays for
    pre-fleet monitors; the fleet router keys on `reason`."""
    if draining is not None and draining.is_set():
        return 503, {"status": "draining", "task": task,
                     "ready": False, "reason": "draining"}
    if ready is not None and not ready.is_set():
        return 503, {"status": "warming", "task": task,
                     "ready": False, "reason": "warmup"}
    return 200, {"status": "ok", "task": task, "ready": True}


def _render_metrics(engine=None, disagg=None) -> str:
    """Prometheus text over the process-global registry plus (when the
    continuous engine is up) the engine's own registry and the disagg
    coordinator's (`fstpu_disagg_*`); `engine.stats()` runs first so
    the pool gauges are scrape-fresh."""
    from fengshen_tpu.observability import get_registry, render_prometheus
    registries = [get_registry()]
    if engine is not None:
        engine.stats()
        # micro-batch engines count through the global registry and
        # have no engine-local one
        if getattr(engine, "metrics", None) is not None:
            registries.append(engine.metrics.registry)
    if disagg is not None:
        registries.append(disagg.registry)
    return render_prometheus(*registries)


def _count_http(route: str, code: int) -> None:
    """`fstpu_http_requests_total{route,code}` in the global registry.
    Routes are the fixed server surface (bounded label cardinality);
    anything else counts as "other"."""
    from fengshen_tpu.observability.httpmetrics import http_requests_total
    http_requests_total().labels(route, code).inc()


def _observe_http(route: str, seconds: float) -> None:
    """`fstpu_http_request_seconds{route}` beside the counter: the
    request-latency histogram both API paths feed (docs/observability.md)."""
    from fengshen_tpu.observability.httpmetrics import http_request_seconds
    http_request_seconds().labels(route).observe(seconds)


def _classify_route(path: str, api_route: str) -> str:
    if path.startswith("/debug/requests/"):
        # one label for every id — request ids must not become a
        # per-request label cardinality leak
        return "/debug/requests/<id>"
    if path.startswith("/kv/"):
        # KV-handoff endpoints (docs/disaggregation.md), same
        # cardinality rule as the debug routes
        return "/kv/<id>"
    if path.startswith("/partial/"):
        # commit-journal endpoint (docs/fault_tolerance.md "Preemption
        # runbook"), same cardinality rule
        return "/partial/<id>"
    return path if path in (api_route, f"{api_route}/stream",
                            "/healthz", "/stats", "/metrics",
                            "/debug/requests", "/debug/dump") else "other"


def _dump_recorder(recorder, engine, reason: str = "on_demand") -> str:
    """POST /debug/dump: refresh a metrics snapshot into the ring, then
    write the bundle; returns its path."""
    from fengshen_tpu.observability import get_registry
    registries = [get_registry()]
    if engine is not None:
        engine.stats()      # gauges scrape-fresh, like /metrics
        if getattr(engine, "metrics", None) is not None:
            registries.append(engine.metrics.registry)
    recorder.snapshot_metrics(registries, force=True)
    return recorder.dump(reason=reason)


def _partial_payload(engine, pipeline, request_id: str) \
        -> tuple[int, dict]:
    """`GET /partial/<id>`: the commit journal's view of one request —
    the committed-token prefix the fleet router resumes a maybe-executed
    retry from after a replica death (`resume_tokens`,
    docs/fault_tolerance.md "Preemption runbook"). 404 when this
    replica never journaled the id (or runs the simple engine). A
    finished entry additionally carries the decoded `result` so the
    router can answer the client without any resubmit. Micro-batch
    engines have no commit journal — same 404 as the simple path."""
    d = engine.partial(request_id) \
        if engine is not None and hasattr(engine, "partial") else None
    if d is None:
        return 404, {"error": f"unknown request_id {request_id!r}"}
    if d.get("state") == "finished" and pipeline is not None:
        d = dict(d, result=pipeline.decode(d["tokens"]))
    return 200, d


def _debug_requests_payload(engine) -> dict:
    if engine is None or not hasattr(engine, "debug_requests"):
        # the simple path (and the micro-batch engines) have no
        # request-lifecycle ring to introspect; keep the payload shape
        # so dashboards need no engine-type branch
        return {"in_flight": [], "recent": [], "debug_ring": 0}
    return engine.debug_requests()


def _accepts_max_new_tokens(pipeline) -> bool:
    """Only generation pipelines take the per-request cap — forwarding
    it to a classification pipeline would turn a client field into a
    TypeError 500."""
    import inspect
    try:
        return "max_new_tokens" in inspect.signature(pipeline).parameters
    except (TypeError, ValueError):
        return False


def warmup_pipeline(pipeline, task: str) -> Optional[float]:
    """Issue one warmup request through the legacy path so the first
    user request doesn't pay jit compilation; returns seconds (None on
    failure — a broken warmup must not keep the server down)."""
    from fengshen_tpu.observability import record_warmup_seconds
    t0 = time.perf_counter()
    try:
        pipeline("warmup")
    except Exception as e:  # noqa: BLE001 — warmup is best-effort
        print(f"[serving] warmup request failed ({e}); first real "
              "request will compile", flush=True)
        return None
    dt = time.perf_counter() - t0
    record_warmup_seconds("pipeline", dt)
    print(f"[serving] warmup request for '{task}' compiled+ran in "
          f"{dt:.1f}s", flush=True)
    return dt


def create_continuous_engine(pipeline, engine_args: dict,
                             aot_args: Optional[dict] = None, log=None,
                             recorder=None):
    """Build (but do not warm or start) the continuous-batching engine;
    `aot_args` is the AOT config block — when it names a cache_dir, the
    engine's programs route through the persistent executable cache
    (docs/aot_cache.md). `recorder` is an optional
    `observability.FlightRecorder` the engine feeds its event stream
    into and dumps through on tick errors."""
    from fengshen_tpu.serving import (ContinuousBatchingEngine,
                                      EngineConfig)
    if not hasattr(pipeline, "engine_config_kwargs"):
        raise ValueError(
            "engine 'continuous' needs a generation pipeline exposing "
            "module/params/engine_config_kwargs (task "
            "'text_generation'), not a per-call classification "
            "pipeline")
    aot = None
    if aot_args and aot_args.get("cache_dir"):
        from fengshen_tpu.aot import AotConfig, AotSetup
        aot = AotSetup(AotConfig(**aot_args), log=log)
    kwargs = {**pipeline.engine_config_kwargs(), **engine_args}
    return ContinuousBatchingEngine(
        pipeline.module, pipeline.params, EngineConfig(**kwargs),
        log=log, aot=aot, recorder=recorder)


def start_continuous_engine(pipeline, engine_args: dict, log=None,
                            aot_args: Optional[dict] = None,
                            recorder=None):
    """Build, warm up (compile all prefill buckets + the decode step,
    logging the time), and start the continuous-batching engine."""
    engine = create_continuous_engine(pipeline, engine_args,
                                      aot_args=aot_args, log=log,
                                      recorder=recorder)
    dt = engine.warmup()
    print(f"[serving] continuous engine warmup "
          f"(buckets={list(engine.ladder.buckets)}, "
          f"num_slots={engine.config.num_slots}) compiled in {dt:.1f}s",
          flush=True)
    engine.start()
    return engine


def _engine_generate(engine, pipeline, req: dict, timeout_s: float,
                     disagg=None) -> tuple[int, dict]:
    """Submit one HTTP request to the engine; returns (status, body).
    Backpressure maps to HTTP: queue full → 429, prompt too long → 413,
    engine timeout/eviction → 503, draining replica → 503 with reason,
    duplicate request_id → 409 (the fleet router's idempotent-safe
    retry contract, docs/fleet.md). A `traceparent` (body field, or the
    HTTP header lifted into the body by the server layer) flows into
    `engine.submit` so the request's timeline and debug-ring entry
    carry the fleet trace ids (docs/observability.md "Distributed
    tracing"); traced responses echo `trace_id` back.

    When the fleet router tagged the body with a `disagg_push_to`
    target and a `disagg` coordinator is wired, the primed lane is
    handed to that decode replica and the 200 body is a
    `disagg_redirect` marker the router collects from the peer
    (docs/disaggregation.md). A failed handoff falls through to the
    plain local wait below — never a client-visible error."""
    from fengshen_tpu.observability import parse_traceparent
    from fengshen_tpu.serving import (FINISHED, Draining,
                                      DuplicateRequest, PromptTooLong,
                                      QueueFull)
    from fengshen_tpu.serving.handoff import EVACUATED
    rid = req.get("request_id")
    ctx = parse_traceparent(req.get("traceparent"))

    def _body(payload: dict) -> dict:
        # only traced requests grow the trace_id key: the untraced
        # response shape stays byte-identical to the pre-trace one
        if ctx is not None:
            payload["trace_id"] = ctx.trace_id
        return payload

    try:
        request = engine.submit(
            pipeline.encode(req["input_text"]),
            max_new_tokens=req.get("max_new_tokens"),
            request_id=None if rid is None else str(rid),
            trace_id=None if ctx is None else ctx.trace_id,
            parent_span_id=None if ctx is None else ctx.span_id,
            resume_tokens=req.get("resume_tokens"),
            resume_source=req.get("resume_source"))
    except Draining as e:
        return 503, _body({"error": str(e), "reason": "draining"})
    except DuplicateRequest as e:
        return 409, _body({"error": str(e)})
    except QueueFull as e:
        return 429, _body({"error": str(e)})
    except PromptTooLong as e:
        return 413, _body({"error": str(e)})
    except (ValueError, TypeError) as e:
        # bad request payload (unencodable input, max_new_tokens < 1)
        return 422, _body({"error": str(e)})
    if disagg is not None and req.get("disagg_push_to"):
        redirect = disagg.handoff(request, str(req["disagg_push_to"]))
        if redirect is not None:
            return 200, _body(dict(redirect))
        # fallback: the lane keeps decoding locally; wait as usual
    if not request.wait(timeout=timeout_s):
        engine.cancel(request.request_id)
        # the request may have completed in the wait→cancel window; a
        # finished result must not be discarded as a timeout
        if request.state not in (FINISHED, EVACUATED):
            return 503, _body({"error":
                               f"request timed out after {timeout_s}s"})
    if request.state == EVACUATED:
        # drain-time live evacuation moved the lane to a healthy peer
        # (docs/fault_tolerance.md "Preemption runbook"): answer the
        # blocked POST with the same disagg-redirect marker a phase
        # handoff uses — the router's existing collect path long-polls
        # the adopter and the client sees one ordinary 200
        return 200, _body({"disagg_redirect": True,
                           "request_id": request.request_id,
                           "target": request.evac_target,
                           "evacuated": True})
    if request.state != FINISHED:
        body = {"error": f"request {request.state} "
                         f"({request.finish_reason})"}
        if request.finish_reason == "draining":
            # queued-but-not-slotted at begin_drain: flushed back as an
            # orderly 503 the router re-places immediately instead of
            # waiting out the drain timeout
            body["reason"] = "draining"
        return 503, _body(body)
    return 200, _body({"result": pipeline.decode(request.tokens),
                       "request_id": request.request_id,
                       "ttft_s": request.ttft_s,
                       "finish_reason": request.finish_reason})


def _engine_stream(engine, pipeline, req: dict, timeout_s: float):
    """`POST /api/<task>/stream` (docs/streaming.md): submit (or
    reattach to) a request and return its live SSE frame iterator.

    Returns `(code, payload, None)` for refusals — the SAME
    backpressure → HTTP map as `_engine_generate`, answered as plain
    JSON before any stream byte is written — or `(200, None, frames)`
    where `frames` yields ready-to-write SSE byte chunks: one `token`
    event per committed token (event id = token index), then exactly
    one terminal `done` / `evacuated` / `timeout` event.

    A body carrying `request_id` + `last_event_id` is the reconnect
    path (`Last-Event-ID`, lifted into the body by the server layer):
    no new submission — the journaled request's stream replays from
    token `last_event_id + 1` and continues live. On `evacuated`, the
    client re-POSTs the same body to the named adopter."""
    from fengshen_tpu.observability import parse_traceparent
    from fengshen_tpu.serving import (Draining, DuplicateRequest,
                                      PromptTooLong, QueueFull)
    from fengshen_tpu.streaming import format_event
    if engine is None or not hasattr(engine, "attach_stream"):
        return 501, {"error": "streaming requires the continuous "
                              "batching engine"}, None
    t0 = time.perf_counter()
    rid = req.get("request_id")
    if rid is not None and req.get("last_event_id") is not None:
        stream = engine.attach_stream(str(rid))
        if stream is None:
            return 404, {"error": f"unknown request_id {rid!r}"}, None
        engine.metrics.record_stream_reconnect()
        start = int(req["last_event_id"]) + 1
        request_id = str(rid)
    else:
        ctx = parse_traceparent(req.get("traceparent"))
        try:
            request = engine.submit(
                pipeline.encode(req["input_text"]),
                max_new_tokens=req.get("max_new_tokens"),
                request_id=None if rid is None else str(rid),
                trace_id=None if ctx is None else ctx.trace_id,
                parent_span_id=None if ctx is None else ctx.span_id,
                resume_tokens=req.get("resume_tokens"),
                resume_source=req.get("resume_source"),
                seed=req.get("seed"), stream=True)
        except Draining as e:
            return 503, {"error": str(e), "reason": "draining"}, None
        except DuplicateRequest as e:
            return 409, {"error": str(e)}, None
        except QueueFull as e:
            return 429, {"error": str(e)}, None
        except PromptTooLong as e:
            return 413, {"error": str(e)}, None
        except (ValueError, TypeError) as e:
            return 422, {"error": str(e)}, None
        stream = engine.streams.get(request.request_id)
        start = 0
        request_id = request.request_id

    def frames():
        first = True
        for kind, idx, payload in stream.events(start,
                                                timeout=timeout_s):
            if first:
                # delivery-layer TTFB: received-to-first-byte, the
                # headline `serve-bench-stream` reads (the engine's
                # ttft_seconds keeps its commit-time meaning)
                engine.metrics.record_stream_ttfb(
                    time.perf_counter() - t0)
                first = False
            if kind == "token":
                yield format_event("token", {"token": payload},
                                   event_id=idx)
            elif kind == "evacuated":
                # the lane moved mid-generation: the terminal event
                # names the adopter; re-POST the same body there with
                # last_event_id to continue gaplessly
                yield format_event(
                    "evacuated",
                    {"request_id": request_id, "target": payload},
                    event_id=idx)
            elif kind == "timeout":
                yield format_event(
                    "timeout",
                    {"request_id": request_id,
                     "error": f"no stream event within {timeout_s}s"},
                    event_id=idx)
            else:   # done
                data = {"request_id": request_id,
                        "finish_reason": payload}
                if payload in ("eos", "length"):
                    data["result"] = pipeline.decode(stream.tokens())
                yield format_event("done", data, event_id=idx)

    return 200, None, frames()


def _multimodal_generate(engine, pipeline, req: dict,
                         timeout_s: float) -> tuple[int, dict]:
    """Submit one HTTP request to a micro-batch engine (batch_image /
    embedding); returns (status, body). Same backpressure → HTTP
    mapping as `_engine_generate` — queue full → 429, draining → 503
    with reason, duplicate request_id → 409 — so the fleet router's
    retry contract holds across engine types. The 200 body carries the
    pipeline's result dict (image payload or embedding) plus the
    `engine_type` the router's heterogeneous placement keys on."""
    from fengshen_tpu.serving import Draining, DuplicateRequest, QueueFull
    from fengshen_tpu.serving.multimodal import MM_FINISHED
    rid = req.get("request_id")
    try:
        request = engine.submit(req["input_text"],
                                request_id=None if rid is None
                                else str(rid))
    except Draining as e:
        return 503, {"error": str(e), "reason": "draining"}
    except DuplicateRequest as e:
        return 409, {"error": str(e)}
    except QueueFull as e:
        return 429, {"error": str(e)}
    except (ValueError, TypeError) as e:
        return 422, {"error": str(e)}
    if not request.wait(timeout=timeout_s):
        engine.cancel(request.request_id)
        # the batch may have landed in the wait→cancel window; a
        # finished result must not be discarded as a timeout
        if request.state != MM_FINISHED:
            return 503, {"error":
                         f"request timed out after {timeout_s}s"}
    if request.state != MM_FINISHED:
        return 503, {"error": f"request {request.state} "
                              f"({request.error})"}
    return 200, {"result": request.result,
                 "request_id": request.request_id,
                 "engine_type": engine.engine_type}


def build_app(pipeline_cfg: PipelineConfig, pipeline=None,
              server_cfg: Optional[ServerConfig] = None, engine=None,
              ready=None, recorder=None, draining=None, disagg=None):
    """Create the FastAPI app around a pipeline instance. `ready` is an
    optional `threading.Event`: until set, `GET /healthz` answers 503
    ("warming") so load balancers keep routing around a replica that is
    still compiling; None means always ready. `draining` is the mirror
    event for the way OUT: once set, `/healthz` answers 503 with reason
    "draining" and new generate requests get 503 while in-flight ones
    finish (docs/fleet.md). `recorder` enables `POST /debug/dump`.
    `disagg` is an optional `DisaggCoordinator` enabling the KV-handoff
    surface (`PUT/GET/DELETE /kv/<id>`, docs/disaggregation.md)."""
    from fastapi import FastAPI, Header
    from fastapi.middleware.cors import CORSMiddleware
    from fastapi.responses import JSONResponse, Response
    from pydantic import BaseModel

    server_cfg = server_cfg or ServerConfig()
    if pipeline is None:
        pipeline = _resolve_pipeline(pipeline_cfg)

    app = FastAPI()
    app.add_middleware(CORSMiddleware, allow_origins=["*"],
                       allow_methods=["*"], allow_headers=["*"])

    class Request(BaseModel):
        input_text: str
        max_new_tokens: Optional[int] = None
        # the fleet router's idempotent-safe retry hook: without this
        # field pydantic silently DROPS the router-assigned id and the
        # engine dedupe (409 contract) never sees it
        request_id: Optional[str] = None
        # distributed-trace context (docs/observability.md): the
        # router sends it BOTH as this body field and as the
        # `traceparent` HTTP header; the body form survives proxies
        # that strip unknown headers
        traceparent: Optional[str] = None
        # phase-aware placement directive (docs/disaggregation.md):
        # the router names the decode replica this prefill replica
        # should push the primed lane to; pydantic must not drop it
        disagg_push_to: Optional[str] = None
        # resume-from-token-k failover (docs/fault_tolerance.md
        # "Preemption runbook"): the router replays a dead replica's
        # journaled prefix so the retry prefills prompt+prefix and
        # decodes only the remainder; pydantic must not drop these
        resume_tokens: Optional[list] = None
        resume_source: Optional[str] = None
        # streaming tier (docs/streaming.md): the per-request sampling
        # seed, and the reconnect cursor (body form of the SSE
        # `Last-Event-ID` header — the body wins when both arrive);
        # pydantic must not drop them
        seed: Optional[int] = None
        last_event_id: Optional[int] = None

    api_route = f"/api/{pipeline_cfg.task}"
    stream_route = f"{api_route}/stream"

    @app.middleware("http")
    async def _time_request(request, call_next):
        # the `fstpu_http_request_seconds{route}` histogram beside the
        # per-route counter (the stdlib path times in _send_bytes)
        t0 = time.perf_counter()
        response = await call_next(request)
        _observe_http(_classify_route(request.url.path, api_route),
                      time.perf_counter() - t0)
        return response

    @app.post(api_route)
    def run(req: Request,
            traceparent: Optional[str] = Header(None)) -> Any:
        if draining is not None and draining.is_set():
            # the engine path would answer the same via Draining; this
            # ALSO covers the simple path, and spares encode work
            _count_http(api_route, 503)
            return JSONResponse(
                status_code=503,
                content={"error": "replica draining",
                         "reason": "draining"})
        if engine is not None:
            payload = req.model_dump()
            if traceparent and not payload.get("traceparent"):
                # header form of the trace context (the body field
                # wins when both are present — they are identical
                # when the fleet router sent them)
                payload["traceparent"] = traceparent
            if getattr(engine, "engine_type",
                       "continuous") == "continuous":
                code, body = _engine_generate(
                    engine, pipeline, payload,
                    server_cfg.request_timeout_s, disagg=disagg)
            else:
                code, body = _multimodal_generate(
                    engine, pipeline, payload,
                    server_cfg.request_timeout_s)
            _count_http(api_route, code)
            return JSONResponse(status_code=code, content=body)
        if req.max_new_tokens is not None and \
                _accepts_max_new_tokens(pipeline):
            result = pipeline(req.input_text,
                              max_new_tokens=req.max_new_tokens)
        else:
            result = pipeline(req.input_text)
        _count_http(api_route, 200)
        return {"result": result}

    class StreamRequest(Request):
        # a reconnect body carries only request_id + last_event_id —
        # no prompt — so input_text relaxes to optional HERE ONLY (the
        # handler 422s a fresh submission without it)
        input_text: Optional[str] = None

    @app.post(stream_route)
    def run_stream(req: StreamRequest,
                   traceparent: Optional[str] = Header(None),
                   last_event_id: Optional[str] = Header(None)) -> Any:
        from fastapi.responses import StreamingResponse
        payload = req.model_dump()
        if traceparent and not payload.get("traceparent"):
            payload["traceparent"] = traceparent
        if last_event_id is not None and \
                payload.get("last_event_id") is None:
            # the SSE-standard reconnect header; EventSource clients
            # send it automatically on reconnection
            try:
                payload["last_event_id"] = int(last_event_id)
            except ValueError:
                pass
        reconnect = payload.get("request_id") is not None and \
            payload.get("last_event_id") is not None
        if not reconnect and payload.get("input_text") is None:
            _count_http(stream_route, 422)
            return JSONResponse(status_code=422,
                                content={"error": "input_text required"})
        if draining is not None and draining.is_set() and not reconnect:
            # reconnects pass through the drain edge: a live lane's
            # reader must still receive its `evacuated` terminal event
            _count_http(stream_route, 503)
            return JSONResponse(
                status_code=503,
                content={"error": "replica draining",
                         "reason": "draining"})
        code, body, frames = _engine_stream(
            engine, pipeline, payload, server_cfg.request_timeout_s)
        _count_http(stream_route, code)
        if frames is None:
            return JSONResponse(status_code=code, content=body)
        return StreamingResponse(frames, media_type="text/event-stream",
                                 headers={"Cache-Control": "no-cache"})

    @app.get("/healthz")
    def healthz():
        code, body = _healthz_payload(pipeline_cfg.task, ready,
                                      draining)
        _count_http("/healthz", code)
        if code != 200:
            return JSONResponse(status_code=code, content=body)
        return body

    @app.get("/stats")
    def stats():
        _count_http("/stats", 200)
        if engine is not None:
            # the replica's disaggregation role EXTENDS the pinned
            # engine payload (same precedent as uptime_s/draining) —
            # the fleet router's poll keys phase-aware placement on it
            return dict(engine.stats(), phase=server_cfg.phase)
        return {"engine": "simple", "task": pipeline_cfg.task,
                "phase": server_cfg.phase}

    @app.get("/metrics")
    def metrics():
        from fengshen_tpu.observability import CONTENT_TYPE_LATEST
        _count_http("/metrics", 200)
        return Response(content=_render_metrics(engine, disagg=disagg),
                        media_type=CONTENT_TYPE_LATEST)

    @app.put("/kv/{request_id}")
    def kv_put(request_id: str, payload: dict):
        if disagg is None:
            _count_http("/kv/<id>", 409)
            return JSONResponse(
                status_code=409,
                content={"adopted": False, "reason": "no_engine"})
        code, body = disagg.handle_put(request_id, payload)
        _count_http("/kv/<id>", code)
        return JSONResponse(status_code=code, content=body)

    @app.get("/kv/{request_id}")
    def kv_get(request_id: str):
        if disagg is None:
            _count_http("/kv/<id>", 404)
            return JSONResponse(
                status_code=404,
                content={"error": "no disagg coordinator"})
        code, body = disagg.handle_get(request_id,
                                       server_cfg.request_timeout_s)
        _count_http("/kv/<id>", code)
        return JSONResponse(status_code=code, content=body)

    @app.delete("/kv/{request_id}")
    def kv_delete(request_id: str):
        if disagg is None:
            _count_http("/kv/<id>", 404)
            return JSONResponse(
                status_code=404,
                content={"error": "no disagg coordinator"})
        code, body = disagg.handle_delete(request_id)
        _count_http("/kv/<id>", code)
        return JSONResponse(status_code=code, content=body)

    @app.get("/partial/{request_id}")
    def partial(request_id: str):
        code, body = _partial_payload(engine, pipeline, request_id)
        _count_http("/partial/<id>", code)
        if code != 200:
            return JSONResponse(status_code=code, content=body)
        return body

    @app.get("/debug/requests")
    def debug_requests():
        _count_http("/debug/requests", 200)
        return _debug_requests_payload(engine)

    @app.get("/debug/requests/{request_id}")
    def debug_request(request_id: str):
        d = engine.debug_request(request_id) \
            if engine is not None and hasattr(engine, "debug_request") \
            else None
        code = 200 if d is not None else 404
        _count_http("/debug/requests/<id>", code)
        if d is None:
            return JSONResponse(
                status_code=404,
                content={"error": f"unknown request_id {request_id!r}"})
        return d

    @app.post("/debug/dump")
    def debug_dump():
        if recorder is None:
            _count_http("/debug/dump", 404)
            return JSONResponse(
                status_code=404,
                content={"error": "no flight recorder configured"})
        try:
            bundle = _dump_recorder(recorder, engine)
        except Exception as e:  # noqa: BLE001 — an unwritable dump_dir
            # (the sick-host case) must answer, not drop the socket
            _count_http("/debug/dump", 500)
            return JSONResponse(status_code=500,
                                content={"error": str(e)[:500]})
        _count_http("/debug/dump", 200)
        return {"bundle": bundle}

    return app


def _resolve_pipeline(pipeline_cfg: PipelineConfig):
    module = importlib.import_module(
        f"fengshen_tpu.pipelines.{pipeline_cfg.task}")
    return module.Pipeline(args=None, model=pipeline_cfg.model,
                           **pipeline_cfg.pipeline_args)


def build_stdlib_server(server_cfg: ServerConfig,
                        pipeline_cfg: PipelineConfig, pipeline=None,
                        engine=None, ready=None, recorder=None,
                        draining=None, disagg=None):
    """Dependency-free fallback server (http.server) exposing the SAME
    surface as the FastAPI app: `POST /api/<task>` with
    `{"input_text": ...}`, `GET /healthz` (503 `{"ready": false,
    "reason": "warmup"}` until the `ready` event is set, 503 with
    reason "draining" once the `draining` event is set — both mirrored
    by build_app), `GET /stats`, `GET /metrics`, and the debug
    introspection routes (`GET /debug/requests[/<id>]`,
    `POST /debug/dump` when a `recorder` is wired). FastAPI/uvicorn
    stay the production path; this keeps the REST surface runnable (and
    testable) where they are not installed. The returned server tracks
    its in-flight generate requests (`server.in_flight()`) so the
    SIGTERM drain handler can wait them out (docs/fleet.md)."""
    import http.server
    import threading

    if pipeline is None:
        pipeline = _resolve_pipeline(pipeline_cfg)
    route = f"/api/{pipeline_cfg.task}"
    inflight_lock = threading.Lock()
    inflight = [0]

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _send_bytes(self, code: int, body: bytes,
                        content_type: str) -> None:
            label = _classify_route(self.path, route)
            _count_http(label, code)
            t0 = getattr(self, "_t_start", None)
            if t0 is not None:
                _observe_http(label, time.perf_counter() - t0)
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Access-Control-Allow-Origin", "*")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send(self, code: int, payload: dict) -> None:
            self._send_bytes(
                code, json.dumps(payload, ensure_ascii=False).encode(),
                "application/json")

        def _send_stream(self, frames) -> None:
            """SSE response: bypasses `_send_bytes` (no Content-Length
            — the body length is unknown until the stream ends), writes
            each frame as it arrives and flushes so tokens reach the
            client at commit time, then closes the connection (the
            `Connection: close` EOF is the stream terminator HTTP/1.0
            clients understand without chunked framing)."""
            label = _classify_route(self.path, route)
            _count_http(label, 200)
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Access-Control-Allow-Origin", "*")
            self.send_header("Connection", "close")
            self.end_headers()
            try:
                for chunk in frames:
                    self.wfile.write(chunk)
                    self.wfile.flush()
            except (BrokenPipeError, ConnectionResetError):
                # the client went away mid-stream; its tokens stay in
                # the journal + stream buffer for a Last-Event-ID
                # reconnect — nothing to clean up here
                pass
            t0 = getattr(self, "_t_start", None)
            if t0 is not None:
                _observe_http(label, time.perf_counter() - t0)

        def do_GET(self):
            self._t_start = time.perf_counter()
            if self.path == "/healthz":
                code, body = _healthz_payload(pipeline_cfg.task, ready,
                                              draining)
                self._send(code, body)
            elif self.path == "/stats":
                if engine is not None:
                    # phase EXTENDS the pinned engine payload (same
                    # precedent as uptime_s/draining): the fleet
                    # router's phase-aware placement polls it
                    self._send(200, dict(engine.stats(),
                                         phase=server_cfg.phase))
                else:
                    self._send(200, {"engine": "simple",
                                     "task": pipeline_cfg.task,
                                     "phase": server_cfg.phase})
            elif self.path == "/metrics":
                from fengshen_tpu.observability import \
                    CONTENT_TYPE_LATEST
                self._send_bytes(
                    200, _render_metrics(engine, disagg=disagg).encode(),
                    CONTENT_TYPE_LATEST)
            elif self.path.startswith("/kv/"):
                rid = self.path[len("/kv/"):]
                if disagg is None:
                    self._send(404,
                               {"error": "no disagg coordinator"})
                else:
                    code, body = disagg.handle_get(
                        rid, server_cfg.request_timeout_s)
                    self._send(code, body)
            elif self.path.startswith("/partial/"):
                rid = self.path[len("/partial/"):]
                code, body = _partial_payload(engine, pipeline, rid)
                self._send(code, body)
            elif self.path == "/debug/requests":
                self._send(200, _debug_requests_payload(engine))
            elif self.path.startswith("/debug/requests/"):
                rid = self.path[len("/debug/requests/"):]
                d = engine.debug_request(rid) \
                    if engine is not None and \
                    hasattr(engine, "debug_request") else None
                if d is None:
                    self._send(404, {"error":
                                     f"unknown request_id {rid!r}"})
                else:
                    self._send(200, d)
            else:
                self._send(404, {"error": "not found"})

        def do_POST(self):
            self._t_start = time.perf_counter()
            if self.path == "/debug/dump":
                if recorder is None:
                    self._send(404, {"error":
                                     "no flight recorder configured"})
                    return
                try:
                    bundle = _dump_recorder(recorder, engine)
                except Exception as e:  # noqa: BLE001 — an unwritable
                    # dump_dir (the sick-host case) must answer, not
                    # drop the socket
                    self._send(500, {"error": str(e)[:500]})
                    return
                self._send(200, {"bundle": bundle})
                return
            if self.path == f"{route}/stream":
                self._post_stream()
                return
            if self.path != route:
                self._send(404, {"error": "not found"})
                return
            length = int(self.headers.get("Content-Length", 0))
            try:
                req = json.loads(self.rfile.read(length) or b"{}")
            except json.JSONDecodeError as e:
                self._send(422, {"error": f"invalid json: {e}"})
                return
            if "input_text" not in req:
                # validated BEFORE the pipeline runs: a KeyError inside
                # the pipeline must surface as 500, not as this 422
                self._send(422, {"error": "input_text required"})
                return
            tp = self.headers.get("traceparent")
            if tp and not req.get("traceparent"):
                # lift the header form of the trace context into the
                # body dict _engine_generate reads (body field wins)
                req["traceparent"] = tp
            if draining is not None and draining.is_set():
                # admission edge of the drain: requests already past
                # it (counted in-flight below) finish normally
                self._send(503, {"error": "replica draining",
                                 "reason": "draining"})
                return
            with inflight_lock:
                inflight[0] += 1
            try:
                if engine is not None and \
                        getattr(engine, "engine_type",
                                "continuous") == "continuous":
                    code, body = _engine_generate(
                        engine, pipeline, req,
                        server_cfg.request_timeout_s, disagg=disagg)
                    self._send(code, body)
                elif engine is not None:
                    code, body = _multimodal_generate(
                        engine, pipeline, req,
                        server_cfg.request_timeout_s)
                    self._send(code, body)
                elif req.get("max_new_tokens") is not None and \
                        _accepts_max_new_tokens(pipeline):
                    # per-request cap on the legacy path too (only
                    # generation pipelines accept it)
                    self._send(200, {"result": pipeline(
                        req["input_text"],
                        max_new_tokens=req["max_new_tokens"])})
                else:
                    self._send(200,
                               {"result": pipeline(req["input_text"])})
            except Exception as e:  # noqa: BLE001 — surface, don't die
                self._send(500, {"error": str(e)[:500]})
            finally:
                with inflight_lock:
                    inflight[0] -= 1

        def _post_stream(self):
            """`POST /api/<task>/stream` (docs/streaming.md): same
            admission surface as the plain route, SSE delivery."""
            length = int(self.headers.get("Content-Length", 0))
            try:
                req = json.loads(self.rfile.read(length) or b"{}")
            except json.JSONDecodeError as e:
                self._send(422, {"error": f"invalid json: {e}"})
                return
            tp = self.headers.get("traceparent")
            if tp and not req.get("traceparent"):
                req["traceparent"] = tp
            lei = self.headers.get("Last-Event-ID")
            if lei is not None and req.get("last_event_id") is None:
                # the SSE-standard reconnect header, lifted into the
                # body form _engine_stream reads (body field wins)
                try:
                    req["last_event_id"] = int(lei)
                except ValueError:
                    pass
            reconnect = req.get("request_id") is not None and \
                req.get("last_event_id") is not None
            if not reconnect and "input_text" not in req:
                self._send(422, {"error": "input_text required"})
                return
            if draining is not None and draining.is_set() and \
                    not reconnect:
                # reconnects pass the drain edge: a live lane's reader
                # must still receive its `evacuated` terminal event
                self._send(503, {"error": "replica draining",
                                 "reason": "draining"})
                return
            with inflight_lock:
                inflight[0] += 1
            try:
                code, body, frames = _engine_stream(
                    engine, pipeline, req,
                    server_cfg.request_timeout_s)
                if frames is None:
                    self._send(code, body)
                else:
                    self._send_stream(frames)
            except Exception as e:  # noqa: BLE001 — surface, don't die
                self._send(500, {"error": str(e)[:500]})
            finally:
                with inflight_lock:
                    inflight[0] -= 1

        def do_PUT(self):
            # KV-handoff adopt endpoint (docs/disaggregation.md): a
            # prefill peer pushes an exported lane; the ack tells it
            # whether to detach (200) or decode locally (decline)
            self._t_start = time.perf_counter()
            if not self.path.startswith("/kv/"):
                self._send(404, {"error": "not found"})
                return
            rid = self.path[len("/kv/"):]
            if disagg is None:
                self._send(409, {"adopted": False,
                                 "reason": "no_engine"})
                return
            length = int(self.headers.get("Content-Length", 0))
            try:
                payload = json.loads(self.rfile.read(length) or b"{}")
            except json.JSONDecodeError as e:
                self._send(422, {"adopted": False,
                                 "reason": "payload_invalid",
                                 "error": f"invalid json: {e}"})
                return
            try:
                code, body = disagg.handle_put(rid, payload)
            except Exception as e:  # noqa: BLE001 — answer, don't die
                code, body = 500, {"adopted": False,
                                   "reason": "internal",
                                   "error": str(e)[:200]}
            self._send(code, body)

        def do_DELETE(self):
            self._t_start = time.perf_counter()
            if not self.path.startswith("/kv/"):
                self._send(404, {"error": "not found"})
                return
            rid = self.path[len("/kv/"):]
            if disagg is None:
                self._send(404, {"error": "no disagg coordinator"})
                return
            code, body = disagg.handle_delete(rid)
            self._send(code, body)

    server = http.server.ThreadingHTTPServer(
        (server_cfg.host, server_cfg.port), Handler)
    server.in_flight = lambda: inflight[0]
    return server


def install_drain_handler(server, draining, engine=None, recorder=None,
                          drain_timeout_s: float = 30.0,
                          poll_s: float = 0.05, disagg=None,
                          peers=()):
    """SIGTERM → graceful replica drain (docs/fleet.md "Drain
    runbook"): set the `draining` event (healthz flips to 503
    `{"reason": "draining"}`; new generates get 503), stop engine
    admission (`begin_drain`), then — on a waiter thread — wait until
    the engine is idle and no HTTP generate is in flight (bounded by
    `drain_timeout_s`), dump the flight recorder, and shut the server
    down so `serve_forever` returns and the process exits 0.

    When a `disagg` coordinator and evacuation `peers` are wired
    (docs/fault_tolerance.md "Preemption runbook"), the waiter first
    EVACUATES every in-flight lane to a healthy peer — the blocked
    POSTs answer with disagg-style redirects the router re-collects —
    so the idle-wait below only covers lanes no peer would adopt
    (which finish locally, never as an error).

    Deliberately REPLACES (does not chain) any prior SIGTERM handler:
    the flight recorder's own handler re-delivers the default
    disposition after dumping — i.e. immediate death — which is
    exactly what a drain must prevent. Its dump still happens, here,
    after the drain. Returns the previous handler (tests restore it)
    or None when not on the main thread."""
    import signal
    import threading
    if threading.current_thread() is not threading.main_thread():
        return None
    previous = signal.getsignal(signal.SIGTERM)

    def handler(signum, frame):
        if draining.is_set():
            return          # second SIGTERM: drain already underway
        draining.set()
        if engine is not None:
            engine.begin_drain()

        def waiter():
            if disagg is not None and peers:
                try:
                    disagg.evacuate_all(list(peers))
                except Exception:  # noqa: BLE001 — evacuation is
                    # best-effort; the idle wait below still finishes
                    # every unevacuated lane locally
                    pass
            deadline = time.monotonic() + drain_timeout_s
            while time.monotonic() < deadline:
                engine_idle = engine is None or engine.idle()
                if engine_idle and server.in_flight() == 0:
                    break
                time.sleep(poll_s)
            if recorder is not None:
                try:
                    recorder.dump(reason="sigterm_drain")
                except Exception:  # noqa: BLE001 — a failed dump must
                    # not leave the server running forever
                    pass
            server.shutdown()

        threading.Thread(target=waiter, daemon=True,
                         name="fstpu-drain").start()

    signal.signal(signal.SIGTERM, handler)
    return previous


def _start_warmup_thread(server_cfg: ServerConfig,
                         pipeline_cfg: PipelineConfig, pipeline,
                         engine):
    """Warm up in the background while the server is already listening
    (docs/aot_cache.md "cold start"): /healthz answers 503 until the
    returned event is set, then 200 — the load-balancer readiness
    contract. With an AOT cache the warmup is mostly deserialization
    and the 503 window shrinks to near zero."""
    import threading
    ready = threading.Event()

    def _warm():
        from fengshen_tpu.observability import record_build_info
        record_build_info()
        try:
            if engine is not None and \
                    getattr(engine, "engine_type",
                            "continuous") == "continuous":
                dt = engine.warmup()
                print(f"[serving] continuous engine warmup "
                      f"(buckets={list(engine.ladder.buckets)}, "
                      f"num_slots={engine.config.num_slots}) ready in "
                      f"{dt:.1f}s", flush=True)
            elif engine is not None:
                dt = engine.warmup()
                print(f"[serving] {engine.engine_type} engine warmup "
                      f"(max_batch={engine.max_batch}) ready in "
                      f"{dt:.1f}s", flush=True)
            elif server_cfg.warmup:
                warmup_pipeline(pipeline, pipeline_cfg.task)
        except Exception as e:  # noqa: BLE001 — warmup is best-effort;
            # requests compile lazily (or surface the same error as a
            # response) once the loop below starts
            print(f"[serving] warmup failed ({e}); serving anyway — "
                  "first requests will compile", flush=True)
        finally:
            # a failed warmup still opens the gate AND starts the serve
            # loop: requests then compile lazily (or fail loudly) — a
            # replica that reports ready while no loop drains its queue
            # would hang every request to its full timeout instead
            if engine is not None:
                engine.start()
            ready.set()

    threading.Thread(target=_warm, daemon=True,
                     name="fstpu-warmup").start()
    return ready


def main(argv=None) -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", required=True, type=str)
    args = parser.parse_args(argv)
    server_cfg, pipeline_cfg = load_config(args.config)
    from fengshen_tpu.observability import (FlightRecorder,
                                            record_build_info)
    record_build_info()
    # post-mortem flight recorder (docs/observability.md): engine tick
    # errors and SIGTERM dump the last window of events; POST
    # /debug/dump does so on demand
    recorder = FlightRecorder(dump_dir=server_cfg.dump_dir)
    recorder.install_sigterm()
    pipeline = _resolve_pipeline(pipeline_cfg)
    engine = None
    disagg = None
    if server_cfg.engine == "continuous":
        # warmup (all prefill buckets + the decode step) runs in the
        # background thread below; construction itself is compile-free
        engine = create_continuous_engine(pipeline,
                                          server_cfg.engine_args,
                                          aot_args=server_cfg.aot_args,
                                          recorder=recorder)
        # every continuous replica can play either side of a KV
        # handoff; the router's phase-aware placement decides which
        from fengshen_tpu.disagg.coordinator import DisaggCoordinator
        disagg = DisaggCoordinator(engine, pipeline)
    elif server_cfg.engine in ("batch_image", "embedding"):
        # micro-batch engines (docs/serving.md "Multimodal engines"):
        # no slot pool, no KV handoff — warmup/start also run in the
        # background thread below
        from fengshen_tpu.serving.multimodal import \
            create_multimodal_engine
        engine = create_multimodal_engine(server_cfg.engine, pipeline,
                                          server_cfg.engine_args)
    ready = _start_warmup_thread(server_cfg, pipeline_cfg, pipeline,
                                 engine)
    import os
    import threading
    draining = threading.Event()
    # FSTPU_PEERS=http://host:port,... names the sibling replicas this
    # one may evacuate live lanes to on drain (the fleet launcher sets
    # it; docs/fault_tolerance.md "Preemption runbook")
    peers_env = os.environ.get("FSTPU_PEERS")
    if peers_env:
        server_cfg.peers = tuple(
            p.strip().rstrip("/") for p in peers_env.split(",")
            if p.strip())
    # FSTPU_API_SERVER=stdlib forces the stdlib path even where
    # uvicorn is installed — the fleet launcher sets it because only
    # this path has the SIGTERM graceful drain (uvicorn installs its
    # own signal handlers; its shutdown drops in-flight engine waits)
    use_stdlib = os.environ.get("FSTPU_API_SERVER",
                                "").lower() == "stdlib"
    app = None
    if not use_stdlib:
        try:
            app = build_app(pipeline_cfg, pipeline=pipeline,
                            server_cfg=server_cfg, engine=engine,
                            ready=ready, recorder=recorder,
                            draining=draining, disagg=disagg)
            import uvicorn
        except ModuleNotFoundError:
            app = None
    if app is None:
        server = build_stdlib_server(server_cfg, pipeline_cfg,
                                     pipeline=pipeline, engine=engine,
                                     ready=ready, recorder=recorder,
                                     draining=draining, disagg=disagg)
        # graceful drain replaces the recorder's dump-then-die SIGTERM
        # chain installed above (the dump still happens, post-drain)
        install_drain_handler(server, draining, engine=engine,
                              recorder=recorder,
                              drain_timeout_s=server_cfg.drain_timeout_s,
                              disagg=disagg, peers=server_cfg.peers)
        why = "FSTPU_API_SERVER=stdlib" if use_stdlib else \
            "fastapi/uvicorn not installed"
        print(f"{why} — stdlib server on "
              f"{server_cfg.host}:{server_cfg.port}", flush=True)
        server.serve_forever()
        server.server_close()
        if engine is not None:
            engine.stop()
        return
    uvicorn.run(app, host=server_cfg.host, port=server_cfg.port,
                log_level=server_cfg.log_level)
    # uvicorn installs its OWN signal handlers (replacing the chained
    # SIGTERM dump above) and returns here after its graceful
    # shutdown — dump on the way out so a drained uvicorn replica
    # still leaves a bundle; the stdlib path keeps the chained handler
    try:
        recorder.dump(reason="shutdown")
    except Exception:  # noqa: BLE001 — never fail process exit on
        # telemetry
        pass


if __name__ == "__main__":
    main()
