"""REST serving: JSON config → pipeline → FastAPI POST endpoint.

Port of reference: fengshen/API/main.py:12-75 + API/utils.py — a config
file names the task/model/server options; the server instantiates the
matching pipeline and exposes `POST /api/<task>`; CORS enabled; run with
uvicorn. FastAPI/uvicorn are optional deps — gated at call time.

    python -m fengshen_tpu.api.main --config text_classification.json
"""

from __future__ import annotations

import argparse
import dataclasses
import importlib
import json
from typing import Any, Optional


@dataclasses.dataclass
class ServerConfig:
    """Reference: fengshen/API/utils.py config dataclasses."""

    host: str = "0.0.0.0"
    port: int = 8000
    log_level: str = "info"


@dataclasses.dataclass
class PipelineConfig:
    task: str = "text_classification"
    model: Optional[str] = None
    pipeline_args: dict = dataclasses.field(default_factory=dict)


def load_config(path: str) -> tuple[ServerConfig, PipelineConfig]:
    with open(path) as f:
        raw = json.load(f)
    server = ServerConfig(**raw.get("SERVER", {}))
    pipeline = PipelineConfig(
        task=raw.get("PIPELINE", {}).get("task", "text_classification"),
        model=raw.get("PIPELINE", {}).get("model"),
        pipeline_args={k: v for k, v in raw.get("PIPELINE", {}).items()
                       if k not in ("task", "model")})
    return server, pipeline


def build_app(pipeline_cfg: PipelineConfig, pipeline=None):
    """Create the FastAPI app around a pipeline instance."""
    from fastapi import FastAPI
    from fastapi.middleware.cors import CORSMiddleware
    from pydantic import BaseModel

    if pipeline is None:
        module = importlib.import_module(
            f"fengshen_tpu.pipelines.{pipeline_cfg.task}")
        pipeline = module.Pipeline(args=None, model=pipeline_cfg.model,
                                   **pipeline_cfg.pipeline_args)

    app = FastAPI()
    app.add_middleware(CORSMiddleware, allow_origins=["*"],
                       allow_methods=["*"], allow_headers=["*"])

    class Request(BaseModel):
        input_text: str

    @app.post(f"/api/{pipeline_cfg.task}")
    def run(req: Request) -> Any:
        return {"result": pipeline(req.input_text)}

    @app.get("/healthz")
    def healthz():
        return {"status": "ok", "task": pipeline_cfg.task}

    return app


def _resolve_pipeline(pipeline_cfg: PipelineConfig):
    module = importlib.import_module(
        f"fengshen_tpu.pipelines.{pipeline_cfg.task}")
    return module.Pipeline(args=None, model=pipeline_cfg.model,
                           **pipeline_cfg.pipeline_args)


def build_stdlib_server(server_cfg: ServerConfig,
                        pipeline_cfg: PipelineConfig, pipeline=None):
    """Dependency-free fallback server (http.server) exposing the SAME
    surface as the FastAPI app: `POST /api/<task>` with
    `{"input_text": ...}` and `GET /healthz`. FastAPI/uvicorn stay the
    production path; this keeps the REST surface runnable (and
    testable) where they are not installed."""
    import http.server

    if pipeline is None:
        pipeline = _resolve_pipeline(pipeline_cfg)
    route = f"/api/{pipeline_cfg.task}"

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _send(self, code: int, payload: dict) -> None:
            body = json.dumps(payload, ensure_ascii=False).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Access-Control-Allow-Origin", "*")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                self._send(200, {"status": "ok",
                                 "task": pipeline_cfg.task})
            else:
                self._send(404, {"error": "not found"})

        def do_POST(self):
            if self.path != route:
                self._send(404, {"error": "not found"})
                return
            length = int(self.headers.get("Content-Length", 0))
            try:
                req = json.loads(self.rfile.read(length) or b"{}")
            except json.JSONDecodeError as e:
                self._send(422, {"error": f"invalid json: {e}"})
                return
            if "input_text" not in req:
                # validated BEFORE the pipeline runs: a KeyError inside
                # the pipeline must surface as 500, not as this 422
                self._send(422, {"error": "input_text required"})
                return
            try:
                self._send(200, {"result": pipeline(req["input_text"])})
            except Exception as e:  # noqa: BLE001 — surface, don't die
                self._send(500, {"error": str(e)[:500]})

    return http.server.ThreadingHTTPServer(
        (server_cfg.host, server_cfg.port), Handler)


def main(argv=None) -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", required=True, type=str)
    args = parser.parse_args(argv)
    server_cfg, pipeline_cfg = load_config(args.config)
    try:
        app = build_app(pipeline_cfg)
        import uvicorn
    except ModuleNotFoundError:
        server = build_stdlib_server(server_cfg, pipeline_cfg)
        print(f"fastapi/uvicorn not installed — stdlib server on "
              f"{server_cfg.host}:{server_cfg.port}", flush=True)
        server.serve_forever()
        return
    uvicorn.run(app, host=server_cfg.host, port=server_cfg.port,
                log_level=server_cfg.log_level)


if __name__ == "__main__":
    main()
