#!/bin/bash
# Rows NOT yet captured in the round-5 hardware window (the relay
# wedged at the decode-int8 row after ~8 healthy minutes). Already
# banked on 2026-07-31: sharded 25,760 tok/s, fused-CE@b28 27,724
# tok/s, offload-update 14,103 tok/s, decode-greedy 2,351 tok/s/chip
# (docs/performance.md "Round-5 hardware window"). Run this on the
# NEXT healthy probe; same rules as run_bench_suite.sh (no external
# timeouts ever).
set -uo pipefail
cd "$(dirname "$0")/.."

probe() {
  python workspace/probe.py || exit 1
}

echo "== probe"; probe

echo "== dispatch-latency probe (quantifies the relay per-dispatch tax)"
python workspace/dispatch_latency_probe.py | tee /tmp/bench_dispatch_latency.json || exit 1

echo "== 13B-shape bench (north star; fresh-process rung ladder)"
BENCH_CONFIG=large python bench.py | tee /tmp/bench_large.json

echo "== probe"; probe

echo "== default bench (fresh-process batch/fused-CE ladder)"
python bench.py | tee /tmp/bench_default.json

echo "== probe"; probe

echo "== fused CE + bigger batch"
BENCH_FUSED_CE=8 BENCH_BATCH=32 python bench.py | tee /tmp/bench_fused_ce_b32.json || true

echo "== headroom lever: int8 LM-head (train)"
BENCH_INT8_LMHEAD=1 python bench.py | tee /tmp/bench_int8_lmhead.json

echo "== dispatch-latency A/B: 5 steps per jitted execution (vs banked 25,760 sharded row)"
BENCH_CONFIG=sharded BENCH_STEPS_PER_EXEC=5 python bench.py | tee /tmp/bench_sharded_spe5.json

echo "== probe"; probe

echo "== measured 7GB claim: 1.3B AFQMC shape with param streaming"
python workspace/offload_7gb_check.py | tee /tmp/bench_offload_7gb.json

echo "== probe"; probe

echo "== decode throughput: seq2seq beam-4 (T5-base shape)"
BENCH_CONFIG=decode BENCH_DECODE=beam python bench.py | tee /tmp/bench_decode_beam.json

echo "== probe"; probe

echo "== WEDGE-SUSPECT ROWS LAST =="
echo "== decode throughput: int8 LM head (wedged the relay in r5)"
BENCH_CONFIG=decode BENCH_INT8_LMHEAD=1 python bench.py | tee /tmp/bench_decode_int8.json

echo "== probe"; probe

echo "== block-sparse vs dense flash timing (wedged r3)"
python workspace/bs_hw_bench.py | tee /tmp/bench_block_sparse.txt

echo "== probe"; probe
echo "ALL DONE"
