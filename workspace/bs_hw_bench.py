"""Block-sparse vs dense-flash timing on the real chip (VERDICT r2 #4).

Longformer and BigBird block layouts at seq 4096/8192, bf16, fwd+bwd.
"""
import os
import sys
import threading
import time

sys.path.insert(0, "/root/repo")

# In-process watchdog (thread-based: SIGALRM handlers can't fire while
# the main thread is blocked in C). The round-3 wedge came from
# timeout-killing THIS script from outside; with a self-abort that must
# never be needed again. Re-armed before each timing phase.
_last_arm = [time.time()]
_DEADLINE = float(os.environ.get("BS_BENCH_DEADLINE", "540"))


def _watch():
    while True:
        time.sleep(10)
        if time.time() - _last_arm[0] > _DEADLINE:
            sys.stderr.write(
                f"bs_hw_bench watchdog: no progress in {_DEADLINE:.0f}s, "
                "aborting\n")
            sys.stderr.flush()
            os._exit(1)


threading.Thread(target=_watch, daemon=True).start()


def arm():
    _last_arm[0] = time.time()

import jax
import jax.numpy as jnp
import numpy as np

from fengshen_tpu.ops.masks import (bigbird_block_layout,
                                    longformer_block_layout)
from fengshen_tpu.ops.pallas.block_sparse_attention import (
    block_sparse_attention)
from fengshen_tpu.ops.pallas.flash_attention import pallas_flash_attention

print("backend:", jax.default_backend())
BLK = 128


def bench(fn, *args, iters=20):
    arm()  # fresh deadline per compile+timing phase
    out = jax.block_until_ready(fn(*args))  # compile
    arm()
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


for S in (4096, 8192):
    B, H, D = 1, 8, 128
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, S, H, D), jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, S, H, D), jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, S, H, D), jnp.bfloat16)

    lf = np.asarray(longformer_block_layout(S, BLK, num_window_blocks=3,
                                            num_global_blocks=1))
    bb = np.asarray(bigbird_block_layout(S, BLK, num_random_blocks=3,
                                         num_window_blocks=3,
                                         num_global_blocks=1))

    def run_sparse(layout):
        f = jax.jit(lambda q, k, v: block_sparse_attention(q, k, v, layout,
                                                           BLK))
        g = jax.jit(jax.grad(lambda q, k, v: (
            block_sparse_attention(q, k, v, layout, BLK)
            .astype(jnp.float32) ** 2).sum(), argnums=(0, 1, 2)))
        return bench(f, q, k, v), bench(g, q, k, v)

    def run_dense():
        f = jax.jit(lambda q, k, v: pallas_flash_attention(q, k, v,
                                                           causal=False))
        g = jax.jit(jax.grad(lambda q, k, v: (
            pallas_flash_attention(q, k, v, causal=False)
            .astype(jnp.float32) ** 2).sum(), argnums=(0, 1, 2)))
        return bench(f, q, k, v), bench(g, q, k, v)

    d_f, d_g = run_dense()
    for name, lay in (("longformer", lf), ("bigbird", bb)):
        s_f, s_g = run_sparse(lay)
        frac = lay.sum() / lay.size
        print(f"S={S} {name}: present={frac:.2%} "
              f"fwd {s_f*1e3:.2f}ms (dense {d_f*1e3:.2f}ms, "
              f"{d_f/s_f:.2f}x) | grad {s_g*1e3:.2f}ms "
              f"(dense {d_g*1e3:.2f}ms, {d_g/s_g:.2f}x)")
print("DONE")
