"""PR 16 verify drive: preemption-tolerant serving over REAL processes.

Spawns three evac-bench replica subprocesses (random-init llama +
DisaggCoordinator + drain handler): A fronts router traffic and is
configured with --peers pointing at B (a standby OUTSIDE the router
set); C is the healthy survivor. A REAL SIGTERM lands on A while it
holds in-flight decodes — the actual install_drain_handler path, not a
test callback — and the drive proves over HTTP: every concurrent
client POST through the real router returns 200 token-identical to
utils.generate.generate; B (which never takes router traffic) shows
fstpu_disagg_adopted_total >= 1 and renders the adopted lane's
"adopted"/"finished" timeline; A's last-gasp /metrics carries
fstpu_evac_lanes_total{outcome="adopted"}. Then the commit-journal +
resume surface directly on C: GET /partial/<rid> serves the finished
journal (unknown -> 404), and re-POSTing with resume_tokens=<first k>
returns the SAME tokens with a "resumed_from" timeline event and the
journal showing resumed_tokens == k.
"""
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, "/root/repo")
import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")

NEW_TOKENS = 64
ENV = {**os.environ, "JAX_PLATFORMS": "cpu",
       "EVAC_BENCH_NEW_TOKENS": str(NEW_TOKENS)}
RA, RB, RC, RP = 8491, 8492, 8493, 8490


def get(url, timeout=5):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def post(port, body, timeout=120, path="/api/text_generation"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def metrics(port):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
        return r.read().decode()


def events(port, rid):
    code, payload = get(f"http://127.0.0.1:{port}/debug/requests/{rid}")
    if code != 200:
        return None
    return [e["event"] for e in payload["events"]]


def replica(port, peers=""):
    cmd = [sys.executable, "-m", "fengshen_tpu.fleet.evac_bench",
           "--replica", "--port", str(port)]
    if peers:
        cmd += ["--peers", peers]
    return subprocess.Popen(cmd, env=ENV)


reps = [replica(RA, peers=f"http://127.0.0.1:{RB}"),
        replica(RB), replica(RC)]
router = subprocess.Popen(
    [sys.executable, "-m", "fengshen_tpu.fleet",
     "--replicas", f"127.0.0.1:{RA},127.0.0.1:{RC}",
     "--host", "127.0.0.1", "--port", str(RP),
     "--poll-interval", "0.2", "--recovery-probes", "1",
     "--request-timeout", "120"], env=ENV)

try:
    t0, fleet = time.time(), {}
    while time.time() - t0 < 240:
        try:
            code, fleet = get(f"http://127.0.0.1:{RP}/fleet")
            code_b, _ = get(f"http://127.0.0.1:{RB}/healthz")
            if fleet.get("healthy") == 2 and code_b == 200:
                break
        except OSError:
            pass
        time.sleep(0.3)
    assert fleet.get("healthy") == 2, fleet
    print("OK fleet up: A+C in rotation, standby B warm")

    # ---- greedy references (same random-init model) -----------------
    import jax.numpy as jnp
    import numpy as np
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.utils.generate import generate
    cfg = LlamaConfig(vocab_size=4096, hidden_size=1024,
                      intermediate_size=2816, num_hidden_layers=4,
                      num_attention_heads=8,
                      max_position_embeddings=64 + NEW_TOKENS,
                      dtype="float32")
    model = LlamaForCausalLM(cfg)
    params = jax.jit(lambda r: model.init(
        r, jnp.zeros((1, 8), jnp.int32))["params"])(
        jax.random.PRNGKey(0))

    def ref(prompt):
        out = np.asarray(generate(
            model, params, jnp.asarray(prompt)[None],
            max_new_tokens=NEW_TOKENS))[0, len(prompt):]
        return " ".join(str(t) for t in out.tolist())

    # ---- baseline through the router --------------------------------
    code, body = post(RP, {"input_text": "5 7 9 11"})
    assert code == 200 and body["result"] == ref([5, 7, 9, 11]), (
        code, body)
    print("OK baseline routed generate token-exact")

    # ---- SIGTERM mid-decode: live lane evacuation A -> B ------------
    prompts = [[3, 5, 7], [11, 13, 17, 19], [2, 4, 6],
               [21, 23, 25, 27, 29]]
    refs = {tuple(p): ref(p) for p in prompts}
    out, lock = [], threading.Lock()

    def drive(p):
        c, b = post(RP, {"input_text": " ".join(str(t) for t in p)})
        with lock:
            out.append((p, c, b))

    threads = [threading.Thread(target=drive, args=(p,))
               for p in prompts]
    for t in threads:
        t.start()

    # last-gasp scraper: A's /metrics until the drained process exits
    a_last = {"m": ""}

    def scrape_a():
        while True:
            try:
                a_last["m"] = metrics(RA)
            except OSError:
                return
            time.sleep(0.05)

    scraper = threading.Thread(target=scrape_a, daemon=True)
    scraper.start()

    t0 = time.time()
    while time.time() - t0 < 15:
        try:
            _, st = get(f"http://127.0.0.1:{RA}/stats")
            if st.get("slots_active", 0) >= 1:
                break
        except OSError:
            pass
        time.sleep(0.02)
    reps[0].send_signal(signal.SIGTERM)
    print("OK SIGTERM delivered to A with lanes in flight")

    for t in threads:
        t.join(timeout=180)
    for p, c, b in out:
        assert c == 200, (p, c, b)
        assert b["result"] == refs[tuple(p)], (p, b["result"])
    print(f"OK all {len(out)} in-flight requests answered 200 "
          "token-identical")

    mb = metrics(RB)
    adopted = [ln for ln in mb.splitlines()
               if ln.startswith("fstpu_disagg_adopted_total")]
    assert adopted and float(adopted[0].split()[-1]) >= 1, adopted
    adopted_rids = []
    for p, c, b in out:
        ev = events(RB, b["request_id"])
        if ev and "adopted" in ev:
            assert "finished" in ev, ev
            adopted_rids.append(b["request_id"])
    assert adopted_rids, "no adopted lane visible on B"
    print(f"OK standby B adopted {adopted[0].split()[-1]} lane(s); "
          f"timeline adopted->finished for {adopted_rids}")

    if 'fstpu_evac_lanes_total{outcome="adopted"}' in a_last["m"]:
        val = [ln for ln in a_last["m"].splitlines()
               if 'fstpu_evac_lanes_total{outcome="adopted"}' in ln]
        print("OK A last-gasp metrics:", val[0])
    else:
        print("note: A exited before a post-evac /metrics scrape "
              "landed (best-effort check)")
    reps[0].wait(timeout=60)
    assert reps[0].returncode == 0, reps[0].returncode
    print("OK A drained and exited 0")

    # ---- commit journal + resume-from-token-k on C ------------------
    code, body = post(RC, {"input_text": "2 3 5 7",
                           "request_id": "drive-j1"})
    assert code == 200, (code, body)
    r_full = body["result"]
    assert r_full == ref([2, 3, 5, 7]), r_full
    code, part = get(f"http://127.0.0.1:{RC}/partial/drive-j1")
    assert code == 200 and part["state"] == "finished", (code, part)
    assert part["result"] == r_full, part
    assert part["generated_tokens"] == NEW_TOKENS, part
    code, _ = get(f"http://127.0.0.1:{RC}/partial/nope")
    assert code == 404, code
    print("OK journal: GET /partial serves the finished result, "
          "unknown id 404s")

    k = 7
    resume = [int(t) for t in r_full.split()[:k]]
    code, body = post(RC, {"input_text": "2 3 5 7",
                           "request_id": "drive-r1",
                           "resume_tokens": resume,
                           "resume_source": "127.0.0.1:dead"})
    assert code == 200 and body["result"] == r_full, (code, body)
    ev = events(RC, "drive-r1")
    assert ev and "resumed_from" in ev and "finished" in ev, ev
    code, part = get(f"http://127.0.0.1:{RC}/partial/drive-r1")
    assert code == 200 and part.get("resumed_tokens") == k, part
    print(f"OK resume from token {k}: token-identical result, "
          "resumed_from event, journal records the resumed prefix")

    print("EVAC DRIVE PASSED")
finally:
    for p in reps + [router]:
        if p.poll() is None:
            p.kill()
            p.wait()
