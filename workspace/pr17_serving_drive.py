"""PR-17 verify drive: serving paths touched by the resource-lifecycle
leak fixes.

1. Paged engine, concurrent POSTs through the stdlib server —
   token-exact vs batch-1 generate (exercises _admit's rewritten
   try/except region on the happy path + ownership transfer).
2. Backpressure: tiny kv_num_blocks, submit > capacity — deferred
   admissions fire, everything still finishes token-exact, blocks_used
   returns to 0 (no leak: the allocator pool is whole after the storm).
3. shard_corpus + auto_split (bert_dataloader rewritten finally paths).
"""
import sys
sys.path.insert(0, "/root/repo")

import jax
jax.config.update("jax_platforms", "cpu")

import json
import os
import tempfile
import threading
import urllib.request

import jax.numpy as jnp
import numpy as np

from fengshen_tpu.api.main import (PipelineConfig, ServerConfig,
                                   build_stdlib_server,
                                   start_continuous_engine)
from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from fengshen_tpu.pipelines.text_generation import Pipeline
from fengshen_tpu.utils.generate import generate

MAX_NEW = 8


class IntTok:
    eos_token_id = None
    pad_token_id = 0

    def encode(self, text):
        return [int(t) for t in text.split()]

    def decode(self, ids):
        return " ".join(str(int(t)) for t in ids)


def build_pipe():
    cfg = LlamaConfig(vocab_size=97, hidden_size=32,
                      intermediate_size=64, num_hidden_layers=2,
                      num_attention_heads=4,
                      max_position_embeddings=64, dtype="float32")
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    return Pipeline(module=model, params=params, tokenizer=IntTok(),
                    max_new_tokens=MAX_NEW, eos_token_id=None,
                    pad_token_id=0)


def ref(pipe, prompt):
    out = np.asarray(generate(pipe.module, pipe.params,
                              jnp.asarray(prompt)[None],
                              max_new_tokens=MAX_NEW))
    return out[0, len(prompt):].tolist()


def post(port, prompt_text):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/api/text_generation",
        data=json.dumps({"input_text": prompt_text}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.loads(r.read())


def get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as r:
        return json.loads(r.read())


def drive_engine(eng_args, prompts, tag):
    pipe = build_pipe()
    engine = start_continuous_engine(pipe, dict(eng_args))
    server = build_stdlib_server(
        ServerConfig(host="127.0.0.1", port=0, engine="continuous"),
        PipelineConfig(task="text_generation"), pipeline=pipe,
        engine=engine)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        refs = {p: ref(pipe, np.asarray([int(x) for x in p.split()],
                                        np.int32))
                for p in prompts}
        results = {}

        def hit(p):
            results[p] = post(port, p)

        threads = [threading.Thread(target=hit, args=(p,))
                   for p in prompts]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        for p in prompts:
            want = " ".join(str(t) for t in refs[p])
            assert results[p]["result"] == want, (
                tag, p, results[p], want)
        return get(port, "/stats")
    finally:
        server.shutdown()
        engine.stop()


prompts = [" ".join(str(5 + i + j) for j in range(6))
           for i in range(6)]

# 1. paged happy path
stats = drive_engine({"num_slots": 4, "buckets": (8,),
                      "kv_layout": "paged", "kv_block_size": 8},
                     prompts, "paged")
assert stats["kv_layout"] == "paged", stats
assert stats["kv_blocks_used"] == 0, stats
assert stats["completed"] >= len(prompts), stats
print("paged happy path: token-exact x%d, blocks_used back to 0"
      % len(prompts))

# 2. backpressure: more demand than blocks — deferred admissions, then
#    full completion token-exact and an intact pool
stats = drive_engine({"num_slots": 4, "buckets": (8,),
                      "kv_layout": "paged", "kv_block_size": 8,
                      "kv_num_blocks": 5}, prompts, "backpressure")
assert stats["kv_blocks_used"] == 0, stats
assert stats["kv_blocks_free"] == stats["kv_blocks_total"], stats
print("backpressure: deferred=%s, pool intact (%d/%d free)"
      % (stats.get("deferred_admissions"), stats["kv_blocks_free"],
         stats["kv_blocks_total"]))

# 3. data loader rewritten finally paths
from fengshen_tpu.data.bert_dataloader.load import (auto_split,
                                                    shard_corpus)

with tempfile.TemporaryDirectory() as d:
    src = os.path.join(d, "corpus.jsonl")
    with open(src, "w") as f:
        for i in range(2000):
            f.write(json.dumps({"text": "x" * 500}) + "\n")
    shards = shard_corpus(src, os.path.join(d, "shards"), shard_mb=1)
    assert len(shards) >= 1
    total = sum(1 for s in shards for _ in open(s))
    assert total == 2000, total
    # auto_split on an oversized file (threshold 0MB forces the path)
    big_dir = os.path.join(d, "big")
    os.makedirs(big_dir)
    with open(os.path.join(big_dir, "wudao.json"), "w") as f:
        for i in range(200):
            f.write(json.dumps({"text": "y" * 100}) + "\n")
    chunks = auto_split(big_dir, threshold_mb=0, chunk_mb=0)
    assert chunks and not os.path.exists(
        os.path.join(big_dir, "wudao.json"))
    n = sum(1 for c in chunks for _ in open(c))
    assert n == 200, n
    print("data loader: %d shards (2000 rows), auto_split %d chunks "
          "(200 rows), originals closed+removed" % (len(shards),
                                                    len(chunks)))

print("PR17 SERVING DRIVE OK")
