#!/bin/bash
# Poll the relay with the watchdogged probe every 20 min; on the first
# healthy probe, run the remaining round-5 bench rows and exit. Each
# probe is a fresh process (round-3/4 practice) — at most one orphaned
# 256x256 matmul is left on an already-wedged relay per poll.
cd "$(dirname "$0")/.."
while true; do
  if python workspace/probe.py; then
    echo "relay healthy at $(date -u +%H:%M:%S) — running remaining rows"
    bash workspace/run_bench_remaining_r5.sh 2>&1 | tee /tmp/bench_remaining_r5.log
    exit 0
  fi
  sleep 1200
done
