"""PR 11 verify drive: the REAL distributed-tracing surface end to end.

Spawns two real replica subprocesses + the REAL router process (the
PR-10 fleet_drive recipe), then proves over HTTP: a routed generate is
token-exact AND returns a trace_id; GET /debug/traces/<trace_id>
assembles ONE cross-process document (router span ledger + the
replica's waterfall, phases summing exactly, clock offset/skew
reported); an incoming traceparent is JOINED; /fleet carries the new
poll-staleness fields; /metrics renders the attempt histogram + trace
counters; and `python -m fengshen_tpu.observability.traceview`
converts the assembled doc to loadable Chrome trace-event JSON.
"""
import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

sys.path.insert(0, "/root/repo")
import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")

ENV = {**os.environ, "JAX_PLATFORMS": "cpu",
       "FLEET_BENCH_VOCAB": "256", "FLEET_BENCH_HIDDEN": "64",
       "FLEET_BENCH_INTER": "128", "FLEET_BENCH_LAYERS": "2",
       "FLEET_BENCH_HEADS": "4", "FLEET_BENCH_BUCKETS": "16,32",
       "FLEET_BENCH_NEW_TOKENS": "8", "FLEET_BENCH_SLOTS": "2"}

P1, P2, RP = 8471, 8472, 8470


def get(url, timeout=5, headers=None):
    req = urllib.request.Request(url, headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def post(url, body, timeout=60, headers=None):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def wait_200(url, deadline_s=120):
    t0 = time.time()
    while time.time() - t0 < deadline_s:
        try:
            if get(url)[0] == 200:
                return True
        except OSError:
            pass
        time.sleep(0.2)
    return False


reps = [subprocess.Popen(
    [sys.executable, "-m", "fengshen_tpu.fleet.bench", "--replica",
     "--port", str(p)], env=ENV) for p in (P1, P2)]
router = subprocess.Popen(
    [sys.executable, "-m", "fengshen_tpu.fleet",
     "--replicas", f"127.0.0.1:{P1},127.0.0.1:{P2}",
     "--host", "127.0.0.1", "--port", str(RP),
     "--poll-interval", "0.2", "--recovery-probes", "1"], env=ENV)

try:
    assert wait_200(f"http://127.0.0.1:{RP}/healthz"), "router not up"
    t0 = time.time()
    while time.time() - t0 < 30:
        code, fleet = get(f"http://127.0.0.1:{RP}/fleet")
        if fleet["healthy"] == 2:
            break
        time.sleep(0.2)
    assert fleet["healthy"] == 2, fleet
    print("OK router up, 2 healthy")

    # ---- satellite: /fleet poll-staleness fields --------------------
    for rep in fleet["replicas"]:
        assert isinstance(rep["last_poll_age_s"], (int, float)), rep
        assert rep["last_poll_age_s"] < 5.0, rep
        assert rep["consecutive_failures"] == 0, rep
    print("OK /fleet last_poll_age_s + consecutive_failures")

    # ---- traced, token-exact generate through the router ------------
    import jax.numpy as jnp
    import numpy as np
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.utils.generate import generate
    cfg = LlamaConfig(vocab_size=256, hidden_size=64,
                      intermediate_size=128, num_hidden_layers=2,
                      num_attention_heads=4,
                      max_position_embeddings=40, dtype="float32")
    model = LlamaForCausalLM(cfg)
    params = jax.jit(lambda r: model.init(
        r, jnp.zeros((1, 8), jnp.int32))["params"])(
        jax.random.PRNGKey(0))
    prompt = [5, 7, 9, 11]
    ref = np.asarray(generate(
        model, params, jnp.asarray(prompt)[None],
        max_new_tokens=8))[0, len(prompt):].tolist()
    code, body = post(f"http://127.0.0.1:{RP}/api/text_generation",
                      {"input_text": "5 7 9 11"})
    assert code == 200, (code, body)
    assert body["result"] == " ".join(str(t) for t in ref), body
    tid, rid = body["trace_id"], body["request_id"]
    assert re.fullmatch(r"[0-9a-f]{32}", tid), tid
    print("OK token-exact through router, trace_id", tid)

    # ---- cross-process assembly at the router -----------------------
    code, doc = get(f"http://127.0.0.1:{RP}/debug/traces/{tid}")
    assert code == 200, (code, doc)
    assert doc["schema"] == 1 and doc["trace_id"] == tid, doc
    assert doc["request_id"] == rid, doc
    names = [s["name"] for s in doc["router"]["spans"]]
    for want in ("fleet/request", "router/enqueue",
                 "router/placement", "router/attempt"):
        assert want in names, names
    att = [s for s in doc["router"]["spans"]
           if s["name"] == "router/attempt"]
    assert len(att) == 1 and att[0]["attrs"]["outcome"] == "ok", att
    assert len(doc["replicas"]) == 1, list(doc["replicas"])
    (rep_name, entry), = doc["replicas"].items()
    wf = entry["waterfall"]
    assert wf["trace_id"] == tid, wf
    ph = wf["phases"]
    total = ph["queue_wait_s"] + ph["prefill_s"] + ph["decode_s"]
    assert abs(total - ph["total_s"]) < 1e-3, ph
    assert isinstance(entry["offset_in_trace_s"], float), entry
    assert isinstance(entry["clock_skew_s"], float), entry
    print("OK assembled trace: 1 attempt span on", rep_name,
          "phases sum", round(total, 4), "skew",
          entry["clock_skew_s"])

    # the replica's own debug ring carries the correlation too
    port = int(rep_name.rsplit(":", 1)[1])
    code, payload = get(f"http://127.0.0.1:{port}/debug/requests/{rid}")
    assert code == 200 and payload["trace_id"] == tid, payload
    # unknown trace id -> 404
    code, _ = get(f"http://127.0.0.1:{RP}/debug/traces/{'0' * 32}")
    assert code == 404, code
    print("OK replica ring correlation + unknown-trace 404")

    # ---- joining an incoming traceparent ----------------------------
    incoming = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
    code, body = post(f"http://127.0.0.1:{RP}/api/text_generation",
                      {"input_text": "5 7 9 11"},
                      headers={"traceparent": incoming})
    assert code == 200 and body["trace_id"] == "ab" * 16, body
    print("OK joined caller traceparent")

    # ---- router metrics: attempt histogram + trace counters ---------
    with urllib.request.urlopen(
            f"http://127.0.0.1:{RP}/metrics", timeout=5) as r:
        text = r.read().decode()
    assert 'fstpu_fleet_attempt_seconds_bucket{outcome="ok"' in text
    assert "fstpu_trace_started_total 2" in text, text[:500]
    assert "fstpu_trace_assembled_total 1" in text
    assert 'fstpu_http_request_seconds_bucket{route="/fleet"' in text
    print("OK /metrics attempt histogram + trace counters")

    # ---- traceview: assembled doc -> Chrome trace-event JSON --------
    with tempfile.TemporaryDirectory() as d:
        src = os.path.join(d, "trace.json")
        out = os.path.join(d, "out.json")
        with open(src, "w") as f:
            json.dump(doc, f)
        rc = subprocess.run(
            [sys.executable, "-m",
             "fengshen_tpu.observability.traceview", src, "-o", out],
            env=ENV, capture_output=True, text=True)
        assert rc.returncode == 0, rc.stderr
        with open(out) as f:
            chrome = json.load(f)
    assert chrome["displayTimeUnit"] == "ms", chrome.keys()
    evs = chrome["traceEvents"]
    metas = [e for e in evs if e["ph"] == "M"]
    spans = [e for e in evs if e["ph"] == "X"]
    assert {m["args"]["name"] for m in metas} >= {"router", rep_name}
    for e in spans:
        assert isinstance(e["ts"], int) and isinstance(e["dur"], int)
        assert e["ts"] >= 0, e
    assert any(e["name"] == "router/attempt" for e in spans)
    assert any(e["name"] == "decode" for e in spans)
    print("OK traceview:", len(spans), "spans,", len(metas),
          "process rows")

    print("TRACE DRIVE PASSED")
finally:
    for p in reps + [router]:
        if p.poll() is None:
            p.kill()
            p.wait()
