"""Per-dispatch latency probe for the axon relay (round-5 diagnostic).

The round-5 window measured trainer rows ~3x below round-2 on identical
programs; one hypothesis is per-dispatch round-trip latency through the
relay tunnel. This probe separates the two costs directly:

- sync:   N tiny matmuls, each dispatched and blocked on individually —
          time/N ≈ dispatch RTT + op time.
- async:  the same N dispatched back-to-back, one final block — measures
          whether the client pipelines dispatches.
- fused:  one jitted lax.fori_loop of N matmuls — a single dispatch;
          time/N ≈ pure op time.

sync/fused ratio ≈ the per-dispatch tax a train step pays when host
code syncs every step; async vs sync shows whether enqueueing hides it.
Run ONLY after the 256x256 probe succeeds; self-watchdogged (no
external timeouts — see NOTES.md wedge protocol).
"""

import json
import os
import threading
import time

_done = threading.Event()
DEADLINE = float(os.environ.get("PROBE_DEADLINE", "300"))


def _watch():
    if not _done.wait(DEADLINE):
        import sys
        sys.stderr.write("dispatch_latency_probe: WEDGED, aborting\n")
        sys.stderr.flush()
        os._exit(3)


threading.Thread(target=_watch, daemon=True).start()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402

N = int(os.environ.get("PROBE_N", "50"))
D = int(os.environ.get("PROBE_DIM", "512"))

x = jnp.ones((D, D), jnp.bfloat16)
mm = jax.jit(lambda a: a @ a)
mm(x).block_until_ready()  # compile + warm


@jax.jit
def fused(a):
    return lax.fori_loop(0, N, lambda _, c: c @ c, a)


fused(x).block_until_ready()  # compile + warm

t0 = time.perf_counter()
for _ in range(N):
    mm(x).block_until_ready()
sync_s = time.perf_counter() - t0

t0 = time.perf_counter()
y = x
for _ in range(N):
    y = mm(y)
y.block_until_ready()
async_s = time.perf_counter() - t0

t0 = time.perf_counter()
fused(x).block_until_ready()
fused_s = time.perf_counter() - t0

_done.set()
print(json.dumps({
    "n": N, "dim": D,
    "sync_ms_per_dispatch": round(1e3 * sync_s / N, 3),
    "async_ms_per_dispatch": round(1e3 * async_s / N, 3),
    "fused_ms_per_op": round(1e3 * fused_s / N, 3),
    "dispatch_tax_ratio_sync_vs_fused": round(sync_s / max(fused_s, 1e-9),
                                              2),
}))
