"""PR 18 verify drive: kernel dispatch seam end-to-end through public surfaces.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 python workspace/kernel_drive.py

Sections (each prints one OK line):
  1. registry   — probe/dispatch_table/kernel_fingerprint/FSTPU_KERNEL_FORCE
  2. serving    — paged int8 engine + stdlib server: concurrent POSTs
                  token-exact vs generate; kernel_dispatch is the FIRST
                  engine event; fstpu_kernel_dispatch gauge on /metrics
  3. interpret  — decode_attention pallas interpret-mode vs xla lowering
  4. fused_ce   — replicated seam sanity (ln V + 0.5) + grads; sharded-vocab
                  fused CE bitwise vs vocab_parallel_cross_entropy on mesh8
"""
import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import json
import math
import threading
import urllib.request

import jax.numpy as jnp
import numpy as np


def section_registry():
    from fengshen_tpu.ops import pallas as P

    pr = P.probe(refresh=True)
    assert pr.backend == "cpu" and pr.pallas_tpu is False and pr.reason, pr
    table = P.dispatch_table()
    assert set(table) >= {"decode_attention", "fused_ce", "flash_attention",
                          "block_sparse_attention"}, table
    assert all(v == "xla" for v in table.values()), table
    fp = P.kernel_fingerprint()
    assert fp.startswith("kernels=") and "backend=cpu" in fp, fp
    assert "decode_attention:xla" in fp, fp
    os.environ["FSTPU_KERNEL_FORCE"] = "pallas"
    try:
        forced = P.probe(refresh=True)
        assert forced.pallas_tpu is True and forced.forced == "pallas", forced
        assert P.kernel_choice("decode_attention") == "pallas"
        fp2 = P.kernel_fingerprint()
        assert fp2 != fp and "decode_attention:pallas" in fp2, fp2
    finally:
        del os.environ["FSTPU_KERNEL_FORCE"]
        P.probe(refresh=True)
    print("OK registry:", fp)


def _http(url, payload=None):
    if payload is not None:
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
    else:
        req = url
    with urllib.request.urlopen(req, timeout=120) as r:
        body = r.read().decode()
        return r.status, body


def section_serving():
    import re

    from fengshen_tpu.api.main import (PipelineConfig, ServerConfig,
                                       build_stdlib_server,
                                       start_continuous_engine)
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.pipelines.text_generation import Pipeline
    from fengshen_tpu.utils.generate import generate

    class _IntTok:
        pad_token_id = 0
        eos_token_id = 1

        def __call__(self, text, **kw):
            return {"input_ids": [[int(t) for t in text.split()]]}

        def encode(self, text, **kw):
            return [int(t) for t in text.split()]

        def decode(self, ids, **kw):
            return " ".join(str(int(i)) for i in ids)

    cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256)
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.ones((1, 8), dtype=jnp.int32))["params"]
    pipe = Pipeline(module=model, params=params, tokenizer=_IntTok())

    captured = []
    engine = start_continuous_engine(
        pipe,
        {"num_slots": 4, "buckets": [16],
         "kv_layout": "paged", "kv_dtype": "int8", "kv_block_size": 16},
        log=captured.append,
    )
    # the dispatch decision must be the FIRST structured event the engine logs
    first = captured[0]
    assert first["event"] == "kernel_dispatch", captured[:2]
    assert first["table"]["decode_attention"] == "xla", first
    assert first["backend"] == "cpu", first

    server = build_stdlib_server(
        ServerConfig(host="127.0.0.1", port=0, engine="continuous"),
        PipelineConfig(task="text_generation"),
        pipeline=pipe, engine=engine)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    port = server.server_address[1]
    base = f"http://127.0.0.1:{port}"
    try:
        prompts = ["5 9 13 7", "3 3 3", "11 2 8 10 6"]
        results = [None] * len(prompts)

        def post(i):
            _, body = _http(f"{base}/api/text_generation",
                            {"input_text": prompts[i], "max_new_tokens": 8})
            results[i] = json.loads(body)["result"]

        threads = [threading.Thread(target=post, args=(i,)) for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i, ptxt in enumerate(prompts):
            ids = jnp.asarray([[int(t) for t in ptxt.split()]], dtype=jnp.int32)
            ref = generate(model, params, ids, max_new_tokens=8,
                           eos_token_id=1, pad_token_id=0)
            new = np.asarray(ref)[0][ids.shape[1]:]  # server returns new tokens only
            ref_txt = " ".join(str(int(x)) for x in new)
            assert results[i] == ref_txt, (i, results[i], ref_txt)

        _, metrics = _http(f"{base}/metrics")
        gauge_lines = [l for l in metrics.splitlines()
                       if l.startswith("fstpu_kernel_dispatch{")]
        assert any('op="decode_attention"' in l and 'impl="xla"' in l
                   and l.rstrip().endswith(" 1") for l in gauge_lines), gauge_lines
        assert any('op="decode_attention"' in l and 'impl="pallas"' in l
                   and l.rstrip().endswith(" 0") for l in gauge_lines), gauge_lines
        for line in metrics.splitlines():
            if line and not line.startswith("#"):
                assert re.match(r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{.*\})? [^ ]+$", line), line
    finally:
        server.shutdown()
        engine.stop()
    print("OK serving: paged-int8 engine token-exact through the seam; "
          "kernel_dispatch first event; gauge rendered")


def section_interpret():
    from fengshen_tpu.ops.pallas import decode_attention

    rng = np.random.default_rng(7)
    B, H, KVH, D, BS, NB = 2, 4, 2, 128, 128, 4
    S = BS * 2  # 2 blocks per lane
    q = jnp.asarray(rng.standard_normal((B, 1, H, D)), dtype=jnp.float32)
    k_pool = jnp.asarray(rng.standard_normal((NB, BS, KVH, D)), dtype=jnp.float32)
    v_pool = jnp.asarray(rng.standard_normal((NB, BS, KVH, D)), dtype=jnp.float32)
    table = jnp.asarray([[2, 0], [3, 1]], dtype=jnp.int32)
    ctx = np.asarray([S - 17, S - 5])
    valid = jnp.asarray(np.arange(S)[None, None, :] < ctx[:, None, None])
    out_x = decode_attention(q, k_pool, v_pool, valid, block_table=table,
                             impl="xla")
    out_p = decode_attention(q, k_pool, v_pool, valid, block_table=table,
                             impl="pallas", interpret=True)
    np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_x),
                               rtol=2e-5, atol=2e-5)
    print("OK interpret: pallas decode kernel (interpret) matches xla lowering")


def section_fused_ce():
    from fengshen_tpu.ops.pallas import fused_ce_loss
    from fengshen_tpu.parallel import (
        MeshConfig, fused_vocab_parallel_ce, make_mesh, set_mesh,
        vocab_parallel_cross_entropy)

    rng = np.random.default_rng(3)
    B, S, Dh, V = 2, 16, 32, 512
    hidden = jnp.asarray(rng.standard_normal((B, S, Dh)) * 0.02, jnp.float32)
    kernel = jnp.asarray(rng.standard_normal((Dh, V)) * 0.02, jnp.float32)
    labels = jnp.asarray(rng.integers(0, V, (B, S)), jnp.int32)
    loss, n_valid, _ = fused_ce_loss(hidden, kernel, labels, num_chunks=4)
    # tiny-scale random logits are ~uniform: CE ~= ln(V) (+0.5 only at unit scale)
    assert abs(float(loss) - math.log(V)) < 0.5, (float(loss), math.log(V))
    assert int(n_valid) == B * S
    g = jax.grad(lambda h, w: fused_ce_loss(h, w, labels, num_chunks=4)[0],
                 argnums=(0, 1))(hidden, kernel)
    assert all(bool(jnp.all(jnp.isfinite(x))) for x in g)

    mesh = make_mesh(MeshConfig(data=2, fsdp=2, sequence=1, tensor=2))
    set_mesh(mesh)
    try:
        V2 = 64
        kernel2 = jnp.asarray(rng.standard_normal((Dh, V2)) * 0.2, jnp.float32)
        labels2 = jnp.asarray(rng.integers(0, V2, (B, S)), jnp.int32)
        logits = hidden @ kernel2
        ref = vocab_parallel_cross_entropy(logits, labels2, mesh=mesh)
        fused = fused_vocab_parallel_ce(hidden, kernel2, labels2, mesh=mesh,
                                        num_chunks=4)
        assert float(fused[0]) == float(ref[0]), (float(fused[0]), float(ref[0]))
    finally:
        set_mesh(None)
    print("OK fused_ce: replicated seam ~ln(V) with finite grads; "
          "sharded-vocab fused CE bitwise vs unfused on the 2x2x2 mesh")


if __name__ == "__main__":
    section_registry()
    section_serving()
    section_interpret()
    section_fused_ce()
    print("DRIVE PASSED")
