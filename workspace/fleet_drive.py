"""PR 10 verify drive: the REAL fleet surface end to end.

Spawns two real replica subprocesses (fleet.bench --replica, tiny
shapes), fronts them with the REAL router process
(`python -m fengshen_tpu.fleet --replicas ...`), and proves over HTTP:
token-exact generate through the router, /fleet + /metrics + /healthz,
routing around a SIGTERMed (draining) replica, structured 503 at zero
healthy, and the router's own SIGTERM drain (exit 0).
"""
import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

sys.path.insert(0, "/root/repo")
import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")

ENV = {**os.environ, "JAX_PLATFORMS": "cpu",
       "FLEET_BENCH_VOCAB": "256", "FLEET_BENCH_HIDDEN": "64",
       "FLEET_BENCH_INTER": "128", "FLEET_BENCH_LAYERS": "2",
       "FLEET_BENCH_HEADS": "4", "FLEET_BENCH_BUCKETS": "16,32",
       "FLEET_BENCH_NEW_TOKENS": "8", "FLEET_BENCH_SLOTS": "2"}

P1, P2, RP = 8461, 8462, 8460


def get(url, timeout=5):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def post(url, body, timeout=60):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def wait_200(url, deadline_s=120):
    t0 = time.time()
    while time.time() - t0 < deadline_s:
        try:
            if get(url)[0] == 200:
                return True
        except OSError:
            pass
        time.sleep(0.2)
    return False


reps = [subprocess.Popen(
    [sys.executable, "-m", "fengshen_tpu.fleet.bench", "--replica",
     "--port", str(p)], env=ENV) for p in (P1, P2)]
router = subprocess.Popen(
    [sys.executable, "-m", "fengshen_tpu.fleet",
     "--replicas", f"127.0.0.1:{P1},127.0.0.1:{P2}",
     "--host", "127.0.0.1", "--port", str(RP),
     "--poll-interval", "0.2", "--recovery-probes", "1",
     "--breaker-threshold", "1"], env=ENV)

try:
    assert wait_200(f"http://127.0.0.1:{RP}/healthz"), "router not up"
    # both replicas in rotation
    t0 = time.time()
    while time.time() - t0 < 30:
        code, fleet = get(f"http://127.0.0.1:{RP}/fleet")
        if fleet["healthy"] == 2:
            break
        time.sleep(0.2)
    assert fleet["healthy"] == 2, fleet
    print("OK router up, 2 healthy")

    # token-exact generate THROUGH the router
    import jax.numpy as jnp
    import numpy as np
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.utils.generate import generate
    cfg = LlamaConfig(vocab_size=256, hidden_size=64,
                      intermediate_size=128, num_hidden_layers=2,
                      num_attention_heads=4,
                      max_position_embeddings=40, dtype="float32")
    model = LlamaForCausalLM(cfg)
    params = jax.jit(lambda r: model.init(
        r, jnp.zeros((1, 8), jnp.int32))["params"])(
        jax.random.PRNGKey(0))
    prompt = [5, 7, 9, 11]
    ref = np.asarray(generate(
        model, params, jnp.asarray(prompt)[None],
        max_new_tokens=8))[0, len(prompt):].tolist()
    code, body = post(f"http://127.0.0.1:{RP}/api/text_generation",
                      {"input_text": "5 7 9 11"})
    assert code == 200, (code, body)
    assert body["result"] == " ".join(str(t) for t in ref), body
    assert body["request_id"].startswith("fleet-")
    print("OK token-exact through router:", body["result"])

    # /metrics renders the fleet registry
    with urllib.request.urlopen(
            f"http://127.0.0.1:{RP}/metrics", timeout=5) as r:
        text = r.read().decode()
    assert 'fstpu_fleet_replicas{state="healthy"} 2' in text, text[:500]
    assert "fstpu_fleet_requests_total 1" in text
    print("OK /metrics")

    # SIGTERM replica 1: graceful drain -> router routes around it.
    # (An IDLE replica drains and exits almost immediately, so its
    # draining-503 window may already be over by the time we probe —
    # the while-in-flight healthz body is pinned deterministically in
    # tests/test_fleet.py; here we assert the fleet-level effect.)
    reps[0].send_signal(signal.SIGTERM)
    try:
        code, body = get(f"http://127.0.0.1:{P1}/healthz")
        assert code == 503 and body["reason"] == "draining", body
        print("OK caught replica draining-503 window")
    except OSError:
        print("OK replica already drained+exited (idle)")
    t0 = time.time()
    while time.time() - t0 < 15:
        code, fleet = get(f"http://127.0.0.1:{RP}/fleet")
        if fleet["healthy"] == 1:
            break
        time.sleep(0.2)
    assert fleet["healthy"] == 1, fleet
    for i in range(3):
        code, body = post(
            f"http://127.0.0.1:{RP}/api/text_generation",
            {"input_text": "5 7 9 11"})
        assert code == 200, (code, body)
        assert body["result"] == " ".join(str(t) for t in ref)
    print("OK routed around draining replica; replica1 exits",
          reps[0].wait(timeout=30))

    # kill replica 2 hard: zero healthy -> structured 503
    reps[1].kill()
    reps[1].wait()
    t0 = time.time()
    while time.time() - t0 < 20:
        code, body = get(f"http://127.0.0.1:{RP}/healthz")
        if code == 503:
            break
        time.sleep(0.2)
    assert code == 503 and body["reason"] == "no_healthy_replicas", body
    assert f"127.0.0.1:{P2}" in body["replicas"], body
    code, body = post(f"http://127.0.0.1:{RP}/api/text_generation",
                      {"input_text": "5 7"})
    assert code == 503 and body["reason"] == "no_healthy_replicas"
    assert body["replicas"], body
    print("OK structured zero-healthy 503")

    # router SIGTERM drain: healthz flips, process exits 0
    router.send_signal(signal.SIGTERM)
    rc = router.wait(timeout=60)
    assert rc == 0, rc
    print("OK router drained and exited 0")
    print("FLEET DRIVE PASSED")
finally:
    for p in reps + [router]:
        if p.poll() is None:
            p.kill()
            p.wait()
