"""PR-19 drive: declarative sharding subsystem + multimodal serving,
through PUBLIC exports only (docs/sharding.md, docs/serving.md
"Multimodal engines").

Forced-CPU 8-virtual-device recipe (axon sitecustomize ignores
JAX_PLATFORMS): run as
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python workspace/sharding_mm_drive.py
"""

import sys

sys.path.insert(0, "/root/repo")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import json  # noqa: E402
import threading  # noqa: E402
import urllib.request  # noqa: E402

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

# ---- 1. the rules table API ----------------------------------------------
from fengshen_tpu.sharding import (DEFAULT_LOGICAL_AXIS_RULES,  # noqa: E402
                                   resolve_spec, rules_fingerprint,
                                   use_rules, validate_rules)

validate_rules(DEFAULT_LOGICAL_AXIS_RULES)
assert resolve_spec(("embed", "heads")) == P("fsdp", "tensor")
assert resolve_spec(("batch", "seq", None)) == \
    P(("data", "fsdp"), "sequence", None)
fp_default = rules_fingerprint()
assert fp_default.startswith("lar1:")
custom = tuple((k, None) if k == "mlp" else (k, v)
               for k, v in DEFAULT_LOGICAL_AXIS_RULES)
with use_rules(custom):
    assert resolve_spec(("embed", "mlp")) == P("fsdp", None)
    assert rules_fingerprint() != fp_default
assert resolve_spec(("embed", "mlp")) == P("fsdp", "tensor")
try:
    validate_rules((("heads", "tenosr"),))
    raise SystemExit("typo table validated?!")
except ValueError:
    pass
print("[1] rules table API ok:", fp_default)

# ---- 2. sharded llama greedy decode token-identical ----------------------
from fengshen_tpu.models.llama import (LlamaConfig,  # noqa: E402
                                       LlamaForCausalLM)
from fengshen_tpu.parallel import (MeshConfig, make_mesh,  # noqa: E402
                                   make_shardings, set_mesh)
from fengshen_tpu.utils.generate import generate  # noqa: E402

assert len(jax.devices()) == 8, "need XLA_FLAGS device_count=8"
mesh = make_mesh(MeshConfig(data=2, fsdp=2, sequence=1, tensor=2))
set_mesh(mesh)
cfg = LlamaConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                  num_hidden_layers=2, num_attention_heads=4,
                  max_position_embeddings=48, dtype="float32")
model = LlamaForCausalLM(cfg)
ids = jnp.asarray(np.random.RandomState(0).randint(3, 127, (2, 8)))
params = model.init(jax.random.PRNGKey(0), ids)["params"]
ref = np.asarray(generate(model, params, ids, max_new_tokens=12,
                          eos_token_id=None, pad_token_id=0))
sharded = jax.device_put(params,
                         make_shardings(model.partition_rules(),
                                        params, mesh))
qk = sharded["model"]["layers_0"]["self_attn"]["q_proj"]["kernel"]
assert any(a is not None for a in qk.sharding.spec), "not sharded"
out = np.asarray(generate(model, sharded, ids, max_new_tokens=12,
                          eos_token_id=None, pad_token_id=0))
np.testing.assert_array_equal(out, ref)
print("[2] sharded llama greedy decode token-identical on 2x2x2 mesh")

# ---- 3. multimodal serving end-to-end ------------------------------------
from fengshen_tpu.api.main import (PipelineConfig,  # noqa: E402
                                   ServerConfig, build_stdlib_server)
from fengshen_tpu.pipelines.embedding import Pipeline  # noqa: E402
from fengshen_tpu.serving import create_multimodal_engine  # noqa: E402

pipe = Pipeline(small_test=True, seed=0)
eng = create_multimodal_engine("embedding", pipe,
                               {"max_batch": 2, "gather_ms": 2.0})
print("[3] embedding warmup:", round(eng.warmup(), 2), "s")
eng.start()
server = build_stdlib_server(
    ServerConfig(host="127.0.0.1", port=0, engine="embedding"),
    PipelineConfig(task="embedding"), pipeline=pipe, engine=eng)
port = server.server_address[1]
threading.Thread(target=server.serve_forever, daemon=True).start()
try:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/api/embedding",
        data=json.dumps({"input_text": "今天天气真好"}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as r:
        body = json.loads(r.read())
    assert body["engine_type"] == "embedding"
    emb = body["result"]["embedding"]
    assert abs(sum(x * x for x in emb) - 1.0) < 1e-3
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/stats",
                                timeout=10) as r:
        stats = json.loads(r.read())
    assert stats["engine_type"] == "embedding"
    assert stats["requests_total"] >= 1
    print("[3] embedding served over HTTP: dim", body["result"]["dim"],
          "| stats", {k: stats[k] for k in ("engine_type",
                                            "batches_total",
                                            "avg_batch")})
finally:
    server.shutdown()
    eng.stop()

print("DRIVE OK")
