"""PR 12 verify drive: the REAL prefill/decode disaggregation surface.

Spawns one prefill-tier + one decode-tier replica subprocess (the
disagg bench's random-init llama + DisaggCoordinator) fronted by the
REAL router process, then proves over HTTP: /fleet shows the
"prefill=1,decode=1" topology and per-replica phases; a routed
generate is token-exact vs utils.generate.generate AND comes back
with "adopted": true (the lane really primed on the prefill replica,
moved int8-on-the-wire over PUT /kv/<id>, and finished on the decode
replica); both replicas' /metrics and /debug/requests carry the
handoff counters and timeline events; the assembled trace shows both
processes; and hard-killing the decode tier degrades to local
prefill-and-decode with the SAME tokens and no client error.
"""
import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

sys.path.insert(0, "/root/repo")
import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")

NEW_TOKENS = 48
ENV = {**os.environ, "JAX_PLATFORMS": "cpu",
       "DISAGG_BENCH_NEW_TOKENS": str(NEW_TOKENS)}

PP, DP, RP = 8481, 8482, 8480


def get(url, timeout=5):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def post(url, body, timeout=120):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def metrics(port):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
        return r.read().decode()


def events(port, rid):
    code, payload = get(f"http://127.0.0.1:{port}/debug/requests/{rid}")
    assert code == 200, (code, payload)
    return [e["event"] for e in payload["events"]]


reps = [subprocess.Popen(
    [sys.executable, "-m", "fengshen_tpu.disagg.bench", "--replica",
     "--port", str(p), "--phase", ph], env=ENV)
    for p, ph in ((PP, "prefill"), (DP, "decode"))]
router = subprocess.Popen(
    [sys.executable, "-m", "fengshen_tpu.fleet",
     "--replicas", f"127.0.0.1:{PP},127.0.0.1:{DP}",
     "--host", "127.0.0.1", "--port", str(RP),
     "--poll-interval", "0.2", "--recovery-probes", "1",
     "--request-timeout", "120"], env=ENV)

try:
    t0 = time.time()
    fleet = {}
    while time.time() - t0 < 180:
        try:
            code, fleet = get(f"http://127.0.0.1:{RP}/fleet")
            if fleet.get("healthy") == 2:
                break
        except OSError:
            pass
        time.sleep(0.3)
    assert fleet.get("healthy") == 2, fleet

    # ---- topology + per-replica phase in /fleet ---------------------
    assert fleet["topology"] == "prefill=1,decode=1", fleet
    phases = {r["name"]: r["phase"] for r in fleet["replicas"]}
    assert phases == {f"127.0.0.1:{PP}": "prefill",
                      f"127.0.0.1:{DP}": "decode"}, phases
    print("OK fleet up, topology", fleet["topology"])

    # ---- the greedy reference (same random-init model) --------------
    import jax.numpy as jnp
    import numpy as np
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.utils.generate import generate
    cfg = LlamaConfig(vocab_size=4096, hidden_size=1024,
                      intermediate_size=2816, num_hidden_layers=4,
                      num_attention_heads=8,
                      max_position_embeddings=64 + NEW_TOKENS,
                      dtype="float32")
    model = LlamaForCausalLM(cfg)
    params = jax.jit(lambda r: model.init(
        r, jnp.zeros((1, 8), jnp.int32))["params"])(
        jax.random.PRNGKey(0))
    prompt = [5, 7, 9, 11]
    ref = " ".join(str(t) for t in np.asarray(generate(
        model, params, jnp.asarray(prompt)[None],
        max_new_tokens=NEW_TOKENS))[0, len(prompt):].tolist())

    # ---- a REAL handoff, visibly redirected -------------------------
    code, body = post(f"http://127.0.0.1:{RP}/api/text_generation",
                      {"input_text": "5 7 9 11"})
    assert code == 200, (code, body)
    assert body.get("adopted") is True, body
    rid, tid = body["request_id"], body["trace_id"]
    print("OK redirected generate", rid)

    # counters: prefill redirected, decode adopted, zero fallbacks
    mp, md = metrics(PP), metrics(DP)
    assert 'fstpu_disagg_handoffs_total{outcome="redirected"} 1' in mp
    assert "fstpu_disagg_fallbacks_total{" not in mp, mp
    assert "fstpu_disagg_adopted_total 1" in md
    # timeline events on BOTH processes
    ep, ed = events(PP, rid), events(DP, rid)
    assert "handoff_export" in ep and "handed_off" in ep, ep
    assert "adopted" in ed and "finished" in ed, ed
    print("OK handoff counters + timeline events on both replicas")

    # exactness contract (docs/disaggregation.md "int8 on the wire"):
    # the prefix the prefill replica committed BEFORE export travels
    # int8-quantized, so on a real-size fp32 model greedy may diverge
    # AFTER the handoff point (near-tie logits) — the pre-export
    # prefix itself must be token-exact vs the single-engine
    # reference, and the full tail must arrive. (The bit-exact pins —
    # int8->int8 verbatim wire, tiny-fixture all-combo identity —
    # live in tests/test_disagg.py.)
    toks, ref_toks = body["result"].split(), ref.split()
    k = sum(1 for e in ep[:ep.index("handoff_export")]
            if e in ("first_token", "commit"))
    assert k >= 1 and toks[:k] == ref_toks[:k], (k, toks[:k],
                                                 ref_toks[:k])
    assert len(toks) == NEW_TOKENS, len(toks)
    print(f"OK {k} pre-export tokens exact, {len(toks)}-token tail "
          "completed on the decode tier"
          + ("" if toks == ref_toks else
             f" (greedy diverged at {next(i for i in range(len(toks)) if toks[i] != ref_toks[i])}: int8-wire tolerance)"))

    # the assembled trace stitches both processes
    code, doc = get(f"http://127.0.0.1:{RP}/debug/traces/{tid}")
    assert code == 200, (code, doc)
    assert set(doc["replicas"]) == {f"127.0.0.1:{PP}",
                                    f"127.0.0.1:{DP}"}, doc["replicas"]
    print("OK assembled trace covers prefill + decode processes")

    # ---- decode tier dies -> degrade to local, same tokens ----------
    reps[1].kill()
    reps[1].wait()
    t0 = time.time()
    while time.time() - t0 < 30:
        code, fleet = get(f"http://127.0.0.1:{RP}/fleet")
        if fleet["healthy"] == 1:
            break
        time.sleep(0.2)
    assert fleet["healthy"] == 1, fleet
    code, body = post(f"http://127.0.0.1:{RP}/api/text_generation",
                      {"input_text": "5 7 9 11"})
    assert code == 200, (code, body)
    assert body["result"] == ref, (body["result"], ref)
    assert body.get("adopted") is None, body
    print("OK degenerate topology: local prefill-and-decode, "
          "same tokens, no client error")

    print("DISAGG DRIVE PASSED")
finally:
    for p in reps + [router]:
        if p.poll() is None:
            p.kill()
            p.wait()
