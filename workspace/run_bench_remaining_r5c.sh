#!/bin/bash
# Rows still pending after the SECOND round-5 hardware window
# (2026-07-31 06:26-06:39 UTC; the int8 LM-head TRAIN row wedged the
# relay — the second wedge attributable to an int8 row, so BOTH int8
# rows now sit at the wedge-suspect end with block-sparse). Banked in
# that window: 13B-shape l4xb1 211.1 tok/s/chip (first 13B-shape
# hardware row), default 300M 25,410 tok/s/chip, dispatch-latency
# probe 0.17/0.058 ms (docs/performance.md "Round-5 second window").
# Same rules as ever: NEVER wrap any row in `timeout`; every script
# self-aborts via an in-process watchdog.
set -uo pipefail
cd "$(dirname "$0")/.."

probe() {
  python workspace/probe.py || exit 1
}

echo "== probe"; probe

echo "== dispatch-latency A/B: 5 steps per jitted execution (vs banked 25,760 sharded row)"
BENCH_CONFIG=sharded BENCH_STEPS_PER_EXEC=5 python bench.py | tee /tmp/bench_sharded_spe5.json

echo "== probe"; probe

echo "== measured 7GB claim: 1.3B AFQMC shape with param streaming"
python workspace/offload_7gb_check.py | tee /tmp/bench_offload_7gb.json

echo "== probe"; probe

echo "== decode throughput: seq2seq beam-4 (T5-base shape)"
BENCH_CONFIG=decode BENCH_DECODE=beam python bench.py | tee /tmp/bench_decode_beam.json

echo "== probe"; probe

echo "== decode throughput: speculative (2-layer draft, gamma 4; mechanism-overhead row on random weights)"
BENCH_CONFIG=decode BENCH_DECODE=spec python bench.py | tee /tmp/bench_decode_spec.json || true

echo "== probe"; probe

echo "== decode throughput: draft-free prompt-lookup (random weights loop, so lookup accepts for real)"
BENCH_CONFIG=decode BENCH_DECODE=lookup python bench.py | tee /tmp/bench_decode_lookup.json || true

echo "== probe"; probe

echo "== 13B-shape l8xb4 retry (died in the remote-compile helper last window, HTTP 500 — terminal-side)"
BENCH_CONFIG=large BENCH_LAYERS=8 BENCH_BATCH=4 BENCH_FUSED_CE=8 python bench.py | tee /tmp/bench_large_l8b4.json || true

echo "== probe"; probe

echo "== headroom lever: LoRA training (stop_gradient DCE vs the full-finetune row)"
BENCH_LORA=8 python bench.py | tee /tmp/bench_lora.json || true

echo "== probe"; probe

echo "== WEDGE-SUSPECT ROWS LAST =="
echo "== headroom lever: int8 LM-head train (wedged the relay in window 2)"
BENCH_INT8_LMHEAD=1 python bench.py | tee /tmp/bench_int8_lmhead.json

echo "== probe"; probe

echo "== decode throughput: int8 LM head (wedged the relay in window 1)"
BENCH_CONFIG=decode BENCH_INT8_LMHEAD=1 python bench.py | tee /tmp/bench_decode_int8.json

echo "== probe"; probe

echo "== block-sparse vs dense flash timing (wedged r3)"
python workspace/bs_hw_bench.py | tee /tmp/bench_block_sparse.txt

echo "== probe"; probe
echo "ALL DONE"
