"""End-to-end drive of the fslint v2 concurrency tier (PR 13).

Runs the REAL CLI (`python -m fengshen_tpu.analysis`) as subprocesses
over a scratch package planted with the three concurrency hazard
shapes, then exercises --changed in a scratch git repo, --format=github,
the index cache, and PYTHONHASHSEED determinism. Pure stdlib, no jax.
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile
import textwrap

REPO = "/root/repo"
PY = sys.executable
FAILS = []


def check(name, ok, detail=""):
    print(("PASS " if ok else "FAIL ") + name + (f"  {detail}" if detail else ""))
    if not ok:
        FAILS.append(name)


def run(argv, cwd=REPO, env_extra=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    if env_extra:
        env.update(env_extra)
    return subprocess.run(argv, cwd=cwd, capture_output=True, text=True,
                          timeout=180, env=env)


tmp = tempfile.mkdtemp(prefix="fslint_drive_")
try:
    # -- 1. plant a scratch package with all three hazard shapes ------
    pkg = os.path.join(tmp, "scratch")
    os.makedirs(pkg)
    open(os.path.join(pkg, "__init__.py"), "w").close()
    with open(os.path.join(pkg, "net.py"), "w") as f:
        f.write(textwrap.dedent("""
            import urllib.request

            def fetch(url):
                return urllib.request.urlopen(url).read()
            """))
    with open(os.path.join(pkg, "state.py"), "w") as f:
        f.write(textwrap.dedent("""
            import threading

            from scratch.net import fetch


            class Store:
                def __init__(self, peer=None):
                    self._lock = threading.Lock()
                    self._items = []
                    self.peer = peer

                def add(self, x):
                    with self._lock:
                        self._items.append(x)

                def trim(self, keep):
                    self._items = self._items[-keep:]   # unguarded write

                def refresh(self, url):
                    with self._lock:                    # blocking under lock,
                        self._items.append(fetch(url))  # one module away
            """))
    with open(os.path.join(pkg, "pair.py"), "w") as f:
        f.write(textwrap.dedent("""
            import threading


            class A:
                def __init__(self, b: "B"):
                    self._la = threading.Lock()
                    self.b = b
                    self.n = 0

                def fwd(self):
                    with self._la:
                        self.b.poke()

                def poke(self):
                    with self._la:
                        self.n += 1


            class B:
                def __init__(self, a: "A"):
                    self._lb = threading.Lock()
                    self.a = a
                    self.m = 0

                def poke(self):
                    with self._lb:
                        self.m += 1

                def back(self):
                    with self._lb:
                        self.a.poke()
            """))

    p = run([PY, "-m", "fengshen_tpu.analysis", pkg, "--no-baseline",
             "--no-index-cache", "--json"])
    check("hazard package exits 1", p.returncode == 1, p.stderr[:200])
    rep = json.loads(p.stdout)
    rules = sorted({f["rule"] for f in rep["findings"]})
    check("all three concurrency rules fire cross-module",
          rules == ["blocking-under-lock", "lock-order",
                    "unguarded-shared-state"], str(rules))
    bl = [f for f in rep["findings"] if f["rule"] == "blocking-under-lock"]
    check("blocking chain names the terminus",
          any("urlopen" in f["message"] and "fetch" in f["message"]
              for f in bl), str([f["message"] for f in bl])[:200])
    check("every finding has line/col/hint/code",
          all(f["line"] > 0 and f["hint"] and f["code"]
              for f in rep["findings"]))

    # -- 2. suppression with rationale silences the line --------------
    state = open(os.path.join(pkg, "state.py")).read()
    state = state.replace(
        "self._items = self._items[-keep:]   # unguarded write",
        "self._items = self._items[-keep:]  # fslint: disable=unguarded-shared-state; drive test")
    open(os.path.join(pkg, "state.py"), "w").write(state)
    p = run([PY, "-m", "fengshen_tpu.analysis", pkg, "--no-baseline",
             "--no-index-cache", "--json"])
    rep2 = json.loads(p.stdout)
    check("inline suppression-with-rationale silences the finding",
          not any(f["rule"] == "unguarded-shared-state"
                  for f in rep2["findings"]))

    # -- 3. PYTHONHASHSEED byte-determinism ---------------------------
    outs = []
    for seed in ("0", "31337"):
        p = run([PY, "-m", "fengshen_tpu.analysis", pkg, "--no-baseline",
                 "--no-index-cache", "--json"],
                env_extra={"PYTHONHASHSEED": seed})
        outs.append(p.stdout)
    check("--json byte-identical across hash seeds", outs[0] == outs[1])

    # -- 4. index cache: warm run same findings, edits invalidate -----
    cache = os.path.join(tmp, "cache.json")
    p1 = run([PY, "-m", "fengshen_tpu.analysis", pkg, "--no-baseline",
              "--json", "--index-cache", cache])
    p2 = run([PY, "-m", "fengshen_tpu.analysis", pkg, "--no-baseline",
              "--json", "--index-cache", cache])
    check("warm cache run byte-identical", p1.stdout == p2.stdout
          and os.path.exists(cache))
    pair = open(os.path.join(pkg, "pair.py")).read()
    edited = pair.replace("with self._lb:\n            self.a.poke()",
                          "self.a.poke()")
    assert edited != pair, "drive bug: edit pattern did not match"
    open(os.path.join(pkg, "pair.py"), "w").write(edited)
    p3 = run([PY, "-m", "fengshen_tpu.analysis", pkg, "--no-baseline",
              "--json", "--index-cache", cache])
    check("content edit through warm cache drops lock-order",
          not any(f["rule"] == "lock-order"
                  for f in json.loads(p3.stdout)["findings"]))

    # -- 5. --format=github -------------------------------------------
    p = run([PY, "-m", "fengshen_tpu.analysis", pkg, "--no-baseline",
             "--no-index-cache", "--format=github"])
    lines = p.stdout.splitlines()
    check("--format=github emits ::error annotations",
          p.returncode == 1 and lines and
          all(l.startswith("::error file=") and "title=fslint " in l
              for l in lines), str(lines[:2]))

    # -- 6. --changed in a scratch git repo ---------------------------
    grepo = os.path.join(tmp, "grepo")
    shutil.copytree(pkg, os.path.join(grepo, "scratch"))
    genv = {"GIT_AUTHOR_NAME": "d", "GIT_AUTHOR_EMAIL": "d@d",
            "GIT_COMMITTER_NAME": "d", "GIT_COMMITTER_EMAIL": "d@d"}
    for cmd in (["git", "init", "-q"], ["git", "add", "-A"],
                ["git", "commit", "-qm", "seed"]):
        subprocess.run(cmd, cwd=grepo, check=True, capture_output=True,
                       env=dict(os.environ, **genv))
    # --changed resolves the project root from the INSTALLED package,
    # so drive the helper against the scratch repo via the real repo's
    # CLI module, then the full mode against /root/repo itself.
    p = run([PY, "-c",
             "import sys; sys.path.insert(0, %r); "
             "from fengshen_tpu.analysis.cli import _changed_py_files; "
             "print(_changed_py_files(%r))" % (REPO, grepo)])
    check("clean scratch repo: no changed files",
          p.returncode == 0 and p.stdout.strip() == "[]", p.stdout)
    with open(os.path.join(grepo, "scratch", "net.py"), "a") as f:
        f.write("\nX = 1\n")
    p = run([PY, "-c",
             "import sys; sys.path.insert(0, %r); "
             "from fengshen_tpu.analysis.cli import _changed_py_files; "
             "print([p.split('/')[-1] for p in _changed_py_files(%r)])"
             % (REPO, grepo)])
    check("edited file discovered by --changed helper",
          "net.py" in p.stdout, p.stdout)
    # full-mode smoke on the real repo (dirty working tree): exit 0,
    # whole-package index, findings only in changed files (tree is clean)
    p = run([PY, "-m", "fengshen_tpu.analysis", "--changed"])
    check("--changed over the real dirty tree is clean",
          p.returncode == 0 and "clean" in p.stdout, p.stdout[:200])

    # -- 7. the real package gate + make entry points -----------------
    p = run([PY, "-m", "fengshen_tpu.analysis", "--no-baseline"])
    check("whole real package clean with all 10 rules",
          p.returncode == 0 and "clean" in p.stdout, p.stdout[:200])
    p = run([PY, "-c", "import sys, fengshen_tpu.analysis.project, "
             "fengshen_tpu.analysis.cli; "
             "assert not [m for m in sys.modules if m.startswith('jax')]"])
    check("analyzer imports no jax", p.returncode == 0, p.stderr[:200])
    p = run(["make", "lint"])
    check("make lint exits 0", p.returncode == 0, p.stderr[:200])
    p = run(["make", "lint-changed"])
    check("make lint-changed exits 0", p.returncode == 0, p.stderr[:200])
finally:
    shutil.rmtree(tmp, ignore_errors=True)

print()
if FAILS:
    print("DRIVE FAILED:", FAILS)
    sys.exit(1)
print("DRIVE OK: fslint v2 concurrency tier verified end-to-end")
