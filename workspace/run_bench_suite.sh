#!/bin/bash
# The pending hardware rows, in one pass. Run ONLY after the 256x256
# probe succeeds (see .claude/skills/verify/SKILL.md). No `timeout`
# wrappers anywhere — killed in-flight TPU work wedges the relay;
# bench.py's internal watchdog is the only safe abort.
#
# Ordering is by value-per-healthy-minute (the round-5 window lasted
# ~8 minutes before the decode-int8 row wedged the relay): the 13B
# north-star ladder FIRST, then the 300M regression rows, then levers,
# then the wedge-suspect rows (int8 decode wedged r5, block-sparse
# timing wedged r3) dead last.
set -uo pipefail
cd "$(dirname "$0")/.."

probe() {
  python workspace/probe.py || exit 1
}

echo "== probe"; probe

echo "== 13B-shape bench (GQA + offload ladder; first compile is long)"
BENCH_CONFIG=large python bench.py | tee /tmp/bench_large.json

echo "== probe"; probe

echo "== default bench (regression guard)"
python bench.py | tee /tmp/bench_default.json

echo "== sharded-step bench"
BENCH_CONFIG=sharded python bench.py | tee /tmp/bench_sharded.json
echo "== dispatch-latency A/B: 5 steps per jitted execution"
BENCH_CONFIG=sharded BENCH_STEPS_PER_EXEC=5 python bench.py | tee /tmp/bench_sharded_spe5.json

echo "== probe"; probe

echo "== headroom lever: chunked fused LM-head+CE (frees the fp32 logits)"
BENCH_FUSED_CE=8 python bench.py | tee /tmp/bench_fused_ce.json
echo "== fused CE + bigger batch (the point of the lever)"
BENCH_FUSED_CE=8 BENCH_BATCH=40 python bench.py | tee /tmp/bench_fused_ce_b40.json || true
BENCH_FUSED_CE=8 BENCH_BATCH=32 python bench.py | tee /tmp/bench_fused_ce_b32.json || true

echo "== headroom lever: int8 LM-head on the default 300M shape"
BENCH_INT8_LMHEAD=1 python bench.py | tee /tmp/bench_int8_lmhead.json

echo "== headroom lever: offloaded optimizer update (300M via Trainer)"
BENCH_CONFIG=sharded BENCH_OFFLOAD=1 python bench.py | tee /tmp/bench_offload.json

echo "== probe"; probe

echo "== measured 7GB claim: 1.3B AFQMC shape with param streaming"
python workspace/offload_7gb_check.py | tee /tmp/bench_offload_7gb.json

echo "== probe"; probe

echo "== decode throughput: greedy KV-cached (300M shape)"
BENCH_CONFIG=decode python bench.py | tee /tmp/bench_decode_greedy.json
echo "== decode throughput: seq2seq beam-4 (T5-base shape)"
BENCH_CONFIG=decode BENCH_DECODE=beam python bench.py | tee /tmp/bench_decode_beam.json

echo "== probe"; probe

echo "== WEDGE-SUSPECT ROWS LAST =="
echo "== decode throughput: int8 LM head (wedged the relay in r5)"
BENCH_CONFIG=decode BENCH_INT8_LMHEAD=1 python bench.py | tee /tmp/bench_decode_int8.json

echo "== probe"; probe

echo "== block-sparse vs dense flash timing (S=4096/8192; wedged r3)"
python workspace/bs_hw_bench.py | tee /tmp/bench_block_sparse.txt

echo "== probe"; probe
echo "ALL DONE — paste the rows into docs/performance.md"
