"""TPU relay probe — 256x256 bf16 matmul with an in-process watchdog.

Per the wedge protocol (NOTES.md): never timeout-kill TPU work from
outside; an in-process abort (os._exit) is the one safe exit.  A daemon
thread is used rather than SIGALRM because the axon plugin import can
reset signal handlers and a main thread blocked in C never re-enters the
interpreter to run a Python signal handler.  Exit 0 = alive, 3 = wedged.
"""
import os
import sys
import threading
import time

DEADLINE = float(os.environ.get("PROBE_DEADLINE", "120"))
_done = threading.Event()


def _watch():
    if not _done.wait(DEADLINE):
        sys.stderr.write(f"probe: relay WEDGED (no response in {DEADLINE:.0f}s)\n")
        sys.stderr.flush()
        os._exit(3)


threading.Thread(target=_watch, daemon=True).start()

t0 = time.time()
import jax
import jax.numpy as jnp

x = jnp.ones((256, 256), jnp.bfloat16)
v = float((x @ x).block_until_ready()[0, 0])
print(f"probe ok: backend={jax.default_backend()} val={v} dt={time.time()-t0:.1f}s")
try:  # tile capacity diagnostic (the r5 window OOM'd at r2-proven sizes)
    stats = jax.devices()[0].memory_stats() or {}
    lim = stats.get("bytes_limit")
    used = stats.get("bytes_in_use")
    if lim:
        print(f"probe hbm: limit={lim/2**30:.2f}GiB in_use={(used or 0)/2**30:.2f}GiB")
except Exception as e:  # noqa: BLE001 — diagnostic only
    print(f"probe hbm: unavailable ({e})")
# disarm only after the LAST device call — the diagnostic is a relay
# round-trip too, and a hung probe defeats the probe's whole contract
_done.set()
