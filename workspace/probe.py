"""TPU relay probe — 256x256 bf16 matmul with an in-process watchdog.

Per the wedge protocol (NOTES.md): never timeout-kill TPU work from
outside; an in-process abort (os._exit) is the one safe exit.  A daemon
thread is used rather than SIGALRM because the axon plugin import can
reset signal handlers and a main thread blocked in C never re-enters the
interpreter to run a Python signal handler.  Exit 0 = alive, 3 = wedged.
"""
import os
import sys
import threading
import time

DEADLINE = float(os.environ.get("PROBE_DEADLINE", "120"))
_done = threading.Event()


def _watch():
    if not _done.wait(DEADLINE):
        sys.stderr.write(f"probe: relay WEDGED (no response in {DEADLINE:.0f}s)\n")
        sys.stderr.flush()
        os._exit(3)


threading.Thread(target=_watch, daemon=True).start()

t0 = time.time()
import jax
import jax.numpy as jnp

x = jnp.ones((256, 256), jnp.bfloat16)
v = float((x @ x).block_until_ready()[0, 0])
_done.set()
print(f"probe ok: backend={jax.default_backend()} val={v} dt={time.time()-t0:.1f}s")
