"""Measured 7GB claim (VERDICT r4 missing #4 / weak #3): run the 1.3B
AFQMC-shape recipe with host-resident parameter streaming on the real
chip and record the HBM high-water mark.

Reference claim: demo_classification_afqmc_erlangshen_offload.sh:9-33
finetunes Erlangshen-MegatronBert-1.3B on one 8GB GPU via DeepSpeed
ZeRO-3 + offload. Analog here: `--offload_params` streams layer params
+ adam moments from host memory (trainer/param_streaming.py), so HBM
holds one layer's working set + boundary activations.

Run ONLY after the relay probe succeeds (never wrap in `timeout`).
Prints one JSON line with peak HBM bytes; paste into
docs/performance.md replacing the analytic argument (commit 150651b).
"""

import json
import os
import threading
import time

_done = threading.Event()
DEADLINE = float(os.environ.get("CHECK_DEADLINE", "1800"))


def _watch():
    if not _done.wait(DEADLINE):
        import sys
        sys.stderr.write("offload_7gb_check: WEDGED, aborting\n")
        os._exit(3)


threading.Thread(target=_watch, daemon=True).start()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from fengshen_tpu.examples.classification.finetune_classification import (  # noqa: E402
    TaskModel)
from fengshen_tpu.models.megatron_bert import MegatronBertConfig  # noqa: E402
from fengshen_tpu.trainer.param_streaming import (  # noqa: E402
    make_streamed, megatron_classifier_stream_spec)
from fengshen_tpu.utils.utils import report_memory  # noqa: E402

# Erlangshen-MegatronBert-1.3B shape (reference config): hidden 2048,
# 24 layers, 32 heads, ffn 8192 — the afqmc recipe at seq 128, batch 16
cfg = MegatronBertConfig(
    vocab_size=int(os.environ.get("CHECK_VOCAB", "21128")),
    hidden_size=int(os.environ.get("CHECK_HIDDEN", "2048")),
    num_hidden_layers=int(os.environ.get("CHECK_LAYERS", "24")),
    num_attention_heads=int(os.environ.get("CHECK_HEADS", "32")),
    intermediate_size=int(os.environ.get("CHECK_INTER", "8192")),
    max_position_embeddings=512, dtype="bfloat16",
    param_dtype="float32", hidden_dropout_prob=0.0,
    attention_probs_dropout_prob=0.0)
seq = int(os.environ.get("CHECK_SEQ", "128"))
batch = int(os.environ.get("CHECK_BATCH", "16"))

model = TaskModel(cfg, "huggingface-megatron_bert", num_labels=2)
rng = np.random.RandomState(0)
ids = jnp.asarray(rng.randint(1, cfg.vocab_size - 1, (batch, seq)),
                  jnp.int32)
batch_d = {"input_ids": ids,
           "attention_mask": jnp.ones_like(ids),
           "labels": jnp.asarray(rng.randint(0, 2, (batch,)), jnp.int32)}

# init on HOST via eval_shape + per-part normal init so the full fp32
# tree never touches HBM (the whole point of the exercise)
abstract = jax.eval_shape(
    lambda: model.init(jax.random.PRNGKey(0), ids[:1, :8]))["params"]
host_params = jax.tree_util.tree_map(
    lambda s: (rng.randn(*s.shape) * 0.02).astype(s.dtype), abstract)

spec = megatron_classifier_stream_spec(cfg, host_params, num_labels=2)
del host_params
eng = make_streamed(spec, learning_rate=2e-5, weight_decay=0.01,
                    clip_norm=1.0)

t0 = time.time()
for step in range(int(os.environ.get("CHECK_STEPS", "3"))):
    loss, metrics = eng.step(batch_d, jax.random.PRNGKey(step))
    mem = report_memory(f"step{step}")
    print(f"step {step}: loss={loss:.4f} "
          f"grad_norm={metrics['grad_norm']:.3g} "
          f"dt={time.time()-t0:.1f}s", flush=True)

mem = report_memory("final")
peak = max(d["peak_bytes_in_use"] for d in mem.values())
_done.set()
print(json.dumps({
    "metric": "afqmc_1p3b_streamed_peak_hbm_gb",
    "value": round(peak / 1e9, 3),
    "unit": "GB",
    "vs_baseline": round(7.0 / max(peak / 1e9, 1e-9), 3),
}))
