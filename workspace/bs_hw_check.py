"""Hardware-compile check for the block-sparse Pallas kernel (VERDICT r2 #4).

Runs fwd + bwd non-interpret on the real chip, compares vs dense reference
with the same block mask. Small shapes first.
"""
import sys

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp
import numpy as np

from fengshen_tpu.ops.pallas.block_sparse_attention import (
    block_sparse_attention)

print("backend:", jax.default_backend())

B, S, H, D = 1, 512, 4, 128
BLK = 128
nb = S // BLK
rng = np.random.RandomState(0)
q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32) * 0.5
k = jnp.asarray(rng.randn(B, S, H, D), jnp.float32) * 0.5
v = jnp.asarray(rng.randn(B, S, H, D), jnp.float32) * 0.5

# causal-ish block layout with a hole
layout = np.tril(np.ones((nb, nb), bool))
layout[3, 1] = False

def dense_ref(q, k, v):
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / (D ** 0.5)
    mask = np.repeat(np.repeat(layout, BLK, 0), BLK, 1)
    scores = jnp.where(jnp.asarray(mask)[None, None], scores, -1e30)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)

def loss_sparse(q, k, v):
    return (block_sparse_attention(q, k, v, layout, BLK) ** 2).sum()

def loss_dense(q, k, v):
    return (dense_ref(q, k, v) ** 2).sum()

out_s = jax.jit(lambda q, k, v: block_sparse_attention(q, k, v, layout, BLK))(q, k, v)
jax.block_until_ready(out_s)
print("fwd compiled OK")
out_d = dense_ref(q, k, v)
print("fwd max abs diff:", float(jnp.abs(out_s - out_d).max()))

gs = jax.jit(jax.grad(loss_sparse, argnums=(0, 1, 2)))(q, k, v)
jax.block_until_ready(gs)
print("bwd compiled OK")
gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
for name, a, b in zip("qkv", gs, gd):
    print(f"d{name} max abs diff:", float(jnp.abs(a - b).max()))
print("DONE")
