"""PR 20 verify drive: the streaming tier end to end.

Section 1 (in-process stdlib server, ~25s forced CPU): tiny
self-draft llama behind the REAL stdlib api server — SSE stream is
token-exact vs batch-1 `utils.generate.generate`, event ids are the
token indices, `Last-Event-ID` reconnect replays the tail, pinned-seed
sampled streams reproduce byte-identically, `/stats` grows
`streams_active`, `/metrics` renders the `fstpu_stream_*` families.

Section 2 (real subprocesses, ~90s): two real replica subprocesses
(fleet.bench --replica) fronted by the REAL router process
(`python -m fengshen_tpu.fleet`) — a clean routed stream is
token-exact, then a second stream whose serving replica is SIGKILLed
mid-flight must arrive GAPLESS (ids 0..n-1 contiguous) and
token-identical to the clean run, with the router's `/metrics`
showing the journal consult.
"""
import http.client
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, "/root/repo")
import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp  # noqa: E402

from fengshen_tpu.api.main import (PipelineConfig, ServerConfig,  # noqa: E402
                                   _start_warmup_thread,
                                   build_stdlib_server,
                                   create_continuous_engine)
from fengshen_tpu.fleet.bench import _IntTokenizer  # noqa: E402
from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM  # noqa: E402
from fengshen_tpu.pipelines.text_generation import Pipeline  # noqa: E402
from fengshen_tpu.streaming import iter_sse  # noqa: E402
from fengshen_tpu.utils.generate import generate as generate_ref  # noqa: E402

PORT, P1, P2, RP = 8481, 8483, 8484, 8482
OK = []


def check(name, cond, detail=""):
    print(("PASS " if cond else "FAIL ") + name + (" " + detail if detail else ""), flush=True)
    OK.append((name, bool(cond)))
    if not cond:
        raise SystemExit(f"FAILED: {name} {detail}")


def sse_post(port, path, body, headers=None, on_event=None):
    """POST and parse the SSE response; on_event(ev, n_tokens) fires
    per frame (for the mid-stream kill)."""
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=180)
    payload = json.dumps(body)
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    conn.request("POST", path, payload, hdrs)
    resp = conn.getresponse()
    if resp.status != 200:
        data = json.loads(resp.read())
        conn.close()
        return resp.status, data, []
    events = []
    for ev in iter_sse(resp):
        events.append(ev)
        if on_event:
            on_event(ev, sum(1 for e in events if e["event"] == "token"))
    conn.close()
    return 200, None, events


def tokens_of(events):
    toks = [(int(e["id"]), int(e["data"]["token"]))
            for e in events if e["event"] == "token"]
    return toks


def get(port, path, timeout=10):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
            body = r.read()
            try:
                return r.status, json.loads(body)
            except ValueError:
                return r.status, body.decode()
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def wait_200(port, path, deadline_s=180):
    t0 = time.time()
    while time.time() - t0 < deadline_s:
        try:
            if get(port, path, timeout=3)[0] == 200:
                return True
        except (OSError, socket.timeout):
            pass
        time.sleep(0.25)
    return False


# ---------------- section 1: in-process streaming surface ------------
print("== section 1: stdlib server, self-draft engine ==", flush=True)
cfg = LlamaConfig(vocab_size=97, hidden_size=32, intermediate_size=64,
                  num_hidden_layers=2, num_attention_heads=4,
                  max_position_embeddings=96, dtype="float32")
model = LlamaForCausalLM(cfg)
params = jax.jit(lambda r: model.init(
    r, jnp.zeros((1, 8), jnp.int32))["params"])(jax.random.PRNGKey(0))
pipe = Pipeline(module=model, params=params, tokenizer=_IntTokenizer(),
                max_new_tokens=12, eos_token_id=None, pad_token_id=0)
engine = create_continuous_engine(
    pipe, {"num_slots": 2, "buckets": [16], "max_new_tokens": 12,
           "max_queue": 32, "spec_mode": "self_draft",
           "spec_draft_layers": 1, "spec_gamma": 2})
scfg = ServerConfig(host="127.0.0.1", port=PORT, engine="continuous")
pcfg = PipelineConfig(task="text_generation")
ready = _start_warmup_thread(scfg, pcfg, pipe, engine)
server = build_stdlib_server(scfg, pcfg, pipeline=pipe, engine=engine,
                             ready=ready)
threading.Thread(target=server.serve_forever, daemon=True).start()
check("healthz ready", wait_200(PORT, "/healthz", 120))

prompt = "5 9 2 7"
ids = jnp.array([[int(t) for t in prompt.split()]], jnp.int32)
ref = generate_ref(model, params, ids, max_new_tokens=12,
                   do_sample=False, eos_token_id=None,
                   pad_token_id=0)[0, ids.shape[1]:].tolist()

st, err, events = sse_post(
    PORT, "/api/text_generation/stream",
    {"input_text": prompt, "request_id": "drive-1"})
check("stream 200", st == 200, str(err))
toks = tokens_of(events)
check("ids are token indices", [i for i, _ in toks] == list(range(12)))
check("greedy streamed token-exact vs generate",
      [t for _, t in toks] == [int(x) for x in ref])
done = [e for e in events if e["event"] == "done"]
check("terminal done with result", len(done) == 1 and
      done[0]["data"]["finish_reason"] == "length" and
      done[0]["data"]["result"] == " ".join(str(t) for _, t in toks))

st, err, events = sse_post(
    PORT, "/api/text_generation/stream", {"request_id": "drive-1"},
    headers={"Last-Event-ID": "7"})
check("Last-Event-ID reconnect replays tail", st == 200 and
      tokens_of(events) == toks[8:])
st, err, _ = sse_post(PORT, "/api/text_generation/stream",
                      {"request_id": "nope", "last_event_id": 3})
check("unknown rid reconnect 404", st == 404, str(st))

st, stats = get(PORT, "/stats")
check("/stats streams_active present and drained",
      stats.get("streams_active") == 0 and
      stats.get("spec_mode") == "self_draft", json.dumps(stats)[:200])
st, metrics = get(PORT, "/metrics")
check("/metrics stream families", st == 200 and
      "fstpu_streams_active 0" in metrics and
      "fstpu_stream_tokens_total" in metrics and
      "fstpu_stream_ttfb_seconds_bucket" in metrics and
      "fstpu_stream_reconnects_total 1" in metrics)

# sampled reproducibility through the wire: same seed twice, then a
# different seed (engine-level sampling knobs; self-draft accept rule)
eng2 = create_continuous_engine(
    pipe, {"num_slots": 2, "buckets": [16], "max_new_tokens": 12,
           "max_queue": 32, "spec_mode": "self_draft",
           "spec_draft_layers": 1, "spec_gamma": 2,
           "do_sample": True, "temperature": 0.9, "top_k": 20})
scfg2 = ServerConfig(host="127.0.0.1", port=PORT + 4,
                     engine="continuous")
ready2 = _start_warmup_thread(scfg2, pcfg, pipe, eng2)
server2 = build_stdlib_server(scfg2, pcfg, pipeline=pipe, engine=eng2,
                              ready=ready2)
threading.Thread(target=server2.serve_forever, daemon=True).start()
check("sampled server ready", wait_200(PORT + 4, "/healthz", 120))
runs = []
for rid in ("s-a", "s-b", "s-c"):
    seed = 7 if rid != "s-c" else 11
    st, err, ev = sse_post(PORT + 4, "/api/text_generation/stream",
                           {"input_text": prompt, "request_id": rid,
                            "seed": seed})
    check(f"sampled stream {rid} 200", st == 200, str(err))
    runs.append([t for _, t in tokens_of(ev)])
check("pinned seed reproduces across the wire", runs[0] == runs[1])
check("different seed diverges", runs[0] != runs[2])
server.shutdown()
server2.shutdown()

# ---------------- section 2: real fleet, kill mid-stream -------------
print("== section 2: real replicas + real router ==", flush=True)
ENV = {**os.environ, "JAX_PLATFORMS": "cpu",
       "FLEET_BENCH_VOCAB": "512", "FLEET_BENCH_HIDDEN": "512",
       "FLEET_BENCH_INTER": "1024", "FLEET_BENCH_LAYERS": "2",
       "FLEET_BENCH_HEADS": "4", "FLEET_BENCH_BUCKETS": "16,32",
       "FLEET_BENCH_NEW_TOKENS": "48", "FLEET_BENCH_SLOTS": "2"}
reps = [subprocess.Popen(
    [sys.executable, "-m", "fengshen_tpu.fleet.bench", "--replica",
     "--port", str(p)], env=ENV) for p in (P1, P2)]
router = subprocess.Popen(
    [sys.executable, "-m", "fengshen_tpu.fleet", "--replicas",
     f"127.0.0.1:{P1},127.0.0.1:{P2}", "--port", str(RP),
     "--poll-interval", "0.3", "--breaker-threshold", "3"],
    env={**os.environ, "JAX_PLATFORMS": "cpu"})
try:
    check("replica 1 ready", wait_200(P1, "/healthz", 180))
    check("replica 2 ready", wait_200(P2, "/healthz", 180))
    check("router healthy", wait_200(RP, "/healthz", 60))

    st, err, ev = sse_post(RP, "/api/text_generation/stream",
                           {"input_text": prompt})
    check("clean routed stream 200", st == 200, str(err))
    clean = tokens_of(ev)
    check("clean routed stream complete",
          [i for i, _ in clean] == list(range(48)) and
          any(e["event"] == "done" for e in ev))

    state = {"killed": False}

    def kill_serving(_ev, n_tokens):
        if state["killed"] or n_tokens < 5:
            return
        for port, proc in ((P1, reps[0]), (P2, reps[1])):
            try:
                s, body = get(port, "/stats", timeout=2)
            except Exception:
                continue
            if s == 200 and body.get("slots_active", 0) >= 1:
                print(f"  SIGKILL replica :{port} mid-stream",
                      flush=True)
                proc.send_signal(signal.SIGKILL)
                state["killed"] = True
                return

    st, err, ev = sse_post(RP, "/api/text_generation/stream",
                           {"input_text": prompt},
                           on_event=kill_serving)
    check("killed-mid-stream 200", st == 200, str(err))
    check("a replica was killed mid-stream", state["killed"])
    got = tokens_of(ev)
    check("gapless ids across the kill",
          [i for i, _ in got] == list(range(48)))
    check("token-identical to the clean run", got == clean)
    check("terminal done after failover",
          any(e["event"] == "done" for e in ev))
    st, m = get(RP, "/metrics")
    consults = sum(
        float(line.rsplit(" ", 1)[1])
        for line in m.splitlines()
        if line.startswith("fstpu_resume_total{"))
    check("router consulted the journal", consults >= 1,
          f"consults={consults}")
    print("ALL CHECKS PASSED", flush=True)
finally:
    for p in reps + [router]:
        if p.poll() is None:
            p.send_signal(signal.SIGKILL)
