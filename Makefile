# Developer/CI entry points. The lint gate is the same analyzer the
# fast pytest lane runs (tests/test_analysis.py); see
# docs/static_analysis.md for the rule catalog and baseline workflow.

PY ?= python

.PHONY: lint lint-changed lint-ci lint-baseline test test-fast \
	serve-bench \
	serve-bench-parity serve-bench-spec serve-bench-fleet \
	serve-bench-disagg serve-bench-evac serve-bench-multimodal \
	serve-bench-stream \
	serve-fleet aot-bench \
	kernel-bench benchdiff

# whole package, all rules (per-file + the cross-module concurrency
# tier); the project index is cached in .fslint_cache.json
lint:
	$(PY) -m fengshen_tpu.analysis --json

# hot-loop variant: lint only files dirty vs HEAD (plus untracked) —
# the concurrency rules still index the whole package for context
lint-changed:
	$(PY) -m fengshen_tpu.analysis --changed

# CI surface: a SARIF 2.1.0 log for code-scanning upload (hashseed
# pinned so the artifact is byte-stable run to run) plus ::error
# workflow annotations inline in the job log; fails on any
# non-baselined finding, like `lint`
lint-ci:
	PYTHONHASHSEED=0 $(PY) -m fengshen_tpu.analysis \
		--format=sarif --stats > fslint.sarif
	$(PY) -m fengshen_tpu.analysis --format=github

# offline serving-throughput microbench (docs/serving.md): continuous
# batching vs sequential per-request decode, one JSON line on CPU so
# BENCH rounds can track serving throughput without a healthy relay
serve-bench:
	JAX_PLATFORMS=cpu $(PY) -m fengshen_tpu.serving.bench

# KV memory-parity mode (docs/performance.md): slot vs paged vs
# paged+int8 at the SAME KV byte budget — max concurrent admitted and
# aggregate tokens/s per variant, one BENCH-schema JSON line
serve-bench-parity:
	JAX_PLATFORMS=cpu SERVE_BENCH_MODE=memory_parity \
		SERVE_BENCH_BUCKETS=32,128 SERVE_BENCH_NEW_TOKENS=32 \
		$(PY) -m fengshen_tpu.serving.bench

# speculative-decode microbench (docs/serving.md "Speculative
# decoding"): committed tokens per target forward + aggregate tokens/s
# of the prompt-lookup engine vs the same engine with spec off, on a
# self-repetitive workload — one BENCH-schema JSON line on CPU
serve-bench-spec:
	JAX_PLATFORMS=cpu SERVE_BENCH_MODE=spec \
		SERVE_BENCH_BUCKETS=32,64 SERVE_BENCH_NEW_TOKENS=96 \
		$(PY) -m fengshen_tpu.serving.bench

# multimodal micro-batch engines (docs/serving.md "Multimodal
# engines"): batch_image (Taiyi-SD denoise loop) and embedding
# (Taiyi-CLIP text tower) engine requests/s vs the sequential
# one-call-per-request path, on the small-test towers — one
# BENCH-schema JSON line per engine type, each carrying `engine_type`
serve-bench-multimodal:
	JAX_PLATFORMS=cpu SERVE_BENCH_MODE=multimodal \
		$(PY) -m fengshen_tpu.serving.bench

# streaming-tier microbench (docs/streaming.md): TTFT first-byte vs
# last-byte at 8 concurrent SSE streams, self-draft committed tokens
# per target forward vs prompt-lookup on NON-repetitive traffic, and
# the kill-mid-stream gapless rung through the real fleet router —
# one BENCH-schema JSON line carrying `stream`/`spec_mode`
serve-bench-stream:
	JAX_PLATFORMS=cpu SERVE_BENCH_MODE=stream \
		$(PY) -m fengshen_tpu.streaming.bench

# fleet-router microbench (docs/fleet.md): aggregate tokens/s over
# N=3 stdlib api replica subprocesses vs one, plus the
# kill-one-replica-mid-run rung (must finish with zero failed
# requests) — one BENCH-schema JSON line carrying the replica count
serve-bench-fleet:
	JAX_PLATFORMS=cpu $(PY) -m fengshen_tpu.fleet.bench

# prefill/decode disaggregation microbench (docs/disaggregation.md):
# aggregate tokens/s of a prefill-tier + decode-tier fleet (KV handoff
# through the real router placement + redirect/collect path) vs a
# homogeneous 3-replica fleet on a long-prompt/short-decode workload,
# plus the adopt-decline fallback rung — one BENCH-schema JSON line
# carrying the phase topology
serve-bench-disagg:
	JAX_PLATFORMS=cpu SERVE_BENCH_MODE=disagg \
		$(PY) -m fengshen_tpu.disagg.bench

# preemption-tolerance drills (docs/fault_tolerance.md "Preemption
# runbook"): SIGTERM-mid-decode (live lane evacuation — every
# in-flight request answers 200 token-identical via a peer, zero lost
# work) and SIGKILL-mid-decode (the adopter dies; requests resume from
# token k out of the commit journal, never from token 0) over a
# 3-replica fleet — one BENCH-schema JSON line carrying the drill
# identity so it never diffs against undisturbed fleet rounds
serve-bench-evac:
	JAX_PLATFORMS=cpu $(PY) -m fengshen_tpu.fleet.evac_bench

# local fleet: spawn $(N) stdlib api replicas from the api config
# $(CONFIG) and front them with the router on port $(PORT)
# (docs/fleet.md), e.g.
#     make serve-fleet CONFIG=generation.json N=3 PORT=8080
serve-fleet:
	@test -n "$(CONFIG)" || \
		{ echo "usage: make serve-fleet CONFIG=<api config json> [N=3] [PORT=8080]"; exit 2; }
	JAX_PLATFORMS=$${JAX_PLATFORMS:-cpu} $(PY) -m fengshen_tpu.fleet \
		--spawn $(or $(N),3) --config $(CONFIG) \
		--port $(or $(PORT),8080)

# AOT cold-start microbench (docs/aot_cache.md): cold-process vs
# warm-process engine warmup through the persistent executable cache,
# one BENCH-schema JSON line (aot_cold_s, aot_warm_s, speedup)
aot-bench:
	JAX_PLATFORMS=cpu $(PY) -m fengshen_tpu.aot.bench

# kernel-layer microbench (docs/kernels.md): the Pallas dispatch seam
# A/B'd against the stock XLA lowerings (paged decode read, fused CE
# grad step) plus the configs/long_context_32k.json trainer config on
# a sequence-sharded mesh. One BENCH-schema JSON line per rung, each
# carrying the `kernel` dispatch decision (pallas|xla) that benchdiff
# folds into the row identity. CPU-shrunk width; hardware rounds drop
# the KERNEL_BENCH_* overrides for the full 32k shape.
kernel-bench:
	JAX_PLATFORMS=cpu \
		XLA_FLAGS="--xla_force_host_platform_device_count=8" \
		BENCH_DEGRADED=1 KERNEL_BENCH_SEQ=2048 \
		KERNEL_BENCH_HIDDEN=64 KERNEL_BENCH_INTER=128 \
		KERNEL_BENCH_LAYERS=2 KERNEL_BENCH_HEADS=4 \
		KERNEL_BENCH_KV=4 KERNEL_BENCH_VOCAB=512 \
		KERNEL_BENCH_FUSED_CE=4 KERNEL_BENCH_STEPS=2 \
		KERNEL_BENCH_DTYPE=float32 \
		$(PY) -m fengshen_tpu.ops.pallas.bench

# bench trajectory comparator (docs/observability.md "benchdiff"):
# classifies each BENCH_r*.json round (ok / wedged / failed), diffs
# every metric against the previous round carrying it (and
# BASELINE.json's published table), and prints a deterministic
# verdict — every future bench round lands with a trajectory readout
benchdiff:
	$(PY) -m fengshen_tpu.observability.benchdiff

lint-baseline:
	$(PY) -m fengshen_tpu.analysis --write-baseline

test-fast:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q
