// Native dataset-index builders for fengshen-tpu.
//
// TPU-native counterpart of the reference's pybind11 helpers
// (reference: fengshen/data/megatron_dataloader/helpers.cpp — exposing
// build_sample_idx / build_mapping / build_blocks_mapping /
// build_blending_indices at :788-793). Exposed with a plain C ABI and bound
// from Python via ctypes (no pybind11 in this environment); all buffers are
// caller-allocated numpy arrays.
//
// Build: `make -C native` → libindex_helpers.so

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <random>

extern "C" {

// GPT contiguous-token sample index (reference: helpers.cpp:101
// build_sample_idx): walks documents in doc_idx order packing tokens into
// seq_length-sized samples. sample_idx is [(num_samples+1) * 2] int32:
// (document position, token offset) per sample boundary.
void build_sample_idx(const int32_t* sizes, const int32_t* doc_idx,
                      int64_t doc_idx_len, int32_t seq_length,
                      int32_t num_epochs, int64_t tokens_per_epoch,
                      int32_t* sample_idx, int64_t num_samples) {
    (void)num_epochs;
    (void)tokens_per_epoch;
    int64_t sample = 0;
    int64_t doc_pos = 0;     // index into doc_idx
    int32_t doc_offset = 0;  // token offset within current document
    sample_idx[0] = 0;
    sample_idx[1] = 0;
    while (sample < num_samples) {
        int64_t remaining = seq_length + 1;  // +1 for the shifted label
        while (remaining > 0 && doc_pos < doc_idx_len) {
            int32_t doc_len = sizes[doc_idx[doc_pos]] - doc_offset;
            if (doc_len >= remaining) {
                // One-token overlap (reference: helpers.cpp:165): the next
                // sample re-starts at this sample's last (label) token, so
                // every boundary token is both a label and the next input.
                doc_offset += static_cast<int32_t>(remaining) - 1;
                remaining = 0;
            } else {
                remaining -= doc_len;
                ++doc_pos;
                doc_offset = 0;
            }
        }
        ++sample;
        sample_idx[2 * sample] = static_cast<int32_t>(doc_pos);
        sample_idx[2 * sample + 1] = doc_offset;
        if (doc_pos >= doc_idx_len && sample < num_samples) {
            // ran out of tokens; repeat the final boundary
            for (int64_t s = sample + 1; s <= num_samples; ++s) {
                sample_idx[2 * s] = sample_idx[2 * sample];
                sample_idx[2 * s + 1] = sample_idx[2 * sample + 1];
            }
            break;
        }
    }
}

// Weighted multi-corpus interleave (reference: helpers.cpp:34
// build_blending_indices): greedy choice of the dataset whose current
// sampled fraction most lags its weight.
void build_blending_indices(int8_t* dataset_index,
                            int64_t* dataset_sample_index,
                            const double* weights, int32_t num_datasets,
                            int64_t size, int32_t verbose) {
    int64_t* counts = new int64_t[num_datasets];
    std::memset(counts, 0, sizeof(int64_t) * num_datasets);
    for (int64_t i = 0; i < size; ++i) {
        double denom = static_cast<double>(i + 1);
        int32_t best = 0;
        double best_gap = -1e300;
        for (int32_t d = 0; d < num_datasets; ++d) {
            double gap = weights[d] * denom - static_cast<double>(counts[d]);
            if (gap > best_gap) {
                best_gap = gap;
                best = d;
            }
        }
        dataset_index[i] = static_cast<int8_t>(best);
        dataset_sample_index[i] = counts[best];
        ++counts[best];
    }
    if (verbose) {
        std::fprintf(stderr, "blending: %lld samples over %d datasets\n",
                     static_cast<long long>(size), num_datasets);
    }
    delete[] counts;
}

// Sentence-pair map for BERT-style datasets (reference: helpers.cpp:214
// build_mapping): emit (doc start sentence, doc end sentence, target length)
// triples for every window of whole sentences fitting max_seq_length; with
// probability short_seq_prob the target length is shortened. Two-pass: call
// with maps == nullptr to count, then with the allocated buffer.
int64_t build_mapping(const int64_t* docs, int64_t num_docs,
                      const int32_t* sizes, int32_t max_seq_length,
                      double short_seq_prob, int32_t seed,
                      int64_t* maps, int64_t max_maps) {
    std::mt19937_64 rng(static_cast<uint64_t>(seed));
    std::uniform_real_distribution<double> uniform(0.0, 1.0);
    int64_t count = 0;
    for (int64_t d = 0; d < num_docs; ++d) {
        const int64_t sent_begin = docs[d];
        const int64_t sent_end = docs[d + 1];
        if (sent_end - sent_begin < 2) continue;  // need a pair
        int64_t start = sent_begin;
        int32_t target = max_seq_length;
        if (uniform(rng) < short_seq_prob) {
            target = 2 + static_cast<int32_t>(
                uniform(rng) * (max_seq_length - 2));
        }
        int32_t len = 0;
        int64_t n_sent = 0;
        for (int64_t s = sent_begin; s < sent_end; ++s) {
            len += sizes[s];
            ++n_sent;
            const bool last = (s == sent_end - 1);
            if ((len >= target && n_sent >= 2) || (last && n_sent >= 2)) {
                if (maps != nullptr) {
                    if (count >= max_maps) return count;
                    maps[3 * count] = start;
                    maps[3 * count + 1] = s + 1;
                    maps[3 * count + 2] = target;
                }
                ++count;
                start = s + 1;
                len = 0;
                n_sent = 0;
                target = max_seq_length;
                if (uniform(rng) < short_seq_prob) {
                    target = 2 + static_cast<int32_t>(
                        uniform(rng) * (max_seq_length - 2));
                }
            }
        }
    }
    return count;
}

// Block map for span/ICT-style datasets (reference: helpers.cpp:513
// build_blocks_mapping): one entry per sentence window of at most
// max_seq_length tokens, no pairing requirement.
int64_t build_blocks_mapping(const int64_t* docs, int64_t num_docs,
                             const int32_t* sizes, int32_t max_seq_length,
                             int64_t* maps, int64_t max_maps) {
    int64_t count = 0;
    for (int64_t d = 0; d < num_docs; ++d) {
        int64_t start = docs[d];
        int32_t len = 0;
        for (int64_t s = docs[d]; s < docs[d + 1]; ++s) {
            if (len + sizes[s] > max_seq_length && len > 0) {
                if (maps != nullptr) {
                    if (count >= max_maps) return count;
                    maps[3 * count] = start;
                    maps[3 * count + 1] = s;
                    maps[3 * count + 2] = len;
                }
                ++count;
                start = s;
                len = 0;
            }
            len += sizes[s];
        }
        if (len > 0 && docs[d + 1] > start) {
            if (maps != nullptr) {
                if (count < max_maps) {
                    maps[3 * count] = start;
                    maps[3 * count + 1] = docs[d + 1];
                    maps[3 * count + 2] = len;
                }
            }
            ++count;
        }
    }
    return count;
}

}  // extern "C"
