"""Benchmark: LLaMA causal-LM training throughput + MFU on the local chip(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The reference publishes no throughput numbers (BASELINE.md), so
`vs_baseline` is measured-MFU / 0.40 — the north-star MFU target.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax


_WATCHDOG_DEADLINE = [None]


def _flight_dump(trigger: str, timeout_s: float = 5.0) -> None:
    """Best-effort post-mortem bundle before the watchdog's os._exit
    (docs/observability.md "Flight recorder"): five BENCH rounds died
    of a wedged relay leaving nothing but a two-line stderr tail — the
    bundle at least carries the rows emitted so far plus a final
    metrics snapshot. Must never hang or raise: the dump runs in a
    daemon thread with a bounded join, so even a sick filesystem
    cannot stall the abort the watchdog exists to guarantee."""
    import threading

    def _run():
        try:
            from fengshen_tpu.observability import (get_flight_recorder,
                                                    get_registry)
            recorder = get_flight_recorder()
            recorder.snapshot_metrics([get_registry()], force=True)
            recorder.dump(reason=trigger)
        except Exception:  # noqa: BLE001 — telemetry must not block
            # the abort
            pass

    t = threading.Thread(target=_run, daemon=True)
    t.start()
    t.join(timeout_s)


def _watchdog(seconds: int = 540) -> None:
    """Fail fast (exit 1) instead of hanging forever if the accelerator or
    its compile service is wedged.

    Thread-based (plus SIGALRM as a second line): a Python SIGALRM
    handler cannot run while the main thread is blocked inside a C call
    — exactly the state a wedged relay leaves us in (the round-4 probe
    proved this; os._exit from a daemon thread still works)."""
    import os
    import signal
    import threading
    import time

    _WATCHDOG_DEADLINE[0] = time.time() + seconds

    def on_alarm(signum, frame):
        import sys
        print("bench watchdog: accelerator unresponsive, aborting",
              file=sys.stderr, flush=True)
        _flight_dump("bench_watchdog")
        os._exit(1)

    try:
        signal.signal(signal.SIGALRM, on_alarm)
        signal.alarm(seconds)
    except (ValueError, OSError):
        pass

    if getattr(_watchdog, "_thread_started", False):
        return
    _watchdog._thread_started = True

    def watch():
        import sys
        while True:
            time.sleep(5)
            deadline = _WATCHDOG_DEADLINE[0]
            if deadline is not None and time.time() > deadline:
                print("bench watchdog (thread): accelerator unresponsive,"
                      " aborting", file=sys.stderr, flush=True)
                _flight_dump("bench_watchdog")
                os._exit(1)

    threading.Thread(target=watch, daemon=True).start()


def _probe_accelerator(seconds: int = 150) -> None:
    """256x256 matmul with its own short deadline BEFORE any heavy work:
    a wedged relay then yields a fast, unambiguous diagnostic instead of
    a slow watchdog abort mid-compile."""
    _watchdog(seconds)
    x = jnp.ones((256, 256), jnp.bfloat16)
    val = float((x @ x).block_until_ready()[0, 0])
    print(f"bench probe ok: backend={jax.default_backend()} val={val}",
          file=__import__("sys").stderr, flush=True)


def main() -> None:
    try:
        _main()
    finally:
        # disarm: a completed bench must leave no armed watchdog (thread
        # deadline OR pending SIGALRM) behind — embedders (e.g. the
        # bench smoke tests) call main() in-process and live long past
        # the deadline
        _disarm_watchdog()


# What an OOM looks like through the relay: compile-time OOMs carry the
# classic "Ran out of memory" allocator text, but RUNTIME OOMs surface as
# a bare "RESOURCE_EXHAUSTED: TPU backend error (ResourceExhausted)."
# (round-5 hardware log) — matching only the former crashed three ladder
# modes on the first healthy relay in three rounds.
_OOM_SIGNATURES = ("Ran out of memory", "RESOURCE_EXHAUSTED",
                   "ResourceExhausted")


def _is_oom_text(text: str) -> bool:
    return any(sig in text for sig in _OOM_SIGNATURES)


def _disarm_watchdog() -> None:
    import signal

    _WATCHDOG_DEADLINE[0] = None
    try:
        signal.alarm(0)
    except (ValueError, OSError):
        pass


def _spawn_rung(env_overrides: dict) -> tuple[int, str]:
    """One pinned bench attempt in a FRESH interpreter.

    Ladder rungs must not share a process: a rung that OOMs leaves its
    device buffers pinned on the relay until the client disconnects (the
    round-5 window showed rung N's leaked buffers OOM-ing rung N+1's
    state init at a size that fits a clean chip), and a fresh process is
    the only reliable release. stdout (the one JSON metric line) is
    inherited; stderr is captured so the caller can tell OOM (ladder
    down) from wedge (stop) from real failure (propagate), then echoed.
    """
    import os
    import subprocess
    import sys

    env = {**os.environ,
           **{k: str(v) for k, v in env_overrides.items()}}
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__)], env=env,
        stderr=subprocess.PIPE, text=True)
    sys.stderr.write(proc.stderr or "")
    sys.stderr.flush()
    return proc.returncode, proc.stderr or ""


def _ladder_of_rungs(rungs: list, label: str,
                     spawn=_spawn_rung) -> None:
    """Run pinned-rung subprocesses until one succeeds.

    OOM → step down; wedge (a child watchdog abort) → exit immediately
    (further rungs would each burn a 150s probe against a dead relay);
    anything else → propagate the child's rc."""
    import sys

    _disarm_watchdog()  # children carry their own watchdogs
    for env_overrides in rungs:
        rc, err = spawn(env_overrides)
        if rc == 0:
            print(f"bench[{label}]: rung {env_overrides} succeeded",
                  file=sys.stderr, flush=True)
            return
        if "accelerator unresponsive" in err:
            print(f"bench[{label}]: relay wedged, aborting ladder",
                  file=sys.stderr, flush=True)
            sys.exit(1)
        if not _is_oom_text(err):
            print(f"bench[{label}]: non-OOM failure (rc={rc}), not "
                  "laddering", file=sys.stderr, flush=True)
            sys.exit(rc)
        print(f"bench[{label}]: OOM at {env_overrides}, stepping down",
              file=sys.stderr, flush=True)
    raise RuntimeError(f"bench[{label}]: every ladder rung OOM")


def _emit(row: dict) -> None:
    """The one JSON metric line, written through the unified jsonl
    sink (docs/observability.md) — same schema, same stdout stream the
    BENCH drivers parse. A CPU-fallback run (BENCH_DEGRADED=1) carries
    `"degraded": true` so the driver never mistakes the rescue number
    for a hardware measurement."""
    import os
    import sys

    from fengshen_tpu.observability import (JsonlSink,
                                            get_flight_recorder)

    if os.environ.get("BENCH_DEGRADED", "0") == "1":
        row["degraded"] = True
    # rows join the flight recorder's ring so a later wedge's
    # post-mortem bundle shows what DID complete this round
    get_flight_recorder().record(row)
    JsonlSink(stream=sys.stdout, only_process_zero=False)(row)


# tiny shapes every mode can run on the CPU backend inside the watchdog
# budget (mirrors tests/test_bench_smoke.py TINY)
_CPU_TINY = {"BENCH_SEQ": "64", "BENCH_VOCAB": "256",
             "BENCH_HIDDEN": "64", "BENCH_INTER": "128",
             "BENCH_LAYERS": "2", "BENCH_HEADS": "4",
             "BENCH_ATTN": "dense", "BENCH_SKIP_PROBE": "1"}


def _cpu_fallback_env(mode: str) -> dict:
    env = {"BENCH_CHILD": "1", "JAX_PLATFORMS": "cpu",
           "BENCH_DEGRADED": "1", **_CPU_TINY}
    if mode == "large":
        env.update({"BENCH_LAYERS": "2", "BENCH_BATCH": "1",
                    "BENCH_KV": "2"})
    elif mode == "decode":
        env.update({"BENCH_BATCH": "1", "BENCH_PROMPT": "16",
                    "BENCH_NEW_TOKENS": "16", "BENCH_DECODE_RUNS": "1"})
    elif mode == "sharded":
        env.update({"BENCH_BATCH": "2", "BENCH_FSDP": "1",
                    "BENCH_TP": "1"})
    else:
        env["BENCH_BATCH"] = "2"
    return env


def _run_with_cpu_fallback(spawn=_spawn_rung) -> None:
    """Top-level rescue rung: run the real bench in a child process;
    if the child dies of a watchdog abort (wedged relay — five BENCH
    rounds ended with `parsed: null` exactly this way), retry ONCE on
    the CPU backend with tiny shapes so the round still emits its one
    JSON line, flagged degraded. Non-wedge failures propagate untouched
    (an OOM ladder or real bug must not be masked by a CPU number)."""
    import os
    import sys

    _disarm_watchdog()  # the child arms its own
    rc, err = spawn({"BENCH_CHILD": "1"})
    if rc == 0:
        return
    if "accelerator unresponsive" not in err:
        sys.exit(rc)
    mode = os.environ.get("BENCH_CONFIG", "default")
    print(f"bench: relay wedged; retrying once on the CPU backend "
          f"(mode={mode}, degraded)", file=sys.stderr, flush=True)
    rc2, _ = spawn(_cpu_fallback_env(mode))
    sys.exit(rc2)


def _probe_and_arm() -> None:
    """Probe + arm the watchdog — called at the top of every LEAF bench
    path (one that actually touches the accelerator). Ladder parents
    never call it: each child rung probes for itself, and a parent-held
    client would contend with its children on exclusive-access backends
    (directly-attached TPU device lock, GPU preallocation)."""
    import os

    if os.environ.get("BENCH_SKIP_PROBE", "0") != "1":
        _probe_accelerator()
    _watchdog()


def _main() -> None:
    import os

    # CPU-fallback wrapper: the OUTERMOST invocation runs the real
    # bench in a child so a wedge (in-process os._exit, no JSON) can
    # still be rescued with a degraded CPU number. BENCH_CHILD marks
    # the inner run; BENCH_CPU_FALLBACK=0 opts out (embedders like the
    # smoke tests set BENCH_CHILD directly to stay in-process).
    if os.environ.get("BENCH_CHILD") != "1" and \
            os.environ.get("BENCH_CPU_FALLBACK", "1") == "1":
        return _run_with_cpu_fallback()

    # Arm the watchdog BEFORE anything can touch the backend: mode
    # entry points call jax.devices() for their shape math, and backend
    # init through a wedged relay hangs forever with no armed deadline
    # (round-5: the sharded A/B row sat 15+ min inside jax.devices()
    # after the int8 row wedged the relay — no probe had run yet, so
    # nothing could abort it). Ladder parents disarm in
    # _ladder_of_rungs; leaf paths re-arm with their own budgets.
    _watchdog()

    mode = os.environ.get("BENCH_CONFIG", "default")
    if mode == "large":
        return _run_large()
    if mode == "sharded":
        return _run_sharded()
    if mode == "decode":
        return _run_decode()

    batches = os.environ.get("BENCH_BATCH")
    if batches:  # pinned: run in-process, let failures propagate
        _probe_and_arm()
        return _run(int(batches))
    # OOM-fallback ladder, one fresh process per rung: the tuned batch
    # first, then safer sizes — an OOM on a differently-provisioned chip
    # must degrade the number, not zero the driver signal. On tiles too
    # small for the materialized-logits path, the chunked fused-CE
    # config is the honest best config (round-5: fused-CE batch 28 ran
    # where materialized 28/24 OOM'd).
    fce_env = os.environ.get("BENCH_FUSED_CE")
    if fce_env or os.environ.get("BENCH_INT8_LMHEAD", "0") != "0" \
            or os.environ.get("BENCH_LORA", "0") != "0":
        # a lever row (explicit fused-CE chunking, int8 head, or LoRA)
        # must not silently mix IN the other lever on fallback — the
        # row would be incomparable to its baseline. Pure batch ladder.
        rungs = [{"BENCH_BATCH": b, "BENCH_FUSED_CE": fce_env or 0}
                 for b in (28, 24, 16, 8)]
    else:
        rungs = [{"BENCH_BATCH": 28, "BENCH_FUSED_CE": 0},
                 {"BENCH_BATCH": 24, "BENCH_FUSED_CE": 0},
                 {"BENCH_BATCH": 28, "BENCH_FUSED_CE": 8},
                 {"BENCH_BATCH": 16, "BENCH_FUSED_CE": 0},
                 {"BENCH_BATCH": 16, "BENCH_FUSED_CE": 8},
                 {"BENCH_BATCH": 8, "BENCH_FUSED_CE": 0}]
    _ladder_of_rungs(rungs, "default")


def _offload_request(default: str = "none") -> str:
    """BENCH_OFFLOAD → an `--offload` ladder request (docs/offload.md).
    Legacy truthy ints (the pre-probe boolean contract) map to "opt",
    "0"/"" keep the mode's default, and anything unrecognized warns and
    falls back to the default — the Trainer's argparse choices would
    otherwise SystemExit the whole bench run."""
    import os
    import sys

    raw = (os.environ.get("BENCH_OFFLOAD", "") or "").strip()
    if raw in ("", "0"):
        return default
    if raw in ("auto", "none", "opt", "opt_master", "stream"):
        return raw
    try:
        return "opt" if int(raw) else default
    except ValueError:
        print(f"bench: unrecognized BENCH_OFFLOAD={raw!r} (expected "
              "0|1|auto|none|opt|opt_master|stream); using "
              f"{default!r}", file=sys.stderr, flush=True)
        return default


def _trainer_bench(config, metric_name: str, per_chip: int,
                   seq: int, flops_attn_term: float,
                   extra_args: list, steps: int = 15) -> bool:
    """One Trainer-driven bench attempt in a FRESH run dir (Trainer
    appends to metrics.jsonl, so reusing a dir would mix runs/rungs).
    Returns True on success; raises on non-OOM errors; returns False on
    compile/runtime OOM so the caller's ladder can step down.

    Logging is windowed (every 3 steps), not per-step: materializing
    metrics each step blocks dispatch on the host pulling device values
    — through the axon relay that adds a full tunnel round-trip to
    EVERY step (the round-5 window measured trainer rows well below the
    raw-loop row on the same shape). With a 3-step window, steady-state
    steps pipeline back-to-back and only the window edge syncs."""
    import argparse
    import os
    import sys
    import tempfile

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.llama import LlamaForCausalLM
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.parallel import set_mesh
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.trainer.modules import CausalLMModule
    from fengshen_tpu.observability import peak_flops_per_chip

    # 900s, not the default 540: a 13B-shape rung is a long remote
    # compile plus 15 steps — a slow-but-healthy rung hitting the
    # watchdog would read as a wedge and abort the whole ladder
    _watchdog(900)
    n_dev = len(jax.devices())
    # BENCH_STEPS_PER_EXEC=K: scan K optimizer steps inside one jitted
    # dispatch (Trainer --steps_per_execution) — A/B row for the relay
    # dispatch-latency tax measured in the round-5 window
    spe = os.environ.get("BENCH_STEPS_PER_EXEC")
    if spe:
        extra_args = extra_args + ["--steps_per_execution", spe]
    root = tempfile.mkdtemp(prefix="fstpu_bench_")
    parser = argparse.ArgumentParser()
    add_module_args(parser)
    add_trainer_args(parser)
    UniversalDataModule.add_data_specific_args(parser)
    args = parser.parse_args([
        "--max_steps", str(steps),
        "--train_batchsize", str(per_chip * n_dev),
        "--log_every_n_steps", "3", "--warmup_steps", "1",
        "--default_root_dir", root] + extra_args)
    rng = np.random.RandomState(0)
    rows = [{"input_ids":
             rng.randint(0, config.vocab_size - 1, seq).tolist()}
            for _ in range(per_chip * n_dev * (steps + 1))]

    class DS:
        def __len__(self):
            return len(rows)

        def __getitem__(self, i):
            return rows[i]

    trainer = None
    try:
        trainer = Trainer(args)
        module = CausalLMModule(args, LlamaForCausalLM(config), config)
        dm = UniversalDataModule(args=args, datasets={"train": DS()})
        state = trainer.fit(module, dm)
        jax.block_until_ready(state.params)
    except Exception as e:  # noqa: BLE001 — ladder on OOM only
        set_mesh(None)
        if not _is_oom_text(str(e)):
            raise
        # the fixed "(ResourceExhausted)" marker guarantees a parent
        # _ladder_of_rungs classifies this rung as OOM (step down) no
        # matter how the backend phrased the message; the excerpt is
        # for the human log
        print(f"bench[{metric_name}]: OOM (ResourceExhausted) at "
              f"per_chip={per_chip}, stepping down ({str(e)[:160]})",
              file=sys.stderr, flush=True)
        return False
    set_mesh(None)
    metrics = [json.loads(line)
               for line in open(f"{root}/metrics.jsonl")]
    # steady-state: drop the first two 3-step windows (compile +
    # settling); average the remaining windowed readings
    tps_list = [m["tokens_per_sec"] for m in metrics
                if "tokens_per_sec" in m][2:]
    tps = float(np.mean(tps_list)) if tps_list else 0.0
    n_params = sum(int(np.prod(p.shape)) for p in
                   jax.tree_util.tree_leaves(state.params))
    flops_per_token = 6.0 * n_params + flops_attn_term
    # resolver honors FSTPU_PEAK_FLOPS and the nominal CPU fallback
    # (docs/observability.md) — same denominator as the decode and
    # serving rows
    peak = peak_flops_per_chip(jax.devices()[0].device_kind)
    mfu = tps * flops_per_token / (peak * n_dev)
    row = {
        "metric": metric_name,
        "value": round(tps / n_dev, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 4),
        "mfu": float(f"{mfu:.4g}"),
    }
    # rows driven at an offload level carry the RESOLVED placement
    # (docs/offload.md) so benchdiff never compares across placements
    # — "auto" resolving to none keeps the row placement-free and
    # directly comparable to --offload=none rows
    policy = getattr(trainer, "_offload_policy", None)
    if policy is not None and policy.level != "none":
        row["offload"] = policy.level
        row["memory_kind"] = policy.opt_state_kind
    _emit(row)
    return True


def _run_large() -> None:
    """13B-SHAPED config (VERDICT r2 item 2): the real LLaMA-13B layer
    shape — hidden 5120, intermediate 13824, 40 query heads at head_dim
    128 with GQA (8 kv heads), 32k vocab, seq 2048 — at the deepest
    layer count that fits one chip, driven through the ACTUAL Trainer so
    the production levers (bf16 params, --offload_optimizer host-resident
    adam, remat) are the ones measured. BENCH_LAYERS + BENCH_BATCH
    (both) pin one ladder rung."""
    import os
    import sys

    from fengshen_tpu.models.llama import LlamaConfig

    seq = int(os.environ.get("BENCH_SEQ", "2048"))
    layers_env = os.environ.get("BENCH_LAYERS")
    batch_env = os.environ.get("BENCH_BATCH")
    if bool(layers_env) != bool(batch_env):
        print("bench-large: set BOTH BENCH_LAYERS and BENCH_BATCH to pin "
              "a rung; ignoring the lone override and running the ladder",
              file=sys.stderr, flush=True)
    if not (layers_env and batch_env):
        # each rung in a fresh process (see _spawn_rung): a failed
        # rung's relay-side buffers otherwise OOM the next rung.
        # Lower rungs mix in chunked fused CE (~1-2 GB of fp32 logits
        # freed at seq 2048) — on a small tile that rescues a deeper
        # rung, which is worth more than a materialized shallow one.
        rungs = [(8, 4, 0), (8, 4, 8), (8, 2, 8), (6, 2, 8),
                 (4, 1, 8), (2, 1, 8)]
        if os.environ.get("BENCH_FUSED_CE"):  # explicit: honor it
            fce = os.environ["BENCH_FUSED_CE"]
            rungs = list(dict.fromkeys(
                (l, b, fce) for l, b, _ in rungs))
        return _ladder_of_rungs(
            [{"BENCH_CONFIG": "large", "BENCH_LAYERS": l,
              "BENCH_BATCH": b, "BENCH_FUSED_CE": f}
             for l, b, f in rungs],
            "large")
    layers, per_chip = int(layers_env), int(batch_env)
    _probe_and_arm()
    # env dim overrides exist ONLY for CPU smoking (a 5120-dim
    # compile exceeds the watchdog on the CPU backend); hardware
    # runs use the 13B defaults
    config = LlamaConfig(
        vocab_size=int(os.environ.get("BENCH_VOCAB", "32000")),
        hidden_size=int(os.environ.get("BENCH_HIDDEN", "5120")),
        intermediate_size=int(os.environ.get("BENCH_INTER", "13824")),
        num_hidden_layers=layers,
        num_attention_heads=int(os.environ.get("BENCH_HEADS", "40")),
        num_key_value_heads=int(os.environ.get("BENCH_KV", "8")),
        max_position_embeddings=seq, dtype="bfloat16",
        param_dtype="bfloat16", attention_impl="flash",
        scan_layers=True, gradient_checkpointing=True,
        remat_policy=os.environ.get("BENCH_REMAT", "dots_no_batch"),
        fused_ce_chunks=int(os.environ.get("BENCH_FUSED_CE", "0")))
    if not _trainer_bench(
            config, f"llama13bshape_l{layers}_train_tokens_per_sec"
            "_per_chip", per_chip, seq,
            flops_attn_term=12.0 * config.num_hidden_layers *
            config.hidden_size * seq,
            # capability-probed placement (docs/offload.md): auto picks
            # the shallowest level whose footprint fits the reported
            # device budget — the pre-probe hard-coded
            # --offload_optimizer aborted this whole mode on backends
            # without pinned_host (the seed-failing bench smoke tests)
            extra_args=["--offload", _offload_request("auto")]):
        raise RuntimeError(
            f"bench-large: rung l{layers} b{per_chip} OOM")


def _run_sharded() -> None:
    """BENCH_CONFIG=sharded: the default 300M shape driven through the
    Trainer's fsdp+tensor-sharded step (partition rules + sharding
    constraints + donation — the code path a pod runs). Axis sizes are
    env-overridable (BENCH_FSDP / BENCH_TP) and default to fsdp=n_dev on
    multi-chip hosts so the mode actually shards when it can."""
    import os

    from fengshen_tpu.models.llama import LlamaConfig

    _probe_and_arm()  # fast wedge diagnostic before any heavy work
    n_dev = len(jax.devices())
    seq = int(os.environ.get("BENCH_SEQ", "1024"))
    per_chip = int(os.environ.get("BENCH_BATCH", "16"))
    fsdp = int(os.environ.get("BENCH_FSDP", str(n_dev)))
    tp = int(os.environ.get("BENCH_TP", "1"))
    config = LlamaConfig(
        vocab_size=int(os.environ.get("BENCH_VOCAB", "32000")),
        hidden_size=int(os.environ.get("BENCH_HIDDEN", "1024")),
        intermediate_size=int(os.environ.get("BENCH_INTER", "2816")),
        num_hidden_layers=int(os.environ.get("BENCH_LAYERS", "16")),
        num_attention_heads=int(os.environ.get("BENCH_HEADS", "8")),
        max_position_embeddings=seq, dtype="bfloat16",
        attention_impl=os.environ.get("BENCH_ATTN", "flash"),
        scan_layers=True, gradient_checkpointing=True,
        remat_policy=os.environ.get("BENCH_REMAT", "dots_no_batch"))
    extra = ["--fsdp_parallel_size", str(fsdp),
             "--tensor_model_parallel_size", str(tp)]
    name = "llama300m_sharded_step_tokens_per_sec_per_chip"
    offload = _offload_request()
    if offload not in ("none", "auto"):
        # headroom lever row (docs/performance.md): host-resident adam
        # moments (and master params at opt_master) between steps —
        # measures the offloaded-update cost on the 300M shape. The
        # memory kind is probe-resolved (docs/offload.md), so this row
        # runs on pinned_host-less backends too.
        extra += ["--offload", offload]
        name = "llama300m_offload_update_tokens_per_sec_per_chip"
    elif offload == "auto":
        # auto at the 300M shape must resolve to "none" whenever the
        # state fits (the <5% tokens/s acceptance bar vs --offload=none
        # holds by construction: same program); keep the base metric
        # name and let the emitted row carry any resolved placement
        extra += ["--offload", "auto"]
    else:
        # the baseline rung is PINNED device-resident: without this the
        # Trainer's --offload default ("auto") could quietly offload on
        # a memory-pressured chip and the base metric would stop being
        # comparable to its published baseline
        extra += ["--offload", "none"]
    if not _trainer_bench(
            config, name, per_chip, seq,
            flops_attn_term=12.0 * config.num_hidden_layers *
            config.hidden_size * seq, extra_args=extra):
        raise RuntimeError("bench-sharded: OOM")


def _run_decode() -> None:
    """BENCH_CONFIG=decode: jitted KV-cached generation throughput
    (VERDICT r4 item 5; reference serving analog:
    fengshen/examples/ziya_inference — greedy/sampled causal decode —
    and the qa_t5/summary beam decodes).

    Default row: greedy decode on the 300M-shape LLaMA (bf16, flash
    prefill, scan KV cache); BENCH_INT8_LMHEAD=1 measures the int8
    serving head. BENCH_DECODE=beam instead measures num_beams=4
    seq2seq beam search on a Randeng-T5-ish encoder-decoder. Metric is
    GENERATED tokens/sec/chip (prompt prefill included in the time).
    CPU-smokable with the usual BENCH_* shrinks + BENCH_NEW_TOKENS.
    """
    import os

    from jax.sharding import NamedSharding, PartitionSpec as P

    from fengshen_tpu.parallel import MeshConfig, make_mesh, set_mesh

    _probe_and_arm()  # fast wedge diagnostic before any heavy work
    n_dev = len(jax.devices())
    batch = int(os.environ.get("BENCH_BATCH", "8")) * n_dev
    prompt = int(os.environ.get("BENCH_PROMPT", "128"))
    new_tokens = int(os.environ.get("BENCH_NEW_TOKENS", "512"))
    runs = max(1, int(os.environ.get("BENCH_DECODE_RUNS", "3")))
    rng = np.random.RandomState(0)
    # shard the batch over all chips (the serving layout); params stay
    # replicated — without this a multi-chip host would decode on one
    # device and the /n_dev per-chip number would lie
    mesh = make_mesh(MeshConfig(data=n_dev, fsdp=1, sequence=1, tensor=1))
    set_mesh(mesh)
    batch_sh = NamedSharding(mesh, P(("data",)))

    if os.environ.get("BENCH_DECODE", "greedy") == "beam":
        from fengshen_tpu.models.t5 import T5Config, T5ForConditionalGeneration
        from fengshen_tpu.utils.generate import seq2seq_generate

        config = T5Config(
            vocab_size=int(os.environ.get("BENCH_VOCAB", "32128")),
            d_model=int(os.environ.get("BENCH_HIDDEN", "768")),
            d_kv=64,
            d_ff=int(os.environ.get("BENCH_INTER", "2048")),
            num_layers=int(os.environ.get("BENCH_LAYERS", "12")),
            num_heads=int(os.environ.get("BENCH_HEADS", "12")),
            dtype="bfloat16", tie_word_embeddings=False,
            # cache must out-size max_new_tokens or seq2seq_generate
            # silently falls back to the uncached O(L^2) re-run path —
            # the row must measure the KV-cached serving loop
            decode_cache_length=new_tokens + prompt + 8)
        model = T5ForConditionalGeneration(config)
        src = jax.device_put(
            jnp.asarray(rng.randint(1, config.vocab_size - 1,
                                    (batch, prompt)), jnp.int32),
            batch_sh)
        params = jax.jit(lambda r: model.init(
            r, jnp.zeros((1, 8), jnp.int32),
            jnp.zeros((1, 4), jnp.int32))["params"])(jax.random.PRNGKey(0))

        @jax.jit
        def _gen(params, src):
            return seq2seq_generate(
                model, params, src, max_new_tokens=new_tokens,
                num_beams=4, eos_token_id=None, pad_token_id=0,
                decoder_start_token_id=0)

        def decode():
            return _gen(params, src)
        metric = "t5beam4_decode_tokens_per_sec_per_chip"
        compile_budget = 1800  # beam-search programs compile slowly
    else:
        from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        from fengshen_tpu.utils.generate import (generate,
                                                 speculative_generate)

        config = LlamaConfig(
            vocab_size=int(os.environ.get("BENCH_VOCAB", "32000")),
            hidden_size=int(os.environ.get("BENCH_HIDDEN", "1024")),
            intermediate_size=int(os.environ.get("BENCH_INTER", "2816")),
            num_hidden_layers=int(os.environ.get("BENCH_LAYERS", "16")),
            num_attention_heads=int(os.environ.get("BENCH_HEADS", "8")),
            max_position_embeddings=prompt + new_tokens,
            dtype="bfloat16", scan_layers=True,
            attention_impl=os.environ.get("BENCH_ATTN", "flash"),
            int8_lm_head=bool(int(os.environ.get("BENCH_INT8_LMHEAD",
                                                 "0"))))
        model = LlamaForCausalLM(config)
        ids = jax.device_put(
            jnp.asarray(rng.randint(1, config.vocab_size - 1,
                                    (batch, prompt)), jnp.int32),
            batch_sh)
        params = jax.jit(lambda r: model.init(
            r, jnp.zeros((1, 8), jnp.int32))["params"])(
            jax.random.PRNGKey(0))

        if os.environ.get("BENCH_DECODE") == "lookup":
            # draft-free prompt-lookup speculation (token-exact greedy;
            # wins scale with output repetitiveness)
            from fengshen_tpu.utils.generate import prompt_lookup_generate
            import dataclasses
            gamma = int(os.environ.get("BENCH_SPEC_GAMMA", "4"))
            config = dataclasses.replace(
                config,
                max_position_embeddings=prompt + new_tokens + gamma)
            model = LlamaForCausalLM(config)

            @jax.jit
            def _gen(params, ids):
                return prompt_lookup_generate(
                    model, params, ids, max_new_tokens=new_tokens,
                    gamma=gamma,
                    ngram=int(os.environ.get("BENCH_LOOKUP_NGRAM", "2")),
                    eos_token_id=None, pad_token_id=0)

            def decode():
                return _gen(params, ids)
            metric = ("llama300m_int8_lookup_decode_tokens_per_sec_per_chip"
                      if config.int8_lm_head else
                      "llama300m_lookup_decode_tokens_per_sec_per_chip")
            compile_budget = 1800 if config.int8_lm_head else 900
        elif os.environ.get("BENCH_DECODE") == "spec":
            # speculative decoding: token-exact greedy via a shallow
            # draft of the same width (BENCH_DRAFT_LAYERS deep). The
            # row measures COMMITTED tokens/sec — acceptance rate on
            # random-init weights is pessimal, so this row is a lower
            # bound on the mechanism's overhead, not a realistic
            # speedup (that needs a trained draft/target pair)
            import dataclasses
            gamma = int(os.environ.get("BENCH_SPEC_GAMMA", "4"))
            # the speculation window needs gamma extra cache slots
            # (speculative_generate refuses loudly without them);
            # params are RoPE so the rebuilt model reuses them as-is
            config = dataclasses.replace(
                config,
                max_position_embeddings=prompt + new_tokens + gamma)
            model = LlamaForCausalLM(config)
            draft_cfg = dataclasses.replace(
                config, num_hidden_layers=int(
                    os.environ.get("BENCH_DRAFT_LAYERS", "2")))
            draft = LlamaForCausalLM(draft_cfg)
            draft_params = jax.jit(lambda r: draft.init(
                r, jnp.zeros((1, 8), jnp.int32))["params"])(
                jax.random.PRNGKey(1))

            @jax.jit
            def _gen(params, draft_params, ids):
                return speculative_generate(
                    model, params, draft, draft_params, ids,
                    max_new_tokens=new_tokens, gamma=gamma,
                    eos_token_id=None, pad_token_id=0)

            def decode():
                return _gen(params, draft_params, ids)
            # the int8 lever composes with spec decode (the verify
            # forward just uses the int8 head) — keep the rows apart
            metric = ("llama300m_int8_spec_decode_tokens_per_sec_per_chip"
                      if config.int8_lm_head else
                      "llama300m_spec_decode_tokens_per_sec_per_chip")
            compile_budget = 1800  # two models + while_loop program
        else:
            @jax.jit
            def _gen(params, ids):
                return generate(model, params, ids,
                                max_new_tokens=new_tokens,
                                eos_token_id=None, pad_token_id=0)

            def decode():
                return _gen(params, ids)
            metric = ("llama300m_int8_decode_tokens_per_sec_per_chip"
                      if config.int8_lm_head else
                      "llama300m_decode_tokens_per_sec_per_chip")
            compile_budget = 1800 if config.int8_lm_head else 900

    # Compile under a GENEROUS budget: both relay wedges this round
    # followed a 540s watchdog abort on an int8 row — the likely
    # mechanism is the abort itself, killing the process with an
    # in-flight remote compile (the one thing the wedge protocol says
    # never to do). A slow-but-alive compile must be allowed to finish;
    # the probe at the top of this function already proved the relay
    # responsive, so a hang here is a slow compile, not a dead relay.
    _watchdog(compile_budget)
    jax.block_until_ready(decode())  # compile
    _watchdog()
    t0 = time.perf_counter()
    for _ in range(runs):
        out = decode()
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    set_mesh(None)
    tps = batch * new_tokens * runs / dt
    # no MFU target for decode (bandwidth-bound); vs_baseline is
    # tokens/sec/chip relative to the training north-star scale (40%
    # MFU train ≈ 43k tok/s at 300M) — a rough single-number context
    row = {
        "metric": metric,
        "value": round(tps / n_dev, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(tps / n_dev / 43000.0, 4),
    }
    # utilization column (forward-only FLOPs — decode does no backward);
    # the low absolute value IS the point: it quantifies how far
    # bandwidth-bound batch-1 decode sits from the chip's matmul peak
    from fengshen_tpu.observability import (estimate_flops_per_token,
                                            peak_flops_per_chip)
    f_tok = estimate_flops_per_token(config, include_backward=False)
    if f_tok:
        peak = peak_flops_per_chip(jax.devices()[0].device_kind)
        row["mfu"] = float(f"{tps * f_tok / (peak * n_dev):.4g}")
    _emit(row)


def _run(per_chip_batch: int) -> None:
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.parallel import MeshConfig, make_mesh, set_mesh
    from fengshen_tpu.parallel.cross_entropy import stable_cross_entropy
    from fengshen_tpu.observability import peak_flops_per_chip

    n_dev = len(jax.devices())
    mesh = make_mesh(MeshConfig(data=n_dev, fsdp=1, sequence=1, tensor=1))
    set_mesh(mesh)

    # ~300M-param LLaMA slice; bf16 compute, fp32 params/adam.
    # Env overrides make the MFU sweep (VERDICT r1 item 2) a flag flip:
    # BENCH_BATCH / BENCH_SEQ / BENCH_REMAT / BENCH_ATTN / BENCH_HEADS.
    # Round-2 final defaults: heads 8 → head_dim 128 (the real
    # LLaMA-13B head_dim, and the Pallas flash kernel's tile-eligibility
    # bound), batch 28, dots_no_batch remat — measured 85,654 tok/s/chip
    # ≈ 79% MFU on the v5e (docs/performance.md has the full sweep).
    import os
    seq = int(os.environ.get("BENCH_SEQ", "1024"))
    config = LlamaConfig(
        vocab_size=int(os.environ.get("BENCH_VOCAB", "32000")),
        hidden_size=int(os.environ.get("BENCH_HIDDEN", "1024")),
        intermediate_size=int(os.environ.get("BENCH_INTER", "2816")),
        num_hidden_layers=int(os.environ.get("BENCH_LAYERS", "16")),
        num_attention_heads=int(os.environ.get("BENCH_HEADS", "8")),
        max_position_embeddings=seq, dtype="bfloat16",
        attention_impl=os.environ.get("BENCH_ATTN", "flash"),
        scan_layers=True, gradient_checkpointing=True,
        remat_policy=os.environ.get("BENCH_REMAT", "dots_no_batch"),
        # headroom lever rows (docs/performance.md): BENCH_INT8_LMHEAD=1
        int8_lm_head=bool(int(os.environ.get("BENCH_INT8_LMHEAD", "0"))),
        # BENCH_FUSED_CE=<chunks>: chunked fused LM-head+CE frees the
        # ~3.7GB fp32 logits tensor → try larger BENCH_BATCH with it
        fused_ce_chunks=int(os.environ.get("BENCH_FUSED_CE", "0")))
    model = LlamaForCausalLM(config)
    batch = per_chip_batch * n_dev

    rng = jax.random.PRNGKey(0)
    params = jax.jit(
        lambda r: model.init(r, jnp.zeros((1, 8), jnp.int32))["params"])(rng)
    lora_rank = int(os.environ.get("BENCH_LORA", "0"))
    if lora_rank:
        # LoRA lever row: frozen base + rank-r adapters on the
        # attention projections — measures the stop_gradient DCE win
        # (no base weight grads, adam only on adapters) vs the full-
        # finetune row at the same shape
        from functools import partial

        from fengshen_tpu.ops.lora import (apply_lora, init_lora,
                                           lora_param_labels)
        params = {"base": params,
                  "lora": init_lora(params, jax.random.PRNGKey(1),
                                    lora_rank,
                                    r"(q_proj|k_proj|v_proj|o_proj)")}
        tx = optax.multi_transform(
            {"lora": optax.adamw(1e-4, weight_decay=0.1),
             "freeze": optax.set_to_zero()},
            partial(lora_param_labels, train_regex=None))
    else:
        tx = optax.adamw(1e-4, weight_decay=0.1)
    opt_state = jax.jit(tx.init)(params)

    ids = jnp.asarray(np.random.RandomState(0).randint(
        0, config.vocab_size - 1, (batch, seq)), jnp.int32)

    if config.fused_ce_chunks:
        from fengshen_tpu.ops.fused_ce import causal_fused_loss

        def loss_fn(p, ids):
            hidden = model.apply({"params": p}, ids, return_hidden=True)
            kernel = p["lm_head"]["kernel"].astype(hidden.dtype)
            loss, _, _ = causal_fused_loss(
                hidden, kernel, ids, num_chunks=config.fused_ce_chunks)
            return loss
    else:
        def loss_fn(p, ids):
            logits = model.apply({"params": p}, ids)
            loss, _ = stable_cross_entropy(logits[:, :-1], ids[:, 1:])
            return loss

    if lora_rank:
        inner_loss = loss_fn

        def loss_fn(p, ids):  # noqa: F811 — merged-view wrapper
            merged = apply_lora(jax.lax.stop_gradient(p["base"]),
                                p["lora"])
            return inner_loss(merged, ids)

    @jax.jit
    def step(p, o, ids):
        loss, grads = jax.value_and_grad(loss_fn)(p, ids)
        updates, o = tx.update(grads, o, p)
        p = optax.apply_updates(p, updates)
        return p, o, loss

    # warmup / compile — generous budget for the int8 path (see
    # _run_decode: a watchdog abort mid-remote-compile is the wedge
    # mechanism; slow compiles must finish, hangs still die at 30 min)
    if config.int8_lm_head:
        _watchdog(1800)
    params, opt_state, loss = step(params, opt_state, ids)
    jax.block_until_ready(loss)
    _watchdog()

    n_steps = 20
    t0 = time.perf_counter()
    for _ in range(n_steps):
        params, opt_state, loss = step(params, opt_state, ids)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    tokens = batch * seq * n_steps
    tps = tokens / dt
    n_params = sum(int(np.prod(p.shape)) for p in
                   jax.tree_util.tree_leaves(params))
    flops_per_token = 6.0 * n_params + 12.0 * config.num_hidden_layers * \
        config.hidden_size * seq  # attention term
    # resolver honors FSTPU_PEAK_FLOPS and the nominal CPU fallback
    # (docs/observability.md) — same denominator as the decode and
    # serving rows
    peak = peak_flops_per_chip(jax.devices()[0].device_kind)
    mfu = tps * flops_per_token / (peak * n_dev)

    _emit({
        # lever rows must be distinguishable in the BENCH file (the
        # int8 head changes numerics; LoRA changes what trains)
        "metric": ("llama300m_lora_train_tokens_per_sec_per_chip"
                   if lora_rank else
                   "llama300m_int8_train_tokens_per_sec_per_chip"
                   if config.int8_lm_head else
                   "llama300m_train_tokens_per_sec_per_chip"),
        "value": round(tps / n_dev, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 4),
        "mfu": float(f"{mfu:.4g}"),
    })


if __name__ == "__main__":
    main()
