#!/bin/bash
#SBATCH --job-name=fengshen-tpu
#SBATCH --nodes=2
#SBATCH --ntasks-per-node=1
#SBATCH --cpus-per-task=32
# Multi-host launcher (reference pattern:
# fengshen/examples/ziya_llama/finetune_with_tp.sh SLURM driver).
# Usage: sbatch launchers/slurm_multihost.sh <module> [args...]

MODULE=${1:-fengshen_tpu.examples.pretrain_t5.pretrain_t5}
shift || true

MASTER_ADDR=$(scontrol show hostnames "$SLURM_JOB_NODELIST" | head -n1)
export FSTPU_COORDINATOR="${MASTER_ADDR}:29500"
export FSTPU_NUM_PROCESSES=$SLURM_NTASKS

srun --export=ALL bash -c "
  FSTPU_PROCESS_ID=\$SLURM_PROCID python - <<PY
from fengshen_tpu.parallel import distributed_initialize
import os, runpy, sys
distributed_initialize(os.environ['FSTPU_COORDINATOR'],
                       int(os.environ['FSTPU_NUM_PROCESSES']),
                       int(os.environ['FSTPU_PROCESS_ID']))
sys.argv = ['$MODULE'] + '$*'.split()
runpy.run_module('$MODULE', run_name='__main__')
PY"
