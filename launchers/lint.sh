#!/usr/bin/env bash
# CI lint gate: run fslint (the AST SPMD hazard analyzer,
# docs/static_analysis.md) over the package and fail on any
# non-baselined finding. Emits the machine-readable report to stdout
# (sorted — safe to diff across hosts); pass extra args through, e.g.
#   launchers/lint.sh --select blanket-except
#   FSLINT_OUT=lint.json launchers/lint.sh
set -euo pipefail
cd "$(dirname "$0")/.."

out="${FSLINT_OUT:-}"
if [[ -n "$out" ]]; then
    python -m fengshen_tpu.analysis --json "$@" | tee "$out"
else
    python -m fengshen_tpu.analysis --json "$@"
fi
