"""TF-checkpoint import (utils/tf_import.py — closes the reference's
convert_tf_checkpoint_to_pytorch surface, previously a documented
non-port).

Oracle: write a synthetic google-research-BERT-named TF checkpoint,
load it into torch through HF's own `load_tf_weights_in_bert`, and
require our direct TF→flax import to reproduce the torch logits.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

tf = pytest.importorskip("tensorflow")
torch = pytest.importorskip("torch")

pytestmark = pytest.mark.slow

H, L, HEADS, FF, V, P_, TT = 32, 2, 4, 64, 120, 64, 2


def _tf_var_specs():
    rng = np.random.RandomState(0)

    def r(*shape):
        return (rng.randn(*shape) * 0.05).astype(np.float32)

    specs = {
        "bert/embeddings/word_embeddings": r(V, H),
        "bert/embeddings/position_embeddings": r(P_, H),
        "bert/embeddings/token_type_embeddings": r(TT, H),
        "bert/embeddings/LayerNorm/gamma": 1 + r(H),
        "bert/embeddings/LayerNorm/beta": r(H),
        "bert/pooler/dense/kernel": r(H, H),
        "bert/pooler/dense/bias": r(H),
        "cls/predictions/transform/dense/kernel": r(H, H),
        "cls/predictions/transform/dense/bias": r(H),
        "cls/predictions/transform/LayerNorm/gamma": 1 + r(H),
        "cls/predictions/transform/LayerNorm/beta": r(H),
        "cls/predictions/output_bias": r(V),
        "cls/seq_relationship/output_weights": r(2, H),
        "cls/seq_relationship/output_bias": r(2),
    }
    for i in range(L):
        p = f"bert/encoder/layer_{i}"
        for sub in ("attention/self/query", "attention/self/key",
                    "attention/self/value", "attention/output/dense"):
            specs[f"{p}/{sub}/kernel"] = r(H, H)
            specs[f"{p}/{sub}/bias"] = r(H)
        specs[f"{p}/attention/output/LayerNorm/gamma"] = 1 + r(H)
        specs[f"{p}/attention/output/LayerNorm/beta"] = r(H)
        specs[f"{p}/intermediate/dense/kernel"] = r(H, FF)
        specs[f"{p}/intermediate/dense/bias"] = r(FF)
        specs[f"{p}/output/dense/kernel"] = r(FF, H)
        specs[f"{p}/output/dense/bias"] = r(H)
        specs[f"{p}/output/LayerNorm/gamma"] = 1 + r(H)
        specs[f"{p}/output/LayerNorm/beta"] = r(H)
    return specs


def _write_tf_ckpt(tmp_path, specs):
    prefix = str(tmp_path / "model.ckpt")
    names = sorted(specs)
    tf.raw_ops.SaveV2(
        prefix=tf.constant(prefix),
        tensor_names=tf.constant(names),
        shape_and_slices=tf.constant([""] * len(names)),
        tensors=[tf.constant(specs[n]) for n in names])
    return prefix


def test_tf_bert_import_matches_hf_loader(tmp_path):
    import transformers
    from transformers.models.bert.modeling_bert import (
        load_tf_weights_in_bert)

    from fengshen_tpu.models.bert import BertConfig, BertForMaskedLM
    from fengshen_tpu.utils.tf_import import tf_bert_checkpoint_to_params

    specs = _tf_var_specs()
    prefix = _write_tf_ckpt(tmp_path, specs)

    # torch oracle: HF's own TF loader
    hf_cfg = transformers.BertConfig(
        vocab_size=V, hidden_size=H, num_hidden_layers=L,
        num_attention_heads=HEADS, intermediate_size=FF,
        max_position_embeddings=P_, type_vocab_size=TT,
        attn_implementation="eager")
    tm = transformers.BertForPreTraining(hf_cfg)
    load_tf_weights_in_bert(tm, hf_cfg, prefix)
    tm.eval()

    cfg = BertConfig(vocab_size=V, hidden_size=H, num_hidden_layers=L,
                     num_attention_heads=HEADS, intermediate_size=FF,
                     max_position_embeddings=P_, type_vocab_size=TT,
                     dtype="float32")
    params = tf_bert_checkpoint_to_params(prefix, cfg)

    ids = np.array([[2, 17, 9, 42, 7, 99, 1, 5]], np.int64)
    with torch.no_grad():
        ref = tm(torch.tensor(ids)).prediction_logits.numpy()
    ours = BertForMaskedLM(cfg).apply(
        {"params": params}, jnp.asarray(ids, jnp.int32))
    np.testing.assert_allclose(np.asarray(ours), ref, atol=3e-4)


def test_tf_import_cli_writes_orbax(tmp_path):
    from fengshen_tpu.models.bert import BertConfig
    from fengshen_tpu.utils import tf_import

    specs = _tf_var_specs()
    prefix = _write_tf_ckpt(tmp_path, specs)
    cfg_dir = tmp_path / "cfg"
    cfg_dir.mkdir()
    BertConfig(vocab_size=V, hidden_size=H, num_hidden_layers=L,
               num_attention_heads=HEADS, intermediate_size=FF,
               max_position_embeddings=P_,
               type_vocab_size=TT).save_pretrained(str(cfg_dir))
    out = tmp_path / "out"
    tf_import.main(["--tf_checkpoint_path", prefix,
                    "--bert_config_file", str(cfg_dir / "config.json"),
                    "--output_path", str(out)])
    assert (out / "config.json").exists()
    assert (out / "params").exists()
