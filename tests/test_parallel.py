"""Parallelism-core tests on the virtual 8-device CPU mesh.

This is the single-host multi-device TP simulation the reference never had
(SURVEY.md §4: multi-node is exercised only via SLURM scripts there).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from fengshen_tpu.parallel import (
    MeshConfig, make_mesh, set_mesh, match_partition_rules, make_shardings,
    with_sharding_constraint, shard_batch_spec, vocab_parallel_cross_entropy,
)
from fengshen_tpu.parallel.cross_entropy import stable_cross_entropy
from fengshen_tpu.ops.ring_attention import ring_attention_sharded


def test_mesh_shapes():
    cfg = MeshConfig(data=-1, fsdp=2, sequence=1, tensor=2)
    assert cfg.resolve(8) == (2, 2, 1, 1, 1, 2)
    with pytest.raises(ValueError):
        MeshConfig(data=3, fsdp=2, tensor=2).resolve(8)
    with pytest.raises(ValueError):
        MeshConfig(tensor=3).resolve(8)


def test_mesh_build(mesh8):
    assert dict(mesh8.shape) == {"data": 2, "fsdp": 2, "expert": 1,
                                 "pipe": 1, "sequence": 1, "tensor": 2}


def test_match_partition_rules():
    tree = {
        "embed": {"embedding": jnp.zeros((100, 16))},
        "layer_0": {"attn": {"qkv": {"kernel": jnp.zeros((16, 48))}},
                    "mlp": {"w2": {"kernel": jnp.zeros((64, 16))}}},
        "norm": {"scale": jnp.zeros((16,))},
        "step": jnp.zeros(()),
    }
    rules = [
        ("embed/embedding", P("tensor", None)),
        ("qkv/kernel", P(None, "tensor")),
        ("w2/kernel", P("tensor", None)),
        ("norm", P(None)),
    ]
    specs = match_partition_rules(rules, tree)
    assert specs["embed"]["embedding"] == P("tensor", None)
    assert specs["layer_0"]["attn"]["qkv"]["kernel"] == P(None, "tensor")
    assert specs["layer_0"]["mlp"]["w2"]["kernel"] == P("tensor", None)
    assert specs["step"] == P()  # scalar always replicated


def test_match_partition_rules_unmatched_raises():
    with pytest.raises(ValueError, match="no partition rule"):
        match_partition_rules([("x", P())], {"y": jnp.zeros((4, 4))})


def test_make_shardings_places_params(mesh8):
    tree = {"w": jnp.zeros((8, 16)), "b": jnp.zeros((16,))}
    rules = [("w", P(None, "tensor")), ("b", P(None))]
    shardings = make_shardings(rules, tree, mesh8)
    placed = jax.device_put(tree, shardings)
    assert placed["w"].sharding.spec == P(None, "tensor")
    # uneven dim falls back to replicated rather than erroring
    tree2 = {"w": jnp.zeros((8, 15)), "b": jnp.zeros((15,))}
    sh2 = make_shardings(rules, tree2, mesh8)
    placed2 = jax.device_put(tree2, sh2)
    assert placed2["w"].sharding.spec == P(None, None)


def test_with_sharding_constraint_no_mesh():
    set_mesh(None)
    x = jnp.ones((4, 4))
    y = with_sharding_constraint(x, P("data", None))
    np.testing.assert_allclose(x, y)


def test_shard_batch_spec():
    assert shard_batch_spec(2) == P(("data", "fsdp"), None)
    assert shard_batch_spec(3, sequence_axis=1) == \
        P(("data", "fsdp"), "sequence", None)


def test_stable_cross_entropy_matches_logsoftmax():
    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(4, 6, 32), jnp.float32)
    targets = jnp.asarray(rng.randint(0, 32, (4, 6)))
    targets = targets.at[:, -2:].set(-100)  # ignore tail
    loss, n = stable_cross_entropy(logits, targets)
    lp = jax.nn.log_softmax(logits, axis=-1)
    valid = np.asarray(targets) != -100
    ref = -np.asarray(lp)[np.nonzero(valid) +
                          (np.asarray(targets)[valid],)].mean()
    np.testing.assert_allclose(loss, ref, atol=1e-5)
    assert int(n) == valid.sum()


def test_vocab_parallel_ce_matches_replicated(mesh8):
    rng = np.random.RandomState(1)
    logits = jnp.asarray(rng.randn(2, 8, 64), jnp.float32)
    targets = jnp.asarray(rng.randint(0, 64, (2, 8)))
    targets = targets.at[0, :3].set(-100)
    ref, _ = stable_cross_entropy(logits, targets)
    loss, n = vocab_parallel_cross_entropy(logits, targets, mesh8)
    np.testing.assert_allclose(loss, ref, atol=1e-5)


def test_vocab_parallel_ce_grad_matches(mesh8):
    rng = np.random.RandomState(2)
    logits = jnp.asarray(rng.randn(2, 4, 64), jnp.float32)
    targets = jnp.asarray(rng.randint(0, 64, (2, 4)))

    def loss_rep(lg):
        return stable_cross_entropy(lg, targets)[0]

    def loss_par(lg):
        return vocab_parallel_cross_entropy(lg, targets, mesh8)[0]

    g_ref = jax.grad(loss_rep)(logits)
    g_par = jax.grad(loss_par)(logits)
    np.testing.assert_allclose(g_par, g_ref, atol=1e-5)


def test_ring_attention_matches_dense(mesh_seq4):
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(2, 16, 4, 8), jnp.float32)
    k = jnp.asarray(rng.randn(2, 16, 4, 8), jnp.float32)
    v = jnp.asarray(rng.randn(2, 16, 4, 8), jnp.float32)

    from fengshen_tpu.ops import dot_product_attention, causal_mask
    ref = dot_product_attention(q, k, v, mask=causal_mask(16)[None, None])
    out = ring_attention_sharded(q, k, v, mesh=mesh_seq4, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


def test_ring_attention_non_causal(mesh_seq4):
    rng = np.random.RandomState(4)
    q = jnp.asarray(rng.randn(1, 8, 2, 4), jnp.float32)
    k = jnp.asarray(rng.randn(1, 8, 2, 4), jnp.float32)
    v = jnp.asarray(rng.randn(1, 8, 2, 4), jnp.float32)
    from fengshen_tpu.ops import dot_product_attention
    ref = dot_product_attention(q, k, v)
    out = ring_attention_sharded(q, k, v, mesh=mesh_seq4, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


def test_ring_attention_segment_ids(mesh_seq4):
    """Ring attention with segment ids (padded batch) matches
    dense-with-mask on valid rows — sequence parallelism no longer
    downgrades under padding (SURVEY §5.7)."""
    import numpy as np
    from fengshen_tpu.ops.attention import dot_product_attention
    from fengshen_tpu.ops.masks import causal_mask
    from fengshen_tpu.ops.ring_attention import ring_attention_sharded

    rng = np.random.RandomState(0)
    batch, seq = 2, 16
    q = jnp.asarray(rng.randn(batch, seq, 2, 8), jnp.float32)
    k = jnp.asarray(rng.randn(batch, seq, 2, 8), jnp.float32)
    v = jnp.asarray(rng.randn(batch, seq, 2, 8), jnp.float32)
    n_valid = 11
    seg = jnp.asarray(
        np.repeat([[1] * n_valid + [0] * (seq - n_valid)], batch, 0),
        jnp.int32)

    out = ring_attention_sharded(q, k, v, segment_ids=seg)
    mask = (seg[:, None, None, :] > 0) & causal_mask(seq)[None, None]
    ref = dot_product_attention(q, k, v, mask=mask)
    np.testing.assert_allclose(np.asarray(out)[:, :n_valid],
                               np.asarray(ref)[:, :n_valid], atol=1e-4)


# -- vocab-parallel embedding (SPMD full-rematerialization hazard) ---------

def test_embed_lookup_onehot_matches_take(mesh8):
    """embed_lookup's one-hot matmul path (vocab sharded over 'tensor')
    matches a plain take bit-for-bit in fp32."""
    from fengshen_tpu.ops.embedding import embed_lookup, vocab_shards

    rng = np.random.RandomState(0)
    table = jnp.asarray(rng.randn(64, 16), jnp.float32)
    ids = jnp.asarray(rng.randint(0, 64, (4, 8)), jnp.int32)
    assert vocab_shards(64) == 2  # one-hot path active under mesh8
    out = embed_lookup(table, ids)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(jnp.take(table, ids, axis=0)))
    # grads flow as a matmul, matching the take gradient
    g_onehot = jax.grad(lambda t: embed_lookup(t, ids).sum())(table)
    g_take = jax.grad(lambda t: jnp.take(t, ids, axis=0).sum())(table)
    np.testing.assert_allclose(np.asarray(g_onehot), np.asarray(g_take),
                               atol=1e-6)


def test_embed_lookup_unsharded_uses_take():
    from fengshen_tpu.ops.embedding import vocab_shards
    assert vocab_shards(64) == 1  # no mesh installed
    assert vocab_shards(63) == 1


def test_no_involuntary_rematerialization_in_sharded_train_step(capfd):
    """Compiling the fsdp+sp+tp-sharded train step must not trigger XLA's
    'Involuntary full rematerialization' fallback (the multi-chip embedding
    hazard VERDICT r2 flagged: a gather on the vocab-sharded table would
    all-gather the whole embedding every step on a real pod)."""
    import optax
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.parallel import (MeshConfig, make_mesh, set_mesh,
                                       make_shardings, match_partition_rules)
    from fengshen_tpu.parallel.partition import shard_batch_spec

    mesh = make_mesh(MeshConfig(data=1, fsdp=2, sequence=2, tensor=2))
    set_mesh(mesh)
    try:
        config = LlamaConfig(
            vocab_size=512, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            max_position_embeddings=64, dtype="float32")
        model = LlamaForCausalLM(config)
        ids = jnp.zeros((4, 32), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), ids[:, :8])["params"]
        shardings = make_shardings(
            match_partition_rules(model.partition_rules(), params),
            params, mesh)
        params = jax.device_put(params, shardings)
        batch_sharding = make_shardings(
            shard_batch_spec(2, sequence_axis=1), ids, mesh)
        ids = jax.device_put(ids, batch_sharding)
        tx = optax.adamw(1e-4)
        opt_state = tx.init(params)

        def train_step(params, opt_state, input_ids):
            def loss_fn(p):
                logits = model.apply({"params": p}, input_ids)
                tgt = jnp.roll(input_ids, -1, axis=1)
                loss, _ = stable_cross_entropy(
                    logits[:, :-1].astype(jnp.float32), tgt[:, :-1])
                return loss
            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        capfd.readouterr()  # drain anything emitted before compile
        compiled = jax.jit(train_step).lower(params, opt_state, ids).compile()
        err = capfd.readouterr().err
        assert "Involuntary full rematerialization" not in err, err
        _, _, loss = compiled(params, opt_state, ids)
        assert np.isfinite(float(loss))
    finally:
        set_mesh(None)


def test_embed_lookup_oob_ids_zero_both_paths(mesh8):
    """Out-of-range/negative ids embed to the zero vector on BOTH the take
    and one-hot paths (reference semantics: an id outside every rank's
    vocab slice psums to zero, mpu/layers.py:106-129) — so single-device
    and pod runs agree."""
    from fengshen_tpu.ops.embedding import embed_lookup
    from fengshen_tpu.parallel import set_mesh, get_mesh

    rng = np.random.RandomState(1)
    table = jnp.asarray(rng.randn(64, 8), jnp.float32)
    ids = jnp.asarray([[0, 63, 64, 100, -1, -100]], jnp.int32)
    sharded = np.asarray(embed_lookup(table, ids))
    mesh = get_mesh()
    set_mesh(None)
    try:
        unsharded = np.asarray(embed_lookup(table, ids))
    finally:
        set_mesh(mesh)
    np.testing.assert_allclose(sharded, unsharded, atol=1e-6)
    assert (sharded[0, 2:] == 0).all()
    np.testing.assert_allclose(sharded[0, 1], np.asarray(table)[63],
                               atol=1e-6)


# -- multi-host DP-rank property tests (VERDICT r4 weak #5) ---------------

def _rank_table(proc_ids, di=0, fi=1):
    """(rank per pid, world) for a synthetic device→process layout."""
    from fengshen_tpu.parallel.mesh import (_dp_rank_world_from_groups,
                                            _host_batch_groups)
    groups = _host_batch_groups(np.asarray(proc_ids), di, fi)
    table = {pid: _dp_rank_world_from_groups(groups, pid)
             for pid in groups}
    worlds = {w for _, w in table.values()}
    assert len(worlds) == 1  # every host agrees on the world size
    return {pid: r for pid, (r, _) in table.items()}, worlds.pop()


def _assert_invariants(proc_ids, di=0, fi=1):
    """The three invariants of host-level data sharding: hosts in one
    replica group share a rank, ranks are dense 0..world-1, and the
    ranks' coordinate sets partition the global batch."""
    from fengshen_tpu.parallel.mesh import _host_batch_groups

    proc_ids = np.asarray(proc_ids)
    ranks, world = _rank_table(proc_ids, di, fi)
    groups = _host_batch_groups(proc_ids, di, fi)
    # same coord set ⇒ same rank; ranks dense
    by_rank: dict = {}
    for pid, r in ranks.items():
        by_rank.setdefault(r, []).append(frozenset(groups[pid]))
    assert sorted(by_rank) == list(range(world))
    for sets in by_rank.values():
        assert len(set(sets)) == 1
    # the distinct sets partition the flattened (data, fsdp) coords
    all_coords = sorted(c for sets in by_rank.values() for c in sets[0])
    n_batch = proc_ids.shape[di] * proc_ids.shape[fi]
    assert all_coords == list(range(n_batch))
    return ranks, world


def test_dp_rank_canonical_layout():
    """4 hosts × 2 devices, data axis split across hosts."""
    # data=8, fsdp=1 → host h owns coords {2h, 2h+1}
    proc_ids = np.arange(8).reshape(8, 1) // 2
    ranks, world = _assert_invariants(proc_ids)
    assert world == 4
    assert [ranks[p] for p in range(4)] == [0, 1, 2, 3]


def test_dp_rank_model_axis_spans_hosts():
    """A model axis spanning hosts: two hosts whose devices cover the
    SAME batch coordinates are one replica group and share a rank."""
    # batch dims (data=2, fsdp=1) × model dim folded into the device
    # list: hosts 0,1 split coord 0's model shards; hosts 2,3 coord 1's
    from fengshen_tpu.parallel.mesh import _dp_rank_world_from_groups
    groups = {0: {0}, 1: {0}, 2: {1}, 3: {1}}
    table = {pid: _dp_rank_world_from_groups(groups, pid)
             for pid in groups}
    assert table[0] == table[1] == (0, 2)
    assert table[2] == table[3] == (1, 2)


def test_dp_rank_reversed_process_order():
    """Reversed device→process assignment must still give dense ranks
    ordered by coordinate, not by process id."""
    proc_ids = (3 - np.arange(8).reshape(8, 1) // 2)
    ranks, world = _assert_invariants(proc_ids)
    assert world == 4
    # host 3 holds the LOWEST coords → rank 0
    assert [ranks[p] for p in (3, 2, 1, 0)] == [0, 1, 2, 3]


def test_dp_rank_interleaved_layout():
    """Interleaved (non-contiguous) coordinate coverage: the old
    contiguous-range shortcut would mis-rank this; the group-set math
    must not."""
    # host 0 covers coords {0, 2}, host 1 covers {1, 3}
    proc_ids = np.array([[0], [1], [0], [1]])
    ranks, world = _assert_invariants(proc_ids)
    assert world == 2
    assert ranks[0] == 0 and ranks[1] == 1


def test_dp_rank_partial_overlap_is_loud():
    """A layout where host groups partially overlap cannot be data-
    sharded at host level — it must raise, not silently mis-shard."""
    from fengshen_tpu.parallel.mesh import (_dp_rank_world_from_groups,
                                            _host_batch_groups)
    # host 0 covers {0,1}, host 1 covers {1,2}: ill-defined
    proc_ids = np.array([[0], [0], [1]])
    groups = _host_batch_groups(proc_ids, 0, 1)
    groups[1].add(1)  # inject the overlap
    with pytest.raises(ValueError, match="overlap"):
        _dp_rank_world_from_groups(groups, 0)
