"""Declarative logical-axis sharding subsystem (fengshen_tpu/sharding/,
docs/sharding.md).

The load-bearing contracts:

- the vocabulary + rules-table validators reject typos loudly (the
  runtime mirror of fslint's ``partition-spec-axes`` checks);
- ``resolve_spec`` / ``to_partition_rules`` produce the exact
  PartitionSpecs the hand-written per-model tables used to declare
  (the migration-equivalence pins below — regressing one silently
  changes how a fleet shards);
- ``use_rules`` scopes an alternative table without leaking across the
  default, and ``rules_fingerprint`` keys the AOT cache so programs
  compiled under different tables can never cross-hit (the
  coexistence test);
- the rule-driven parity matrix: llama, transfo_xl, sd_unet and clip
  run SHARDED on the virtual 8-device mesh numerically equal to
  replicated — including the two towers whose divergences this
  subsystem root-caused (the concat-contraction mispartition,
  docs/sharding.md "Root cause");
- llama greedy decode is token-identical sharded vs replicated after
  the migration.
"""

import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from fengshen_tpu.sharding import (DEFAULT_LOGICAL_AXIS_RULES,
                                   LOGICAL_AXES, LOGICAL_AXIS_SET,
                                   get_rules, resolve_spec,
                                   rules_fingerprint, set_rules,
                                   to_partition_rules, use_rules,
                                   validate_rules)


# ---- vocabulary + validation -------------------------------------------

def test_vocabulary_is_flat_and_frozen():
    assert isinstance(LOGICAL_AXES, tuple)
    assert all(isinstance(a, str) for a in LOGICAL_AXES)
    assert LOGICAL_AXIS_SET == frozenset(LOGICAL_AXES)
    assert len(set(LOGICAL_AXES)) == len(LOGICAL_AXES)
    # the default table maps every role exactly once
    assert {k for k, _ in DEFAULT_LOGICAL_AXIS_RULES} == LOGICAL_AXIS_SET


def test_validate_rules_rejects_malformed_tables():
    validate_rules(DEFAULT_LOGICAL_AXIS_RULES)  # must not raise
    with pytest.raises(ValueError, match="unknown logical axis"):
        validate_rules((("head", "tensor"),))
    with pytest.raises(ValueError, match="unknown mesh axis"):
        validate_rules((("heads", "tenosr"),))
    with pytest.raises(ValueError, match="unknown mesh axis"):
        validate_rules((("batch", ("data", "fsp")),))
    with pytest.raises(ValueError, match="mapped twice"):
        validate_rules((("heads", "tensor"), ("heads", None)))
    with pytest.raises(ValueError, match="not a"):
        validate_rules((("heads",),))


def test_resolve_spec_default_table():
    assert resolve_spec(("embed", "heads")) == P("fsdp", "tensor")
    assert resolve_spec(("batch", "seq", "mlp")) == \
        P(("data", "fsdp"), "sequence", "tensor")
    # None entries and deliberately-unsharded roles stay replicated
    assert resolve_spec((None, "relpos")) == P(None, None)
    assert resolve_spec(("norm",)) == P(None)
    assert resolve_spec(()) == P(None)
    with pytest.raises(ValueError, match="unknown logical axis"):
        resolve_spec(("head",))


def test_use_rules_scoping_and_set_rules():
    custom = tuple((k, None) if k == "mlp" else (k, v)
                   for k, v in DEFAULT_LOGICAL_AXIS_RULES)
    assert resolve_spec(("embed", "mlp")) == P("fsdp", "tensor")
    with use_rules(custom):
        assert get_rules() == custom
        assert resolve_spec(("embed", "mlp")) == P("fsdp", None)
        with use_rules(None):
            # nested scope back to the default
            assert resolve_spec(("embed", "mlp")) == P("fsdp", "tensor")
        assert resolve_spec(("embed", "mlp")) == P("fsdp", None)
    assert get_rules() == DEFAULT_LOGICAL_AXIS_RULES
    with pytest.raises(ValueError, match="unknown logical axis"):
        set_rules((("head", "tensor"),))
    assert get_rules() == DEFAULT_LOGICAL_AXIS_RULES


def test_rules_fingerprint_stable_and_order_insensitive():
    fp = rules_fingerprint()
    assert fp.startswith("lar1:") and len(fp) == len("lar1:") + 16
    assert fp == rules_fingerprint(DEFAULT_LOGICAL_AXIS_RULES)
    # order-insensitive: two spellings of the same mapping, one key
    assert rules_fingerprint(tuple(reversed(
        DEFAULT_LOGICAL_AXIS_RULES))) == fp
    # tuple-vs-list spelling of a multi-axis mapping, one key
    respelled = tuple((k, list(v)) if isinstance(v, tuple) else (k, v)
                      for k, v in DEFAULT_LOGICAL_AXIS_RULES)
    assert rules_fingerprint(respelled) == fp
    custom = tuple((k, None) if k == "mlp" else (k, v)
                   for k, v in DEFAULT_LOGICAL_AXIS_RULES)
    assert rules_fingerprint(custom) != fp
    with use_rules(custom):
        assert rules_fingerprint() == rules_fingerprint(custom)


# ---- migration-equivalence pins ----------------------------------------

def _first(rules, path):
    """First-match semantics, exactly like
    parallel.partition.match_partition_rules (re.search, order wins)."""
    for pattern, spec in rules:
        if re.search(pattern, path):
            return pattern, spec
    raise AssertionError(f"no rule matched {path!r}")


def test_llama_partition_rule_pins():
    """The specs the hand-written LLAMA_PARTITION_RULES table used to
    pin — the migration must not have changed a single one."""
    from fengshen_tpu.models.llama.modeling_llama import (
        PARTITION_RULES, SCAN_PARTITION_RULES)
    pins = {
        "model/embed_tokens/embedding": P("tensor", "fsdp"),
        "model/layers_0/self_attn/q_proj/kernel": P("fsdp", "tensor"),
        "model/layers_0/self_attn/o_proj/kernel": P("tensor", "fsdp"),
        "model/layers_0/mlp/gate_proj/kernel": P("fsdp", "tensor"),
        "model/layers_0/mlp/down_proj/kernel": P("tensor", "fsdp"),
        "model/layers_0/mlp/experts_gate": P("expert", None, "tensor"),
        "model/layers_0/input_layernorm/scale": P(None),
        "lm_head/kernel": P("fsdp", "tensor"),
    }
    for path, want in pins.items():
        assert _first(PARTITION_RULES, path)[1] == want, path
    scan_pins = {
        "model/layers/self_attn/q_proj/kernel":
            P(None, "fsdp", "tensor"),
        "model/layers/mlp/down_proj/kernel": P(None, "tensor", "fsdp"),
        "model/layers/mlp/experts_down": P(None, "expert", "tensor",
                                           None),
    }
    for path, want in scan_pins.items():
        assert _first(SCAN_PARTITION_RULES, path)[1] == want, path


def test_encoder_family_partition_rule_pins():
    from fengshen_tpu.models.bert.modeling_bert import (
        PARTITION_RULES as BERT)
    from fengshen_tpu.models.clip.modeling_taiyi_clip import (
        PARTITION_RULES as CLIP)
    from fengshen_tpu.models.t5.modeling_t5 import (
        PARTITION_RULES as T5)
    pins = [
        (BERT, "bert/embeddings/word_embeddings/embedding",
         P("tensor", None)),
        (BERT, "encoder/layer_0/attention/self/query/kernel",
         P("fsdp", "tensor")),
        (BERT, "encoder/layer_0/attention_output_dense/kernel",
         P("tensor", "fsdp")),
        (CLIP, "text_model/embeddings/word_embeddings/embedding",
         P("tensor", None)),
        (CLIP, "vision_model/layers_0/self_attn/q_proj/kernel",
         P("fsdp", "tensor")),
        (CLIP, "vision_model/layers_0/self_attn/out_proj/kernel",
         P("tensor", "fsdp")),
        (T5, "shared/embedding", P("tensor", "fsdp")),
        (T5, "encoder/block_0/layer_0/SelfAttention/o/kernel",
         P("tensor", "fsdp")),
        (T5, "lm_head/kernel", P("fsdp", "tensor")),
    ]
    for rules, path, want in pins:
        assert _first(rules, path)[1] == want, path


def test_t5_wo_rule_ordering_pin():
    """`re.search("o/kernel")` matches INSIDE "wo/kernel", so the
    feed-forward `wo` rule must sit before the attention `o` rule —
    this pin keeps the ordering load-bearing fact from regressing
    (the resolved specs coincide under the DEFAULT table, but a table
    sharding heads differently from mlp would miscategorize wo)."""
    from fengshen_tpu.models.t5.modeling_t5 import PARAM_LOGICAL_AXES
    pattern, axes = _first(PARAM_LOGICAL_AXES,
                           "block_0/layer_1/DenseReluDense/wo/kernel")
    assert pattern == r"wo/kernel" and tuple(axes) == ("mlp", "embed")


def test_gpt2_c_proj_rule_ordering_pin():
    """gpt2 reuses the name `c_proj` for the attention output AND the
    MLP output; the path-qualified attn rule must win for attention
    paths. Pinned under a table that shards heads and mlp differently
    so a regression cannot hide behind coinciding default specs."""
    from fengshen_tpu.models.gpt2.modeling_gpt2 import PARAM_LOGICAL_AXES
    custom = tuple((k, None) if k == "mlp" else (k, v)
                   for k, v in DEFAULT_LOGICAL_AXIS_RULES)
    rules = to_partition_rules(PARAM_LOGICAL_AXES, rules=custom)
    assert _first(rules, "h_0/attn/c_proj/kernel")[1] == \
        P("tensor", "fsdp")
    assert _first(rules, "h_0/mlp/c_proj/kernel")[1] == P(None, "fsdp")


def test_root_cause_tower_rule_pins():
    """The two root-caused towers (docs/sharding.md "Root cause"):
    transfo_xl's `relative` is column-parallel with a REPLICATED
    contraction dim (relpos), and the SD UNet convs shard only their
    output channels — both keep concat outputs away from sharded
    matmul contractions."""
    from fengshen_tpu.models.stable_diffusion.unet_sd import (
        SD_PARTITION_RULES)
    from fengshen_tpu.models.transfo_xl_denoise.modeling_transfo_xl \
        import XL_PARTITION_RULES
    assert _first(XL_PARTITION_RULES,
                  "layer_0/attention/relative/kernel")[1] == \
        P(None, "tensor")
    assert _first(XL_PARTITION_RULES,
                  "layer_0/attention/query_key_value/kernel")[1] == \
        P("fsdp", "tensor")
    assert _first(SD_PARTITION_RULES,
                  "down_blocks_0/resnets_0/conv1/kernel")[1] == \
        P(None, None, None, "fsdp")
    assert _first(
        SD_PARTITION_RULES,
        "down_blocks_0/attentions_0/transformer_blocks_0/attn2/"
        "to_q/kernel")[1] == P(None, "tensor")


# ---- rule-driven parity matrix (sharded == replicated) -----------------

def _parity(model, params, apply_fn, mesh, atol, shard_probe):
    """Shared harness: replicated reference vs the same program on
    params sharded through the model's (rule-driven) partition table."""
    from fengshen_tpu.parallel import make_shardings
    ref = apply_fn(params)
    shardings = make_shardings(model.partition_rules(), params, mesh)
    sharded = jax.device_put(params, shardings)
    probe = shard_probe(sharded)
    assert any(e is not None for e in probe.sharding.spec), \
        "the rules did not actually shard the probe kernel"
    out = jax.jit(apply_fn)(sharded)
    for r, o in zip(jax.tree_util.tree_leaves(ref),
                    jax.tree_util.tree_leaves(out)):
        np.testing.assert_allclose(np.asarray(o), np.asarray(r),
                                   atol=atol)


def test_parity_matrix_llama(mesh8):
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    cfg = LlamaConfig(vocab_size=128, hidden_size=32,
                      intermediate_size=64, num_hidden_layers=2,
                      num_attention_heads=4,
                      max_position_embeddings=32, dtype="float32")
    model = LlamaForCausalLM(cfg)
    ids = jnp.asarray(np.random.RandomState(0).randint(3, 127, (2, 8)))
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    _parity(model, params,
            lambda p: model.apply({"params": p}, ids), mesh8, 2e-5,
            lambda s: s["model"]["layers_0"]["self_attn"]["q_proj"][
                "kernel"])


def test_parity_matrix_transfo_xl(mesh8):
    from fengshen_tpu.models.transfo_xl_denoise.modeling_transfo_xl \
        import TransfoXLConfig, TransfoXLModel
    cfg = TransfoXLConfig.small_test_config()
    model = TransfoXLModel(cfg)
    ids = jnp.asarray(np.random.RandomState(1).randint(0, 127, (2, 8)))
    params = model.init(jax.random.PRNGKey(1), ids)["params"]
    _parity(model, params,
            lambda p: model.apply({"params": p}, ids)[0], mesh8, 2e-5,
            lambda s: s["layer_0"]["attention"]["query_key_value"][
                "kernel"])


def test_parity_matrix_sd_unet(mesh8):
    from fengshen_tpu.models.stable_diffusion.unet_sd import (
        SDUNetConfig, SDUNet2DConditionModel)
    cfg = SDUNetConfig.small_test_config(block_out_channels=(32, 64),
                                         cross_attention_dim=32)
    model = SDUNet2DConditionModel(cfg)
    rng = np.random.RandomState(2)
    lat = jnp.asarray(rng.randn(2, 8, 8, 4), jnp.float32)
    t = jnp.asarray([3, 411])
    ctx = jnp.asarray(rng.randn(2, 5, 32), jnp.float32)
    params = model.init(jax.random.PRNGKey(2), lat, t, ctx)["params"]
    _parity(model, params,
            lambda p: model.apply({"params": p}, lat, t, ctx), mesh8,
            2e-4,
            lambda s: s["down_blocks_0"]["attentions_0"][
                "transformer_blocks_0"]["attn2"]["to_q"]["kernel"])


def test_parity_matrix_clip(mesh8):
    from fengshen_tpu.models.bert import BertConfig
    from fengshen_tpu.models.clip.modeling_taiyi_clip import (
        CLIPVisionConfig, TaiyiCLIPModel)
    text_cfg = BertConfig(vocab_size=128, hidden_size=32,
                          num_hidden_layers=2, num_attention_heads=2,
                          intermediate_size=64,
                          max_position_embeddings=64, dtype="float32")
    model = TaiyiCLIPModel(text_cfg, CLIPVisionConfig.small_test_config())
    rng = np.random.RandomState(3)
    ids = jnp.asarray(rng.randint(3, 127, (2, 8)))
    pixels = jnp.asarray(rng.randn(2, 32, 32, 3), jnp.float32)
    params = model.init(jax.random.PRNGKey(3), ids, pixels)["params"]
    _parity(model, params,
            lambda p: model.apply({"params": p}, ids, pixels), mesh8,
            2e-5,
            lambda s: s["text_model"]["layer_0"]["query"]["kernel"])


def test_llama_greedy_decode_token_identity_sharded(mesh8):
    """The end-to-end acceptance pin: greedy decode over sharded params
    emits the exact token sequence the replicated model does."""
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.parallel import make_shardings
    from fengshen_tpu.utils.generate import generate
    cfg = LlamaConfig(vocab_size=128, hidden_size=32,
                      intermediate_size=64, num_hidden_layers=2,
                      num_attention_heads=4,
                      max_position_embeddings=48, dtype="float32")
    model = LlamaForCausalLM(cfg)
    ids = jnp.asarray(np.random.RandomState(4).randint(3, 127, (2, 8)))
    params = model.init(jax.random.PRNGKey(4), ids)["params"]
    ref = np.asarray(generate(model, params, ids, max_new_tokens=12,
                              eos_token_id=None, pad_token_id=0))
    shardings = make_shardings(model.partition_rules(), params, mesh8)
    sharded = jax.device_put(params, shardings)
    out = np.asarray(generate(model, sharded, ids, max_new_tokens=12,
                              eos_token_id=None, pad_token_id=0))
    np.testing.assert_array_equal(out, ref)


# ---- AOT-key coexistence ------------------------------------------------

class _FpCapture:
    """Stands in for AotSetup: records the fingerprint_extra each wrap
    site bakes into its cache key."""

    def __init__(self):
        self.fps = {}

    def wrap(self, fn, name, fingerprint_extra=None, donate_argnums=()):
        self.fps[name] = fingerprint_extra
        return jax.jit(fn, donate_argnums=donate_argnums)


def test_engine_aot_key_separates_rules_tables():
    """Two deployments of the SAME model under different rules tables
    must produce different AOT cache keys — the executables bake
    different collectives, so a cross-hit would be wrong-program replay
    (docs/aot_cache.md)."""
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.serving import (ContinuousBatchingEngine,
                                      EngineConfig)
    cfg = LlamaConfig(vocab_size=97, hidden_size=32,
                      intermediate_size=64, num_hidden_layers=2,
                      num_attention_heads=4,
                      max_position_embeddings=64, dtype="float32")
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    ecfg = dict(num_slots=2, buckets=(8, 16), max_new_tokens=8,
                max_queue=4)

    default_aot = _FpCapture()
    ContinuousBatchingEngine(model, params, EngineConfig(**ecfg),
                             aot=default_aot)
    custom = tuple((k, None) if k == "mlp" else (k, v)
                   for k, v in DEFAULT_LOGICAL_AXIS_RULES)
    custom_aot = _FpCapture()
    with use_rules(custom):
        ContinuousBatchingEngine(model, params, EngineConfig(**ecfg),
                                 aot=custom_aot)

    assert set(default_aot.fps) == {"serving/prefill", "serving/assign",
                                    "serving/decode"}
    for name, fp in default_aot.fps.items():
        assert rules_fingerprint(DEFAULT_LOGICAL_AXIS_RULES) in fp
        assert rules_fingerprint(custom) in custom_aot.fps[name]
        assert fp != custom_aot.fps[name]


def test_trainer_key_extra_carries_non_default_rules(tmp_path):
    """The trainer's AOT key gains the rules fingerprint ONLY for
    non-default tables (the level-none precedent: existing caches keyed
    without it must keep hitting)."""
    from fengshen_tpu.trainer.trainer import Trainer

    captured = []

    class _Setup:
        def wrap(self, fn, name, key_extra=None, **kw):
            captured.append((name, key_extra))
            return fn

    class _Args:
        aot_cache_dir = str(tmp_path)

    tr = Trainer.__new__(Trainer)
    tr.args = _Args()
    tr._aot_setup = _Setup()
    tr._offload_policy = None
    tr._maybe_aot_wrap(lambda x: x, "t/step")
    custom = tuple((k, None) if k == "mlp" else (k, v)
                   for k, v in DEFAULT_LOGICAL_AXIS_RULES)
    with use_rules(custom):
        tr._maybe_aot_wrap(lambda x: x, "t/step")

    (_, default_extra), (_, custom_extra) = captured
    assert not default_extra
    assert custom_extra and rules_fingerprint(custom) in custom_extra
