"""Generation control kwargs vs HF torch `generate` (VERDICT r2 item 7):
repetition_penalty, no_repeat_ngram_size, min_length on gpt2
(decoder-only path) and bart (seq2seq cached + beam paths).
"""

import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # full-fit/e2e lane: run with -m slow or no -m filter

torch = pytest.importorskip("torch")
import transformers  # noqa: E402

from fengshen_tpu.models.bart import (BartConfig,  # noqa: E402
                                      BartForConditionalGeneration)
from fengshen_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel  # noqa


@pytest.fixture(scope="module")
def gpt2_pair():
    from fengshen_tpu.models.gpt2.convert import torch_to_params
    hf_cfg = transformers.GPT2Config(
        vocab_size=128, n_positions=64, n_embd=32, n_layer=2, n_head=4,
        attn_implementation="eager")
    torch.manual_seed(0)
    tm = transformers.GPT2LMHeadModel(hf_cfg).eval()
    cfg = GPT2Config(vocab_size=128, n_positions=64, n_embd=32, n_layer=2,
                     n_head=4, dtype="float32")
    return torch_to_params(tm.state_dict(), cfg), tm, cfg


@pytest.fixture(scope="module")
def bart_pair():
    from fengshen_tpu.models.bart.convert import torch_to_params
    hf_cfg = transformers.BartConfig(
        vocab_size=128, d_model=32, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=64, decoder_ffn_dim=64,
        max_position_embeddings=64, attn_implementation="eager",
        decoder_start_token_id=2, eos_token_id=2, pad_token_id=1,
        bos_token_id=0, forced_bos_token_id=None, forced_eos_token_id=None)
    torch.manual_seed(1)
    tm = transformers.BartForConditionalGeneration(hf_cfg).eval()
    cfg = BartConfig(vocab_size=128, d_model=32, encoder_layers=2,
                     decoder_layers=2, encoder_attention_heads=4,
                     decoder_attention_heads=4, encoder_ffn_dim=64,
                     decoder_ffn_dim=64, max_position_embeddings=64,
                     dtype="float32")
    return torch_to_params(tm.state_dict(), cfg), tm, cfg


@pytest.mark.parametrize("kwargs", [
    {"repetition_penalty": 1.5},
    {"no_repeat_ngram_size": 2},
    {"min_length": 12},
    {"repetition_penalty": 1.3, "no_repeat_ngram_size": 3,
     "min_length": 10},
])
def test_gpt2_greedy_controls_match_hf(gpt2_pair, kwargs):
    from fengshen_tpu.utils.generate import generate
    params, tm, cfg = gpt2_pair
    prompt = np.array([[5, 11, 42, 7]], dtype=np.int64)
    hf_kwargs = dict(kwargs)
    if "min_length" not in hf_kwargs:
        # HF's GenerationConfig default min_length=0
        hf_kwargs["min_length"] = 0
    with torch.no_grad():
        ref = tm.generate(torch.tensor(prompt), max_new_tokens=10,
                          do_sample=False, pad_token_id=0,
                          eos_token_id=99, **hf_kwargs).numpy()
    out = generate(GPT2LMHeadModel(cfg), params,
                   jnp.asarray(prompt, jnp.int32), max_new_tokens=10,
                   eos_token_id=99, pad_token_id=0, **kwargs)
    np.testing.assert_array_equal(np.asarray(out)[0, :ref.shape[1]],
                                  ref[0])


@pytest.mark.parametrize("kwargs", [
    {"repetition_penalty": 1.5},
    {"no_repeat_ngram_size": 2},
    {"min_length": 10},
])
def test_bart_greedy_controls_match_hf(bart_pair, kwargs):
    from fengshen_tpu.utils.generate import seq2seq_generate
    params, tm, cfg = bart_pair
    enc_ids = np.array([[0, 17, 9, 42, 33, 2]], dtype=np.int64)
    hf_kwargs = {"min_length": 0} | kwargs
    with torch.no_grad():
        ref = tm.generate(torch.tensor(enc_ids), max_new_tokens=12,
                          do_sample=False, num_beams=1,
                          **hf_kwargs).numpy()
    out = seq2seq_generate(
        BartForConditionalGeneration(cfg), params,
        jnp.asarray(enc_ids, jnp.int32), max_new_tokens=12,
        decoder_start_token_id=2, eos_token_id=2, pad_token_id=1,
        **kwargs)
    n = min(ref.shape[1], np.asarray(out).shape[1])
    np.testing.assert_array_equal(np.asarray(out)[0, :n], ref[0, :n])


def test_bart_beam_controls_match_hf(bart_pair):
    from fengshen_tpu.utils.generate import seq2seq_generate
    params, tm, cfg = bart_pair
    enc_ids = np.array([[0, 9, 17, 42, 2]], dtype=np.int64)
    kwargs = dict(no_repeat_ngram_size=2, repetition_penalty=1.2,
                  min_length=8)
    with torch.no_grad():
        ref = tm.generate(torch.tensor(enc_ids), max_new_tokens=10,
                          num_beams=3, length_penalty=1.0,
                          early_stopping=True, **kwargs).numpy()
    out = seq2seq_generate(
        BartForConditionalGeneration(cfg), params,
        jnp.asarray(enc_ids, jnp.int32), max_new_tokens=10,
        decoder_start_token_id=2, eos_token_id=2, pad_token_id=1,
        num_beams=3, length_penalty=1.0, **kwargs)
    n = min(ref.shape[1], np.asarray(out).shape[1])
    np.testing.assert_array_equal(np.asarray(out)[0, :n], ref[0, :n])


def test_controls_leftpad_history_mask(gpt2_pair):
    """Left padding must not leak pad tokens into the repetition
    penalty's seen-set: a left-padded prompt and the same prompt unpadded
    generate the same continuation."""
    from fengshen_tpu.utils.generate import generate
    params, _, cfg = gpt2_pair
    model = GPT2LMHeadModel(cfg)
    prompt = np.array([[5, 11, 42, 7]], dtype=np.int32)
    padded = np.array([[0, 0, 5, 11, 42, 7]], dtype=np.int32)
    mask = np.array([[0, 0, 1, 1, 1, 1]], dtype=np.int32)
    kwargs = dict(max_new_tokens=8, repetition_penalty=2.0,
                  no_repeat_ngram_size=2, pad_token_id=1)
    out_a = np.asarray(generate(model, params, jnp.asarray(prompt),
                                **kwargs))[0, 4:]
    out_b = np.asarray(generate(model, params, jnp.asarray(padded),
                                attention_mask=jnp.asarray(mask),
                                **kwargs))[0, 6:]
    np.testing.assert_array_equal(out_a, out_b)


@pytest.mark.parametrize("kwargs", [
    {"repetition_penalty": 1.5},
    {"no_repeat_ngram_size": 2},
    {"min_length": 10},
])
def test_bart_buffer_path_controls_match_cached(bart_pair, kwargs,
                                                monkeypatch):
    """The non-cached buffer fallback (models without KV-cache support or
    overflowing decode_cache_length) must produce the same controlled
    greedy output as the cached path."""
    import importlib
    G = importlib.import_module("fengshen_tpu.utils.generate")
    params, _, cfg = bart_pair
    model = BartForConditionalGeneration(cfg)
    enc_ids = np.array([[0, 17, 9, 42, 33, 2]], dtype=np.int32)
    common = dict(max_new_tokens=12, decoder_start_token_id=2,
                  eos_token_id=2, pad_token_id=1, **kwargs)
    cached = np.asarray(G.seq2seq_generate(
        model, params, jnp.asarray(enc_ids), **common))
    monkeypatch.setattr(G, "_seq2seq_supports_cache", lambda m: False)
    buffered = np.asarray(G.seq2seq_generate(
        model, params, jnp.asarray(enc_ids), **common))
    np.testing.assert_array_equal(cached, buffered)


def test_bart_beam_buffer_path_controls_match_cached(bart_pair,
                                                     monkeypatch):
    import importlib
    G = importlib.import_module("fengshen_tpu.utils.generate")
    params, _, cfg = bart_pair
    model = BartForConditionalGeneration(cfg)
    enc_ids = np.array([[0, 9, 17, 42, 2]], dtype=np.int32)
    common = dict(max_new_tokens=10, decoder_start_token_id=2,
                  eos_token_id=2, pad_token_id=1, num_beams=3,
                  no_repeat_ngram_size=2, repetition_penalty=1.2,
                  min_length=8)
    cached = np.asarray(G.seq2seq_generate(
        model, params, jnp.asarray(enc_ids), **common))
    monkeypatch.setattr(G, "_seq2seq_supports_cache", lambda m: False)
    buffered = np.asarray(G.seq2seq_generate(
        model, params, jnp.asarray(enc_ids), **common))
    np.testing.assert_array_equal(cached, buffered)


def test_ngram_size_one_bans_all_seen_tokens(gpt2_pair):
    """HF semantics at no_repeat_ngram_size=1: no token may ever repeat."""
    from fengshen_tpu.utils.generate import generate
    params, tm, cfg = gpt2_pair
    prompt = np.array([[5, 11, 42, 7]], dtype=np.int64)
    with torch.no_grad():
        ref = tm.generate(torch.tensor(prompt), max_new_tokens=10,
                          do_sample=False, pad_token_id=0,
                          no_repeat_ngram_size=1, min_length=0).numpy()
    out = generate(GPT2LMHeadModel(cfg), params,
                   jnp.asarray(prompt, jnp.int32), max_new_tokens=10,
                   pad_token_id=0, no_repeat_ngram_size=1)
    np.testing.assert_array_equal(np.asarray(out)[0], ref[0])
    assert len(set(np.asarray(out)[0].tolist())) == out.shape[1]
