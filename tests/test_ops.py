"""Numeric tests for the ops tier against plain-jnp references.

Mirrors the role of the reference's kernel test
(reference: fengshen/models/megatron/fused_kernels/tests/test_fused_kernels.py
— fused kernel vs torch softmax elementwise closeness), but runs on the CPU
XLA backend so it is CI-able.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fengshen_tpu.ops import (
    dot_product_attention, causal_mask, sliding_window_mask, bigbird_mask,
    make_attention_bias, rotary_cos_sin, apply_rotary_pos_emb, alibi_slopes,
    alibi_bias, get_activation, RMSNorm, LayerNorm, get_norm,
)
from fengshen_tpu.ops.flash_attention import blockwise_attention


def _ref_attention(q, k, v, bias=None):
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / jnp.sqrt(d)
    if bias is not None:
        s = s + bias
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))


@pytest.fixture
def qkv():
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(2, 16, 4, 8), jnp.float32)
    k = jnp.asarray(rng.randn(2, 16, 4, 8), jnp.float32)
    v = jnp.asarray(rng.randn(2, 16, 4, 8), jnp.float32)
    return q, k, v


def test_dense_attention_matches_reference(qkv):
    q, k, v = qkv
    out = dot_product_attention(q, k, v)
    np.testing.assert_allclose(out, _ref_attention(q, k, v), atol=1e-5)


def test_dense_attention_causal(qkv):
    q, k, v = qkv
    mask = causal_mask(16)[None, None]
    out = dot_product_attention(q, k, v, mask=mask)
    ref = _ref_attention(q, k, v, bias=make_attention_bias(mask))
    np.testing.assert_allclose(out, ref, atol=1e-5)
    # causality: output at position t must not depend on k/v after t
    v2 = v.at[:, -1].set(99.0)
    out2 = dot_product_attention(q, k, v2, mask=mask)
    np.testing.assert_allclose(out[:, :-1], out2[:, :-1], atol=1e-5)


def test_blockwise_attention_matches_dense(qkv):
    q, k, v = qkv
    ref = _ref_attention(q, k, v)
    out = blockwise_attention(q, k, v, block_size=4)
    np.testing.assert_allclose(out, ref, atol=1e-4)


def test_blockwise_attention_with_bias_and_ragged_block(qkv):
    q, k, v = qkv
    bias = make_attention_bias(causal_mask(16)[None, None])
    ref = _ref_attention(q, k, v, bias)
    out = blockwise_attention(q, k, v, bias=bias, block_size=5)
    np.testing.assert_allclose(out, ref, atol=1e-4)


def test_rotary_norm_preserving():
    q = jnp.ones((1, 8, 2, 16))
    k = jnp.ones((1, 8, 2, 16))
    pos = jnp.arange(8)[None]
    q2, k2 = apply_rotary_pos_emb(q, k, pos)
    np.testing.assert_allclose(
        jnp.linalg.norm(q2, axis=-1), jnp.linalg.norm(q, axis=-1), atol=1e-5)
    # position 0 is identity
    np.testing.assert_allclose(q2[:, 0], q[:, 0], atol=1e-6)


def test_rotary_partial():
    q = jnp.asarray(np.random.RandomState(1).randn(1, 4, 2, 16), jnp.float32)
    pos = jnp.arange(4)[None]
    q2, _ = apply_rotary_pos_emb(q, q, pos, rotary_dim=8)
    # pass-through dims untouched (reference: transformer.py:240-257)
    np.testing.assert_allclose(q2[..., 8:], q[..., 8:], atol=1e-6)


def test_rotary_relative_property():
    # attention score q_i . k_j after rope depends only on i-j
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(1, 10, 1, 16), jnp.float32)
    k = jnp.asarray(rng.randn(1, 10, 1, 16), jnp.float32)
    qa = jnp.tile(q[:, :1], (1, 10, 1, 1))
    ka = jnp.tile(k[:, :1], (1, 10, 1, 1))
    pos = jnp.arange(10)[None]
    q2, k2 = apply_rotary_pos_emb(qa, ka, pos)
    s = jnp.einsum("bqhd,bkhd->bqk", q2, k2)[0]
    for off in range(1, 5):
        np.testing.assert_allclose(s[0, off], s[3, 3 + off], atol=1e-4)


def test_alibi_slopes_pow2():
    s = alibi_slopes(8)
    assert s.shape == (8,)
    np.testing.assert_allclose(s[0], 2 ** -1.0, atol=1e-6)
    b = alibi_bias(8, 4, 4)
    assert b.shape == (8, 4, 4)
    np.testing.assert_allclose(np.diagonal(b, axis1=1, axis2=2), 0.0)


def test_alibi_slopes_non_pow2():
    s = alibi_slopes(12)
    assert s.shape == (12,)
    assert np.all(np.asarray(s) > 0)


def test_masks_shapes():
    m = sliding_window_mask(8, 3)
    assert bool(m[5, 3]) and bool(m[5, 5]) and not bool(m[5, 2]) \
        and not bool(m[5, 6])
    bb = bigbird_mask(16, 4, num_random_blocks=1, num_global_blocks=1,
                      num_window_blocks=3)
    assert bb.shape == (16, 16)
    assert bool(bb[0, 15])  # global row


def test_activations():
    x = jnp.linspace(-2, 2, 8)
    for name in ["gelu", "relu", "silu", "mish", "softsign", "swish"]:
        y = get_activation(name)(x)
        assert y.shape == x.shape
    g = get_activation("geglu")(jnp.ones((2, 8)))
    assert g.shape == (2, 4)


def test_rmsnorm_matches_formula():
    x = jnp.asarray(np.random.RandomState(3).randn(2, 4, 8), jnp.float32)
    mod = RMSNorm(epsilon=1e-6)
    params = mod.init(jax.random.PRNGKey(0), x)
    y = mod.apply(params, x)
    ref = x / np.sqrt((np.asarray(x) ** 2).mean(-1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(y, ref, atol=1e-5)


def test_layernorm_bf16_stats_fp32():
    x = (jnp.asarray(np.random.RandomState(4).randn(2, 8), jnp.float32) * 100
         ).astype(jnp.bfloat16)
    mod = LayerNorm()
    params = mod.init(jax.random.PRNGKey(0), x)
    y = mod.apply(params, x)
    assert y.dtype == jnp.bfloat16
    assert np.isfinite(np.asarray(y, dtype=np.float32)).all()


def test_get_norm_dispatch():
    assert isinstance(get_norm("rmsnorm"), RMSNorm)
    assert isinstance(get_norm("layernorm"), LayerNorm)
    with pytest.raises(ValueError):
        get_norm("nope")


def test_blockwise_attention_causal_param(qkv):
    q, k, v = qkv
    bias = make_attention_bias(causal_mask(16)[None, None])
    ref = _ref_attention(q, k, v, bias)
    out = blockwise_attention(q, k, v, causal=True, block_size=4)
    np.testing.assert_allclose(out, ref, atol=1e-4)


def test_blockwise_attention_decode_alignment():
    # Sq < Sk: queries are the suffix of the keys (KV-cache decode shape)
    rng = np.random.RandomState(7)
    q = jnp.asarray(rng.randn(1, 2, 2, 8), jnp.float32)
    k = jnp.asarray(rng.randn(1, 10, 2, 8), jnp.float32)
    v = jnp.asarray(rng.randn(1, 10, 2, 8), jnp.float32)
    ref = _ref_attention(q, k, v, make_attention_bias(
        causal_mask(2, 10)[None, None]))
    out = blockwise_attention(q, k, v, causal=True, block_size=4)
    np.testing.assert_allclose(out, ref, atol=1e-4)


def test_pallas_flash_interpret_matches_dense(qkv):
    from fengshen_tpu.ops.pallas.flash_attention import pallas_flash_attention
    q, k, v = qkv
    ref = _ref_attention(q, k, v)
    out = pallas_flash_attention(q, k, v, None, None, False, 8, 8, True)
    np.testing.assert_allclose(out, ref, atol=1e-4)
    refc = _ref_attention(q, k, v, make_attention_bias(
        causal_mask(16)[None, None]))
    outc = pallas_flash_attention(q, k, v, None, None, True, 8, 8, True)
    np.testing.assert_allclose(outc, refc, atol=1e-4)


def test_pallas_flash_segment_ids_interpret(qkv):
    """Padded batch as segment ids == dense with a padding mask (on the
    valid rows)."""
    from fengshen_tpu.ops.pallas.flash_attention import pallas_flash_attention
    q, k, v = qkv
    batch, seq = q.shape[0], q.shape[1]
    n_valid = 10
    seg = jnp.asarray(
        np.repeat([[1] * n_valid + [0] * (seq - n_valid)], batch, 0),
        jnp.int32)
    mask = (seg[:, None, None, :] > 0) & causal_mask(seq)[None, None]
    ref = _ref_attention(q, k, v, make_attention_bias(mask))
    out = pallas_flash_attention(q, k, v, seg, seg, True, 8, 8, True)
    np.testing.assert_allclose(np.asarray(out)[:, :n_valid],
                               np.asarray(ref)[:, :n_valid], atol=1e-4)


def test_pallas_flash_fused_backward_matches_xla(qkv):
    """The fused Pallas bwd kernels (dq/dk/dv) must match XLA autodiff of
    the blockwise implementation."""
    from fengshen_tpu.ops.pallas.flash_attention import pallas_flash_attention
    q, k, v = qkv

    def f_pallas(q, k, v):
        return (pallas_flash_attention(
            q, k, v, None, None, True, 8, 8, True) ** 2).sum()

    def f_ref(q, k, v):
        return (blockwise_attention(q, k, v, causal=True,
                                    block_size=8) ** 2).sum()

    gp = jax.grad(f_pallas, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-3)


def test_pallas_flash_fused_backward_segments(qkv):
    """Fused bwd with segment ids matches autodiff of masked dense."""
    from fengshen_tpu.ops.pallas.flash_attention import pallas_flash_attention
    q, k, v = qkv
    batch, seq = q.shape[0], q.shape[1]
    seg = jnp.asarray(
        np.repeat([[1] * 12 + [0] * (seq - 12)], batch, 0), jnp.int32)

    def f_pallas(q, k, v):
        out = pallas_flash_attention(q, k, v, seg, seg, True, 8, 8, True)
        return (out ** 2 * (seg > 0)[:, :, None, None]).sum()

    def f_ref(q, k, v):
        mask = ((seg[:, None, None, :] == seg[:, None, :, None]) &
                causal_mask(seq)[None, None])
        out = _ref_attention(q, k, v, make_attention_bias(mask))
        return (out ** 2 * (seg > 0)[:, :, None, None]).sum()

    gp = jax.grad(f_pallas, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-3)


def test_blockwise_attention_segment_ids(qkv):
    q, k, v = qkv
    batch, seq = q.shape[0], q.shape[1]
    seg = jnp.asarray(
        np.repeat([[1] * 9 + [2] * (seq - 9)], batch, 0), jnp.int32)
    mask = ((seg[:, None, None, :] == seg[:, None, :, None]) &
            causal_mask(seq)[None, None])
    ref = _ref_attention(q, k, v, make_attention_bias(mask))
    out = blockwise_attention(q, k, v, causal=True, block_size=4,
                              q_segment_ids=seg, kv_segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


def test_attention_ring_impl_no_mesh_falls_back(qkv):
    from fengshen_tpu.parallel import set_mesh
    set_mesh(None)
    q, k, v = qkv
    out = dot_product_attention(q, k, v, impl="ring")
    ref = _ref_attention(q, k, v, make_attention_bias(
        causal_mask(16)[None, None]))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


def test_pallas_flash_decode_alignment_interpret():
    # q_len < k_len must use right-aligned (decode) causal convention,
    # matching blockwise_attention
    from fengshen_tpu.ops.pallas.flash_attention import pallas_flash_attention
    rng = np.random.RandomState(9)
    q = jnp.asarray(rng.randn(1, 8, 2, 8), jnp.float32)
    k = jnp.asarray(rng.randn(1, 16, 2, 8), jnp.float32)
    v = jnp.asarray(rng.randn(1, 16, 2, 8), jnp.float32)
    ref = blockwise_attention(q, k, v, causal=True, block_size=8)
    out = pallas_flash_attention(q, k, v, None, None, True, 8, 8, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


def test_gelu_exact_vs_tanh():
    x = jnp.linspace(-3, 3, 64)
    exact = get_activation("gelu")(x)
    import scipy.special as sp
    ref = np.asarray(x) * 0.5 * (1 + sp.erf(np.asarray(x) / np.sqrt(2)))
    np.testing.assert_allclose(np.asarray(exact), ref, atol=1e-6)
    approx = get_activation("gelu_new")(x)
    assert float(jnp.abs(exact - approx).max()) > 1e-5


def test_pallas_flash_gqa_interpret_matches_dense():
    """Kernel-native GQA (KVH < H): fwd + fused bwd vs dense with k/v
    repeated on the host (ADVICE r2: the GQA BlockSpec index maps h//rep
    and the backward group-sum had no interpret-mode coverage)."""
    from fengshen_tpu.ops.pallas.flash_attention import pallas_flash_attention
    rng = np.random.RandomState(1)
    H, KVH = 8, 2
    q = jnp.asarray(rng.randn(2, 16, H, 8), jnp.float32)
    k = jnp.asarray(rng.randn(2, 16, KVH, 8), jnp.float32)
    v = jnp.asarray(rng.randn(2, 16, KVH, 8), jnp.float32)
    rep = H // KVH
    k_full = jnp.repeat(k, rep, axis=2)
    v_full = jnp.repeat(v, rep, axis=2)

    out = pallas_flash_attention(q, k, v, None, None, True, 8, 8, True)
    mask = causal_mask(16)[None, None]
    ref = _ref_attention(q, k_full, v_full, make_attention_bias(mask))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)

    def f_gqa(q, k, v):
        return (pallas_flash_attention(
            q, k, v, None, None, True, 8, 8, True) ** 2).sum()

    def f_ref(q, k_full, v_full):
        out = _ref_attention(q, k_full, v_full, make_attention_bias(mask))
        return (out ** 2).sum()

    gq, gk, gv = jax.grad(f_gqa, argnums=(0, 1, 2))(q, k, v)
    rq, rkf, rvf = jax.grad(f_ref, argnums=(0, 1, 2))(q, k_full, v_full)
    # dense grads for repeated k/v heads group-sum back onto the shared head
    rk = rkf.reshape(2, 16, KVH, rep, 8).sum(axis=3)
    rv = rvf.reshape(2, 16, KVH, rep, 8).sum(axis=3)
    np.testing.assert_allclose(np.asarray(gq), np.asarray(rq), atol=1e-3)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(rk), atol=1e-3)
    np.testing.assert_allclose(np.asarray(gv), np.asarray(rv), atol=1e-3)


def test_fused_lm_head_ce_matches_unfused():
    """Chunked fused head+CE (ops/fused_ce.py): identical loss and
    gradients to the materialized-logits path."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fengshen_tpu.ops.fused_ce import causal_fused_loss, fused_lm_head_ce
    from fengshen_tpu.parallel.cross_entropy import stable_cross_entropy

    rng = np.random.RandomState(0)
    B, S, H, V = 2, 12, 16, 32
    hidden = jnp.asarray(rng.randn(B, S, H), jnp.float32)
    kernel = jnp.asarray(rng.randn(H, V) * 0.1, jnp.float32)
    labels = jnp.asarray(rng.randint(0, V, (B, S)), jnp.int32)
    labels = labels.at[0, :3].set(-100)  # ignore_index masking

    def unfused(h, k):
        return stable_cross_entropy(h @ k, labels)[0]

    def fused(h, k):
        return fused_lm_head_ce(h, k, labels, num_chunks=4)[0]

    l_u, (gh_u, gk_u) = jax.value_and_grad(unfused, argnums=(0, 1))(
        hidden, kernel)
    l_f, (gh_f, gk_f) = jax.value_and_grad(fused, argnums=(0, 1))(
        hidden, kernel)
    assert abs(float(l_u - l_f)) < 1e-5
    np.testing.assert_allclose(gh_u, gh_f, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(gk_u, gk_f, rtol=1e-4, atol=1e-6)

    # accuracy numerator matches a direct argmax
    loss, n, correct = fused_lm_head_ce(hidden, kernel, labels,
                                        num_chunks=4)
    logits = hidden @ kernel
    valid = labels != -100
    assert int(n) == int(valid.sum())
    assert int(correct) == int(((logits.argmax(-1) == labels) *
                                valid).sum())

    # odd seq lens pad up to the chunk multiple — same value as the
    # unfused path, full chunk count preserved (ADVICE r4: the causal
    # variant's S-1 must not silently collapse to one chunk)
    loss11, n11, _ = fused_lm_head_ce(hidden[:, :11], kernel,
                                      labels[:, :11], num_chunks=4)
    ls11, _ = stable_cross_entropy(hidden[:, :11] @ kernel, labels[:, :11])
    assert abs(float(loss11 - ls11)) < 1e-5
    assert int(n11) == int((labels[:, :11] != -100).sum())

    # causal variant == shift-by-one of the plain one
    lc, _, _ = causal_fused_loss(hidden, kernel, labels, num_chunks=4)
    ls, _ = stable_cross_entropy(hidden[:, :-1] @ kernel, labels[:, 1:])
    assert abs(float(lc - ls)) < 1e-5


def test_causal_lm_module_fused_ce_path(mesh8):
    """CausalLMModule with fused_ce_chunks: same loss as the plain path
    (tensor axis is 2 on mesh8, so the gate must keep it OFF there; on a
    tensor=1 mesh it engages)."""
    import argparse
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.parallel import MeshConfig, make_mesh, set_mesh
    from fengshen_tpu.trainer.modules import CausalLMModule

    base = LlamaConfig(vocab_size=64, hidden_size=32,
                       intermediate_size=64, num_hidden_layers=2,
                       num_attention_heads=4,
                       max_position_embeddings=32, dtype="float32")
    args = argparse.Namespace(max_seq_length=16)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 63, (2, 16)),
                      jnp.int32)
    batch = {"input_ids": ids}
    rng = jax.random.PRNGKey(0)

    plain = CausalLMModule(args, LlamaForCausalLM(base), base)
    params = plain.init_params(rng)
    cfg_f = dataclasses.replace(base, fused_ce_chunks=4)
    fused = CausalLMModule(args, LlamaForCausalLM(cfg_f), cfg_f)

    # tensor=2 mesh: gate keeps the fused path off
    assert not fused._fused_ce_active()

    set_mesh(None)
    try:
        mesh1 = make_mesh(MeshConfig(data=8, fsdp=1, sequence=1,
                                     tensor=1))
        set_mesh(mesh1)
        assert fused._fused_ce_active()
        l_p, m_p = plain.training_loss(params, batch, rng)
        l_f, m_f = fused.training_loss(params, batch, rng)
        assert abs(float(l_p - l_f)) < 1e-5
        assert abs(float(m_p["acc"] - m_f["acc"])) < 1e-6
    finally:
        set_mesh(None)
