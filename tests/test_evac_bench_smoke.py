"""`make serve-bench-evac` harness guard (ISSUE 16): the preemption
drills must emit their one BENCH-schema JSON line — with the drill in
the row, part of benchdiff's comparison identity — the SIGTERM rung
must finish every request 200 and token-identical through live lane
evacuation (at least one lane adopted by the standby peer), and the
SIGKILL rung must recover every request through resume-from-token-k
out of the commit journal: `resumed >= 1`, zero journal misses (no
request regenerated from token 0), recovered-request overhead strictly
below regenerate-from-zero.

The fast lane runs the harness in FAKE mode: in-process stdlib
replicas speaking the full evacuation surface (generate + draining
/stats + PUT/GET /kv + GET /partial) with a position-deterministic
token function, driven through the REAL router's redirect / collect /
journal-consult / resume machinery — the whole three-rung ladder runs
in seconds without a model. The real-subprocess mode (actual engine
drains, KV evacuations, and resume prefills under real signals) is
the slow lane.
"""

import io
import json
import os
from contextlib import redirect_stdout

import pytest

FAKE = {"EVAC_BENCH_FAKE": "1", "EVAC_BENCH_REQUESTS": "24",
        "EVAC_BENCH_FAKE_TOKEN_S": "0.02"}


def _run(monkeypatch, env: dict, base: dict = FAKE) -> dict:
    from fengshen_tpu.fleet import evac_bench

    for key in list(os.environ):
        if key.startswith(("EVAC_BENCH_", "FLEET_BENCH_",
                           "BENCH_DEGRADED")):
            monkeypatch.delenv(key)
    for key, val in {**base, **env}.items():
        monkeypatch.setenv(key, val)
    out = io.StringIO()
    with redirect_stdout(out):
        evac_bench.main([])
    lines = [l for l in out.getvalue().splitlines()
             if l.startswith("{")]
    assert lines, out.getvalue()
    return json.loads(lines[-1])


def test_evac_bench_fake_schema_and_drills(monkeypatch):
    row = _run(monkeypatch, {})
    assert set(row) >= {"metric", "value", "unit", "vs_baseline",
                        "drill", "replicas", "requests", "sigterm",
                        "sigkill", "resumed", "zero_regenerated",
                        "fake"}
    assert row["metric"] == "evac_tokens_per_sec"
    assert row["unit"] == "tokens/s"
    assert row["value"] > 0 and row["tokens_per_sec_baseline"] > 0
    # the comparison identity benchdiff keys on: a preemption drill is
    # never diffed against an undisturbed fleet round
    assert row["drill"] == "preempt"
    assert row["replicas"] == 3
    assert row["fake"] is True and row["backend"] == "fake"
    # the SIGTERM bar: a drain with live decodes answers EVERY request
    # 200 token-identical — at least one lane rode an evacuation to
    # the standby peer, and nothing fell back to regenerating from
    # token 0 (a transient reset MAY legitimately ride the resume
    # path, so only the miss outcome is pinned to zero)
    assert row["failed"] == 0
    assert row["token_identical_sigterm"] is True
    assert row["sigterm"]["adopted"] >= 1
    assert row["sigterm"]["resume"].get("miss", 0) == 0
    # the SIGKILL bar: the adopter dies mid-decode and every affected
    # request comes back through resume-from-token-k — token-identical,
    # at least one resume, ZERO regenerated from token 0
    assert row["token_identical_sigkill"] is True
    assert row["resumed"] >= 1
    assert row["zero_regenerated"] is True
    assert row["sigkill"]["resume"].get("miss", 0) == 0
    # a recovered request re-decodes strictly less than all of its
    # tokens: the journal prefix is real saved work
    assert row["recovered_overhead_vs_regenerate"] is not None
    assert 0.0 < row["recovered_overhead_vs_regenerate"] < 1.0
    assert "degraded" not in row


def test_evac_bench_fleet_env_fallback(monkeypatch):
    """EVAC_BENCH_* knobs fall back to FLEET_BENCH_* so one CI env
    block can steer the whole fleet-bench family."""
    row = _run(monkeypatch,
               {"FLEET_BENCH_FAKE": "1",
                "FLEET_BENCH_REQUESTS": "12",
                "FLEET_BENCH_FAKE_TOKEN_S": "0.02"}, base={})
    assert row["fake"] is True
    assert row["requests"] == 12
    assert row["failed"] == 0


def test_evac_bench_degraded_flag(monkeypatch):
    row = _run(monkeypatch, {"BENCH_DEGRADED": "1",
                             "EVAC_BENCH_REQUESTS": "12"})
    assert row["degraded"] is True


@pytest.mark.slow
def test_evac_bench_real_signals_zero_failed(monkeypatch):
    """The real path: replica subprocesses (random-init llama,
    continuous engines, drain handlers wired with evacuation peers)
    under a real SIGTERM and a real SIGKILL — every request completes,
    token-identical to the undisturbed baseline, nothing regenerated
    from token 0. ~minutes on CPU."""
    row = _run(monkeypatch,
               {"EVAC_BENCH_BASE_PORT": "8470",
                "EVAC_BENCH_REQUESTS": "12"}, base={})
    assert row["fake"] is False
    assert row["failed"] == 0
    assert row["token_identical_sigterm"] is True, row
    assert row["token_identical_sigkill"] is True, row
    assert row["zero_regenerated"] is True, row
