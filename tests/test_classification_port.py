"""The real finetune_classification port (VERDICT r3 missing #2).

Covers the reference arg surface
(reference: fengshen/examples/classification/finetune_classification.py:
124-199 TaskDataModel, 299-324 TaskModelCheckpoint) and an e2e tiny-config
fit → predict → save_test run, plus the offload recipe
(demo_classification_afqmc_erlangshen_offload.sh analog).
"""

import json
import os

import numpy as np
import pytest

from fengshen_tpu.examples.classification import finetune_classification as fc

CHARS = list("蚂蚁花呗借呗如何开通还款利息手续费用查询额度提升冻结解冻转账"
             "收款验证失败异常原因网络天气很好糟糕")


def _write_task_dir(tmp_path, n_train=12, n_dev=6, n_test=6):
    rng = np.random.RandomState(0)
    labels = ["0", "1"]

    def row(i):
        a = "".join(rng.choice(CHARS, 6))
        b = "".join(rng.choice(CHARS, 5))
        return {"id": i, "sentence1": a, "sentence2": b,
                "label": labels[i % 2]}

    data_dir = tmp_path / "afqmc"
    data_dir.mkdir()
    for name, n in (("train.json", n_train), ("dev.json", n_dev),
                    ("test.json", n_test)):
        with open(data_dir / name, "w") as f:
            for i in range(n):
                f.write(json.dumps(row(i), ensure_ascii=False) + "\n")
    return data_dir


def _write_model_dir(tmp_path, model_type="bert"):
    from transformers import BertTokenizer
    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + \
        sorted(set(CHARS))
    (tmp_path / "vocab.txt").write_text("\n".join(vocab))
    tok = BertTokenizer(str(tmp_path / "vocab.txt"))
    model_dir = tmp_path / "model"
    model_dir.mkdir()
    tok.save_pretrained(str(model_dir))
    cfg = {"model_type": model_type, "vocab_size": len(vocab),
           "hidden_size": 32, "num_hidden_layers": 2,
           "num_attention_heads": 2, "intermediate_size": 64,
           "max_position_embeddings": 64, "type_vocab_size": 2,
           "dtype": "float32"}
    with open(model_dir / "config.json", "w") as f:
        json.dump(cfg, f)
    return model_dir


def test_reference_arg_surface_parses():
    """Every flag of the reference shells must be declared (the round-3
    stub could not parse --data_dir/--texta_name/--dirpath at all)."""
    parser = fc.build_parser()
    args = parser.parse_args([
        "--pretrained_model_path", "/tmp/x",
        "--output_save_path", "./predict.json",
        "--model_type", "huggingface-auto",
        "--data_dir", "/tmp/d", "--train_data", "train.json",
        "--valid_data", "dev.json", "--test_data", "test.json",
        "--train_batchsize", "8", "--valid_batchsize", "32",
        "--max_length", "128",
        "--texta_name", "sentence1", "--textb_name", "sentence2",
        "--label_name", "label", "--id_name", "id",
        "--learning_rate", "0.000001", "--weight_decay", "0.001",
        "--warmup", "0.001", "--num_labels", "2",
        "--monitor", "val_acc", "--mode", "max", "--save_top_k", "3",
        "--every_n_train_steps", "0", "--save_weights_only", "True",
        "--dirpath", "/tmp/ckpt",
        "--filename", "model-{epoch:02d}-{val_acc:.4f}",
        "--max_epochs", "67", "--gradient_clip_val", "1.0",
        "--precision", "16", "--default_root_dir", "/tmp/root",
        "--offload_optimizer",
    ])
    assert args.texta_name == "sentence1"
    assert args.save_weights_only is True
    assert args.save_top_k == 3.0  # reference type: float
    assert args.model_type == "huggingface-auto"


def test_model_dict_covers_reference_types():
    """reference finetune_classification.py:44-51 model_dict keys (zen1 is
    commented out there but its shells need it)."""
    for key in ("huggingface-bert", "fengshen-roformer",
                "huggingface-megatron_bert", "fengshen-megatron_t5",
                "fengshen-longformer"):
        assert key in fc.model_dict


def test_schema_first_seen_order(tmp_path):
    data_dir = _write_task_dir(tmp_path)
    parser = fc.build_parser()
    args = parser.parse_args(
        ["--texta_name", "sentence1", "--textb_name", "sentence2"])
    label2id, id2label = fc.TaskDataModel.load_schema(
        fc.TaskDataModel, str(data_dir / "train.json"), args)
    assert label2id == {"0": 0, "1": 1}
    assert id2label == {0: "0", 1: "1"}


def test_collator_pair_vs_single_is_per_sample(tmp_path):
    """One row with an empty textb must not drop textb for the rest of
    the batch — the reference decides pair-vs-single per sample
    (reference: finetune_classification.py:87-121; ADVICE r4)."""
    model_dir = _write_model_dir(tmp_path)
    from transformers import BertTokenizer
    tok = BertTokenizer.from_pretrained(str(model_dir))
    parser = fc.build_parser()
    args = parser.parse_args(
        ["--texta_name", "sentence1", "--textb_name", "sentence2",
         "--max_length", "32"])
    coll = fc.TaskCollator(args=args, tokenizer=tok)
    pair = {"sentence1": "蚂蚁花呗", "sentence2": "借呗开通",
            "label": 1, "id": 0}
    single = {"sentence1": "天气很好", "sentence2": "",
              "label": 0, "id": 1}
    mixed = coll([pair, single, pair])
    pure = coll([pair, pair])
    # the pair rows keep their textb encoding even next to a single row
    np.testing.assert_array_equal(mixed["input_ids"][0],
                                  pure["input_ids"][0])
    np.testing.assert_array_equal(mixed["input_ids"][2],
                                  pure["input_ids"][0])
    # and the single row really is single-encoded (no second segment)
    only_single = coll([single])
    np.testing.assert_array_equal(mixed["input_ids"][1],
                                  only_single["input_ids"][0])
    assert mixed["labels"].tolist() == [1, 0, 1]


def test_simple_batch_sampler_tail_keeps_ranks_in_step():
    """drop_last=False pads the tail global batch by cycling its own
    indices, so every rank yields the same number of batches
    (ADVICE r4 — multi-host ranks must not desynchronize)."""
    from fengshen_tpu.data.universal_datamodule import _SimpleBatchSampler

    total, batch, world = 10, 2, 4  # tail global batch has 2 of 8 slots
    per_rank = [list(_SimpleBatchSampler(total, batch, r, world,
                                         shuffle=False, drop_last=False))
                for r in range(world)]
    counts = [len(b) for b in per_rank]
    assert counts == [counts[0]] * world  # identical batch counts
    for batches in per_rank:
        assert all(len(b) == batch for b in batches)  # all full batches
    # every real index is still covered across ranks
    seen = {i for batches in per_rank for b in batches for i in b}
    assert seen == set(range(total))
    # drop_last=True is untouched: exact division, no padding
    strict = [list(_SimpleBatchSampler(total, batch, r, world,
                                       shuffle=False, drop_last=True))
              for r in range(world)]
    assert all(len(b) == 1 for b in strict)


@pytest.mark.slow
def test_backbone_import_from_hf_checkpoint(tmp_path):
    """--pretrained_model_path with real torch weights: the module's
    init must carry the HF encoder into params['bert_encoder'] (the
    reference's `.from_pretrained` at :207-208), with the classifier
    randomly initialised."""
    import jax
    import jax.numpy as jnp
    torch = pytest.importorskip("torch")
    import transformers

    model_dir = _write_model_dir(tmp_path)
    hf_cfg = transformers.BertConfig(
        vocab_size=json.load(open(model_dir / "config.json"))["vocab_size"],
        hidden_size=32, num_hidden_layers=2, num_attention_heads=2,
        intermediate_size=64, max_position_embeddings=64,
        type_vocab_size=2)
    torch.manual_seed(0)
    tm = transformers.BertForSequenceClassification(hf_cfg)
    torch.save(tm.state_dict(), str(model_dir / "pytorch_model.bin"))

    parser = fc.build_parser()
    args = parser.parse_args([
        "--pretrained_model_path", str(model_dir),
        "--model_type", "huggingface-bert", "--num_labels", "2",
        "--max_length", "32"])
    module = fc.ClassificationModule(args)
    params = module.init_params(jax.random.PRNGKey(0))
    # imported embedding equals torch's, token for token
    got = np.asarray(params["bert_encoder"]["word_embeddings"]
                     ["embedding"])
    want = tm.bert.embeddings.word_embeddings.weight.detach().numpy()
    np.testing.assert_allclose(got, want, rtol=1e-6)
    # pooled forward parity vs torch
    ids = np.random.RandomState(0).randint(
        0, hf_cfg.vocab_size, (2, 8)).astype(np.int64)
    tm.eval()
    with torch.no_grad():
        t_pool = tm.bert(torch.tensor(ids)).pooler_output.numpy()
    logits = module._apply(params, {"input_ids": jnp.asarray(ids,
                                                             jnp.int32)},
                           deterministic=True)
    assert logits.shape == (2, 2)
    # classifier is random, so compare the imported tower directly
    _, _, enc_cls = fc._family("huggingface-bert")
    enc = enc_cls(module.config, add_pooling_layer=True)
    _, j_pool = enc.apply({"params": params["bert_encoder"]},
                          jnp.asarray(ids, jnp.int32))
    np.testing.assert_allclose(np.asarray(j_pool), t_pool, atol=2e-4)


@pytest.mark.slow
@pytest.mark.parametrize("extra", [[], ["--offload_optimizer"],
                                   ["--steps_per_execution", "2"]],
                         ids=["plain", "offload", "multistep"])
def test_finetune_classification_e2e(tmp_path, mesh8, extra, monkeypatch):
    """fit → predict → save_test on a tiny huggingface-auto (bert) config;
    the offload variant is the 7 GB demo recipe path."""
    monkeypatch.chdir(tmp_path)
    data_dir = _write_task_dir(tmp_path)
    model_dir = _write_model_dir(tmp_path)
    out = tmp_path / "predict.json"
    fc.main([
        "--pretrained_model_path", str(model_dir),
        "--model_type", "huggingface-auto",
        "--output_save_path", str(out),
        "--data_dir", str(data_dir),
        "--texta_name", "sentence1", "--textb_name", "sentence2",
        "--label_name", "label", "--id_name", "id",
        "--train_batchsize", "4", "--valid_batchsize", "4",
        "--max_length", "32", "--num_labels", "2",
        "--learning_rate", "1e-4", "--max_epochs", "1", "--max_steps", "3",
        "--monitor", "val_acc", "--mode", "max",
        "--every_n_train_steps", "0", "--save_weights_only", "True",
        "--dirpath", str(tmp_path / "ckpt"),
        "--default_root_dir", str(tmp_path / "runs"),
        "--precision", "fp32",
    ] + extra)
    lines = [json.loads(x) for x in
             open(str(out) + ".0", encoding="utf-8")]
    assert len(lines) == 6
    assert all(set(r) == {"id", "label"} for r in lines)
    assert all(r["label"] in ("0", "1") for r in lines)
    # ids survive the round trip (reference save_test contract)
    assert sorted(r["id"] for r in lines) == list(range(6))
    if not extra:
        # predict-only path: restore (or random-init) + predict without
        # a validation sweep, same output contract
        os.remove(str(out) + ".0")
        fc.main([
            "--pretrained_model_path", str(model_dir),
            "--model_type", "huggingface-auto",
            "--output_save_path", str(out),
            "--data_dir", str(data_dir),
            "--texta_name", "sentence1", "--textb_name", "sentence2",
            "--valid_batchsize", "4", "--max_length", "32",
            "--num_labels", "2", "--do_predict_only",
            "--dirpath", str(tmp_path / "ckpt"),
            "--default_root_dir", str(tmp_path / "runs"),
            "--precision", "fp32",
        ])
        lines = [json.loads(x) for x in
                 open(str(out) + ".0", encoding="utf-8")]
        assert len(lines) == 6


def test_hf_dataset_view_maps_labels_through_schema():
    """--dataset_name rows must get label2id applied exactly like the
    jsonl path, or save_test's id2label round-trip label-flips."""
    parser = fc.build_parser()
    args = parser.parse_args(
        ["--texta_name", "sentence1", "--textb_name", "sentence2"])
    rows = [{"id": 7, "sentence1": "a", "sentence2": "b",
             "label": "entailment"},
            {"id": 8, "sentence1": "c", "sentence2": "d",
             "label": "contradiction"}]
    label2id, id2label = fc.TaskDataModel._schema_from_rows(rows, args)
    view = fc._HFView(rows, args, label2id)
    assert view[0]["label"] == 0 and view[1]["label"] == 1
    assert view[0]["id"] == 7
    assert id2label[view[1]["label"]] == "contradiction"


def test_auto_resolution_happens_once_in_main_surface():
    """resolve_model_type on an explicit type is the identity, and the
    RoFormer special case in the collator keys on the RESOLVED type."""
    assert fc.resolve_model_type("fengshen-roformer", "/nope") == \
        "fengshen-roformer"


def test_bart_backbone_forward():
    """fengshen-bart: encoder-only pass pooled at the last real token."""
    import jax
    import jax.numpy as jnp

    from fengshen_tpu.models.bart import BartConfig

    cfg = BartConfig(vocab_size=32, d_model=16, encoder_layers=1,
                     decoder_layers=1, encoder_attention_heads=2,
                     decoder_attention_heads=2, encoder_ffn_dim=32,
                     decoder_ffn_dim=32, max_position_embeddings=64)
    model = fc.TaskModel(cfg, "fengshen-bart", num_labels=3)
    ids = jnp.ones((2, 8), jnp.int32)
    mask = jnp.array([[1] * 8, [1] * 5 + [0] * 3], jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids, attention_mask=mask)
    logits = model.apply(params, ids, attention_mask=mask)
    assert logits.shape == (2, 3)


@pytest.mark.slow
def test_finetune_classification_t5_backbone(tmp_path, mesh8, monkeypatch):
    """fengshen-megatron_t5 path: encoder-only backbone, [CLS]-token
    encode (reference:215-218)."""
    monkeypatch.chdir(tmp_path)
    data_dir = _write_task_dir(tmp_path, 8, 4, 4)
    model_dir = _write_model_dir(tmp_path, model_type="t5")
    cfg = json.load(open(model_dir / "config.json"))
    cfg.update({"d_model": 32, "d_kv": 16, "d_ff": 64, "num_layers": 2,
                "num_heads": 2})
    json.dump(cfg, open(model_dir / "config.json", "w"))
    out = tmp_path / "predict.json"
    fc.main([
        "--pretrained_model_path", str(model_dir),
        "--model_type", "fengshen-megatron_t5",
        "--output_save_path", str(out),
        "--data_dir", str(data_dir),
        "--texta_name", "sentence1", "--textb_name", "sentence2",
        "--train_batchsize", "4", "--valid_batchsize", "4",
        "--max_length", "32", "--num_labels", "2",
        "--max_epochs", "1", "--max_steps", "2",
        "--dirpath", str(tmp_path / "ckpt"),
        "--default_root_dir", str(tmp_path / "runs"),
        "--precision", "fp32",
    ])
    assert os.path.exists(str(out) + ".0")


@pytest.mark.slow
def test_offload_demo_fits_7gb_at_1p3b_shape(tmp_path):
    """The README-headline claim behind
    demo_classification_afqmc_erlangshen_offload.sh: with
    --offload_optimizer (adam moments host-resident), the 1.3B-shape
    classification grad step fits a ~7-8 GB accelerator. Verified
    analytically via AOT compile + XLA memory analysis on a 1-device
    mesh (no real buffers), mirroring the demo's batch 1 x seq 128."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from fengshen_tpu.models.megatron_bert import MegatronBertConfig
    from fengshen_tpu.parallel import MeshConfig, make_mesh, set_mesh
    from fengshen_tpu.parallel.partition import make_shardings

    # Erlangshen-MegatronBert-1.3B dims (hidden 2048, 24 layers,
    # vocab 21128), bf16 params as the fp16 demo runs
    config = MegatronBertConfig(
        vocab_size=21248, hidden_size=2048, num_hidden_layers=24,
        num_attention_heads=32, intermediate_size=8192,
        max_position_embeddings=512, type_vocab_size=2,
        dtype="bfloat16", param_dtype="bfloat16", scan_layers=True)
    model = fc.TaskModel(config, "huggingface-megatron_bert",
                         num_labels=2)

    set_mesh(None)
    # params fully replicated: per-device footprint equals the demo's
    # single-accelerator footprint regardless of the data-axis width
    mesh = make_mesh(MeshConfig(data=8, fsdp=1, sequence=1, tensor=1))
    set_mesh(mesh)
    try:
        rng = jax.random.PRNGKey(0)
        ids_small = jnp.zeros((1, 16), jnp.int32)
        params_struct = jax.eval_shape(
            lambda r: model.init(r, ids_small)["params"], rng)
        n_params = sum(np.prod(l.shape) for l in
                       jax.tree_util.tree_leaves(params_struct))
        assert 1.2e9 < n_params < 1.5e9, f"{n_params:.2e}"

        batch_struct = {
            "input_ids": jax.ShapeDtypeStruct((1, 128), jnp.int32),
            "attention_mask": jax.ShapeDtypeStruct((1, 128), jnp.int32),
            "token_type_ids": jax.ShapeDtypeStruct((1, 128), jnp.int32),
            "labels": jax.ShapeDtypeStruct((1,), jnp.int32)}

        def loss_fn(params, batch):
            from fengshen_tpu.parallel.cross_entropy import (
                stable_cross_entropy)
            logits = model.apply(
                {"params": params}, batch["input_ids"],
                attention_mask=batch["attention_mask"],
                token_type_ids=batch["token_type_ids"],
                deterministic=True)
            loss, _ = stable_cross_entropy(logits[:, None, :],
                                           batch["labels"][:, None])
            return loss

        param_sh = make_shardings(
            [(".*", jax.sharding.PartitionSpec(None))], params_struct,
            mesh)
        grad_step = jax.jit(jax.grad(loss_fn),
                            in_shardings=(param_sh, None))
        compiled = grad_step.lower(params_struct, batch_struct).compile()
        mem = compiled.memory_analysis()
        args_gb = mem.argument_size_in_bytes / 2**30
        out_gb = mem.output_size_in_bytes / 2**30
        temp_gb = mem.temp_size_in_bytes / 2**30
        print(f"\n1.3B offload-demo grad step: args {args_gb:.2f} + "
              f"grads {out_gb:.2f} + temp(cpu) {temp_gb:.2f} GiB")
        # Device-resident steady state under --offload_optimizer =
        # bf16 params + bf16 grads (moments live on HOST between
        # steps); at batch 1 x seq 128 activations are tens of MB.
        # That is the 7 GB claim: ~4.7 GiB + activations < 7.
        assert args_gb + out_gb < 5.5, "params+grads exceed the claim"
        # The CPU backend upcasts bf16 matmul operands to fp32 (no
        # native bf16 CPU matmul), which shows up as a ~fp32-params
        # temp; bound temp by that artifact + 1 GiB of activations so
        # a real activation blow-up still fails the test. On TPU the
        # bf16 MXU consumes the weights directly and this temp term
        # does not exist.
        fp32_params_gb = n_params * 4 / 2**30
        assert temp_gb < fp32_params_gb + 1.0, (
            f"temp {temp_gb:.2f} GiB exceeds the CPU-upcast artifact "
            f"bound {fp32_params_gb + 1.0:.2f}")
        # and WITHOUT offload the fp32 adam moments alone add ~9.4 GiB
        # device-resident — the demo could not fit; the offload is
        # what makes the 7 GB recipe real
        moments_gb = 2 * n_params * 4 / 2**30
        assert args_gb + out_gb + moments_gb > 12.0
    finally:
        set_mesh(None)
