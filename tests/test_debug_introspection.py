"""Per-request lifecycle tracing, flight recorder, and debug
introspection (ISSUE 8).

The load-bearing contracts:

- every request's timeline derives a waterfall whose queue_wait +
  prefill + decode phases SUM to its wall-clock latency, and timelines
  add only host-side work: greedy output stays token-identical to
  sequential generate with ONE decode compile, on non-spec AND spec
  engines;
- a faulted engine run dumps a complete post-mortem bundle (events
  jsonl, /stats snapshot, engine/model config, last-N request
  timelines), deterministically (byte-identical across
  PYTHONHASHSEED); a trainer step-guard rewind dumps the last window
  of step stats the same way;
- `GET /debug/requests[/<id>]` + `POST /debug/dump` work on the stdlib
  API path; `fstpu_http_request_seconds{route}` and
  `fstpu_request_phase_seconds{phase}` land in /metrics;
- /stats only EXTENDS (uptime_s, last_error as type+age — no
  traceback); benchdiff classifies the repo's BENCH trajectory
  deterministically and flags synthetic regressions.
"""

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from fengshen_tpu.observability import (FlightRecorder, JsonlSink,
                                        RequestTimeline, get_registry)
from fengshen_tpu.serving import (ContinuousBatchingEngine, EngineConfig,
                                  QueueFull)
from fengshen_tpu.utils.generate import generate

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig(vocab_size=97, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      max_position_embeddings=64, dtype="float32")
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    return model, params


def _prompts(lengths, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(3, 96, n).astype(np.int32) for n in lengths]


def _ref(model, params, prompt, max_new, **kw):
    out = np.asarray(generate(model, params, jnp.asarray(prompt)[None],
                              max_new_tokens=max_new, **kw))
    return out[0, len(prompt):].tolist()


class _FakeTokenizer:
    eos_token_id = None
    pad_token_id = 0

    def encode(self, text):
        return [int(t) for t in text.split()]

    def decode(self, ids):
        return " ".join(str(int(t)) for t in ids)


def _gen_pipeline(tiny, **kw):
    from fengshen_tpu.pipelines.text_generation import Pipeline
    model, params = tiny
    return Pipeline(module=model, params=params,
                    tokenizer=_FakeTokenizer(), **kw)


def _phase_sum_matches(d, tol=1e-3):
    ph = d["phases"]
    total = ph["queue_wait_s"] + ph["prefill_s"] + ph["decode_s"]
    assert abs(total - ph["total_s"]) <= tol, ph
    assert all(v >= 0 for v in ph.values()), ph


# ---- timeline unit behavior ---------------------------------------------

def test_timeline_phases_and_event_cap():
    tl = RequestTimeline(t0=100.0, max_events=4)
    tl.add(100.0, "enqueued", prompt_tokens=3)
    tl.add(100.5, "prefill_start", bucket=8)
    tl.add(101.0, "first_token")
    tl.add(102.0, "commit", n=1, tick_s=0.25)
    tl.add(102.5, "commit", n=1, tick_s=0.25)   # over cap: dropped
    assert tl.dropped == 1
    # the dropped commit's tick time still counts against stall, and a
    # TERMINAL event always lands even past the cap — a capped
    # timeline must keep its end mark
    tl.add(103.0, "finished", reason="length")
    assert [e[1] for e in tl.events][-1] == "finished"
    ph = tl.phases(now=999.0)                   # terminal wins over now
    assert ph == {"queue_wait_s": 0.5, "prefill_s": 0.5,
                  "decode_s": 2.0, "decode_stall_s": 1.5,
                  "total_s": 3.0}
    # a terminal event pins the end regardless of `now`
    tl2 = RequestTimeline(t0=0.0)
    tl2.add(0.0, "enqueued")
    tl2.add(1.0, "rejected", reason="queue_full")
    ph2 = tl2.phases(now=50.0)
    assert ph2["total_s"] == 1.0
    assert ph2["queue_wait_s"] == 1.0      # never admitted: all wait
    assert ph2["prefill_s"] == 0.0 and ph2["decode_s"] == 0.0


# ---- engine waterfall + parity (the tentpole contract) ------------------

def test_waterfall_phases_sum_to_latency(tiny):
    """Every finished request's derived phases partition its wall-clock
    latency; the lifecycle marks are all present and ordered."""
    model, params = tiny
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=2, buckets=(8, 16),
                                    max_new_tokens=6, max_queue=16))
    reqs = [eng.submit(p) for p in _prompts((5, 11, 16, 7))]
    eng.run_until_idle()
    for req in reqs:
        d = eng.debug_request(req.request_id)
        assert d is not None and d["state"] == "finished"
        _phase_sum_matches(d)
        names = [e["event"] for e in d["events"]]
        for mark in ("enqueued", "admitted", "prefill_start",
                     "first_token", "commit", "finished"):
            assert mark in names
        assert names[0] == "enqueued" and names[-1] == "finished"
        # commits carry the per-tick token counts: prefill commits the
        # first token, ticks the other max_new-1
        committed = sum(e["n"] for e in d["events"]
                        if e["event"] == "commit")
        assert committed == len(req.tokens) - 1
        # ttft == queue_wait + prefill by construction
        ph = d["phases"]
        assert abs(d["ttft_s"] -
                   (ph["queue_wait_s"] + ph["prefill_s"])) <= 1e-3


def test_timeline_parity_and_one_compile(tiny):
    """Timelines must not add traced work: with tracing active, greedy
    output is still token-identical to sequential generate under
    staggered admission, with exactly ONE decode compile — on the
    non-spec AND the spec engine."""
    model, params = tiny
    prompts = _prompts((5, 11, 16, 7))
    refs = [_ref(model, params, p, 8) for p in prompts]
    for extra in ({}, {"spec_mode": "prompt_lookup", "spec_gamma": 2,
                       "spec_ngram": 2}):
        eng = ContinuousBatchingEngine(
            model, params,
            EngineConfig(num_slots=2, buckets=(8, 16),
                         max_new_tokens=8, max_queue=16, **extra))
        if not hasattr(eng._decode_jit, "_cache_size"):
            pytest.skip("jit cache introspection unavailable")
        reqs = [eng.submit(p) for p in prompts[:2]]
        for _ in range(3):
            eng.step()
        reqs += [eng.submit(p) for p in prompts[2:]]
        eng.run_until_idle()
        for req, ref in zip(reqs, refs):
            assert req.tokens == ref
            d = eng.debug_request(req.request_id)
            _phase_sum_matches(d)
            commits = [e for e in d["events"] if e["event"] == "commit"]
            assert sum(e["n"] for e in commits) == len(ref) - 1
            if extra:
                # spec commits carry accept counts for the waterfall
                assert all("accepted" in e for e in commits)
        assert eng._decode_jit._cache_size() == 1


def test_debug_requests_ring_and_rejections(tiny):
    """The list endpoint surfaces in-flight + recent; queue-full
    rejections join the ring with reason and phases; the ring is
    bounded by debug_ring; unknown ids return None."""
    model, params = tiny
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=1, buckets=(8,),
                                    max_new_tokens=2, max_queue=2,
                                    debug_ring=3))
    for p in _prompts((4, 5)):
        eng.submit(p)
    with pytest.raises(QueueFull):
        eng.submit(_prompts((6,))[0], request_id="rejected-1")
    dbg = eng.debug_requests()
    assert len(dbg["in_flight"]) == 2
    rej = [r for r in dbg["recent"] if r["request_id"] == "rejected-1"]
    assert rej and rej[0]["state"] == "rejected"
    assert rej[0]["finish_reason"] == "queue_full"
    d = eng.debug_request("rejected-1")
    assert d["events"][-1]["event"] == "rejected"
    # 413-class rejections (no bucket fits) join the ring too — a
    # burst of 413s must be diagnosable, not invisible
    from fengshen_tpu.serving import PromptTooLong
    with pytest.raises(PromptTooLong):
        eng.submit(_prompts((20,))[0], request_id="too-long-1")
    d413 = eng.debug_request("too-long-1")
    assert d413["state"] == "rejected"
    assert d413["finish_reason"] == "prompt_too_long"
    assert d413["events"][-1]["prompt_tokens"] == 20
    eng.run_until_idle()
    dbg = eng.debug_requests()
    assert not dbg["in_flight"]
    assert len(dbg["recent"]) == 3          # bounded: oldest aged out
    assert eng.debug_request("never-existed") is None


def test_stats_uptime_and_last_error(tiny):
    """/stats gains uptime_s and last_error (type + age only — never a
    traceback payload); a serve-loop tick error populates it and the
    phase histograms stay renderable."""
    from fengshen_tpu.observability import render_prometheus

    model, params = tiny
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=1, buckets=(8,),
                                    max_new_tokens=2, max_queue=4))
    stats = eng.stats()
    assert stats["uptime_s"] >= 0 and stats["last_error"] is None
    real = eng._decode_jit
    boom = [True]

    def flaky(*args):
        if boom[0]:
            boom[0] = False
            raise RuntimeError("transient XLA failure")
        return real(*args)

    eng._decode_jit = flaky
    eng.start()
    try:
        failed = eng.submit(_prompts((5,))[0])
        assert failed.wait(timeout=60)
        assert failed.finish_reason == "engine_error"
    finally:
        eng.stop()
    stats = eng.stats()
    assert stats["last_error"] == {"type": "RuntimeError",
                                   "age_s": stats["last_error"]["age_s"]}
    assert stats["last_error"]["age_s"] >= 0
    # the failed request's timeline landed in the ring
    d = eng.debug_request(failed.request_id)
    assert d["state"] == "expired"
    text = render_prometheus(eng.metrics.registry)
    assert 'fstpu_request_phase_seconds' in text


def test_engine_tick_error_dumps_postmortem(tiny, tmp_path):
    """The acceptance bar: a faulted engine run produces a complete
    bundle — manifest, events jsonl (with the tick error), and the
    engine provider's stats/config/last-N request timelines."""
    model, params = tiny
    rec = FlightRecorder(dump_dir=str(tmp_path))
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=1, buckets=(8,),
                                    max_new_tokens=2, max_queue=4),
        recorder=rec)
    real = eng._decode_jit
    boom = [True]

    def flaky(*args):
        if boom[0]:
            boom[0] = False
            raise RuntimeError("injected fault")
        return real(*args)

    eng._decode_jit = flaky
    eng.start()
    try:
        failed = eng.submit(_prompts((5,))[0], request_id="victim")
        assert failed.wait(timeout=60)
        assert failed.finish_reason == "engine_error"
        ok = eng.submit(_prompts((5,))[0])
        assert ok.wait(timeout=60)
    finally:
        eng.stop()
    bundles = sorted(os.listdir(tmp_path))
    assert bundles and bundles[0].endswith("engine_tick_error")
    bundle = tmp_path / bundles[0]
    manifest = json.loads((bundle / "manifest.json").read_text())
    assert manifest["reason"] == "engine_tick_error"
    assert manifest["extra"]["error_type"] == "RuntimeError"
    assert sorted(manifest["files"]) == ["engine.json", "events.jsonl"]
    assert not manifest["provider_errors"]
    events = [json.loads(line) for line in
              (bundle / "events.jsonl").read_text().splitlines()]
    assert any(e.get("event") == "serving_tick_error" for e in events)
    assert any(e.get("event") == "metrics_snapshot" for e in events)
    engine_dump = json.loads((bundle / "engine.json").read_text())
    assert engine_dump["stats"]["expired"] >= 1
    assert "EngineConfig" in engine_dump["engine_config"]
    victims = [r for r in engine_dump["requests"]
               if r["request_id"] == "victim"]
    assert victims and victims[0]["state"] == "expired"
    assert victims[0]["events"]             # the full timeline rode along


# ---- flight recorder unit behavior --------------------------------------

def test_flight_recorder_ring_capacity_and_providers(tmp_path):
    clock = iter(float(i) for i in range(10_000))
    rec = FlightRecorder(capacity=8, dump_dir=str(tmp_path),
                         clock=lambda: next(clock))
    for i in range(20):
        rec.record({"event": "tick", "i": i})
    rec.attach("good", lambda: {"b": 2, "a": 1})
    rec.attach("bad", lambda: 1 / 0)
    b1 = rec.dump("first", extra={"k": "v"})
    b2 = rec.dump("first")
    assert os.path.basename(b1) == "dump-0000-first"
    assert os.path.basename(b2) == "dump-0001-first"   # seq, not clobber
    events = [json.loads(line) for line in
              open(os.path.join(b1, "events.jsonl"))]
    assert len(events) == 8                            # bounded ring
    assert [e["i"] for e in events] == list(range(12, 20))
    manifest = json.loads(
        open(os.path.join(b1, "manifest.json")).read())
    assert manifest["files"] == ["events.jsonl", "good.json"]
    assert manifest["provider_errors"]["bad"].startswith(
        "ZeroDivisionError")
    assert manifest["extra"] == {"k": "v"}
    assert json.load(open(os.path.join(b1, "good.json"))) == \
        {"a": 1, "b": 2}


def test_flight_recorder_restart_never_clobbers_prior_bundles(tmp_path):
    """A restarted process (fresh seq counter) must skip past the
    bundles its predecessor left — a crash-restart-crash loop keeps
    EVERY post-mortem."""
    first = FlightRecorder(dump_dir=str(tmp_path))
    b0 = first.dump("crash")
    marker = os.path.join(b0, "manifest.json")
    before = open(marker).read()
    second = FlightRecorder(dump_dir=str(tmp_path))   # "restart"
    second.record({"event": "new_life"})
    b1 = second.dump("crash")
    assert b1 != b0
    assert os.path.basename(b1) == "dump-0001-crash"
    assert open(marker).read() == before              # untouched
    assert sorted(os.listdir(tmp_path)) == ["dump-0000-crash",
                                            "dump-0001-crash"]


def test_flight_recorder_snapshot_rate_limit(tmp_path):
    t = [0.0]
    rec = FlightRecorder(dump_dir=str(tmp_path), clock=lambda: t[0],
                         snapshot_interval_s=10.0)
    reg = get_registry()
    assert rec.snapshot_metrics([reg]) is True
    t[0] = 5.0
    assert rec.snapshot_metrics([reg]) is False        # rate-limited
    assert rec.snapshot_metrics([reg], force=True) is True
    t[0] = 16.0
    assert rec.snapshot_metrics([reg]) is True


def test_flight_recorder_sigterm_chains_previous_handler(tmp_path):
    import signal
    rec = FlightRecorder(dump_dir=str(tmp_path))
    fired = []
    original = signal.getsignal(signal.SIGTERM)
    try:
        signal.signal(signal.SIGTERM, lambda s, f: fired.append(s))
        assert rec.install_sigterm()
        signal.raise_signal(signal.SIGTERM)
        assert fired == [signal.SIGTERM]               # chained, not lost
        assert any(b.endswith("sigterm") for b in os.listdir(tmp_path))
    finally:
        signal.signal(signal.SIGTERM, original)


def test_flight_recorder_sigterm_default_disposition_still_dies(tmp_path):
    """With SIG_DFL as the previous handler, the dump must not turn
    SIGTERM into a no-op: the process dumps, then still terminates."""
    script = r"""
import os, signal, sys
from fengshen_tpu.observability import FlightRecorder
signal.signal(signal.SIGTERM, signal.SIG_DFL)
rec = FlightRecorder(dump_dir=sys.argv[1])
assert rec.install_sigterm()
signal.raise_signal(signal.SIGTERM)
print("UNREACHABLE")           # the re-delivered default must kill us
"""
    out = subprocess.run(
        [sys.executable, "-c", script, str(tmp_path)],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == -15, (out.returncode, out.stdout)
    assert "UNREACHABLE" not in out.stdout
    assert any(b.endswith("sigterm") for b in os.listdir(tmp_path))


def test_flight_recorder_bundle_deterministic_across_hashseed(tmp_path):
    """Same inputs + injected clock => byte-identical bundles, no
    matter the hash seed (the post-mortem diff workflow depends on
    it)."""
    script = r"""
import hashlib, json, os, sys
from fengshen_tpu.observability import FlightRecorder
clock = iter(float(i) / 10 for i in range(1000))
rec = FlightRecorder(capacity=16, dump_dir=sys.argv[1],
                     clock=lambda: next(clock))
for i in range(20):
    rec.record({"event": "tick", "zz": i, "aa": -i, "mm": {"x": 1, "b": 2}})
rec.attach("prov_b", lambda: {"zeta": 1, "alpha": {"q": 3, "a": 4}})
rec.attach("prov_a", lambda: {"rows": [{"m": i, "z": -i} for i in range(5)]})
bundle = rec.dump("determinism", extra={"b": 2, "a": 1})
h = hashlib.sha256()
for name in sorted(os.listdir(bundle)):
    h.update(name.encode())
    h.update(open(os.path.join(bundle, name), "rb").read())
print(h.hexdigest())
"""
    digests = []
    for seed in ("0", "1"):
        out = subprocess.run(
            [sys.executable, "-c", script,
             str(tmp_path / f"seed{seed}")],
            env={**os.environ, "PYTHONHASHSEED": seed,
                 "JAX_PLATFORMS": "cpu"},
            capture_output=True, text=True, cwd=REPO)
        assert out.returncode == 0, out.stderr
        digests.append(out.stdout.strip())
    assert digests[0] == digests[1]


# ---- jsonl sink rotation ------------------------------------------------

def test_jsonl_sink_size_rotation(tmp_path):
    """Opt-in max_bytes rotates path -> path.1 -> path.2; every line
    survives somewhere in the chain, byte-identical format."""
    path = str(tmp_path / "metrics.jsonl")
    sink = JsonlSink(path=path, max_bytes=120, backups=2)
    entries = [{"event": "step", "step": i, "loss": float(i)}
               for i in range(12)]
    for e in entries:
        sink(e)
    assert os.path.exists(path) and os.path.exists(path + ".1")
    assert os.path.getsize(path) <= 120
    kept = []
    for name in (path + ".2", path + ".1", path):
        if os.path.exists(name):
            kept += [json.loads(line) for line in open(name)]
    # the chain holds a contiguous SUFFIX of the stream (oldest file
    # may have been dropped), with the exact original payloads
    assert kept == entries[-len(kept):]
    assert len(kept) >= 6
    # no rotation configured -> single unbounded file, unchanged format
    p2 = str(tmp_path / "plain.jsonl")
    s2 = JsonlSink(path=p2)
    for e in entries:
        s2(e)
    assert [json.loads(line) for line in open(p2)] == entries
    assert not os.path.exists(p2 + ".1")


def test_jsonl_sink_rotation_under_concurrent_writers(tmp_path):
    """ISSUE 11 satellite: two threads logging across rotation
    boundaries — every surviving line parses (no interleaved/corrupt
    writes), no line is lost from the retained window, and every
    backup in the chain is well-formed jsonl. The sink's internal lock
    is what makes the multi-step rotate-then-append atomic; without it
    a racing writer can append to the file mid-rename and lose its
    line."""
    path = str(tmp_path / "metrics.jsonl")
    # small cap + a backup chain deep enough for the WHOLE stream:
    # every line survives somewhere, so lost writes are detectable,
    # not masked by legitimate aging-out (2x100 lines x ~60 B ≈ 12 KB
    # « 64 backups x 256 B + slack)
    sink = JsonlSink(path=path, max_bytes=256, backups=64)
    n_per_thread = 100
    errors = []

    def writer(tag):
        try:
            for i in range(n_per_thread):
                sink({"event": "step", "writer": tag, "i": i,
                      "pad": "x" * (i % 7)})
        except Exception as e:  # noqa: BLE001 — surface in-thread
            errors.append(e)    # failures as test failures

    threads = [threading.Thread(target=writer, args=(t,))
               for t in ("a", "b")]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    kept = []
    chain = [path] + [f"{path}.{i}" for i in range(1, 65)]
    for name in chain:
        if not os.path.exists(name):
            continue
        with open(name) as f:
            for line in f:
                entry = json.loads(line)     # well-formed or it raises
                assert entry["event"] == "step"
                kept.append(entry)
    # zero lost lines: both writers' full sequences are present
    assert len(kept) == 2 * n_per_thread
    for tag in ("a", "b"):
        seq = sorted(e["i"] for e in kept if e["writer"] == tag)
        assert seq == list(range(n_per_thread))


# ---- API surface (stdlib path) ------------------------------------------

def test_debug_endpoints_and_http_latency_stdlib(tiny, tmp_path):
    """GET /debug/requests[/<id>], POST /debug/dump, and the
    fstpu_http_request_seconds{route} histogram on the stdlib server."""
    import urllib.error
    import urllib.request

    from fengshen_tpu.api.main import (PipelineConfig, ServerConfig,
                                       build_stdlib_server,
                                       start_continuous_engine)

    pipe = _gen_pipeline(tiny, max_new_tokens=4)
    rec = FlightRecorder(dump_dir=str(tmp_path))
    engine = start_continuous_engine(
        pipe, {"num_slots": 2, "buckets": (8,), "max_queue": 8},
        recorder=rec)
    server = build_stdlib_server(
        ServerConfig(host="127.0.0.1", port=0, engine="continuous"),
        PipelineConfig(task="text_generation"), pipeline=pipe,
        engine=engine, recorder=rec)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{port}"
    try:
        req = urllib.request.Request(
            f"{base}/api/text_generation",
            data=json.dumps({"input_text": "5 7 9"}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            rid = json.loads(r.read())["request_id"]
        with urllib.request.urlopen(f"{base}/debug/requests",
                                    timeout=10) as r:
            listing = json.loads(r.read())
        assert any(e["request_id"] == rid for e in listing["recent"])
        with urllib.request.urlopen(f"{base}/debug/requests/{rid}",
                                    timeout=10) as r:
            d = json.loads(r.read())
        assert d["state"] == "finished"
        _phase_sum_matches(d)
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"{base}/debug/requests/nope",
                                   timeout=10)
        assert exc.value.code == 404
        dump_req = urllib.request.Request(f"{base}/debug/dump",
                                          data=b"", method="POST")
        with urllib.request.urlopen(dump_req, timeout=10) as r:
            bundle = json.loads(r.read())["bundle"]
        assert os.path.exists(os.path.join(bundle, "manifest.json"))
        engine_dump = json.loads(
            open(os.path.join(bundle, "engine.json")).read())
        assert any(q["request_id"] == rid
                   for q in engine_dump["requests"])
        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as r:
            text = r.read().decode()
        assert 'fstpu_http_request_seconds_bucket' in text
        assert 'route="/debug/requests"' in text
        assert 'fstpu_request_phase_seconds_bucket' in text
        assert 'phase="decode"' in text
    finally:
        server.shutdown()
        engine.stop()


def test_debug_endpoints_simple_engine(tiny):
    """The simple path keeps the payload shape (empty lifecycle) and
    404s /debug/dump without a recorder."""
    import urllib.error
    import urllib.request

    from fengshen_tpu.api.main import (PipelineConfig, ServerConfig,
                                       build_stdlib_server)

    pipe = _gen_pipeline(tiny, max_new_tokens=2)
    server = build_stdlib_server(
        ServerConfig(host="127.0.0.1", port=0),
        PipelineConfig(task="text_generation"), pipeline=pipe)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/requests",
                timeout=10) as r:
            assert json.loads(r.read()) == {
                "in_flight": [], "recent": [], "debug_ring": 0}
        dump_req = urllib.request.Request(
            f"http://127.0.0.1:{port}/debug/dump", data=b"",
            method="POST")
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(dump_req, timeout=10)
        assert exc.value.code == 404
    finally:
        server.shutdown()


# ---- trainer wiring -----------------------------------------------------

def test_trainer_rewind_dumps_postmortem(tmp_path):
    """A FaultPlan-driven step-guard rewind leaves a post-mortem bundle
    under <root>/flightrec whose event ring holds the step-stats
    entries (tokens/s, mfu, goodput) leading into the divergence."""
    import argparse

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.resilience import FaultPlan
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.trainer.modules import CausalLMModule
    from fengshen_tpu.utils import UniversalCheckpoint

    parser = argparse.ArgumentParser()
    add_module_args(parser)
    add_trainer_args(parser)
    UniversalDataModule.add_data_specific_args(parser)
    UniversalCheckpoint.add_argparse_args(parser)
    ck = tmp_path / "ck"
    args = parser.parse_args(
        ["--train_batchsize", "4", "--learning_rate", "1e-3",
         "--warmup_steps", "1", "--log_every_n_steps", "1",
         "--default_root_dir", str(tmp_path),
         "--max_steps", "4", "--every_n_train_steps", "2",
         "--max_consecutive_bad_steps", "2",
         "--save_ckpt_path", str(ck), "--load_ckpt_path", str(ck)])
    cfg = LlamaConfig(vocab_size=64, hidden_size=16,
                      intermediate_size=32, num_hidden_layers=1,
                      num_attention_heads=2,
                      max_position_embeddings=32, dtype="float32")
    rng = np.random.RandomState(0)
    rows = [{"input_ids": rng.randint(0, 63, 16).tolist()}
            for _ in range(64)]

    class DS:
        def __len__(self):
            return len(rows)

        def __getitem__(self, i):
            return rows[i]

    module = CausalLMModule(args, LlamaForCausalLM(cfg), cfg)
    dm = UniversalDataModule(args=args, datasets={"train": DS()})
    trainer = Trainer(args)
    trainer.callbacks.append(UniversalCheckpoint(args))
    FaultPlan(nan_loss_at_steps={1, 2}).install(trainer)
    try:
        state = trainer.fit(module, dm)
    finally:
        # don't leak the trainer's mesh into later sharding-sensitive
        # tests (the documented subset-ordering flake)
        from fengshen_tpu.parallel import set_mesh
        set_mesh(None)
    assert int(state.step) == 4

    flight = tmp_path / "flightrec"
    bundles = sorted(os.listdir(flight))
    assert bundles and bundles[0].endswith("rewind")
    bundle = flight / bundles[0]
    manifest = json.loads((bundle / "manifest.json").read_text())
    assert manifest["reason"] == "rewind"
    assert manifest["extra"]["from_step"] == 3
    assert manifest["extra"]["to_step"] == 2
    events = [json.loads(line) for line in
              (bundle / "events.jsonl").read_text().splitlines()]
    # the last window of step stats rode along in the ring
    steps = [e for e in events if "tokens_per_sec" in e]
    assert steps and all("mfu" in e and "goodput" in e for e in steps)
    assert any(e.get("event") == "rewind" for e in events)
    assert any(e.get("event") == "metrics_snapshot" for e in events)
    trainer_dump = json.loads((bundle / "trainer.json").read_text())
    assert trainer_dump["step"] == 2
    assert trainer_dump["args"]["max_consecutive_bad_steps"] == 2


# ---- benchdiff ----------------------------------------------------------

def test_benchdiff_classifies_repo_trajectory(capsys):
    """`make benchdiff` over the checked-in BENCH_r01..r05 rounds:
    deterministic classification, no crash on wedged (parsed: null)
    rounds."""
    from fengshen_tpu.observability import benchdiff

    assert benchdiff.main(["--dir", REPO]) == 0
    out1 = capsys.readouterr().out
    assert benchdiff.main(["--dir", REPO]) == 0
    out2 = capsys.readouterr().out
    assert out1 == out2
    assert "verdict:" in out1
    for n in range(1, 6):
        assert f"r{n:02d} " in out1


def _write_round(directory, n, rows, rc=0, tail=""):
    payload = {"n": n, "cmd": "bench", "rc": rc, "tail": tail,
               "parsed": rows}
    with open(os.path.join(directory, f"BENCH_r{n:02d}.json"),
              "w") as f:
        json.dump(payload, f)


def test_benchdiff_flags_regressions(tmp_path):
    from fengshen_tpu.observability import benchdiff

    d = str(tmp_path)
    _write_round(d, 1, [{"metric": "tps", "value": 100.0,
                         "unit": "tok/s", "vs_baseline": 1.0}])
    _write_round(d, 2, None, rc=1,
                 tail="bench watchdog: accelerator unresponsive, "
                      "aborting\n")
    _write_round(d, 3, [{"metric": "tps", "value": 50.0,
                         "unit": "tok/s", "vs_baseline": 0.5},
                        {"metric": "mfu_row", "value": 0.5,
                         "unit": "mfu", "vs_baseline": 1.0}])
    _write_round(d, 4, [{"metric": "tps", "value": 49.0,
                         "unit": "tok/s", "vs_baseline": 0.5},
                        {"metric": "mfu_row", "value": 0.8,
                         "unit": "mfu", "vs_baseline": 1.6},
                        {"metric": "cpu_row", "value": 10.0,
                         "degraded": True, "unit": "tok/s",
                         "vs_baseline": 0.1}])
    _write_round(d, 5, [{"metric": "cpu_row", "value": 9.0,
                         "unit": "tok/s", "vs_baseline": 0.1},
                        {"metric": "zero_row", "value": 0.0,
                         "unit": "rate", "vs_baseline": 0.0}])
    _write_round(d, 6, [{"metric": "zero_row", "value": 0.4,
                         "unit": "rate", "vs_baseline": 1.0}])
    report = benchdiff.diff_rounds(benchdiff.load_rounds(d),
                                   threshold=0.15)
    assert report["verdict"] == "REGRESSED"
    by_key = {(c["metric"], c["round"]): c
              for c in report["comparisons"]}
    # r03 tps regressed vs r01 (the wedged r02 is skipped over)
    assert by_key[("tps", 3)]["status"] == "regression"
    assert by_key[("tps", 3)]["prev_round"] == 1
    assert by_key[("tps", 4)]["status"] == "flat"
    assert by_key[("mfu_row", 4)]["status"] == "improvement"
    # degraded vs non-degraded must never read as a regression
    assert by_key[("cpu_row", 5)]["status"] == "incomparable"
    # a move off a zero-valued metric is a change, never "flat +0%"
    assert by_key[("zero_row", 6)]["status"] == "improvement"
    assert by_key[("zero_row", 6)]["delta_pct"] is None
    assert report["counts"] == {"ok": 5, "wedged": 1, "failed": 0}
    # --strict exits 3 on REGRESSED
    assert benchdiff.main(["--dir", d, "--strict"]) == 3
    assert benchdiff.main(["--dir", d]) == 0
    # empty dir exits 2
    empty = tmp_path / "empty"
    empty.mkdir()
    assert benchdiff.main(["--dir", str(empty)]) == 2


def test_benchdiff_never_compares_across_placements(tmp_path):
    """ISSUE 9 satellite: offload rows carry their resolved placement
    ({"offload", "memory_kind"}, docs/offload.md) and rows at
    different placements are INCOMPARABLE — an offloaded-update rung
    slowing down relative to a device-resident rung is a placement
    change, not a perf regression."""
    from fengshen_tpu.observability import benchdiff

    d = str(tmp_path)
    _write_round(d, 1, [{"metric": "off_tps", "value": 100.0,
                         "unit": "tok/s", "vs_baseline": 1.0}])
    # same metric, now measured at an offload placement: incomparable
    _write_round(d, 2, [{"metric": "off_tps", "value": 40.0,
                         "unit": "tok/s", "vs_baseline": 0.4,
                         "offload": "opt",
                         "memory_kind": "unpinned_host"}])
    # same placement again: comparable, and this IS a regression
    _write_round(d, 3, [{"metric": "off_tps", "value": 30.0,
                         "unit": "tok/s", "vs_baseline": 0.3,
                         "offload": "opt",
                         "memory_kind": "unpinned_host"}])
    # same level on a DIFFERENT memory kind: incomparable again
    _write_round(d, 4, [{"metric": "off_tps", "value": 60.0,
                         "unit": "tok/s", "vs_baseline": 0.6,
                         "offload": "opt",
                         "memory_kind": "pinned_host"}])
    report = benchdiff.diff_rounds(benchdiff.load_rounds(d),
                                   threshold=0.15)
    by_round = {c["round"]: c for c in report["comparisons"]}
    assert by_round[2]["status"] == "incomparable"
    assert by_round[2]["delta_pct"] is None
    assert by_round[3]["status"] == "regression"
    assert by_round[4]["status"] == "incomparable"


def test_benchdiff_never_compares_across_replica_counts(tmp_path):
    """ISSUE 10 satellite: fleet rows carry their replica count
    (docs/fleet.md) and rows at different N are INCOMPARABLE — a
    2-replica aggregate dropping below a 3-replica one is a deployment
    change, not a perf regression. Same N still diffs normally."""
    from fengshen_tpu.observability import benchdiff

    d = str(tmp_path)
    base = {"metric": "fleet_router_tokens_per_sec", "unit": "tok/s"}
    _write_round(d, 1, [dict(base, value=300.0, vs_baseline=2.3,
                             replicas=3)])
    # fewer replicas: lower aggregate is a different deployment
    _write_round(d, 2, [dict(base, value=210.0, vs_baseline=1.6,
                             replicas=2)])
    # back at N=3: still incomparable (prev round carried N=2)
    _write_round(d, 3, [dict(base, value=290.0, vs_baseline=2.2,
                             replicas=3)])
    # same N as the previous round: compares normally — a regression
    _write_round(d, 4, [dict(base, value=150.0, vs_baseline=1.1,
                             replicas=3)])
    report = benchdiff.diff_rounds(benchdiff.load_rounds(d),
                                   threshold=0.15)
    by_round = {c["round"]: c for c in report["comparisons"]}
    assert by_round[2]["status"] == "incomparable"
    assert by_round[2]["delta_pct"] is None
    assert by_round[3]["status"] == "incomparable"  # vs round 2 (N=2)
    assert by_round[4]["status"] == "regression"
    assert report["verdict"] == "REGRESSED"


def test_benchdiff_never_compares_across_phase_topologies(tmp_path):
    """ISSUE 13 satellite: disaggregated rows carry their phase
    topology (docs/disaggregation.md) and rows at different topologies
    are INCOMPARABLE even at equal replica counts — a
    prefill=1,decode=2 split measuring below a homogeneous 3-replica
    fleet is a deployment change, not a perf regression. The same
    topology still diffs normally."""
    from fengshen_tpu.observability import benchdiff

    d = str(tmp_path)
    base = {"metric": "disagg_tokens_per_sec", "unit": "tok/s",
            "replicas": 3}
    _write_round(d, 1, [dict(base, value=300.0, vs_baseline=1.4,
                             topology="prefill=1,decode=2")])
    # same N, homogeneous topology: a different deployment
    _write_round(d, 2, [dict(base, value=220.0, vs_baseline=1.0,
                             topology="homogeneous")])
    # back at the split: still incomparable (prev was homogeneous)
    _write_round(d, 3, [dict(base, value=290.0, vs_baseline=1.35,
                             topology="prefill=1,decode=2")])
    # same topology as the previous round: a real regression
    _write_round(d, 4, [dict(base, value=150.0, vs_baseline=0.7,
                             topology="prefill=1,decode=2")])
    report = benchdiff.diff_rounds(benchdiff.load_rounds(d),
                                   threshold=0.15)
    by_round = {c["round"]: c for c in report["comparisons"]}
    assert by_round[2]["status"] == "incomparable"
    assert by_round[2]["delta_pct"] is None
    assert by_round[3]["status"] == "incomparable"
    assert by_round[4]["status"] == "regression"


def test_benchdiff_report_deterministic_across_hashseed(tmp_path):
    d = str(tmp_path)
    _write_round(d, 1, [{"metric": f"m{i}", "value": float(i + 1),
                         "unit": "u", "vs_baseline": 1.0}
                        for i in range(8)])
    _write_round(d, 2, [{"metric": f"m{i}", "value": float(i + 2),
                         "unit": "u", "vs_baseline": 1.0}
                        for i in range(8)])
    outs = []
    for seed in ("0", "1"):
        out = subprocess.run(
            [sys.executable, "-m",
             "fengshen_tpu.observability.benchdiff", "--dir", d,
             "--json"],
            env={**os.environ, "PYTHONHASHSEED": seed,
                 "JAX_PLATFORMS": "cpu"},
            capture_output=True, text=True, cwd=REPO)
        assert out.returncode == 0, out.stderr
        outs.append(out.stdout)
    assert outs[0] == outs[1]
