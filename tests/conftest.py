"""Test config: run everything on a virtual 8-device CPU mesh.

Must set env before jax initialises its backends — conftest is imported
before any test module, so this is the earliest reliable hook.
"""

import os

if "--xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8"
                               ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

# The environment may pre-register an accelerator plugin via sitecustomize
# and force jax_platforms programmatically; override it back to CPU before
# any backend initialises so tests always run on the virtual 8-device mesh.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def mesh8():
    """2x2x1x2 (data, fsdp, sequence, tensor) mesh on 8 CPU devices."""
    from fengshen_tpu.parallel import MeshConfig, make_mesh, set_mesh
    mesh = make_mesh(MeshConfig(data=2, fsdp=2, sequence=1, tensor=2))
    set_mesh(mesh)
    yield mesh
    set_mesh(None)


@pytest.fixture
def mesh_seq4():
    """1x1x4x2 mesh exercising sequence parallelism."""
    from fengshen_tpu.parallel import MeshConfig, make_mesh, set_mesh
    mesh = make_mesh(MeshConfig(data=1, fsdp=1, sequence=4, tensor=2))
    set_mesh(mesh)
    yield mesh
    set_mesh(None)
