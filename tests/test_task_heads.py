"""Task-head coverage tests: every BERT-like family exposes the full
ForSequenceClassification / ForTokenClassification / ForQuestionAnswering /
ForMultipleChoice set (VERDICT r1 missing #6), with HF torch parity for
the bert family and shape/grad smoke tests for all."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # full-fit/e2e lane: run with -m slow or no -m filter


def _family(name):
    import importlib
    models = importlib.import_module(f"fengshen_tpu.models.{name}")
    return models


FAMILIES = [
    # (module, config factory kwargs, class prefix, extra call kwargs)
    ("bert", "BertConfig", "Bert"),
    ("megatron_bert", "MegatronBertConfig", "MegatronBert"),
    ("deberta_v2", "DebertaV2Config", "DebertaV2"),
    ("longformer", "LongformerConfig", "Longformer"),
    ("roformer", "RoFormerConfig", "RoFormer"),
    ("albert", "AlbertConfig", "Albert"),
    ("zen", "ZenConfig", "Zen"),
]


@pytest.mark.parametrize("fam,cfg_name,prefix", FAMILIES)
def test_token_classification_and_qa_shapes(fam, cfg_name, prefix):
    mod = _family(fam)
    cfg = getattr(mod, cfg_name).small_test_config(dtype="float32")
    ids = jnp.asarray(np.random.RandomState(0).randint(5, 100, (2, 16)),
                      jnp.int32)

    tok_cls_cls = getattr(mod, f"{prefix}ForTokenClassification")
    if "num_labels" in {f.name for f in
                        __import__("dataclasses").fields(tok_cls_cls)}:
        tok_cls = tok_cls_cls(cfg, num_labels=5)
    else:  # round-1 classes read num_labels from the config
        import dataclasses as _dc
        tok_cls = tok_cls_cls(_dc.replace(cfg, num_labels=5))
    params = tok_cls.init(jax.random.PRNGKey(0), ids)["params"]
    logits = tok_cls.apply({"params": params}, ids)
    assert logits.shape == (2, 16, 5)
    assert np.isfinite(np.asarray(logits)).all()

    qa = getattr(mod, f"{prefix}ForQuestionAnswering")(cfg)
    params = qa.init(jax.random.PRNGKey(0), ids)["params"]
    start, end = qa.apply({"params": params}, ids)
    assert start.shape == (2, 16) and end.shape == (2, 16)

    # grads flow end-to-end
    def loss(p):
        s, e = qa.apply({"params": p}, ids)
        return (s ** 2).mean() + (e ** 2).mean()
    g = jax.grad(loss)(params)
    assert np.isfinite(float(jax.tree_util.tree_reduce(
        lambda a, b: a + jnp.abs(b).sum(), g, 0.0)))


@pytest.mark.parametrize("fam,cfg_name,prefix", FAMILIES)
def test_multiple_choice_shapes(fam, cfg_name, prefix):
    mod = _family(fam)
    cfg = getattr(mod, cfg_name).small_test_config(dtype="float32")
    rng = np.random.RandomState(1)
    ids = jnp.asarray(rng.randint(5, 100, (2, 3, 12)), jnp.int32)
    mask = jnp.ones((2, 3, 12), jnp.int32)

    mc = getattr(mod, f"{prefix}ForMultipleChoice")(cfg)
    params = mc.init(jax.random.PRNGKey(0), ids,
                     attention_mask=mask)["params"]
    scores = mc.apply({"params": params}, ids, attention_mask=mask)
    assert scores.shape == (2, 3)
    assert np.isfinite(np.asarray(scores)).all()


def test_bert_token_classification_hf_parity():
    torch = pytest.importorskip("torch")
    import transformers

    from fengshen_tpu.models.bert import (BertConfig,
                                          BertForTokenClassification)
    from fengshen_tpu.models.bert.convert import torch_to_params
    from fengshen_tpu.utils.convert_common import make_helpers

    hf_cfg = transformers.BertConfig(
        vocab_size=100, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=32, num_labels=5)
    torch.manual_seed(0)
    tm = transformers.BertForTokenClassification(hf_cfg).eval()

    cfg = BertConfig(vocab_size=100, hidden_size=32, num_hidden_layers=2,
                     num_attention_heads=4, intermediate_size=64,
                     max_position_embeddings=32, dtype="float32",
                     hidden_dropout_prob=0.0)
    sd = tm.state_dict()
    _, lin, _ = make_helpers(sd)
    params = {"bert": torch_to_params(sd, cfg)["bert"],
              "classifier": lin("classifier")}
    ids = np.array([[2, 17, 9, 42, 7, 99, 1, 5]], np.int32)
    ours = BertForTokenClassification(cfg, num_labels=5).apply(
        {"params": params}, jnp.asarray(ids))
    with torch.no_grad():
        ref = tm(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    np.testing.assert_allclose(np.asarray(ours), ref, atol=2e-3)


def test_longformer_mc_with_global_mask():
    from fengshen_tpu.models.longformer import (LongformerConfig,
                                                LongformerForMultipleChoice)
    cfg = LongformerConfig.small_test_config(dtype="float32")
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(5, 100, (2, 2, 16)), jnp.int32)
    gmask = jnp.zeros((2, 2, 16), jnp.int32).at[:, :, 0].set(1)
    mc = LongformerForMultipleChoice(cfg)
    params = mc.init(jax.random.PRNGKey(0), ids,
                     global_attention_mask=gmask)["params"]
    scores = mc.apply({"params": params}, ids,
                      global_attention_mask=gmask)
    assert scores.shape == (2, 2)
