"""`make serve-bench-fleet` harness guard (ISSUE 10): the fleet bench
must emit its one BENCH-schema JSON line — with the replica count in
the row, benchdiff's comparison identity — and its kill rung must
finish with zero failed requests.

The fast lane runs the harness in FAKE mode: in-process stdlib replica
servers with a deterministic token function and a per-token sleep
standing in for decode, so the whole three-phase flow (one replica →
N replicas → kill-one-mid-run) exercises the REAL router, transport,
retry, and kill paths in a couple of seconds without a model. The
≥2x-at-3-replicas acceptance number comes from the real-subprocess
mode on the default weight-memory-bound shape — slow lane.
"""

import io
import json
import os
from contextlib import redirect_stdout

import pytest

FAKE = {"FLEET_BENCH_FAKE": "1", "FLEET_BENCH_REPLICAS": "3",
        "FLEET_BENCH_SLOTS": "2", "FLEET_BENCH_REQUESTS": "24",
        "FLEET_BENCH_NEW_TOKENS": "16",
        "FLEET_BENCH_FAKE_TOKEN_S": "0.003"}


def _run(monkeypatch, env: dict, base: dict = FAKE) -> dict:
    from fengshen_tpu.fleet import bench

    for key in list(os.environ):
        if key.startswith(("FLEET_BENCH_", "BENCH_DEGRADED")):
            monkeypatch.delenv(key)
    for key, val in {**base, **env}.items():
        monkeypatch.setenv(key, val)
    out = io.StringIO()
    with redirect_stdout(out):
        bench.main([])
    lines = [l for l in out.getvalue().splitlines()
             if l.startswith("{")]
    assert lines, out.getvalue()
    return json.loads(lines[-1])


def test_fleet_bench_fake_schema_and_kill_rung(monkeypatch):
    row = _run(monkeypatch, {})
    assert set(row) >= {"metric", "value", "unit", "vs_baseline",
                        "replicas", "kill", "tokens_per_sec_1",
                        "requests", "fake"}
    assert row["metric"] == "fleet_router_tokens_per_sec"
    assert row["unit"] == "tokens/s"
    assert row["value"] > 0 and row["tokens_per_sec_1"] > 0
    # the comparison identity benchdiff keys on
    assert row["replicas"] == 3
    assert row["fake"] is True and row["backend"] == "fake"
    # no request may fail in ANY phase; N-replica outputs must equal
    # the single-replica outputs (deterministic fake decode)
    assert row["failed"] == 0
    assert row["token_identical_n_vs_1"] is True
    # the kill rung: one replica dies mid-run, zero failed requests,
    # outputs identical to the un-killed run, and the recovery cost is
    # visible as retries
    kill = row["kill"]
    assert kill["enabled"] is True
    assert kill["failed"] == 0
    assert kill["completed"] == row["requests"]
    assert kill["token_identical"] is True
    assert kill["retries"] >= 1
    # fake decode is sleep-bound, so 3 replicas over 1 is a real
    # capacity ratio even in the fast lane (loose bar: timing)
    assert row["vs_baseline"] >= 1.3
    assert "degraded" not in row


def test_fleet_bench_kill_rung_disabled(monkeypatch):
    row = _run(monkeypatch, {"FLEET_BENCH_KILL": "0"})
    assert row["kill"] == {"enabled": False}
    assert row["failed"] == 0


def test_fleet_bench_degraded_flag(monkeypatch):
    row = _run(monkeypatch, {"BENCH_DEGRADED": "1",
                             "FLEET_BENCH_KILL": "0",
                             "FLEET_BENCH_REQUESTS": "6"})
    assert row["degraded"] is True


@pytest.mark.slow
def test_fleet_bench_real_default_shape_2x_and_zero_failed(monkeypatch):
    """The acceptance bars (ISSUE 10) on the real path: 3 replica
    subprocesses (random-init llama, weight-memory-bound shape),
    aggregate tokens/s ≥ 2x one replica, and the SIGKILL-one-mid-run
    rung completes every request with zero failures, token-identical
    to the un-killed run. ~3-4 min on CPU."""
    row = _run(monkeypatch, {"FLEET_BENCH_BASE_PORT": "8390"}, base={})
    assert row["fake"] is False
    assert row["replicas"] == 3
    assert row["vs_baseline"] >= 2.0, row
    assert row["failed"] == 0
    assert row["kill"]["enabled"] is True
    assert row["kill"]["failed"] == 0
    assert row["kill"]["completed"] == row["requests"]
    assert row["kill"]["token_identical"] is True, row
