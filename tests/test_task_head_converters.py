"""Importer tests for the task-head families (VERDICT r2 item 3).

Each test builds a torch model with the REFERENCE state-dict naming —
HF towers straight from transformers, head math re-stated inline from the
reference definitions (fengshen/models/{unimc,ubert,uniex}/,
fengshen/models/tagging_models/) — converts with the family's convert.py,
and checks forward parity against the flax model.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")


def _tiny_bert_cfg():
    from transformers import BertConfig as HFBertConfig
    return HFBertConfig(vocab_size=64, hidden_size=32,
                        num_hidden_layers=2, num_attention_heads=4,
                        intermediate_size=64, max_position_embeddings=32,
                        type_vocab_size=2)


def _our_bert_cfg():
    from fengshen_tpu.models.megatron_bert import MegatronBertConfig
    return MegatronBertConfig(
        vocab_size=64, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=32, type_vocab_size=2, dtype="float32")


@pytest.fixture
def ids():
    rng = np.random.RandomState(0)
    return rng.randint(0, 64, (2, 12))


def test_unimc_convert_megatron_backbone(ids):
    """UniMC import path for the published MegatronBERT-1.3B family:
    `bert.` attr prefix + MegatronBertForMaskedLM inside, Lightning
    `model.` wrapper on top (reference: modeling_unimc.py:297-310)."""
    import jax.numpy as jnp
    from transformers import MegatronBertConfig as HFCfg
    from transformers import MegatronBertForMaskedLM as HFMLM

    from fengshen_tpu.models.unimc.convert import torch_to_params
    from fengshen_tpu.models.unimc.modeling_unimc import UniMCModel

    hf_cfg = HFCfg(vocab_size=64, hidden_size=32, num_hidden_layers=2,
                   num_attention_heads=4, intermediate_size=64,
                   max_position_embeddings=32, type_vocab_size=2)
    torch.manual_seed(0)
    tm = HFMLM(hf_cfg).eval()
    sd = {f"model.bert.{k}": v for k, v in tm.state_dict().items()}

    cfg = _our_bert_cfg()
    params = torch_to_params(sd, cfg)
    model = UniMCModel(cfg, yes_token_id=3)
    opts = np.asarray([[1, 4], [2, 6]])
    scores = model.apply({"params": params}, jnp.asarray(ids),
                         option_positions=jnp.asarray(opts))

    with torch.no_grad():
        logits = tm(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    ref = np.take_along_axis(logits, opts[..., None].repeat(64, -1),
                             axis=1)[..., 3]
    np.testing.assert_allclose(np.asarray(scores), ref, atol=2e-4)


def test_ubert_convert_forward_parity(ids):
    """Reference UbertModel head (modeling_ubert.py:257-300): GELU
    query/key projections + [d+1, 1, d+1] biaffine over a plain Bert
    tower."""
    import jax.numpy as jnp
    from transformers import BertModel as HFBert

    from fengshen_tpu.models.ubert.convert import torch_to_params
    from fengshen_tpu.models.ubert.modeling_ubert import UbertModel

    torch.manual_seed(1)
    tower = HFBert(_tiny_bert_cfg()).eval()
    d = 8
    q = torch.nn.Linear(32, d)
    k = torch.nn.Linear(32, d)
    U = torch.randn(d + 1, 1, d + 1)
    sd = {f"bert.{key}": v for key, v in tower.state_dict().items()}
    for name, lin_mod in (("query_layer.0", q), ("key_layer.0", k)):
        sd[f"{name}.weight"] = lin_mod.weight
        sd[f"{name}.bias"] = lin_mod.bias
    sd["biaffine_query_key_cls.U"] = U

    cfg = _our_bert_cfg()
    params = torch_to_params(sd, cfg)
    model = UbertModel(cfg, biaffine_size=d, backbone_type="bert")
    ours = model.apply({"params": params}, jnp.asarray(ids))

    with torch.no_grad():
        hidden = tower(torch.tensor(ids, dtype=torch.long)
                       ).last_hidden_state
        gelu = torch.nn.GELU()
        x = gelu(q(hidden))
        y = gelu(k(hidden))
        x = torch.cat([x, torch.ones_like(x[..., :1])], -1)
        y = torch.cat([y, torch.ones_like(y[..., :1])], -1)
        span = torch.einsum("bxi,ioj,byj->bxyo", x, U, y)[..., 0]
        ref = torch.sigmoid(span).numpy()
    np.testing.assert_allclose(np.asarray(ours), ref, atol=2e-4)


def test_uniex_convert_forward_parity(ids):
    """Reference UniEX head (modeling_uniex.py:858-900): three GELU MLPs
    + [T, T, T] triaffine; our bias-augmented U embeds it at [:T, :, :T]."""
    import jax.numpy as jnp
    from transformers import BertModel as HFBert

    from fengshen_tpu.models.uniex.convert import torch_to_params
    from fengshen_tpu.models.uniex.modeling_uniex import UniEXBertModel

    torch.manual_seed(2)
    tower = HFBert(_tiny_bert_cfg()).eval()
    d = 8
    mlps = {n: torch.nn.Linear(32, d)
            for n in ("mlp_start", "mlp_end", "mlp_cls")}
    W = torch.randn(d, d, d)
    sd = {f"bert.{key}": v for key, v in tower.state_dict().items()}
    for n, m in mlps.items():
        sd[f"{n}.mlp.0.weight"] = m.weight
        sd[f"{n}.mlp.0.bias"] = m.bias
    sd["triaffine.weight"] = W

    cfg = _our_bert_cfg()
    params = torch_to_params(sd, cfg)
    model = UniEXBertModel(cfg, biaffine_size=d, backbone_type="bert")
    tpos = np.asarray([[1, 3], [2, 5]])
    ours = model.apply({"params": params}, jnp.asarray(ids),
                       jnp.asarray(tpos))

    with torch.no_grad():
        hidden = tower(torch.tensor(ids, dtype=torch.long)
                       ).last_hidden_state
        gelu = torch.nn.GELU()
        start = gelu(mlps["mlp_start"](hidden))
        end = gelu(mlps["mlp_end"](hidden))
        th = torch.gather(hidden, 1, torch.tensor(
            tpos[..., None].repeat(32, -1), dtype=torch.long))
        typ = gelu(mlps["mlp_cls"](th))
        span = torch.einsum("bxi,ioj,byj->bxyo", start, W, end)
        logits = torch.einsum("bxyo,bzo->bxyz", span, typ)
        ref = torch.sigmoid(logits).permute(0, 3, 1, 2).numpy()
    np.testing.assert_allclose(np.asarray(ours), ref, atol=2e-4)


def test_tcbert_convert_forward_parity(ids):
    """Reference TCBert (modeling_tcbert.py:203-233): full ForMaskedLM
    under `bert.` + `linear_classifier` on the [CLS] hidden state."""
    import jax.numpy as jnp
    from transformers import BertForMaskedLM as HFMLM

    from fengshen_tpu.models.tcbert.convert import torch_to_params
    from fengshen_tpu.models.tcbert.modeling_tcbert import TCBertModel

    torch.manual_seed(3)
    tm = HFMLM(_tiny_bert_cfg()).eval()
    clf = torch.nn.Linear(32, 5)
    sd = {f"bert.{k}": v for k, v in tm.state_dict().items()}
    sd["linear_classifier.weight"] = clf.weight
    sd["linear_classifier.bias"] = clf.bias

    cfg = _our_bert_cfg()
    params = torch_to_params(sd, cfg)
    model = TCBertModel(cfg, backbone_type="bert", num_labels=5)
    mlm_ours, cls_ours = model.apply({"params": params}, jnp.asarray(ids))

    with torch.no_grad():
        out = tm(torch.tensor(ids, dtype=torch.long),
                 output_hidden_states=True)
        mlm_ref = out.logits.numpy()
        cls_ref = clf(out.hidden_states[-1][:, 0]).numpy()
    np.testing.assert_allclose(np.asarray(mlm_ours), mlm_ref, atol=2e-4)
    np.testing.assert_allclose(np.asarray(cls_ours), cls_ref, atol=2e-4)


def test_tagging_linear_and_crf_convert(ids):
    """BertLinear + BertCrf: classifier mapping and verbatim CRF
    transition tensors (reference: layers/crf.py:32-36)."""
    import jax.numpy as jnp
    from transformers import BertModel as HFBert

    from fengshen_tpu.models.tagging.convert import torch_to_params
    from fengshen_tpu.models.tagging.modeling_tagging import (BertCrf,
                                                              BertLinear)

    torch.manual_seed(4)
    tower = HFBert(_tiny_bert_cfg()).eval()
    L = 5
    clf = torch.nn.Linear(32, L)
    sd = {f"bert.{k}": v for k, v in tower.state_dict().items()}
    sd["classifier.weight"] = clf.weight
    sd["classifier.bias"] = clf.bias

    cfg = _our_bert_cfg()
    params = torch_to_params(sd, cfg, head="linear")
    model = BertLinear(cfg, num_labels=L, backbone_type="bert")
    ours = model.apply({"params": params}, jnp.asarray(ids))
    with torch.no_grad():
        hidden = tower(torch.tensor(ids, dtype=torch.long)
                       ).last_hidden_state
        ref = clf(hidden).numpy()
    np.testing.assert_allclose(np.asarray(ours), ref, atol=2e-4)

    sd["crf.start_transitions"] = torch.randn(L)
    sd["crf.end_transitions"] = torch.randn(L)
    sd["crf.transitions"] = torch.randn(L, L)
    params = torch_to_params(sd, cfg, head="crf")
    crf_model = BertCrf(cfg, num_labels=L, backbone_type="bert")
    logits = crf_model.apply({"params": params}, jnp.asarray(ids))
    assert logits.shape == (2, 12, L)
    np.testing.assert_allclose(np.asarray(params["crf"]["transitions"]),
                               sd["crf.transitions"].numpy())


def test_tagging_span_convert_forward_parity(ids):
    """BertSpan: PoolerStartLogits/PoolerEndLogits with softmax start
    conditioning at inference (reference: bert_for_tagging.py:140-155,
    layers/linears.py:18-40)."""
    import jax.numpy as jnp
    from transformers import BertModel as HFBert

    from fengshen_tpu.models.tagging.convert import torch_to_params
    from fengshen_tpu.models.tagging.modeling_tagging import BertSpan

    torch.manual_seed(5)
    tower = HFBert(_tiny_bert_cfg()).eval()
    L, H = 5, 32
    start_fc = torch.nn.Linear(H, L)
    dense_0 = torch.nn.Linear(H + L, H + L)
    lnorm = torch.nn.LayerNorm(H + L)
    dense_1 = torch.nn.Linear(H + L, L)
    sd = {f"bert.{k}": v for k, v in tower.state_dict().items()}
    sd["start_fc.dense.weight"] = start_fc.weight
    sd["start_fc.dense.bias"] = start_fc.bias
    sd["end_fc.dense_0.weight"] = dense_0.weight
    sd["end_fc.dense_0.bias"] = dense_0.bias
    sd["end_fc.LayerNorm.weight"] = lnorm.weight
    sd["end_fc.LayerNorm.bias"] = lnorm.bias
    sd["end_fc.dense_1.weight"] = dense_1.weight
    sd["end_fc.dense_1.bias"] = dense_1.bias

    cfg = _our_bert_cfg()
    params = torch_to_params(sd, cfg, head="span")
    model = BertSpan(cfg, num_labels=L, backbone_type="bert")
    s_ours, e_ours = model.apply({"params": params}, jnp.asarray(ids))

    with torch.no_grad():
        hidden = tower(torch.tensor(ids, dtype=torch.long)
                       ).last_hidden_state
        s_ref = start_fc(hidden)
        soft = torch.softmax(s_ref, -1)
        x = dense_1(lnorm(torch.tanh(dense_0(
            torch.cat([hidden, soft], -1)))))
    np.testing.assert_allclose(np.asarray(s_ours), s_ref.numpy(),
                               atol=2e-4)
    np.testing.assert_allclose(np.asarray(e_ours), x.numpy(), atol=2e-4)


def test_tagging_biaffine_convert_forward_parity(ids):
    """BertBiaffine: 2-layer bi-LSTM + ReLU projections + [d+1, L, d+1]
    biaffine (reference: bert_for_tagging.py:77-96) — exercises the torch
    LSTM → flax OptimizedLSTMCell gate mapping."""
    import jax.numpy as jnp
    from transformers import BertModel as HFBert

    from fengshen_tpu.models.tagging.convert import torch_to_params
    from fengshen_tpu.models.tagging.modeling_tagging import BertBiaffine

    torch.manual_seed(6)
    tower = HFBert(_tiny_bert_cfg()).eval()
    L, H, d = 5, 32, 8
    lstm = torch.nn.LSTM(H, H // 2, num_layers=2, batch_first=True,
                         bidirectional=True).eval()
    start_l = torch.nn.Linear(H, d)
    end_l = torch.nn.Linear(H, d)
    U = torch.randn(d + 1, L, d + 1)
    sd = {f"bert.{k}": v for k, v in tower.state_dict().items()}
    for k, v in lstm.state_dict().items():
        sd[f"lstm.{k}"] = v
    sd["start_layer.0.weight"] = start_l.weight
    sd["start_layer.0.bias"] = start_l.bias
    sd["end_layer.0.weight"] = end_l.weight
    sd["end_layer.0.bias"] = end_l.bias
    sd["biaffne_layer.U"] = U

    cfg = _our_bert_cfg()
    params = torch_to_params(sd, cfg, head="biaffine")
    model = BertBiaffine(cfg, num_labels=L, biaffine_size=d,
                         backbone_type="bert")
    ours = model.apply({"params": params}, jnp.asarray(ids))

    with torch.no_grad():
        hidden = tower(torch.tensor(ids, dtype=torch.long)
                       ).last_hidden_state
        mixed = lstm(hidden)[0]
        relu = torch.nn.ReLU()
        s = relu(start_l(mixed))
        e = relu(end_l(mixed))
        s = torch.cat([s, torch.ones_like(s[..., :1])], -1)
        e = torch.cat([e, torch.ones_like(e[..., :1])], -1)
        ref = torch.einsum("bxi,ioj,byj->bxyo", s, U, e).numpy()
    np.testing.assert_allclose(np.asarray(ours), ref, atol=5e-4)
