"""Training-dynamics parity vs the torch oracle.

The zero-egress environment blocks downloading released checkpoints
(QUALITY_r02.md), so quality parity is established on what CAN be
measured: starting from IDENTICAL weights on IDENTICAL data with the
SAME optimizer hyperparameters, the per-step loss trajectory of this
framework must track torch's step for step. This subsumes forward parity
(step 0) and extends it to gradients + AdamW update semantics
(optax.adamw == torch.optim.AdamW: decoupled weight decay, bias
correction, eps-after-sqrt).

Mirrors the reference's own verification doctrine of comparable loss
curves (SURVEY.md §4, reference publishes wandb loss curves for Ziya,
fengshen/examples/ziya_llama/README.md:47-48).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

pytestmark = pytest.mark.slow  # full-fit/e2e lane: run with -m slow or no -m filter



torch = pytest.importorskip("torch")
import transformers  # noqa: E402


LR, WD, BETAS, EPS = 1e-3, 0.01, (0.9, 0.999), 1e-8
N_STEPS = 25


def _torch_adamw(model):
    return torch.optim.AdamW(model.parameters(), lr=LR, betas=BETAS,
                             eps=EPS, weight_decay=WD)


def _optax_adamw():
    return optax.adamw(LR, b1=BETAS[0], b2=BETAS[1], eps=EPS,
                       weight_decay=WD)


def test_bert_classifier_loss_curve_matches_torch():
    from fengshen_tpu.models.bert import BertConfig
    from fengshen_tpu.models.bert.convert import torch_to_params
    from fengshen_tpu.models.bert.task_heads import (
        BertForSequenceClassification)
    from fengshen_tpu.utils.convert_common import make_helpers

    hf_cfg = transformers.BertConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=32, num_labels=3,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        classifier_dropout=0.0)
    torch.manual_seed(0)
    tm = transformers.BertForSequenceClassification(hf_cfg).train()

    cfg = BertConfig(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                     num_attention_heads=4, intermediate_size=64,
                     max_position_embeddings=32, dtype="float32",
                     hidden_dropout_prob=0.0,
                     attention_probs_dropout_prob=0.0)
    sd = tm.state_dict()
    _, lin, _ = make_helpers(sd)
    params = {"bert": torch_to_params(sd, cfg)["bert"],
              "classifier": lin("classifier")}
    params = jax.tree_util.tree_map(
        lambda x: jnp.asarray(np.asarray(x), jnp.float32), params)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, 128, (4, 8, 16)).astype(np.int64)  # 4 batches
    labels = rng.randint(0, 3, (4, 8)).astype(np.int64)

    model = BertForSequenceClassification(cfg, num_labels=3)
    tx = _optax_adamw()
    opt_state = tx.init(params)

    @jax.jit
    def step(p, o, ids, labels):
        def loss_fn(p):
            logits = model.apply({"params": p}, ids)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, labels).mean()
        loss, grads = jax.value_and_grad(loss_fn)(p)
        updates, o = tx.update(grads, o, p)
        return optax.apply_updates(p, updates), o, loss

    opt = _torch_adamw(tm)
    torch_losses, jax_losses = [], []
    for i in range(N_STEPS):
        b = i % 4
        out = tm(torch.tensor(ids[b]), labels=torch.tensor(labels[b]))
        opt.zero_grad()
        out.loss.backward()
        opt.step()
        torch_losses.append(float(out.loss.detach()))

        params, opt_state, loss = step(params, opt_state,
                                       jnp.asarray(ids[b], jnp.int32),
                                       jnp.asarray(labels[b], jnp.int32))
        jax_losses.append(float(loss))

    diffs = np.abs(np.array(torch_losses) - np.array(jax_losses))
    print(f"\nBERT-cls loss parity: torch[0]={torch_losses[0]:.4f} "
          f"jax[0]={jax_losses[0]:.4f} torch[-1]={torch_losses[-1]:.4f} "
          f"jax[-1]={jax_losses[-1]:.4f} max|d|={diffs.max():.5f}")
    assert diffs.max() < 5e-3, (torch_losses, jax_losses)
    # the run must actually learn something, or parity is vacuous
    assert torch_losses[-1] < torch_losses[0] - 0.1


def test_llama_causal_lm_loss_curve_matches_torch():
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.models.llama.convert import torch_to_params
    from fengshen_tpu.parallel.cross_entropy import stable_cross_entropy

    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=32, rms_norm_eps=1e-6,
        attn_implementation="eager", tie_word_embeddings=False)
    torch.manual_seed(0)
    tm = transformers.LlamaForCausalLM(hf_cfg).train()

    cfg = LlamaConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=4, max_position_embeddings=32,
                      rms_norm_eps=1e-6, dtype="float32")
    params = jax.tree_util.tree_map(
        lambda x: jnp.asarray(np.asarray(x), jnp.float32),
        torch_to_params(tm.state_dict(), cfg))

    rng = np.random.RandomState(1)
    ids = rng.randint(0, 128, (4, 4, 16)).astype(np.int64)

    model = LlamaForCausalLM(cfg)
    tx = _optax_adamw()
    opt_state = tx.init(params)

    @jax.jit
    def step(p, o, ids):
        def loss_fn(p):
            logits = model.apply({"params": p}, ids)
            return stable_cross_entropy(logits[:, :-1], ids[:, 1:])[0]
        loss, grads = jax.value_and_grad(loss_fn)(p)
        updates, o = tx.update(grads, o, p)
        return optax.apply_updates(p, updates), o, loss

    opt = _torch_adamw(tm)
    torch_losses, jax_losses = [], []
    for i in range(N_STEPS):
        b = i % 4
        t_ids = torch.tensor(ids[b])
        out = tm(t_ids, labels=t_ids)  # HF shifts internally
        opt.zero_grad()
        out.loss.backward()
        opt.step()
        torch_losses.append(float(out.loss.detach()))

        params, opt_state, loss = step(params, opt_state,
                                       jnp.asarray(ids[b], jnp.int32))
        jax_losses.append(float(loss))

    diffs = np.abs(np.array(torch_losses) - np.array(jax_losses))
    print(f"\nLLaMA-lm loss parity: torch[0]={torch_losses[0]:.4f} "
          f"jax[0]={jax_losses[0]:.4f} torch[-1]={torch_losses[-1]:.4f} "
          f"jax[-1]={jax_losses[-1]:.4f} max|d|={diffs.max():.5f}")
    assert diffs.max() < 5e-3, (torch_losses, jax_losses)
    assert torch_losses[-1] < torch_losses[0] - 0.1


def test_t5_seq2seq_loss_curve_matches_torch():
    """Encoder-decoder family: T5ForConditionalGeneration 25-step AdamW
    loss-curve parity vs HF torch (teacher-forced seq2seq CE)."""
    from fengshen_tpu.models.t5 import T5Config, T5ForConditionalGeneration
    from fengshen_tpu.models.t5.convert import torch_to_params

    hf_cfg = transformers.T5Config(
        vocab_size=96, d_model=32, d_kv=8, d_ff=64, num_layers=2,
        num_decoder_layers=2, num_heads=4, relative_attention_num_buckets=8,
        relative_attention_max_distance=16, dropout_rate=0.0,
        feed_forward_proj="relu", tie_word_embeddings=True,
        decoder_start_token_id=0)
    torch.manual_seed(0)
    tm = transformers.T5ForConditionalGeneration(hf_cfg).train()

    cfg = T5Config(vocab_size=96, d_model=32, d_kv=8, d_ff=64, num_layers=2,
                   num_decoder_layers=2, num_heads=4,
                   relative_attention_num_buckets=8,
                   relative_attention_max_distance=16, dropout_rate=0.0,
                   feed_forward_proj="relu", tie_word_embeddings=True,
                   dtype="float32")
    params = jax.tree_util.tree_map(
        lambda x: jnp.asarray(np.asarray(x), jnp.float32),
        torch_to_params(tm.state_dict(), cfg))

    rng = np.random.RandomState(2)
    src = rng.randint(2, 96, (4, 4, 12)).astype(np.int64)
    tgt = rng.randint(2, 96, (4, 4, 8)).astype(np.int64)
    tgt[:, :, -1] = 1  # eos
    dec_in = np.concatenate([np.zeros_like(tgt[:, :, :1]), tgt[:, :, :-1]],
                            axis=-1)

    model = T5ForConditionalGeneration(cfg)
    tx = _optax_adamw()
    opt_state = tx.init(params)

    @jax.jit
    def step(p, o, src_b, dec_b, tgt_b):
        def loss_fn(p):
            logits = model.apply({"params": p}, src_b, dec_b)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, tgt_b).mean()
        loss, grads = jax.value_and_grad(loss_fn)(p)
        updates, o = tx.update(grads, o, p)
        return optax.apply_updates(p, updates), o, loss

    opt = _torch_adamw(tm)
    torch_losses, jax_losses = [], []
    for i in range(N_STEPS):
        b = i % 4
        out = tm(input_ids=torch.tensor(src[b]),
                 labels=torch.tensor(tgt[b]))
        opt.zero_grad()
        out.loss.backward()
        opt.step()
        torch_losses.append(float(out.loss.detach()))

        params, opt_state, loss = step(
            params, opt_state, jnp.asarray(src[b], jnp.int32),
            jnp.asarray(dec_in[b], jnp.int32), jnp.asarray(tgt[b], jnp.int32))
        jax_losses.append(float(loss))

    diffs = np.abs(np.array(torch_losses) - np.array(jax_losses))
    print(f"\nT5-seq2seq loss parity: torch[0]={torch_losses[0]:.4f} "
          f"jax[0]={jax_losses[0]:.4f} torch[-1]={torch_losses[-1]:.4f} "
          f"jax[-1]={jax_losses[-1]:.4f} max|d|={diffs.max():.5f}")
    assert diffs.max() < 5e-3, (torch_losses, jax_losses)
    assert torch_losses[-1] < torch_losses[0] - 0.1


def test_unimc_finetune_loss_curve_matches_torch():
    """Task-head training dynamics (round 3): the full UniMC path —
    imported MegatronBert tower + reference encoding (block-diagonal
    option masks, position restarts) + yes-token option scoring + CE —
    must track a torch program computing the identical loss, step for
    step under AdamW."""
    from fengshen_tpu.models.megatron_bert import MegatronBertConfig
    from fengshen_tpu.models.unimc.convert import torch_to_params
    from fengshen_tpu.models.unimc.modeling_unimc import (UniMCModel,
                                                          collate_unimc)

    hf_cfg = transformers.MegatronBertConfig(
        vocab_size=96, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, type_vocab_size=2,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    torch.manual_seed(3)
    tm = transformers.MegatronBertForMaskedLM(hf_cfg).train()

    cfg = MegatronBertConfig(
        vocab_size=96, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, type_vocab_size=2, dtype="float32",
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    yes_id = 7
    # the unimc converter accepts a raw ForMaskedLM state dict directly
    params = torch_to_params(tm.state_dict(), cfg)
    params = jax.tree_util.tree_map(
        lambda x: jnp.asarray(np.asarray(x), jnp.float32), params)
    model = UniMCModel(cfg, yes_token_id=yes_id)

    # synthetic pre-encoded batches in the shared encoding's format:
    # two options at fixed positions, block-diagonal mask, restarts.
    # (Hand-built so no tokenizer is needed; the REAL encode_unimc output
    # is parity-checked against the torch oracle in
    # test_clue_harness.py::test_unimc_reference_scoring_matches_torch —
    # this test adds the training-dynamics dimension.)
    rng = np.random.RandomState(1)
    S, n_opt = 16, 2
    batches = []
    for _ in range(4):
        enc_rows = []
        for _ in range(4):
            ids = rng.randint(8, 96, S)
            label_idx = [1, 4, 7]  # [CLS] [M] o o [M] o o text...
            att = np.ones((S, S), np.int32)
            att[1:7, 1:7] = 0
            att[1:4, 1:4] = 1
            att[4:7, 4:7] = 1
            pos = [0, 1, 2, 3, 1, 2, 3] + list(range(4, 4 + S - 7))
            tt = [0] + [1] * 7 + [0] * (S - 8)
            ids[label_idx[:-1]] = 5  # mask token id
            label = int(rng.randint(0, n_opt))
            # learnable signal: a text token announces the gold option,
            # so the anti-vacuousness check below has something to learn
            ids[8] = 8 + label
            enc_rows.append({
                "input_ids": ids, "attention_mask": att,
                "token_type_ids": np.asarray(tt),
                "position_ids": np.asarray(pos),
                "option_positions": label_idx[:-1],
                "label": label})
        batches.append(collate_unimc(enc_rows))

    tx = _optax_adamw()
    opt_state = tx.init(params)

    @jax.jit
    def step(p, o, batch):
        def loss_fn(p):
            scores = model.apply(
                {"params": p}, batch["input_ids"],
                attention_mask=batch["attention_mask"],
                token_type_ids=batch["token_type_ids"],
                option_positions=batch["option_positions"],
                position_ids=batch["position_ids"])
            return optax.softmax_cross_entropy_with_integer_labels(
                scores, batch["labels"]).mean()
        loss, grads = jax.value_and_grad(loss_fn)(p)
        updates, o = tx.update(grads, o, p)
        return optax.apply_updates(p, updates), o, loss

    opt = _torch_adamw(tm)
    ce = torch.nn.CrossEntropyLoss()
    torch_losses, jax_losses = [], []
    # this head learns through the tied MLM logits, which moves slowly
    # at the shared LR — run longer so the anti-vacuousness check has
    # teeth; strict parity is asserted over the first N_STEPS, past
    # which the collapsed loss amplifies fp-order noise chaotically
    for i in range(3 * N_STEPS):
        b = batches[i % 4]
        logits = tm(
            torch.tensor(b["input_ids"], dtype=torch.long),
            attention_mask=torch.tensor(b["attention_mask"],
                                        dtype=torch.float),
            token_type_ids=torch.tensor(b["token_type_ids"],
                                        dtype=torch.long),
            position_ids=torch.tensor(b["position_ids"],
                                      dtype=torch.long)).logits
        opt_pos = torch.tensor(b["option_positions"], dtype=torch.long)
        scores = torch.gather(
            logits[..., yes_id], 1, opt_pos)
        t_loss = ce(scores, torch.tensor(b["labels"], dtype=torch.long))
        opt.zero_grad()
        t_loss.backward()
        opt.step()
        torch_losses.append(float(t_loss.detach()))

        jb = {k: jnp.asarray(v) for k, v in b.items()}
        params, opt_state, loss = step(params, opt_state, jb)
        jax_losses.append(float(loss))

    diffs = np.abs(np.array(torch_losses[:N_STEPS]) -
                   np.array(jax_losses[:N_STEPS]))
    print(f"\nUniMC loss parity: torch[0]={torch_losses[0]:.4f} "
          f"jax[0]={jax_losses[0]:.4f} torch[-1]={torch_losses[-1]:.4f} "
          f"jax[-1]={jax_losses[-1]:.4f} "
          f"max|d|[:{N_STEPS}]={diffs.max():.5f}")
    assert diffs.max() < 5e-3, (torch_losses, jax_losses)
    # the full run must actually learn the planted signal
    assert torch_losses[-1] < torch_losses[0] - 0.1
    assert jax_losses[-1] < jax_losses[0] - 0.1
