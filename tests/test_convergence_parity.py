"""Training-dynamics parity vs the torch oracle.

The zero-egress environment blocks downloading released checkpoints
(QUALITY_r02.md), so quality parity is established on what CAN be
measured: starting from IDENTICAL weights on IDENTICAL data with the
SAME optimizer hyperparameters, the per-step loss trajectory of this
framework must track torch's step for step. This subsumes forward parity
(step 0) and extends it to gradients + AdamW update semantics
(optax.adamw == torch.optim.AdamW: decoupled weight decay, bias
correction, eps-after-sqrt).

Mirrors the reference's own verification doctrine of comparable loss
curves (SURVEY.md §4, reference publishes wandb loss curves for Ziya,
fengshen/examples/ziya_llama/README.md:47-48).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

pytestmark = pytest.mark.slow  # full-fit/e2e lane: run with -m slow or no -m filter



torch = pytest.importorskip("torch")
import transformers  # noqa: E402


LR, WD, BETAS, EPS = 1e-3, 0.01, (0.9, 0.999), 1e-8
N_STEPS = 25


def _torch_adamw(model):
    return torch.optim.AdamW(model.parameters(), lr=LR, betas=BETAS,
                             eps=EPS, weight_decay=WD)


def _optax_adamw():
    return optax.adamw(LR, b1=BETAS[0], b2=BETAS[1], eps=EPS,
                       weight_decay=WD)


def test_bert_classifier_loss_curve_matches_torch():
    from fengshen_tpu.models.bert import BertConfig
    from fengshen_tpu.models.bert.convert import torch_to_params
    from fengshen_tpu.models.bert.task_heads import (
        BertForSequenceClassification)
    from fengshen_tpu.utils.convert_common import make_helpers

    hf_cfg = transformers.BertConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=32, num_labels=3,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        classifier_dropout=0.0)
    torch.manual_seed(0)
    tm = transformers.BertForSequenceClassification(hf_cfg).train()

    cfg = BertConfig(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                     num_attention_heads=4, intermediate_size=64,
                     max_position_embeddings=32, dtype="float32",
                     hidden_dropout_prob=0.0,
                     attention_probs_dropout_prob=0.0)
    sd = tm.state_dict()
    _, lin, _ = make_helpers(sd)
    params = {"bert": torch_to_params(sd, cfg)["bert"],
              "classifier": lin("classifier")}
    params = jax.tree_util.tree_map(
        lambda x: jnp.asarray(np.asarray(x), jnp.float32), params)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, 128, (4, 8, 16)).astype(np.int64)  # 4 batches
    labels = rng.randint(0, 3, (4, 8)).astype(np.int64)

    model = BertForSequenceClassification(cfg, num_labels=3)
    tx = _optax_adamw()
    opt_state = tx.init(params)

    @jax.jit
    def step(p, o, ids, labels):
        def loss_fn(p):
            logits = model.apply({"params": p}, ids)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, labels).mean()
        loss, grads = jax.value_and_grad(loss_fn)(p)
        updates, o = tx.update(grads, o, p)
        return optax.apply_updates(p, updates), o, loss

    opt = _torch_adamw(tm)
    torch_losses, jax_losses = [], []
    for i in range(N_STEPS):
        b = i % 4
        out = tm(torch.tensor(ids[b]), labels=torch.tensor(labels[b]))
        opt.zero_grad()
        out.loss.backward()
        opt.step()
        torch_losses.append(float(out.loss.detach()))

        params, opt_state, loss = step(params, opt_state,
                                       jnp.asarray(ids[b], jnp.int32),
                                       jnp.asarray(labels[b], jnp.int32))
        jax_losses.append(float(loss))

    diffs = np.abs(np.array(torch_losses) - np.array(jax_losses))
    print(f"\nBERT-cls loss parity: torch[0]={torch_losses[0]:.4f} "
          f"jax[0]={jax_losses[0]:.4f} torch[-1]={torch_losses[-1]:.4f} "
          f"jax[-1]={jax_losses[-1]:.4f} max|d|={diffs.max():.5f}")
    assert diffs.max() < 5e-3, (torch_losses, jax_losses)
    # the run must actually learn something, or parity is vacuous
    assert torch_losses[-1] < torch_losses[0] - 0.1


def test_llama_causal_lm_loss_curve_matches_torch():
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.models.llama.convert import torch_to_params
    from fengshen_tpu.parallel.cross_entropy import stable_cross_entropy

    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=32, rms_norm_eps=1e-6,
        attn_implementation="eager", tie_word_embeddings=False)
    torch.manual_seed(0)
    tm = transformers.LlamaForCausalLM(hf_cfg).train()

    cfg = LlamaConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=4, max_position_embeddings=32,
                      rms_norm_eps=1e-6, dtype="float32")
    params = jax.tree_util.tree_map(
        lambda x: jnp.asarray(np.asarray(x), jnp.float32),
        torch_to_params(tm.state_dict(), cfg))

    rng = np.random.RandomState(1)
    ids = rng.randint(0, 128, (4, 4, 16)).astype(np.int64)

    model = LlamaForCausalLM(cfg)
    tx = _optax_adamw()
    opt_state = tx.init(params)

    @jax.jit
    def step(p, o, ids):
        def loss_fn(p):
            logits = model.apply({"params": p}, ids)
            return stable_cross_entropy(logits[:, :-1], ids[:, 1:])[0]
        loss, grads = jax.value_and_grad(loss_fn)(p)
        updates, o = tx.update(grads, o, p)
        return optax.apply_updates(p, updates), o, loss

    opt = _torch_adamw(tm)
    torch_losses, jax_losses = [], []
    for i in range(N_STEPS):
        b = i % 4
        t_ids = torch.tensor(ids[b])
        out = tm(t_ids, labels=t_ids)  # HF shifts internally
        opt.zero_grad()
        out.loss.backward()
        opt.step()
        torch_losses.append(float(out.loss.detach()))

        params, opt_state, loss = step(params, opt_state,
                                       jnp.asarray(ids[b], jnp.int32))
        jax_losses.append(float(loss))

    diffs = np.abs(np.array(torch_losses) - np.array(jax_losses))
    print(f"\nLLaMA-lm loss parity: torch[0]={torch_losses[0]:.4f} "
          f"jax[0]={jax_losses[0]:.4f} torch[-1]={torch_losses[-1]:.4f} "
          f"jax[-1]={jax_losses[-1]:.4f} max|d|={diffs.max():.5f}")
    assert diffs.max() < 5e-3, (torch_losses, jax_losses)
    assert torch_losses[-1] < torch_losses[0] - 0.1


def test_t5_seq2seq_loss_curve_matches_torch():
    """Encoder-decoder family: T5ForConditionalGeneration 25-step AdamW
    loss-curve parity vs HF torch (teacher-forced seq2seq CE)."""
    from fengshen_tpu.models.t5 import T5Config, T5ForConditionalGeneration
    from fengshen_tpu.models.t5.convert import torch_to_params

    hf_cfg = transformers.T5Config(
        vocab_size=96, d_model=32, d_kv=8, d_ff=64, num_layers=2,
        num_decoder_layers=2, num_heads=4, relative_attention_num_buckets=8,
        relative_attention_max_distance=16, dropout_rate=0.0,
        feed_forward_proj="relu", tie_word_embeddings=True,
        decoder_start_token_id=0)
    torch.manual_seed(0)
    tm = transformers.T5ForConditionalGeneration(hf_cfg).train()

    cfg = T5Config(vocab_size=96, d_model=32, d_kv=8, d_ff=64, num_layers=2,
                   num_decoder_layers=2, num_heads=4,
                   relative_attention_num_buckets=8,
                   relative_attention_max_distance=16, dropout_rate=0.0,
                   feed_forward_proj="relu", tie_word_embeddings=True,
                   dtype="float32")
    params = jax.tree_util.tree_map(
        lambda x: jnp.asarray(np.asarray(x), jnp.float32),
        torch_to_params(tm.state_dict(), cfg))

    rng = np.random.RandomState(2)
    src = rng.randint(2, 96, (4, 4, 12)).astype(np.int64)
    tgt = rng.randint(2, 96, (4, 4, 8)).astype(np.int64)
    tgt[:, :, -1] = 1  # eos
    dec_in = np.concatenate([np.zeros_like(tgt[:, :, :1]), tgt[:, :, :-1]],
                            axis=-1)

    model = T5ForConditionalGeneration(cfg)
    tx = _optax_adamw()
    opt_state = tx.init(params)

    @jax.jit
    def step(p, o, src_b, dec_b, tgt_b):
        def loss_fn(p):
            logits = model.apply({"params": p}, src_b, dec_b)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, tgt_b).mean()
        loss, grads = jax.value_and_grad(loss_fn)(p)
        updates, o = tx.update(grads, o, p)
        return optax.apply_updates(p, updates), o, loss

    opt = _torch_adamw(tm)
    torch_losses, jax_losses = [], []
    for i in range(N_STEPS):
        b = i % 4
        out = tm(input_ids=torch.tensor(src[b]),
                 labels=torch.tensor(tgt[b]))
        opt.zero_grad()
        out.loss.backward()
        opt.step()
        torch_losses.append(float(out.loss.detach()))

        params, opt_state, loss = step(
            params, opt_state, jnp.asarray(src[b], jnp.int32),
            jnp.asarray(dec_in[b], jnp.int32), jnp.asarray(tgt[b], jnp.int32))
        jax_losses.append(float(loss))

    diffs = np.abs(np.array(torch_losses) - np.array(jax_losses))
    print(f"\nT5-seq2seq loss parity: torch[0]={torch_losses[0]:.4f} "
          f"jax[0]={jax_losses[0]:.4f} torch[-1]={torch_losses[-1]:.4f} "
          f"jax[-1]={jax_losses[-1]:.4f} max|d|={diffs.max():.5f}")
    assert diffs.max() < 5e-3, (torch_losses, jax_losses)
    assert torch_losses[-1] < torch_losses[0] - 0.1
