"""LLaMA golden-value parity vs HF torch, sharding equivalence, and an
end-to-end trainer smoke run — the test pyramid SURVEY.md §4 calls for."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from fengshen_tpu.models.llama.convert import (torch_to_params,
                                               params_to_torch_state)


@pytest.fixture(scope="module")
def small_pair():
    """(jax params, torch model, config) with identical small weights."""
    torch = pytest.importorskip("torch")
    import transformers

    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=64, rms_norm_eps=1e-6,
        attn_implementation="eager", tie_word_embeddings=False)
    torch.manual_seed(0)
    tm = transformers.LlamaForCausalLM(hf_cfg).eval()

    cfg = LlamaConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=4, max_position_embeddings=64,
                      rms_norm_eps=1e-6, dtype="float32")
    params = torch_to_params(tm.state_dict(), cfg)
    return params, tm, cfg


def test_forward_parity_with_hf(small_pair):
    import torch
    params, tm, cfg = small_pair
    ids = np.array([[3, 17, 9, 42, 7, 99, 1, 5]], dtype=np.int32)
    model = LlamaForCausalLM(cfg)
    logits = model.apply({"params": params}, jnp.asarray(ids))
    with torch.no_grad():
        ref = tm(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    np.testing.assert_allclose(np.asarray(logits), ref, atol=2e-3)


def test_roundtrip_convert(small_pair):
    params, tm, cfg = small_pair
    state = params_to_torch_state(params, cfg)
    ref = {k: v.detach().numpy() for k, v in tm.state_dict().items()}
    for k in ref:
        np.testing.assert_allclose(state[k], ref[k], atol=1e-6,
                                   err_msg=k)


def test_gqa_forward_parity():
    torch = pytest.importorskip("torch")
    import transformers
    hf_cfg = transformers.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=32, attn_implementation="eager",
        tie_word_embeddings=False)
    torch.manual_seed(1)
    tm = transformers.LlamaForCausalLM(hf_cfg).eval()
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=32,
                      dtype="float32")
    params = torch_to_params(tm.state_dict(), cfg)
    ids = np.array([[5, 3, 60, 2, 11, 7]], dtype=np.int32)
    logits = LlamaForCausalLM(cfg).apply({"params": params},
                                         jnp.asarray(ids))
    with torch.no_grad():
        ref = tm(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    np.testing.assert_allclose(np.asarray(logits), ref, atol=2e-3)


def test_sharded_forward_matches_replicated(small_pair, mesh8):
    """TP+FSDP sharded execution must be numerically equal to single-device
    — the invariant the reference could only check by eyeballing loss curves
    across cluster runs."""
    params, _, cfg = small_pair
    model = LlamaForCausalLM(cfg)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 127, (4, 16)),
                      jnp.int32)
    ref = model.apply({"params": params}, ids)

    from fengshen_tpu.parallel import make_shardings
    from fengshen_tpu.models.llama.modeling_llama import PARTITION_RULES
    shardings = make_shardings(PARTITION_RULES, params, mesh8)
    sharded_params = jax.device_put(params, shardings)
    out = jax.jit(lambda p, i: model.apply({"params": p}, i))(
        sharded_params, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


def test_kv_cache_decode_matches_full_forward(small_pair):
    """Greedy decode step-by-step through the cache must equal slicing the
    full forward — catches the decode-under-pjit correctness risk SURVEY.md
    ranks #2."""
    params, _, cfg = small_pair
    model = LlamaForCausalLM(cfg)
    ids = np.array([[3, 17, 9, 42, 7, 99]], dtype=np.int32)
    full = model.apply({"params": params}, jnp.asarray(ids))

    # prefill with the first 4 tokens, then decode 2 steps
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 1), jnp.int32), init_cache=True)
    cache = variables["cache"]
    logits, mutated = model.apply(
        {"params": params, "cache": cache}, jnp.asarray(ids[:, :4]),
        init_cache=True, mutable=["cache"])
    np.testing.assert_allclose(np.asarray(logits), np.asarray(full[:, :4]),
                               atol=1e-3)
    cache = mutated["cache"]
    for t in range(4, 6):
        pos = jnp.array([[t]])
        logits, mutated = model.apply(
            {"params": params, "cache": cache},
            jnp.asarray(ids[:, t:t + 1]), position_ids=pos,
            init_cache=True, mutable=["cache"])
        cache = mutated["cache"]
        np.testing.assert_allclose(np.asarray(logits[:, 0]),
                                   np.asarray(full[:, t]), atol=1e-3)


def test_scan_layers_parity(small_pair):
    """scan_layers=True must produce identical logits from stacked weights."""
    import dataclasses
    params, tm, cfg = small_pair
    scan_cfg = dataclasses.replace(cfg, scan_layers=True)
    scan_params = torch_to_params(tm.state_dict(), scan_cfg)
    ids = np.array([[3, 17, 9, 42, 7, 99, 1, 5]], dtype=np.int32)
    ref = LlamaForCausalLM(cfg).apply({"params": params}, jnp.asarray(ids))
    out = LlamaForCausalLM(scan_cfg).apply({"params": scan_params},
                                           jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_scan_layers_init_shapes():
    cfg = LlamaConfig.small_test_config(dtype="float32", scan_layers=True)
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    k = params["model"]["layers"]["layer"]["self_attn"]["q_proj"]["kernel"]
    assert k.shape == (cfg.num_hidden_layers, cfg.hidden_size,
                       cfg.hidden_size)


def test_padded_batch_flash_matches_dense(small_pair):
    """VERDICT r1 weak #3: padded SFT batches must stay on the flash path
    (segment ids), matching the dense-with-mask numerics on valid rows."""
    import dataclasses
    params, _, cfg = small_pair
    ids = np.array([[3, 17, 9, 42, 7, 99, 1, 5],
                    [8, 2, 30, 11, 0, 0, 0, 0]], dtype=np.int32)
    mask = np.array([[1] * 8, [1] * 4 + [0] * 4], dtype=np.int32)
    dense = LlamaForCausalLM(dataclasses.replace(cfg, attention_impl="dense"))
    flash = LlamaForCausalLM(dataclasses.replace(cfg, attention_impl="flash"))
    out_d = dense.apply({"params": params}, jnp.asarray(ids),
                        attention_mask=jnp.asarray(mask))
    out_f = flash.apply({"params": params}, jnp.asarray(ids),
                        attention_mask=jnp.asarray(mask))
    valid = np.asarray(mask, bool)
    np.testing.assert_allclose(np.asarray(out_f)[valid],
                               np.asarray(out_d)[valid], atol=2e-3)


def test_resize_token_embeddings():
    """Reference: models/llama/modeling_llama.py:386-405 — grow the vocab,
    old rows preserved, old-token logits unchanged; shrink truncates."""
    import dataclasses
    from fengshen_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                           resize_token_embeddings)

    cfg = LlamaConfig(vocab_size=64, hidden_size=16, intermediate_size=32,
                      num_hidden_layers=1, num_attention_heads=2,
                      num_key_value_heads=2, max_position_embeddings=16,
                      dtype="float32")
    model = LlamaForCausalLM(cfg)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (1, 8)),
                      jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    base = model.apply({"params": params}, ids)

    grown, cfg2 = resize_token_embeddings(params, cfg, 80,
                                          rng=jax.random.PRNGKey(1))
    assert cfg2.vocab_size == 80
    assert grown["model"]["embed_tokens"]["embedding"].shape[0] == 80
    assert grown["lm_head"]["kernel"].shape == (16, 80)
    out = LlamaForCausalLM(cfg2).apply({"params": grown}, ids)
    np.testing.assert_allclose(np.asarray(out)[..., :64],
                               np.asarray(base), atol=1e-5)

    shrunk, cfg3 = resize_token_embeddings(params, cfg, 48)
    assert shrunk["model"]["embed_tokens"]["embedding"].shape[0] == 48
    out3 = LlamaForCausalLM(cfg3).apply(
        {"params": shrunk}, jnp.clip(ids, 0, 47))
    assert out3.shape[-1] == 48
