"""Fleet router (ISSUE 10, docs/fleet.md): health-gated multi-replica
serving with retries, backoff, circuit breaking, and graceful drain.

Two tiers:

- deterministic UNIT tests over an in-memory fake transport + manual
  clock + recorded sleeps (no jax, no sockets): placement, health
  gating with eased recovery, retry/backoff semantics, the breaker's
  open/half-open/close lifecycle, structured zero-healthy degradation,
  router drain, and the PYTHONHASHSEED-pinned `/fleet` debug JSON;
- INTEGRATION tests over three REAL stdlib api replicas (tiny llama,
  continuous engines) behind a `FleetFaultPlan`-wrapped transport: the
  acceptance pin — kill one replica mid-run, every greedy request
  still completes token-identical to a single sequential engine, zero
  dropped or duplicated responses, and `fstpu_fleet_retries_total`
  matches the injected fault count EXACTLY — plus the replica-side
  SIGTERM drain (healthz flips to draining-503 while an in-flight
  request completes; extends the PR-8 SIGTERM-chain coverage) and the
  request-id dedupe/reject hook the idempotent-safe retries rest on.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fengshen_tpu.fleet import (BROKEN, DRAINING, HEALTHY,
                                FleetConfig, FleetFaultPlan,
                                FleetRouter, TransportError,
                                UrllibTransport, healthz_payload)
from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from fengshen_tpu.serving import (ContinuousBatchingEngine, Draining,
                                  DuplicateRequest, EngineConfig)
from fengshen_tpu.utils.generate import generate

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---- unit tier: fake transport, manual clock ----------------------------

class ManualClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class FakeReplica:
    """One simulated replica the fake transport answers for."""

    def __init__(self, num_slots: int = 4):
        self.healthz = (200, {"status": "ok", "ready": True})
        self.stats = {"slots_active": 0, "queue_depth": 0,
                      "num_slots": num_slots, "draining": False}
        self.fail = None            # None | "connect" | "timeout"
        self.generate_code = 200
        self.requests = []          # bodies seen by /api/ POSTs

    def response(self, body):
        return self.generate_code, {
            "result": "ok", "request_id": body.get("request_id"),
            "finish_reason": "length"}


class FakeTransport:
    def __init__(self, replicas):
        self.replicas = replicas    # name -> FakeReplica

    def request(self, base_url, method, path, body, timeout_s):
        rep = self.replicas[base_url.split("://", 1)[1]]
        if rep.fail is not None:
            raise TransportError(f"injected {rep.fail}",
                                 sent=rep.fail == "timeout")
        if path == "/healthz":
            return rep.healthz
        if path == "/stats":
            return 200, rep.stats
        if method == "POST" and path.startswith("/api/"):
            rep.requests.append(body)
            return rep.response(body)
        return 404, {}


def _mk_router(names, replicas, clock=None, sleeps=None, **cfg):
    cfg.setdefault("recovery_probes", 1)
    cfg.setdefault("backoff_base_s", 0.05)
    cfg.setdefault("seed", 0)
    return FleetRouter(
        FleetConfig(replicas=names, **cfg),
        transport=FakeTransport(replicas),
        clock=clock or ManualClock(),
        sleep=(sleeps.append if sleeps is not None else lambda s: None))


def test_health_gating_and_eased_recovery():
    """Unprobed replicas are OUT; healthz 503 takes one out in a single
    poll; re-entry needs `recovery_probes` CONSECUTIVE healthy polls."""
    reps = {"a:1": FakeReplica(), "b:2": FakeReplica()}
    router = _mk_router(("a:1", "b:2"), reps, recovery_probes=2)
    # unprobed: nothing routed, loud structured 503
    code, body = router.route_generate({"input_text": "1"})
    assert code == 503 and body["reason"] == "no_healthy_replicas"
    assert set(body["replicas"]) == {"a:1", "b:2"}
    router.poll_once()
    assert router.healthy_count() == 0      # streak 1 of 2
    router.poll_once()
    assert router.healthy_count() == 2
    # b drains (orderly 503): out after ONE poll, breaker untouched
    reps["b:2"].healthz = (503, {"ready": False, "reason": "draining"})
    router.poll_once()
    state = router.fleet_state()
    b = [r for r in state["replicas"] if r["name"] == "b:2"][0]
    assert b["state"] == DRAINING and b["reason"] == "draining"
    assert b["breaker"]["consecutive_failures"] == 0
    assert router.healthy_count() == 1
    # recovery is eased: one healthy poll is not enough
    reps["b:2"].healthz = (200, {"ready": True})
    router.poll_once()
    assert router.healthy_count() == 1
    router.poll_once()
    assert router.healthy_count() == 2


def test_stats_draining_routes_around_before_healthz():
    """engine.begin_drain() without the API-layer event: /stats flips
    `draining` while /healthz is still 200 — the poll must take the
    replica out orderly (no breaker charge) on that signal alone, and
    ease it back in once it stops reporting draining."""
    reps = {"a:1": FakeReplica(), "b:2": FakeReplica()}
    router = _mk_router(("a:1", "b:2"), reps, recovery_probes=2)
    router.poll_once()
    router.poll_once()
    assert router.healthy_count() == 2
    reps["b:2"].stats = dict(reps["b:2"].stats, draining=True)
    router.poll_once()
    state = {r["name"]: r for r in router.fleet_state()["replicas"]}
    assert state["b:2"]["state"] == DRAINING
    assert state["b:2"]["reason"] == "draining"
    assert state["b:2"]["breaker"]["consecutive_failures"] == 0
    assert state["b:2"]["occupancy"]["draining_reported"] is True
    code, _ = router.route_generate({"input_text": "1"})
    assert code == 200
    assert [len(r.requests) for r in reps.values()] == [1, 0]
    # stops draining → eased re-entry, like any other recovery
    reps["b:2"].stats = dict(reps["b:2"].stats, draining=False)
    router.poll_once()
    assert router.healthy_count() == 1
    router.poll_once()
    assert router.healthy_count() == 2


def test_least_occupancy_pick_is_deterministic():
    """Least (slots_active+queue_depth+in_flight)/num_slots wins; ties
    break by replica index."""
    reps = {n: FakeReplica() for n in ("a:1", "b:2", "c:3")}
    reps["a:1"].stats.update(slots_active=3)
    reps["b:2"].stats.update(slots_active=1)
    reps["c:3"].stats.update(slots_active=1, queue_depth=2)
    router = _mk_router(("a:1", "b:2", "c:3"), reps)
    router.poll_once()
    code, _ = router.route_generate({"input_text": "1"})
    assert code == 200
    assert [len(r.requests) for r in reps.values()] == [0, 1, 0]
    # tie (fresh stats make b and c equal) → lowest index among ties
    reps["c:3"].stats.update(queue_depth=0)
    router.poll_once()
    router.route_generate({"input_text": "2"})
    assert [len(r.requests) for r in reps.values()] == [0, 2, 0]


def test_retry_on_connect_failure_lands_on_different_replica():
    """A connect failure retries on ANOTHER replica after a jittered
    backoff; the failed replica's breaker charges; the retry counter
    carries the reason."""
    reps = {"a:1": FakeReplica(), "b:2": FakeReplica()}
    router = _mk_router(("a:1", "b:2"), reps, sleeps=(sleeps := []),
                        breaker_threshold=1, max_retries=2,
                        backoff_base_s=0.1)
    router.poll_once()
    reps["a:1"].fail = "connect"
    code, body = router.route_generate({"input_text": "1"})
    assert code == 200
    assert len(reps["b:2"].requests) == 1
    assert router.retries_total() == {"connect": 1}
    # jitter is seeded-uniform in [0.5, 1.0) x nominal
    assert len(sleeps) == 1 and 0.05 <= sleeps[0] < 0.1
    a = router.fleet_state()["replicas"][0]
    assert a["state"] == BROKEN and a["breaker"]["open"]
    # both attempts carried the SAME router-assigned request id — the
    # replica-side dedupe hook makes this retry idempotent-safe
    assert body["request_id"].startswith("fleet-")


def test_5xx_retries_and_503_is_orderly():
    """HTTP 500 charges the breaker and retries; HTTP 503 (the replica
    saying warming/draining) retries and leaves rotation WITHOUT
    charging the breaker."""
    reps = {"a:1": FakeReplica(), "b:2": FakeReplica()}
    router = _mk_router(("a:1", "b:2"), reps, breaker_threshold=2,
                        max_retries=1)
    router.poll_once()
    reps["a:1"].generate_code = 500
    code, _ = router.route_generate({"input_text": "1"})
    assert code == 200 and len(reps["b:2"].requests) == 1
    assert router.retries_total() == {"http_500": 1}
    state = {r["name"]: r for r in router.fleet_state()["replicas"]}
    assert state["a:1"]["breaker"]["consecutive_failures"] == 1
    # now a 503: replica leaves rotation, breaker count RESETS (orderly)
    reps["a:1"].generate_code = 503
    router.poll_once()           # back to healthy first
    router.poll_once()
    code, _ = router.route_generate({"input_text": "2"})
    assert code == 200
    state = {r["name"]: r for r in router.fleet_state()["replicas"]}
    assert state["a:1"]["state"] == DRAINING
    assert state["a:1"]["breaker"]["consecutive_failures"] == 0


def test_maybe_executed_failure_not_retried_when_disabled():
    """With retry_maybe_executed=False a timeout (the replica may
    still be executing) is NOT retried: 502 back to the caller."""
    reps = {"a:1": FakeReplica(), "b:2": FakeReplica()}
    router = _mk_router(("a:1", "b:2"), reps,
                        retry_maybe_executed=False, max_retries=2)
    router.poll_once()
    reps["a:1"].fail = "timeout"
    code, body = router.route_generate({"input_text": "1"})
    assert code == 502 and body["reason"] == "timeout"
    assert router.retries_total() == {}
    assert len(reps["b:2"].requests) == 0


def test_circuit_breaker_half_open_probe_cycle():
    """threshold failures open the breaker; during cooldown the replica
    takes no traffic (structured 503 when it was the only one); after
    cooldown exactly one half-open probe may close it."""
    clock = ManualClock()
    reps = {"a:1": FakeReplica()}
    router = _mk_router(("a:1",), reps, clock=clock,
                        breaker_threshold=2, breaker_cooldown_s=5.0,
                        max_retries=0)
    router.poll_once()
    reps["a:1"].fail = "connect"
    for _ in range(2):
        code, _ = router.route_generate({"input_text": "x"})
        assert code == 502
    assert router.fleet_state()["replicas"][0]["state"] == BROKEN
    # cooldown holds: no attempt reaches the replica at all
    n_before = len(reps["a:1"].requests)
    code, body = router.route_generate({"input_text": "x"})
    assert code == 503 and body["reason"] == "no_healthy_replicas"
    assert body["replicas"]["a:1"]["state"] == BROKEN
    assert len(reps["a:1"].requests) == n_before
    # past cooldown + replica recovered: the half-open probe closes it
    clock.advance(5.1)
    reps["a:1"].fail = None
    code, _ = router.route_generate({"input_text": "y"})
    assert code == 200
    assert router.fleet_state()["replicas"][0]["state"] == HEALTHY
    # healthy polls past cooldown close it too (poll-as-probe): break
    # it again, recover via polls only
    reps["a:1"].fail = "connect"
    router.route_generate({"input_text": "z"})
    router.route_generate({"input_text": "z"})
    assert router.fleet_state()["replicas"][0]["state"] == BROKEN
    clock.advance(5.1)
    reps["a:1"].fail = None
    router.poll_once()
    assert router.fleet_state()["replicas"][0]["state"] == HEALTHY


def test_router_drain_stops_admission():
    reps = {"a:1": FakeReplica()}
    router = _mk_router(("a:1",), reps)
    router.poll_once()
    assert healthz_payload(router)[0] == 200
    router.drain()
    code, body = router.route_generate({"input_text": "1"})
    assert code == 503 and body["reason"] == "draining"
    code, body = healthz_payload(router)
    assert code == 503 and body["ready"] is False
    assert body["reason"] == "draining"
    assert router.wait_drained(timeout_s=1.0)
    assert len(reps["a:1"].requests) == 0


def test_fleet_state_json_deterministic_across_hashseed(tmp_path):
    """`/fleet` (sorted JSON) is byte-identical across PYTHONHASHSEED —
    the debug payload the acceptance pin reads must be deterministic.
    Pure-stdlib subprocess: the fleet package must not pull jax."""
    script = """
import json, sys
assert "jax" not in sys.modules
from fengshen_tpu.fleet import FleetConfig, FleetRouter, TransportError
assert "jax" not in sys.modules, "fleet package must stay jax-free"

class Clock:
    # constant: poll_once sweeps replicas on parallel threads, so an
    # advancing clock would make timestamps scheduling-dependent
    def __call__(self): return 100.0

class T:
    def request(self, base_url, method, path, body, timeout_s):
        if base_url.endswith(":1"):
            if path == "/healthz": return 200, {"ready": True}
            if path == "/stats": return 200, {"slots_active": 1,
                                              "num_slots": 4,
                                              "queue_depth": 0,
                                              "phase": "prefill"}
            return 200, {"result": "ok",
                         "request_id": body["request_id"]}
        raise TransportError("dead", sent=False)

r = FleetRouter(FleetConfig(replicas=("a:1", "b:2"),
                            recovery_probes=1, breaker_threshold=1,
                            backoff_base_s=0.0),
                transport=T(), clock=Clock(), sleep=lambda s: None)
r.poll_once()
r.route_generate({"input_text": "1"})
print(json.dumps(r.fleet_state(), sort_keys=True))
"""
    outs = []
    for seed in ("0", "1"):
        out = subprocess.run(
            [sys.executable, "-c", script],
            env={**os.environ, "PYTHONHASHSEED": seed},
            capture_output=True, text=True, cwd=REPO)
        assert out.returncode == 0, out.stderr
        outs.append(out.stdout)
    assert outs[0] == outs[1]
    state = json.loads(outs[0])
    assert state["healthy"] == 1 and state["broken"] == 1
    # phase flows from polled /stats into /fleet; the unpolled dead
    # replica stays "both", and the topology label reflects the mix
    phases = {r["name"]: r["phase"] for r in state["replicas"]}
    assert phases == {"a:1": "prefill", "b:2": "both"}
    assert state["topology"] == "prefill=1,decode=0,both=1"


def test_fault_plan_coordinates():
    """FleetFaultPlan: kills are sticky from their index on, 503/slow
    are one-shot at (index, replica), polls never advance the index."""
    reps = {"a:1": FakeReplica(), "b:2": FakeReplica()}
    inner = FakeTransport(reps)
    plan = FleetFaultPlan(kill_at={2: "a:1"},
                          error_503_at={0: "b:2"}, slow_at={1: "b:2"},
                          slow_s=0.01)
    slept = []
    t = plan.wrap(inner, sleep=slept.append)
    # polls: no index movement, a:1 still alive
    assert t.request("http://a:1", "GET", "/healthz", None, 1)[0] == 200
    # idx 0 → b: one-shot 503
    code, body = t.request("http://b:2", "POST", "/api/t",
                           {"input_text": "x"}, 1)
    assert code == 503 and body["reason"] == "injected"
    # idx 1 → b: slow, then fine
    code, _ = t.request("http://b:2", "POST", "/api/t",
                        {"input_text": "x"}, 1)
    assert code == 200 and slept == [0.01]
    # idx 2 arms the kill; this attempt targets a → dead
    with pytest.raises(TransportError) as e:
        t.request("http://a:1", "POST", "/api/t",
                  {"input_text": "x"}, 1)
    assert e.value.sent is False
    # and a stays dead for polls too
    with pytest.raises(TransportError):
        t.request("http://a:1", "GET", "/healthz", None, 1)
    assert plan.fired == [("error_503", 0, "b:2"), ("slow", 1, "b:2"),
                          ("kill", 2, "a:1")]
    assert plan.fault_count == 3


# ---- integration tier: real replicas, tiny model ------------------------

@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig(vocab_size=97, hidden_size=32,
                      intermediate_size=64, num_hidden_layers=2,
                      num_attention_heads=4,
                      max_position_embeddings=64, dtype="float32")
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    return model, params


class _IntTok:
    eos_token_id = None
    pad_token_id = 0

    def encode(self, text):
        return [int(t) for t in text.split()]

    def decode(self, ids):
        return " ".join(str(int(t)) for t in ids)


def _ref(model, params, prompt, max_new):
    out = np.asarray(generate(model, params, jnp.asarray(prompt)[None],
                              max_new_tokens=max_new))
    return out[0, len(prompt):].tolist()


def _start_replica(tiny, max_new=5, num_slots=2, start=True):
    """One real stdlib api replica over a continuous engine. Returns
    (server, engine, serve_thread, draining_event, pipeline)."""
    from fengshen_tpu.api.main import (PipelineConfig, ServerConfig,
                                       build_stdlib_server)
    from fengshen_tpu.pipelines.text_generation import Pipeline
    model, params = tiny
    pipe = Pipeline(module=model, params=params, tokenizer=_IntTok(),
                    max_new_tokens=max_new, eos_token_id=None,
                    pad_token_id=0)
    engine = ContinuousBatchingEngine(
        model, params,
        EngineConfig(num_slots=num_slots, buckets=(8,),
                     max_new_tokens=max_new, max_queue=32,
                     pad_token_id=0))
    engine.warmup()
    if start:
        engine.start()
    ready = threading.Event()
    ready.set()
    draining = threading.Event()
    server = build_stdlib_server(
        ServerConfig(host="127.0.0.1", port=0, engine="continuous"),
        PipelineConfig(task="text_generation"), pipeline=pipe,
        engine=engine, ready=ready, draining=draining)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server, engine, thread, draining, pipe


def test_fleet_kill_one_replica_token_identical_exact_retries(tiny):
    """THE acceptance pin (ISSUE 10): 3 replicas, one killed mid-run at
    a deterministic request index — every submitted greedy request
    completes token-identical to a single sequential engine, zero
    dropped or duplicated responses, and the router's retry counter
    matches the injected fault count EXACTLY."""
    model, params = tiny
    fleet = [_start_replica(tiny) for _ in range(3)]
    targets = [f"127.0.0.1:{s.server_address[1]}"
               for s, *_ in fleet]
    plan = FleetFaultPlan(kill_at={4: targets[0]})
    transport = plan.wrap(UrllibTransport())
    router = FleetRouter(
        FleetConfig(replicas=targets, max_retries=2,
                    breaker_threshold=1, recovery_probes=1,
                    backoff_base_s=0.0, request_timeout_s=60.0),
        transport=transport, sleep=lambda s: None)
    transport.bind(router)
    try:
        router.poll_once()
        assert router.healthy_count() == 3
        rng = np.random.RandomState(0)
        prompts = [rng.randint(3, 96, n).astype(np.int32)
                   for n in (3, 5, 7, 4, 6, 8, 2, 5, 3)]
        responses = []
        for p in prompts:
            code, body = router.route_generate(
                {"input_text": " ".join(str(t) for t in p)})
            responses.append((code, body))
        # zero dropped: every request answered 200
        assert [c for c, _ in responses] == [200] * len(prompts)
        # token-identical to a single sequential engine
        refs = [" ".join(str(t) for t in _ref(model, params, p, 5))
                for p in prompts]
        assert [b["result"] for _, b in responses] == refs
        # zero duplicated: one distinct router-assigned id per request
        rids = [b["request_id"] for _, b in responses]
        assert len(set(rids)) == len(prompts)
        assert all(r.startswith("fleet-") for r in rids)
        # retries == injected faults, EXACTLY (the kill fired once:
        # breaker_threshold=1 takes the dead replica out after its
        # single failed attempt)
        assert plan.fired == [("kill", 4, targets[0])]
        assert router.retries_total() == {"connect": 1}
        # the dead replica reads broken in /fleet; the JSON is sorted-
        # dumpable (the hashseed pin covers byte determinism)
        state = {r["name"]: r
                 for r in router.fleet_state()["replicas"]}
        assert state[targets[0]]["state"] == BROKEN
        json.dumps(router.fleet_state(), sort_keys=True)
    finally:
        for server, engine, thread, *_ in fleet:
            server.shutdown()
            server.server_close()
            engine.stop()


def test_wedged_replica_retry_is_idempotent_safe(tiny):
    """A WEDGE (timeout: the replica may still be executing) retries on
    a different replica because the surface is idempotent-safe — the
    response comes from the healthy replica, once."""
    model, params = tiny
    fleet = [_start_replica(tiny) for _ in range(2)]
    targets = [f"127.0.0.1:{s.server_address[1]}"
               for s, *_ in fleet]
    plan = FleetFaultPlan(wedge_at={1: targets[0]})
    transport = plan.wrap(UrllibTransport())
    router = FleetRouter(
        FleetConfig(replicas=targets, max_retries=2,
                    breaker_threshold=1, recovery_probes=1,
                    backoff_base_s=0.0, request_timeout_s=60.0),
        transport=transport, sleep=lambda s: None)
    transport.bind(router)
    try:
        router.poll_once()
        prompt = np.asarray([5, 7, 9], np.int32)
        text = "5 7 9"
        codes = []
        for _ in range(3):
            code, body = router.route_generate({"input_text": text})
            codes.append(code)
            assert body["result"] == " ".join(
                str(t) for t in _ref(model, params, prompt, 5))
        assert codes == [200, 200, 200]
        assert router.retries_total() == {"timeout": 1}
        assert plan.fired == [("wedge", 1, targets[0])]
    finally:
        for server, engine, thread, *_ in fleet:
            server.shutdown()
            server.server_close()
            engine.stop()


def test_replica_sigterm_drains_while_inflight_completes(tiny):
    """Satellite (extends the PR-8 SIGTERM chain): SIGTERM to a stdlib
    api replica flips /healthz to the draining-503 body; new requests
    get 503; a request that is queued-but-not-slotted when the drain
    lands is flushed back as the SAME orderly 503 immediately (the
    router re-places it on a healthy replica — docs/fleet.md "Drain
    runbook" step 2; RUNNING lanes completing or evacuating is pinned
    in tests/test_evac.py); the server then shuts itself down once
    idle."""
    from fengshen_tpu.api.main import install_drain_handler
    # serve loop NOT started yet: the posted request stays queued on
    # the replica — deterministically in flight when SIGTERM lands —
    # and starts decoding only after the drain assertions below
    server, engine, thread, draining, _pipe = _start_replica(
        tiny, max_new=50, start=False)
    port = server.server_address[1]
    base = f"http://127.0.0.1:{port}"
    prev = install_drain_handler(server, draining, engine=engine,
                                 drain_timeout_s=30.0)
    result = {}

    def worker():
        req = urllib.request.Request(
            base + "/api/text_generation",
            data=json.dumps({"input_text": "5 7 9"}).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=60) as r:
                result["code"] = r.status
                result["body"] = json.loads(r.read())
        except urllib.error.HTTPError as e:
            result["code"] = e.code
            result["body"] = json.loads(e.read())

    def _get(path):
        try:
            with urllib.request.urlopen(base + path, timeout=10) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    try:
        w = threading.Thread(target=worker)
        w.start()
        # wait for the request to be IN FLIGHT (queued on the engine;
        # the serve loop is idle so /stats is contention-free)
        import time as _time
        for _ in range(2000):
            if engine.stats()["queue_depth"] >= 1:
                break
            _time.sleep(0.005)
        else:
            pytest.fail("request never admitted")
        signal.raise_signal(signal.SIGTERM)
        # the replica answers draining-503 on /healthz (the body the
        # fleet router keys on) while the in-flight request runs on
        code, body = _get("/healthz")
        assert code == 503
        assert body == {"status": "draining", "task": "text_generation",
                        "ready": False, "reason": "draining"}
        # new work is refused at the admission edge
        req = urllib.request.Request(
            base + "/api/text_generation",
            data=json.dumps({"input_text": "3 4"}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=30)
        assert exc.value.code == 503
        assert json.loads(exc.value.read())["reason"] == "draining"
        # /stats exposes the drain for the router's poll, and the
        # queued-but-not-slotted request was FLUSHED, not kept waiting
        code, stats = _get("/stats")
        assert code == 200 and stats["draining"] is True
        assert stats["queue_depth"] == 0
        # ... flushed as the same orderly 503 the admission edge
        # answers — the router treats it as draining (no breaker
        # charge) and re-places it on a healthy replica
        w.join(timeout=60)
        assert not w.is_alive()
        assert result["code"] == 503
        assert result["body"]["reason"] == "draining"
        # and the drained server shuts itself down (serve_forever
        # returns in the serving thread) once the engine runs idle
        engine.start()
        thread.join(timeout=30)
        assert not thread.is_alive()
    finally:
        signal.signal(signal.SIGTERM, prev)
        try:
            server.shutdown()
            server.server_close()
        except OSError:
            pass
        engine.stop()


def test_request_id_dedupe_and_engine_drain(tiny):
    """The replica-side idempotency hook: a live duplicate request_id
    is REJECTED (DuplicateRequest → 409 at the API layer); begin_drain
    refuses new submissions (Draining → 503 reason draining) and shows
    in /stats."""
    from fengshen_tpu.api.main import _engine_generate
    model, params = tiny
    engine = ContinuousBatchingEngine(
        model, params,
        EngineConfig(num_slots=1, buckets=(8,), max_new_tokens=4,
                     max_queue=8, pad_token_id=0))
    # no serve thread: submissions stay QUEUED, deterministically live
    engine.submit(np.asarray([5, 7], np.int32), request_id="fleet-9")
    with pytest.raises(DuplicateRequest):
        engine.submit(np.asarray([5, 7], np.int32),
                      request_id="fleet-9")
    engine.submit(np.asarray([5, 7], np.int32), request_id="fleet-10")
    assert engine.stats()["rejected_duplicate"] == 1

    class _Pipe:
        def encode(self, text):
            return [int(t) for t in text.split()]

        def decode(self, ids):
            return " ".join(str(int(t)) for t in ids)

    code, body = _engine_generate(
        engine, _Pipe(), {"input_text": "5 7", "request_id": "fleet-9"},
        timeout_s=1.0)
    assert code == 409 and "fleet-9" in body["error"]
    # drain: stats flip + 503 with reason at the API mapping
    assert engine.stats()["draining"] is False
    engine.begin_drain()
    assert engine.stats()["draining"] is True
    with pytest.raises(Draining):
        engine.submit(np.asarray([3, 4], np.int32))
    code, body = _engine_generate(engine, _Pipe(),
                                  {"input_text": "3 4"}, timeout_s=1.0)
    assert code == 503 and body["reason"] == "draining"
    assert engine.stats()["rejected_draining"] == 2
