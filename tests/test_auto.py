"""Auto-class registry tests."""

import json

import pytest

from fengshen_tpu.models.auto import AutoConfig, AutoModel, register_model


def test_auto_config_from_path(tmp_path):
    (tmp_path / "config.json").write_text(json.dumps({
        "model_type": "llama", "vocab_size": 64, "hidden_size": 32,
        "intermediate_size": 64, "num_hidden_layers": 1,
        "num_attention_heads": 4}))
    cfg = AutoConfig.from_pretrained(str(tmp_path))
    assert type(cfg).__name__ == "LlamaConfig"
    assert cfg.vocab_size == 64


def test_auto_model_from_config():
    cfg = AutoConfig.for_model("gpt2", vocab_size=64, n_embd=32, n_layer=1,
                               n_head=4)
    model = AutoModel.from_config(cfg, head="causal_lm")
    assert type(model).__name__ == "GPT2LMHeadModel"


def test_auto_unknown_type():
    with pytest.raises(KeyError, match="unknown model_type"):
        AutoConfig.for_model("nope")


def test_register_model():
    register_model("test-fake", "fengshen_tpu.models.llama", "LlamaConfig",
                   {"base": "LlamaModel"})
    cfg = AutoConfig.for_model("test-fake", vocab_size=8, hidden_size=16,
                               intermediate_size=32, num_hidden_layers=1,
                               num_attention_heads=2)
    assert cfg.vocab_size == 8


def test_auto_from_pretrained_generic_torch_converter(tmp_path):
    """AutoModel.from_pretrained loads reference-format torch weights
    through the family's torch_to_params when no HF loader exists."""
    import json

    import numpy as np
    import pytest
    torch = pytest.importorskip("torch")
    from transformers import BertConfig as HFBertConfig
    from transformers import BertForMaskedLM as HFMLM

    from fengshen_tpu.models.auto import AutoModel

    hf_cfg = HFBertConfig(vocab_size=64, hidden_size=32,
                          num_hidden_layers=2, num_attention_heads=4,
                          intermediate_size=64,
                          max_position_embeddings=32, type_vocab_size=2)
    torch.manual_seed(0)
    tm = HFMLM(hf_cfg).eval()
    ckpt = tmp_path / "bert_ckpt"
    ckpt.mkdir()
    torch.save(tm.state_dict(), ckpt / "pytorch_model.bin")
    (ckpt / "config.json").write_text(json.dumps({
        "model_type": "bert", "vocab_size": 64, "hidden_size": 32,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "intermediate_size": 64, "max_position_embeddings": 32,
        "type_vocab_size": 2, "dtype": "float32"}))

    model, params = AutoModel.from_pretrained(str(ckpt), head="masked_lm")
    assert params is not None
    import jax.numpy as jnp
    ids = np.array([[3, 9, 17, 4]], dtype=np.int32)
    logits = model.apply({"params": params}, jnp.asarray(ids))
    with torch.no_grad():
        ref = tm(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    np.testing.assert_allclose(np.asarray(logits), ref, atol=2e-4)
