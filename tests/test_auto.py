"""Auto-class registry tests."""

import json

import pytest

from fengshen_tpu.models.auto import AutoConfig, AutoModel, register_model


def test_auto_config_from_path(tmp_path):
    (tmp_path / "config.json").write_text(json.dumps({
        "model_type": "llama", "vocab_size": 64, "hidden_size": 32,
        "intermediate_size": 64, "num_hidden_layers": 1,
        "num_attention_heads": 4}))
    cfg = AutoConfig.from_pretrained(str(tmp_path))
    assert type(cfg).__name__ == "LlamaConfig"
    assert cfg.vocab_size == 64


def test_auto_model_from_config():
    cfg = AutoConfig.for_model("gpt2", vocab_size=64, n_embd=32, n_layer=1,
                               n_head=4)
    model = AutoModel.from_config(cfg, head="causal_lm")
    assert type(model).__name__ == "GPT2LMHeadModel"


def test_auto_unknown_type():
    with pytest.raises(KeyError, match="unknown model_type"):
        AutoConfig.for_model("nope")


def test_register_model():
    register_model("test-fake", "fengshen_tpu.models.llama", "LlamaConfig",
                   {"base": "LlamaModel"})
    cfg = AutoConfig.for_model("test-fake", vocab_size=8, hidden_size=16,
                               intermediate_size=32, num_hidden_layers=1,
                               num_attention_heads=2)
    assert cfg.vocab_size == 8
