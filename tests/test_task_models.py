"""UniMC / UBERT / TCBert smoke + behavioural tests."""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fengshen_tpu.models.megatron_bert import MegatronBertConfig

pytestmark = pytest.mark.slow  # full-fit/e2e lane: run with -m slow or no -m filter


def _bert_tokenizer(tmp_path):
    from transformers import BertTokenizer
    chars = list("是否这则一体育财经新闻运动员比赛股市经济测试文本北京大学")
    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + \
        sorted(set(chars))
    vf = tmp_path / "vocab.txt"
    vf.write_text("\n".join(vocab))
    return BertTokenizer(str(vf))


def _small_cfg(tok):
    return MegatronBertConfig(
        vocab_size=len(tok), hidden_size=32, num_hidden_layers=1,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, dtype="float32")


def test_unimc_train_and_predict(tmp_path, mesh8):
    from fengshen_tpu.models.unimc import UniMCPipelines
    tok = _bert_tokenizer(tmp_path)
    parser = argparse.ArgumentParser()
    parser = UniMCPipelines.add_pipeline_specific_args(parser)
    args = parser.parse_args([
        "--max_steps", "2", "--train_batchsize", "2",
        "--log_every_n_steps", "1", "--warmup_steps", "1",
        "--default_root_dir", str(tmp_path / "runs")])
    pipe = UniMCPipelines(args=args, tokenizer=tok, config=_small_cfg(tok))
    data = [{"texta": "运动员比赛", "choices": ["体育", "财经"], "label": 0},
            {"texta": "股市经济", "choices": ["体育", "财经"], "label": 1}] * 4
    pipe.train(data)
    preds = pipe.predict(data[:2])
    assert len(preds) == 2 and all(p in (0, 1) for p in preds)


def test_ubert_predict_shapes(tmp_path):
    from fengshen_tpu.models.ubert import UbertPipelines
    tok = _bert_tokenizer(tmp_path)
    pipe = UbertPipelines(args=None, tokenizer=tok, config=_small_cfg(tok))
    out = pipe.predict([{"task_type": "抽取任务", "text": "北京大学",
                         "choices": [{"entity_type": "机构"}]}])
    assert len(out) == 1
    assert out[0]["choices"][0]["entity_type"] == "机构"
    for ent in out[0]["choices"][0]["entity_list"]:
        assert set(ent) >= {"entity_name", "score", "start", "end"}


def test_tcbert_predict(tmp_path):
    from fengshen_tpu.models.tcbert import TCBertPipelines
    tok = _bert_tokenizer(tmp_path)
    pipe = TCBertPipelines(args=None, tokenizer=tok, config=_small_cfg(tok),
                           label_words=["体育", "财经"])
    preds = pipe.predict(["运动员比赛", "股市经济"])
    assert len(preds) == 2 and all(p in (0, 1) for p in preds)


def test_uniex_predict(tmp_path):
    from fengshen_tpu.models.uniex import UniEXPipelines
    tok = _bert_tokenizer(tmp_path)
    pipe = UniEXPipelines(args=None, tokenizer=tok, config=_small_cfg(tok))
    out = pipe.predict([{"text": "北京大学", "choices": ["机构"]}])
    assert len(out) == 1 and out[0]["text"] == "北京大学"
    for ent in out[0]["entity_list"]:
        assert set(ent) == {"entity_type", "entity_name", "score",
                            "start", "end"}
