"""Tests for gMLP, SoftEmbedding, and init-function dispatch.

Reference behaviors: fengshen/models/megatron/layers/gmlp.py (zero-init
spatial gate → identity-like start, causal masking),
layers/word_embeddings.py:157-215 (prompt prepend + mask extension,
string init tiling), layers/init_functions.py (std formulas).
"""

import math
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np

from fengshen_tpu.ops import GMLPBlock, SoftEmbedding, get_init_methods
from fengshen_tpu.ops.gmlp import SpatialGatingUnit
from fengshen_tpu.ops.soft_embedding import init_prompt_from_string


def test_sgu_zero_init_is_identity_gate():
    # zero spatial weight + ones bias => gate path == normed gate * 1,
    # so output == res * (bias-only mix) with no cross-position leakage.
    sgu = SpatialGatingUnit(d_ff=8, max_seq_len=16, causal=True)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 6, 16))
    params = sgu.init(jax.random.PRNGKey(1), x)
    out = sgu.apply(params, x)
    assert out.shape == (2, 6, 8)
    # at init the spatial weight is zero: perturbing position 0 of the
    # *gate* half must not change output at position 3
    x2 = x.at[:, 0, 8:].add(10.0)
    out2 = sgu.apply(params, x2)
    np.testing.assert_allclose(out[:, 3], out2[:, 3], atol=1e-6)


def test_gmlp_block_causality():
    blk = GMLPBlock(hidden_size=16, intermediate_size=32, max_seq_len=8,
                    causal=True)
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 8, 16))
    params = blk.init(jax.random.PRNGKey(1), x)
    # make the spatial weight non-trivial so mixing actually happens
    params = jax.tree_util.tree_map(lambda p: p, params)
    flat = params["params"]["sgu"]["spatial_weight"]
    params["params"]["sgu"]["spatial_weight"] = flat + 0.1
    out = blk.apply(params, x)
    # random (not constant — LayerNorm is shift-invariant) perturbations:
    noise = jax.random.normal(jax.random.PRNGKey(2), (16,))
    # perturb the LAST position: earlier positions must be unchanged
    x2 = x.at[:, -1].add(noise)
    out2 = blk.apply(params, x2)
    np.testing.assert_allclose(out[:, :-1], out2[:, :-1], atol=1e-5)
    # perturb the FIRST position: later positions must change
    x3 = x.at[:, 0].add(noise)
    out3 = blk.apply(params, x3)
    assert float(jnp.abs(out3[:, -1] - out[:, -1]).max()) > 1e-4


def test_gmlp_amlp_variant_runs():
    blk = GMLPBlock(hidden_size=16, intermediate_size=32, max_seq_len=8,
                    d_attn=8, causal=True)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 16))
    mask = jnp.tril(jnp.ones((8, 8), bool))[None]
    params = blk.init(jax.random.PRNGKey(1), x, mask)
    out = blk.apply(params, x, mask)
    assert out.shape == (2, 8, 16)


def test_gmlp_amlp_causal_without_mask():
    # causal=True must be safe even when the caller passes no mask — the
    # SGU builds the causal mask for tiny attention internally
    blk = GMLPBlock(hidden_size=16, intermediate_size=32, max_seq_len=8,
                    d_attn=8, causal=True)
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 8, 16))
    params = blk.init(jax.random.PRNGKey(1), x)
    out = blk.apply(params, x)
    noise = jax.random.normal(jax.random.PRNGKey(2), (16,))
    out2 = blk.apply(params, x.at[:, -1].add(noise))
    np.testing.assert_allclose(out[:, :-1], out2[:, :-1], atol=1e-5)


def test_soft_embedding_prepend_and_mask():
    mod = SoftEmbedding(n_tokens=4, hidden_size=8)
    emb = jnp.ones((2, 5, 8))
    mask = jnp.ones((2, 5), jnp.int32)
    params = mod.init(jax.random.PRNGKey(0), emb, mask)
    out, m = mod.apply(params, emb, mask)
    assert out.shape == (2, 9, 8) and m.shape == (2, 9)
    # prompt rows are the learned table, token rows untouched
    np.testing.assert_allclose(np.asarray(out[:, 4:]), np.ones((2, 5, 8)))
    # incremental decode: prepend=False passes through
    out2, m2 = mod.apply(params, emb, mask, prepend=False)
    assert out2.shape == (2, 5, 8)
    # max_len clamp (reference word_embeddings.py:204-205)
    out3, m3 = mod.apply(params, emb, mask, max_len=6)
    assert out3.shape == (2, 6, 8) and m3.shape == (2, 6)


def test_soft_embedding_string_init_tiles():
    wte = np.arange(40, dtype=np.float32).reshape(10, 4)
    init = init_prompt_from_string(wte, [3, 7], n_tokens=5)
    assert init.shape == (5, 4)
    np.testing.assert_allclose(init[0], wte[3])
    np.testing.assert_allclose(init[1], wte[7])
    np.testing.assert_allclose(init[2], wte[3])  # tiled
    mod = SoftEmbedding(n_tokens=5, hidden_size=4, init_value=init)
    emb = jnp.zeros((1, 2, 4))
    params = mod.init(jax.random.PRNGKey(0), emb)
    out, _ = mod.apply(params, emb)
    np.testing.assert_allclose(np.asarray(out[0, :5]), init)


def test_init_method_stds():
    cfg = SimpleNamespace(init_method="normal",
                          output_layer_init_method="scaled_normal",
                          init_method_std=0.02, hidden_size=256,
                          num_hidden_layers=8)
    init, out_init = get_init_methods(cfg)
    k = jax.random.PRNGKey(0)
    a = init(k, (2000, 2000), jnp.float32)
    b = out_init(k, (2000, 2000), jnp.float32)
    assert abs(float(a.std()) - 0.02) < 2e-3
    assert abs(float(b.std()) - 0.02 / math.sqrt(16)) < 2e-3
    # wang / small_init formulas
    cfg.init_method = "wang_init"
    cfg.output_layer_init_method = "small_init"
    w, s = get_init_methods(cfg)
    assert abs(float(w(k, (2000, 2000), jnp.float32).std())
               - 2 / 8 / math.sqrt(256)) < 2e-3
    assert abs(float(s(k, (2000, 2000), jnp.float32).std())
               - math.sqrt(2 / (5 * 256))) < 2e-3
