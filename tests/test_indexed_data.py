"""Indexed dataset + native helpers + blending + T5 span corruption tests."""

import numpy as np
import pytest

from fengshen_tpu.data.megatron_dataloader import (
    MMapIndexedDataset, MMapIndexedDatasetBuilder, BlendableDataset,
    GPTDataset)
from fengshen_tpu.data.megatron_dataloader.helpers import (
    _get_lib, build_sample_idx, build_blending_indices, build_mapping,
    build_blocks_mapping)


def _write_corpus(tmp_path, docs):
    prefix = str(tmp_path / "corpus")
    b = MMapIndexedDatasetBuilder(prefix, dtype=np.int32)
    for doc in docs:
        for sent in doc:
            b.add_item(sent)
        b.end_document()
    b.finalize()
    return prefix


def test_mmap_roundtrip(tmp_path):
    docs = [[[1, 2, 3], [4, 5]], [[6, 7, 8, 9]]]
    prefix = _write_corpus(tmp_path, docs)
    ds = MMapIndexedDataset(prefix)
    assert len(ds) == 3
    np.testing.assert_array_equal(ds[0], [1, 2, 3])
    np.testing.assert_array_equal(ds[1], [4, 5])
    np.testing.assert_array_equal(ds[2], [6, 7, 8, 9])
    np.testing.assert_array_equal(ds.doc_idx, [0, 2, 3])
    np.testing.assert_array_equal(ds.get(2, offset=1, length=2), [7, 8])
    assert MMapIndexedDataset.exists(prefix)


def test_native_lib_builds():
    assert _get_lib() is not None, "native helpers failed to build"


def test_build_sample_idx_native_matches_numpy():
    import fengshen_tpu.data.megatron_dataloader.helpers as H
    sizes = np.array([5, 3, 7, 2, 9], np.int32)
    doc_idx = np.array([2, 0, 4, 1, 3], np.int32)
    native = build_sample_idx(sizes, doc_idx, seq_length=4, num_epochs=1,
                              tokens_per_epoch=26)
    lib, H._lib, H._lib_tried = H._lib, None, True  # force numpy fallback
    try:
        fallback = build_sample_idx(sizes, doc_idx, seq_length=4,
                                    num_epochs=1, tokens_per_epoch=26)
    finally:
        H._lib, H._lib_tried = lib, True
    np.testing.assert_array_equal(native, fallback)
    # boundaries advance monotonically
    assert (np.diff(native[:, 0]) >= 0).all()


def test_gpt_dataset_packing(tmp_path):
    docs = [[list(range(10, 20))], [list(range(30, 45))],
            [list(range(50, 58))]]
    prefix = _write_corpus(tmp_path, docs)
    ds = GPTDataset(MMapIndexedDataset(prefix), seq_length=8, seed=3,
                    cache_dir=str(tmp_path / "cache"))
    assert len(ds) >= 3
    s = ds[0]
    # tile-aligned seq_length inputs; the training module owns the shift
    assert s["input_ids"].shape == (8,)
    np.testing.assert_array_equal(s["input_ids"], s["labels"])
    # contiguous packing: sample i is exactly stream[i*8 : i*8+8] of the
    # shuffled token stream (one-token-overlap windows minus the label tail)
    stream = np.concatenate([np.asarray(ds.indexed[int(j)])
                             for j in ds.seq_order])
    for i in range(len(ds)):
        np.testing.assert_array_equal(ds[i]["input_ids"],
                                      stream[i * 8: i * 8 + 8])
        assert (ds[i]["labels"] != -100).all()
    # cache file written and reused
    import os
    cached = os.listdir(tmp_path / "cache")
    assert any(f.endswith(".npy") for f in cached)
    ds2 = GPTDataset(MMapIndexedDataset(prefix), seq_length=8, seed=3,
                     cache_dir=str(tmp_path / "cache"))
    np.testing.assert_array_equal(np.asarray(ds.sample_idx),
                                  np.asarray(ds2.sample_idx))


def test_blending_matches_weights():
    class Const:
        def __init__(self, v):
            self.v = v

        def __len__(self):
            return 100

        def __getitem__(self, i):
            return self.v

    ds = BlendableDataset([Const(0), Const(1)], weights=[0.75, 0.25],
                          size=1000)
    picks = np.asarray([ds.dataset_index[i] for i in range(1000)])
    frac = (picks == 0).mean()
    assert abs(frac - 0.75) < 0.01
    assert ds[0] in (0, 1)


def test_build_mapping_windows():
    # 2 docs: doc0 has sentences sizes [4,5,6], doc1 [3,3]
    docs = np.array([0, 3, 5], np.int64)
    sizes = np.array([4, 5, 6, 3, 3], np.int32)
    maps = build_mapping(docs, sizes, max_seq_length=10,
                         short_seq_prob=0.0, seed=1)
    assert maps.shape[1] == 3
    assert len(maps) >= 2
    for start, end, target in maps:
        assert end - start >= 2  # pairable windows only
        assert target == 10


def test_build_blocks_mapping():
    docs = np.array([0, 3], np.int64)
    sizes = np.array([4, 5, 6], np.int32)
    maps = build_blocks_mapping(docs, sizes, max_seq_length=9)
    assert len(maps) == 2
    total = sum(int(m[2]) for m in maps)
    assert total == 15


# -- t5 span corruption ---------------------------------------------------

def test_compute_input_and_target_lengths():
    from fengshen_tpu.data.t5_dataloader import (
        compute_input_and_target_lengths)
    tokens_len, targets_len = compute_input_and_target_lengths(
        512, noise_density=0.15, mean_noise_span_length=3.0)
    assert tokens_len > 512  # raw text is longer than the corrupted input
    assert 0 < targets_len < 512


def test_random_spans_noise_mask():
    from fengshen_tpu.data.t5_dataloader import random_spans_noise_mask
    rng = np.random.RandomState(0)
    mask = random_spans_noise_mask(100, 0.15, 3.0, rng)
    assert mask.shape == (100,)
    assert abs(mask.sum() - 15) <= 1


def test_t5_collator_shapes():
    from fengshen_tpu.data.t5_dataloader import T5SpanCorruptionCollator

    class FakeTok:
        eos_token_id = 1
        pad_token_id = 0

        def __len__(self):
            return 120

        def encode(self, text, add_special_tokens=True):
            return [3 + (ord(c) % 90) for c in text]

    coll = T5SpanCorruptionCollator(FakeTok(), max_seq_length=32, seed=0)
    batch = coll([{"text": "hello world this is a span corruption test"},
                  {"text": "another document for the t5 pretraining"}])
    assert batch["input_ids"].shape == (2, 32)
    assert batch["decoder_input_ids"].shape[0] == 2
    assert batch["labels"].shape == batch["decoder_input_ids"].shape
    # sentinels present in the corrupted input (ids near vocab end)
    assert (batch["input_ids"] >= 110).any()
    # decoder input starts with decoder_start_token
    assert (batch["decoder_input_ids"][:, 0] == 0).all()


class _MiniTok:
    """Char-level tokenizer stub with BERT special ids."""

    cls_token_id, sep_token_id, mask_token_id, pad_token_id = 2, 3, 4, 0

    def __init__(self, n=80):
        self._vocab = {f"tok{i}": i for i in range(n)}

    def get_vocab(self):
        return self._vocab


def _corpus(tmp_path, n_docs=4, sents_per_doc=4):
    rng = np.random.RandomState(0)
    prefix = str(tmp_path / "bx")
    b = MMapIndexedDatasetBuilder(prefix, dtype=np.int32)
    for _ in range(n_docs):
        for _ in range(sents_per_doc):
            b.add_item(rng.randint(5, 79, rng.randint(4, 9)).tolist())
        b.end_document()
    b.finalize()
    return MMapIndexedDataset(prefix)


def test_bert_dataset_mlm_nsp(tmp_path):
    from fengshen_tpu.data.megatron_dataloader import BertDataset
    ds = BertDataset(_corpus(tmp_path), _MiniTok(), max_seq_length=48,
                     seed=1, zh_tokenizer=False)
    assert len(ds) > 0
    s = ds[0]
    assert s["input_ids"].shape == (48,)
    assert s["input_ids"][0] == 2  # [CLS]
    # MLM: some positions carry original-token labels, rest are -100
    assert (s["labels"] != -100).sum() > 0
    masked = s["labels"] != -100
    assert (s["input_ids"][masked] != s["labels"][masked]).any()
    assert s["next_sentence_label"] in (0, 1)
    # token types mark the A/B segments
    assert set(np.unique(s["token_type_ids"])) <= {0, 1}


def test_bart_dataset_denoising(tmp_path):
    from fengshen_tpu.data.megatron_dataloader import BartDataset
    ds = BartDataset(_corpus(tmp_path), _MiniTok(), max_seq_length=64,
                     seed=1, zh_tokenizer=False)
    assert len(ds) == 4
    s = ds[0]
    assert s["input_ids"].shape == (64,)
    assert s["input_ids"][0] == 2  # [CLS] stays first
    n_src = int(s["attention_mask"].sum())
    n_tgt = int((s["labels"] != -100).sum())
    # infilling shortens the source vs the clean target (+1 for no CLS)
    assert n_src < n_tgt + 1
    # mask token present in the corrupted source
    assert (s["input_ids"][:n_src] == 4).any()
    # labels are the CLEAN text (no masks)
    assert not (s["labels"][:n_tgt] == 4).any()


def test_dialog_collator():
    from fengshen_tpu.data.t5_dataloader import DialogCollator

    class FakeTok:
        eos_token_id = 1
        pad_token_id = 0
        sep_token_id = 3
        unk_token_id = 2

        def encode(self, text, add_special_tokens=True, **kw):
            return [5 + (ord(c) % 90) for c in text]

        def convert_tokens_to_ids(self, name):
            return self.unk_token_id  # markers not in vocab -> [SEP]

    coll = DialogCollator(FakeTok(), max_seq_length=32,
                          max_knowledge_length=8, max_target_length=8)
    batch = coll([{"context": ["你好", "你好呀今天想聊什么"],
                   "knowledge": "天气知识",
                   "target": "今天晴天"}])
    assert batch["input_ids"].shape == (1, 32)
    assert batch["labels"].shape == (1, 8)
    assert batch["decoder_input_ids"][0, 0] == 0
    # markers degraded to [SEP]=3 delimit knowledge/context
    assert (batch["input_ids"][0] == 3).sum() >= 4
