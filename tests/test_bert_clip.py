"""BERT parity + Taiyi-CLIP behaviour tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fengshen_tpu.models.bert import BertConfig, BertModel
from fengshen_tpu.models.clip import (CLIPVisionConfig, TaiyiCLIPModel,
                                      clip_contrastive_loss)

pytestmark = pytest.mark.slow  # full-fit/e2e lane: run with -m slow or no -m filter


def test_bert_forward_parity():
    torch = pytest.importorskip("torch")
    import transformers
    hf_cfg = transformers.BertConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, attn_implementation="eager")
    torch.manual_seed(0)
    tm = transformers.BertModel(hf_cfg).eval()
    cfg = BertConfig(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                     num_attention_heads=4, intermediate_size=64,
                     max_position_embeddings=64, dtype="float32")
    sd = tm.state_dict()

    def t(n):
        return sd[n].detach().numpy()

    def lin(p):
        return {"kernel": t(f"{p}.weight").T, "bias": t(f"{p}.bias")}

    def ln(p):
        return {"scale": t(f"{p}.weight"), "bias": t(f"{p}.bias")}

    params = {
        "word_embeddings": {
            "embedding": t("embeddings.word_embeddings.weight")},
        "position_embeddings": {
            "embedding": t("embeddings.position_embeddings.weight")},
        "token_type_embeddings": {
            "embedding": t("embeddings.token_type_embeddings.weight")},
        "embeddings_ln": ln("embeddings.LayerNorm"),
        "pooler": lin("pooler.dense"),
    }
    for i in range(2):
        pre = f"encoder.layer.{i}"
        params[f"layer_{i}"] = {
            "query": lin(f"{pre}.attention.self.query"),
            "key": lin(f"{pre}.attention.self.key"),
            "value": lin(f"{pre}.attention.self.value"),
            "attention_output_dense": lin(f"{pre}.attention.output.dense"),
            "attention_ln": ln(f"{pre}.attention.output.LayerNorm"),
            "intermediate_dense": lin(f"{pre}.intermediate.dense"),
            "output_dense": lin(f"{pre}.output.dense"),
            "output_ln": ln(f"{pre}.output.LayerNorm"),
        }
    ids = np.array([[2, 17, 9, 42, 7, 99, 1, 5]], dtype=np.int32)
    mask = np.array([[1, 1, 1, 1, 1, 1, 1, 1]], dtype=np.int32)
    hidden, pooled = BertModel(cfg).apply(
        {"params": params}, jnp.asarray(ids),
        attention_mask=jnp.asarray(mask))
    with torch.no_grad():
        out = tm(torch.tensor(ids, dtype=torch.long),
                 attention_mask=torch.tensor(mask, dtype=torch.long))
    np.testing.assert_allclose(np.asarray(hidden),
                               out.last_hidden_state.numpy(), atol=2e-3)
    np.testing.assert_allclose(np.asarray(pooled),
                               out.pooler_output.numpy(), atol=2e-3)


def test_taiyi_clip_shapes_and_loss():
    text_cfg = BertConfig.small_test_config(dtype="float32")
    vis_cfg = CLIPVisionConfig.small_test_config()
    model = TaiyiCLIPModel(text_cfg, vis_cfg)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 127, (4, 10)),
                      jnp.int32)
    pix = jnp.asarray(np.random.RandomState(1).rand(4, 32, 32, 3),
                      jnp.float32)
    params = model.init(jax.random.PRNGKey(0), ids, pix)["params"]
    text_emb, image_emb, scale = model.apply({"params": params}, ids, pix)
    assert text_emb.shape == (4, 16) and image_emb.shape == (4, 16)
    np.testing.assert_allclose(np.linalg.norm(np.asarray(text_emb), axis=-1),
                               1.0, atol=1e-5)
    loss, logits = clip_contrastive_loss(text_emb, image_emb, scale)
    assert logits.shape == (4, 4)
    assert np.isfinite(float(loss))
    # identical towers on matched pairs should beat shuffled pairs
    loss_shuf, _ = clip_contrastive_loss(text_emb, image_emb[::-1], scale)
    assert np.isfinite(float(loss_shuf))
