"""Disco guidance machinery (examples/disco_project/guidance.py —
VERDICT r4 missing #5 / weak #7: real capability behind the demo).

Losses are checked against a direct torch restatement of the reference
formulas (disco.py:354-370); cutouts for shape/content invariants; the
full CLIP-guided sampler end-to-end over the faithful SD towers.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fengshen_tpu.examples.disco_project.guidance import (
    DiscoConfig, make_cutouts, range_loss, sat_loss,
    spherical_dist_loss, tv_loss)

torch = pytest.importorskip("torch")


def test_losses_match_torch_reference():
    import torch.nn.functional as F

    rng = np.random.RandomState(0)
    img = rng.randn(2, 8, 8, 3).astype(np.float32) * 1.2
    t_img = torch.tensor(img.transpose(0, 3, 1, 2))

    # tv_loss: replicate pad + squared diffs (disco.py:360-366)
    pad = F.pad(t_img, (0, 1, 0, 1), "replicate")
    x_diff = pad[..., :-1, 1:] - pad[..., :-1, :-1]
    y_diff = pad[..., 1:, :-1] - pad[..., :-1, :-1]
    ref_tv = (x_diff ** 2 + y_diff ** 2).mean(dim=[1, 2, 3]).numpy()
    np.testing.assert_allclose(np.asarray(tv_loss(jnp.asarray(img))),
                               ref_tv, rtol=1e-5)

    # range_loss (disco.py:368-369)
    ref_range = ((t_img - t_img.clamp(-1, 1)) ** 2).mean(
        dim=[1, 2, 3]).numpy()
    np.testing.assert_allclose(
        np.asarray(range_loss(jnp.asarray(img))), ref_range, rtol=1e-5)

    # sat loss (cond_fn: disco.py:638)
    ref_sat = (t_img - t_img.clamp(-1, 1)).abs().mean().numpy()
    np.testing.assert_allclose(np.asarray(sat_loss(jnp.asarray(img))),
                               ref_sat, rtol=1e-5)

    # spherical distance (disco.py:354-357)
    x = rng.randn(4, 16).astype(np.float32)
    y = rng.randn(4, 16).astype(np.float32)
    tx, ty = torch.tensor(x), torch.tensor(y)
    ref = ((F.normalize(tx, dim=-1) - F.normalize(ty, dim=-1))
           .norm(dim=-1).div(2).arcsin().pow(2).mul(2)).numpy()
    np.testing.assert_allclose(
        np.asarray(spherical_dist_loss(jnp.asarray(x), jnp.asarray(y))),
        ref, rtol=1e-5)


def test_make_cutouts_shapes_and_variants():
    rng = np.random.RandomState(1)
    img = jnp.asarray(rng.rand(2, 16, 16, 3), jnp.float32)
    cuts = make_cutouts(jax.random.PRNGKey(0), img, cut_size=8,
                        overview=4, innercut=3, skip_augs=True)
    assert cuts.shape == (7 * 2, 8, 8, 3)
    # overview variant 1 is the grayscale of variant 0
    v0, v1 = np.asarray(cuts[0:2]), np.asarray(cuts[2:4])
    assert np.allclose(v1[..., 0], v1[..., 1])  # gray: channels equal
    assert not np.allclose(v0[..., 0], v0[..., 1])
    # variant 2 is the horizontal flip of variant 0
    v2 = np.asarray(cuts[4:6])
    np.testing.assert_allclose(v2, v0[:, :, ::-1], atol=1e-6)
    # jits (static counts, traced offsets)
    jitted = jax.jit(lambda r, x: make_cutouts(r, x, 8, 2, 2))
    out = jitted(jax.random.PRNGKey(1), img)
    assert out.shape == (4 * 2, 8, 8, 3)
    assert np.isfinite(np.asarray(out)).all()


def test_disco_phase_schedule():
    cfg = DiscoConfig()
    # late timesteps (early in sampling, t near 1000) use the EARLY phase
    assert cfg.phase(900) == (12, 4, 0.2)
    assert cfg.phase(300) == (4, 12, 0.0)


@pytest.mark.slow
def test_clip_guided_sample_faithful_towers_e2e(tmp_path):
    """guided_diffusion_demo produces an image end-to-end on the
    faithful SD towers (VERDICT r4 item 8's done-criterion)."""
    from fengshen_tpu.examples.disco_project.guided_diffusion_demo import (
        main)

    out_png = tmp_path / "disco.png"
    arr = main(argv=["--image_size", "16", "--num_steps", "3",
                     "--faithful_towers", "--tv_scale", "10",
                     "--sat_scale", "1",
                     "--output", str(out_png)])
    assert arr.shape == (1, 16, 16, 3)
    assert np.isfinite(arr).all()
    assert arr.min() >= 0.0 and arr.max() <= 1.0
    assert out_png.exists()
