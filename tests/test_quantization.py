"""int8 weight-only quantization tests (VERDICT r1 weak #9: the 8-bit Ziya
serving path)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def test_quantize_roundtrip_error_small():
    from fengshen_tpu.utils.quantization import (dequantize_params,
                                                 quantization_error,
                                                 quantize_params_int8,
                                                 quantized_nbytes)
    rng = np.random.RandomState(0)
    params = {"a": {"kernel": jnp.asarray(rng.randn(128, 64),
                                          jnp.float32)},
              "bias": jnp.asarray(rng.randn(64), jnp.float32)}
    q = quantize_params_int8(params, min_size=1024)
    # small leaves stay float; big kernels become int8+scale
    assert q["bias"].dtype == jnp.float32
    assert q["a"]["kernel"]["_int8"].dtype == jnp.int8
    # ~4x smaller for the quantized kernel
    assert q["a"]["kernel"]["_int8"].nbytes == \
        params["a"]["kernel"].nbytes // 4
    err = quantization_error(params, q)
    assert err < 0.01, err
    deq = dequantize_params(q, jnp.float32)
    assert deq["a"]["kernel"].shape == (128, 64)


@pytest.mark.xfail(
    reason="pre-existing at seed (NOTES.md tier-1 triage): on this "
           "jax/CPU build greedy argmax agreement lands at 0.8125 vs "
           "the 0.9 bar — random-init tiny-model logits sit too close "
           "to ties for int8 rounding; needs a margin-aware fixture "
           "(trained or scaled weights), not a threshold shave",
    strict=False)
def test_quantized_generation_matches_fp_greedy():
    """Greedy decode with int8 weights must match full-precision on a
    small model (weight-only quantization preserves argmax almost
    everywhere at this scale)."""
    from fengshen_tpu.examples.ziya_inference.generate_ziya_int8 import (
        quantized_generate)
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.utils.generate import generate
    from fengshen_tpu.utils.quantization import quantize_params_int8

    cfg = LlamaConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      max_position_embeddings=64, dtype="float32")
    model = LlamaForCausalLM(cfg)
    ids = jnp.asarray(np.random.RandomState(0).randint(3, 120, (1, 8)),
                      jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]

    full = generate(model, params, ids, max_new_tokens=8)
    q = quantize_params_int8(params, min_size=512)
    quant = quantized_generate(model, q, ids, max_new_tokens=8)
    agree = float((np.asarray(full) == np.asarray(quant)).mean())
    assert agree > 0.9, agree


def test_int8_matmul_numerics_and_grads():
    """Dynamic int8 x int8 matmul (ops/int8_matmul.py): forward within
    quantization error of the exact matmul; backward is the exact
    (straight-through) gradient."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fengshen_tpu.ops.int8_matmul import int8_matmul

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(4, 8, 64), jnp.float32)
    w = jnp.asarray(rng.randn(64, 128) * 0.05, jnp.float32)

    exact = x @ w
    approx = int8_matmul(x, w)
    rel = float(jnp.linalg.norm(approx - exact) /
                jnp.linalg.norm(exact))
    assert rel < 2e-2, f"int8 forward rel error {rel:.4f}"

    def loss_q(x, w):
        return (int8_matmul(x, w) ** 2).mean()

    def loss_e(x, w):
        return ((x @ w) ** 2).mean()

    gq_x, gq_w = jax.grad(loss_q, argnums=(0, 1))(x, w)
    ge_x, ge_w = jax.grad(loss_e, argnums=(0, 1))(x, w)
    # straight-through backward: d(loss)/dx = 2/N * (y_q @ w.T) — equals
    # the exact-matmul gradient up to the forward's quantization noise
    assert float(jnp.linalg.norm(gq_x - ge_x) /
                 jnp.linalg.norm(ge_x)) < 5e-2
    assert float(jnp.linalg.norm(gq_w - ge_w) /
                 jnp.linalg.norm(ge_w)) < 5e-2


def test_int8_lm_head_llama_forward_and_params():
    """cfg.int8_lm_head keeps the lm_head/kernel param path (partition
    rules + converters unchanged) and yields close logits."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    base = LlamaConfig(vocab_size=64, hidden_size=32,
                       intermediate_size=64, num_hidden_layers=2,
                       num_attention_heads=4,
                       max_position_embeddings=32, dtype="float32",
                       tie_word_embeddings=False)
    ids = jnp.ones((2, 8), jnp.int32)
    model = LlamaForCausalLM(base)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    assert "kernel" in params["lm_head"]

    q_model = LlamaForCausalLM(
        dataclasses.replace(base, int8_lm_head=True))
    q_params = q_model.init(jax.random.PRNGKey(0), ids)["params"]
    # identical tree structure: int8 head is a drop-in
    assert jax.tree_util.tree_structure(params) == \
        jax.tree_util.tree_structure(q_params)
    exact = model.apply({"params": params}, ids)
    approx = q_model.apply({"params": params}, ids)
    rel = float(jnp.linalg.norm(approx - exact) /
                jnp.linalg.norm(exact))
    assert rel < 5e-2

    # tied variant routes through int8 too
    tied = LlamaForCausalLM(dataclasses.replace(
        base, tie_word_embeddings=True, int8_lm_head=True))
    tied_params = tied.init(jax.random.PRNGKey(0), ids)["params"]
    assert tied.apply({"params": tied_params}, ids).shape == (2, 8, 64)
