"""int8 weight-only quantization tests (VERDICT r1 weak #9: the 8-bit Ziya
serving path)."""

import jax
import jax.numpy as jnp
import numpy as np


def test_quantize_roundtrip_error_small():
    from fengshen_tpu.utils.quantization import (dequantize_params,
                                                 quantization_error,
                                                 quantize_params_int8,
                                                 quantized_nbytes)
    rng = np.random.RandomState(0)
    params = {"a": {"kernel": jnp.asarray(rng.randn(128, 64),
                                          jnp.float32)},
              "bias": jnp.asarray(rng.randn(64), jnp.float32)}
    q = quantize_params_int8(params, min_size=1024)
    # small leaves stay float; big kernels become int8+scale
    assert q["bias"].dtype == jnp.float32
    assert q["a"]["kernel"]["_int8"].dtype == jnp.int8
    # ~4x smaller for the quantized kernel
    assert q["a"]["kernel"]["_int8"].nbytes == \
        params["a"]["kernel"].nbytes // 4
    err = quantization_error(params, q)
    assert err < 0.01, err
    deq = dequantize_params(q, jnp.float32)
    assert deq["a"]["kernel"].shape == (128, 64)


def test_quantized_generation_matches_fp_greedy():
    """Greedy decode with int8 weights must match full-precision on a
    small model (weight-only quantization preserves argmax almost
    everywhere at this scale)."""
    from fengshen_tpu.examples.ziya_inference.generate_ziya_int8 import (
        quantized_generate)
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.utils.generate import generate
    from fengshen_tpu.utils.quantization import quantize_params_int8

    cfg = LlamaConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      max_position_embeddings=64, dtype="float32")
    model = LlamaForCausalLM(cfg)
    ids = jnp.asarray(np.random.RandomState(0).randint(3, 120, (1, 8)),
                      jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]

    full = generate(model, params, ids, max_new_tokens=8)
    q = quantize_params_int8(params, min_size=512)
    quant = quantized_generate(model, q, ids, max_new_tokens=8)
    agree = float((np.asarray(full) == np.asarray(quant)).mean())
    assert agree > 0.9, agree
