"""int8 weight-only quantization tests (VERDICT r1 weak #9: the 8-bit Ziya
serving path)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def test_quantize_roundtrip_error_small():
    from fengshen_tpu.utils.quantization import (dequantize_params,
                                                 quantization_error,
                                                 quantize_params_int8,
                                                 quantized_nbytes)
    rng = np.random.RandomState(0)
    params = {"a": {"kernel": jnp.asarray(rng.randn(128, 64),
                                          jnp.float32)},
              "bias": jnp.asarray(rng.randn(64), jnp.float32)}
    q = quantize_params_int8(params, min_size=1024)
    # small leaves stay float; big kernels become int8+scale
    assert q["bias"].dtype == jnp.float32
    assert q["a"]["kernel"]["_int8"].dtype == jnp.int8
    # ~4x smaller for the quantized kernel
    assert q["a"]["kernel"]["_int8"].nbytes == \
        params["a"]["kernel"].nbytes // 4
    err = quantization_error(params, q)
    assert err < 0.01, err
    deq = dequantize_params(q, jnp.float32)
    assert deq["a"]["kernel"].shape == (128, 64)


def test_quantized_generation_matches_fp_greedy():
    """Greedy decode with int8 weights, judged margin-aware (NOTES.md
    triage item 2 — the old fixture xfailed at 0.8125 raw agreement
    because random-init tiny-model logits sit in near-ties that int8
    rounding legitimately flips).

    The margin-aware bar: quantization noise must never flip a
    CONFIDENT decision. The lm_head is scaled up so top-2 logit gaps
    dominate the rounding noise on enough positions to make the test
    non-vacuous; "confident" is judged per position against the
    DIRECTLY MEASURED teacher-forced logit perturbation (fp vs
    dequantized-int8 forward on the same sequence — no drift), and
    agreement is asserted on confident positions only. The
    autoregressive decode may only diverge at an unconfident step."""
    from fengshen_tpu.examples.ziya_inference.generate_ziya_int8 import (
        quantized_generate)
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.utils.generate import generate
    from fengshen_tpu.utils.quantization import (dequantize_params,
                                                 quantize_params_int8)

    cfg = LlamaConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      max_position_embeddings=64, dtype="float32")
    model = LlamaForCausalLM(cfg)
    ids = jnp.asarray(np.random.RandomState(0).randint(3, 120, (1, 8)),
                      jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    # sharpen the top-2 gaps past int8 rounding noise (the margins and
    # the head's own noise scale together; what this buys is headroom
    # over the earlier layers' fixed perturbation)
    params = dict(params,
                  lm_head={"kernel": params["lm_head"]["kernel"] * 4.0})
    prompt_len, max_new = ids.shape[1], 8

    full = np.asarray(generate(model, params, ids,
                               max_new_tokens=max_new))
    q = quantize_params_int8(params, min_size=512)
    quant = np.asarray(quantized_generate(model, q, ids,
                                          max_new_tokens=max_new))

    # teacher-forced on the fp trajectory: per-position noise + margin
    seq = jnp.asarray(full[0])[None]
    logits_fp = np.asarray(model.apply({"params": params}, seq))[0]
    logits_q = np.asarray(model.apply(
        {"params": dequantize_params(q)}, seq).astype(jnp.float32))[0]
    gen_pos = range(prompt_len - 1, prompt_len + max_new - 1)
    confident = 0
    for t in gen_pos:
        noise = float(np.abs(logits_fp[t] - logits_q[t]).max())
        top2 = np.sort(logits_fp[t])[-2:]
        if top2[1] - top2[0] <= 2 * noise:
            continue                       # a legitimate near-tie
        confident += 1
        assert logits_fp[t].argmax() == logits_q[t].argmax(), (
            f"int8 flipped a confident position {t}: margin "
            f"{top2[1] - top2[0]:.4f} vs noise {noise:.4f}")
    assert confident >= 3, (
        f"fixture went vacuous: only {confident} confident positions")

    # the autoregressive decode may only leave the fp trajectory at an
    # unconfident step (after that, drift makes tokens incomparable)
    for t in range(max_new):
        a, b = full[0, prompt_len + t], quant[0, prompt_len + t]
        if a == b:
            continue
        pos = prompt_len + t - 1
        noise = float(np.abs(logits_fp[pos] - logits_q[pos]).max())
        top2 = np.sort(logits_fp[pos])[-2:]
        assert top2[1] - top2[0] <= 2 * noise, (
            f"greedy decode diverged at CONFIDENT step {t}")
        break


def test_int8_matmul_numerics_and_grads():
    """Dynamic int8 x int8 matmul (ops/int8_matmul.py): forward within
    quantization error of the exact matmul; backward is the exact
    (straight-through) gradient."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fengshen_tpu.ops.int8_matmul import int8_matmul

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(4, 8, 64), jnp.float32)
    w = jnp.asarray(rng.randn(64, 128) * 0.05, jnp.float32)

    exact = x @ w
    approx = int8_matmul(x, w)
    rel = float(jnp.linalg.norm(approx - exact) /
                jnp.linalg.norm(exact))
    assert rel < 2e-2, f"int8 forward rel error {rel:.4f}"

    def loss_q(x, w):
        return (int8_matmul(x, w) ** 2).mean()

    def loss_e(x, w):
        return ((x @ w) ** 2).mean()

    gq_x, gq_w = jax.grad(loss_q, argnums=(0, 1))(x, w)
    ge_x, ge_w = jax.grad(loss_e, argnums=(0, 1))(x, w)
    # straight-through backward: d(loss)/dx = 2/N * (y_q @ w.T) — equals
    # the exact-matmul gradient up to the forward's quantization noise
    assert float(jnp.linalg.norm(gq_x - ge_x) /
                 jnp.linalg.norm(ge_x)) < 5e-2
    assert float(jnp.linalg.norm(gq_w - ge_w) /
                 jnp.linalg.norm(ge_w)) < 5e-2


def test_int8_lm_head_llama_forward_and_params():
    """cfg.int8_lm_head keeps the lm_head/kernel param path (partition
    rules + converters unchanged) and yields close logits."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    base = LlamaConfig(vocab_size=64, hidden_size=32,
                       intermediate_size=64, num_hidden_layers=2,
                       num_attention_heads=4,
                       max_position_embeddings=32, dtype="float32",
                       tie_word_embeddings=False)
    ids = jnp.ones((2, 8), jnp.int32)
    model = LlamaForCausalLM(base)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    assert "kernel" in params["lm_head"]

    q_model = LlamaForCausalLM(
        dataclasses.replace(base, int8_lm_head=True))
    q_params = q_model.init(jax.random.PRNGKey(0), ids)["params"]
    # identical tree structure: int8 head is a drop-in
    assert jax.tree_util.tree_structure(params) == \
        jax.tree_util.tree_structure(q_params)
    exact = model.apply({"params": params}, ids)
    approx = q_model.apply({"params": params}, ids)
    rel = float(jnp.linalg.norm(approx - exact) /
                jnp.linalg.norm(exact))
    assert rel < 5e-2

    # tied variant routes through int8 too
    tied = LlamaForCausalLM(dataclasses.replace(
        base, tie_word_embeddings=True, int8_lm_head=True))
    tied_params = tied.init(jax.random.PRNGKey(0), ids)["params"]
    assert tied.apply({"params": tied_params}, ids).shape == (2, 8, 64)
