"""bench.py harness guard: every mode must produce its one JSON line on
the CPU mesh with tiny env shapes. The driver's BENCH artifact is the
round's perf signal — a harness regression (bad flag wiring, broken
lever path) must fail HERE, not on the one healthy-relay window.
"""

import io
import json
import os
from contextlib import redirect_stdout

import pytest

pytestmark = pytest.mark.slow

TINY = {"BENCH_SEQ": "64", "BENCH_VOCAB": "256", "BENCH_HIDDEN": "64",
        "BENCH_INTER": "128", "BENCH_LAYERS": "2", "BENCH_HEADS": "4",
        "BENCH_BATCH": "2", "BENCH_ATTN": "dense",
        "BENCH_SKIP_PROBE": "1",
        # stay in-process: the CPU-fallback wrapper would re-exec bench
        # in a child whose stdout escapes redirect_stdout
        "BENCH_CHILD": "1"}


def _run_bench(monkeypatch, env: dict) -> dict:
    import importlib

    import bench

    for key in list(os.environ):
        if key.startswith("BENCH_"):
            monkeypatch.delenv(key)
    for key, val in {**TINY, **env}.items():
        monkeypatch.setenv(key, val)
    importlib.reload(bench)
    out = io.StringIO()
    with redirect_stdout(out):
        bench.main()
    lines = [l for l in out.getvalue().splitlines() if l.startswith("{")]
    assert lines, out.getvalue()
    row = json.loads(lines[-1])
    assert set(row) >= {"metric", "value", "unit", "vs_baseline"}
    assert row["value"] > 0
    return row


def test_bench_default_mode(monkeypatch):
    row = _run_bench(monkeypatch, {})
    assert row["metric"] == "llama300m_train_tokens_per_sec_per_chip"


def test_bench_default_levers(monkeypatch):
    row = _run_bench(monkeypatch, {"BENCH_INT8_LMHEAD": "1",
                                   "BENCH_FUSED_CE": "4"})
    # the int8 lever changes numerics, so its row carries its own name
    assert row["metric"] == \
        "llama300m_int8_train_tokens_per_sec_per_chip"


def test_bench_lora_lever(monkeypatch):
    row = _run_bench(monkeypatch, {"BENCH_LORA": "2"})
    assert row["metric"] == "llama300m_lora_train_tokens_per_sec_per_chip"


def test_bench_sharded_and_offload(monkeypatch):
    """Seed-failing until ISSUE 9: the offload row hard-coded
    pinned_host and raised at sharding construction on this backend.
    The capability probe (docs/offload.md) resolves the host kind, and
    the row records the RESOLVED placement so benchdiff never compares
    across placements."""
    from fengshen_tpu.trainer.memory import probe_memory_capabilities

    row = _run_bench(monkeypatch, {"BENCH_CONFIG": "sharded",
                                   "BENCH_FSDP": "2", "BENCH_TP": "2",
                                   "BENCH_OFFLOAD": "1"})
    assert row["metric"] == \
        "llama300m_offload_update_tokens_per_sec_per_chip"
    assert row["offload"] == "opt"
    assert row["memory_kind"] == probe_memory_capabilities().host_kind


def test_bench_sharded_offload_opt_master(monkeypatch):
    row = _run_bench(monkeypatch, {"BENCH_CONFIG": "sharded",
                                   "BENCH_OFFLOAD": "opt_master"})
    assert row["metric"] == \
        "llama300m_offload_update_tokens_per_sec_per_chip"
    assert row["offload"] == "opt_master"


def test_bench_sharded_offload_auto_matches_plain_row(monkeypatch):
    """Acceptance (ISSUE 9): a small-shape rung at --offload=auto is
    within 5% tokens/s of --offload=none. On a shape that fits, auto
    resolves to level "none" and runs the IDENTICAL fused step program
    — the row keeps the base metric name and carries no placement
    fields, so the <5% bar holds by construction (same program, and
    benchdiff treats the rows as directly comparable)."""
    row = _run_bench(monkeypatch, {"BENCH_CONFIG": "sharded",
                                   "BENCH_OFFLOAD": "auto"})
    assert row["metric"] == \
        "llama300m_sharded_step_tokens_per_sec_per_chip"
    assert "offload" not in row and "memory_kind" not in row


def test_bench_offload_request_mapping(capsys):
    """BENCH_OFFLOAD contract: legacy truthy ints -> opt, ladder names
    pass through, unknown values WARN and fall back instead of letting
    the Trainer's argparse choices SystemExit the whole bench run."""
    import bench

    for raw, expect in (("", "none"), ("0", "none"), ("1", "opt"),
                        ("2", "opt"), ("auto", "auto"), ("opt", "opt"),
                        ("opt_master", "opt_master"), ("none", "none")):
        os.environ["BENCH_OFFLOAD"] = raw
        try:
            assert bench._offload_request() == expect, raw
        finally:
            del os.environ["BENCH_OFFLOAD"]
    os.environ["BENCH_OFFLOAD"] = "zero3"
    try:
        assert bench._offload_request("auto") == "auto"
    finally:
        del os.environ["BENCH_OFFLOAD"]
    assert "unrecognized BENCH_OFFLOAD" in capsys.readouterr().err


def test_bench_large_ladder_rung(monkeypatch):
    """Seed-failing until ISSUE 9 (same pinned_host abort as the
    offload row — the large mode always offloaded): the rung now runs
    end-to-end at the level --offload=auto resolves on the live
    backend."""
    row = _run_bench(monkeypatch, {"BENCH_CONFIG": "large",
                                   "BENCH_KV": "2",
                                   "BENCH_FUSED_CE": "4"})
    assert row["metric"].startswith("llama13bshape_l2")


def test_bench_decode_greedy(monkeypatch):
    row = _run_bench(monkeypatch, {"BENCH_CONFIG": "decode",
                                   "BENCH_PROMPT": "16",
                                   "BENCH_NEW_TOKENS": "16",
                                   "BENCH_DECODE_RUNS": "1"})
    assert row["metric"] == "llama300m_decode_tokens_per_sec_per_chip"


def test_bench_decode_int8(monkeypatch):
    row = _run_bench(monkeypatch, {"BENCH_CONFIG": "decode",
                                   "BENCH_INT8_LMHEAD": "1",
                                   "BENCH_PROMPT": "16",
                                   "BENCH_NEW_TOKENS": "16",
                                   "BENCH_DECODE_RUNS": "1"})
    assert row["metric"] == \
        "llama300m_int8_decode_tokens_per_sec_per_chip"


def test_bench_decode_spec(monkeypatch):
    row = _run_bench(monkeypatch, {"BENCH_CONFIG": "decode",
                                   "BENCH_DECODE": "spec",
                                   "BENCH_SPEC_GAMMA": "2",
                                   "BENCH_DRAFT_LAYERS": "1",
                                   "BENCH_PROMPT": "16",
                                   "BENCH_NEW_TOKENS": "16",
                                   "BENCH_DECODE_RUNS": "1"})
    assert row["metric"] == \
        "llama300m_spec_decode_tokens_per_sec_per_chip"


def test_bench_decode_lookup(monkeypatch):
    row = _run_bench(monkeypatch, {"BENCH_CONFIG": "decode",
                                   "BENCH_DECODE": "lookup",
                                   "BENCH_SPEC_GAMMA": "2",
                                   "BENCH_PROMPT": "16",
                                   "BENCH_NEW_TOKENS": "16",
                                   "BENCH_DECODE_RUNS": "1"})
    assert row["metric"] == \
        "llama300m_lookup_decode_tokens_per_sec_per_chip"


def test_bench_decode_beam(monkeypatch):
    row = _run_bench(monkeypatch, {"BENCH_CONFIG": "decode",
                                   "BENCH_DECODE": "beam",
                                   "BENCH_PROMPT": "16",
                                   "BENCH_NEW_TOKENS": "16",
                                   "BENCH_DECODE_RUNS": "1"})
    assert row["metric"] == "t5beam4_decode_tokens_per_sec_per_chip"


# ---- fresh-process OOM ladder (round-5 fix) -------------------------
# The first healthy relay in three rounds crashed three bench modes:
# runtime OOMs surface as a bare "ResourceExhausted" (not "Ran out of
# memory"), and an OOM'd rung's relay-side buffers OOM the NEXT rung
# when rungs share a process. The ladder now matches both signatures
# and runs each rung via _spawn_rung; these tests drive the ladder
# decision logic through a stub spawner.


def test_is_oom_text_matches_both_relay_forms():
    import bench

    assert bench._is_oom_text(
        "RESOURCE_EXHAUSTED: TPU backend error (ResourceExhausted).")
    assert bench._is_oom_text(
        "XlaRuntimeError: Ran out of memory in memory space hbm")
    assert not bench._is_oom_text("INTERNAL: HTTP 500: compile helper")


def test_ladder_steps_down_on_oom_then_stops():
    import bench

    calls = []

    def spawn(env):
        calls.append(env)
        return (0, "") if len(calls) == 3 else \
            (1, "jax.errors.JaxRuntimeError: RESOURCE_EXHAUSTED: TPU "
                "backend error (ResourceExhausted).")

    bench._ladder_of_rungs(
        [{"BENCH_BATCH": b} for b in (28, 24, 16, 8)], "t",
        spawn=spawn)
    assert [c["BENCH_BATCH"] for c in calls] == [28, 24, 16]


def test_ladder_aborts_on_wedge_without_retrying(capsys):
    import bench

    calls = []

    def spawn(env):
        calls.append(env)
        return 1, ("bench watchdog (thread): accelerator unresponsive,"
                   " aborting")

    with pytest.raises(SystemExit):
        bench._ladder_of_rungs([{"BENCH_BATCH": 28},
                                {"BENCH_BATCH": 8}], "t", spawn=spawn)
    assert len(calls) == 1  # no pointless probes against a dead relay


def test_ladder_propagates_non_oom_failure():
    import bench

    def spawn(env):
        return 7, "ValueError: something real broke"

    with pytest.raises(SystemExit) as exc:
        bench._ladder_of_rungs([{"BENCH_BATCH": 28},
                                {"BENCH_BATCH": 8}], "t", spawn=spawn)
    assert exc.value.code == 7


def test_ladder_raises_when_every_rung_ooms():
    import bench

    def spawn(env):
        return 1, "Ran out of memory in memory space hbm"

    with pytest.raises(RuntimeError, match="every ladder rung OOM"):
        bench._ladder_of_rungs([{"BENCH_BATCH": 28}], "t", spawn=spawn)


def test_bench_sharded_steps_per_exec(monkeypatch):
    row = _run_bench(monkeypatch, {"BENCH_CONFIG": "sharded",
                                   "BENCH_STEPS_PER_EXEC": "3"})
    assert row["metric"] == "llama300m_sharded_step_tokens_per_sec_per_chip"


# ---- CPU fallback rung (always emit the one JSON line) --------------
# Five BENCH rounds ended `parsed: null`: the relay wedged and the
# watchdog's os._exit killed the process before any JSON. The top-level
# wrapper now reruns ONCE on the CPU backend with tiny shapes, flagged
# degraded, so the driver always gets a number it can label honestly.


def test_cpu_fallback_engages_on_wedge_only():
    import bench

    calls = []

    def spawn(env):
        calls.append(env)
        if len(calls) == 1:
            return 1, "bench watchdog: accelerator unresponsive, aborting"
        return 0, ""

    with pytest.raises(SystemExit) as exc:
        bench._run_with_cpu_fallback(spawn=spawn)
    assert exc.value.code == 0
    assert calls[0] == {"BENCH_CHILD": "1"}
    rescue = calls[1]
    assert rescue["JAX_PLATFORMS"] == "cpu"
    assert rescue["BENCH_DEGRADED"] == "1"
    assert rescue["BENCH_CHILD"] == "1"


def test_cpu_fallback_propagates_non_wedge_failures():
    import bench

    def spawn(env):
        return 3, "Ran out of memory in memory space hbm"

    with pytest.raises(SystemExit) as exc:
        bench._run_with_cpu_fallback(spawn=spawn)
    # an OOM (or any non-wedge rc) must surface, not be masked by a
    # degraded CPU number
    assert exc.value.code == 3


def test_cpu_fallback_env_pins_every_mode():
    import bench

    for mode in ("default", "large", "sharded", "decode"):
        env = bench._cpu_fallback_env(mode)
        assert env["BENCH_DEGRADED"] == "1"
        assert env["BENCH_CHILD"] == "1"
        assert "BENCH_BATCH" in env  # every mode runs pinned, no ladder
    assert bench._cpu_fallback_env("large")["BENCH_LAYERS"] == "2"


def test_degraded_flag_lands_in_json(monkeypatch):
    row = _run_bench(monkeypatch, {"BENCH_DEGRADED": "1"})
    assert row["degraded"] is True
    assert row["metric"] == "llama300m_train_tokens_per_sec_per_chip"
