"""Smoke tests for pipeline-driver examples (ubert/unimc/uniex), DeltaLM
translation, and ZEN1 finetune — tiny data, 8-device CPU mesh."""

import json



import numpy as np
import pytest

pytestmark = pytest.mark.slow  # full-fit/e2e lane: run with -m slow or no -m filter



def _bert_tokenizer_dir(tmp_path):
    from transformers import BertTokenizer
    chars = list("彭小军认为国内银行现在走的是台湾发卡模式就天涯网推出彩票服务"
                 "频道凌云研发产两轮电动车怎么样有什惊喜街头偶遇长安颜值美炸"
                 "教育科技军事旅游房汽产中英文测试句子好很大新闻类别属于下面")
    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + \
        sorted(set(chars))
    vf = tmp_path / "vocab.txt"
    vf.write_text("\n".join(vocab))
    tok = BertTokenizer(str(vf))
    model_dir = tmp_path / "model"
    model_dir.mkdir(exist_ok=True)
    tok.save_pretrained(str(model_dir))
    return tok, model_dir


def _tiny_trainer_args(parser_builder, tmp_path, extra=()):
    import argparse
    parser = argparse.ArgumentParser()
    parser = parser_builder(parser)
    return parser.parse_args([
        "--train_batchsize", "2", "--max_steps", "2",
        "--log_every_n_steps", "1", "--warmup_steps", "1",
        "--default_root_dir", str(tmp_path / "runs"), *extra])


def test_ubert_example_fit_predict(tmp_path, mesh8):
    from fengshen_tpu.examples.ubert import example
    from fengshen_tpu.models.megatron_bert import MegatronBertConfig
    from fengshen_tpu.pipelines.information_extraction import Pipeline
    tok, _ = _bert_tokenizer_dir(tmp_path)
    cfg = MegatronBertConfig.small_test_config(vocab_size=len(tok))
    args = _tiny_trainer_args(Pipeline.pipelines_args, tmp_path,
                              ["--max_length", "64"])
    pipe = Pipeline(args, tokenizer=tok, config=cfg)
    result = example.main(argv=[
        "--train_batchsize", "2", "--max_steps", "2",
        "--log_every_n_steps", "1", "--warmup_steps", "1",
        "--default_root_dir", str(tmp_path / "runs"),
        "--max_length", "64"], pipeline=pipe)
    assert len(result) == 1
    assert all("entity_list" in c for c in result[0]["choices"])


def test_unimc_example_train_predict(tmp_path, mesh8):
    from fengshen_tpu.examples.unimc import example
    from fengshen_tpu.models.megatron_bert import MegatronBertConfig
    from fengshen_tpu.pipelines.multiplechoice import Pipeline
    tok, _ = _bert_tokenizer_dir(tmp_path)
    cfg = MegatronBertConfig.small_test_config(vocab_size=len(tok))
    args = _tiny_trainer_args(Pipeline.add_pipeline_specific_args, tmp_path)
    pipe = Pipeline(args, tokenizer=tok, config=cfg)
    result = example.main(argv=[
        "--train_batchsize", "2", "--max_steps", "2",
        "--log_every_n_steps", "1", "--warmup_steps", "1",
        "--default_root_dir", str(tmp_path / "runs")], pipeline=pipe)
    assert len(result) == 1 and 0 <= result[0] < 4


def test_uniex_example_predict(tmp_path, mesh8):
    from fengshen_tpu.examples.uniex import example
    from fengshen_tpu.models.megatron_bert import MegatronBertConfig
    from fengshen_tpu.models.uniex import UniEXPipelines
    import argparse
    tok, _ = _bert_tokenizer_dir(tmp_path)
    cfg = MegatronBertConfig.small_test_config(vocab_size=len(tok))
    parser = UniEXPipelines.pipelines_args(argparse.ArgumentParser())
    args = parser.parse_args(["--max_length", "64"])
    pipe = UniEXPipelines(args, tokenizer=tok, config=cfg)
    result = example.main(argv=[], pipeline=pipe)
    assert len(result) == 1


def test_translate_deltalm_e2e(tmp_path, mesh8):
    from fengshen_tpu.examples.translate import finetune_deltalm
    from fengshen_tpu.models.deltalm import DeltaLMConfig
    import dataclasses
    import json as _json
    import os
    tok, model_dir = _bert_tokenizer_dir(tmp_path)
    cfg = DeltaLMConfig.small_test_config(vocab_size=len(tok))
    with open(os.path.join(model_dir, "config.json"), "w") as f:
        _json.dump(dataclasses.asdict(cfg), f)
    train = tmp_path / "train.json"
    with open(train, "w") as f:
        for _ in range(8):
            f.write(json.dumps({"src": "中文测试句子很好",
                                "tgt": "英文测试句子很大"},
                               ensure_ascii=False) + "\n")
    finetune_deltalm.main([
        "--model_path", str(model_dir), "--train_file", str(train),
        "--train_batchsize", "2", "--max_steps", "2",
        "--log_every_n_steps", "1", "--warmup_steps", "1",
        "--default_root_dir", str(tmp_path / "runs"),
        "--save_ckpt_path", str(tmp_path / "ckpt"),
        "--load_ckpt_path", str(tmp_path / "ckpt"),
        "--max_enc_length", "16", "--max_dec_length", "16", "--seed", "1"])
    lines = [json.loads(l) for l in open(tmp_path / "runs" / "metrics.jsonl")]
    losses = [l["loss"] for l in lines if "loss" in l]
    assert len(losses) == 2 and all(np.isfinite(losses))


def test_zen1_finetune_e2e(tmp_path, mesh8):
    from fengshen_tpu.examples.zen1_finetune import (
        fengshen_sequence_level_ft_task as task)
    from fengshen_tpu.models.zen import ZenConfig
    import dataclasses
    import json as _json
    import os
    tok, model_dir = _bert_tokenizer_dir(tmp_path)
    cfg = ZenConfig.small_test_config(vocab_size=len(tok))
    with open(os.path.join(model_dir, "config.json"), "w") as f:
        _json.dump(dataclasses.asdict(cfg), f)
    (model_dir / "ngram.txt").write_text("中文,5\n测试,3\n句子,2\n")
    train = tmp_path / "train.json"
    with open(train, "w") as f:
        for i in range(8):
            f.write(json.dumps({"sentence": "中文测试句子很好",
                                "label": i % 2}, ensure_ascii=False) + "\n")
    task.main([
        "--model_path", str(model_dir), "--train_file", str(train),
        "--train_batchsize", "2", "--max_steps", "2",
        "--log_every_n_steps", "1", "--warmup_steps", "1",
        "--default_root_dir", str(tmp_path / "runs"),
        "--save_ckpt_path", str(tmp_path / "ckpt"),
        "--load_ckpt_path", str(tmp_path / "ckpt"),
        "--max_seq_length", "32", "--num_labels", "2", "--seed", "1"])
    lines = [json.loads(l) for l in open(tmp_path / "runs" / "metrics.jsonl")]
    losses = [l["loss"] for l in lines if "loss" in l]
    assert len(losses) == 2 and all(np.isfinite(losses))


def test_zen_ngram_dict_match(tmp_path):
    from fengshen_tpu.models.zen import ZenNgramDict
    p = tmp_path / "ngram.txt"
    p.write_text("中文,5\n测试句,3\n")
    d = ZenNgramDict(str(p), max_ngram_in_seq=8)
    ids, pos = d.match(list("中文测试句子"))
    assert (ids > 0).sum() == 2
    # "中文" covers chars 0-1, "测试句" covers 2-4
    assert pos[0, 0] == 1 and pos[1, 0] == 1
    assert pos[2, 1] == 1 and pos[4, 1] == 1
