"""Sequence-packing SFT: packed rows must train identically to padded
rows (same per-token losses over the same label set) — segment-id
attention + restarting position ids make packing a pure FLOP saving.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fengshen_tpu.examples.ziya_llama.finetune_ziya_llama import (
    LlamaSFTCollator, LlamaSFTPackedCollator)
from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from fengshen_tpu.parallel.cross_entropy import stable_cross_entropy

pytestmark = pytest.mark.slow  # full-fit/e2e lane: run with -m slow or no -m filter


class CharTok:
    """Minimal char tokenizer with the HF encode() surface."""

    pad_token_id = 0
    eos_token_id = 1

    def encode(self, text, add_special_tokens=True):
        return [2 + (ord(c) % 60) for c in text]


SAMPLES = [
    {"query": "ab", "answer": "cde"},
    {"query": "fgh", "answer": "ij"},
    {"query": "k", "answer": "lmnop"},
    {"query": "qr", "answer": "st"},
]


def _sum_loss(model, params, batch, packed):
    kwargs = {"attention_mask": jnp.asarray(batch["attention_mask"])}
    if packed:
        kwargs["position_ids"] = jnp.asarray(batch["position_ids"])
    logits = model.apply({"params": params},
                         jnp.asarray(batch["input_ids"]), **kwargs)
    labels = jnp.asarray(batch["labels"])
    mean, n = stable_cross_entropy(logits[:, :-1], labels[:, 1:])
    return float(mean) * float(n), float(n)


@pytest.mark.parametrize("impl", ["dense", "flash"])
def test_packed_loss_equals_padded(impl):
    tok = CharTok()
    padded = LlamaSFTCollator(tok, max_seq_length=48)(SAMPLES)
    packed = LlamaSFTPackedCollator(tok, max_seq_length=48)(SAMPLES)
    assert packed["input_ids"].shape[0] < padded["input_ids"].shape[0]
    # segment ids: per-example within a row, 0 on pads
    assert packed["attention_mask"].max() >= 2

    base = dict(vocab_size=64, hidden_size=32, intermediate_size=64,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=4, max_position_embeddings=48,
                dtype="float32", attention_impl=impl)
    model_pad = LlamaForCausalLM(LlamaConfig(**base))
    model_pack = LlamaForCausalLM(
        LlamaConfig(**base, packed_sequences=True))
    params = model_pad.init(
        jax.random.PRNGKey(0),
        jnp.asarray(padded["input_ids"]))["params"]

    loss_pad, n_pad = _sum_loss(model_pad, params, padded, packed=False)
    loss_pack, n_pack = _sum_loss(model_pack, params, packed, packed=True)
    assert n_pad == n_pack  # identical label sets
    np.testing.assert_allclose(loss_pack, loss_pad, rtol=2e-5)


def test_packed_collator_layout():
    tok = CharTok()
    out = LlamaSFTPackedCollator(tok, max_seq_length=48)(SAMPLES)
    for row in range(out["input_ids"].shape[0]):
        segs = out["attention_mask"][row]
        pos = out["position_ids"][row]
        # segments are 1..n then 0-pad, each starting at position 0
        prev = 0
        for i, s in enumerate(segs):
            if s != prev:
                if s != 0:
                    assert s == prev + 1  # consecutive ids
                    assert pos[i] == 0    # restart per example
                prev = s
            elif s != 0 and i > 0:
                assert pos[i] == pos[i - 1] + 1
        # pads are trailing only
        nz = np.nonzero(segs)[0]
        assert nz.size == 0 or nz[-1] == nz.size - 1


def test_packed_fixed_rows():
    tok = CharTok()
    coll = LlamaSFTPackedCollator(tok, max_seq_length=48, fixed_rows=3)
    out = coll(SAMPLES)
    assert out["input_ids"].shape == (3, 48)
    coll1 = LlamaSFTPackedCollator(tok, max_seq_length=48, fixed_rows=1)
    out1 = coll1(SAMPLES)  # overflow rows dropped
    assert out1["input_ids"].shape == (1, 48)
