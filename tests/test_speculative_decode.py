"""Speculative decoding (utils/generate.py speculative_generate).

The contract is TOKEN-EXACTNESS: whatever the draft model proposes, the
committed output must be bit-identical to plain greedy `generate` on the
target — the draft only changes how many target dispatches it takes.
(Beyond-reference serving capability; the reference decodes per-token:
fengshen/examples/ziya_llama/llama_generate.py:17-58.)
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from fengshen_tpu.utils.generate import generate, speculative_generate


def _models():
    tgt_cfg = LlamaConfig(vocab_size=97, hidden_size=32,
                          intermediate_size=64, num_hidden_layers=4,
                          num_attention_heads=4,
                          max_position_embeddings=128, dtype="float32")
    drf_cfg = LlamaConfig(vocab_size=97, hidden_size=32,
                          intermediate_size=64, num_hidden_layers=2,
                          num_attention_heads=4,
                          max_position_embeddings=128, dtype="float32")
    tgt, drf = LlamaForCausalLM(tgt_cfg), LlamaForCausalLM(drf_cfg)
    ids = jnp.asarray(np.random.RandomState(0).randint(3, 96, (3, 12)),
                      jnp.int32)
    mask = jnp.asarray([[1] * 12, [0] * 4 + [1] * 8, [0] * 7 + [1] * 5],
                       jnp.int32)
    ids = ids * mask
    tp = tgt.init(jax.random.PRNGKey(0), ids[:, :4])["params"]
    dp = drf.init(jax.random.PRNGKey(1), ids[:, :4])["params"]
    return tgt, tp, drf, dp, ids, mask


@pytest.mark.parametrize("gamma", [1, 3, 4])
def test_speculative_exact_vs_greedy(gamma):
    """An unrelated random draft must not change a single output token
    (worst case: zero acceptance, still exact)."""
    tgt, tp, drf, dp, ids, mask = _models()
    ref = generate(tgt, tp, ids, attention_mask=mask, max_new_tokens=24)
    out = speculative_generate(tgt, tp, drf, dp, ids,
                               attention_mask=mask, max_new_tokens=24,
                               gamma=gamma)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_speculative_self_draft_accepts_everything():
    """draft == target: every proposal accepted, so 24 tokens commit in
    ceil(23 / (gamma+1)) rounds — the mechanism that buys the speedup."""
    tgt, tp, _, _, ids, mask = _models()
    ref = generate(tgt, tp, ids, attention_mask=mask, max_new_tokens=24)
    out, stats = speculative_generate(
        tgt, tp, tgt, tp, ids, attention_mask=mask, max_new_tokens=24,
        gamma=4, return_stats=True)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    assert int(stats["rounds"]) == 5  # ceil(23 / 5)
    assert int(stats["accepted"]) == int(stats["rounds"]) * 4


def test_speculative_eos_exact():
    """Early stopping on eos must cut and pad exactly like generate —
    pick an eos that actually occurs mid-generation in the reference
    output so the cut happens inside a speculation window."""
    tgt, tp, drf, dp, ids, mask = _models()
    ref_free = generate(tgt, tp, ids, attention_mask=mask,
                        max_new_tokens=24)
    gen_part = np.asarray(ref_free[:, ids.shape[1]:])
    eos = int(gen_part[0, gen_part.shape[1] // 2])  # mid-stream token
    ref = generate(tgt, tp, ids, attention_mask=mask, max_new_tokens=24,
                   eos_token_id=eos, pad_token_id=0)
    out = speculative_generate(tgt, tp, drf, dp, ids,
                               attention_mask=mask, max_new_tokens=24,
                               gamma=4, eos_token_id=eos, pad_token_id=0)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    # and with a perfectly-agreeing draft (window commits are longest)
    out2 = speculative_generate(tgt, tp, tgt, tp, ids,
                                attention_mask=mask, max_new_tokens=24,
                                gamma=4, eos_token_id=eos, pad_token_id=0)
    np.testing.assert_array_equal(np.asarray(out2), np.asarray(ref))


def test_speculative_refuses_undersized_cache():
    """The verify window writes gamma extra cache entries past
    total_len; a cache without that headroom would silently clamp the
    write and corrupt committed entries — must refuse loudly."""
    tgt, tp, drf, dp, ids, mask = _models()
    room = 128 - ids.shape[1]  # max_position_embeddings - prompt
    with pytest.raises(ValueError, match="gamma extra cache slots"):
        speculative_generate(tgt, tp, drf, dp, ids,
                             attention_mask=mask,
                             max_new_tokens=room - 1, gamma=4)


def test_ziya_inference_speculative_cli(tmp_path, capsys):
    """The serving demo's --draft_model_path switch: two tiny HF-format
    llama dirs (export round-trip), a char tokenizer, and the CLI must
    print the target's exact greedy continuation plus acceptance stats."""
    import unittest.mock as mock

    import torch

    from fengshen_tpu.examples.ziya_inference import generate_ziya
    from fengshen_tpu.models.llama.convert import params_to_torch_state

    def write_hf_dir(path, n_layers, seed):
        cfg = LlamaConfig(vocab_size=128, hidden_size=32,
                          intermediate_size=64, num_hidden_layers=n_layers,
                          num_attention_heads=4,
                          max_position_embeddings=128, dtype="float32")
        m = LlamaForCausalLM(cfg)
        p = m.init(jax.random.PRNGKey(seed),
                   jnp.zeros((1, 4), jnp.int32))["params"]
        path.mkdir()
        cfg.save_pretrained(str(path))
        state = {k: torch.as_tensor(np.asarray(v))
                 for k, v in params_to_torch_state(p, cfg).items()}
        torch.save(state, str(path / "pytorch_model.bin"))
        return cfg, m, p

    tgt_dir, drf_dir = tmp_path / "target", tmp_path / "draft"
    cfg, m, p = write_hf_dir(tgt_dir, 3, 0)
    write_hf_dir(drf_dir, 1, 1)

    class CharTok:
        def encode(self, text):
            return [1] + [3 + (ord(c) % 120) for c in text]

        def decode(self, ids, skip_special_tokens=True):
            return " ".join(str(i) for i in ids)

        @classmethod
        def from_pretrained(cls, path):
            return cls()

    with mock.patch("transformers.AutoTokenizer.from_pretrained",
                    CharTok.from_pretrained):
        generate_ziya.main([
            "--model_path", str(tgt_dir), "--query", "hi",
            "--draft_model_path", str(drf_dir), "--gamma", "3",
            "--max_new_tokens", "12"])
    out = capsys.readouterr().out
    assert "[speculative] rounds=" in out

    tok = CharTok()
    ids = tok.encode("<human>:hi\n<bot>:")
    ref = generate(m, p, jnp.asarray([ids], jnp.int32), max_new_tokens=12,
                   eos_token_id=cfg.eos_token_id,
                   pad_token_id=cfg.pad_token_id)
    expected = tok.decode(list(ref[0][len(ids):])).strip()
    assert expected in out


def test_speculative_jits():
    """The whole loop (prefill + while_loop of draft-scan/verify/
    rollback) must compile into one jitted program."""
    tgt, tp, drf, dp, ids, mask = _models()

    @jax.jit
    def run(tp, dp, ids, mask):
        return speculative_generate(tgt, tp, drf, dp, ids,
                                    attention_mask=mask,
                                    max_new_tokens=16, gamma=3)

    ref = generate(tgt, tp, ids, attention_mask=mask, max_new_tokens=16)
    np.testing.assert_array_equal(np.asarray(run(tp, dp, ids, mask)),
                                  np.asarray(ref))
