"""Speculative decoding (utils/generate.py speculative_generate).

Two contracts, both asserted here: GREEDY mode is token-exact — whatever
the draft proposes, the committed output is bit-identical to plain
greedy `generate` on the target; SAMPLING mode is distribution-exact —
the rejection scheme's committed tokens follow the target's filtered
distribution, checked empirically against analytic softmax
probabilities. The draft only changes how many target dispatches it
takes. (Beyond-reference serving capability; the reference decodes
per-token: fengshen/examples/ziya_llama/llama_generate.py:17-58.)
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from fengshen_tpu.utils.generate import generate, speculative_generate


def _models():
    tgt_cfg = LlamaConfig(vocab_size=97, hidden_size=32,
                          intermediate_size=64, num_hidden_layers=4,
                          num_attention_heads=4,
                          max_position_embeddings=128, dtype="float32")
    drf_cfg = LlamaConfig(vocab_size=97, hidden_size=32,
                          intermediate_size=64, num_hidden_layers=2,
                          num_attention_heads=4,
                          max_position_embeddings=128, dtype="float32")
    tgt, drf = LlamaForCausalLM(tgt_cfg), LlamaForCausalLM(drf_cfg)
    ids = jnp.asarray(np.random.RandomState(0).randint(3, 96, (3, 12)),
                      jnp.int32)
    mask = jnp.asarray([[1] * 12, [0] * 4 + [1] * 8, [0] * 7 + [1] * 5],
                       jnp.int32)
    ids = ids * mask
    tp = tgt.init(jax.random.PRNGKey(0), ids[:, :4])["params"]
    dp = drf.init(jax.random.PRNGKey(1), ids[:, :4])["params"]
    return tgt, tp, drf, dp, ids, mask


@pytest.mark.parametrize("gamma", [1, 3, 4])
def test_speculative_exact_vs_greedy(gamma):
    """An unrelated random draft must not change a single output token
    (worst case: zero acceptance, still exact)."""
    tgt, tp, drf, dp, ids, mask = _models()
    ref = generate(tgt, tp, ids, attention_mask=mask, max_new_tokens=24)
    out = speculative_generate(tgt, tp, drf, dp, ids,
                               attention_mask=mask, max_new_tokens=24,
                               gamma=gamma)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_speculative_self_draft_accepts_everything():
    """draft == target: every proposal accepted, so 24 tokens commit in
    ceil(23 / (gamma+1)) rounds — the mechanism that buys the speedup."""
    tgt, tp, _, _, ids, mask = _models()
    ref = generate(tgt, tp, ids, attention_mask=mask, max_new_tokens=24)
    out, stats = speculative_generate(
        tgt, tp, tgt, tp, ids, attention_mask=mask, max_new_tokens=24,
        gamma=4, return_stats=True)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    assert int(stats["rounds"]) == 5  # ceil(23 / 5)
    assert int(stats["accepted"]) == int(stats["rounds"]) * 4
    assert float(stats["acceptance_rate"]) == 1.0


def test_speculative_eos_exact():
    """Early stopping on eos must cut and pad exactly like generate —
    pick an eos that actually occurs mid-generation in the reference
    output so the cut happens inside a speculation window."""
    tgt, tp, drf, dp, ids, mask = _models()
    ref_free = generate(tgt, tp, ids, attention_mask=mask,
                        max_new_tokens=24)
    gen_part = np.asarray(ref_free[:, ids.shape[1]:])
    eos = int(gen_part[0, gen_part.shape[1] // 2])  # mid-stream token
    ref = generate(tgt, tp, ids, attention_mask=mask, max_new_tokens=24,
                   eos_token_id=eos, pad_token_id=0)
    out = speculative_generate(tgt, tp, drf, dp, ids,
                               attention_mask=mask, max_new_tokens=24,
                               gamma=4, eos_token_id=eos, pad_token_id=0)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    # and with a perfectly-agreeing draft (window commits are longest)
    out2 = speculative_generate(tgt, tp, tgt, tp, ids,
                                attention_mask=mask, max_new_tokens=24,
                                gamma=4, eos_token_id=eos, pad_token_id=0)
    np.testing.assert_array_equal(np.asarray(out2), np.asarray(ref))


def test_spec_round_tokens_hand_computed():
    """The factored accept/commit helper (`_spec_round_tokens`) against
    hand-computed cases — the engine's speculative tick and the
    generate-level loop both call THIS function, so pinning its exact
    outputs here proves the two paths share one implementation.

    Greedy: accept = longest draft==argmax prefix, w = the per-position
    argmax corrections. Rejection sampling with degenerate one-hot
    (p, q): disjoint mass rejects at position 0 and resamples from the
    residual (= p); identical mass accepts everything and samples the
    bonus from p — all deterministic despite the random key."""
    from fengshen_tpu.utils.generate import _spec_round_tokens

    # greedy, V=12, gamma=3: row 0 accepts 2 then mismatches, row 1
    # rejects immediately, row 2 accepts all 3
    targets = np.array([[7, 9, 8, 1], [5, 4, 3, 2], [6, 6, 6, 6]])
    t_logits = jnp.asarray(np.eye(12, dtype=np.float32)[targets])
    d = jnp.asarray([[7, 9, 9], [9, 4, 3], [6, 6, 6]], jnp.int32)
    n_r, w = _spec_round_tokens(t_logits, None, d, jax.random.PRNGKey(0),
                                do_sample=False)
    np.testing.assert_array_equal(np.asarray(n_r), [2, 0, 3])
    np.testing.assert_array_equal(np.asarray(w), targets)

    # rejection sampling, gamma=2: q one-hot on token 0, p one-hot on
    # token 1 → accept prob p(0)/q(0) ~ e^-50, the draft is rejected
    # and the resample comes from norm(max(p-q, 0)) = one-hot(1)
    big = 50.0
    q_log = jnp.asarray(np.eye(4, dtype=np.float32)[[0, 0]])[None] * big
    p_log = jnp.asarray(np.eye(4, dtype=np.float32)[[1, 1]])[None] * big
    t3 = jnp.concatenate([p_log, p_log[:, :1]], axis=1)  # [1, 3, 4]
    d2 = jnp.zeros((1, 2), jnp.int32)                    # draft ~ q
    n_r, w = _spec_round_tokens(t3, q_log, d2, jax.random.PRNGKey(1),
                                do_sample=True)
    assert int(n_r[0]) == 0
    assert int(w[0, 0]) == 1
    # p == q (both one-hot on 2): min(1, p/q) = 1 accepts every draft,
    # the bonus is sampled from p_gamma = one-hot(2)
    pq = jnp.asarray(np.eye(4, dtype=np.float32)[[2, 2, 2]])[None] * big
    d3 = jnp.full((1, 2), 2, jnp.int32)
    n_r, w = _spec_round_tokens(pq, pq[:, :2], d3, jax.random.PRNGKey(2),
                                do_sample=True)
    assert int(n_r[0]) == 2
    np.testing.assert_array_equal(np.asarray(w), [[2, 2, 2]])


def test_spec_round_sampling_distribution_exact():
    """The rejection scheme's committed tokens must be distributed
    EXACTLY as the target's filtered distribution — checked empirically
    against analytic softmax probabilities over 40k i.i.d. rows sharing
    one (p, q) pair: accept d~q with prob min(1, p/q), else resample
    from norm(max(0, p-q)). Any bias in accept, residual, or bonus math
    shifts the histogram by more than the 4-sigma tolerance."""
    from fengshen_tpu.utils.generate import _spec_round_tokens

    B, V, gamma = 40000, 8, 2
    key = jax.random.PRNGKey(7)
    k1, k2, k3, k4 = jax.random.split(key, 4)
    # one shared, deliberately mismatched (p, q) pair (scale 1.0 keeps
    # enough overlap that thousands of rows survive acceptance at each
    # position, powering the conditional histograms)
    t_log = jax.random.normal(k1, (1, gamma + 1, V))
    d_log = jax.random.normal(k2, (1, gamma, V))
    t_logits = jnp.broadcast_to(t_log, (B, gamma + 1, V))
    d_logits = jnp.broadcast_to(d_log, (B, gamma, V))
    # draft proposals ~ q, independently per row/position
    q = jax.nn.softmax(d_log.astype(jnp.float32), axis=-1)
    d = jax.random.categorical(
        k3, jnp.broadcast_to(jnp.log(q), (B, gamma, V)), axis=-1)

    n_r, w = _spec_round_tokens(t_logits, d_logits, d.astype(jnp.int32),
                                k4, do_sample=True)
    p = np.asarray(jax.nn.softmax(t_log.astype(jnp.float32), -1))[0]

    # position 0 commits for every row: histogram == p_0
    hist0 = np.bincount(np.asarray(w[:, 0]), minlength=V) / B
    np.testing.assert_allclose(hist0, p[0], atol=4 * np.sqrt(0.25 / B))

    # position 1 commits when position 0 accepted: conditional
    # histogram == p_1 (independent draws, shared fixed p/q)
    sel = np.asarray(n_r) >= 1
    assert sel.sum() > 3000
    hist1 = np.bincount(np.asarray(w[sel, 1]),
                        minlength=V) / sel.sum()
    np.testing.assert_allclose(hist1, p[1],
                               atol=4 * np.sqrt(0.25 / sel.sum()))

    # full acceptance -> bonus position sampled from p_2
    sel2 = np.asarray(n_r) == gamma
    if sel2.sum() > 1000:
        hist2 = np.bincount(np.asarray(w[sel2, 2]),
                            minlength=V) / sel2.sum()
        np.testing.assert_allclose(hist2, p[2],
                                   atol=4 * np.sqrt(0.25 / sel2.sum()))


def test_speculative_sampling_e2e_properties():
    """Sampled speculative decode: deterministic under a fixed rng,
    full acceptance when draft == target (p == q makes the rejection
    test always pass), and eos cuts with pad like plain generate."""
    tgt, tp, drf, dp, ids, mask = _models()

    out1, st1 = speculative_generate(
        tgt, tp, drf, dp, ids, attention_mask=mask, max_new_tokens=20,
        gamma=4, do_sample=True, temperature=0.9, top_p=0.9,
        rng=jax.random.PRNGKey(5), return_stats=True)
    out2 = speculative_generate(
        tgt, tp, drf, dp, ids, attention_mask=mask, max_new_tokens=20,
        gamma=4, do_sample=True, temperature=0.9, top_p=0.9,
        rng=jax.random.PRNGKey(5))
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    out3 = speculative_generate(
        tgt, tp, drf, dp, ids, attention_mask=mask, max_new_tokens=20,
        gamma=4, do_sample=True, temperature=0.9, top_p=0.9,
        rng=jax.random.PRNGKey(6))
    assert not np.array_equal(np.asarray(out1), np.asarray(out3))

    # draft == target: p == q, min(1, p/q) == 1, every proposal accepted
    _, st = speculative_generate(
        tgt, tp, tgt, tp, ids, attention_mask=mask, max_new_tokens=20,
        gamma=4, do_sample=True, rng=jax.random.PRNGKey(8),
        return_stats=True)
    assert int(st["accepted"]) == int(st["rounds"]) * 4

    # eos inside the stream: everything after the first eos is pad
    gen = np.asarray(out1[:, ids.shape[1]:])
    eos = int(gen[0, gen.shape[1] // 2])
    out4 = np.asarray(speculative_generate(
        tgt, tp, drf, dp, ids, attention_mask=mask, max_new_tokens=20,
        gamma=4, do_sample=True, temperature=0.9, top_p=0.9,
        eos_token_id=eos, pad_token_id=0, rng=jax.random.PRNGKey(5)))
    for row in out4[:, ids.shape[1]:]:
        hits = np.where(row == eos)[0]
        if hits.size:
            assert (row[hits[0] + 1:] == 0).all()


@pytest.mark.parametrize("scan", [False, True])
def test_self_draft_exact_and_aliased(scan):
    """make_self_draft: the target's own first-K-layer tower as the
    draft — output stays token-exact vs plain greedy, shared leaves
    alias the target's arrays (no copy), and under scan_layers the
    stacked leaves slice to K."""
    from fengshen_tpu.models.llama import make_self_draft

    cfg = LlamaConfig(vocab_size=97, hidden_size=32,
                      intermediate_size=64, num_hidden_layers=4,
                      num_attention_heads=4,
                      max_position_embeddings=128, dtype="float32",
                      scan_layers=scan)
    tgt = LlamaForCausalLM(cfg)
    ids = jnp.asarray(np.random.RandomState(2).randint(3, 96, (2, 10)),
                      jnp.int32)
    tp = tgt.init(jax.random.PRNGKey(0), ids[:, :4])["params"]

    d_cfg, d_params = make_self_draft(cfg, tp, 2)
    assert d_cfg.num_hidden_layers == 2
    assert d_params["model"]["embed_tokens"]["embedding"] is \
        tp["model"]["embed_tokens"]["embedding"]
    if scan:
        leaf = jax.tree_util.tree_leaves(d_params["model"]["layers"])[0]
        assert leaf.shape[0] == 2
    else:
        assert "layers_2" not in d_params["model"]
        assert d_params["model"]["layers_1"] is tp["model"]["layers_1"]
    draft = LlamaForCausalLM(d_cfg)

    ref = generate(tgt, tp, ids, max_new_tokens=16)
    out, stats = speculative_generate(
        tgt, tp, draft, d_params, ids, max_new_tokens=16, gamma=4,
        return_stats=True)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    with pytest.raises(ValueError, match="must be in"):
        make_self_draft(cfg, tp, 4)


def test_ngram_propose_mechanics():
    """The proposal search: latest earlier occurrence wins, continuation
    is what followed it, no-match rows propose pads, and matches whose
    continuation would start at/after t are excluded."""
    from fengshen_tpu.utils.generate import _ngram_propose

    #        0  1  2  3  4  5  6  7  8 (t) ...
    buf = jnp.asarray([
        [5, 6, 9, 5, 6, 7, 5, 6, 0, 0, 0, 0],   # suffix [5,6] at 6..7
        [1, 2, 3, 4, 5, 6, 7, 8, 0, 0, 0, 0],   # no earlier [7,8]
    ], jnp.int32)
    d = _ngram_propose(buf, jnp.int32(8), ngram=2, gamma=3,
                       pad_token_id=0)
    # row 0: latest earlier [5,6] is at 3..4 (6..7 is the suffix
    # itself; its continuation starts at t and is excluded) -> the
    # tokens that followed: 7, 5, 6
    np.testing.assert_array_equal(np.asarray(d[0]), [7, 5, 6])
    # row 1: [7,8] never occurred before -> pads
    np.testing.assert_array_equal(np.asarray(d[1]), [0, 0, 0])

    # fit preference: on a period-1 loop the LATEST match's window runs
    # into the uncommitted pad region (capping acceptance); an earlier
    # match whose whole continuation lies in committed text must win
    loop = jnp.asarray([[9, 4, 4, 4, 4, 4, 4, 0, 0, 0, 0, 0]], jnp.int32)
    d2 = _ngram_propose(loop, jnp.int32(7), ngram=2, gamma=3,
                        pad_token_id=0)
    # matches of suffix [4,4] at j=1..4; j=4's continuation (5,6,7)
    # reads one real token then... j+ngram+gamma<=t selects j<=2 ->
    # j=2, continuation buf[4:7] = [4,4,4], all real committed tokens
    np.testing.assert_array_equal(np.asarray(d2[0]), [4, 4, 4])


@pytest.mark.parametrize("ngram", [1, 2])
def test_prompt_lookup_exact_vs_greedy(ngram):
    """Draft-free prompt lookup must be token-exact vs plain greedy,
    and on this looping tiny model actually accept proposals (the
    greedy continuation repeats, so the lookup finds it)."""
    from fengshen_tpu.utils.generate import prompt_lookup_generate

    tgt, tp, _, _, ids, mask = _models()
    ref = generate(tgt, tp, ids, attention_mask=mask, max_new_tokens=24)
    out, stats = prompt_lookup_generate(
        tgt, tp, ids, attention_mask=mask, max_new_tokens=24,
        gamma=4, ngram=ngram, return_stats=True)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    # the random 4-layer model's greedy continuation loops (repeated
    # n-grams), so lookup acceptance must be non-trivial
    assert int(stats["accepted"]) > 0
    assert int(stats["rounds"]) < 23  # strictly fewer target passes
    assert float(stats["acceptance_rate"]) == pytest.approx(
        int(stats["accepted"]) / int(stats["drafted"]))


def test_speculative_edge_shapes_exact():
    """Edge interactions stay token-exact: gamma larger than the whole
    budget (first round over-commits, final slice trims), batch of one,
    and eos on the very first token (the loop must run zero rounds)."""
    tgt, tp, drf, dp, ids, mask = _models()

    # gamma > max_new_tokens
    ref = generate(tgt, tp, ids, attention_mask=mask, max_new_tokens=4)
    out = speculative_generate(tgt, tp, drf, dp, ids,
                               attention_mask=mask, max_new_tokens=4,
                               gamma=8)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    # batch of one
    one, m1 = ids[:1], mask[:1]
    ref1 = generate(tgt, tp, one, attention_mask=m1, max_new_tokens=12)
    out1 = speculative_generate(tgt, tp, drf, dp, one,
                                attention_mask=m1, max_new_tokens=12,
                                gamma=3)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(ref1))

    # eos == the first generated token: per-row early finish stays
    # exact on the mixed batch, and on the single-row batch the loop
    # runs ZERO speculation rounds (finished before the first round)
    eos = int(np.asarray(ref)[0, ids.shape[1]])  # row 0's first token
    ref_e = generate(tgt, tp, ids, attention_mask=mask, max_new_tokens=8,
                     eos_token_id=eos, pad_token_id=0)
    out_e = speculative_generate(tgt, tp, drf, dp, ids,
                                 attention_mask=mask, max_new_tokens=8,
                                 gamma=4, eos_token_id=eos,
                                 pad_token_id=0)
    np.testing.assert_array_equal(np.asarray(out_e), np.asarray(ref_e))
    _, st = speculative_generate(tgt, tp, drf, dp, one,
                                 attention_mask=m1, max_new_tokens=8,
                                 gamma=4, eos_token_id=eos,
                                 pad_token_id=0, return_stats=True)
    assert int(st["rounds"]) == 0


def test_speculative_int8_lm_head_exact():
    """The bench composes BENCH_INT8_LMHEAD with spec/lookup decode;
    with the int8 head on BOTH the reference and speculative paths the
    outputs must still be token-exact (the head changes logits, not
    the speculation contract)."""
    from fengshen_tpu.utils.generate import prompt_lookup_generate

    import dataclasses

    cfg = LlamaConfig(vocab_size=97, hidden_size=32,
                      intermediate_size=64, num_hidden_layers=3,
                      num_attention_heads=4,
                      max_position_embeddings=128, dtype="float32",
                      int8_lm_head=True)
    tgt = LlamaForCausalLM(cfg)
    ids = jnp.asarray(np.random.RandomState(4).randint(3, 96, (2, 8)),
                      jnp.int32)
    tp = tgt.init(jax.random.PRNGKey(0), ids[:, :4])["params"]
    drf_cfg = dataclasses.replace(cfg, num_hidden_layers=1,
                                  int8_lm_head=False)
    drf = LlamaForCausalLM(drf_cfg)
    dp = drf.init(jax.random.PRNGKey(1), ids[:, :4])["params"]

    ref = generate(tgt, tp, ids, max_new_tokens=16)
    # unrelated draft: zero acceptance, correction path only
    out = speculative_generate(tgt, tp, drf, dp, ids,
                               max_new_tokens=16, gamma=3)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    # self-draft: FULL acceptance, so the int8 logits must agree
    # between the multi-token verify pass and the per-token draft pass
    # for ACCEPTED tokens too (non-vacuous accept-path coverage)
    out_sd, st = speculative_generate(tgt, tp, tgt, tp, ids,
                                      max_new_tokens=16, gamma=3,
                                      return_stats=True)
    np.testing.assert_array_equal(np.asarray(out_sd), np.asarray(ref))
    assert int(st["accepted"]) == int(st["rounds"]) * 3
    out2 = prompt_lookup_generate(tgt, tp, ids, max_new_tokens=16,
                                  gamma=3, ngram=2)
    np.testing.assert_array_equal(np.asarray(out2), np.asarray(ref))


def test_speculative_refuses_undersized_cache():
    """The verify window writes gamma extra cache entries past
    total_len; a cache without that headroom would silently clamp the
    write and corrupt committed entries — must refuse loudly."""
    tgt, tp, drf, dp, ids, mask = _models()
    room = 128 - ids.shape[1]  # max_position_embeddings - prompt
    with pytest.raises(ValueError, match="gamma extra cache slots"):
        speculative_generate(tgt, tp, drf, dp, ids,
                             attention_mask=mask,
                             max_new_tokens=room - 1, gamma=4)


def test_ziya_inference_speculative_cli(tmp_path, capsys):
    """The serving demo's --draft_model_path switch: two tiny HF-format
    llama dirs (export round-trip), a char tokenizer, and the CLI must
    print the target's exact greedy continuation plus acceptance stats."""
    import unittest.mock as mock

    import torch

    from fengshen_tpu.examples.ziya_inference import generate_ziya
    from fengshen_tpu.models.llama.convert import params_to_torch_state

    def write_hf_dir(path, n_layers, seed):
        cfg = LlamaConfig(vocab_size=128, hidden_size=32,
                          intermediate_size=64, num_hidden_layers=n_layers,
                          num_attention_heads=4,
                          max_position_embeddings=128, dtype="float32")
        m = LlamaForCausalLM(cfg)
        p = m.init(jax.random.PRNGKey(seed),
                   jnp.zeros((1, 4), jnp.int32))["params"]
        path.mkdir()
        cfg.save_pretrained(str(path))
        state = {k: torch.as_tensor(np.asarray(v))
                 for k, v in params_to_torch_state(p, cfg).items()}
        torch.save(state, str(path / "pytorch_model.bin"))
        return cfg, m, p

    tgt_dir, drf_dir = tmp_path / "target", tmp_path / "draft"
    cfg, m, p = write_hf_dir(tgt_dir, 3, 0)
    write_hf_dir(drf_dir, 1, 1)

    class CharTok:
        def encode(self, text):
            return [1] + [3 + (ord(c) % 120) for c in text]

        def decode(self, ids, skip_special_tokens=True):
            return " ".join(str(i) for i in ids)

        @classmethod
        def from_pretrained(cls, path):
            return cls()

    with mock.patch("transformers.AutoTokenizer.from_pretrained",
                    CharTok.from_pretrained):
        generate_ziya.main([
            "--model_path", str(tgt_dir), "--query", "hi",
            "--draft_model_path", str(drf_dir), "--gamma", "3",
            "--greedy", "--max_new_tokens", "12"])
    out = capsys.readouterr().out
    assert "[speculative] rounds=" in out

    tok = CharTok()
    ids = tok.encode("<human>:hi\n<bot>:")
    ref = generate(m, p, jnp.asarray([ids], jnp.int32), max_new_tokens=12,
                   eos_token_id=cfg.eos_token_id,
                   pad_token_id=cfg.pad_token_id)
    expected = tok.decode(list(ref[0][len(ids):])).strip()
    assert expected in out

    # the sampled draft flow (default --do_sample) must run too
    with mock.patch("transformers.AutoTokenizer.from_pretrained",
                    CharTok.from_pretrained):
        generate_ziya.main([
            "--model_path", str(tgt_dir), "--query", "hi",
            "--self_draft_layers", "1", "--gamma", "3",
            "--top_p", "0.9", "--max_new_tokens", "12"])
    assert "[speculative] rounds=" in capsys.readouterr().out

    # conflicting draft flags fail fast, before any checkpoint load
    with pytest.raises(SystemExit, match="mutually exclusive"):
        generate_ziya.main([
            "--model_path", str(tgt_dir), "--query", "hi",
            "--draft_model_path", str(drf_dir),
            "--self_draft_layers", "1"])


def test_speculative_jits():
    """The whole loop (prefill + while_loop of draft-scan/verify/
    rollback) must compile into one jitted program."""
    tgt, tp, drf, dp, ids, mask = _models()

    @jax.jit
    def run(tp, dp, ids, mask):
        return speculative_generate(tgt, tp, drf, dp, ids,
                                    attention_mask=mask,
                                    max_new_tokens=16, gamma=3)

    ref = generate(tgt, tp, ids, attention_mask=mask, max_new_tokens=16)
    np.testing.assert_array_equal(np.asarray(run(tp, dp, ids, mask)),
                                  np.asarray(ref))
