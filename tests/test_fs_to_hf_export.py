"""fs→HF export round-trips for the generic inverter families
(VERDICT r4 missing #3; reference merge-back analog:
fengshen/utils/llama_convert/fs_to_hf.py, merge_lt_mp_to_hf.py).

Two properties per family:
  1. export(import(state)) == state for EVERY key — keys the importer
     reads must round-trip bit-exactly; keys it never reads must keep
     their template values.
  2. a perturbed (="finetuned") flax tree survives export → re-import
     unchanged, so the export really carries the flax values and does
     not just echo the template.
"""

import numpy as np
import pytest

import jax

torch = pytest.importorskip("torch")


def _bart():
    import transformers

    from fengshen_tpu.models.bart.modeling_bart import BartConfig
    from fengshen_tpu.models.bart import convert

    hf_cfg = transformers.BartConfig(
        vocab_size=128, d_model=32, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=64, decoder_ffn_dim=64,
        max_position_embeddings=64, attn_implementation="eager")
    torch.manual_seed(0)
    tm = transformers.BartForConditionalGeneration(hf_cfg).eval()
    cfg = BartConfig(vocab_size=128, d_model=32, encoder_layers=2,
                     decoder_layers=2, encoder_attention_heads=4,
                     decoder_attention_heads=4, encoder_ffn_dim=64,
                     decoder_ffn_dim=64, max_position_embeddings=64,
                     dtype="float32")
    return convert, tm.state_dict(), cfg, {}


def _pegasus():
    import transformers

    from fengshen_tpu.models.pegasus import PegasusConfig
    from fengshen_tpu.models.pegasus import convert

    hf_cfg = transformers.PegasusConfig(
        vocab_size=120, d_model=32, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=64, decoder_ffn_dim=64,
        max_position_embeddings=64, activation_function="relu",
        scale_embedding=False)
    torch.manual_seed(0)
    tm = transformers.PegasusForConditionalGeneration(hf_cfg).eval()
    cfg = PegasusConfig(vocab_size=120, d_model=32, encoder_layers=2,
                        decoder_layers=2, encoder_attention_heads=4,
                        decoder_attention_heads=4, encoder_ffn_dim=64,
                        decoder_ffn_dim=64, max_position_embeddings=64,
                        activation_function="relu", scale_embedding=False,
                        dtype="float32")
    return convert, tm.state_dict(), cfg, {}


def _deberta():
    import transformers

    from fengshen_tpu.models.deberta_v2 import DebertaV2Config
    from fengshen_tpu.models.deberta_v2 import convert

    hf_cfg = transformers.DebertaV2Config(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, relative_attention=True,
        position_buckets=8, norm_rel_ebd="layer_norm", share_att_key=True,
        pos_att_type=["p2c", "c2p"], position_biased_input=False,
        attn_implementation="eager")
    torch.manual_seed(0)
    tm = transformers.DebertaV2Model(hf_cfg).eval()
    cfg = DebertaV2Config(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, position_buckets=8, dtype="float32")
    state = {f"deberta.{k}": v for k, v in tm.state_dict().items()}
    return convert, state, cfg, {}


def _roformer():
    import transformers

    from fengshen_tpu.models.roformer import RoFormerConfig
    from fengshen_tpu.models.roformer import convert

    hf_cfg = transformers.RoFormerConfig(
        vocab_size=128, embedding_size=32, hidden_size=32,
        num_hidden_layers=2, num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, rotary_value=False,
        attn_implementation="eager")
    torch.manual_seed(0)
    tm = transformers.RoFormerModel(hf_cfg).eval()
    cfg = RoFormerConfig(vocab_size=128, hidden_size=32,
                         num_hidden_layers=2, num_attention_heads=4,
                         intermediate_size=64, max_position_embeddings=64,
                         dtype="float32")
    state = {f"roformer.{k}": v for k, v in tm.state_dict().items()}
    return convert, state, cfg, {}


def _longformer():
    import transformers

    from fengshen_tpu.models.longformer.modeling_longformer import (
        LongformerConfig)
    from fengshen_tpu.models.longformer import convert

    hf_cfg = transformers.LongformerConfig(
        vocab_size=120, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=66, attention_window=[8, 8],
        pad_token_id=0)
    torch.manual_seed(0)
    tm = transformers.LongformerModel(hf_cfg, add_pooling_layer=False).eval()
    cfg = LongformerConfig(
        vocab_size=120, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, attention_window=8, dtype="float32")
    state = {f"longformer.{k}": v for k, v in tm.state_dict().items()}
    return convert, state, cfg, {}


def _albert():
    import transformers

    from fengshen_tpu.models.albert import AlbertConfig
    from fengshen_tpu.models.albert import convert

    hf_cfg = transformers.AlbertConfig(
        vocab_size=128, embedding_size=16, hidden_size=32,
        num_hidden_layers=3, num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, attn_implementation="eager")
    torch.manual_seed(0)
    tm = transformers.AlbertModel(hf_cfg).eval()
    cfg = AlbertConfig(vocab_size=128, embedding_size=16, hidden_size=32,
                       num_hidden_layers=3, num_attention_heads=4,
                       intermediate_size=64, max_position_embeddings=64,
                       dtype="float32")
    state = {f"albert.{k}": v for k, v in tm.state_dict().items()}
    return convert, state, cfg, {}


def _deltalm():
    from fengshen_tpu.models.deltalm import DeltaLMConfig
    from fengshen_tpu.models.deltalm import convert

    cfg = DeltaLMConfig.small_test_config()
    d, f = cfg.d_model, cfg.encoder_ffn_dim
    shapes = {"encoder.embed_tokens.weight": (cfg.vocab_size, d),
              "encoder.embed_positions.weight": (
                  cfg.max_position_embeddings + 2, d)}
    for pre, n in (("encoder", cfg.encoder_layers),
                   ("decoder", cfg.decoder_layers)):
        shapes[f"{pre}.layernorm_embedding.weight"] = (d,)
        shapes[f"{pre}.layernorm_embedding.bias"] = (d,)
        shapes[f"{pre}.layer_norm.weight"] = (d,)
        shapes[f"{pre}.layer_norm.bias"] = (d,)
        for i in range(n):
            p = f"{pre}.layers.{i}"
            for att in (["self_attn"] if pre == "encoder" else
                        ["self_attn", "encoder_attn"]):
                for proj in ("q_proj", "k_proj", "v_proj", "out_proj"):
                    shapes[f"{p}.{att}.{proj}.weight"] = (d, d)
                    shapes[f"{p}.{att}.{proj}.bias"] = (d,)
                shapes[f"{p}.{att}_layer_norm.weight"] = (d,)
                shapes[f"{p}.{att}_layer_norm.bias"] = (d,)
            fcs = ("fc1", "fc2") if pre == "encoder" else \
                ("fc1", "fc2", "fc3", "fc4")
            for fc in fcs:
                wide = fc in ("fc1", "fc3")
                shapes[f"{p}.{fc}.weight"] = (f, d) if wide else (d, f)
                shapes[f"{p}.{fc}.bias"] = (f,) if wide else (d,)
            shapes[f"{p}.final_layer_norm.weight"] = (d,)
            shapes[f"{p}.final_layer_norm.bias"] = (d,)
            if pre == "decoder":
                shapes[f"{p}.ffn_layer_norm.weight"] = (d,)
                shapes[f"{p}.ffn_layer_norm.bias"] = (d,)
    rng = np.random.RandomState(7)
    state = {k: rng.randn(*s).astype(np.float32) for k, s in shapes.items()}
    return convert, state, cfg, {}


def _gpt2():
    import transformers

    from fengshen_tpu.models.gpt2 import GPT2Config
    from fengshen_tpu.models.gpt2 import convert

    hf_cfg = transformers.GPT2Config(
        vocab_size=128, n_positions=64, n_embd=32, n_layer=2, n_head=4,
        attn_implementation="eager")
    torch.manual_seed(0)
    tm = transformers.GPT2LMHeadModel(hf_cfg).eval()
    cfg = GPT2Config(vocab_size=128, n_positions=64, n_embd=32,
                     n_layer=2, n_head=4, dtype="float32",
                     scan_layers=True)
    return convert, tm.state_dict(), cfg, {}


FAMILIES = {"bart": _bart, "pegasus": _pegasus, "deberta_v2": _deberta,
            "roformer": _roformer, "longformer": _longformer,
            "albert": _albert, "deltalm": _deltalm, "gpt2": _gpt2}


def test_export_follows_tied_duplicates():
    """Keys the importer never reads but that are TIED to read tensors
    (GPT2's lm_head.weight ↔ wte) must track the finetuned values — a
    stale copy would be load_state_dict'ed into the shared storage last
    and silently revert the finetune."""
    convert, state, cfg, kw = _gpt2()
    assert "lm_head.weight" in state  # torch materializes the tied key
    params = convert.torch_to_params(state, cfg, **kw)
    leaves, treedef = jax.tree_util.tree_flatten(params)
    bumped = jax.tree_util.tree_unflatten(
        treedef, [np.asarray(x) + 1e-3 for x in leaves])
    out = convert.params_to_torch_state(bumped, cfg, state, **kw)
    np.testing.assert_array_equal(out["lm_head.weight"],
                                  out["transformer.wte.weight"])
    assert not np.array_equal(
        out["lm_head.weight"],
        state["lm_head.weight"].detach().numpy())  # not the stale copy


def test_export_preserves_template_dtype():
    """An fp16/bf16 source checkpoint exports back in its own dtype."""
    convert, state, cfg, kw = _bart()
    state16 = {k: v.half() for k, v in state.items()}
    params = convert.torch_to_params(state16, cfg, **kw)
    out = convert.params_to_torch_state(params, cfg, state16, **kw)
    assert all(v.dtype == np.float16 for v in out.values()), \
        {k: v.dtype for k, v in out.items() if v.dtype != np.float16}


@pytest.mark.parametrize("family", sorted(FAMILIES))
def test_export_round_trip(family):
    convert, state, cfg, kw = FAMILIES[family]()
    ref = {k: np.array(v.detach().numpy() if hasattr(v, "detach") else v)
           for k, v in state.items()}
    params = convert.torch_to_params(state, cfg, **kw)

    # 1. export of the untouched import reproduces the source state dict
    #    exactly — read keys round-trip, unread keys keep template values
    out = convert.params_to_torch_state(params, cfg, state, **kw)
    assert set(out) == set(ref)
    for k in ref:
        np.testing.assert_array_equal(
            out[k].astype(np.float32), ref[k].astype(np.float32),
            err_msg=f"{family}: {k}")

    # 2. a "finetuned" tree survives export → re-import bit-exactly
    leaves, treedef = jax.tree_util.tree_flatten(params)
    bumped = jax.tree_util.tree_unflatten(
        treedef, [np.asarray(x) + (i % 13) * 1e-3
                  for i, x in enumerate(leaves)])
    out2 = convert.params_to_torch_state(bumped, cfg, state, **kw)
    back = convert.torch_to_params(out2, cfg, **kw)
    for path_a, a in jax.tree_util.tree_flatten_with_path(bumped)[0]:
        b = dict(jax.tree_util.tree_flatten_with_path(back)[0])[path_a]
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=0, atol=1e-6,
            err_msg=f"{family}: {jax.tree_util.keystr(path_a)}")
